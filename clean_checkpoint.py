#!/usr/bin/env python3
"""Strip a training checkpoint to release weights
(reference: clean_checkpoint.py:1-115): drops optimizer/model_state entries,
keeps (EMA) weights, writes safetensors with a hash-tagged filename.
"""
from __future__ import annotations

import argparse
import hashlib
import os

import numpy as np

parser = argparse.ArgumentParser(description='Checkpoint cleaner')
parser.add_argument('--checkpoint', default='', type=str, metavar='PATH')
parser.add_argument('--output', default='', type=str, metavar='PATH')
parser.add_argument('--use-ema', dest='use_ema', action='store_true')
parser.add_argument('--no-hash', dest='no_hash', action='store_true')


def main():
    from timm_tpu.models import load_state_dict, save_state_dict
    args = parser.parse_args()
    assert args.checkpoint, '--checkpoint required'

    sd = load_state_dict(args.checkpoint, use_ema=args.use_ema)
    # already unwrapped to plain weight keys by load_state_dict
    print(f"Loaded {len(sd)} weight tensors from '{args.checkpoint}'")

    out = args.output or os.path.splitext(args.checkpoint)[0] + '_clean.safetensors'
    save_state_dict(sd, out)

    if not args.no_hash:
        with open(out, 'rb') as f:
            sha = hashlib.sha256(f.read()).hexdigest()
        base, ext = os.path.splitext(out)
        final = f'{base}-{sha[:8]}{ext}'
        os.rename(out, final)
        out = final
    print(f"Wrote cleaned checkpoint to '{out}'")


if __name__ == '__main__':
    main()
