#!/usr/bin/env python3
"""Driver benchmark: prints JSON status lines; the LAST line is always a valid
result `{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`.

Headline: ViT-B/16 @224 train-step throughput (img/s/chip), bf16, batch 128
per chip, AdamW — vs the reference's published train throughput for the same
model (BASELINE.md: 393.0 img/s, RTX 3090 AMP NHWC).

Methodology: K steps are fused into ONE XLA program (lax.scan carrying
params/opt-state), so the measurement is pure device time — host dispatch and
transfer latency (large through the axon relay) is excluded, matching how the
reference's CUDA-event timing excludes host overhead (benchmark.py:149-157).

Driver-window contract (the round-4 failure was rc=124 with an EMPTY tail —
the old layout printed its one JSON line only at the very end of a worst-case
~40-minute run):
  * A status JSON line is printed IMMEDIATELY at process start, then replaced
    at every phase boundary and every ~25s while the measurement child runs.
    Whenever the driver kills this process, the tail is a parseable JSON line
    saying exactly which phase was reached.
  * Total wall-clock is capped at BENCH_TOTAL_BUDGET seconds (default 420,
    i.e. 7 minutes): one short probe, then the measurement child gets whatever
    budget remains.

Fallback policy: ONLY when the device is provably unreachable (probe failed
AND the fresh-process bench attempt failed) does it replay the most recent
self-measured result from BENCH_SELF.json — clearly labelled with
`replay: true`, the original measurement timestamp, and a NONZERO exit code so
automated consumers can distinguish it from a live measurement. If the probe
succeeds but the bench child fails, that is a genuine code regression: it
reports value 0.0 and a nonzero exit code — never a stale number.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINES = {
    ('vit_base_patch16_224', 'train'): 393.0,
    ('vit_base_patch16_224', 'infer'): 3915.6,
    ('vit_tiny_patch16_224', 'train'): 2299.6,
    ('vit_tiny_patch16_224', 'infer'): 26140.3,
    ('convnext_base', 'train'): 338.7,
    ('convnext_base', 'infer'): 2618.0,
    ('efficientnetv2_s', 'train'): 559.2,
    ('efficientnetv2_s', 'infer'): 3683.6,
}

# bf16 peak FLOP/s per chip for MFU reporting
CHIP_PEAK = {'v5e': 197e12, 'v5litepod': 197e12, 'v4': 275e12, 'v5p': 459e12, 'v6e': 918e12}

SELF_RESULT_PATH = os.environ.get(
    'TIMM_TPU_BENCH_SELF',
    os.path.join(os.path.dirname(os.path.abspath(__file__)), 'BENCH_SELF.json'))

TOTAL_BUDGET = int(os.environ.get('BENCH_TOTAL_BUDGET', '420'))

# fast-fail knob for a downed TPU relay: the probe gets this long, and when it
# FAILS the single fresh-process retry is capped to the same window instead of
# the full remaining budget (the old behavior burned ~400s of child hangs
# before aborting)
PROBE_TIMEOUT = int(os.environ.get('TIMM_TPU_BENCH_PROBE_TIMEOUT', '60'))

# minimum seconds between "measuring" heartbeat status lines
HEARTBEAT_S = 60


def _max_attempts(probed_ok: bool) -> int:
    """Bench-child retry budget: a live probe earns real retries; a failed
    probe gets exactly one fresh-process attempt before the abort line."""
    return 3 if probed_ok else 1

_START = time.time()
_WATCHDOG = None


def _status(phase: str, **extra):
    """Print a status line that is ALSO a valid result schema, so that if the
    driver kills us right now its recorded tail still parses."""
    d = {'metric': f'bench status: {phase} (t+{time.time() - _START:.0f}s)',
         'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}
    d.update(extra)
    print(json.dumps(d), flush=True)


def _remaining() -> float:
    return TOTAL_BUDGET - (time.time() - _START)


def _arm_watchdog(seconds: int):
    """Emit an error JSON line and exit instead of hanging forever if the TPU
    relay wedges mid-measurement (device ops block inside PJRT C++ where
    signals can't preempt — so use a timer thread and os._exit)."""
    import threading
    global _WATCHDOG

    def fire():
        print(json.dumps({
            'metric': 'benchmark watchdog: TPU unreachable (device ops hung)',
            'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
        sys.stdout.flush()
        os._exit(2)

    _WATCHDOG = threading.Timer(seconds, fire)
    _WATCHDOG.daemon = True
    _WATCHDOG.start()


def _probe_device(timeout_s: int) -> bool:
    """Run a tiny device op in a SUBPROCESS so a wedged relay can't hang us."""
    if os.environ.get('TIMM_TPU_BENCH_FORCE_PROBE_FAIL'):
        return False  # test knob: drill the abort/replay paths without a downed relay
    code = (
        'import jax, jax.numpy as jnp\n'
        'x = jnp.ones((128, 128))\n'
        'print(float((x @ x).sum()))\n'
    )
    try:
        r = subprocess.run([sys.executable, '-c', code], timeout=timeout_s,
                           capture_output=True)
        return r.returncode == 0
    except Exception:
        return False


def _replay_self_result(reason: str) -> int:
    """Last-resort fallback, used ONLY when the device is provably unreachable
    (probe failed): replay the most recent self-measured result committed
    during the round. The output is explicitly labelled (`replay: true`,
    original timestamp in `measured_at`) and the exit code is nonzero (3) so
    automated consumers can tell it apart from a live driver-time measurement."""
    try:
        with open(SELF_RESULT_PATH) as f:
            saved = json.load(f)
        if not saved.get('result'):
            # a v2 file holding only abort records has nothing honest to replay
            raise ValueError('no replayable result recorded')
        out = dict(saved['result'])
        out['replay'] = True
        out['measured_at'] = saved.get('measured_at', '?')
        out['replay_reason'] = reason
        out['metric'] = (
            f"REPLAY of self-measured result from {saved.get('measured_at', '?')} "
            f"({reason}; see BENCH_SELF.json): " + out['metric'])
        print(json.dumps(out), flush=True)
        return 3
    except Exception:
        print(json.dumps({
            'metric': f'benchmark aborted: {reason}; no BENCH_SELF.json to replay',
            'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
        return 2


def _run_child(args, timeout_s: int) -> dict | None:
    """Run the actual measurement in a FRESH subprocess, polling it and
    printing a heartbeat status every ~25s; return the parsed JSON result line
    or None on failure/timeout.

    Child stdout/stderr go to temp FILES, not pipes: a pipe would fill at
    ~64KB of JAX/TPU-runtime warnings and deadlock the un-drained child."""
    import tempfile
    cmd = [sys.executable, os.path.abspath(__file__), '--child',
           '--model', args.model, '--bench', args.bench,
           '--img-size', str(args.img_size), '--steps', str(args.steps),
           # child's wedge backstop = the budget WE enforce, plus a grace
           # margin — so an orphaned child can't hold the TPU lease long
           # after the driver kills this parent
           '--watchdog-s', str(timeout_s + 30)]
    if args.batch_size:
        cmd += ['--batch-size', str(args.batch_size)]
    # precision/alignment A/B levers must reach the measurement process
    if args.block_scan:
        cmd += ['--block-scan']
    if args.device_augment:
        cmd += ['--device-augment']
    if args.fsdp:
        cmd += ['--fsdp', str(args.fsdp)]
    if args.tp:
        cmd += ['--tp', str(args.tp)]
    if args.no_donate:
        cmd += ['--no-donate']
    if args.pad_tokens:
        cmd += ['--pad-tokens', str(args.pad_tokens)]
    if args.softmax_dtype:
        cmd += ['--softmax-dtype', args.softmax_dtype]
    if args.norm_dtype:
        cmd += ['--norm-dtype', args.norm_dtype]
    if args.mu_dtype:
        cmd += ['--mu-dtype', args.mu_dtype]
    if args.quantize:
        cmd += ['--quantize', args.quantize]
    t0 = time.time()
    out_f = tempfile.NamedTemporaryFile('w+', suffix='.out', delete=False)
    err_f = tempfile.NamedTemporaryFile('w+', suffix='.err', delete=False)
    try:
        try:
            proc = subprocess.Popen(cmd, stdout=out_f, stderr=err_f, text=True)
        except Exception as e:
            print(f'bench child failed to launch: {e!r}', file=sys.stderr, flush=True)
            return None
        last_beat = time.time()
        beats = 0
        while proc.poll() is None:
            if time.time() - t0 > timeout_s:
                proc.kill()
                proc.wait()
                print(f'bench child timed out after {timeout_s}s', file=sys.stderr, flush=True)
                _status('measurement child timed out; killed')
                return None
            # Heartbeat is rate-limited to one line per ≥60s: BENCH_r05.json
            # recorded dozens of identical 25s "measuring" lines, which only
            # bloat the driver log — the line exists so a killed parent's tail
            # parses, not as a progress bar.
            if time.time() - last_beat >= HEARTBEAT_S:
                _status(f'measuring ({args.model} {args.bench}, child alive {time.time() - t0:.0f}s)')
                last_beat = time.time()
                beats += 1
            time.sleep(1)
        _status(f'measurement child finished (rc={proc.returncode}, {time.time() - t0:.0f}s, '
                f'{beats} heartbeat(s) suppressed to ≥{HEARTBEAT_S}s cadence)')
        out_f.seek(0)
        stdout = out_f.read()
        err_f.seek(0)
        stderr = err_f.read()
    finally:
        for f in (out_f, err_f):
            try:
                f.close()
                os.unlink(f.name)
            except OSError:
                pass
    for line in reversed((stdout or '').strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict) and 'value' in d:
                return d
        except Exception:
            continue
    # no parseable result: surface the child's diagnostics to the driver log
    tail = '\n'.join((stderr or '').strip().splitlines()[-15:])
    print(f'bench child rc={proc.returncode}, no result line; stderr tail:\n{tail}',
          file=sys.stderr, flush=True)
    return None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='vit_base_patch16_224')
    parser.add_argument('--bench', default='train', choices=['train', 'infer'])
    parser.add_argument('--batch-size', type=int, default=None)
    parser.add_argument('--img-size', type=int, default=224)
    parser.add_argument('--steps', type=int, default=10)
    parser.add_argument('--fast', action='store_true', help='small model / few steps smoke mode')
    parser.add_argument('--no-probe', action='store_true')
    # --- TPU alignment / precision A/B levers (PERF.md checklist items 3-4).
    # All default OFF = exact pre-PR numerics; each is independent.
    parser.add_argument('--pad-tokens', default='',
                        help="tile-align the ViT token count: 'auto' (next sublane "
                             "multiple, 197→200), an int (e.g. 256), or '' = off")
    parser.add_argument('--softmax-dtype', default='',
                        help="attention softmax internals: 'bfloat16' = fp32 max-"
                             "subtraction + bf16 exp/normalize, '' = legacy fp32")
    parser.add_argument('--norm-dtype', default='',
                        help="LayerNorm/RmsNorm statistics dtype: 'bfloat16' or '' = fp32")
    parser.add_argument('--mu-dtype', default='',
                        help="optimizer first-moment dtype: 'bfloat16' halves m HBM "
                             "traffic (v stays fp32), '' = fp32")
    parser.add_argument('--quantize', default='', choices=['', 'int8'],
                        help="serve-path weight quantization A/B: 'int8' runs the "
                             'measurement (--bench infer) against weight-only int8 '
                             'params with dequant fused at use; also smoked by '
                             "--dry-run. '' = dense weights")
    parser.add_argument('--block-scan', action='store_true', default=False,
                        help='scan-over-layers block execution: one lax.scan over '
                             'stacked per-layer params (O(1)-in-depth trace/compile)')
    parser.add_argument('--device-augment', action='store_true', default=False,
                        help='A/B the on-device data path: the train batch stays raw '
                             'uint8 with host-sampled augment params, and the jitted '
                             'normalize + mixup + erase program runs fused ahead of '
                             'every step (data/device_augment.py)')
    parser.add_argument('--fsdp', type=int, default=0, metavar='N',
                        help='shard params + optimizer state over an N-way fsdp mesh '
                             "axis (ZeRO-style; mesh becomes ('data', 'fsdp')); 0 = off")
    parser.add_argument('--tp', type=int, default=0, metavar='N',
                        help="tensor parallelism: N-way 'model' mesh axis sharding "
                             'attention heads + MLP hidden, with activation sharding '
                             'constraints on the residual stream; composes with --fsdp '
                             "(mesh becomes ('data'[, 'fsdp'], 'model')); 0 = off")
    parser.add_argument('--no-donate', action='store_true', default=False,
                        help='disable buffer donation of params/opt state in the jitted '
                             'step (A/B the input-output aliasing win)')
    parser.add_argument('--compile-report', action='store_true', default=False,
                        help='CPU compile-cost report: cold trace ms / cold compile ms / '
                             'warm-disk-cache ms / jaxpr equation counts, scan off vs on '
                             '(4 fresh child processes; no TPU, no probe)')
    parser.add_argument('--compile-child', action='store_true',
                        help='internal: run one compile-cost measurement in this process')
    parser.add_argument('--dry-run', action='store_true',
                        help='in-process CPU smoke: build the model + one tiny train/infer '
                             'step with the requested levers, print a result line, exit. '
                             'No probe, no child, no TPU.')
    parser.add_argument('--fault-inject', default='', metavar='SPEC',
                        help='(with --dry-run) also run the resilience fault-injection '
                             'selftest: truncated-checkpoint fallback, reader retry/backoff, '
                             'poison-skip budget, @-step faults incl. elastic resize@N:D '
                             '(fire-once parse + device-count capture). SPEC is '
                             'parse-checked; the canonical drill set always runs '
                             '(tier-1 smoke, no TPU).')
    parser.add_argument('--serve', action='store_true',
                        help='run the serving load drill instead of a train/infer bench: '
                             'canonical continuous-batching vs per-request A/B (two models, '
                             'two buckets, one LRU eviction) on synthetic open-loop Poisson '
                             'traffic, reporting p50/p99 latency and img/s. CPU-runnable; '
                             'combine with --dry-run for the tier-1 smoke.')
    parser.add_argument('--serve-requests', type=int, default=256, metavar='N',
                        help='(with --serve) requests per drill arm')
    parser.add_argument('--replay', action='store_true',
                        help='execute the entire queued PERF.md A/B checklist (donation, '
                             'pad-tokens, bf16 knobs, fsdp x tp grid, flash gate, profiler '
                             'trace, serve drill) as one scripted sequence, recording every '
                             'step into BENCH_SELF.json. Combine with --dry-run for the '
                             'tier-1 CPU smoke (tiny models, same code path).')
    parser.add_argument('--replay-steps', default='', metavar='A,B',
                        help='(with --replay) comma-separated subset of step ids')
    parser.add_argument('--kernels', action='store_true',
                        help='kernel portfolio win-or-delete A/B: run every registered '
                             'Pallas kernel (timm_tpu/kernels/registry.py) against its '
                             'XLA reference at the declared regime shapes and print one '
                             'keep/delete/pending verdict line per kernel, recording the '
                             'verdicts into BENCH_SELF.json. Combine with --dry-run for '
                             'the tier-1 CPU smoke (parity always runs; timed verdicts '
                             'settle on the claimed hardware). Also runs as the replay '
                             "checklist's `kernels` step.")
    parser.add_argument('--analysis', action='store_true',
                        help='run the static-analysis suite (timm_tpu/analysis: source/'
                             'jaxpr/HLO rules + zoo abstract-trace) and record the report '
                             'into BENCH_SELF.json. Combine with --dry-run for the cheap '
                             'arm (Tier A source rules + zoo smoke, no probe lowering) — '
                             "the same spec the replay checklist's `analysis` step smokes "
                             'in tier-1; the full run also walks the jaxpr/HLO of every '
                             'probe program. Exit 0 clean / 2 violations / 3 analyzer '
                             'error.')
    parser.add_argument('--profile', action='store_true',
                        help='capture a jax.profiler trace of the train step for --model '
                             'and print the self-parsed MXU vs non-MXU op summary '
                             '(PERF.md checklist item 6, unattended)')
    parser.add_argument('--profile-dir', default='', metavar='DIR',
                        help='trace output dir (default: a fresh temp dir; TensorBoard-'
                             'loadable for the deep-dive)')
    parser.add_argument('--child', action='store_true',
                        help='internal: run the measurement in this process')
    parser.add_argument('--watchdog-s', type=int, default=None,
                        help='internal: child wedge-backstop seconds (set by parent)')
    parser.add_argument('--save-self', action='store_true',
                        help='on success, record result to BENCH_SELF.json')
    args = parser.parse_args()
    if (args.quantize and args.bench == 'train'
            and not (args.dry_run or args.serve or args.replay
                     or args.profile or args.compile_report)):
        parser.error('--quantize int8 quantizes weights for the serve path; '
                     'measure it with --bench infer (or smoke with --dry-run)')
    if args.fast:
        args.model = 'vit_tiny_patch16_224'
        args.steps = 5

    if args.compile_child:
        raise SystemExit(_compile_child(args))

    if args.compile_report:
        raise SystemExit(_compile_report(args))

    if args.replay:
        raise SystemExit(_replay_checklist(args))

    if args.kernels:
        raise SystemExit(_kernels_ab(args))

    if args.analysis:
        raise SystemExit(_analysis(args))

    if args.profile:
        raise SystemExit(_profile_run(args))

    if args.serve:
        raise SystemExit(_serve_drill(args))

    if args.dry_run:
        raise SystemExit(_dry_run(args))

    if args.child:
        raise SystemExit(_measure(args))

    # ---- parent orchestration: never touches the device itself ----
    _status('started, probing TPU')

    probed_ok = True
    if not args.no_probe:
        # One short probe; its only purpose is to distinguish "unreachable
        # relay" (replay is honest) from "code regression" (report 0.0).
        probed_ok = _probe_device(timeout_s=int(min(PROBE_TIMEOUT, max(10, _remaining() - 60))))
        _status(f'probe {"succeeded" if probed_ok else "FAILED"}, launching measurement')

    # Even if the probe failed, still attempt the real run: the probe process
    # itself may have wedged where a fresh process would not. A live probe
    # earns retries against the remaining budget; a FAILED probe gets exactly
    # one fresh-process attempt capped at PROBE_TIMEOUT, so a downed relay
    # aborts in ~2x TIMM_TPU_BENCH_PROBE_TIMEOUT instead of eating the whole
    # budget in wedged children.
    result = None
    attempts_made = 0
    while _remaining() - 15 >= 30 and attempts_made < _max_attempts(probed_ok):
        child_budget = int(_remaining() - 15)
        if not probed_ok:
            child_budget = min(child_budget, PROBE_TIMEOUT)
        result = _run_child(args, child_budget)
        attempts_made += 1
        if result is not None and result.get('value', 0) > 0:
            break

    if result is not None and result.get('value', 0) > 0:
        print(json.dumps(result), flush=True)
        if args.save_self:
            # v2 document writer: preserves the abort history + last replay
            # run instead of clobbering the whole file with a bare result
            from timm_tpu.perfbudget.replay import record_result
            record_result(SELF_RESULT_PATH, result)
        raise SystemExit(0)

    attempted = (f'{attempts_made} fresh-process bench attempt(s) failed'
                 if attempts_made else 'no bench attempt fit the remaining budget')
    if not probed_ok:
        # Device provably unreachable: replay is honest here (and exits 3).
        reason = f'TPU unreachable: probe failed and {attempted}'
        _record_abort(reason, args)
        raise SystemExit(_replay_self_result(reason))
    if not attempts_made:
        _record_abort('INCOMPLETE: probe succeeded but no bench attempt fit the budget', args)
        print(json.dumps({
            'metric': 'benchmark INCOMPLETE: probe succeeded but no bench attempt fit '
                      f'the remaining budget (BENCH_TOTAL_BUDGET={TOTAL_BUDGET}s too small)',
            'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
        raise SystemExit(2)
    # Probe succeeded but the bench failed: a genuine regression.
    # Never mask it with a stale replay — report 0.0 and fail.
    _record_abort(f'FAILED: {attempted} despite a live device probe', args)
    print(json.dumps({
        'metric': f'benchmark FAILED: {attempted} despite a '
                  'live device probe (likely code regression; see stderr)',
        'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
    raise SystemExit(2)


def _apply_precision_knobs(args):
    """Activate the requested alignment/precision levers process-wide and
    return (model_kwargs, opt_kwargs, tag) for the run. Every lever defaults
    off → this is a no-op returning empty kwargs and '' tag."""
    from timm_tpu.layers import set_norm_internal_dtype, set_softmax_dtype
    model_kwargs, opt_kwargs, tags = {}, {}, []
    if args.pad_tokens:
        pad = args.pad_tokens if args.pad_tokens == 'auto' else int(args.pad_tokens)
        model_kwargs['pad_tokens_to'] = pad
        tags.append(f'pad_tokens={args.pad_tokens}')
    if args.softmax_dtype:
        set_softmax_dtype(args.softmax_dtype)
        tags.append(f'softmax={args.softmax_dtype}')
    if args.norm_dtype:
        set_norm_internal_dtype(args.norm_dtype)
        tags.append(f'norm={args.norm_dtype}')
    if args.mu_dtype:
        opt_kwargs['mu_dtype'] = args.mu_dtype
        tags.append(f'mu={args.mu_dtype}')
    return model_kwargs, opt_kwargs, (' [' + ', '.join(tags) + ']' if tags else '')


def _dry_run(args) -> int:
    """CPU smoke path for the A/B levers: builds the model with the requested
    knobs and runs one tiny train + infer step in-process. Exists so every
    flag combination has a fast correctness gate that needs no TPU
    (tests/test_precision_policy.py sweeps it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import nnx

    import timm_tpu
    from timm_tpu.loss import cross_entropy
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.parallel import (
        build_opt_shardings, build_param_shardings, create_mesh, set_global_mesh, shard_batch,
    )
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()
    # single-device mesh unless --fsdp/--tp is being smoked: SPMD-partitioning
    # the tiny dry-run program over every visible device multiplies its compile
    # cost for no extra coverage (the flag-combination sweep runs 9 of these)
    fsdp = getattr(args, 'fsdp', 0)
    tp = getattr(args, 'tp', 0)
    if fsdp or tp:
        mesh = create_mesh(fsdp=fsdp or None, tp=tp or None)
    else:
        mesh = create_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    model_kwargs, opt_kwargs, tag = _apply_precision_knobs(args)
    img = min(args.img_size, 64)  # tiny input: the gate is "traces + runs", not perf
    model = timm_tpu.create_model(args.model, img_size=img, **model_kwargs)
    if getattr(args, 'block_scan', False) and hasattr(model, 'set_block_scan'):
        model.set_block_scan(True)
        tag += ' [block_scan]'
    if getattr(args, 'fsdp', 0):
        tag += f' [fsdp={args.fsdp}]'
    if getattr(args, 'tp', 0):
        tag += f' [tp={args.tp}]'
    if getattr(args, 'no_donate', False):
        tag += ' [no-donate]'
    rng = np.random.RandomState(0)
    n = max(2, mesh.size)  # batch must divide over the mesh batch axes
    if getattr(args, 'device_augment', False):
        import functools

        from timm_tpu.data.device_augment import augment_image_batch
        tag += ' [device_augment]'
        raw = shard_batch({
            'image': jnp.asarray((rng.rand(n, img, img, 3) * 255).astype(np.uint8)),
            'target': jnp.asarray(rng.randint(0, model.num_classes, n)),
            'lam': jnp.full((n,), 0.7, jnp.float32),
            'use_cutmix': jnp.zeros((n,), bool),
            'bbox': jnp.zeros((n, 4), jnp.int32)}, mesh)
        aug_fn = functools.partial(
            augment_image_batch, mean=(0.5,) * 3, std=(0.5,) * 3,
            num_classes=model.num_classes, smoothing=0.1)
        x, y_soft = jax.jit(aug_fn)(raw)  # not donated: x feeds the eval pass too

        def loss_for(m):
            # soft-target CE mirrors the device-mixup train path
            return -(y_soft * jax.nn.log_softmax(m(x))).sum(-1).mean()
    else:
        batch = shard_batch({'x': jnp.asarray(rng.rand(n, img, img, 3), jnp.float32),
                             't': jnp.asarray(rng.randint(0, model.num_classes, n))}, mesh)
        x, t = batch['x'], batch['t']

        def loss_for(m):
            return cross_entropy(m(x), t)

    model.train()
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05, **opt_kwargs)
    graphdef, params, rest = nnx.split(model, nnx.Param, ...)
    param_sh = build_param_shardings(params, mesh)
    opt_sh, _ = build_opt_shardings(opt, params, mesh)
    params = jax.device_put(params, param_sh)
    opt_state = jax.jit(opt.init, out_shardings=opt_sh)(params)  # no-donate: init

    def train_step(p, o):
        def loss_fn(p):
            m = nnx.merge(graphdef, p, rest)
            return loss_for(m)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = opt.update(grads, o, p, lr=1e-3)
        return optax.apply_updates(p, updates), o, loss

    donate = () if getattr(args, 'no_donate', False) else (0, 1)
    params, opt_state, loss = jax.jit(
        train_step, donate_argnums=donate,
        in_shardings=(param_sh, opt_sh), out_shardings=(param_sh, opt_sh, None))(params, opt_state)
    model = nnx.merge(graphdef, params, rest)
    model.eval()
    logits = model(x)
    ok = bool(jnp.isfinite(loss)) and bool(jnp.isfinite(logits).all())
    quant_note = ''
    if getattr(args, 'quantize', ''):
        # int8 arm: quantize the just-trained eval state and run the same
        # batch through the dequant-at-use program; the gate is "stays finite
        # and tracks the fp32 logits", the tight tolerance lives in tier-1
        from timm_tpu.quantize import dequantize_tree, quantize_tree
        tag += ' [quant=int8]'
        gd_e, st_e = nnx.split(model)
        qstate = quantize_tree(st_e)
        qlogits = jax.jit(
            lambda q, xx: nnx.merge(gd_e, dequantize_tree(q))(xx))(qstate, x)
        qdiff = float(jnp.max(jnp.abs(qlogits.astype(jnp.float32)
                                      - logits.astype(jnp.float32))))
        ok = ok and bool(jnp.isfinite(qlogits).all())
        quant_note = f', int8 logits max|d|={qdiff:.4f}'
    fault_note = ''
    if getattr(args, 'fault_inject', ''):
        # exercise the injection hooks + their recovery paths without a slow
        # run: truncate→fallback, io_error→retry, poison budget, @-faults
        from timm_tpu.resilience import fault_selftest
        drill = fault_selftest(getattr(args, 'fault_inject', ''))
        ok = ok and drill['ok']
        failed = [k for k, v in drill['checks'].items() if not v]
        fault_note = (f', fault-inject drills {"all passed" if drill["ok"] else f"FAILED: {failed}"}'
                      f' ({len(drill["checks"])} checks)')
    print(json.dumps({
        'metric': f'dry-run {args.model}{tag}: 1 train step + 1 infer step on '
                  f'{jax.default_backend()}, loss finite={ok}{quant_note}{fault_note}',
        'value': 1.0 if ok else 0.0, 'unit': 'ok', 'vs_baseline': None}), flush=True)
    return 0 if ok else 2


def _serve_drill(args) -> int:
    """Canonical serving A/B drill (ISSUE 8 acceptance): the SAME open-loop
    Poisson schedule against the continuous-batching engine (buckets (4, 16),
    two models under an HBM budget that forces one LRU eviction) and the
    per-request baseline (bucket (1,), zero wait). Prints the human p50/p99
    summary line, then the JSON result line whose value is the img/s speedup.
    CPU-runnable end to end — wired like the --fault-inject drill smoke."""
    from timm_tpu.serve import canonical_drill, summary_line

    _status('serve drill: continuous-batching vs per-request A/B')
    t0 = time.perf_counter()
    try:
        ab = canonical_drill(num_requests=args.serve_requests,
                             persist_all_programs=True)
    except AssertionError as e:
        print(json.dumps({
            'metric': f'serve drill FAILED: {e}',
            'value': 0.0, 'unit': 'x img/s vs per-request', 'vs_baseline': None}),
            flush=True)
        return 2
    c, b = ab['continuous'], ab['per_request']
    print(summary_line(ab), flush=True)
    print(json.dumps({
        'metric': (f'serve drill: continuous-batching img/s vs per-request at equal '
                   f'offered load ({c["num_requests"]} reqs @ {c["offered_rps"]} req/s; '
                   f'continuous p50 {c["p50_ms"]}ms p99 {c["p99_ms"]}ms, '
                   f'per-request p50 {b["p50_ms"]}ms p99 {b["p99_ms"]}ms; '
                   f'buckets {tuple(c["buckets"])}, {c["evictions"]} eviction(s), '
                   f'{time.perf_counter() - t0:.1f}s wall)'),
        'value': ab['speedup'], 'unit': 'x img/s vs per-request',
        'vs_baseline': None}), flush=True)
    return 0


def _force_cpu_topology():
    """The fsdp x tp replay/profile steps need 8 devices; a CPU host only
    grows them if the XLA flag is exported before jax's FIRST import (no-op
    once jax is loaded, and harmless on a real TPU backend)."""
    if 'jax' in sys.modules:
        return
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()


def _record_abort(reason: str, args) -> None:
    """An aborted round used to leave an EMPTY BENCH_SELF.json behind (the
    round-4/round-5 failure mode); now it appends a structured abort record
    to the v2 document while preserving the last good result. Gated on
    --save-self (same consent as the result write) and must never itself
    take the process down."""
    if not args.save_self:
        return
    try:
        from timm_tpu.perfbudget.replay import record_abort
        record_abort(SELF_RESULT_PATH, reason, {
            'model': args.model, 'bench': args.bench,
            'budget_s': TOTAL_BUDGET, 'probe_timeout_s': PROBE_TIMEOUT})
    except Exception as e:
        print(f'abort record failed: {e!r}', file=sys.stderr, flush=True)


def _replay_checklist(args) -> int:
    """The whole queued PERF.md "next-round on-device checklist" as ONE
    unattended scripted sequence (timm_tpu.perfbudget.replay). --dry-run is
    the tier-1 CPU smoke over the identical code path; live mode is the real
    relay-window run. Either way every step's record streams into
    BENCH_SELF.json as it lands, so a run killed mid-checklist keeps
    everything measured so far."""
    _force_cpu_topology()
    from timm_tpu.perfbudget.replay import load_self_doc, run_replay, validate_self_result
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()
    names = [s.strip() for s in args.replay_steps.split(',') if s.strip()] or None
    _status(f'replay: PERF.md checklist ({"dry-run" if args.dry_run else "LIVE"})')
    doc, rc = run_replay(dry_run=args.dry_run, self_path=SELF_RESULT_PATH,
                         names=names, trace_dir=args.profile_dir or None,
                         log=lambda m: _status(m))
    errs = validate_self_result(load_self_doc(SELF_RESULT_PATH))
    statuses = ' '.join(f"{s['id']}={s['status']}" for s in doc['steps'])
    print(json.dumps({
        'metric': (f"replay ({'dry-run' if args.dry_run else 'live'}): "
                   f"{doc['completed']}/{doc['total']} ok, {doc['failed']} failed, "
                   f"{doc['skipped']} skipped -> {SELF_RESULT_PATH} [{statuses}]"
                   + (f'; SCHEMA ERRORS: {errs}' if errs else '')),
        'value': float(doc['completed']), 'unit': 'checklist steps ok',
        'vs_baseline': None}), flush=True)
    return rc if not errs else (rc or 2)


def _kernels_ab(args) -> int:
    """Kernel-portfolio win-or-delete A/B (PERF.md 'Kernel portfolio &
    win-or-delete harness'): every registered Pallas kernel runs its declared
    regime cases against its XLA reference — parity first (a kernel that is
    wrong gets 'delete' without being timed), then wall-clock on hardware the
    kernel actually claimed. The dry-run arm is the tier-1 CPU smoke: parity
    still gates, TPU-claimed kernels come back 'pending'. Verdict records
    stream into BENCH_SELF.json so the round file carries the decision data
    even when the driver keeps only the tail line."""
    _force_cpu_topology()
    from timm_tpu.perfbudget.replay import load_self_doc, save_self_doc
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()
    from timm_tpu.kernels.harness import format_verdict_line, run_kernel_ab

    live = not args.dry_run
    _status(f'kernels: portfolio win-or-delete A/B ({"LIVE" if live else "dry-run"})')
    verdicts = run_kernel_ab(live=live, steps=max(1, min(args.steps, 20)))
    for rec in verdicts:
        print(format_verdict_line(rec), flush=True)
    doc = load_self_doc(SELF_RESULT_PATH)
    doc['kernels'] = {'at': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
                      'live': live, 'verdicts': verdicts}
    save_self_doc(SELF_RESULT_PATH, doc)
    counts = {v: sum(1 for r in verdicts if r['verdict'] == v)
              for v in ('keep', 'pending', 'delete')}
    print(json.dumps({
        'metric': (f"kernel portfolio A/B ({'live' if live else 'dry-run'}): "
                   f"{counts['keep']} keep, {counts['pending']} pending, "
                   f"{counts['delete']} delete of {len(verdicts)} registered "
                   f'-> {SELF_RESULT_PATH}'),
        'value': float(len(verdicts) - counts['delete']),
        'unit': 'kernels surviving', 'vs_baseline': None}), flush=True)
    return 0 if counts['delete'] == 0 else 2


def _analysis(args) -> int:
    """Static-analysis gate as a bench mode: the same suite the replay
    checklist's `analysis` step runs, callable standalone so a bench round
    (and .bench_loop.sh) can refuse to measure a repo the analyzers reject.
    --dry-run is the cheap arm (Tier A source rules + the zoo smoke subset,
    no probe lowering); full mode runs every rule, including the jaxpr/HLO
    passes over the freshly lowered probe programs. The per-rule report
    lands in BENCH_SELF.json next to the kernel verdicts."""
    _force_cpu_topology()
    from timm_tpu.perfbudget.replay import _run_analysis, load_self_doc, save_self_doc
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()
    _status(f'analysis: static-analysis suite ({"dry-run" if args.dry_run else "full"})')
    spec = dict(tiers=('A',), zoo='smoke') if args.dry_run else {}
    result = _run_analysis(spec)
    doc = load_self_doc(SELF_RESULT_PATH)
    doc['analysis'] = dict(result, at=time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()))
    save_self_doc(SELF_RESULT_PATH, doc)
    print(json.dumps({
        'metric': (f"static analysis ({'dry-run' if args.dry_run else 'full'}): "
                   f"{result['violations']} violation(s), {result['waived']} waived, "
                   f"{len(result['errors'])} analyzer error(s) -> {SELF_RESULT_PATH}"),
        'value': float(result['violations']), 'unit': 'violations',
        'vs_baseline': None}), flush=True)
    return result['exit_code']


def _profile_run(args) -> int:
    """Unattended profiler harness (PERF.md checklist item 6): capture a
    jax.profiler trace of the train step for --model and print the
    self-parsed MXU vs non-MXU op-category summary. The trace directory is
    kept on disk (TensorBoard/XProf-loadable) for the human deep-dive."""
    _force_cpu_topology()
    from timm_tpu.perfbudget.replay import _run_profile
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()
    img = min(args.img_size, 64) if args.dry_run else args.img_size
    spec = {'model': args.model, 'img_size': img,
            'batch': args.batch_size or (8 if args.dry_run else 32),
            'steps': max(1, min(args.steps, 3))}
    if args.fsdp:
        spec['fsdp'] = args.fsdp
    if args.tp:
        spec['tp'] = args.tp
    _status(f'profile: tracing {args.model} train step ({spec["steps"]} step(s))')
    summary = _run_profile(spec, args.profile_dir or None)
    ok = summary.get('status') == 'ok'
    mxu = summary.get('mxu_frac')
    print(json.dumps({
        'metric': (f"profiler trace {args.model}: {summary.get('total_events', 0)} device-op "
                   f"events, MXU {summary.get('mxu_us', 0.0):.0f}us vs other "
                   f"{summary.get('non_mxu_us', 0.0):.0f}us -> {summary.get('trace_dir', '?')}"),
        'value': round(mxu, 4) if mxu is not None else 0.0,
        'unit': 'MXU time fraction', 'vs_baseline': None,
        'summary': summary}), flush=True)
    return 0 if ok else 2


def _compile_child(args) -> int:
    """One compile-cost measurement in a FRESH process (so 'cold' means cold):
    trace ms, lower+compile ms (hits the persistent disk cache when
    TIMM_TPU_COMPILE_CACHE points at a warm dir), total jaxpr equation count."""
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')  # compile cost needs no TPU
    # timm-tpu-lint: disable=silent-except platform may be pinned after jax init; cpu is the fallback either way
    except Exception:
        pass
    from timm_tpu.utils.compile_cache import configure_compile_cache, count_jaxpr_eqns
    cache_dir = configure_compile_cache()

    import jax.numpy as jnp
    from flax import nnx

    import timm_tpu

    model = timm_tpu.create_model(args.model, img_size=args.img_size)
    if args.block_scan and hasattr(model, 'set_block_scan'):
        model.set_block_scan(True)
    model.eval()
    graphdef, state = nnx.split(model)
    x = jnp.zeros((2, args.img_size, args.img_size, 3), jnp.float32)

    def fwd(s, xx):
        return nnx.merge(graphdef, s)(xx)

    t0 = time.perf_counter()
    traced = jax.jit(fwd).trace(state, x)
    trace_ms = (time.perf_counter() - t0) * 1e3
    eqns = count_jaxpr_eqns(traced.jaxpr)
    t0 = time.perf_counter()
    traced.lower().compile()
    compile_ms = (time.perf_counter() - t0) * 1e3
    print(json.dumps({
        'metric': f'{args.model} fwd compile cost (scan={"on" if args.block_scan else "off"}, '
                  f'cache={"set" if cache_dir else "off"})',
        'value': round(trace_ms + compile_ms, 1), 'unit': 'ms', 'vs_baseline': None,
        'trace_ms': round(trace_ms, 1), 'compile_ms': round(compile_ms, 1),
        'jaxpr_eqns': eqns}), flush=True)
    return 0


def _run_compile_child(args, block_scan: bool, cache_dir: str):
    """Spawn a fresh-process _compile_child run and parse its result line."""
    cmd = [sys.executable, os.path.abspath(__file__), '--compile-child',
           '--model', args.model, '--img-size', str(args.img_size)]
    if block_scan:
        cmd += ['--block-scan']
    env = dict(os.environ, JAX_PLATFORMS='cpu', TIMM_TPU_COMPILE_CACHE=cache_dir)
    try:
        r = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        return None
    for line in reversed((r.stdout or '').strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict) and 'trace_ms' in d:
                return d
        except Exception:
            continue
    tail = '\n'.join((r.stderr or '').strip().splitlines()[-10:])
    print(f'compile child rc={r.returncode}, no result; stderr tail:\n{tail}',
          file=sys.stderr, flush=True)
    return None


def _compile_report(args) -> int:
    """Compile & input-pipeline cost report (PERF.md 'compile & input
    pipeline'): for scan off/on, run a COLD child (fresh process, empty disk
    cache) and a WARM child (fresh process, same disk cache) and report
    cold-trace / cold-compile / warm-compile ms + jaxpr equation counts. CPU
    only, measurable with the TPU relay down."""
    import shutil
    import tempfile

    _status('compile-report: 4 fresh-process measurements (scan off/on x cold/warm)')
    rows = {}
    for scan in (False, True):
        cache_dir = tempfile.mkdtemp(prefix='timm_tpu_ccache_')
        try:
            for run in ('cold', 'warm'):
                r = _run_compile_child(args, scan, cache_dir)
                if r is None:
                    print(json.dumps({
                        'metric': f'compile-report FAILED at scan={scan} {run}',
                        'value': 0.0, 'unit': 'x', 'vs_baseline': None}), flush=True)
                    return 2
                rows[(scan, run)] = r
                _status(f'compile-report: scan={"on" if scan else "off"} {run}: '
                        f'trace {r["trace_ms"]}ms compile {r["compile_ms"]}ms '
                        f'eqns {r["jaxpr_eqns"]}')
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)

    def total(scan, run):
        r = rows[(scan, run)]
        return r['trace_ms'] + r['compile_ms']

    scan_speedup = total(False, 'cold') / max(total(True, 'cold'), 1e-9)
    warm_ratio = rows[(True, 'warm')]['compile_ms'] / max(rows[(True, 'cold')]['compile_ms'], 1e-9)
    eqn_ratio = rows[(False, 'cold')]['jaxpr_eqns'] / max(rows[(True, 'cold')]['jaxpr_eqns'], 1)
    print(json.dumps({
        'metric': (f'{args.model} compile report: cold trace+compile '
                   f'{total(False, "cold"):.0f}ms (loop) -> {total(True, "cold"):.0f}ms (scan) '
                   f'= {scan_speedup:.1f}x; warm disk-cache compile '
                   f'{rows[(True, "warm")]["compile_ms"]:.0f}ms vs cold '
                   f'{rows[(True, "cold")]["compile_ms"]:.0f}ms; jaxpr eqns '
                   f'{rows[(False, "cold")]["jaxpr_eqns"]} (loop) vs '
                   f'{rows[(True, "cold")]["jaxpr_eqns"]} (scan, {eqn_ratio:.1f}x fewer)'),
        'value': round(scan_speedup, 2), 'unit': 'x cold trace+compile (scan vs loop)',
        'vs_baseline': None,
        'detail': {f'{"scan" if s else "loop"}_{r}': rows[(s, r)]
                   for s in (False, True) for r in ('cold', 'warm')},
        'warm_vs_cold_compile': round(warm_ratio, 3)}), flush=True)
    return 0


def _measure(args) -> int:
    """The actual device measurement (runs in the child process)."""
    # The parent enforces the real budget; this is a backstop so a wedged
    # device op can't outlive the parent's kill by hanging in C++. The parent
    # passes its enforced budget (+grace) via --watchdog-s; standalone --child
    # runs fall back to the total budget.
    _arm_watchdog(args.watchdog_s if args.watchdog_s else TOTAL_BUDGET)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import nnx

    import timm_tpu
    from timm_tpu.loss import cross_entropy
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.parallel import (
        build_opt_shardings, build_param_shardings, create_mesh, data_sharding,
        replicate_sharding, set_global_mesh,
    )
    from timm_tpu.utils import configure_compile_cache

    configure_compile_cache()

    mesh = create_mesh(fsdp=args.fsdp if args.fsdp else None,
                       tp=args.tp if args.tp else None)
    set_global_mesh(mesh)
    n_chips = mesh.size
    # bs128/chip benched fastest for ViT-B train on v5e with the einsum
    # attention path (867 img/s vs 786 w/ XLA dot_product_attention, 758 @64)
    batch_size = args.batch_size or ((128 if args.bench == 'train' else 256) * n_chips)
    K = args.steps

    model_kwargs, opt_kwargs, knob_tag = _apply_precision_knobs(args)
    kwargs = dict(model_kwargs)
    if args.img_size != 224:
        kwargs['img_size'] = args.img_size
    model = timm_tpu.create_model(args.model, dtype=jnp.bfloat16, **kwargs)
    if args.block_scan and hasattr(model, 'set_block_scan'):
        model.set_block_scan(True)
        knob_tag += ' [block_scan]'

    rng = np.random.RandomState(0)
    x = jax.device_put(
        jnp.asarray(rng.rand(batch_size, args.img_size, args.img_size, 3), jnp.bfloat16),
        data_sharding(mesh, 4))
    t = jax.device_put(jnp.asarray(rng.randint(0, model.num_classes, batch_size)),
                       data_sharding(mesh, 1))

    aug_fn = aug_raw = None
    if args.device_augment:
        # on-device data path A/B: the batch stays raw uint8 + host-sampled
        # params, and the augment program runs fused inside the scanned step
        # so its per-step cost rides the measurement
        import functools

        from timm_tpu.data.device_augment import augment_image_batch
        s = args.img_size
        aug_raw = {
            'image': jax.device_put(jnp.asarray(
                rng.randint(0, 256, (batch_size, s, s, 3)).astype(np.uint8)),
                data_sharding(mesh, 4)),
            'target': t,
            'lam': jax.device_put(jnp.asarray(rng.beta(0.8, 0.8, batch_size), jnp.float32),
                                  data_sharding(mesh, 1)),
            'use_cutmix': jax.device_put(jnp.zeros((batch_size,), bool),
                                         data_sharding(mesh, 1)),
            'bbox': jax.device_put(jnp.zeros((batch_size, 4), jnp.int32),
                                   data_sharding(mesh, 2)),
        }
        aug_fn = functools.partial(
            augment_image_batch, mean=(0.5,) * 3, std=(0.5,) * 3,
            num_classes=model.num_classes, smoothing=0.1, out_dtype=jnp.bfloat16)
        knob_tag += ' [device_augment]'

    if args.bench == 'train':
        model.train()
        opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05, **opt_kwargs)
        graphdef, params, rest = nnx.split(model, nnx.Param, ...)
        # FSDP placement: large weights + their m/v shard over the 'fsdp'
        # axis (replicated-everything when the mesh has no such axis)
        param_sh = build_param_shardings(params, mesh)
        opt_sh, _ = build_opt_shardings(opt, params, mesh)
        params = jax.device_put(params, param_sh)
        # abstract on-mesh init: replicated m/v never materialize
        opt_state = jax.jit(opt.init, out_shardings=opt_sh)(params)  # no-donate: init

        # donation + returning the updated state lets XLA alias the params and
        # AdamW buffers in place (input-output aliasing): ~1 GB less HBM copy
        # traffic per fused K-step call for ViT-B. --no-donate A/Bs it off.
        donate = () if args.no_donate else (0, 1)

        def multi_step(params, opt_state, x, t):
            def body(carry, _):
                params, opt_state = carry

                def loss_fn(p):
                    m = nnx.merge(graphdef, p, rest)
                    if aug_fn is not None:
                        xf, y = aug_fn(x)  # x is the raw uint8 batch dict
                        return -(y * jax.nn.log_softmax(
                            m(xf).astype(jnp.float32))).sum(-1).mean()
                    return cross_entropy(m(x), t)
                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params, lr=1e-3)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss
            (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), None, length=K)
            return params, opt_state, losses[-1]

        if aug_fn is not None:
            x = aug_raw  # the augment program consumes the whole param'd batch
            x_sh = {'image': data_sharding(mesh, 4), 'target': data_sharding(mesh, 1),
                    'lam': data_sharding(mesh, 1), 'use_cutmix': data_sharding(mesh, 1),
                    'bbox': data_sharding(mesh, 2)}
        else:
            x_sh = data_sharding(mesh, 4)
        multi_step = jax.jit(
            multi_step, donate_argnums=donate,
            in_shardings=(param_sh, opt_sh, x_sh, data_sharding(mesh, 1)),
            out_shardings=(param_sh, opt_sh, replicate_sharding(mesh)))

        # warm-up compiles + runs once; its returned state feeds the timed
        # call (donation invalidates the inputs, and chaining state is the
        # realistic steady-state pattern)
        params, opt_state, out = multi_step(params, opt_state, x, t)
        float(out)
        t0 = time.perf_counter()
        params, opt_state, out = multi_step(params, opt_state, x, t)
        float(out)
        dt = time.perf_counter() - t0
        flops_mult = 3.0  # fwd + bwd
    else:
        model.eval()
        graphdef, state = nnx.split(model)
        if args.quantize:
            # serve-path A/B: the program's weight inputs become the int8
            # qvalues + scales; dequant runs at use inside every scanned
            # forward, so HBM holds (and streams) the ~0.27x footprint
            from timm_tpu.quantize import dequantize_tree, quantize_tree
            state = quantize_tree(state)
            knob_tag += ' [quant=int8]'

        @jax.jit
        def multi_fwd(state, x):
            def body(carry, _):
                m_state = dequantize_tree(state) if args.quantize else state
                out = nnx.merge(graphdef, m_state)(x + carry * 0)
                return out.mean().astype(jnp.bfloat16), ()
            final, _ = jax.lax.scan(body, jnp.zeros((), jnp.bfloat16), None, length=K)
            return final

        float(multi_fwd(state, x))
        t0 = time.perf_counter()
        float(multi_fwd(state, x))
        dt = time.perf_counter() - t0
        flops_mult = 1.0

    per_step = dt / K
    img_per_sec_chip = batch_size / per_step / n_chips

    # MFU from compiled forward cost
    mfu = None
    try:
        graphdef_e, state_e = nnx.split(model)
        x_e = x['image'].astype(jnp.bfloat16) / 255 if isinstance(x, dict) else x
        fwd_flops = jax.jit(lambda s, xx: nnx.merge(graphdef_e, s)(xx)).lower(
            state_e, x_e).compile().cost_analysis().get('flops', 0)
        kind = jax.devices()[0].device_kind.lower().replace(' ', '').replace('tpu', '')
        peak = next((v for k, v in CHIP_PEAK.items() if k in kind or kind in k), 197e12)
        mfu = (fwd_flops * flops_mult / n_chips) / per_step / peak
    # timm-tpu-lint: disable=silent-except MFU is best-effort decoration (cost_analysis may be absent); the bench result row stands without it
    except Exception:
        pass

    if _WATCHDOG is not None:
        _WATCHDOG.cancel()  # measurement done; disarm watchdog
    baseline = BASELINES.get((args.model, args.bench))
    # mesh shape + donation state make BENCH_*.json rows attributable to the
    # sharding/donation configuration that produced them
    mesh_tag = 'x'.join(str(mesh.shape[a]) for a in mesh.axis_names) + f'({",".join(mesh.axis_names)})'
    knob_tag += f' [mesh={mesh_tag}, donate={"off" if args.no_donate else "on"}]'
    metric = f'{args.model} {args.bench} img/s/chip (bf16, bs{batch_size}, {n_chips} chip){knob_tag}'
    if mfu is not None:
        metric += f', MFU={mfu:.2f}'
    print(json.dumps({
        'metric': metric,
        'value': round(img_per_sec_chip, 1),
        'unit': 'img/s/chip',
        'vs_baseline': round(img_per_sec_chip / baseline, 3) if baseline else None,
    }))
    return 0


if __name__ == '__main__':
    main()
