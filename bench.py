#!/usr/bin/env python3
"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Headline: ViT-B/16 @224 train-step throughput (img/s/chip), bf16, batch 128
per chip, AdamW — vs the reference's published train throughput for the same
model (BASELINE.md: 393.0 img/s, RTX 3090 AMP NHWC).

Methodology: K steps are fused into ONE XLA program (lax.scan carrying
params/opt-state), so the measurement is pure device time — host dispatch and
transfer latency (large through the axon relay) is excluded, matching how the
reference's CUDA-event timing excludes host overhead (benchmark.py:149-157).
"""
from __future__ import annotations

import argparse
import json
import time

BASELINES = {
    ('vit_base_patch16_224', 'train'): 393.0,
    ('vit_base_patch16_224', 'infer'): 3915.6,
    ('vit_tiny_patch16_224', 'train'): 2299.6,
    ('vit_tiny_patch16_224', 'infer'): 26140.3,
    ('convnext_base', 'train'): 338.7,
    ('convnext_base', 'infer'): 2618.0,
    ('efficientnetv2_s', 'train'): 559.2,
    ('efficientnetv2_s', 'infer'): 3683.6,
}

# bf16 peak FLOP/s per chip for MFU reporting
CHIP_PEAK = {'v5e': 197e12, 'v5litepod': 197e12, 'v4': 275e12, 'v5p': 459e12, 'v6e': 918e12}


_WATCHDOG = None


def _arm_watchdog(seconds: int = 540):
    """Emit an error JSON line and exit instead of hanging forever if the TPU
    relay is wedged (observed: a stale tile lease makes every device op block
    inside PJRT C++, where signals can't preempt — so use a timer thread and
    os._exit, which works regardless of where the main thread is stuck)."""
    import os
    import sys
    import threading
    global _WATCHDOG

    def fire():
        print(json.dumps({
            'metric': 'benchmark watchdog: TPU unreachable (device ops hung)',
            'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
        sys.stdout.flush()
        os._exit(2)

    _WATCHDOG = threading.Timer(seconds, fire)
    _WATCHDOG.daemon = True
    _WATCHDOG.start()


def _probe_device(timeout_s: int = 120) -> bool:
    """Run a tiny device op in a SUBPROCESS so a wedged relay can't hang us.
    Returns True if the TPU answers within the timeout."""
    import subprocess
    import sys
    code = (
        'import jax, jax.numpy as jnp\n'
        'x = jnp.ones((128, 128))\n'
        'print(float((x @ x).sum()))\n'
    )
    try:
        r = subprocess.run([sys.executable, '-c', code], timeout=timeout_s,
                           capture_output=True)
        return r.returncode == 0
    except Exception:
        return False


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='vit_base_patch16_224')
    parser.add_argument('--bench', default='train', choices=['train', 'infer'])
    parser.add_argument('--batch-size', type=int, default=None)
    parser.add_argument('--img-size', type=int, default=224)
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--fast', action='store_true', help='small model / few steps smoke mode')
    parser.add_argument('--no-probe', action='store_true')
    args = parser.parse_args()
    if args.fast:
        args.model = 'vit_tiny_patch16_224'
        args.steps = 5

    # A wedged relay lease makes every device op block forever inside PJRT.
    # Probe in a throwaway subprocess first; retry once after a cooldown so a
    # transiently-held lease doesn't zero the round's benchmark.
    if not args.no_probe:
        if not _probe_device():
            time.sleep(60)
            if not _probe_device():
                print(json.dumps({
                    'metric': 'benchmark aborted: TPU liveness probe failed twice (relay wedged)',
                    'value': 0.0, 'unit': 'img/s/chip', 'vs_baseline': None}), flush=True)
                raise SystemExit(2)

    # budget: compile (+relay) headroom plus per-step margin for big fused runs
    _arm_watchdog(480 + 12 * max(args.steps, 10))
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import nnx

    import timm_tpu
    from timm_tpu.loss import cross_entropy
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.parallel import create_mesh, data_sharding, set_global_mesh

    mesh = create_mesh()
    set_global_mesh(mesh)
    n_chips = mesh.size
    # bs128/chip benched fastest for ViT-B train on v5e with the einsum
    # attention path (867 img/s vs 786 w/ XLA dot_product_attention, 758 @64)
    batch_size = args.batch_size or ((128 if args.bench == 'train' else 256) * n_chips)
    K = args.steps

    kwargs = {}
    if args.img_size != 224:
        kwargs['img_size'] = args.img_size
    model = timm_tpu.create_model(args.model, dtype=jnp.bfloat16, **kwargs)

    rng = np.random.RandomState(0)
    x = jax.device_put(
        jnp.asarray(rng.rand(batch_size, args.img_size, args.img_size, 3), jnp.bfloat16),
        data_sharding(mesh, 4))
    t = jax.device_put(jnp.asarray(rng.randint(0, model.num_classes, batch_size)),
                       data_sharding(mesh, 1))

    if args.bench == 'train':
        model.train()
        opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
        graphdef, params, rest = nnx.split(model, nnx.Param, ...)
        opt_state = opt.init(params)

        @jax.jit
        def multi_step(params, opt_state, x, t):
            def body(carry, _):
                params, opt_state = carry

                def loss_fn(p):
                    m = nnx.merge(graphdef, p, rest)
                    return cross_entropy(m(x), t)
                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params, lr=1e-3)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss
            (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), None, length=K)
            return losses[-1]

        out = multi_step(params, opt_state, x, t)
        float(out)  # compile + run once
        t0 = time.perf_counter()
        float(multi_step(params, opt_state, x, t))
        dt = time.perf_counter() - t0
        flops_mult = 3.0  # fwd + bwd
    else:
        model.eval()
        graphdef, state = nnx.split(model)

        @jax.jit
        def multi_fwd(state, x):
            def body(carry, _):
                out = nnx.merge(graphdef, state)(x + carry * 0)
                return out.mean().astype(jnp.bfloat16), ()
            final, _ = jax.lax.scan(body, jnp.zeros((), jnp.bfloat16), None, length=K)
            return final

        float(multi_fwd(state, x))
        t0 = time.perf_counter()
        float(multi_fwd(state, x))
        dt = time.perf_counter() - t0
        flops_mult = 1.0

    per_step = dt / K
    img_per_sec_chip = batch_size / per_step / n_chips

    # MFU from compiled forward cost
    mfu = None
    try:
        graphdef_e, state_e = nnx.split(model)
        fwd_flops = jax.jit(lambda s, xx: nnx.merge(graphdef_e, s)(xx)).lower(
            state_e, x).compile().cost_analysis().get('flops', 0)
        kind = jax.devices()[0].device_kind.lower().replace(' ', '').replace('tpu', '')
        peak = next((v for k, v in CHIP_PEAK.items() if k in kind or kind in k), 197e12)
        mfu = (fwd_flops * flops_mult / n_chips) / per_step / peak
    except Exception:
        pass

    if _WATCHDOG is not None:
        _WATCHDOG.cancel()  # measurement done; disarm watchdog
    baseline = BASELINES.get((args.model, args.bench))
    metric = f'{args.model} {args.bench} img/s/chip (bf16, bs{batch_size}, {n_chips} chip)'
    if mfu is not None:
        metric += f', MFU={mfu:.2f}'
    print(json.dumps({
        'metric': metric,
        'value': round(img_per_sec_chip, 1),
        'unit': 'img/s/chip',
        'vs_baseline': round(img_per_sec_chip / baseline, 3) if baseline else None,
    }))


if __name__ == '__main__':
    main()
