"""NaFlex tests (reference: tests/test_naflex_dataset.py — collator/batching
invariants; plus model masking invariance)."""
import jax.numpy as jnp
import numpy as np
import pytest

import timm_tpu
from timm_tpu.data.naflex_loader import (
    NaFlexCollator, calculate_naflex_batch_size, patchify_np, resize_to_seq_len,
)
from timm_tpu.models.naflexvit import create_attention_mask, global_pool_naflex


def test_batch_size_from_token_budget():
    assert calculate_naflex_batch_size(1024, 256) == 4
    assert calculate_naflex_batch_size(1000, 256) == 3
    assert calculate_naflex_batch_size(1024, 256, max_size=2) == 2
    assert calculate_naflex_batch_size(100, 1024) == 1  # never zero


def test_collator_pads_and_masks():
    coll = NaFlexCollator(patch_size=16)
    p1, c1 = np.ones((10, 768), np.float32), np.zeros((10, 2), np.int32)
    p2, c2 = np.ones((16, 768), np.float32), np.zeros((16, 2), np.int32)
    batch = coll([(p1, c1, 3), (p2, c2, 7)], seq_len=16)
    assert batch['patches'].shape == (2, 16, 768)
    assert batch['patch_valid'][0].sum() == 10
    assert batch['patch_valid'][1].sum() == 16
    assert (batch['patches'][0, 10:] == 0).all()
    assert list(batch['target']) == [3, 7]


def test_patchify_roundtrip_coords():
    arr = np.arange(32 * 48 * 3, dtype=np.float32).reshape(32, 48, 3)
    patches, coord = patchify_np(arr, 16)
    assert patches.shape == (6, 768)
    assert coord.max(axis=0).tolist() == [1, 2]
    # first patch is the top-left block
    expect = arr[:16, :16].reshape(-1)
    np.testing.assert_array_equal(patches[0], expect)


def test_resize_respects_budget():
    from PIL import Image
    img = Image.new('RGB', (640, 480))
    out = resize_to_seq_len(img, seq_len=576, patch_size=16)
    gw, gh = out.size[0] // 16, out.size[1] // 16
    assert gw * gh <= 576
    assert gw * gh >= 576 * 0.7  # uses most of the budget
    # aspect roughly preserved
    assert abs((out.size[0] / out.size[1]) - (640 / 480)) < 0.4


def test_attention_mask_shapes():
    valid = jnp.asarray([[True, True, False], [True, False, False]])
    m = create_attention_mask(valid, num_prefix_tokens=1)
    assert m.shape == (2, 1, 4, 4)
    assert bool(m[0, 0, 0, 0]) and not bool(m[0, 0, 0, 3])
    mk = create_attention_mask(valid, symmetric=False)
    assert mk.shape == (2, 1, 1, 3)


def test_masked_pooling():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 4, 3))
    valid = jnp.asarray([[True, True, False, False]])
    avg = global_pool_naflex(x, valid, 'avg')
    np.testing.assert_allclose(np.asarray(avg)[0], x[0, :2].mean(axis=0))


def test_model_padding_invariance():
    m = timm_tpu.create_model('test_naflexvit', num_classes=10)
    m.eval()
    rng = np.random.RandomState(0)
    B, L = 2, 32
    patches = jnp.asarray(rng.rand(B, L, 768), jnp.float32)
    coord = jnp.asarray(rng.randint(0, 5, (B, L, 2)))
    valid = jnp.asarray(np.arange(L)[None, :] < np.array([20, 32])[:, None])
    out1 = m({'patches': patches, 'patch_coord': coord, 'patch_valid': valid})
    out2 = m({'patches': patches.at[0, 20:].set(123.0), 'patch_coord': coord, 'patch_valid': valid})
    assert bool(jnp.allclose(out1, out2, atol=1e-4))


def test_naflex_loader_buckets(tmp_path):
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in ('a', 'b'):
        d = tmp_path / 'train' / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.randint(0, 255, (40 + 8 * i, 56, 3), np.uint8)).save(d / f'{i}.jpg')
    from timm_tpu.data import create_dataset
    from timm_tpu.data.naflex_loader import create_naflex_loader
    ds = create_dataset('', root=str(tmp_path), split='train')
    loader = create_naflex_loader(
        ds, patch_size=16, train_seq_lens=(16, 25), max_seq_len=25, batch_size=4, is_training=True)
    seen = set()
    for batch in loader:
        assert batch['patches'].shape[1] == batch['seq_len']
        assert batch['patches'].shape[1] in (16, 25)
        assert batch['patch_valid'].any(axis=1).all()  # every row has tokens
        seen.add(batch['seq_len'])
    assert seen  # produced at least one batch


def test_naflex_mixup_lam_math():
    """Mixed-target loss math: lam-weighted per-sample CE on padded batches
    must equal the hand-computed mix of one-hot CE terms."""
    import jax
    import jax.numpy as jnp
    from flax import nnx
    import timm_tpu
    from timm_tpu.task.classification import NaFlexClassificationTask
    import optax

    m = timm_tpu.create_model('test_naflexvit', num_classes=7)
    m.train()
    task = NaFlexClassificationTask(m, optimizer=None)

    rng = np.random.RandomState(0)
    B, L, pd = 4, 16, 16 * 16 * 3
    batch = {
        'patches': jnp.asarray(rng.rand(B, L, pd), jnp.float32),
        'patch_coord': jnp.asarray(rng.randint(0, 4, (B, L, 2))),
        'patch_valid': jnp.asarray(np.arange(L)[None, :] < np.array([8, 16, 12, 16])[:, None]),
        'target': jnp.asarray([0, 1, 2, 3]),
        'target_b': jnp.asarray([3, 2, 1, 0]),
        'lam': jnp.asarray([1.0, 0.25, 0.5, 0.75], jnp.float32),
    }
    loss, output = task.loss_forward(m, batch)
    logprobs = jax.nn.log_softmax(np.asarray(output, np.float64))
    expect = 0.0
    for i in range(B):
        la = -logprobs[i, int(batch['target'][i])]
        lb = -logprobs[i, int(batch['target_b'][i])]
        lam = float(batch['lam'][i])
        expect += lam * la + (1 - lam) * lb
    expect /= B
    assert abs(float(loss) - expect) < 1e-4


def test_naflex_mix_batch_variable_size():
    from timm_tpu.data.naflex_mixup import mix_batch_variable_size
    rng = np.random.RandomState(0)
    imgs = [rng.rand(h, w, 3).astype(np.float32)
            for h, w in ((32, 48), (48, 32), (40, 40), (32, 32))]
    mixed, lams, pair_to = mix_batch_variable_size(imgs, mixup_alpha=0.8, cutmix_alpha=0.0)
    assert len(mixed) == 4 and len(lams) == 4
    for i, (m, o) in enumerate(zip(mixed, imgs)):
        assert m.shape == o.shape, 'mixing must preserve each sample shape'
        assert 0.0 <= lams[i] <= 1.0
    # every paired sample actually changed
    for i, j in pair_to.items():
        assert not np.allclose(mixed[i], imgs[i])


def test_naflex_random_erasing_token_space():
    from timm_tpu.data.naflex_loader import NaFlexRandomErasing, patchify_np
    rng = np.random.RandomState(0)
    arr = rng.rand(64, 48, 3).astype(np.float32)
    p, c = patchify_np(arr, 16)
    re = NaFlexRandomErasing(probability=1.0, mode='const')
    p2 = re(p, c)
    erased = (p2 == 0).all(axis=1)
    assert erased.any(), 'some patches must be erased'
    assert not erased.all(), 'not every patch may be erased'
    # erased patches form a rectangle in grid coords
    ys, xs = c[erased, 0], c[erased, 1]
    assert len(set(ys)) * len(set(xs)) == erased.sum()


def test_naflex_variable_patch_size_forward():
    import jax.numpy as jnp
    import timm_tpu
    m = timm_tpu.create_model('test_naflexvit', num_classes=5)
    m.eval()
    rng = np.random.RandomState(0)
    for P in (8, 16):
        pd = P * P * 3
        out = m({
            'patches': jnp.asarray(rng.rand(2, 16, pd), jnp.float32),
            'patch_coord': jnp.asarray(rng.randint(0, 4, (2, 16, 2))),
            'patch_valid': jnp.asarray(np.ones((2, 16), bool)),
        })
        assert out.shape == (2, 5)
        assert bool(jnp.isfinite(out).all())


def test_naflex_loader_mixup_and_patch_choices(tmp_path):
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in ('a', 'b'):
        d = tmp_path / 'train' / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.randint(0, 255, (48 + 8 * i, 56, 3), np.uint8)).save(d / f'{i}.jpg')
    from timm_tpu.data import create_dataset
    from timm_tpu.data.naflex_loader import create_naflex_loader
    ds = create_dataset('', root=str(tmp_path), split='train')
    loader = create_naflex_loader(
        ds, patch_size=16, patch_size_choices=(8, 16), train_seq_lens=(16, 25),
        max_seq_len=25, batch_size=4, is_training=True,
        mixup_alpha=0.8, cutmix_alpha=1.0, re_prob=0.5)
    seen_pd = set()
    for batch in loader:
        assert 'lam' in batch and 'target_b' in batch
        assert batch['lam'].shape == batch['target'].shape
        assert ((batch['lam'] >= 0) & (batch['lam'] <= 1)).all()
        seen_pd.add(batch['patches'].shape[-1])
    assert seen_pd <= {8 * 8 * 3, 16 * 16 * 3} and seen_pd
