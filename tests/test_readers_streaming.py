"""Streaming reader tests (reference: timm's reader sharding behavior,
reader_tfds.py:207-249 / reader_wds.py) — synthetic tar shards, shard
assignment asserted across simulated multi-process workers."""
import io
import json
import os
import tarfile

import numpy as np
import pytest
from PIL import Image

from timm_tpu.data import ReaderImageInTar, ReaderWds, assign_shards, create_dataset
from timm_tpu.data.loader import StreamingLoader


def _write_wds_shards(tmp_path, num_shards=4, per_shard=8, size=32):
    """Synthetic webdataset shards: NNN.jpg + NNN.cls pairs."""
    paths = []
    idx = 0
    for s in range(num_shards):
        p = tmp_path / f'shard-{s:04d}.tar'
        with tarfile.open(p, 'w') as tf:
            for _ in range(per_shard):
                img = Image.fromarray(
                    np.full((size, size, 3), idx % 255, np.uint8))
                buf = io.BytesIO()
                img.save(buf, format='JPEG')
                data = buf.getvalue()
                info = tarfile.TarInfo(f'{idx:06d}.jpg')
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                cls = str(idx % 10).encode()
                info = tarfile.TarInfo(f'{idx:06d}.cls')
                info.size = len(cls)
                tf.addfile(info, io.BytesIO(cls))
                idx += 1
        paths.append(str(p))
    with open(tmp_path / '_info.json', 'w') as f:
        json.dump({'num_samples': idx}, f)
    return paths, idx


def test_assign_shards_partition():
    shards = [f's{i}' for i in range(8)]
    seen = []
    for w in range(8):
        mine = assign_shards(shards, w, 8)
        assert len(mine) == 1
        seen += mine
    assert sorted(seen) == sorted(shards)  # disjoint + complete

    # more shards than workers: round robin, still a partition
    shards = [f's{i}' for i in range(10)]
    seen = []
    for w in range(4):
        seen += assign_shards(shards, w, 4)
    assert sorted(seen) == sorted(shards)


def test_wds_reader_full_coverage(tmp_path):
    _, total = _write_wds_shards(tmp_path, num_shards=4, per_shard=8)
    reader = ReaderWds(str(tmp_path), is_training=False)
    samples = list(reader)
    assert len(samples) == total
    targets = sorted(t for _, t in samples)
    assert targets[0] >= 0


def test_wds_reader_sharded_partition(tmp_path):
    """8 global workers over 4 shards: sample-stride fallback still covers
    every sample exactly once (the hard case from reference
    reader_tfds.py:230-242)."""
    _, total = _write_wds_shards(tmp_path, num_shards=4, per_shard=8, size=16)
    all_pixels = []
    for rank in range(8):
        reader = ReaderWds(str(tmp_path), is_training=False, dist_rank=rank, dist_num_replicas=8)
        for img, t in reader:
            all_pixels.append(int(np.asarray(img)[0, 0, 0]))
    assert len(all_pixels) == total, 'workers must partition samples exactly'
    assert len(set(all_pixels)) == total, 'no sample may appear twice'

    # shards >= workers: shard-level round robin
    all_pixels = []
    for rank in range(4):
        reader = ReaderWds(str(tmp_path), is_training=False, dist_rank=rank, dist_num_replicas=4)
        all_pixels += [int(np.asarray(img)[0, 0, 0]) for img, _ in reader]
    assert len(all_pixels) == total and len(set(all_pixels)) == total


def test_wds_reader_nondivisible_workers(tmp_path):
    """Worker count NOT a multiple of shard count (the reviewer-found case):
    3 shards x 4 and 5 workers must still partition every sample exactly once."""
    _, total = _write_wds_shards(tmp_path, num_shards=3, per_shard=7, size=16)
    for world in (4, 5, 7):
        all_pixels = []
        for rank in range(world):
            reader = ReaderWds(str(tmp_path), is_training=False, dist_rank=rank, dist_num_replicas=world)
            all_pixels += [int(np.asarray(img)[0, 0, 0]) for img, _ in reader]
        assert len(all_pixels) == total, f'world={world}: dropped/duplicated samples'
        assert len(set(all_pixels)) == total, f'world={world}: duplicate samples'


def test_streaming_loader_equalizes_hosts(tmp_path):
    """Uneven shard slices: every host must emit the same number of batches
    (cycling its stream if short) so multi-host steps stay in lockstep."""
    _, total = _write_wds_shards(tmp_path, num_shards=2, per_shard=8)
    # make shard 1 shorter by rewriting with fewer samples
    import tarfile as _tar
    p = tmp_path / 'shard-0001.tar'
    with _tar.open(p, 'w') as tf:
        img = Image.fromarray(np.full((32, 32, 3), 7, np.uint8))
        buf = io.BytesIO(); img.save(buf, format='JPEG'); data = buf.getvalue()
        info = _tar.TarInfo('x.jpg'); info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
        cls = b'0'; info = _tar.TarInfo('x.cls'); info.size = len(cls)
        tf.addfile(info, io.BytesIO(cls))
    with open(tmp_path / '_info.json', 'w') as f:
        json.dump({'num_samples': 9}, f)

    from timm_tpu.data.transforms_factory import create_transform
    counts = []
    for rank in range(2):
        reader = ReaderWds(str(tmp_path), is_training=True, shuffle_size=0,
                           dist_rank=rank, dist_num_replicas=2)
        from timm_tpu.data.dataset import IterableImageDataset
        ds = IterableImageDataset(str(tmp_path), reader=reader)
        ds.transform = create_transform(32, is_training=False)
        loader = StreamingLoader(ds, batch_size=2, is_training=True,
                                 process_index=rank, process_count=2)
        counts.append(len(list(loader)))
    assert counts[0] == counts[1] == len(loader), f'hosts diverged: {counts}'


def test_wds_training_shuffle_reseeds(tmp_path):
    _write_wds_shards(tmp_path, num_shards=4, per_shard=8, size=16)
    reader = ReaderWds(str(tmp_path), is_training=True, shuffle_size=8, seed=0)
    reader.set_epoch(0)
    e0 = [int(np.asarray(img)[0, 0, 0]) for img, _ in reader]
    reader.set_epoch(1)
    e1 = [int(np.asarray(img)[0, 0, 0]) for img, _ in reader]
    assert sorted(e0) == sorted(e1)
    assert e0 != e1, 'epoch reseed must change sample order'


def test_streaming_loader_batches(tmp_path):
    _, total = _write_wds_shards(tmp_path, num_shards=2, per_shard=8)
    ds = create_dataset('wds/' + str(tmp_path), root=None, split='train', is_training=True)
    from timm_tpu.data.transforms_factory import create_transform
    ds.transform = create_transform(32, is_training=False)
    loader = StreamingLoader(ds, batch_size=4, is_training=True)
    batches = list(loader)
    assert len(batches) == total // 4
    x, t = batches[0]
    assert x.shape == (4, 32, 32, 3) and t.shape == (4,)


def test_tar_reader(tmp_path):
    # class-per-directory tar layout
    p = tmp_path / 'data.tar'
    with tarfile.open(p, 'w') as tf:
        for cls in ('cat', 'dog'):
            for i in range(3):
                img = Image.fromarray(np.zeros((16, 16, 3), np.uint8))
                buf = io.BytesIO()
                img.save(buf, format='JPEG')
                data = buf.getvalue()
                info = tarfile.TarInfo(f'{cls}/{i}.jpg')
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    reader = ReaderImageInTar(str(p))
    assert len(reader) == 6
    assert reader.class_to_idx == {'cat': 0, 'dog': 1}
    fobj, target = reader[0]
    img = Image.open(fobj)
    assert img.size == (16, 16) and target == 0

    ds = create_dataset('tar', root=str(p))
    assert len(ds) == 6
    img, target = ds[5]
    assert target == 1


def test_reader_hfids_imagefolder(tmp_path):
    """hfids/ streaming scheme over a local imagefolder builder
    (reference readers/reader_hfids.py:29)."""
    import numpy as np
    from PIL import Image

    from timm_tpu.data import create_dataset

    for cls in ('x', 'y'):
        d = tmp_path / 'train' / cls
        d.mkdir(parents=True)
        for i in range(3):
            Image.fromarray((np.random.rand(32, 32, 3) * 255).astype('uint8')).save(d / f'{i}.jpg')

    ds = create_dataset('hfids/imagefolder', root=str(tmp_path), split='train', is_training=False)
    samples = list(iter(ds))
    assert len(samples) == 6
    img, target = samples[0]
    assert img.size == (32, 32)
    assert target in (0, 1)


def test_torch_scheme_raises_without_torchvision():
    from timm_tpu.data import create_dataset
    try:
        import torchvision  # noqa: F401
        has_tv = True
    except ImportError:
        has_tv = False
    if has_tv:
        import pytest
        pytest.skip('torchvision installed; scheme exercised elsewhere')
    import pytest
    with pytest.raises(ImportError, match='torchvision'):
        create_dataset('torch/cifar10', root='/tmp/nonexistent')
