"""The checked-in family coverage matrix (analysis/coverage.py).

tests/fixtures/coverage_matrix.json is ISSUE-20's sweep artifact: per-family
booleans for abstract trace, stage/block scan, sharded donated step, serve
AOT buckets, and device prefetch. Tier-1 re-derives the 5-family smoke
subset and diffs it against the fixture — a capability silently regressing
(or silently appearing unpinned) fails here. The full 51-family recompute
runs under ``-m slow`` and via ``python -m timm_tpu.analysis.coverage --check``.
"""
import pytest

from timm_tpu.analysis.coverage import (
    COVERAGE_CHECKS,
    DEEP_CHECKS,
    SMOKE_COVERAGE_FAMILIES,
    deep_eligible,
    diff_matrix,
    family_coverage,
    load_matrix,
)


@pytest.fixture(scope='module')
def matrix():
    return load_matrix()


def test_fixture_shape(matrix):
    """Schema, check list, and one row per registered family."""
    import timm_tpu
    assert matrix['checks'] == list(COVERAGE_CHECKS)
    fams = matrix['families']
    assert set(fams) == set(timm_tpu.list_modules())
    for module, row in fams.items():
        assert isinstance(row['abstract_trace'], bool), module
        assert isinstance(row['stage_or_block_scan'], bool), module
        for c in DEEP_CHECKS:
            # measured rows carry booleans; shallow rows carry null — a
            # measured check can never be recorded as "unknown"
            assert row[c] is None or isinstance(row[c], bool), (module, c)
            assert (row[c] is None) == (not row['deep']), (module, c)


def test_fixture_meets_acceptance_floor(matrix):
    """ISSUE-20 acceptance: >=14 families green through the sharded donated
    train step AND serve AOT; every family traces abstractly; a healthy set
    of scan-capable families."""
    fams = matrix['families']
    green = [m for m, r in fams.items()
             if r['sharded_donated_step'] and r['serve_aot']]
    assert len(green) >= 14, sorted(green)
    assert all(r['abstract_trace'] for r in fams.values()), [
        m for m, r in fams.items() if not r['abstract_trace']]
    scan = [m for m, r in fams.items() if r['stage_or_block_scan']]
    assert {'convnext', 'metaformer', 'pvt_v2', 'mambaout',
            'vision_transformer'} <= set(scan), sorted(scan)


def test_smoke_families_match_reality(matrix):
    """Re-derive the smoke subset live and diff against the fixture. The
    smoke families are all deep-eligible, so every cell — including the
    compile-for-real ones — is re-measured here in tier-1."""
    assert all(deep_eligible(m) for m in SMOKE_COVERAGE_FAMILIES)
    live = family_coverage(families=SMOKE_COVERAGE_FAMILIES)
    problems = diff_matrix(matrix['families'], live)
    assert not problems, '\n'.join(problems)


@pytest.mark.slow
def test_full_matrix_matches_reality(matrix):
    live = family_coverage()
    problems = diff_matrix(matrix['families'], live)
    assert not problems, '\n'.join(problems)
