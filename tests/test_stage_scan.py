"""Stage-level lax.scan (models/_manipulate.build_stage_stack et al).

ISSUE-20 acceptance: with `stage_scan` enabled, hierarchical families run
each homogeneous stage as ONE lax.scan and stay bit-identical under jit to
the Python block loop — forward ≤1e-6, grads ≤1e-5 — on at least three
families (convnext, swin, metaformer here; pvt_v2/regnet/mambaout share the
same machinery and ride the coverage matrix). The jaxpr regression pins the
compile-cost claim: trace size is O(1) in stage depth under scan and O(depth)
under the loop.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.models._manipulate import BlockStackError, plan_stage_stack
from timm_tpu.utils.compile_cache import count_jaxpr_eqns

_ATOL_FWD = 1e-6
_ATOL_GRAD = 1e-5


def _loop_vs_scan(model, img_size, batch=2):
    """(loop_logits, scan_logits, loop_grads, scan_grads) for one model
    instance — same params, eval mode (DropPath inert, so the loop and the
    scanned body compute the identical function)."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, img_size, img_size, 3), jnp.float32)
    model.eval()

    def run(m):
        graphdef, params, rest = nnx.split(m, nnx.Param, ...)

        def fwd(p, xx):
            return nnx.merge(graphdef, p, rest)(xx)

        def loss(p):
            return jnp.sum(fwd(p, x) ** 2)

        return jax.jit(fwd)(params, x), jax.jit(jax.grad(loss))(params)

    model.set_stage_scan(False)
    loop_logits, loop_grads = run(model)
    model.set_stage_scan(True)
    scan_logits, scan_grads = run(model)
    return loop_logits, scan_logits, loop_grads, scan_grads


def _assert_parity(model, img_size, batch=2):
    loop_logits, scan_logits, loop_grads, scan_grads = _loop_vs_scan(
        model, img_size, batch=batch)
    fwd_diff = float(jnp.abs(loop_logits - scan_logits).max())
    assert fwd_diff <= _ATOL_FWD, f'forward diverged: {fwd_diff}'
    diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                         loop_grads, scan_grads)
    worst = max(jax.tree.leaves(diffs))
    assert worst <= _ATOL_GRAD, f'grads diverged: {worst}'


def _planned_stages(block_lists):
    n = 0
    for blocks in block_lists:
        try:
            plan_stage_stack(list(blocks))
            n += 1
        except BlockStackError:
            pass
    return n


def test_stage_scan_parity_convnext():
    model = timm_tpu.create_model('test_convnext', num_classes=10,
                                  drop_path_rate=0.1)
    assert _planned_stages(s.blocks for s in model.stages) >= 1
    _assert_parity(model, 64)


def test_stage_scan_parity_swin():
    # depths where scan actually engages: the depth-2 SHIFTED stages fall
    # back by design (period-2 needs >=4 blocks), the depth-4 stage plans
    # (0, 2), and the final stage (window == resolution disables shift, all
    # blocks identical) plans (0, 1)
    from timm_tpu.models.swin_transformer import SwinTransformer
    model = SwinTransformer(
        img_size=64, patch_size=4, window_size=4, embed_dim=16,
        depths=(2, 2, 4, 2), num_heads=(1, 2, 2, 4), num_classes=10,
        drop_path_rate=0.1, rngs=nnx.Rngs(0))
    assert _planned_stages(s.blocks for s in model.layers) >= 2
    _assert_parity(model, 64)


def test_stage_scan_parity_metaformer():
    from timm_tpu.models.metaformer import MetaFormer
    model = MetaFormer(depths=(2, 2, 4, 2), dims=(16, 24, 32, 40),
                       num_classes=10, drop_path_rate=0.1, rngs=nnx.Rngs(0))
    assert _planned_stages(s.blocks for s in model.stages) == 4
    _assert_parity(model, 64)


def test_stage_scan_jaxpr_eqns_sublinear_in_depth():
    """The compile-cost contract: deepening one stage 4 -> 12 blocks adds
    O(depth) eqns to the loop trace but O(1) to the scanned trace."""
    from timm_tpu.models.metaformer import MetaFormer

    def eqns(depth, scan):
        model = MetaFormer(depths=(2, 2, depth, 2), dims=(16, 24, 32, 40),
                           num_classes=10, rngs=nnx.Rngs(0))
        model.eval()
        model.set_stage_scan(scan)
        graphdef, state = nnx.split(model)
        x = jnp.zeros((2, 64, 64, 3), jnp.float32)
        closed = jax.make_jaxpr(lambda s, xx: nnx.merge(graphdef, s)(xx))(state, x)
        return count_jaxpr_eqns(closed)

    loop_growth = eqns(12, scan=False) - eqns(4, scan=False)
    scan_growth = eqns(12, scan=True) - eqns(4, scan=True)
    assert loop_growth > 100, loop_growth  # the loop really is O(depth)
    # under scan the only depth-dependent eqns are the per-param stacks that
    # build the carry-in stacked weights (a handful per block, no block body)
    assert scan_growth < 100, scan_growth
    assert scan_growth * 4 < loop_growth, (scan_growth, loop_growth)


def test_stage_scan_regnet_train_falls_back_loudly(caplog):
    """BatchNorm running stats can't ride a scanned carry: regnet scans in
    eval and falls back to the loop (with the warn_scan_fallback log line)
    in train mode, without changing results."""
    import logging
    model = timm_tpu.create_model('test_regnet', num_classes=10)
    model.set_stage_scan(True)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(2, 64, 64, 3), jnp.float32)
    model.train()
    with caplog.at_level(logging.WARNING, logger='timm_tpu.models._manipulate'):
        out = model(x)
    assert np.isfinite(np.asarray(out)).all()
    assert any('fell back' in r.message for r in caplog.records)
