"""Torch-reference numerical-parity harness.

Stubs torchvision (absent in this image) well enough to import the reference
timm from /root/reference as a TEST ORACLE, builds randomly-initialized torch
models, converts their state dicts with timm_tpu's torch converter, and
compares logits. Not run in the default suite (imports the reference repo);
invoke directly: `python tests/ref_parity_harness.py [model ...]`.
"""
from __future__ import annotations

import sys
import types


def install_torchvision_stub():
    import torch

    def make_mod(name, pkg=False):
        m = types.ModuleType(name)
        if pkg:
            m.__path__ = []
        sys.modules[name] = m
        return m

    class _Any:
        def __init__(self, *a, **k):
            pass

        def __getattr__(self, item):
            return _Any()

    class InterpolationMode:
        NEAREST = 'nearest'
        BILINEAR = 'bilinear'
        BICUBIC = 'bicubic'
        LANCZOS = 'lanczos'
        BOX = 'box'
        HAMMING = 'hamming'

    tv = make_mod('torchvision', pkg=True)
    ops = make_mod('torchvision.ops', pkg=True)
    misc = make_mod('torchvision.ops.misc')

    class FrozenBatchNorm2d(torch.nn.Module):
        def __init__(self, num_features, eps=1e-5):
            super().__init__()

    misc.FrozenBatchNorm2d = FrozenBatchNorm2d
    ops.misc = misc
    tv.ops = ops

    tfm = make_mod('torchvision.transforms', pkg=True)
    tfmf = make_mod('torchvision.transforms.functional')
    tfmf.InterpolationMode = InterpolationMode
    for n in ('resize', 'crop', 'center_crop', 'hflip', 'vflip', 'pad', 'to_tensor',
              'normalize', 'resized_crop', 'get_image_size'):
        setattr(tfmf, n, _Any())
    tfm.functional = tfmf
    tfm.InterpolationMode = InterpolationMode
    for n in ('Compose', 'ToTensor', 'Normalize', 'Resize', 'CenterCrop', 'RandomResizedCrop',
              'RandomHorizontalFlip', 'RandomVerticalFlip', 'ColorJitter', 'Grayscale',
              'RandomApply', 'RandomChoice', 'RandomGrayscale', 'GaussianBlur', 'PILToTensor',
              'RandomCrop', 'Lambda'):
        setattr(tfm, n, _Any)
    tv.transforms = tfm

    ds = make_mod('torchvision.datasets')
    for n in ('CIFAR100', 'CIFAR10', 'MNIST', 'KMNIST', 'FashionMNIST', 'ImageFolder',
              'QMNIST', 'ImageNet', 'Places365'):
        setattr(ds, n, _Any)
    tv.datasets = ds


def compare(model_name: str, img_size: 'int | None' = None) -> float:
    import numpy as np
    import torch
    import jax.numpy as jnp
    import timm as ref_timm  # /root/reference on sys.path
    import timm_tpu
    from timm_tpu.models import load_state_dict_into_model
    from timm_tpu.models._torch_convert import convert_torch_state_dict

    if img_size is None:
        from timm_tpu.models import get_pretrained_cfg
        cfg = get_pretrained_cfg(model_name)
        img_size = cfg.input_size[-1] if cfg is not None else 224

    tm = ref_timm.create_model(model_name, num_classes=10)
    tm.eval()
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}

    m = timm_tpu.create_model(model_name, num_classes=10)
    m.eval()
    # use the family's checkpoint filter when it exists
    import importlib
    from timm_tpu.models._registry import _model_to_module, get_arch_name
    mod_name = _model_to_module.get(get_arch_name(model_name))
    filter_fn = convert_torch_state_dict
    if mod_name:
        mod = importlib.import_module(f'timm_tpu.models.{mod_name}')
        filter_fn = getattr(mod, 'checkpoint_filter_fn', convert_torch_state_dict)
    conv = filter_fn(sd, m)
    load_state_dict_into_model(m, conv, strict=True)

    x = np.random.RandomState(0).rand(2, 3, img_size, img_size).astype(np.float32)
    with torch.no_grad():
        ref_out = tm(torch.from_numpy(x)).numpy()
    our_out = np.asarray(m(jnp.asarray(x.transpose(0, 2, 3, 1))))
    # scale-aware ONLY for pathological magnitudes: multi-branch nets
    # (e.g. MobileOne) explode at random init with logits of ~1e14, making
    # absolute error meaningless. Ordinary models (|logits| < 1e3) keep the
    # strict absolute gate.
    scale = float(np.abs(ref_out).max())
    scale = scale if scale > 1e3 else 1.0
    return float(np.abs(ref_out - our_out).max() / scale)


def main(models, tol: float = 2e-3):
    import os
    import jax
    jax.config.update('jax_platforms', 'cpu')
    install_torchvision_stub()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
    sys.path.insert(0, '/root/reference')
    results = {}
    for name in models:
        try:
            d = compare(name)
            results[name] = d
            print(f'{name}: max|Δlogits| = {d:.2e}  {"PARITY OK" if d < tol else "MISMATCH"}')
        except Exception as e:
            results[name] = None
            print(f'{name}: ERROR {str(e)[:200]}')
    ok = all(d is not None and d < tol for d in results.values())
    return results, ok


if __name__ == '__main__':
    names = sys.argv[1:] or ['vit_tiny_patch16_224', 'resnet18', 'convnext_atto']
    _, ok = main(names)
    sys.exit(0 if ok else 1)
