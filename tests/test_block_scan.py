"""Scan-over-layers (block_scan) parity + trace-cost regression, persistent
compile cache, and device-prefetch pipeline tests (ISSUE 4).

Promises guarded here:

1. `block_scan=True` is numerically the Python loop: forward within fp32
   fusion noise (≤1e-6) on the golden-fixture path, grads within ≤1e-5, for
   ViT / DeiT / BEiT / EVA (incl. mixed rope), with DropPath, LayerScale,
   remat-inside-scan, forward_intermediates and pruned stacks.
2. Trace cost is O(1) in depth: a scanned depth-12 ViT's jaxpr equation count
   is < 2x the depth-2 count (the loop's is ~6x).
3. Heterogeneous stacks (depth-dependent statics) fall back to the loop with
   identical outputs — never silently wrong numbers.
4. The persistent compile cache writes executables a second cold process
   reuses, and DevicePrefetcher preserves batch order/contents with clean
   early-termination drain.
"""
import os
import subprocess
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.models._manipulate import (
    BlockStackError, build_block_stack, drop_path_scan_inputs, scan_block_stack,
)
from timm_tpu.utils.compile_cache import configure_compile_cache, count_jaxpr_eqns

_FIXTURE = os.path.join(os.path.dirname(__file__), 'fixtures', 'vit_tiny_img64_golden.npz')


def _fixture_x():
    return jnp.asarray(np.load(_FIXTURE)['x'])


def _grads(model, x):
    graphdef, params, rest = nnx.split(model, nnx.Param, ...)

    def loss(p):
        return (nnx.merge(graphdef, p, rest)(x) ** 2).mean()

    return jax.jit(jax.grad(loss))(params)


# ---- 1. scan-vs-loop parity --------------------------------------------------

@pytest.mark.blockscan
def test_scan_parity_golden_fixture():
    """Acceptance: block_scan matches the loop forward within ≤1e-6 fp32 on
    the golden fixture path (and the loop itself still matches the fixture).
    Under jit — the production mode — scan vs loop is typically bit-identical
    (XLA resolves both to the same fused program); the ≤1e-6 bound is the
    contract."""
    g = np.load(_FIXTURE)
    x = jnp.asarray(g['x'])
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64)
    model.eval()
    assert (np.asarray(model.forward_features(x)) == g['feats']).all(), \
        'loop path regressed vs golden fixture'

    def jit_fwd(m):
        graphdef, state = nnx.split(m)
        f = jax.jit(lambda s, xx: nnx.merge(graphdef, s).forward_features(xx))
        f2 = jax.jit(lambda s, xx: nnx.merge(graphdef, s)(xx))
        return np.asarray(f(state, x)), np.asarray(f2(state, x))

    feats_loop, logits_loop = jit_fwd(model)
    model.set_block_scan(True)
    feats_scan, logits_scan = jit_fwd(model)
    assert float(np.abs(feats_scan - feats_loop).max()) <= 1e-6, \
        f'feats: {np.abs(feats_scan - feats_loop).max()}'
    assert float(np.abs(logits_scan - logits_loop).max()) <= 1e-6, \
        f'logits: {np.abs(logits_scan - logits_loop).max()}'


@pytest.mark.blockscan
def test_scan_grad_parity():
    """Acceptance: grads under scan match the loop within ≤1e-5."""
    x = _fixture_x()
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64, depth=4)
    model.train()
    g_loop = _grads(model, x)
    model.set_block_scan(True)
    g_scan = _grads(model, x)
    err = max(float(jnp.abs(a - b).max())
              for a, b in zip(jax.tree.leaves(g_loop), jax.tree.leaves(g_scan)))
    assert err < 1e-5, f'grad divergence {err}'


@pytest.mark.blockscan
def test_scan_remat_grad_parity():
    """set_grad_checkpointing composes with scan (remat-inside-scan replaces
    checkpoint_seq) without changing gradients."""
    x = _fixture_x()
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64, depth=4)
    model.train()
    g_ref = _grads(model, x)
    model.set_grad_checkpointing(True)
    model.set_block_scan(True)
    g_scan = _grads(model, x)
    err = max(float(jnp.abs(a - b).max())
              for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_scan)))
    assert err < 1e-5, f'remat-in-scan grad divergence {err}'


@pytest.mark.blockscan
def test_scan_drop_path_rates():
    """Per-layer DropPath rates ride the scanned rate vector: train mode runs
    (stochastic, finite), eval mode is exactly the loop."""
    x = _fixture_x()
    model = timm_tpu.create_model(
        'vit_tiny_patch16_224', img_size=64, depth=4, drop_path_rate=0.3)
    model.train()
    model.set_block_scan(True)
    out = model(x)
    assert bool(jnp.isfinite(out).all())
    # scan inputs exist in train mode and carry the linear ramp incl. rate-0 layer 0
    dp = drop_path_scan_inputs(list(model.blocks))
    assert dp is not None
    rates, keys = dp
    assert rates.shape == (4, 2) and float(rates[0, 0]) == 0.0 and float(rates[-1, 0]) > 0.0
    assert keys.shape[:2] == (4, 2)
    model.eval()
    assert drop_path_scan_inputs(list(model.blocks)) is None
    ref = model(x)
    model.set_block_scan(False)
    loop = model(x)
    assert np.allclose(np.asarray(ref), np.asarray(loop), rtol=1e-6, atol=1e-6)


@pytest.mark.blockscan
def test_scan_parity_model_families():
    """BEiT (shared rel-pos bias constant) and EVA (incl. per-depth mixed rope
    threaded through the scan) inherit block_scan via the shared helper."""
    x = jnp.asarray(np.random.RandomState(0).rand(2, 56, 56, 3), jnp.float32)
    for name in ('beit_base_patch16_224.in22k_ft_in22k_in1k',
                 'eva02_tiny_patch14_224.mim_in22k',
                 'vit_small_patch16_rope_mixed_224.naver_in1k'):
        model = timm_tpu.create_model(name, img_size=56, depth=2)
        model.eval()
        ref = np.asarray(model(x))
        model.set_block_scan(True)
        out = np.asarray(model(x))
        assert np.allclose(out, ref, rtol=1e-6, atol=1e-6), \
            f'{name}: {np.abs(out - ref).max()}'


@pytest.mark.blockscan
def test_scan_forward_intermediates_and_prune():
    x = _fixture_x()
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64, depth=6)
    model.eval()
    xf, inter_loop = model.forward_intermediates(x, indices=[1, 3, 5])
    model.set_block_scan(True)
    xs, inter_scan = model.forward_intermediates(x, indices=[1, 3, 5])
    assert len(inter_scan) == len(inter_loop) == 3
    for a, b in zip(inter_loop, inter_scan):
        assert np.allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)
    assert np.allclose(np.asarray(xf), np.asarray(xs), rtol=1e-6, atol=1e-6)

    # stop_early slices self.blocks — must not silently disagree with scan:
    # it always takes the loop and matches the loop-mode result exactly
    early_scan = model.forward_intermediates(x, indices=[1], stop_early=True,
                                             intermediates_only=True)
    model.set_block_scan(False)
    early_loop = model.forward_intermediates(x, indices=[1], stop_early=True,
                                             intermediates_only=True)
    assert (np.asarray(early_scan[0]) == np.asarray(early_loop[0])).all()

    # pruning rebuilds self.blocks; the call-time stack follows transparently
    model.prune_intermediate_layers([3], prune_head=True)
    ref = model.forward_features(x)
    model.set_block_scan(True)
    out = model.forward_features(x)
    assert len(model.blocks) == 4
    assert np.allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)


@pytest.mark.blockscan
def test_scan_fallback_heterogeneous_is_exact():
    """Depth-dependent statics (diff-attention lambda_init) must NOT be
    silently scanned with block 0's constants: the stack check rejects them
    and the loop fallback output is bit-identical to block_scan=False."""
    x = _fixture_x()
    model = timm_tpu.create_model(
        'vit_tiny_patch16_224', img_size=64, depth=3, attn_layer='diff')
    model.eval()
    ref = np.asarray(model(x))
    with pytest.raises(BlockStackError):
        build_block_stack(list(model.blocks))
    model.set_block_scan(True)
    out = np.asarray(model(x))
    assert (out == ref).all()


@pytest.mark.blockscan
def test_scan_rejects_active_inner_dropout():
    """Train-mode attention/proj dropout consumes RNG inside the block — the
    scan body cannot advance those streams, so the stack must refuse."""
    model = timm_tpu.create_model(
        'vit_tiny_patch16_224', img_size=64, depth=2, proj_drop_rate=0.1)
    model.train()
    with pytest.raises(BlockStackError, match='dropout'):
        build_block_stack(list(model.blocks))
    model.eval()  # deterministic: scannable again
    build_block_stack(list(model.blocks))


@pytest.mark.blockscan
def test_task_block_scan_toggle():
    """TrainingTask.set_block_scan toggles the owned model and invalidates
    the jitted steps, so the next step runs in the new execution mode."""
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask
    x = _fixture_x()
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64, depth=2)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3)
    task = ClassificationTask(model, optimizer=opt)
    batch = {'input': x, 'target': jnp.zeros((x.shape[0],), jnp.int32)}
    ref = np.asarray(task.eval_step(batch))
    assert task.set_block_scan(True)
    assert model.block_scan and task._eval_step is None
    out = np.asarray(task.eval_step(batch))
    assert np.allclose(out, ref, rtol=1e-6, atol=1e-6)
    m = task.train_step(batch, lr=1e-3)
    assert bool(np.isfinite(np.asarray(m['loss'])))


# ---- 2. trace-cost regression ------------------------------------------------

@pytest.mark.blockscan
def test_trace_cost_o1_in_depth():
    """Acceptance: scanned depth-12 jaxpr equation count < 2x the depth-2
    count (the Python loop's grows ~linearly in depth)."""
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)

    def eqns(depth, scan):
        model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64, depth=depth)
        model.set_block_scan(scan)
        model.eval()
        graphdef, state = nnx.split(model)
        jaxpr = jax.make_jaxpr(lambda s, xx: nnx.merge(graphdef, s)(xx))(state, x)
        return count_jaxpr_eqns(jaxpr)

    from timm_tpu.perfbudget import check_ratio_max, check_ratio_min

    scan2, scan12 = eqns(2, True), eqns(12, True)
    check_ratio_max('scanned trace cost vs depth (eqns d12/d2)', scan12, scan2, 2.0)
    loop12 = eqns(12, False)
    check_ratio_min('loop jaxpr vs scanned (eqns loop12/scan12)', loop12, scan12, 2.0)


# ---- 3. persistent compile cache ---------------------------------------------

_CACHE_PROBE = r'''
import importlib.util, sys
import jax, jax.numpy as jnp
spec = importlib.util.spec_from_file_location('cc_mod', sys.argv[1])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
assert mod.configure_compile_cache(sys.argv[2], min_entry_size_bytes=0,
                                   min_compile_time_secs=0.0) == sys.argv[2]
events = []
from jax._src import monitoring
monitoring.register_event_listener(lambda e, **kw: events.append(e))
f = jax.jit(lambda a: ((a @ a) @ a).sum())
f(jnp.ones((128, 128), jnp.float32)).block_until_ready()
print('CACHE_HITS', sum('/compilation_cache/cache_hits' in e for e in events))
'''


@pytest.mark.compilecache
def test_compile_cache_survives_processes(tmp_path):
    """Acceptance: a second cold process with TIMM_TPU_COMPILE_CACHE set
    reuses the first process's on-disk executable (observed via JAX's
    cache-hit event), instead of recompiling."""
    cache_dir = str(tmp_path / 'xla_cache')
    mod_path = os.path.join(os.path.dirname(__file__), '..',
                            'timm_tpu', 'utils', 'compile_cache.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)  # keep the probe processes single-device/cheap

    def run():
        r = subprocess.run([sys.executable, '-c', _CACHE_PROBE, mod_path, cache_dir],
                           capture_output=True, text=True, timeout=240, env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        return int(r.stdout.strip().splitlines()[-1].split()[-1])

    hits_cold = run()
    entries = os.listdir(cache_dir)
    assert entries, 'first (cold) process persisted no executables'
    hits_warm = run()
    assert hits_cold == 0 and hits_warm >= 1, (hits_cold, hits_warm)


@pytest.mark.compilecache
def test_compile_cache_env_resolution(monkeypatch):
    from timm_tpu.utils import compile_cache as cc
    monkeypatch.setenv('TIMM_TPU_COMPILE_CACHE', '/tmp/somewhere')
    assert cc.resolve_cache_dir() == '/tmp/somewhere'
    monkeypatch.setenv('TIMM_TPU_COMPILE_CACHE', 'off')
    assert cc.resolve_cache_dir() is None
    assert cc.configure_compile_cache() is None  # disabled == no-op
    monkeypatch.delenv('TIMM_TPU_COMPILE_CACHE')
    monkeypatch.setenv('TIMM_TPU_XLA_CACHE', '/tmp/legacy')  # legacy spelling
    assert cc.resolve_cache_dir() == '/tmp/legacy'
    monkeypatch.delenv('TIMM_TPU_XLA_CACHE')
    assert cc.resolve_cache_dir() == cc.DEFAULT_CACHE_DIR
    assert cc.resolve_cache_dir('') is None


@pytest.mark.compilecache
def test_tier1_pins_compile_cache_env():
    """The conftest pins TIMM_TPU_COMPILE_CACHE so subprocess tests and
    re-runs hit one deterministic warm dir (no ambient-warmth dependence)."""
    assert os.environ.get('TIMM_TPU_COMPILE_CACHE'), \
        'tests/conftest.py must pin TIMM_TPU_COMPILE_CACHE for tier-1'
    assert jax.config.jax_compilation_cache_dir == os.environ['TIMM_TPU_COMPILE_CACHE']


# ---- 4. device prefetch ------------------------------------------------------

class _CountingLoader:
    """4 deterministic numpy batches + a close-observable iterator."""

    def __init__(self, n=4, batch=8):
        self.n, self.batch = n, batch
        self.pulled = 0
        self.closed = False
        self.mean = np.zeros(3, np.float32)  # attribute-delegation probe

    def __len__(self):
        return self.n

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        try:
            for i in range(self.n):
                self.pulled += 1
                yield (np.full((self.batch, 4, 4, 3), i, np.float32),
                       np.full((self.batch,), i, np.int32))
        finally:
            self.closed = True


@pytest.mark.compilecache
def test_device_prefetcher_contents_and_order():
    from timm_tpu.data.loader import DevicePrefetcher
    from timm_tpu.parallel import create_mesh, set_global_mesh
    set_global_mesh(create_mesh())
    inner = _CountingLoader()
    pf = DevicePrefetcher(inner, size=2)
    assert len(pf) == 4 and pf.mean.shape == (3,)  # delegation
    pf.set_epoch(3)
    assert inner.epoch == 3
    batches = list(pf)
    assert len(batches) == 4
    for i, (x, t) in enumerate(batches):
        assert isinstance(x, jax.Array) and isinstance(t, jax.Array)
        assert float(x[0, 0, 0, 0]) == i and int(t[0]) == i
    assert inner.closed


@pytest.mark.compilecache
def test_device_prefetcher_early_stop_drains():
    """Breaking out mid-epoch (preemption) must close the inner iterator and
    drop in-flight batches without hanging — and prefetch depth must not
    shift which batches were yielded."""
    from timm_tpu.data.loader import DevicePrefetcher
    from timm_tpu.parallel import create_mesh, set_global_mesh
    set_global_mesh(create_mesh())
    inner = _CountingLoader(n=10)
    pf = DevicePrefetcher(inner, size=3)
    seen = []
    for x, t in pf:
        seen.append(int(t[0]))
        if len(seen) == 2:
            break
    assert seen == [0, 1]
    assert inner.closed
    assert inner.pulled <= 2 + 3 + 1  # yielded + prefetch depth (+1 in flight)


@pytest.mark.compilecache
def test_shard_batch_scalar_and_nonarray_leaves():
    from timm_tpu.parallel import create_mesh, set_global_mesh, shard_batch
    set_global_mesh(create_mesh())
    batch = {'x': np.ones((8, 2), np.float32), 'seq_len': 196, 'step': np.int32(7)}
    out = shard_batch(batch)
    assert isinstance(out['x'], jax.Array)
    assert out['seq_len'] == 196            # non-array passes through
    assert int(out['step']) == 7            # 0-d array replicated, not sharded


# ---- 5. bench fast-fail ------------------------------------------------------

@pytest.mark.compilecache
def test_bench_probe_fastfail_policy():
    import importlib.util
    bench_path = os.path.join(os.path.dirname(__file__), '..', 'bench.py')
    spec = importlib.util.spec_from_file_location('bench_ff', bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._max_attempts(True) == 3
    assert bench._max_attempts(False) == 1, \
        'a failed probe must abort after one fresh-process retry'
    assert bench.PROBE_TIMEOUT == int(os.environ.get('TIMM_TPU_BENCH_PROBE_TIMEOUT', '60'))
