"""Elastic pod-scale training acceptance drills (ISSUE 13 tentpole).

Each test runs `tests/fsdp_drill.py elastic8to4|elastic4to8` in a subprocess:
a train.py run on the FROM topology is resize-faulted (`resize@3:D` → SIGTERM
+ recovery checkpoint) mid-epoch, then restarted as a fresh process on the TO
topology with `--resume auto --elastic`. The planner rebuilds the mesh from
the live device count, holds the global batch constant, and the resumed run's
final params/optimizer state must match an uninterrupted run to ≤1e-6.

The drill pins each child's topology via XLA_FLAGS
(--xla_force_host_platform_device_count), so these tests spawn grandchildren
and are the slowest resilience drills. Since the multi-host PR they run under
`-m slow` (~5 min of subprocess wall time for properties that are otherwise
covered fast): the in-process twins below exercise the same planner decisions
(plan_elastic_resume clamp + re-solve, rescale_for_devices, loader-position
conversion against a real recovery checkpoint), and the process-boundary +
`--resume auto --elastic` acceptance stays in tier-1 via the multi-host kill
drill (tests/test_multihost.py), whose resume leg replans 2 processes -> 1.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.elastic

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drill(mode, workdir):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, 'tests', 'fsdp_drill.py'),
         mode, str(workdir)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=900)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_elastic_shrink_8_to_4(tmp_path):
    """Full subprocess acceptance drill (see module docstring for why this is
    `-m slow`): fast twin `test_elastic_plan_shrink_in_process` below."""
    out = _drill('elastic8to4', tmp_path)
    assert out['saved_global_batch'] == 8  # geometry recorded by the dead run
    assert out['max_param_diff'] <= 1e-6, out
    assert out['recovery_pruned'], out  # end-of-epoch save reaped the recovery file


@pytest.mark.slow
def test_elastic_grow_4_to_8(tmp_path):
    """Full subprocess acceptance drill (see module docstring for why this is
    `-m slow`): fast twin `test_elastic_plan_grow_in_process` below."""
    out = _drill('elastic4to8', tmp_path)
    assert out['saved_global_batch'] == 8
    assert out['max_param_diff'] <= 1e-6, out
    assert out['recovery_pruned'], out


# ---------------------------------------------------------------------------
# fast in-process twins of the subprocess drills: the same planner decisions
# against a real recovery checkpoint, no grandchildren
# ---------------------------------------------------------------------------

def _write_recovery(tmp_path, global_batch=8, batch_size=8, name='recovery-0-3.npz'):
    from timm_tpu.resilience import atomic_write_npz
    path = str(tmp_path / name)
    atomic_write_npz(path, {
        'state_dict.w': np.zeros((2, 2), np.float32),
        '_resume.global_batch': np.asarray(global_batch),
        '_resume.batch_size': np.asarray(batch_size),
        '_resume.loader_batches': np.asarray(3),
    }, meta={'epoch': 0})
    return path


def test_elastic_plan_shrink_in_process(tmp_path):
    """8 -> 4 devices: same decisions the `elastic8to4` drill asserts via
    train.py — global batch held constant from the dead run's recovery state,
    fsdp=4 still legal on 4 devices, loader batch preserved (bit-deterministic
    resume order), loader position convertible exactly."""
    from timm_tpu.resilience import convert_loader_position, plan_elastic_resume
    path = _write_recovery(tmp_path, global_batch=8, batch_size=8)
    plan = plan_elastic_resume(4, batch_size=8, grad_accum=1, fsdp=4,
                               resume=path)
    assert plan.global_batch == 8 and plan.source == path
    assert plan.batch_size == 8 and plan.grad_accum == 1
    assert plan.fsdp == 4
    assert convert_loader_position(3, 8, plan.batch_size) == (3, True)


def test_elastic_plan_grow_in_process(tmp_path):
    """4 -> 8 devices: growing the mesh must not inflate the global batch —
    the invariant the `elastic4to8` drill enforces end-to-end."""
    from timm_tpu.resilience import plan_elastic_resume
    path = _write_recovery(tmp_path, global_batch=8, batch_size=8)
    plan = plan_elastic_resume(8, batch_size=8, grad_accum=1, fsdp=4,
                               resume=path)
    assert plan.global_batch == 8
    assert plan.batch_size * plan.grad_accum == 8
    assert plan.batch_size % 8 == 0  # still shards over all 8 devices


def test_elastic_plan_clamps_and_rescales(tmp_path):
    """The clamp/rescale fallback paths: a dead run's fsdp=8 on a 4-device
    restart clamps to the largest divisor, and an accum run re-solves
    batch_size x accum while keeping the recovered global batch."""
    from timm_tpu.resilience import plan_elastic_resume, rescale_for_devices
    path = _write_recovery(tmp_path, global_batch=16, batch_size=8)
    plan = plan_elastic_resume(4, batch_size=8, grad_accum=2, fsdp=8,
                               resume=path)
    assert plan.fsdp == 4 and any('clamped' in n for n in plan.notes)
    assert plan.batch_size * plan.grad_accum == 16
    assert rescale_for_devices(16, 4, prefer_batch_size=8) == (8, 2)
    with pytest.raises(ValueError, match='[Nn]earest legal'):
        rescale_for_devices(6, 4)
