"""Elastic pod-scale training acceptance drills (ISSUE 13 tentpole).

Each test runs `tests/fsdp_drill.py elastic8to4|elastic4to8` in a subprocess:
a train.py run on the FROM topology is resize-faulted (`resize@3:D` → SIGTERM
+ recovery checkpoint) mid-epoch, then restarted as a fresh process on the TO
topology with `--resume auto --elastic`. The planner rebuilds the mesh from
the live device count, holds the global batch constant, and the resumed run's
final params/optimizer state must match an uninterrupted run to ≤1e-6.

The drill pins each child's topology via XLA_FLAGS
(--xla_force_host_platform_device_count), so these tests spawn grandchildren
and are the slowest resilience drills — but they are the acceptance criteria,
so they stay in tier-1.
"""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.elastic

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drill(mode, workdir):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, 'tests', 'fsdp_drill.py'),
         mode, str(workdir)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=900)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_elastic_shrink_8_to_4(tmp_path):
    out = _drill('elastic8to4', tmp_path)
    assert out['saved_global_batch'] == 8  # geometry recorded by the dead run
    assert out['max_param_diff'] <= 1e-6, out
    assert out['recovery_pruned'], out  # end-of-epoch save reaped the recovery file


def test_elastic_grow_4_to_8(tmp_path):
    out = _drill('elastic4to8', tmp_path)
    assert out['saved_global_batch'] == 8
    assert out['max_param_diff'] <= 1e-6, out
    assert out['recovery_pruned'], out
