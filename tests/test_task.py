"""Task / train-step tests (reference: tests/test_task.py — checkpoint schema,
EMA; plus multi-device sharded step tests the reference lacks)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.loss import LabelSmoothingCrossEntropy
from timm_tpu.optim import create_optimizer_v2
from timm_tpu.parallel import shard_batch
from timm_tpu.task import ClassificationTask, LogitDistillationTask


def _make_task(mesh, **kwargs):
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
    return ClassificationTask(
        model, optimizer=opt, mesh=mesh,
        train_loss_fn=LabelSmoothingCrossEntropy(0.1), **kwargs)


def _batch(mesh, n=16, seed=0):
    rng = np.random.RandomState(seed)
    return shard_batch({
        'input': jnp.asarray(rng.rand(n, 32, 32, 3), jnp.float32),
        'target': jnp.asarray(rng.randint(0, 10, n)),
    }, mesh)


def test_train_step_decreases_loss(mesh8):
    task = _make_task(mesh8, clip_grad=1.0)
    batch = _batch(mesh8)
    losses = [float(task.train_step(batch, lr=1e-3, step=i)['loss']) for i in range(6)]
    assert losses[-1] < losses[0]


def test_train_step_sharded_over_mesh(mesh8):
    assert mesh8.size == 8
    task = _make_task(mesh8)
    batch = _batch(mesh8)
    # input actually sharded across devices
    assert len(batch['input'].sharding.device_set) == 8
    metrics = task.train_step(batch, lr=1e-3)
    assert np.isfinite(float(metrics['loss']))


def test_grad_accumulation_matches_large_batch(mesh8):
    # same data: accum over 2 microbatches ≈ one step on full batch
    t1 = _make_task(mesh8)
    t2 = _make_task(mesh8, grad_accum_steps=2)
    batch = _batch(mesh8, n=16)
    l1 = float(t1.train_step(batch, lr=1e-3)['loss'])
    l2 = float(t2.train_step(batch, lr=1e-3)['loss'])
    assert l1 == pytest.approx(l2, abs=1e-3)


def test_ema_update_and_eval(mesh8):
    task = _make_task(mesh8)
    task.setup_ema(decay=0.5)
    batch = _batch(mesh8)
    for i in range(3):
        task.train_step(batch, lr=1e-2, step=i + 1)
    out = task.eval_step({'input': batch['input']})
    out_ema = task.eval_step({'input': batch['input']}, use_ema=True)
    assert out.shape == (16, 10)
    assert not bool(jnp.allclose(out, out_ema))


def test_checkpoint_schema_and_roundtrip(mesh8):
    task = _make_task(mesh8)
    task.setup_ema(decay=0.9)
    task.train_step(_batch(mesh8), lr=1e-3, step=1)
    state = task.get_checkpoint_state()
    assert any(k.startswith('state_dict.') for k in state)
    assert any(k.startswith('state_dict_ema.') for k in state)
    assert any(k.startswith('optimizer.') for k in state)
    assert not any('rngs' in k for k in state)
    # roundtrip into a fresh task
    task2 = _make_task(mesh8)
    task2.setup_ema(decay=0.9)
    task2.train_step(_batch(mesh8, seed=3), lr=1e-3, step=1)
    task2.load_checkpoint_state(state)
    x = _batch(mesh8)['input']
    a = task.eval_step({'input': x})
    b = task2.eval_step({'input': x})
    assert bool(jnp.allclose(a, b, atol=1e-5))


def test_checkpoint_saver(tmp_path, mesh8):
    from timm_tpu.utils import CheckpointSaver
    task = _make_task(mesh8)
    saver = CheckpointSaver(task, checkpoint_dir=str(tmp_path), recovery_dir=str(tmp_path), max_history=2)
    for ep, metric in [(0, 10.0), (1, 30.0), (2, 20.0)]:
        best, best_ep = saver.save_checkpoint(ep, metric)
    assert best == 30.0 and best_ep == 1
    files = {f.name for f in tmp_path.iterdir()}
    assert 'last.npz' in files and 'model_best.npz' in files
    # retention: only 2 epoch checkpoints kept (each with a manifest sidecar)
    assert len([f for f in files if f.startswith('checkpoint-') and f.endswith('.npz')]) == 2
    assert len([f for f in files if f.startswith('checkpoint-') and f.endswith('.manifest.json')]) == 2
    # recovery
    saver.save_recovery(2, batch_idx=5)
    assert saver.find_recovery()


def test_logit_distillation(mesh8):
    student = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    teacher = timm_tpu.create_model('test_vit2', num_classes=10, img_size=32)
    opt = create_optimizer_v2(student, opt='adamw', lr=1e-3)
    task = LogitDistillationTask(
        student, teacher, optimizer=opt, mesh=mesh8,
        train_loss_fn=LabelSmoothingCrossEntropy(0.1), distill_alpha=0.5, distill_temperature=2.0)
    m = task.train_step(_batch(mesh8), lr=1e-3)
    assert np.isfinite(float(m['loss']))


def test_dryrun_multichip_entry():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        'graft_entry', os.path.join(os.path.dirname(__file__), '..', '__graft_entry__.py'))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_ema_decay_zero_syncs_to_model(mesh8):
    """decay==0 must copy model params into EMA (reference ModelEmaV3 lerp
    weight 1.0 during the update_after_step window), not freeze the EMA
    (ADVICE r1 medium)."""
    task = _make_task(mesh8)
    task.setup_ema(decay=0.999, warmup=True, update_after_step=100)
    batch = _batch(mesh8)
    # inside the update_after_step window → get_decay == 0 → EMA tracks model
    assert task.ema.get_decay(1) == 0.0
    for i in range(2):
        task.train_step(batch, lr=1e-2, step=i + 1)
    params = jax.tree.leaves(nnx.state(task.model, nnx.Param))
    ema = jax.tree.leaves(task.ema_params)
    assert all(np.allclose(np.asarray(p), np.asarray(e)) for p, e in zip(params, ema))
