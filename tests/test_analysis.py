"""Unified static-analysis suite (timm_tpu/analysis).

1. Pragma semantics: trailing / standalone / module scope, mandatory reason,
   legacy shims, pragma-spellings inside strings are not pragmas.
2. Registry: every migrated in-test lint exists as a registered rule.
3. Tier A at HEAD: the source rules pass on the live repo (this replaces the
   five in-test lint copies deleted from test_sharding/test_kernels/
   test_layers/test_data).
4. Planted violations (tests/fixtures/lint_violations/): each fixture fails
   its rule, each waived twin is suppressed, the waiver stays in the report.
5. Tier B/C on the session capture: the jaxpr/HLO rules pass over the
   programs the perfbudget probes lowered ONCE for the whole session
   (tests/conftest.py `analysis_programs`) — nothing is lowered twice.
6. CLI exit codes pinned: 0 clean / 2 violations / 3 internal error, plus
   the JSON report schema.
7. Zoo abstract-trace smoke: the cheap family subset traces clean (the full
   51-family sweep runs under -m slow and via the CLI).
"""
import json
import os

import pytest

from timm_tpu.analysis import (
    EXIT_CLEAN, EXIT_ERROR, EXIT_VIOLATIONS, AnalysisContext, FilePragmas,
    Finding, Report, all_rules, ensure_registered, run_analysis, select,
)
from timm_tpu.analysis.__main__ import main as analysis_main
from timm_tpu.analysis.jaxpr_rules import audit_softmax_policy, scan_module_program
from timm_tpu.analysis.zoo import SMOKE_FAMILIES, sweep

pytestmark = pytest.mark.analysis

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures', 'lint_violations')

# the five lints this PR migrated out of tests/, plus the new passes
MIGRATED = {'donation-declared', 'partition-rules', 'kernel-registered',
            'fp32-softmax', 'silent-except'}
NEW = {'host-sync', 'traced-branch', 'pragma-syntax', 'large-literal',
       'dtype-promotion', 'donation-alias', 'replicated-residual',
       'baked-constant', 'zoo-abstract-trace', 'process-zero-io'}


# ---- 1. pragma semantics ----------------------------------------------------

def test_trailing_pragma_waives_its_own_line():
    text = 'x = 1\n' * 9 + 'y = 2  # timm-tpu-lint: disable=my-rule because reasons\n'
    fp = FilePragmas(text)
    assert fp.waiver_for('my-rule', 10) == 'because reasons'
    assert fp.waiver_for('my-rule', 9) is None
    assert fp.waiver_for('other-rule', 10) is None
    assert not fp.malformed


def test_standalone_pragma_waives_next_line():
    lines = ['x = 1'] * 8 + ['# timm-tpu-lint: disable=my-rule planted', 'y = 2']
    fp = FilePragmas('\n'.join(lines) + '\n')
    assert fp.waiver_for('my-rule', 10) == 'planted'
    assert fp.waiver_for('my-rule', 9) is None


def test_first_five_lines_waive_file_wide():
    text = ('# timm-tpu-lint: disable=my-rule module-wide reason\n'
            + 'x = 1\n' * 40)
    fp = FilePragmas(text)
    assert fp.waiver_for('my-rule', 37) == 'module-wide reason'
    assert fp.waiver_for('my-rule') == 'module-wide reason'
    assert fp.waiver_for('other-rule') is None


def test_comma_list_waives_each_listed_rule():
    text = 'x = 1\n' * 9 + 'y = 2  # timm-tpu-lint: disable=rule-a,rule-b shared reason\n'
    fp = FilePragmas(text)
    assert fp.waiver_for('rule-a', 10) == 'shared reason'
    assert fp.waiver_for('rule-b', 10) == 'shared reason'


def test_reasonless_pragma_waives_nothing_and_is_malformed():
    text = 'x = 1\n' * 9 + 'y = 2  # timm-tpu-lint: disable=my-rule\n'
    fp = FilePragmas(text)
    assert fp.waiver_for('my-rule', 10) is None
    assert any('reason' in msg for _, msg in fp.malformed)

    garbled = 'x = 1\n' * 9 + 'y = 2  # timm-tpu-lint: sdisable my-rule\n'
    assert FilePragmas(garbled).malformed


def test_shims_keep_their_historical_rules_and_scopes():
    # standalone no-donate shim waives the next line for donation-declared
    lines = ['import jax'] * 6 + ['# no-donate: eval keeps its inputs',
                                  'step = jax.jit(f)']
    fp = FilePragmas('\n'.join(lines) + '\n')
    assert fp.waiver_for('donation-declared', 8) == 'eval keeps its inputs'
    assert fp.waiver_for('kernel-registered', 8) is None

    # first-5-lines no-kernel-registry shim waives file-wide
    fp = FilePragmas('# no-kernel-registry: host-side helper\nx = 1\n')
    assert fp.waiver_for('kernel-registered') == 'host-side helper'

    # a reasonless shim is malformed and waives nothing
    fp = FilePragmas('# no-kernel-registry:\nx = 1\n')
    assert fp.waiver_for('kernel-registered') is None
    assert fp.malformed


def test_pragma_spelling_inside_string_is_not_a_pragma():
    text = ('x = 1\n' * 6
            + 's = "# timm-tpu-lint: disable=my-rule not a real pragma"\n')
    fp = FilePragmas(text)
    assert fp.waiver_for('my-rule', 7) is None
    assert fp.waiver_for('my-rule') is None
    assert not fp.malformed


# ---- 2. registry ------------------------------------------------------------

def test_registry_covers_every_migrated_lint_and_all_tiers():
    rules = all_rules()
    names = {r.name for r in rules}
    assert MIGRATED <= names, MIGRATED - names
    assert NEW <= names, NEW - names
    tiers = {r.tier for r in rules}
    assert tiers == {'A', 'B', 'C'}
    # Tier B/C rules that walk programs declare it, so the CLI knows when
    # the probe lowering (and the 8-device re-exec) is actually needed
    for r in rules:
        if r.name in ('large-literal', 'donation-alias',
                      'replicated-residual', 'baked-constant'):
            assert r.needs_programs, r.name


def test_select_rejects_unknown_names_and_tiers():
    with pytest.raises(KeyError, match='no-such-rule'):
        select(names=['no-such-rule'])
    with pytest.raises(KeyError, match='unknown tier'):
        select(tiers=['Z'])


def test_report_exit_codes_error_outranks_violations():
    rep = Report()
    rep.add('clean', [], 0.0)
    assert rep.exit_code == EXIT_CLEAN
    rep.add('dirty', [Finding('dirty', 'p.py', 1, 'm')], 0.0)
    assert rep.exit_code == EXIT_VIOLATIONS
    rep.add('crashed', [], 0.0, error='ValueError: boom')
    assert rep.exit_code == EXIT_ERROR
    assert rep.to_dict()['rules']['crashed']['status'] == 'error'
    # waived findings stay in the report but don't drive the exit code
    rep2 = Report()
    rep2.add('waivy', [Finding('waivy', 'p.py', 1, 'm', waived=True,
                               waive_reason='r')], 0.0)
    assert rep2.exit_code == EXIT_CLEAN and len(rep2.waived) == 1


# ---- 3. Tier A at HEAD ------------------------------------------------------

def test_tier_a_clean_at_head():
    """The consolidated source rules pass on the live repo — this single run
    replaces the five in-test lint copies this PR deleted. partition-rules
    sweeps the zoo smoke families here; the all-family sweep is the slow
    test below."""
    ensure_registered()
    report = run_analysis(AnalysisContext(zoo_families=SMOKE_FAMILIES),
                          select(tiers=['A']))
    assert report.exit_code == EXIT_CLEAN, report.format_text()
    assert set(report.rules) >= (MIGRATED | {'host-sync', 'traced-branch',
                                             'pragma-syntax', 'process-zero-io'})


@pytest.mark.slow
def test_partition_rules_disjoint_over_every_registered_family():
    """The acceptance gate at full width: every param path of every
    registered family matches exactly one non-catch-all partition rule, with
    the conv rules active (same sweep as `python -m timm_tpu.analysis`)."""
    ensure_registered()
    report = run_analysis(AnalysisContext(), select(names=['partition-rules']))
    assert report.exit_code == EXIT_CLEAN, report.format_text()


# ---- 4. planted violations --------------------------------------------------

def _run_rule(rule_name, subdir):
    ctx = AnalysisContext(root=os.path.join(FIXTURES, subdir))
    return run_analysis(ctx, select(names=[rule_name]))


@pytest.mark.parametrize('rule_name,filename', [
    ('silent-except', 'bare_except.py'),
    ('donation-declared', 'missing_donation.py'),
    ('host-sync', 'host_sync.py'),
    ('traced-branch', 'traced_branch.py'),
    ('fp32-softmax', 'fp32_softmax.py'),
    ('process-zero-io', 'process_zero_io.py'),
])
def test_planted_source_violation_fails_and_waiver_suppresses(rule_name, filename):
    report = _run_rule(rule_name, 'source')
    assert report.exit_code == EXIT_VIOLATIONS, report.format_text()
    paths = [f.path for f in report.violations]
    assert any(p.endswith(filename) for p in paths), (filename, paths)
    assert not any(p.endswith('_waived.py') for p in paths), paths


def test_waived_finding_stays_in_the_report():
    """A waiver suppresses the violation but not the audit trail."""
    report = _run_rule('silent-except', 'source')
    waived = [f for f in report.waived if f.path.endswith('bare_except_waived.py')]
    assert waived and waived[0].waive_reason


def test_planted_unregistered_kernel_fails_and_waives():
    report = _run_rule('kernel-registered', 'kernels')
    assert report.exit_code == EXIT_VIOLATIONS, report.format_text()
    paths = [f.path for f in report.violations]
    assert any(p.endswith('unregistered_kernel.py') for p in paths), paths
    assert not any(p.endswith('unregistered_kernel_waived.py') for p in paths)


def test_planted_baked_constant_detected_and_module_waiver_honored():
    findings = scan_module_program(
        os.path.join(FIXTURES, 'jaxpr', 'baked_constant.py'))
    assert findings, 'the planted 2 MB baked constant must be detected'
    assert not any(f.waived for f in findings)

    waived = scan_module_program(
        os.path.join(FIXTURES, 'jaxpr', 'baked_constant_waived.py'))
    assert waived and all(f.waived for f in waived)


def test_dtype_promotion_clean_on_policy_softmax_and_flags_planted_upcast():
    import jax
    import jax.numpy as jnp

    assert audit_softmax_policy() == []

    def bad_softmax(x):
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)

    findings = audit_softmax_policy(
        bad_softmax, (jnp.zeros((2, 4, 8, 8), jnp.bfloat16),))
    assert findings, 'planted fp32 upcast under a declared-bf16 policy'
    assert all('exp' in f.message or 'div' in f.message for f in findings)


# ---- 5. Tier B/C on the session capture -------------------------------------

def test_capture_covers_the_expected_programs(analysis_programs):
    names = {rec['name'] for rec in analysis_programs['programs']}
    assert 'base/train_step' in names, names
    assert 'tp22/fwd' in names, names
    assert any(n.startswith('serve_test_vit/bucket') for n in names), names
    assert 'elastic_resize/train_step_postresize' in names, names
    assert 'stage_scan_convnext/train_step' in names, names
    assert 'stage_scan_swin/train_step' in names, names


def test_tier_bc_rules_clean_on_captured_programs(analysis_programs):
    """The jaxpr + compiled-HLO passes run over the programs the perfbudget
    comparisons already lowered (same session fixture): donation survived
    compilation, the tp residual stays sharded, nothing baked a >1 MB
    constant."""
    ctx = AnalysisContext(programs=analysis_programs['programs'])
    rules = [r for r in all_rules() if r.needs_programs]
    report = run_analysis(ctx, rules)
    assert report.exit_code == EXIT_CLEAN, report.format_text()
    assert {'large-literal', 'donation-alias', 'replicated-residual',
            'baked-constant'} <= set(report.rules)


# ---- 6. CLI exit codes ------------------------------------------------------

def test_cli_exit_0_on_clean_rules():
    assert analysis_main(['--rules', 'fp32-softmax,pragma-syntax', '-q']) == EXIT_CLEAN


def test_cli_exit_2_on_planted_violations():
    rc = analysis_main(['--rules', 'silent-except', '-q',
                        '--source-root', os.path.join(FIXTURES, 'source')])
    assert rc == EXIT_VIOLATIONS


def test_cli_exit_3_on_unknown_rule():
    assert analysis_main(['--rules', 'no-such-rule', '-q']) == EXIT_ERROR


def test_cli_exit_3_on_internal_rule_error():
    """A crashed rule must never read as a clean repo: an unknown probe
    config makes large-literal's lowering raise before any probing, and the
    run reports exit 3 (error), not 0/2."""
    rc = analysis_main(['--rules', 'large-literal', '-q',
                        '--probe-configs', 'bogus-config'])
    assert rc == EXIT_ERROR


def test_cli_json_report_schema(tmp_path):
    out = tmp_path / 'report.json'
    rc = analysis_main(['--rules', 'fp32-softmax', '--json', str(out), '-q'])
    assert rc == EXIT_CLEAN
    doc = json.loads(out.read_text())
    assert doc['schema'] == 'timm-tpu-analysis/v1'
    assert doc['exit_code'] == EXIT_CLEAN
    assert set(doc['rules']) == {'fp32-softmax'}
    for rec in doc['rules'].values():
        assert {'status', 'wall_s', 'error', 'findings'} <= set(rec)


def test_cli_list_prints_rule_table(capsys):
    assert analysis_main(['--list']) == 0
    out = capsys.readouterr().out
    for name in MIGRATED | NEW:
        assert name in out, name


# ---- 7. zoo abstract-trace --------------------------------------------------

def test_zoo_smoke_families_trace_clean():
    records = sweep(families=SMOKE_FAMILIES)
    assert len(records) == len(SMOKE_FAMILIES)
    bad = [r for r in records if not r['ok']]
    assert not bad, bad


@pytest.mark.slow
def test_zoo_full_sweep_every_registered_family():
    """ROADMAP item 5 gate at full width: every registered family constructs
    and abstract-forwards at its native input size — this is the sweep that
    caught the res2net/resnest/sknet aa_layer constructor bug."""
    records = sweep()
    bad = [r for r in records if not r['ok']]
    assert not bad, bad
