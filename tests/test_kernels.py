"""Pallas kernel tests (interpret mode on CPU; native on TPU).

1. Flash-attention numerics (the original hand-written checks).
2. Registry behaviour (the every-module-registered-or-waived lint moved to
   timm_tpu/analysis, rule `kernel-registered`).
3. Auto-generated parity: one test per (kernel, declared regime case) pair,
   jitted kernel vs jitted XLA reference at the case's dry shapes.
4. Fused AdamW+EMA: 5 donated TrainingTask steps with fused_update=True must
   track the optax path leaf-for-leaf (params, EMA, full opt_state) within
   1e-6, for fp32 and bfloat16 first-moment state; a non-adamw optimizer is
   rejected at task construction.
5. Augment epilogue vs the PR-9 numpy oracle (the source of truth — the XLA
   program is only the A/B reference arm).
6. Win-or-delete harness: a parity-exact but deliberately slow toy kernel on
   its claimed backend gets `delete`, its fast twin gets `keep`, and a
   parity-broken kernel is deleted without being timed.
7. The perfbudget `kernels` probe stays within the checked-in budgets,
   including the fused-update one-pass bytes reduction.
"""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from timm_tpu.kernels import harness, registry
from timm_tpu.kernels.flash_attention import _flash, flash_attention
from timm_tpu.layers.attention import _sdpa

pytestmark = pytest.mark.kernels


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


def test_flash_matches_sdpa():
    B, H, N, D = 2, 2, 256, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    ref = _sdpa(q, k, v)
    out = _flash(q, k, v, None, D ** -0.5)
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_key_mask():
    B, H, N, D = 2, 2, 256, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    mask = jnp.asarray(np.random.RandomState(3).rand(B, N) > 0.3)
    ref = _sdpa(q, k, v, attn_mask=mask[:, None, None, :])
    out = flash_attention(q, k, v, mask=mask)
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_unaligned_seq():
    # N=197 exercises the pad-and-mask path
    B, H, N, D = 1, 2, 197, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    ref = _sdpa(q, k, v)
    out = _flash(q, k, v, None, D ** -0.5)
    assert out.shape == ref.shape
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_grads_match():
    B, H, N, D = 1, 2, 128, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    g1 = jax.grad(lambda q, k, v: (_flash(q, k, v, None, D ** -0.5) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: (_sdpa(q, k, v) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-2


# ---- 2. registry ------------------------------------------------------------
# The every-module-registered-or-waived lint is now the analysis rule
# `kernel-registered` (timm_tpu/analysis/source_rules.py); the
# `# no-kernel-registry: <reason>` waiver spelling is unchanged.


def test_registry_portfolio_and_dup_rejection():
    assert registry.kernel_names() == (
        'augment_epilogue', 'flash_attention', 'fused_adamw')
    with pytest.raises(ValueError, match='already registered'):
        registry.register(registry.get('fused_adamw'))
    with pytest.raises(ValueError, match='regime is empty'):
        dataclasses.replace(registry.get('fused_adamw'), name='empty', cases=())


# ---- 3. auto-generated parity (one test per declared regime case) -----------

_PARITY_GRID = harness.parity_cases()


@pytest.mark.parametrize(
    'spec,case', _PARITY_GRID,
    ids=[f'{s.name}-{c.name}' for s, c in _PARITY_GRID])
def test_kernel_parity(spec, case):
    rec = harness.parity_check(spec, case)
    assert rec['ok'], (
        f"{rec['kernel']}/{rec['case']}: max abs err {rec['max_abs_err']:.3g} "
        f"> tol {rec['tol']:.3g}")


# ---- 4. fused AdamW+EMA through the donated TrainingTask step ---------------


class _TinyNet(nnx.Module):
    def __init__(self, rngs):
        self.fc1 = nnx.Linear(24, 48, rngs=rngs)
        self.fc2 = nnx.Linear(48, 10, rngs=rngs)
        self.num_classes = 10

    def __call__(self, x):
        return self.fc2(nnx.relu(self.fc1(x.reshape(x.shape[0], -1))))


def _run_adamw_arm(fused, mu_dtype, steps=5):
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask

    model = _TinyNet(nnx.Rngs(0))
    opt_kwargs = {'mu_dtype': mu_dtype} if mu_dtype else {}
    opt = create_optimizer_v2(model, opt='adamw', lr=0.01, weight_decay=0.05,
                              **opt_kwargs)
    task = ClassificationTask(model, optimizer=opt, fused_update=fused)
    task.setup_ema(decay=0.99)
    rng = np.random.RandomState(0)
    losses = []
    for i in range(steps):
        batch = {'input': jnp.asarray(rng.rand(8, 2, 2, 6), jnp.float32),
                 'target': jnp.asarray(rng.randint(0, 10, 8))}
        metrics = task.train_step(batch, lr=0.01, step=i)
        losses.append(float(metrics['loss']))
    return (losses, nnx.state(task.model, nnx.Param), task.ema_params,
            task.opt_state)


def _max_leaf_diff(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return max(float(jnp.max(jnp.abs(x.astype(jnp.float32)
                                     - y.astype(jnp.float32))))
               for x, y in zip(leaves_a, leaves_b))


@pytest.mark.parametrize('mu_dtype', [None, 'bfloat16'],
                         ids=['fp32', 'mu_bf16'])
def test_fused_adamw_five_step_drift_vs_optax(mesh8, mu_dtype):
    """Acceptance: 5 donated train steps with fused_update=True track the
    optax path within 1e-6 — params, EMA tree, AND the full opt_state
    (mu/nu/counters), for fp32 and bfloat16 first-moment state."""
    l_ref, p_ref, e_ref, o_ref = _run_adamw_arm(False, mu_dtype)
    l_fus, p_fus, e_fus, o_fus = _run_adamw_arm(True, mu_dtype)
    assert np.allclose(l_ref, l_fus, atol=1e-6), (l_ref, l_fus)
    assert _max_leaf_diff(p_ref, p_fus) <= 1e-6
    assert _max_leaf_diff(e_ref, e_fus) <= 1e-6
    assert _max_leaf_diff(o_ref, o_fus) <= 1e-6


def test_fused_update_rejects_non_adamw(mesh8):
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask

    model = _TinyNet(nnx.Rngs(0))
    opt = create_optimizer_v2(model, opt='sgd', lr=0.01)
    with pytest.raises(ValueError, match='fused_adamw_args'):
        ClassificationTask(model, optimizer=opt, fused_update=True)


# ---- 5. augment epilogue vs the PR-9 numpy oracle ---------------------------

@pytest.mark.parametrize('case_kwargs', [
    dict(),                                  # mixup/cutmix + erase
    dict(with_mix=False),                    # eval-style erase + normalize
    dict(erase_k=2, batch=6, size=24),       # multiple erase boxes
], ids=['mix_erase', 'no_mix', 'two_boxes'])
def test_augment_epilogue_matches_numpy_oracle(case_kwargs):
    from timm_tpu.data.device_augment import augment_image_batch_np
    from timm_tpu.kernels.augment_epilogue import (
        _STATICS, _make_inputs, augment_image_batch_fused,
    )

    batch = _make_inputs(seed=3, **case_kwargs)['batch']
    x, y = jax.jit(
        functools.partial(augment_image_batch_fused, **_STATICS))(batch)
    xn, yn = augment_image_batch_np({k: np.asarray(v) for k, v in batch.items()},
                                    **_STATICS)
    assert float(np.abs(np.asarray(x) - xn).max()) <= 1e-6
    assert float(np.abs(np.asarray(y, np.float32)
                        - np.asarray(yn, np.float32)).max()) <= 1e-6


def test_augment_epilogue_out_of_regime_falls_back():
    """'pixel' erase mode is outside the declared regime: the fused twin must
    route through the XLA program bit-for-bit, not the kernel."""
    from timm_tpu.data.device_augment import augment_image_batch
    from timm_tpu.kernels.augment_epilogue import (
        _STATICS, _make_inputs, augment_epilogue_supported,
        augment_image_batch_fused,
    )

    batch = _make_inputs(seed=5)['batch']
    assert augment_epilogue_supported(batch, 'const')
    assert not augment_epilogue_supported(batch, 'pixel')
    batch = dict(batch, noise_epoch=jnp.asarray(0, jnp.int32),
                 noise_step=jnp.asarray(0, jnp.int32))
    kwargs = dict(_STATICS, re_mode='pixel', re_std=(0.2, 0.2, 0.2))
    x_f, y_f = jax.jit(functools.partial(augment_image_batch_fused, **kwargs))(batch)
    x_r, y_r = jax.jit(functools.partial(augment_image_batch, **kwargs))(batch)
    assert float(jnp.abs(x_f - x_r).max()) == 0.0
    assert float(jnp.abs(y_f - y_r).max()) == 0.0


# ---- 6. win-or-delete verdicts ----------------------------------------------


def _toy_specs():
    """Toy kernel/reference pair that claims the CURRENT backend, so the
    timed arm of `ab_verdict` actually runs in tier-1. The slow arm is
    parity-exact but drags a chain of 256x256 matmuls whose contribution is
    scaled to zero magnitude yet cannot be eliminated."""
    def make_inputs(seed=0, n=256):
        rng = np.random.default_rng(seed)
        return {'x': jnp.asarray(rng.standard_normal((n, n)), jnp.float32)}

    def fast(x):
        return x * 2.0 + 1.0

    def slow(x):
        acc = x
        eye = jnp.eye(x.shape[0], dtype=x.dtype)
        for _ in range(60):
            acc = acc @ eye
        return x * 2.0 + 1.0 + acc * 1e-30

    backend = jax.default_backend()
    losing = registry.KernelSpec(
        name='toy_losing', module=__name__,
        regime='nowhere (test fixture)', gate='win or delete',
        parity_tol=1e-6, kernel_fn=slow, reference_fn=fast,
        make_inputs=make_inputs,
        cases=(registry.KernelCase(name='only', dry=dict(n=256),
                                   live=dict(n=256)),),
        backends=(backend,))
    winning = dataclasses.replace(losing, name='toy_winning',
                                  kernel_fn=fast, reference_fn=slow)
    return losing, winning


def test_losing_kernel_gets_delete_winning_twin_keep():
    """The win-or-delete gate is executable: a parity-clean kernel that loses
    the timed A/B on its claimed backend is deleted; the fast twin (same
    regime, arms swapped) is kept. Neither spec is registered — the verdict
    machinery is exercised directly."""
    losing, winning = _toy_specs()
    rec = harness.ab_verdict(losing, steps=3)
    assert rec['parity_ok']
    assert rec['verdict'] == 'delete', rec
    assert 'loses to the XLA reference' in rec['reason']
    assert 'DELETE' in harness.format_verdict_line(rec)

    rec = harness.ab_verdict(winning, steps=3)
    assert rec['parity_ok'] and rec['verdict'] == 'keep', rec


def test_parity_broken_kernel_deleted_without_timing():
    losing, _ = _toy_specs()
    broken = dataclasses.replace(losing, name='toy_broken',
                                 kernel_fn=lambda x: x * 2.0 + 1.001)
    rec = harness.ab_verdict(broken, steps=1)
    assert rec['verdict'] == 'delete' and not rec['parity_ok']
    assert 'wrong beats slow' in rec['reason']
    assert 'cases' not in rec  # never timed


def test_portfolio_verdicts_pending_off_claimed_hardware():
    """The shipped portfolio claims TPU; in tier-1 (CPU) every verdict must
    be `pending` with parity measured — the dry arm of the replay `kernels`
    step and `bench.py --kernels --dry-run`."""
    recs = harness.run_kernel_ab(live=False, steps=1)
    assert [r['kernel'] for r in recs] == sorted(r['kernel'] for r in recs)
    assert {r['kernel'] for r in recs} == set(registry.kernel_names())
    backend = jax.default_backend()
    for rec in recs:
        assert rec['parity_ok'], rec
        if backend in rec['backends_claimed']:
            assert rec['verdict'] in ('keep', 'delete')
        else:
            assert rec['verdict'] == 'pending'
            assert 'settles the gate' in rec['reason']
        line = harness.format_verdict_line(rec)
        assert rec['kernel'] in line and rec['verdict'].upper() in line


# ---- 7. perfbudget `kernels` probe ------------------------------------------


def test_kernels_probe_within_budgets():
    """The `kernels` probe metrics stay inside the checked-in bands, and the
    fused-update acceptance evidence holds: the kernel's analytic one-pass
    io bytes sit measurably below the compiled unfused chain's bytes
    accessed (refused silent improvement included — band policy)."""
    from timm_tpu.perfbudget import budgets as B
    from timm_tpu.perfbudget.probe import run_matrix

    measured = run_matrix(names=['kernels'])
    violations = B.compare_budgets(measured, B.load_budgets(),
                                   configs=['kernels'])
    assert not violations, B.format_violations(violations)
    m = measured['kernels']
    assert m['kernels_registered'] == len(registry.kernel_names())
    for name in registry.kernel_names():
        assert m[f'{name}_wins_bytes'], (
            f'{name}: io bytes {m[f"{name}_io_bytes"]} do not beat the '
            f'reference bytes accessed {m[f"{name}_ref_bytes_accessed"]}')
    assert m['fused_adamw_io_bytes'] < m['fused_adamw_ref_bytes_accessed']
