"""Pallas kernel tests (interpret mode on CPU; native on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from timm_tpu.kernels.flash_attention import _flash, flash_attention
from timm_tpu.layers.attention import _sdpa


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


def test_flash_matches_sdpa():
    B, H, N, D = 2, 2, 256, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    ref = _sdpa(q, k, v)
    out = _flash(q, k, v, None, D ** -0.5)
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_key_mask():
    B, H, N, D = 2, 2, 256, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    mask = jnp.asarray(np.random.RandomState(3).rand(B, N) > 0.3)
    ref = _sdpa(q, k, v, attn_mask=mask[:, None, None, :])
    out = flash_attention(q, k, v, mask=mask)
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_unaligned_seq():
    # N=197 exercises the pad-and-mask path
    B, H, N, D = 1, 2, 197, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    ref = _sdpa(q, k, v)
    out = _flash(q, k, v, None, D ** -0.5)
    assert out.shape == ref.shape
    assert float(jnp.abs(ref - out).max()) < 2e-2


def test_flash_grads_match():
    B, H, N, D = 1, 2, 128, 32
    q, k, v = _rand((B, H, N, D), 0), _rand((B, H, N, D), 1), _rand((B, H, N, D), 2)
    g1 = jax.grad(lambda q, k, v: (_flash(q, k, v, None, D ** -0.5) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: (_sdpa(q, k, v) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-2
