"""CPU parity fixtures for the TPU alignment + precision subsystem (ISSUE 2).

Everything here runs on the CPU backend and guards two promises:

1. Every knob at its default (off) setting is *bit-identical* to the
   pre-knob code (regression fixture generated at the pre-PR commit).
2. Every knob switched on stays within its documented tolerance of the
   exact path (pad 197→200/256 ≤1e-5 fp32 / ≤1e-2 bf16; bf16 softmax and
   bf16 optimizer-m within step tolerance).
"""
import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.layers import (
    Attention, AttentionPoolLatent, LayerNorm, RmsNorm, global_pool_nlc,
    set_norm_internal_dtype, set_softmax_dtype, softmax_with_policy,
)
from timm_tpu.layers.attention import _sdpa

pytestmark = pytest.mark.precision_policy

_FIXTURE = os.path.join(os.path.dirname(__file__), 'fixtures', 'vit_tiny_img64_golden.npz')


# ---- 1. defaults are bit-identical to pre-PR ---------------------------------

def test_regression_defaults_bit_identical():
    """Golden fixture recorded at the pre-PR commit: with every knob at its
    default, the model output must not change by a single bit."""
    g = np.load(_FIXTURE)
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64)
    model.eval()
    x = jnp.asarray(g['x'])
    feats = np.asarray(model.forward_features(x))
    logits = np.asarray(model(x))
    assert (feats == g['feats']).all(), 'forward_features changed at default settings'
    assert (logits == g['logits']).all(), 'logits changed at default settings'


def test_softmax_policy_default_bit_exact():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8, 197).astype(np.float32)) * 8
    legacy = jax.nn.softmax(x.astype(jnp.float32), axis=-1)
    assert (np.asarray(softmax_with_policy(x)) == np.asarray(legacy)).all()


def test_norm_policy_default_bit_exact():
    x = jnp.asarray(np.random.RandomState(1).randn(2, 17, 64).astype(np.float32))
    ln = LayerNorm(64, rngs=nnx.Rngs(0))
    raw = nnx.LayerNorm(64, epsilon=1e-6, rngs=nnx.Rngs(0))
    assert (np.asarray(ln(x)) == np.asarray(raw(x))).all()
    rn = RmsNorm(64, rngs=nnx.Rngs(0))
    raw_r = nnx.RMSNorm(64, epsilon=1e-6, rngs=nnx.Rngs(0))
    assert (np.asarray(rn(x)) == np.asarray(raw_r(x))).all()


def test_mu_dtype_default_state_fp32():
    from timm_tpu.optim import create_optimizer_v2
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
    state = opt.init(nnx.state(model, nnx.Param))
    assert not any(
        l.dtype == jnp.bfloat16 for l in jax.tree.leaves(state) if hasattr(l, 'dtype')), \
        'default optimizer state must stay fp32'


# ---- 2. fast paths stay within tolerance -------------------------------------

def test_softmax_bf16_fast_path_close():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8, 200).astype(np.float32)) * 8
    ref = jax.nn.softmax(x.astype(jnp.float32), axis=-1)
    with set_softmax_dtype('bfloat16'):
        fast = softmax_with_policy(x)
    assert fast.dtype == jnp.bfloat16
    assert float(jnp.abs(fast.astype(jnp.float32) - ref).max()) < 1e-2
    # per-call override beats the (default) process policy
    fast2 = softmax_with_policy(x, dtype='bfloat16')
    assert (np.asarray(fast2) == np.asarray(fast)).all()


def test_masked_softmax_agrees_with_dense():
    """A key-padding mask over pad columns must reproduce the dense softmax
    over the real columns — the padding path's core invariant."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 4, 197, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 4, 197, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 4, 197, 16).astype(np.float32))
    dense = _sdpa(q, k, v)
    pad = 256 - 197
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    mask = jnp.broadcast_to((jnp.arange(256) < 197)[None, None, None, :], (2, 1, 1, 256))
    masked = _sdpa(qp, kp, vp, attn_mask=mask)[:, :, :197]
    assert float(jnp.abs(masked - dense).max()) < 1e-5
    # all-true mask degenerates to dense exactly (up to reduction order)
    full = _sdpa(q, k, v, attn_mask=jnp.ones((2, 1, 1, 197), bool))
    assert float(jnp.abs(full - dense).max()) < 1e-6


def test_norm_bf16_fast_path_close():
    x = jnp.asarray(np.random.RandomState(3).randn(2, 197, 192).astype(np.float32))
    ln = LayerNorm(192, rngs=nnx.Rngs(0))
    ref = ln(x)
    with set_norm_internal_dtype('bfloat16'):
        fast = ln(x)
    assert fast.dtype == ref.dtype  # activation dtype unchanged
    assert float(jnp.abs(fast - ref).max()) < 5e-2
    # pinned instances ignore the policy
    from timm_tpu.layers import LayerNormFp32
    pinned = LayerNormFp32(192, rngs=nnx.Rngs(0))
    a = pinned(x)
    with set_norm_internal_dtype('bfloat16'):
        b = pinned(x)
    assert (np.asarray(a) == np.asarray(b)).all()


# ---- 3. tile-aligned token padding parity ------------------------------------

@pytest.fixture(scope='module')
def vit_b16_fp32():
    model = timm_tpu.create_model('vit_base_patch16_224')
    model.eval()
    return model


def test_vit_b16_padding_parity_fp32(vit_b16_fp32):
    """ViT-B/16 @224: N=197 → 200 ('auto') and → 256 must match the unpadded
    forward_features within 1e-5 (acceptance criterion)."""
    model = vit_b16_fp32
    x = jnp.asarray(np.random.RandomState(0).rand(1, 224, 224, 3), jnp.float32)
    base = model.forward_features(x)
    assert base.shape[1] == 197
    try:
        for pad, expect_n in (('auto', 200), (256, 256)):
            model.pad_tokens_to = pad
            out = model.forward_features(x)
            assert out.shape == base.shape  # pad stripped before the head
            err = float(jnp.abs(out - base).max())
            assert err < 1e-5, f'pad_tokens_to={pad}: max err {err}'
    finally:
        model.pad_tokens_to = None


def test_vit_b16_padding_parity_bf16(vit_b16_fp32):
    """bf16: padding must stay within the bf16 noise floor. A 12-block bf16
    ViT-B already sits ~3% max relative from its own fp32 twin (median ~0.3%)
    purely from accumulation rounding, so element-max against the bf16 base
    would test the format, not the padding. Instead: (a) the bulk of the
    distribution (p99) vs the bf16 base is ≤1e-2, and (b) the padded model is
    no farther from the fp32 reference than the unpadded bf16 noise floor
    (with 2× headroom) — i.e. padding adds no error of its own. (Measured:
    median ~3e-3, p99 ~1.3e-2, max ~4e-2 — all matching the unpadded
    bf16-vs-fp32 spread.)"""
    model = timm_tpu.create_model('vit_base_patch16_224', dtype=jnp.bfloat16)
    model.eval()
    x32 = jnp.asarray(np.random.RandomState(0).rand(1, 224, 224, 3), jnp.float32)
    ref = vit_b16_fp32.forward_features(x32)
    x = x32.astype(jnp.bfloat16)
    base = model.forward_features(x).astype(jnp.float32)

    def rel(a, b):
        return np.asarray(jnp.abs(a - b) / (1.0 + jnp.abs(b)))

    noise_floor = rel(base, ref).max()
    for pad in ('auto', 256):
        model.pad_tokens_to = pad
        out = model.forward_features(x).astype(jnp.float32)
        med = float(np.median(rel(out, base)))
        assert med < 1e-2, f'pad_tokens_to={pad} (bf16): median rel err {med}'
        vs_ref = rel(out, ref).max()
        assert vs_ref < 2 * noise_floor + 1e-2, (
            f'pad_tokens_to={pad} (bf16): {vs_ref} vs fp32 ref exceeds 2x the '
            f'unpadded bf16 noise floor {noise_floor}')


def test_vit_padding_logits_and_head_paths(vit_b16_fp32):
    """End-to-end logits parity + the masked pool/attn-pool capability."""
    model = vit_b16_fp32
    x = jnp.asarray(np.random.RandomState(1).rand(1, 224, 224, 3), jnp.float32)
    base = model(x)
    try:
        model.pad_tokens_to = 256
        out = model(x)
        assert float(jnp.abs(out - base).max()) < 1e-5
    finally:
        model.pad_tokens_to = None
    # masked global pool over a still-padded sequence == unpadded pool
    feats = model.forward_features(x)
    padded = jnp.pad(feats, ((0, 0), (0, 59), (0, 0)))
    mask = jnp.broadcast_to((jnp.arange(256) < 197)[None], (1, 256))
    for pt in ('avg', 'max', 'avgmax'):
        a = global_pool_nlc(feats, pt, num_prefix_tokens=1)
        b = global_pool_nlc(padded, pt, num_prefix_tokens=1, mask=mask)
        assert float(jnp.abs(a - b).max()) < 1e-5, pt


def test_attention_pool_latent_key_mask():
    rngs = nnx.Rngs(0)
    pool = AttentionPoolLatent(64, num_heads=4, rngs=rngs)
    x = jnp.asarray(np.random.RandomState(4).randn(2, 50, 64).astype(np.float32))
    base = pool(x)
    xp = jnp.pad(x, ((0, 0), (0, 14), (0, 0)))
    mask = jnp.broadcast_to((jnp.arange(64) < 50)[None], (2, 64))
    out = pool(xp, attn_mask=mask)
    assert float(jnp.abs(out - base).max()) < 1e-5


def test_padding_rejects_patch_drop():
    with pytest.raises(ValueError):
        timm_tpu.create_model(
            'vit_tiny_patch16_224', img_size=64, pad_tokens_to=256, patch_drop_rate=0.25)


def test_flash_attention_mask_validation():
    from timm_tpu.kernels import flash_attention
    q = jnp.ones((2, 4, 128, 32))
    with pytest.raises(ValueError):
        flash_attention(q, q, q, mask=jnp.ones((2, 128), jnp.float32))  # additive
    with pytest.raises(ValueError):
        flash_attention(q, q, q, mask=jnp.ones((2, 4, 128, 128), bool))  # per-query


# ---- 4. optimizer mu_dtype ---------------------------------------------------

def test_mu_dtype_bf16_adamw_step_close():
    import optax
    from timm_tpu.optim import create_optimizer_v2

    class Tiny(nnx.Module):
        def __init__(self, rngs):
            self.fc1 = nnx.Linear(32, 64, rngs=rngs)
            self.fc2 = nnx.Linear(64, 8, rngs=rngs)

    def run(mu_dtype):
        m = Tiny(nnx.Rngs(0))
        params = nnx.state(m, nnx.Param)
        opt = create_optimizer_v2(m, opt='adamw', lr=1e-2, weight_decay=0.01, mu_dtype=mu_dtype)
        state = opt.init(params)
        rng = np.random.RandomState(5)
        for _ in range(5):
            grads = jax.tree.map(lambda p: jnp.asarray(rng.randn(*p.shape), p.dtype) * 0.1, params)
            updates, state = opt.update(grads, state, params, lr=1e-2)
            params = optax.apply_updates(params, updates)
        return params, state

    p_ref, _ = run(None)
    p_bf, s_bf = run('bfloat16')
    assert any(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(s_bf) if hasattr(l, 'dtype')), \
        'mu_dtype=bf16 did not reduce the first moment'
    err = max(float(jnp.abs(a - b).max())
              for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_bf)))
    assert err < 1e-3, f'5-step AdamW divergence {err} vs fp32 reference'


def test_mu_dtype_nadamw_lamb_state_reduced():
    from timm_tpu.optim import create_optimizer_v2

    class Tiny(nnx.Module):
        def __init__(self, rngs):
            self.fc = nnx.Linear(16, 16, rngs=rngs)

    for name in ('nadamw', 'lamb'):
        m = Tiny(nnx.Rngs(0))
        opt = create_optimizer_v2(m, opt=name, lr=1e-3, weight_decay=0.01, mu_dtype='bfloat16')
        state = opt.init(nnx.state(m, nnx.Param))
        assert any(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(state) if hasattr(l, 'dtype')), name


# ---- 5. bench.py dry-run sweep ----------------------------------------------

def test_bench_dry_run_flag_combinations():
    """Acceptance: a dry-run smoke of each A/B flag combination completes on
    CPU. Runs in-process (one interpreter, shared jit cache) over all 2³
    combinations of the three levers plus the pad='auto' spelling."""
    import importlib.util
    bench_path = os.path.join(os.path.dirname(__file__), '..', 'bench.py')
    spec = importlib.util.spec_from_file_location('bench', bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Args:
        model = 'vit_tiny_patch16_224'
        img_size = 32
        pad_tokens = ''
        softmax_dtype = ''
        norm_dtype = ''
        mu_dtype = ''

    combos = list(itertools.product(('', '256'), ('', 'bfloat16'), ('', 'bfloat16')))
    combos.append(('auto', '', ''))
    from timm_tpu.layers import config as layer_config
    for pad, sm, mu in combos:
        args = Args()
        args.pad_tokens, args.softmax_dtype, args.mu_dtype = pad, sm, mu
        try:
            rc = bench._dry_run(args)
        finally:
            # _apply_precision_knobs sets process-level policy; reset per combo
            layer_config.set_softmax_dtype(None)
            layer_config.set_norm_internal_dtype(None)
        assert rc == 0, f'dry-run failed for pad={pad!r} softmax={sm!r} mu={mu!r}'
