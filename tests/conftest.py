"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

(The axon TPU plugin registers itself via sitecustomize and wins over
JAX_PLATFORMS env, so the platform must be pinned via jax.config here.)
"""
import os

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=8')

import jax

try:
    jax.config.update('jax_platforms', 'cpu')
    jax.config.update('jax_num_cpu_devices', 8)
except Exception:
    pass

# Persistent XLA compilation cache: model sweeps recompile the same tiny
# fixture programs every run; caching compiled executables across pytest
# invocations cuts full-suite wall time from ~9 min cold to well under the
# 10-minute budget on warm runs (VERDICT r3 weak #7). The env var is PINNED
# here (not merely defaulted at read time) so subprocess tests (resilience
# drills, compile-cache round-trips, bench children) inherit the same warm
# dir — tier-1's budget must not depend on ambient process warmth.
os.environ.setdefault(
    'TIMM_TPU_COMPILE_CACHE',
    os.environ.get('TIMM_TPU_XLA_CACHE', '/tmp/timm_tpu_xla_cache'))
try:
    jax.config.update('jax_compilation_cache_dir', os.environ['TIMM_TPU_COMPILE_CACHE'])
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
    jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
except Exception:
    pass

import pytest


def pytest_configure(config):
    # registered in pyproject.toml too; double registration is harmless and
    # keeps `pytest tests/test_serve.py` warning-free outside the repo root
    config.addinivalue_line(
        'markers',
        'serve: continuous-batching inference engine — bucketing, admission '
        'queue, AOT prewarm, LRU residency, load drill (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'perfbudget: hardware-independent perf-regression budgets + profiler '
        'harness + bench replay smoke (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'deviceaug: on-device batch augmentation + NaFlex packed bucketed '
        'batching — host/device parity, donation, zero-recompile epochs '
        '(runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'quant: int8 post-training weight-only quantization — round-trip '
        'bounds, golden-fixture logits tolerance, scale-spec inheritance, '
        'quantized serve parity, distill smoke (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'kernels: Pallas kernel portfolio — registry lint, auto-generated '
        'parity, fused AdamW/EMA drift, augment-epilogue oracle parity, '
        'win-or-delete verdicts (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'elastic: elastic pod-scale training — resize-the-mesh resume drills '
        '(8↔4 devices, global batch invariant) + async checkpoint writer '
        '(runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'analysis: unified static-analysis suite — source/jaxpr/HLO rules, '
        'pragma waivers, planted-violation fixtures, CLI exit codes, zoo '
        'abstract-trace smoke (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'autotune: config autotuner — legal-space enumeration, roofline '
        'ranking, estimator/probed agreement, elastic re-solve, bucket-'
        'ladder DP (runs in tier-1)')
    config.addinivalue_line(
        'markers',
        'multihost: multi-process pod runtime — KV-store consensus, '
        'process-local sharded checkpoints, host-loss kill drill '
        '(runs in tier-1)')


@pytest.fixture(scope='session')
def mesh8():
    from timm_tpu.parallel import create_mesh, set_global_mesh
    mesh = create_mesh()
    set_global_mesh(mesh)
    return mesh


@pytest.fixture(scope='session')
def analysis_programs():
    """ONE probe run shared by the perf-budget comparisons (test_perfbudget)
    and the analysis suite's Tier B/C passes (test_analysis): run_matrix
    lowers each program exactly once, and capture_programs hands the jaxprs
    + compiled executables to the jaxpr/HLO rules without re-lowering.
    probe_config saves/restores the global mesh, so this composes with
    whatever mesh the consuming test file has active."""
    from timm_tpu.perfbudget import run_matrix
    from timm_tpu.perfbudget.probe import capture_programs

    names = ('base', 'accum4', 'serve_test_vit', 'tp22', 'elastic_resize',
             'stage_scan_convnext', 'stage_scan_swin')
    with capture_programs() as programs:
        measured = run_matrix(names=list(names))
    return {'names': names, 'measured': measured, 'programs': list(programs)}
