"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

(The axon TPU plugin registers itself via sitecustomize and wins over
JAX_PLATFORMS env, so the platform must be pinned via jax.config here.)
"""
import os

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=8')

import jax

try:
    jax.config.update('jax_platforms', 'cpu')
    jax.config.update('jax_num_cpu_devices', 8)
except Exception:
    pass

import pytest


@pytest.fixture(scope='session')
def mesh8():
    from timm_tpu.parallel import create_mesh, set_global_mesh
    mesh = create_mesh()
    set_global_mesh(mesh)
    return mesh
