"""Planted waiver twin: a module-scope pragma (first 5 lines) waives
large-literal file-wide."""
# timm-tpu-lint: disable=large-literal planted fixture proving the module-scope waiver
import numpy as np

_BIG = np.ones((512, 1024), np.float32)


def program(x):
    return x + _BIG


def example_args():
    return (np.zeros((512, 1024), np.float32),)
