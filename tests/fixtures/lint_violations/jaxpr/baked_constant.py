"""Planted violation: a 2 MB numpy constant closed over into the traced
program (rule large-literal) — the PR 9 landmine in miniature."""
import numpy as np

_BIG = np.ones((512, 1024), np.float32)  # 2.0 MB baked constant


def program(x):
    return x + _BIG


def example_args():
    return (np.zeros((512, 1024), np.float32),)
