"""Planted violation: a kernels/ module that registers no KernelSpec and
carries no waiver (rule kernel-registered)."""


def fused_noop(x):
    return x
