"""Planted waiver twin for kernel-registered."""
# no-kernel-registry: planted fixture - host-side helper, no kernel to register


def fused_noop(x):
    return x
