"""Waived twin of process_zero_io.py: the same unguarded write carrying an
explicit reason — suppressed, but kept in the report's audit trail."""
import json


def write_summary(output_dir, metrics):
    with open(output_dir + '/summary.json', 'w') as f:  # timm-tpu-lint: disable=process-zero-io fixture twin: single-process tool by design
        json.dump(metrics, f)
