"""Planted violation: a jax.jit call with neither donate_argnums nor a
`# no-donate: <reason>` waiver (rule donation-declared)."""
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step)
