"""Planted waiver twin: the legacy `# no-donate:` shim suppresses the rule."""
import jax


def eval_step(state, batch):
    return state


# no-donate: planted fixture - eval step reuses its inputs across calls
step = jax.jit(eval_step)
