"""Planted process-zero-io violation: a driver-style summary write with no
primary-process guard — on a pod every host would race this file."""
import json

rank = 0


def write_summary(output_dir, metrics):
    with open(output_dir + '/summary.json', 'w') as f:
        json.dump(metrics, f)


def write_guarded(output_dir, metrics, args=None):
    # the guarded spellings the rule must accept
    if rank == 0:
        with open(output_dir + '/args.yaml', 'w') as f:
            f.write('ok')
    if is_primary(args):
        with open(output_dir + '/best.json', 'w') as f:
            json.dump(metrics, f)


def is_primary(args=None):
    return rank == 0
