"""Planted violation: a hard-coded fp32 softmax outside the policy module
(rule fp32-softmax)."""
import jax
import jax.numpy as jnp


def attend(scores):
    return jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
