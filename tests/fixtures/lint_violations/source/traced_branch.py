"""Planted violation: a Python branch on a traced argument value inside a
jitted function (rule traced-branch)."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp_positive(x):
    if x > 0:
        return x
    return jnp.zeros_like(x)
