"""Planted violation: a silent exception swallow (rule silent-except)."""


def read_maybe(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:
        pass
    return None
