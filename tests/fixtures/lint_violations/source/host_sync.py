"""Planted violation: a host-synchronizing call inside a jitted body
(rule host-sync)."""
import jax


@jax.jit
def loss_scalar(x):
    return x.sum().item()
