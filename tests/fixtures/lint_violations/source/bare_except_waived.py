"""Planted waiver twin: the same swallow, waived with a mandatory reason.

The standalone pragma waives the NEXT line, which is where the finding
anchors (the `except` line).
"""


def read_maybe(path):
    try:
        with open(path) as f:
            return f.read()
    # timm-tpu-lint: disable=silent-except planted fixture proving the line-scope waiver
    except Exception:
        pass
    return None
