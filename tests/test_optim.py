"""Optimizer tests (reference: tests/test_optim.py — registry construction,
convergence on a toy problem, layer-decay grouping, caution variants)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.optim import create_optimizer_v2, list_optimizers, param_groups_weight_decay

ALL_OPTS = [o for o in list_optimizers() if o != 'lookahead']


class Toy(nnx.Module):
    def __init__(self, rngs):
        self.fc1 = nnx.Linear(4, 8, rngs=rngs)
        self.fc2 = nnx.Linear(8, 2, rngs=rngs)

    def __call__(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def _toy_problem():
    model = Toy(nnx.Rngs(0))
    x = jnp.asarray(np.random.RandomState(0).randn(32, 4), jnp.float32)
    w = jnp.asarray(np.random.RandomState(1).randn(4, 2), jnp.float32)
    y = x @ w
    return model, x, y


@pytest.mark.parametrize('opt_name', ALL_OPTS)
def test_optimizer_step(opt_name):
    model, x, y = _toy_problem()
    opt = create_optimizer_v2(model, opt=opt_name, lr=1e-2, weight_decay=0.01)
    params = nnx.state(model, nnx.Param)
    state = opt.init(params)

    def loss_fn(p):
        m = nnx.merge(nnx.graphdef(model), p)
        return jnp.mean((m(x) - y) ** 2)

    # two steps: some optimizers (ADOPT) only initialize state on step one
    for _ in range(2):
        loss0, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params, lr=1e-2)
        params = optax.apply_updates(params, updates)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(nnx.state(model, nnx.Param)), jax.tree.leaves(params)))


@pytest.mark.parametrize('opt_name', ['sgd', 'adamw', 'lamb', 'lion', 'muon', 'nadamw', 'adopt', 'madgrad', 'laprop', 'mars'])
def test_optimizer_converges(opt_name):
    from timm_tpu.optim import list_optimizers
    if opt_name not in list_optimizers():
        pytest.skip(f'{opt_name} not available in this optax version (registry gates on hasattr)')
    model, x, y = _toy_problem()
    opt = create_optimizer_v2(model, opt=opt_name, lr=5e-2, weight_decay=0.0)
    params = nnx.state(model, nnx.Param)
    state = opt.init(params)
    graphdef = nnx.graphdef(model)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            m = nnx.merge(graphdef, p)
            return jnp.mean((m(x) - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params, lr=5e-2)
        return optax.apply_updates(params, updates), state, loss

    losses = []
    for _ in range(50):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, f'{opt_name} failed to reduce loss: {losses[0]} -> {losses[-1]}'


def _flat_values(tree):
    from timm_tpu.utils.serialization import _kp_str
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {_kp_str(kp): v for kp, v in flat}


def test_weight_decay_mask():
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=64)
    mask = param_groups_weight_decay(model, weight_decay=0.05)
    flat = _flat_values(mask)
    assert flat['cls_token'] == False  # noqa: E712
    assert flat['pos_embed'] == False  # noqa: E712
    assert flat['blocks.0.attn.qkv.bias'] == False  # noqa: E712
    assert flat['blocks.0.attn.qkv.kernel'] == True  # noqa: E712


def test_layer_decay_scales():
    from timm_tpu.optim import param_groups_layer_decay
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=64)
    scales, mask = param_groups_layer_decay(model, layer_decay=0.5)
    flat = _flat_values(scales)
    # stem gets smallest scale, head largest
    assert flat['patch_embed.proj.kernel'] < flat['blocks.1.attn.qkv.kernel']
    assert flat['head.kernel'] == 1.0


def test_caution_masks_disagreeing_updates():
    model, x, y = _toy_problem()
    opt = create_optimizer_v2(model, opt='sgd', lr=1e-2, momentum=0.0, caution=True)
    params = nnx.state(model, nnx.Param)
    state = opt.init(params)

    def loss_fn(p):
        m = nnx.merge(nnx.graphdef(model), p)
        return jnp.mean((m(x) - y) ** 2)

    _, grads = jax.value_and_grad(loss_fn)(params)
    updates, _ = opt.update(grads, state, params, lr=1e-2)
    # plain SGD update = -lr*g, always sign-disagreeing with g → never masked
    for u, g in zip(jax.tree.leaves(updates), jax.tree.leaves(grads)):
        assert bool(jnp.all((np.asarray(u) == 0) | (np.sign(u) != np.sign(g))))


def test_optimizer_kwargs_bridge():
    from types import SimpleNamespace
    from timm_tpu.optim import optimizer_kwargs
    cfg = SimpleNamespace(opt='adamw', lr=1e-3, weight_decay=0.05, momentum=0.9,
                          opt_eps=1e-8, opt_betas=(0.9, 0.95), layer_decay=0.75,
                          layer_decay_min_scale=None, opt_kwargs={}, opt_caution=False)
    kw = optimizer_kwargs(cfg)
    assert kw['opt'] == 'adamw' and kw['betas'] == (0.9, 0.95) and kw['layer_decay'] == 0.75


def test_coupled_l2_for_wd_less_factories():
    """Optimizers whose optax factory lacks a weight_decay param must still
    apply (coupled L2) decay — ADVICE r1 high: sgd/adam/etc silently trained
    unregularized."""
    for opt_name in ('sgd', 'adam', 'rmsprop'):
        model, x, y = _toy_problem()
        opt = create_optimizer_v2(model, opt=opt_name, lr=1e-2, weight_decay=0.1)
        params = nnx.state(model, nnx.Param)
        state = opt.init(params)
        zero_grads = jax.tree.map(jnp.zeros_like, params)
        updates, _ = opt.update(zero_grads, state, params, lr=1e-2)
        # with zero grads the only update source is weight decay: kernels move
        flat = {'/'.join(map(str, p)): v for p, v in jax.tree_util.tree_leaves_with_path(updates)}
        kernel_updates = [v for k, v in flat.items() if 'kernel' in k]
        assert kernel_updates and all(float(jnp.abs(u).max()) > 0 for u in kernel_updates), opt_name
        # bias params are WD-masked (filter_bias_and_bn) and must not move
        bias_updates = [v for k, v in flat.items() if 'bias' in k]
        assert all(float(jnp.abs(u).max()) == 0 for u in bias_updates), opt_name


def test_adan_three_betas():
    """--opt-betas with 3 values must reach optax.adan's b3 (ADVICE r1 low)."""
    from timm_tpu.optim import list_optimizers
    if 'adan' not in list_optimizers():
        pytest.skip('adan not available in this optax version (registry gates on hasattr)')
    model, x, y = _toy_problem()
    opt = create_optimizer_v2(model, opt='adan', lr=1e-3, betas=(0.9, 0.95, 0.99))
    assert opt.defaults['b3'] == pytest.approx(0.99)

    def run(b3):
        o = create_optimizer_v2(model, opt='adan', lr=1e-3, betas=(0.9, 0.95, b3))
        params = nnx.state(model, nnx.Param)
        state = o.init(params)

        def loss_fn(p):
            m = nnx.merge(nnx.graphdef(model), p)
            return jnp.mean((m(x) - y) ** 2)

        for _ in range(3):
            _, grads = jax.value_and_grad(loss_fn)(params)
            updates, state = o.update(grads, state, params, lr=1e-3)
            params = optax.apply_updates(params, updates)
        return np.asarray(jax.tree.leaves(params)[0])

    assert not np.allclose(run(0.5), run(0.999))
