"""Autotuner tests: legal-space enumeration, roofline ranking, estimator vs
probed agreement, elastic re-solve, bucket-ladder DP.

Everything here runs on the forced 8-virtual-CPU-device topology
(conftest.py). The one real lowering (the estimator/probed agreement band)
reuses the session-scoped `analysis_programs` probe run as its anchor plus a
single extra compile that rides the persistent compile cache; the
`train.py --autotune` subprocess smoke is `-m slow` with the in-process CLI
twin kept in tier-1.
"""
import argparse
import itertools
import json
import logging
import os
import subprocess
import sys

import pytest

import timm_tpu  # noqa: F401  — device topology + registry side effects

pytestmark = pytest.mark.autotune

MODEL_KW = {'num_classes': 10, 'img_size': 32}
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _abstract_info():
    from timm_tpu.autotune.solver import abstract_model_info
    return abstract_model_info('test_vit', MODEL_KW)


# ---- enumerator legality ----------------------------------------------------

def test_enumerator_points_build_real_meshes_and_pass_partition_lint():
    import jax

    from timm_tpu.autotune import enumerate_configs
    from timm_tpu.parallel.mesh import create_mesh
    from timm_tpu.parallel.sharding import _kp_str, path_specs

    params, dims, _ = _abstract_info()
    legal, _rej = enumerate_configs(n_devices=8, global_batch=64,
                                    params=params, model_dims=dims)
    assert legal, 'no legal configs for the canonical tiny space'

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    seen_pairs = set()
    for p in legal:
        cfg = p.config
        # batch decomposition holds the global batch and the shard rule
        assert cfg.batch_size * cfg.grad_accum == 64
        assert cfg.batch_size % 8 == 0
        assert p.hbm_bytes == p.param_bytes * 2 + p.opt_bytes + p.act_bytes
        if (cfg.fsdp, cfg.tp) in seen_pairs:
            continue
        seen_pairs.add((cfg.fsdp, cfg.tp))
        # the emitted axes build a REAL mesh...
        mesh = create_mesh(fsdp=cfg.fsdp if cfg.fsdp > 1 else None,
                           tp=cfg.tp if cfg.tp > 1 else None)
        assert mesh.size == 8
        # ...and every param's resolved spec divides its dims evenly
        specs = path_specs(params, mesh)
        for kp, leaf in flat:
            spec = specs[_kp_str(kp)]
            for dim, ax in zip(leaf.shape, tuple(spec)):
                if ax is None:
                    continue
                shards = 1
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    shards *= int(mesh.shape[a])
                assert dim % shards == 0, (
                    f'{_kp_str(kp)}: dim {dim} not divisible by {shards} '
                    f'(fsdp={cfg.fsdp}, tp={cfg.tp})')
    assert (1, 1) in seen_pairs and (8, 1) in seen_pairs


def test_illegal_global_batch_refused_with_nearest_legal_text():
    from timm_tpu.autotune import AutotuneError, autotune, enumerate_configs

    legal, rej = enumerate_configs(n_devices=8, global_batch=30)
    assert not legal
    msg = ' '.join(str(r) for r in rej)
    assert 'nearest legal global batch' in msg
    assert '24 or 32' in msg

    with pytest.raises(AutotuneError) as ei:
        autotune('test_vit', MODEL_KW, global_batch=30, probe_anchor=False)
    assert 'no legal config' in str(ei.value)
    assert ei.value.rejections


def test_illegal_mesh_axes_rejected_with_clamp_suggestion():
    from timm_tpu.autotune import mesh_axis_points

    pairs, rej = mesh_axis_points(8, fsdp_candidates=(3,), tp_candidates=(1,))
    assert pairs == []
    assert len(rej) == 1
    assert 'does not divide' in rej[0].reason
    assert 'fsdp=2 tp=1' in rej[0].suggestion


def test_hbm_budget_rejections_are_loud():
    from timm_tpu.autotune import enumerate_configs

    params, dims, _ = _abstract_info()
    legal, rej = enumerate_configs(n_devices=8, global_batch=64,
                                   params=params, model_dims=dims,
                                   hbm_budget_bytes=10 * 1024)
    assert not legal
    hbm_rej = [r for r in rej if 'HBM budget' in r.reason]
    assert hbm_rej
    assert any('remat' in r.suggestion or 'fsdp' in r.suggestion
               for r in hbm_rej)


# ---- roofline ranking -------------------------------------------------------

def test_roofline_monotone_in_flops_and_bytes():
    from timm_tpu.autotune import DEVICE_CLASSES, roofline_ms

    dc = DEVICE_CLASSES['v5e']
    base = roofline_ms(1e12, 1e9, dc)[0]
    assert roofline_ms(2e12, 1e9, dc)[0] >= base
    assert roofline_ms(1e12, 2e9, dc)[0] >= base
    # the bound label flips where the two service times cross
    assert roofline_ms(1e15, 1, dc)[3] == 'compute'
    assert roofline_ms(1, 1e12, dc)[3] == 'memory'


def test_analytic_ranking_is_deterministic_and_scan_wins_ties():
    from timm_tpu.autotune import autotune

    kw = dict(global_batch=64, probe_anchor=False, correction=1.0)
    r1 = autotune('test_vit', MODEL_KW, **kw)
    r2 = autotune('test_vit', MODEL_KW, **kw)
    assert [rp.point.config for rp in r1.ranked] == \
        [rp.point.config for rp in r2.ranked]
    assert r1.tier == 'analytic'
    assert r1.winner.block_scan, \
        'trace-penalty tiebreak must prefer the scanned program'
    # a no-scan twin of the winner exists and ranks strictly below it
    import dataclasses
    twin = dataclasses.replace(r1.winner, block_scan=False)
    ranks = {rp.point.config: rp.rank for rp in r1.ranked}
    assert ranks[twin] > ranks[r1.winner]


def test_correction_factor_scales_time_but_not_order():
    from timm_tpu.autotune import autotune

    r1 = autotune('test_vit', MODEL_KW, global_batch=64, probe_anchor=False,
                  correction=1.0)
    r2 = autotune('test_vit', MODEL_KW, global_batch=64, probe_anchor=False,
                  correction=2.0)
    assert [rp.point.config for rp in r2.ranked] == \
        [rp.point.config for rp in r1.ranked]
    assert r2.ranked[0].cost.step_ms == pytest.approx(
        2.0 * r1.ranked[0].cost.step_ms, rel=1e-6)


def test_load_correction_reads_bench_self(tmp_path):
    from timm_tpu.autotune import load_correction

    path = tmp_path / 'BENCH_SELF.json'
    assert load_correction(str(path)) == 1.0             # missing file
    path.write_text(json.dumps({'autotune': {'correction': 1.37}}))
    assert load_correction(str(path)) == pytest.approx(1.37)
    path.write_text('not json')
    assert load_correction(str(path)) == 1.0             # corrupt -> neutral


# ---- estimator vs probed ----------------------------------------------------

def test_estimator_passes_exactly_through_probed_anchor(analysis_programs):
    from timm_tpu.autotune import CandidateConfig
    from timm_tpu.autotune.cost import (analytic_cost, detect_device_class,
                                        fit_scales, probed_cost)
    from timm_tpu.autotune.solver import _anchor_point

    anchor = analysis_programs['measured']['base']   # test_vit b=8 fsdp=1 tp=1
    assert 'flops' in anchor and 'bytes_accessed' in anchor
    params, dims, mlp = _abstract_info()
    dc = detect_device_class()
    a_cfg = CandidateConfig(batch_size=8)
    ap = _anchor_point(a_cfg, params, dims, 8, 1, mlp)

    fs, bs = fit_scales(anchor, ap, dims, dc, 8, mlp)
    est = analytic_cost(ap, dims, dc, 8, mlp_ratio=mlp,
                        flops_scale=fs, bytes_scale=bs, tier='estimator')
    pr = probed_cost(anchor, ap, dc)
    # calibration guarantee: at the anchor the estimator IS the probed cost
    assert est.flops == pytest.approx(pr.flops, rel=1e-9)
    assert est.bytes == pytest.approx(pr.bytes, rel=1e-9)
    assert est.step_ms == pytest.approx(pr.step_ms, rel=1e-9)


def test_estimator_vs_probed_agreement_band(analysis_programs):
    """Off-anchor, the estimator must stay within a (loose) multiplicative
    band of the probed roofline — the correction-factor protocol assumes the
    RANKING survives even though absolute CPU-class milliseconds are
    nominal. One extra compile (the fsdp4 matrix config's real train step),
    shared with the persistent compile cache."""
    from timm_tpu.autotune import CandidateConfig, enumerate_configs
    from timm_tpu.autotune.cost import (analytic_cost, detect_device_class,
                                        fit_scales, probed_cost)
    from timm_tpu.autotune.solver import _anchor_point
    from timm_tpu.perfbudget.probe import DEFAULT_MATRIX, probe_config

    anchor = analysis_programs['measured']['base']
    params, dims, mlp = _abstract_info()
    dc = detect_device_class()
    ap = _anchor_point(CandidateConfig(batch_size=8), params, dims, 8, 1, mlp)
    fs, bs = fit_scales(anchor, ap, dims, dc, 8, mlp)

    fsdp4 = next(c for c in DEFAULT_MATRIX if c.name == 'fsdp4')
    probed_metrics = probe_config(fsdp4)
    legal, _ = enumerate_configs(n_devices=8, global_batch=8, params=params,
                                 model_dims=dims, fsdp_candidates=(4,),
                                 tp_candidates=(1,), allow_remat=False,
                                 include_block_scan=False)
    point = next(p for p in legal
                 if p.config == CandidateConfig(fsdp=4, batch_size=8))
    est = analytic_cost(point, dims, dc, 8, mlp_ratio=mlp,
                        flops_scale=fs, bytes_scale=bs, tier='estimator')
    pr = probed_cost(probed_metrics, point, dc)
    assert pr is not None
    ratio = est.step_ms / pr.step_ms
    assert 0.1 <= ratio <= 10.0, (
        f'estimator/probed = {ratio:.3f} outside the agreement band '
        f'(est {est.step_ms:.4f} ms vs probed {pr.step_ms:.4f} ms)')


# ---- elastic re-solve -------------------------------------------------------

def test_elastic_resolve_identity_at_unchanged_topology():
    from timm_tpu.autotune import CandidateConfig, resolve_config_for_topology

    cfg = resolve_config_for_topology(
        8, 8, model='test_vit', model_kwargs=MODEL_KW,
        fsdp=4, tp=None, prefer_batch_size=8)
    assert cfg == CandidateConfig(fsdp=4, tp=1, batch_size=8, grad_accum=1)


def test_plan_elastic_resume_solver_matches_clamp_when_request_legal():
    from timm_tpu.resilience.elastic import plan_elastic_resume

    with_solver = plan_elastic_resume(8, batch_size=8, grad_accum=1, fsdp=4,
                                      model='test_vit', model_kwargs=MODEL_KW)
    clamp_only = plan_elastic_resume(8, batch_size=8, grad_accum=1, fsdp=4)
    for field in ('devices', 'fsdp', 'tp', 'batch_size', 'grad_accum',
                  'global_batch'):
        assert getattr(with_solver, field) == getattr(clamp_only, field), field
    assert not any('re-solved' in n for n in with_solver.notes)


def test_elastic_resize_8_to_4_keeps_requested_legal_config():
    # the 8->4 drill geometry: fsdp=4, b=8 is STILL legal on 4 devices, so
    # the re-solve is the identity and the drill's 1e-6 parity bound holds
    from timm_tpu.autotune import CandidateConfig, resolve_config_for_topology

    cfg = resolve_config_for_topology(
        4, 8, model='test_vit', model_kwargs=MODEL_KW,
        fsdp=4, tp=None, prefer_batch_size=8)
    assert cfg == CandidateConfig(fsdp=4, tp=1, batch_size=8, grad_accum=1)


def test_elastic_resolve_replaces_illegal_request():
    from timm_tpu.autotune import resolve_config_for_topology

    # fsdp=8 cannot exist on 4 devices: the solver must re-solve, holding
    # the global batch, and prefer axes near the request
    cfg = resolve_config_for_topology(
        4, 8, model='test_vit', model_kwargs=MODEL_KW,
        fsdp=8, tp=None, prefer_batch_size=8)
    assert cfg is not None
    assert cfg.global_batch == 8
    assert 4 % (cfg.fsdp * cfg.tp) == 0
    assert cfg.fsdp == 4, 'nearest legal fsdp to the requested 8 on 4 devices'


def test_plan_elastic_resume_falls_back_when_solver_refuses():
    from timm_tpu.resilience.elastic import plan_elastic_resume

    plan = plan_elastic_resume(8, batch_size=8, grad_accum=1, fsdp=4,
                               model='not_a_registered_model')
    assert plan.fsdp == 4 and plan.batch_size == 8 and plan.grad_accum == 1
    assert any('falling back to the largest-divisor clamp' in n
               for n in plan.notes)


# ---- bucket-ladder DP -------------------------------------------------------

def test_bucket_dp_matches_brute_force():
    from timm_tpu.autotune import ladder_cost, propose_buckets

    hist = {1: 7, 3: 2, 4: 11, 6: 1, 9: 5, 16: 3}
    sizes = sorted(hist)
    for k in (1, 2, 3, 4):
        # brute force over ladders covering the largest observed size (the
        # DP's covering constraint — no request is ever chunked)
        best = min(ladder_cost(c, hist)
                   for r in range(1, k + 1)
                   for c in itertools.combinations(sizes, r)
                   if max(sizes) in c)
        got = propose_buckets(hist, max_buckets=k)
        assert len(got) <= k
        assert max(got) == max(sizes)
        assert ladder_cost(got, hist) == best, (k, got)


def test_propose_buckets_divisor_cap_determinism_and_empty():
    from timm_tpu.autotune import ladder_waste, propose_buckets

    hist = {3: 5, 7: 1}
    got = propose_buckets(hist, max_buckets=2, divisor=4)
    assert all(b % 4 == 0 for b in got)
    assert max(got) >= 7

    capped = propose_buckets({3: 5, 100: 1}, max_buckets=2, max_bucket=16)
    assert max(capped) <= 16

    assert propose_buckets(hist, max_buckets=3) == \
        propose_buckets(hist, max_buckets=3)
    assert 0.0 <= ladder_waste(got, hist) < 1.0

    with pytest.raises(ValueError):
        propose_buckets({})


def test_serve_engine_bucket_advisory():
    from timm_tpu.serve.engine import InferenceEngine

    eng = InferenceEngine(buckets=(2, 16))
    assert eng.bucket_advisory() is None            # no traffic yet
    eng.stats['request_sizes'].update({1: 50, 2: 30, 16: 1})
    adv = eng.bucket_advisory()
    assert adv is not None
    assert adv['proposed_waste'] < adv['current_waste']
    assert adv['requests'] == 81
    assert max(adv['proposed']) >= 16


# ---- probe integration / small fix ------------------------------------------

def test_cost_analysis_logs_config_name_once(caplog):
    from timm_tpu.perfbudget.probe import _COST_WARNED, _cost_analysis

    class Boom:
        def cost_analysis(self):
            raise RuntimeError('backend says no')

    _COST_WARNED.discard('boomcfg')
    with caplog.at_level(logging.WARNING, logger='timm_tpu.perfbudget.probe'):
        assert _cost_analysis(Boom(), 'boomcfg') == {}
        assert _cost_analysis(Boom(), 'boomcfg') == {}
    msgs = [r.getMessage() for r in caplog.records if 'boomcfg' in r.getMessage()]
    assert len(msgs) == 1, 'the warning must fire exactly once per config'
    assert 'RuntimeError' in msgs[0] and 'backend says no' in msgs[0]


def test_probe_matrix_and_budgets_carry_autotune_config():
    from timm_tpu.perfbudget.budgets import load_budgets
    from timm_tpu.perfbudget.probe import DEFAULT_MATRIX

    cfg = next(c for c in DEFAULT_MATRIX if c.name == 'autotune')
    assert cfg.collect == 'autotune'
    assert cfg.batch_size * cfg.grad_accum == 64
    budgets = load_budgets()
    entry = budgets['configs']['autotune']
    for key in ('autotune_candidates', 'autotune_winner_fsdp',
                'autotune_winner_legal', 'donation_ok', 'flops'):
        assert key in entry, key


def test_replay_checklist_has_autotune_step():
    from timm_tpu.perfbudget.replay import REPLAY_STEPS

    assert len(REPLAY_STEPS) == 22
    step = next(s for s in REPLAY_STEPS if s['id'] == 'autotune')
    assert step['kind'] == 'autotune'
    assert step['dry']['top_k'] >= 2 and step['live']['top_k'] == 3


# ---- user surfaces ----------------------------------------------------------

def test_apply_to_args_and_json_surface():
    from timm_tpu.autotune import apply_to_args, autotune, format_table, to_json

    res = autotune('test_vit', MODEL_KW, global_batch=64, probe_anchor=False,
                   correction=1.0)
    ns = argparse.Namespace(fsdp=0, tp=0, batch_size=8, grad_accum_steps=8,
                            block_scan=False, grad_checkpointing=False)
    notes = apply_to_args(ns, res)
    w = res.winner
    assert ns.batch_size * ns.grad_accum_steps == 64
    assert ns.fsdp == (w.fsdp if w.fsdp > 1 else 0)
    assert ns.tp == (w.tp if w.tp > 1 else 0)
    assert ns.block_scan == w.block_scan
    assert any('batch_size' in n or 'fsdp' in n for n in notes)

    table = format_table(res)
    assert 'winner:' in table and w.flags() in table

    doc = to_json(res)
    json.dumps(doc)   # must be serializable as-is
    assert doc['schema'] == 'autotune/v1'
    assert doc['winner_flags'] == w.flags()
    assert doc['ranked'][0]['rank'] == 1
    assert doc['global_batch'] == 64


def test_module_cli_emits_json(capsys):
    from timm_tpu.autotune.__main__ import main

    rc = main(['--model', 'test_vit',
               '--model-kwargs', json.dumps(MODEL_KW),
               '--global-batch', '64', '--devices', '8', '--top', '3'])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['schema'] == 'autotune/v1'
    assert doc['n_devices'] == 8 and len(doc['ranked']) == 3
    assert doc['tier'] == 'analytic'

    rc = main(['--model', 'test_vit',
               '--model-kwargs', json.dumps(MODEL_KW),
               '--global-batch', '30', '--devices', '8'])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert 'error' in doc and doc['rejections']


@pytest.mark.slow
def test_train_autotune_subprocess(tmp_path):
    """End-to-end acceptance drill: `train.py --autotune` on the 8-device CPU
    topology enumerates, ranks, applies the winner, and completes an epoch.
    Tier-1 covers the same surface in-process (apply_to_args + CLI tests)."""
    cmd = [
        sys.executable, os.path.join(REPO, 'train.py'),
        '--synthetic-data', '--model', 'test_vit', '--img-size', '32',
        '-b', '8', '--grad-accum-steps', '2', '--synthetic-len', '32',
        '--epochs', '1', '--opt', 'sgd', '--lr', '0.05', '--sched', 'cosine',
        '--warmup-epochs', '0', '--workers', '1', '--log-interval', '50',
        '--autotune', '--output', str(tmp_path), '--experiment', 'at',
    ]
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               XLA_FLAGS='--xla_force_host_platform_device_count=8')
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]
    assert '[autotune] winner:' in r.stderr, r.stderr[-3000:]
    assert '[autotune] applied' in r.stderr, r.stderr[-3000:]
