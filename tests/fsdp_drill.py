"""Subprocess drill for the FSDP acceptance tests (tests/test_sharding.py).

Run with XLA_FLAGS=--xla_force_host_platform_device_count=N (the parent test
sets it). Modes:

  parity8 <dir>  — 8 virtual CPU devices: train the golden-fixture ViT 3
                   steps under a ('data','fsdp')=(2,4) mesh AND on a single
                   device; assert param/EMA parity ≤1e-6; durably save the
                   sharded task's checkpoint twice (raw sharded jax arrays vs
                   pre-gathered host arrays) and prove the SHA-256 sidecars
                   are byte-identical.
  load1 <dir>    — 1 device: verify the 8-device checkpoint, load it into a
                   single-device task, compare eval logits against the ones
                   the sharded task recorded, and re-save to prove the
                   manifest is stable across a save→load→save round trip.
  parity_tp <dir> — 8 virtual CPU devices: same golden-fixture train under a
                   full ('data','fsdp','model')=(2,2,2) mesh (tensor
                   parallelism + activation sharding constraints) vs a single
                   device; assert parity, assert the attention/MLP kernels
                   are ACTUALLY sharded over 'model' (NamedSharding specs),
                   and durably save the 2-D-sharded checkpoint.
  load1_tp <dir> — 1 device: verify + load the (2,2,2) checkpoint and eval —
                   the save is mesh-shape-agnostic.
  elastic8to4 <dir> — elastic resume drill: an 8-device ('data','fsdp')=(2,4)
                   train.py run is resize-faulted (`resize@3:4` → SIGTERM)
                   mid-epoch, then restarted as a FRESH 4-device process with
                   `--resume auto --elastic`; the planner holds the global
                   batch constant, the mesh rebuilds as (1,4), and final
                   params/optimizer state must match an uninterrupted run to
                   ≤1e-6. Spawns 3 train.py subprocesses with XLA_FLAGS
                   overridden per topology.
  elastic4to8 <dir> — same drill scaling UP from 4 to 8 devices.

Prints one JSON line with the results; exit 0 on success.
"""
import json
import os
import sys

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=8')

import jax

try:
    jax.config.update('jax_platforms', 'cpu')
    jax.config.update('jax_num_cpu_devices', int(os.environ.get('TIMM_TPU_DRILL_DEVICES', '8')))
except Exception:
    pass

import jax.numpy as jnp
import numpy as np
from flax import nnx

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import timm_tpu  # noqa: E402
from timm_tpu.loss import LabelSmoothingCrossEntropy  # noqa: E402
from timm_tpu.optim import create_optimizer_v2  # noqa: E402
from timm_tpu.parallel import create_mesh, shard_batch  # noqa: E402
from timm_tpu.resilience import load_with_fallback  # noqa: E402
from timm_tpu.resilience.durable import atomic_write_npz, read_manifest, verify_checkpoint  # noqa: E402
from timm_tpu.task import ClassificationTask  # noqa: E402
from timm_tpu.utils import configure_compile_cache  # noqa: E402
from timm_tpu.utils.serialization import flatten_pytree  # noqa: E402

configure_compile_cache()

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'fixtures', 'vit_tiny_img64_golden.npz')
MODEL, IMG, CLASSES = 'vit_tiny_patch16_224', 64, 1000
STEPS, BATCH = 3, 8


def golden_batch(mesh):
    with np.load(FIXTURE) as d:
        x = np.tile(d['x'], (BATCH // d['x'].shape[0], 1, 1, 1))
    t = np.random.RandomState(0).randint(0, CLASSES, BATCH)
    return shard_batch({'input': jnp.asarray(x), 'target': jnp.asarray(t)}, mesh)


def make_task(mesh):
    model = timm_tpu.create_model(MODEL, img_size=IMG)
    # block_scan composes with fsdp sharding + scanned accumulation (PR 4);
    # it also keeps the drill's compile cost O(1) in depth
    model.set_block_scan(True)
    opt = create_optimizer_v2(model, opt='sgd', lr=0.05, momentum=0.9)
    task = ClassificationTask(model, optimizer=opt, mesh=mesh,
                              train_loss_fn=LabelSmoothingCrossEntropy(0.1))
    task.setup_ema(decay=0.9)
    return task


def train(task, mesh):
    batch = golden_batch(mesh)
    for i in range(STEPS):
        metrics = task.train_step(batch, lr=0.05, step=i + 1)
    assert np.isfinite(float(metrics['loss'])), metrics
    return task


def host_params(task):
    return {k: np.asarray(v) for k, v in flatten_pytree(nnx.state(task.model, nnx.Param)).items()}


def max_diff(a, b):
    assert set(a) == set(b)
    return max(float(np.abs(a[k] - b[k]).max()) for k in a)


def parity8(workdir):
    assert len(jax.devices()) == 8, jax.devices()
    mesh_fsdp = create_mesh(fsdp=4)
    task_f = train(make_task(mesh_fsdp), mesh_fsdp)

    mesh_1 = create_mesh(devices=jax.devices()[:1])
    task_1 = train(make_task(mesh_1), mesh_1)

    p_diff = max_diff(host_params(task_f), host_params(task_1))
    e_diff = max_diff({k: np.asarray(v) for k, v in flatten_pytree(task_f.ema_params).items()},
                      {k: np.asarray(v) for k, v in flatten_pytree(task_1.ema_params).items()})

    # eval logits recorded for the cross-mesh reload drill
    batch = golden_batch(mesh_fsdp)
    logits = np.asarray(task_f.eval_step({'input': batch['input']}))
    np.save(os.path.join(workdir, 'logits_fsdp.npy'), logits)

    # durable save #1: the full checkpoint schema, with the PARAM leaves left
    # as raw fsdp-sharded jax.Arrays — exercising durable._gather_to_host
    state = task_f.get_checkpoint_state()
    raw = dict(state)
    from jax.tree_util import tree_flatten_with_path
    from timm_tpu.parallel.sharding import _kp_str
    for kp, leaf in tree_flatten_with_path(nnx.state(task_f.model, nnx.Param))[0]:
        raw['state_dict.' + _kp_str(kp)] = leaf  # sharded jax.Array, NOT gathered
    ckpt_f = os.path.join(workdir, 'ckpt_fsdp.npz')
    atomic_write_npz(ckpt_f, raw, meta={'epoch': 0, 'mesh': '2x4'})
    # durable save #2: same content pre-gathered to host — the sidecars must
    # be byte-identical or checkpoint hashes would depend on the mesh shape
    ckpt_h = os.path.join(workdir, 'ckpt_host.npz')
    atomic_write_npz(ckpt_h, {k: np.asarray(v) for k, v in raw.items()}, meta={'epoch': 0})
    mf, mh = read_manifest(ckpt_f), read_manifest(ckpt_h)
    same = {k: v['sha256'] for k, v in mf['arrays'].items()} == \
           {k: v['sha256'] for k, v in mh['arrays'].items()}

    print(json.dumps({
        'devices': len(jax.devices()),
        'mesh': [int(mesh_fsdp.shape['data']), int(mesh_fsdp.shape['fsdp'])],
        'max_param_diff': p_diff,
        'max_ema_diff': e_diff,
        'manifest_matches_unsharded': bool(same),
    }))


def load1(workdir):
    assert len(jax.devices()) == 1, jax.devices()
    ckpt = os.path.join(workdir, 'ckpt_fsdp.npz')
    ok, reason = verify_checkpoint(ckpt)
    state, meta, used = load_with_fallback(ckpt)
    mesh = create_mesh()
    task = make_task(mesh)
    task.load_checkpoint_state(state)
    with np.load(FIXTURE) as d:
        x = np.tile(d['x'], (BATCH // d['x'].shape[0], 1, 1, 1))
    logits = np.asarray(task.eval_step({'input': shard_batch(jnp.asarray(x), mesh)}))
    saved = np.load(os.path.join(workdir, 'logits_fsdp.npy'))
    eval_diff = float(np.abs(logits - saved).max())

    resaved = os.path.join(workdir, 'ckpt_resaved.npz')
    atomic_write_npz(resaved, {k: np.asarray(v) for k, v in state.items()}, meta={'epoch': 0})
    m0, m1 = read_manifest(ckpt), read_manifest(resaved)
    stable = {k: v['sha256'] for k, v in m0['arrays'].items()} == \
             {k: v['sha256'] for k, v in m1['arrays'].items()}

    print(json.dumps({
        'devices': len(jax.devices()),
        'verified': bool(ok), 'verify_reason': reason,
        'loaded': used == ckpt,
        'eval_matches_saved_logits': eval_diff,
        'resave_manifest_matches': bool(stable),
    }))


def parity_tp(workdir):
    assert len(jax.devices()) == 8, jax.devices()
    from timm_tpu.parallel import set_global_mesh
    mesh_tp = create_mesh(fsdp=2, tp=2)
    assert mesh_tp.axis_names == ('data', 'fsdp', 'model'), mesh_tp
    # the activation constraints inside the model read the GLOBAL mesh
    set_global_mesh(mesh_tp)
    task_t = train(make_task(mesh_tp), mesh_tp)

    # acceptance: qkv / proj / fc1 / fc2 kernels really carry 'model' in
    # their NamedSharding (not just a rule-table claim)
    blk = nnx.state(task_t.model, nnx.Param)['blocks'][0]
    tp_sharded = {}
    for mod, name in (('attn', 'qkv'), ('attn', 'proj'), ('mlp', 'fc1'), ('mlp', 'fc2')):
        spec = blk[mod][name]['kernel'].value.sharding.spec
        tp_sharded[f'{mod}.{name}'] = 'model' in tuple(spec) and 'fsdp' in tuple(spec)

    mesh_1 = create_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh_1)
    task_1 = train(make_task(mesh_1), mesh_1)

    p_diff = max_diff(host_params(task_t), host_params(task_1))
    e_diff = max_diff({k: np.asarray(v) for k, v in flatten_pytree(task_t.ema_params).items()},
                      {k: np.asarray(v) for k, v in flatten_pytree(task_1.ema_params).items()})

    set_global_mesh(mesh_tp)
    batch = golden_batch(mesh_tp)
    logits = np.asarray(task_t.eval_step({'input': batch['input']}))
    np.save(os.path.join(workdir, 'logits_tp.npy'), logits)

    # durable save with raw 2-D-sharded (fsdp x model) param leaves: the
    # gather-to-host path must produce the same sidecar a host save does
    state = task_t.get_checkpoint_state()
    raw = dict(state)
    from jax.tree_util import tree_flatten_with_path
    from timm_tpu.parallel.sharding import _kp_str
    for kp, leaf in tree_flatten_with_path(nnx.state(task_t.model, nnx.Param))[0]:
        raw['state_dict.' + _kp_str(kp)] = leaf  # sharded jax.Array, NOT gathered
    ckpt_t = os.path.join(workdir, 'ckpt_tp.npz')
    atomic_write_npz(ckpt_t, raw, meta={'epoch': 0, 'mesh': '2x2x2'})
    ckpt_h = os.path.join(workdir, 'ckpt_tp_host.npz')
    atomic_write_npz(ckpt_h, {k: np.asarray(v) for k, v in raw.items()}, meta={'epoch': 0})
    mf, mh = read_manifest(ckpt_t), read_manifest(ckpt_h)
    same = {k: v['sha256'] for k, v in mf['arrays'].items()} == \
           {k: v['sha256'] for k, v in mh['arrays'].items()}

    print(json.dumps({
        'devices': len(jax.devices()),
        'mesh': [int(mesh_tp.shape[a]) for a in mesh_tp.axis_names],
        'max_param_diff': p_diff,
        'max_ema_diff': e_diff,
        'tp_sharded': tp_sharded,
        'manifest_matches_unsharded': bool(same),
    }))


def load1_tp(workdir):
    assert len(jax.devices()) == 1, jax.devices()
    ckpt = os.path.join(workdir, 'ckpt_tp.npz')
    ok, reason = verify_checkpoint(ckpt)
    state, meta, used = load_with_fallback(ckpt)
    mesh = create_mesh()
    task = make_task(mesh)
    task.load_checkpoint_state(state)
    with np.load(FIXTURE) as d:
        x = np.tile(d['x'], (BATCH // d['x'].shape[0], 1, 1, 1))
    logits = np.asarray(task.eval_step({'input': shard_batch(jnp.asarray(x), mesh)}))
    saved = np.load(os.path.join(workdir, 'logits_tp.npy'))
    print(json.dumps({
        'devices': len(jax.devices()),
        'verified': bool(ok), 'verify_reason': reason,
        'loaded': used == ckpt,
        'eval_matches_saved_logits': float(np.abs(logits - saved).max()),
    }))


def serve8(workdir):
    """Sharded serving: an InferenceEngine on an 8-device ('data','fsdp')
    mesh loads the SAME mesh-shape-agnostic checkpoint as a single-device
    engine and must produce identical logits (≤1e-5) for identical requests —
    the serving tier can scale out without touching the checkpoint format."""
    assert len(jax.devices()) == 8, jax.devices()
    from timm_tpu.models import model_state_dict, save_state_dict
    from timm_tpu.serve import InferenceEngine

    serve_model, img = 'test_vit', 32
    ckpt = os.path.join(workdir, 'serve_ckpt.npz')
    save_state_dict(model_state_dict(timm_tpu.create_model(serve_model, img_size=img)), ckpt)

    rng = np.random.RandomState(0)
    imgs = rng.standard_normal((8, img, img, 3)).astype(np.float32)

    def engine_logits(mesh):
        # bucket 8 divides every mesh shard count used here (1 and 8); a long
        # admission wait means all 8 requests coalesce into ONE device step
        eng = InferenceEngine(buckets=(8,), max_wait_ms=2000.0, mesh=mesh)
        eng.add_model(serve_model, checkpoint=ckpt, img_size=img)
        eng.start()
        try:
            futs = [eng.submit(im) for im in imgs]
            rows = np.stack([f.result(timeout=300.0) for f in futs])
        finally:
            eng.shutdown(drain=True)
        return rows, eng

    logits_1, _ = engine_logits(None)  # engine default: single-device mesh
    mesh_fsdp = create_mesh(fsdp=4)
    logits_8, eng8 = engine_logits(mesh_fsdp)

    # the 8-device engine really sharded the weights over 'fsdp'
    res = eng8.pool.acquire(serve_model)
    param_sharded = any(
        'fsdp' in tuple(getattr(getattr(l, 'sharding', None), 'spec', ()) or ())
        for l in jax.tree.leaves(res.state))

    diff = float(np.abs(logits_8 - logits_1).max())
    print(json.dumps({
        'devices': len(jax.devices()),
        'mesh': [int(mesh_fsdp.shape[a]) for a in mesh_fsdp.axis_names],
        'buckets': [8],
        'param_sharded_over_fsdp': bool(param_sharded),
        'steps_by_bucket': eng8.snapshot_stats()['steps_by_bucket'],
        'logits_max_diff': diff,
    }))
    assert diff <= 1e-5, f'sharded serving logits diverged: {diff}'


def quant_save8(workdir):
    """Weight-only int8 under a real ('data','fsdp') mesh: the quantized
    pytree places via build_quant_shardings (scales riding their kernels'
    specs), the int8 checkpoint saves mesh-shape-agnostically, and a
    quantized engine on the SAME mesh serves from it — logits recorded for
    the 1-device reload drill."""
    assert len(jax.devices()) == 8, jax.devices()
    from timm_tpu.parallel import build_quant_shardings, set_global_mesh
    from timm_tpu.quantize import quantize_tree, quantized_paths, save_quantized, tree_bytes
    from timm_tpu.serve import InferenceEngine

    serve_model, img = 'test_vit', 32
    mesh = create_mesh(fsdp=4)
    set_global_mesh(mesh)
    model = timm_tpu.create_model(serve_model, img_size=img)
    model.eval()
    _, state = nnx.split(model)
    qstate = quantize_tree(state)
    placed = jax.device_put(qstate, build_quant_shardings(qstate, mesh))
    qvalues_sharded = any(
        'fsdp' in tuple(getattr(getattr(l, 'sharding', None), 'spec', ()) or ())
        for l in jax.tree.leaves(placed['qvalues']))
    ckpt = os.path.join(workdir, 'quant_ckpt.npz')
    save_quantized(placed, ckpt)

    rng = np.random.RandomState(0)
    imgs = rng.standard_normal((8, img, img, 3)).astype(np.float32)
    eng = InferenceEngine(buckets=(8,), max_wait_ms=2000.0, mesh=mesh)
    eng.add_model(serve_model, img_size=img, quantize='int8', quantized_checkpoint=ckpt)
    eng.start()
    try:
        futs = [eng.submit(im) for im in imgs]
        rows = np.stack([f.result(timeout=300.0) for f in futs])
    finally:
        eng.shutdown(drain=True)
    np.save(os.path.join(workdir, 'logits_quant8.npy'), rows)
    res = eng.pool.acquire(serve_model)
    print(json.dumps({
        'devices': len(jax.devices()),
        'mesh': [int(mesh.shape[a]) for a in mesh.axis_names],
        'num_quantized': len(quantized_paths(placed)),
        'qvalues_sharded_over_fsdp': bool(qvalues_sharded),
        'quantize': res.quantize,
        'param_bytes': int(res.param_bytes),
        'dense_bytes': int(tree_bytes(state)),
    }))


def quant_load1(workdir):
    """1 device: the int8 checkpoint saved on 8 devices loads into a
    single-device quantized engine and serves identical logits (the dequant
    math is deterministic; only matmul reduction order can differ)."""
    assert len(jax.devices()) == 1, jax.devices()
    from timm_tpu.serve import InferenceEngine

    serve_model, img = 'test_vit', 32
    ckpt = os.path.join(workdir, 'quant_ckpt.npz')
    rng = np.random.RandomState(0)
    imgs = rng.standard_normal((8, img, img, 3)).astype(np.float32)
    eng = InferenceEngine(buckets=(8,), max_wait_ms=2000.0)
    eng.add_model(serve_model, img_size=img, quantize='int8', quantized_checkpoint=ckpt)
    eng.start()
    try:
        futs = [eng.submit(im) for im in imgs]
        rows = np.stack([f.result(timeout=300.0) for f in futs])
    finally:
        eng.shutdown(drain=True)
    saved = np.load(os.path.join(workdir, 'logits_quant8.npy'))
    diff = float(np.abs(rows - saved).max())
    res = eng.pool.acquire(serve_model)
    print(json.dumps({
        'devices': len(jax.devices()),
        'quantize': res.quantize,
        'param_bytes': int(res.param_bytes),
        'logits_max_diff': diff,
    }))
    assert diff <= 1e-5, f'quantized cross-mesh serving diverged: {diff}'


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _elastic_train(workdir, experiment, devices, *extra):
    """One train.py child pinned to a virtual CPU topology of `devices`."""
    import subprocess
    cmd = [
        sys.executable, os.path.join(REPO, 'train.py'),
        '--synthetic-data', '--model', 'test_vit', '--img-size', '32', '-b', '8',
        '--synthetic-len', '64', '--epochs', '1', '--opt', 'sgd', '--lr', '0.05',
        '--sched', 'cosine', '--warmup-epochs', '0', '--workers', '1',
        '--log-interval', '50', '--fsdp', '4',
        '--output', str(workdir), '--experiment', experiment, *extra,
    ]
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               XLA_FLAGS=f'--xla_force_host_platform_device_count={devices}')
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=420)


def _host_ckpt(path):
    with np.load(path, allow_pickle=False) as d:
        return {k: d[k] for k in d.files if k.startswith(('state_dict.', 'optimizer.'))}


def _elastic(workdir, n_from, n_to):
    """Resize drill: uninterrupted run at n_from devices vs a run resize-
    faulted mid-epoch and resumed as a fresh n_to-device process. `--fsdp 4`
    on every leg (4 divides both topologies: (2,4) on 8 devices, (1,4) on 4)
    and batch geometry 8x1 is held constant so the synthetic loader stream —
    and hence the final state — is reproducible across the resize."""
    r = _elastic_train(workdir, 'base', n_from)
    assert r.returncode == 0, r.stderr[-2000:]
    r = _elastic_train(workdir, 'pre', n_from, '--fault-inject', f'resize@3:{n_to}')
    assert r.returncode == 0, r.stderr[-2000:]
    pre_dir = os.path.join(workdir, 'pre')
    recs = [n for n in os.listdir(pre_dir) if n.startswith('recovery-') and n.endswith('.npz')]
    assert recs, (sorted(os.listdir(pre_dir)), r.stderr[-2000:])
    # the recovery checkpoint advertises the dead run's batch geometry
    with np.load(os.path.join(pre_dir, recs[0])) as d:
        saved_global = int(d['_resume.global_batch'])
        saved_devices = int(d['_resume.device_count'])
    assert saved_global == 8 and saved_devices == n_from, (saved_global, saved_devices)

    r = _elastic_train(workdir, 'pre', n_to, '--resume', 'auto', '--elastic')
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'Resumed mid-epoch' in r.stderr, r.stderr[-2000:]
    assert '[elastic] live topology' in r.stderr, r.stderr[-2000:]

    base = _host_ckpt(os.path.join(workdir, 'base', 'last.npz'))
    resumed = _host_ckpt(os.path.join(pre_dir, 'last.npz'))
    assert set(base) == set(resumed)
    diff = max(float(np.abs(base[k].astype(np.float64) - resumed[k].astype(np.float64)).max())
               for k in base)
    print(json.dumps({
        'from_devices': n_from, 'to_devices': n_to,
        'saved_global_batch': saved_global,
        'max_param_diff': diff,
        'recovery_pruned': not [n for n in os.listdir(pre_dir) if n.startswith('recovery-')],
    }))
    assert diff <= 1e-6, f'elastic resume diverged from uninterrupted run: {diff}'


def elastic8to4(workdir):
    _elastic(workdir, 8, 4)


def elastic4to8(workdir):
    _elastic(workdir, 4, 8)


if __name__ == '__main__':
    mode, workdir = sys.argv[1], sys.argv[2]
    {'parity8': parity8, 'load1': load1, 'parity_tp': parity_tp, 'load1_tp': load1_tp,
     'serve8': serve8, 'quant_save8': quant_save8, 'quant_load1': quant_load1,
     'elastic8to4': elastic8to4, 'elastic4to8': elastic4to8}[mode](workdir)
