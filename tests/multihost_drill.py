"""CLI for the multi-process host-loss drill (tests/test_multihost.py runs the
same drill in tier-1; this wrapper exists for manual runs and bench replay).

Launches an N-subprocess JAX cluster on CPU (one device per process, real
`jax.distributed.initialize` over a localhost coordinator), trains the tiny
fixture ViT on host-sharded synthetic data, SIGKILLs one host mid-epoch, and
asserts the full recovery contract:

  - the survivors reach stop consensus over the coordination-service KV store
    and exit 0 with their recovery state saved;
  - the save that lost the victim leaves only uncommitted shard litter (no
    global manifest) — the previous checkpoint stays the newest valid one;
  - a fresh cluster resumes `--resume auto --elastic` from the host-sharded
    checkpoint and lands within 1e-6 of an uninterrupted baseline.

Usage:
  python tests/multihost_drill.py [workdir]
      [--processes N] [--kill-update K] [--victim P]
      [--no-compare] [--no-resume] [--timeout SECONDS]

Prints one JSON line with {ok, checks, details}; exit 0 on success.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('workdir', nargs='?', default=None,
                    help='scratch dir for logs + checkpoints (default: a tempdir)')
    ap.add_argument('--processes', type=int, default=2)
    ap.add_argument('--kill-update', type=int, default=4,
                    help='global update index at which the victim SIGKILLs itself')
    ap.add_argument('--victim', type=int, default=None,
                    help='process index to kill (default: the last, keeping the '
                         'coordinator on process 0 alive)')
    ap.add_argument('--no-compare', action='store_true',
                    help='skip the uninterrupted-baseline parity leg')
    ap.add_argument('--no-resume', action='store_true',
                    help='stop after the kill + crash-safety checks')
    ap.add_argument('--timeout', type=float, default=420.0)
    args = ap.parse_args()

    from timm_tpu.resilience import run_kill_drill

    workdir = args.workdir or tempfile.mkdtemp(prefix='timm_tpu_multihost_')
    result = run_kill_drill(
        workdir,
        processes=args.processes,
        kill_update=args.kill_update,
        victim=args.victim,
        compare=not args.no_compare,
        resume=not args.no_resume,
        timeout=args.timeout,
        log=lambda m: print(f'[multihost_drill] {m}', file=sys.stderr, flush=True),
    )
    print(json.dumps(result, sort_keys=True, default=str))
    return 0 if result['ok'] else 1


if __name__ == '__main__':
    sys.exit(main())
