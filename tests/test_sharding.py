"""Sharded-execution tests.

1. BatchNorm semantics under a sharded batch (SURVEY §7 hard part (c)).
2. FSDP partition rules: m/v optimizer slots mirror their param's spec
   (what makes donation aliasing legal). The disjoint/exhaustive rule-table
   lint moved to timm_tpu/analysis (rule `partition-rules`).
3. Donated jitted steps: re-using a donated buffer raises. The source and
   compiled-HLO donation lints moved to timm_tpu/analysis (rules
   `donation-declared`, `donation-alias`).
4. Scanned grad accumulation: grad parity ≤1e-6 vs the legacy unroll, and
   jaxpr trace size is O(1) in grad_accum_steps.
5. 8-CPU-device subprocess drills: ('data','fsdp') train parity vs a single
   device ≤1e-6 after 3 updates, and checkpoint save-on-8-device →
   load-on-1-device with a byte-stable SHA-256 sidecar.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx
from jax.sharding import PartitionSpec as P

import timm_tpu
from timm_tpu.layers import BatchNormAct2d
from timm_tpu.loss import LabelSmoothingCrossEntropy
from timm_tpu.optim import create_optimizer_v2
from timm_tpu.parallel import (
    build_opt_shardings, build_param_shardings, create_mesh,
    param_bytes_per_device, path_specs, shard_batch, spec_for_param,
)
from timm_tpu.task import ClassificationTask

pytestmark = pytest.mark.sharding

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), '..'))


# ---- BatchNorm under a sharded batch (pre-FSDP coverage, kept) --------------

def test_bn_sharded_stats_match_global(mesh8):
    """Train-mode BN over an 8-way sharded batch: running stats and outputs
    must match the single-device global-batch computation (XLA inserts the
    cross-device reductions for the batch mean/var)."""
    rng = np.random.RandomState(0)
    x_np = rng.rand(16, 8, 8, 6).astype(np.float32) * 3.0 + 1.0

    def run(shard: bool):
        bn = BatchNormAct2d(6, rngs=nnx.Rngs(0))
        bn.train()
        graphdef, state = nnx.split(bn)

        @jax.jit
        def step(state, x):
            m = nnx.merge(graphdef, state)
            y = m(x)
            _, new_state = nnx.split(m)
            return y, new_state

        x = jnp.asarray(x_np)
        if shard:
            x = shard_batch(x, mesh8)
        y, new_state = step(state, x)
        return np.asarray(y), jax.tree.map(np.asarray, nnx.to_pure_dict(new_state))

    y_global, state_global = run(shard=False)
    y_sharded, state_sharded = run(shard=True)

    np.testing.assert_allclose(y_sharded, y_global, rtol=1e-5, atol=1e-5)
    flat_g = jax.tree_util.tree_leaves_with_path(state_global)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(state_sharded))
    checked = 0
    for path, leaf_g in flat_g:
        leaf_s = flat_s[path]
        np.testing.assert_allclose(leaf_s, leaf_g, rtol=1e-5, atol=1e-6,
                                   err_msg=f'BN state diverged at {path}')
        checked += 1
    assert checked >= 2  # at least running mean + var compared


def test_bn_model_sharded_train_step_matches_global(mesh8):
    """Full jitted train step of a BN trunk (test_resnet) through the REAL
    task path: loss, grad norm, and updated BN running stats identical
    whether the batch is 8-way sharded or unsharded."""
    rng = np.random.RandomState(0)
    x_np = rng.rand(16, 64, 64, 3).astype(np.float32)
    t_np = rng.randint(0, 10, 16)

    def run(shard: bool):
        model = timm_tpu.create_model('test_resnet', num_classes=10)
        task = ClassificationTask(
            model, optimizer=create_optimizer_v2(model, opt='sgd', lr=0.1), mesh=mesh8)
        batch = {'input': jnp.asarray(x_np), 'target': jnp.asarray(t_np)}
        if shard:
            batch = shard_batch(batch, mesh8)
        metrics = task.train_step(batch, lr=0.1, step=1)
        stats = jax.tree.map(np.asarray, nnx.to_pure_dict(nnx.state(model, nnx.BatchStat)))
        return float(metrics['loss']), float(metrics.get('grad_norm', 0.0)), stats

    loss_g, gnorm_g, stats_g = run(shard=False)
    loss_s, gnorm_s, stats_s = run(shard=True)
    assert abs(loss_s - loss_g) < 1e-4, f'sharded loss {loss_s} != global {loss_g}'
    assert abs(gnorm_s - gnorm_g) / max(gnorm_g, 1e-8) < 1e-3
    flat_g = jax.tree_util.tree_leaves_with_path(stats_g)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(stats_s))
    assert flat_g, 'model must expose BatchStat state'
    for path, leaf_g in flat_g:
        np.testing.assert_allclose(
            flat_s[path], leaf_g, rtol=1e-4, atol=1e-5,
            err_msg=f'sharded BN running stats diverged at {path}')


# ---- FSDP partition rules ----------------------------------------------------

def _fsdp_mesh(fsdp=4):
    return create_mesh(fsdp=fsdp)


def _param_paths(model_name, **kwargs):
    model = timm_tpu.create_model(model_name, **kwargs)
    from timm_tpu.utils.serialization import flatten_pytree
    return flatten_pytree(nnx.state(model, nnx.Param))


def test_rule_specs_shard_large_kernels_replicate_small(mesh8):
    mesh = _fsdp_mesh(4)
    specs = path_specs(_param_paths('test_vit', num_classes=10, img_size=32), mesh)
    # large matmul weights shard on 'fsdp'
    for path in ('blocks.0.attn.qkv.kernel', 'blocks.0.mlp.fc1.kernel', 'blocks.1.mlp.fc2.kernel'):
        assert any(ax == 'fsdp' for ax in specs[path]), f'{path}: {specs[path]}'
    # norm scales / biases / tokens stay replicated
    for path in ('blocks.0.norm1.scale', 'blocks.0.attn.qkv.bias', 'cls_token', 'pos_embed', 'norm.bias'):
        assert specs[path] == P(), f'{path}: {specs[path]}'
    # a 1-axis data mesh replicates everything (exact pre-FSDP behaviour)
    flat_specs = path_specs(_param_paths('test_vit', num_classes=10, img_size=32), mesh8)
    assert all(s == P() for s in flat_specs.values())


def test_opt_state_specs_mirror_param_specs():
    """AdamW m/v (and any other param-shaped slot) must inherit the param's
    spec leaf-for-leaf — donation aliasing requires input and output
    placement to agree, and m/v live exactly where their param lives."""
    mesh = _fsdp_mesh(4)
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
    params = nnx.state(model, nnx.Param)
    pspecs = path_specs(params, mesh)
    opt_sh, abstract = build_opt_shardings(opt, params, mesh)

    from jax.tree_util import tree_flatten_with_path
    from timm_tpu.parallel.sharding import _kp_str
    flat, _ = tree_flatten_with_path(opt_sh)
    mirrored = 0
    for kp, sharding in flat:
        path = _kp_str(kp)
        for ppath, pspec in pspecs.items():
            if path == ppath or path.endswith('.' + ppath):
                assert sharding.spec == pspec, f'{path}: {sharding.spec} != param {pspec}'
                mirrored += 1
                break
        else:
            assert sharding.spec == P(), f'non-param slot {path} must be replicated'
    # at least mu+nu for every param mirrored
    assert mirrored >= 2 * len(pspecs)


def test_param_bytes_per_device_accounting():
    mesh = _fsdp_mesh(4)
    params = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    tree = nnx.state(params, nnx.Param)
    rep, shard = param_bytes_per_device(tree, mesh)
    assert shard < rep, (rep, shard)
    # every sharded kernel contributes bytes/4; the floor is all-replicated
    assert shard > rep // 4


# ---- 2-axis mesh + batch divisibility ---------------------------------------

def test_create_mesh_fsdp_shapes(mesh8):
    mesh = create_mesh(fsdp=4)
    assert mesh.axis_names == ('data', 'fsdp')
    assert dict(mesh.shape) == {'data': 2, 'fsdp': 4}
    assert create_mesh().axis_names == ('data',)  # fsdp=1 keeps the 1-axis mesh
    with pytest.raises(ValueError, match='fsdp=3'):
        create_mesh(fsdp=3)


def test_shard_batch_2axis_and_divisibility_error(mesh8):
    mesh = _fsdp_mesh(4)
    batch = shard_batch({'input': jnp.ones((16, 4, 4, 3)), 'target': jnp.zeros((16,), jnp.int32)}, mesh)
    # batch shards over the data x fsdp product
    assert len(batch['input'].sharding.device_set) == 8
    # loud error instead of an opaque XLA reshape failure
    with pytest.raises(ValueError, match='not divisible by the mesh batch-shard count 8'):
        shard_batch(jnp.ones((12, 4)), mesh)
    with pytest.raises(ValueError, match='divisible'):
        shard_batch({'input': jnp.ones((6, 2))}, mesh8)


# ---- donated jitted steps ----------------------------------------------------

def _make_task(mesh, opt='sgd', **kwargs):
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    optimizer = create_optimizer_v2(model, opt=opt, lr=0.1, momentum=0.9)
    return ClassificationTask(model, optimizer=optimizer, mesh=mesh,
                              train_loss_fn=LabelSmoothingCrossEntropy(0.1), **kwargs)


def _batch(mesh, n=16, seed=0):
    rng = np.random.RandomState(seed)
    return shard_batch({'input': jnp.asarray(rng.rand(n, 32, 32, 3), jnp.float32),
                        'target': jnp.asarray(rng.randint(0, 10, n))}, mesh)


def test_train_step_donates_param_and_opt_buffers(mesh8):
    """The jitted step donates params/opt state/EMA: after one step the OLD
    buffers are deleted, and touching one raises instead of silently reading
    stale memory."""
    task = _make_task(mesh8)
    task.setup_ema(decay=0.5)
    old_param = jax.tree.leaves(nnx.state(task.model, nnx.Param))[0]
    old_opt = next(l for l in jax.tree.leaves(task.opt_state)
                   if hasattr(l, 'shape') and l.size > 1)
    old_ema = jax.tree.leaves(task.ema_params)[0]
    task.train_step(_batch(mesh8), lr=0.1, step=1)
    for name, buf in [('param', old_param), ('opt', old_opt), ('ema', old_ema)]:
        with pytest.raises(RuntimeError):
            np.asarray(buf)
            pytest.fail(f'donated {name} buffer was still readable')


def test_eval_after_donated_train_step(mesh8):
    """Donation must not leave the task holding deleted arrays: eval (incl.
    EMA eval) works right after a donated train step."""
    task = _make_task(mesh8)
    task.setup_ema(decay=0.5)
    batch = _batch(mesh8)
    for i in range(2):
        task.train_step(batch, lr=0.1, step=i + 1)
    out = task.eval_step({'input': batch['input']})
    out_ema = task.eval_step({'input': batch['input']}, use_ema=True)
    assert np.isfinite(np.asarray(out)).all() and np.isfinite(np.asarray(out_ema)).all()


# The in-test donation lints that lived here (source regex over timm_tpu/task/
# and donation_evidence on compiled artifacts) are now analysis rules
# `donation-declared` (Tier A) and `donation-alias` (Tier C) — see
# timm_tpu/analysis and tests/test_analysis.py.


# ---- scanned grad accumulation ----------------------------------------------

def test_scanned_accum_matches_unrolled(mesh8):
    """Grad parity: one SGD step at lr=0.1 makes the param delta a scaled
    gradient, so param agreement ≤1e-6 is gradient agreement ≤1e-5."""
    batch = _batch(mesh8)
    results = {}
    for scan in (True, False):
        task = _make_task(mesh8, grad_accum_steps=4, grad_accum_scan=scan)
        m = task.train_step(batch, lr=0.1, step=1)
        results[scan] = (float(m['loss']),
                         jax.tree.map(np.asarray, nnx.state(task.model, nnx.Param)))
    assert results[True][0] == pytest.approx(results[False][0], abs=1e-6)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(a - b).max()), results[True][1], results[False][1]))
    assert max(diffs) <= 1e-6, f'scan vs unroll param diff {max(diffs)}'


def test_scanned_accum_matches_single_large_batch(mesh8):
    t1 = _make_task(mesh8)
    t2 = _make_task(mesh8, grad_accum_steps=2)
    batch = _batch(mesh8, n=16)
    l1 = float(t1.train_step(batch, lr=1e-3)['loss'])
    l2 = float(t2.train_step(batch, lr=1e-3)['loss'])
    assert l1 == pytest.approx(l2, abs=1e-3)


def test_accum_trace_size_o1_in_steps(mesh8):
    """Acceptance: grad_accum_steps=8 no longer scales trace size ~8x vs
    grad_accum_steps=2 (the old Python unroll did)."""
    from timm_tpu.utils.compile_cache import count_jaxpr_eqns
    batch = _batch(mesh8)

    def eqns(accum, scan):
        task = _make_task(mesh8, grad_accum_steps=accum, grad_accum_scan=scan)
        return count_jaxpr_eqns(task.trace_train_step(batch, lr=0.1))

    from timm_tpu.perfbudget import check_ratio_max, check_ratio_min

    scan2, scan8 = eqns(2, True), eqns(8, True)
    check_ratio_max('scanned trace cost vs accum steps (eqns a8/a2)', scan8, scan2, 2.0)
    unroll8 = eqns(8, False)
    check_ratio_min('unrolled jaxpr vs scanned (eqns unroll8/scan8)', unroll8, scan8, 2.0)


# ---- fsdp end-to-end in-process ---------------------------------------------

def test_fsdp_task_train_eval_checkpoint_roundtrip(mesh8):
    """('data','fsdp') task: params/opt actually sharded, train+eval run, and
    a checkpoint saved from the fsdp task loads into a plain data-mesh task
    with identical eval outputs (round-trip across mesh shapes, in-process)."""
    mesh = _fsdp_mesh(4)
    task = _make_task(mesh, opt='adamw')
    qkv = nnx.state(task.model, nnx.Param)['blocks'][0]['attn']['qkv']['kernel'].value
    assert any(ax == 'fsdp' for ax in qkv.sharding.spec)
    sharded_opt = [l for l in jax.tree.leaves(task.opt_state)
                   if hasattr(l, 'sharding') and any(ax is not None for ax in l.sharding.spec)]
    assert sharded_opt, 'optimizer m/v must be fsdp-sharded'
    batch = _batch(mesh)
    for i in range(2):
        m = task.train_step(batch, lr=1e-3, step=i + 1)
    assert np.isfinite(float(m['loss']))
    state = task.get_checkpoint_state()

    task2 = _make_task(mesh8, opt='adamw')
    task2.load_checkpoint_state(state)
    x = _batch(mesh8)['input']
    a = np.asarray(task.eval_step({'input': shard_batch(np.asarray(x), mesh)}))
    b = np.asarray(task2.eval_step({'input': x}))
    # params round-trip bit-exactly; the tolerance is fp32 reduction-order
    # noise from evaluating under different mesh shapes
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_create_sharded_model_abstract_init(caplog):
    """`nnx.eval_shape`-based init creates params directly on-mesh (no eager
    replicated copy, no fallback warning) with rule-conformant placement."""
    import logging
    from timm_tpu.parallel import create_sharded_model
    mesh = _fsdp_mesh(4)
    with caplog.at_level(logging.WARNING, logger='timm_tpu.parallel.sharding'):
        model = create_sharded_model(
            lambda: timm_tpu.create_model('test_vit', num_classes=10, img_size=32), mesh)
    assert not any('abstract init failed' in r.message for r in caplog.records), \
        'abstract init silently fell back to eager construction'
    qkv = nnx.state(model, nnx.Param)['blocks'][0]['attn']['qkv']['kernel'].value
    assert any(ax == 'fsdp' for ax in qkv.sharding.spec)
    x = shard_batch(jnp.zeros((8, 32, 32, 3)), mesh)
    model.eval()
    out = model(x)
    assert out.shape == (8, 10) and np.isfinite(np.asarray(out)).all()


# ---- subprocess drills: forced 8-device mesh parity + 1-device reload -------

_DRILL = os.path.join(os.path.dirname(__file__), 'fsdp_drill.py')


def _run_drill(mode, workdir, devices):
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        XLA_FLAGS=f'--xla_force_host_platform_device_count={devices}',
        TIMM_TPU_DRILL_DEVICES=str(devices),
        TF_CPP_MIN_LOG_LEVEL='3',
    )
    r = subprocess.run([sys.executable, _DRILL, mode, str(workdir)],
                       capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=300)
    assert r.returncode == 0, f'{mode} drill failed rc={r.returncode}:\n{r.stderr[-3000:]}'
    out = [l for l in r.stdout.strip().splitlines() if l.startswith('{')]
    assert out, f'no JSON result from {mode} drill:\n{r.stdout[-2000:]}'
    return json.loads(out[-1])


def test_fsdp_8device_parity_and_cross_mesh_checkpoint(tmp_path):
    """Acceptance drill: under a forced 8-CPU-device ('data','fsdp') mesh the
    golden-fixture train step matches the single-device step ≤1e-6 (params
    after 3 updates), the durable checkpoint written from the sharded task
    carries the same SHA-256 sidecar a single-device save produces, and a
    fresh 1-device process verifies + loads it (save-on-8 → load-on-1)."""
    res = _run_drill('parity8', tmp_path, devices=8)
    assert res['devices'] == 8 and res['mesh'] == [2, 4]
    assert res['max_param_diff'] <= 1e-6, res
    assert res['max_ema_diff'] <= 1e-6, res
    assert os.path.exists(tmp_path / 'ckpt_fsdp.npz')
    # sidecar is byte-stable across mesh shapes: sharded-save hashes equal
    # the unsharded-save hashes computed in the same child
    assert res['manifest_matches_unsharded'], res

    res1 = _run_drill('load1', tmp_path, devices=1)
    assert res1['devices'] == 1
    assert res1['verified'] and res1['loaded'], res1
    assert res1['resave_manifest_matches'], res1
    # logits re-computed on a different mesh shape: fp32 reduction-order noise
    # only (params themselves round-trip bit-exactly, proven by the manifest)
    assert res1['eval_matches_saved_logits'] <= 1e-5, res1


# ---- 3-axis mesh: tensor parallelism -----------------------------------------

def _tp_mesh(fsdp=2, tp=2):
    return create_mesh(fsdp=fsdp, tp=tp)


@pytest.fixture
def restore_global_mesh():
    """The activation constraints read the GLOBAL mesh; tests that set it must
    put back whatever was there (it leaks across tests otherwise)."""
    from timm_tpu.parallel import peek_global_mesh, set_global_mesh
    from timm_tpu.parallel import mesh as mesh_mod
    saved = peek_global_mesh()
    yield
    mesh_mod._GLOBAL_MESH = saved


def test_create_mesh_tp_shapes_and_error_names_all_axes(mesh8):
    mesh = _tp_mesh()
    assert mesh.axis_names == ('data', 'fsdp', 'model')
    assert dict(mesh.shape) == {'data': 2, 'fsdp': 2, 'model': 2}
    # tp without fsdp still gets its axis; tp=1 keeps today's meshes exactly
    assert create_mesh(tp=2).axis_names == ('data', 'model')
    assert create_mesh(fsdp=2, tp=1).axis_names == ('data', 'fsdp')
    assert create_mesh(tp=1).axis_names == ('data',)
    with pytest.raises(ValueError, match=r'fsdp=2 x tp=3'):
        create_mesh(fsdp=2, tp=3)
    # the builder error names every requested axis and the device count
    with pytest.raises(ValueError, match=r'8 devices'):
        create_mesh(fsdp=2, tp=3)


def test_create_mesh_tp_env(monkeypatch, mesh8):
    monkeypatch.setenv('TIMM_TPU_TP', '2')
    monkeypatch.setenv('TIMM_TPU_FSDP', '2')
    mesh = create_mesh()
    assert mesh.axis_names == ('data', 'fsdp', 'model')
    assert dict(mesh.shape) == {'data': 2, 'fsdp': 2, 'model': 2}


def test_shard_batch_3axis_error_names_axes_and_nearest_batch(mesh8):
    mesh = _tp_mesh()
    batch = shard_batch({'input': jnp.ones((16, 4, 4, 3))}, mesh)
    assert len(batch['input'].sharding.device_set) == 8
    with pytest.raises(ValueError) as ei:
        shard_batch(jnp.ones((12, 4)), mesh)
    msg = str(ei.value)
    # names ALL axes with sizes, keeps the historical phrase, suggests the fix
    assert 'not divisible by the mesh batch-shard count 8' in msg
    assert 'data=2' in msg and 'fsdp=2' in msg and 'model=2' in msg
    assert 'Nearest legal global batch: 8 or 16' in msg


# The tp disjoint/exhaustive + every-model-rule-exercised lint is now the
# analysis rule `partition-rules` (timm_tpu/analysis/source_rules.py).


def test_tp1_specs_bit_identical_to_fsdp_only():
    """tp=1 must reproduce the 2-axis placement exactly — same spec for every
    param, so programs, donation aliasing, and checkpoints are unchanged."""
    paths = _param_paths('test_vit', num_classes=10, img_size=32)
    a = path_specs(paths, _fsdp_mesh(4))
    b = path_specs(paths, create_mesh(fsdp=4, tp=1))
    assert a == b


def test_tp_nondivisible_dims_warn_not_silent(caplog):
    """A head/hidden dim not divisible by the 'model' axis replicates with a
    logged WARNING (once per path), never silently."""
    import logging
    from timm_tpu.parallel.sharding import _WARNED_PATHS
    mesh = _tp_mesh()
    _WARNED_PATHS.discard('blocks.9.attn.qkv.kernel')
    with caplog.at_level(logging.WARNING, logger='timm_tpu.parallel.sharding'):
        spec = spec_for_param('blocks.9.attn.qkv.kernel', (192, 575), mesh)
    assert spec == P()
    warned = [r for r in caplog.records if 'not divisible' in r.message
              and 'blocks.9.attn.qkv.kernel' in r.message]
    assert warned, 'non-divisible tp dim must log a warning'
    # warn-once: a second resolve stays quiet
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger='timm_tpu.parallel.sharding'):
        spec_for_param('blocks.9.attn.qkv.kernel', (192, 575), mesh)
    assert not [r for r in caplog.records if 'blocks.9.attn.qkv.kernel' in r.message]


def test_tp_opt_state_mirrors_2d_param_specs():
    """m/v of a (fsdp x model)-sharded kernel inherit the full 2-D spec —
    donation aliasing under tensor parallelism needs leaf-for-leaf agreement
    exactly as it did for 1-D fsdp."""
    mesh = _tp_mesh()
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
    params = nnx.state(model, nnx.Param)
    pspecs = path_specs(params, mesh)
    assert any(len([ax for ax in s if ax is not None]) == 2 for s in pspecs.values())
    opt_sh, _ = build_opt_shardings(opt, params, mesh)
    from jax.tree_util import tree_flatten_with_path
    from timm_tpu.parallel.sharding import _kp_str
    mirrored_2d = 0
    for kp, sharding in tree_flatten_with_path(opt_sh)[0]:
        path = _kp_str(kp)
        for ppath, pspec in pspecs.items():
            if path == ppath or path.endswith('.' + ppath):
                assert sharding.spec == pspec, f'{path}: {sharding.spec} != {pspec}'
                if len([ax for ax in pspec if ax is not None]) == 2:
                    mirrored_2d += 1
                break
    assert mirrored_2d > 0


def test_param_and_activation_bytes_tp_accounting():
    """2-D specs divide param bytes by fsdp*tp, and the activation estimate
    shows the constraints' ~1/tp scaling (equal numbers at tp=1)."""
    from timm_tpu.parallel import activation_bytes_per_device
    tree = nnx.state(timm_tpu.create_model('test_vit', num_classes=10, img_size=32), nnx.Param)
    rep2, shard2 = param_bytes_per_device(tree, _fsdp_mesh(4))
    rep3, shard3 = param_bytes_per_device(tree, _tp_mesh())
    assert rep2 == rep3
    # both meshes have 4-way sharding of the big kernels (4 fsdp vs 2x2), so
    # the per-device bytes land in the same ballpark and well under replicated
    assert shard3 < rep3 and abs(shard3 - shard2) < rep3 // 4

    u, c = activation_bytes_per_device(
        _tp_mesh(), batch_size=64, seq_len=197, width=192, depth=12)
    assert u == 2 * c  # tp=2, all dims divisible -> constraints halve activations
    u1, c1 = activation_bytes_per_device(
        _fsdp_mesh(4), batch_size=64, seq_len=197, width=192, depth=12)
    assert u1 == c1  # no 'model' axis -> estimate unchanged


def test_shard_activation_noop_paths(restore_global_mesh, mesh8):
    """shard_activation must be identity when it can't apply: no 'model'
    axis, wrong rank, or a non-divisible batch dim."""
    from timm_tpu.parallel import set_global_mesh, shard_activation
    x = jnp.ones((8, 17, 192))
    set_global_mesh(mesh8)
    assert shard_activation(x, 'residual') is x  # no 'model' axis
    mesh = _tp_mesh()
    set_global_mesh(mesh)
    x2 = jnp.ones((8, 17))
    assert shard_activation(x2, 'residual') is x2  # rank guard
    y = shard_activation(x, 'residual')
    assert y.sharding.spec == P(('data', 'fsdp'), None, 'model')
    # heads: 3 heads not divisible by tp=2 -> heads dim left unsharded
    h = shard_activation(jnp.ones((8, 3, 17, 64)), 'heads')
    assert all(ax != 'model' for ax in h.sharding.spec)
    with pytest.raises(ValueError):
        shard_activation(x, 'bogus')


def _find_scan_constraint(jaxpr):
    """True iff some scan body in `jaxpr` contains a sharding_constraint eqn."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == 'scan':
            body = eqn.params['jaxpr'].jaxpr
            if any(e.primitive.name == 'sharding_constraint' for e in body.eqns) or \
                    _find_scan_constraint(body):
                return True
        else:
            for v in eqn.params.values():
                inner = getattr(getattr(v, 'jaxpr', v), 'jaxpr', None) or getattr(v, 'jaxpr', None)
                if inner is not None and hasattr(inner, 'eqns') and _find_scan_constraint(inner):
                    return True
    return False


def test_tp_constraint_in_scan_body_and_no_involuntary_remat(restore_global_mesh):
    """Acceptance (compiled evidence, regression-tested): for vit_tiny at
    fsdp x tp = (2,2) with block_scan on,
      1. the scanned block body's jaxpr contains the residual-stream
         sharding_constraint (the carry is explicitly pinned), and
      2. the compiled HLO's while-loop runs on the PER-DEVICE residual
         f32[2,17,96] (batch 8/(data*fsdp)=2, width 192/tp=96) and the full
         replicated residual f32[8,17,192] never materializes — which is the
         involuntary-remat pattern PERF.md documented."""
    from timm_tpu.parallel import set_global_mesh
    mesh = _tp_mesh()
    set_global_mesh(mesh)
    model = timm_tpu.create_model('vit_tiny_patch16_224', img_size=64)
    model.set_block_scan(True)
    model.eval()
    graphdef, state = nnx.split(model)
    state = jax.device_put(state, build_param_shardings(state, mesh))

    def fwd(state, x):
        return nnx.merge(graphdef, state)(x)

    x = shard_batch(jnp.zeros((8, 64, 64, 3), jnp.float32), mesh)
    closed = jax.make_jaxpr(fwd)(state, x)
    assert _find_scan_constraint(closed.jaxpr), \
        'residual sharding_constraint missing from the scanned block body'

    compiled = jax.jit(fwd).lower(state, x).compile()
    hlo = compiled.as_text()
    assert 'f32[2,17,96]' in hlo, \
        'per-device (batch/4, tokens, width/2) residual not found in compiled HLO'
    assert 'f32[8,17,192]' not in hlo, \
        'full replicated residual materialized: involuntary-remat pattern is back'
    out = compiled(state, x)
    assert out.shape == (8, 1000) and bool(jnp.isfinite(out).all())


def test_tp_task_train_eval_in_process(restore_global_mesh):
    """(2,2,2) task end-to-end in-process: kernels 2-D sharded, donated train
    steps run, eval finite, and loss tracks the fsdp-only task closely (fp
    reduction-order noise only — constraints change layout, not math)."""
    from timm_tpu.parallel import set_global_mesh
    mesh = _tp_mesh()
    set_global_mesh(mesh)
    task = _make_task(mesh, opt='adamw')
    qkv = nnx.state(task.model, nnx.Param)['blocks'][0]['attn']['qkv']['kernel'].value
    assert 'model' in tuple(qkv.sharding.spec) and 'fsdp' in tuple(qkv.sharding.spec)
    batch = _batch(mesh)
    losses_tp = [float(task.train_step(batch, lr=1e-3, step=i + 1)['loss']) for i in range(2)]
    out = task.eval_step({'input': batch['input']})
    assert np.isfinite(np.asarray(out)).all()

    set_global_mesh(_fsdp_mesh(4))
    task_f = _make_task(_fsdp_mesh(4), opt='adamw')
    batch_f = _batch(_fsdp_mesh(4))
    losses_f = [float(task_f.train_step(batch_f, lr=1e-3, step=i + 1)['loss']) for i in range(2)]
    # step 1 runs on identical params: pure forward reduction-order noise.
    # step 2 runs after one AdamW update, which amplifies that noise — the
    # tight ≤1e-5 parity acceptance lives in the 8-device subprocess drill.
    np.testing.assert_allclose(losses_tp[0], losses_f[0], atol=1e-4)
    np.testing.assert_allclose(losses_tp[1], losses_f[1], rtol=5e-2)


def test_bench_dry_run_tp_smoke(restore_global_mesh):
    """`bench.py --dry-run --fsdp 2 --tp 2` compiles + runs a (2,2,2)-mesh
    train/infer step on CPU (the tp compile smoke the on-device A/B rides on)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location('bench_tp_smoke', os.path.join(REPO_ROOT, 'bench.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Args:
        model = 'vit_tiny_patch16_224'
        img_size = 32
        pad_tokens = ''
        softmax_dtype = ''
        norm_dtype = ''
        mu_dtype = ''
        fsdp = 2
        tp = 2

    assert bench._dry_run(Args()) == 0


@pytest.mark.slow
def test_tp_8device_parity_and_cross_mesh_checkpoint(tmp_path):
    """Acceptance drill: ('data','fsdp','model')=(2,2,2) golden-fixture train
    matches single-device params ≤1e-5 after 3 updates, the qkv/proj/fc1/fc2
    kernels are verifiably (fsdp x model)-sharded, the durable checkpoint's
    sidecar is mesh-shape-agnostic, and a fresh 1-device process loads + evals
    it within fp reduction-order noise.

    `-m slow` since the autotune PR (tier-1 headroom): two cold subprocesses
    cost ~146 s — the single most expensive tier-1 item — while every
    property except the 1-device process boundary is covered in-process by
    `test_tp_task_train_eval_in_process` (loose train parity vs fsdp) and
    `test_tp_cross_mesh_checkpoint_in_process` (sharded-save manifest
    stability + cross-mesh-shape reload, below). The process-boundary +
    1-device reload acceptance for the SAME save/load code path stays in
    tier-1 via the fsdp drill above."""
    res = _run_drill('parity_tp', tmp_path, devices=8)
    assert res['devices'] == 8 and res['mesh'] == [2, 2, 2]
    assert res['max_param_diff'] <= 1e-5, res
    assert res['max_ema_diff'] <= 1e-5, res
    assert res['tp_sharded'] and all(res['tp_sharded'].values()), res
    assert res['manifest_matches_unsharded'], res

    res1 = _run_drill('load1_tp', tmp_path, devices=1)
    assert res1['devices'] == 1
    assert res1['verified'] and res1['loaded'], res1
    assert res1['eval_matches_saved_logits'] <= 1e-5, res1


def test_tp_cross_mesh_checkpoint_in_process(restore_global_mesh, tmp_path):
    """In-process twin of the `-m slow` tp subprocess drill: the durable
    checkpoint written with raw (fsdp x model)-sharded param leaves hashes
    identically to a host-array save (the gather-to-host path is manifest-
    stable), verifies, and loads into a task on a DIFFERENT mesh shape
    ((2,4) fsdp-only, same 8 devices) with bit-exact params and eval logits
    matching within fp reduction-order noise.

    Runs the usual img_size=32 again: the 5-token (2,2,2)-mesh eval
    divergence that forced this twin onto img_size=64 was bisected to an
    XLA:CPU SPMD miscompile of the constrained-residual + megatron-MLP add
    at tiny token extents, and `shard_activation` now skips constraints
    below its observed-safe floor (constraints._MIN_TOKENS) — see
    test_tp_tiny_geometry_eval_parity below and the PERF.md note."""
    from jax.tree_util import tree_flatten_with_path
    from timm_tpu.parallel import set_global_mesh
    from timm_tpu.parallel.sharding import _kp_str
    from timm_tpu.resilience import load_with_fallback
    from timm_tpu.resilience.durable import atomic_write_npz, read_manifest, verify_checkpoint
    from timm_tpu.utils.serialization import flatten_pytree

    def _task32(mesh):
        model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
        opt = create_optimizer_v2(model, opt='adamw', lr=0.1)
        return ClassificationTask(model, optimizer=opt, mesh=mesh,
                                  train_loss_fn=LabelSmoothingCrossEntropy(0.1))

    def _batch32(mesh):
        rng = np.random.RandomState(0)
        return shard_batch(
            {'input': jnp.asarray(rng.rand(16, 32, 32, 3), jnp.float32),
             'target': jnp.asarray(rng.randint(0, 10, 16))}, mesh)

    mesh = _tp_mesh()
    set_global_mesh(mesh)
    task = _task32(mesh)
    batch = _batch32(mesh)
    task.train_step(batch, lr=1e-3, step=1)
    logits_tp = np.asarray(task.eval_step({'input': batch['input']}))

    # durable save with raw 2-D-sharded leaves, exactly like the drill: the
    # gathered sidecar must equal the one a pure-host save produces
    state = task.get_checkpoint_state()
    raw = dict(state)
    for kp, leaf in tree_flatten_with_path(nnx.state(task.model, nnx.Param))[0]:
        raw['state_dict.' + _kp_str(kp)] = leaf.value if hasattr(leaf, 'value') else leaf
    ckpt = str(tmp_path / 'ckpt_tp.npz')
    atomic_write_npz(ckpt, raw, meta={'epoch': 0, 'mesh': '2x2x2'})
    host = str(tmp_path / 'ckpt_host.npz')
    atomic_write_npz(host, {k: np.asarray(v) for k, v in raw.items()}, meta={'epoch': 0})
    assert {k: v['sha256'] for k, v in read_manifest(ckpt)['arrays'].items()} == \
        {k: v['sha256'] for k, v in read_manifest(host)['arrays'].items()}
    ok, reason = verify_checkpoint(ckpt)
    assert ok, reason

    mesh_f = _fsdp_mesh(4)
    set_global_mesh(mesh_f)
    task_f = _task32(mesh_f)
    loaded, _meta, used = load_with_fallback(ckpt)
    assert used == ckpt
    task_f.load_checkpoint_state(loaded)
    a = {k: np.asarray(v) for k, v in flatten_pytree(nnx.state(task.model, nnx.Param)).items()}
    b = {k: np.asarray(v) for k, v in flatten_pytree(nnx.state(task_f.model, nnx.Param)).items()}
    assert a.keys() == b.keys()
    assert max(float(np.abs(a[k] - b[k]).max()) for k in a) == 0.0
    logits_f = np.asarray(task_f.eval_step({'input': _batch32(mesh_f)['input']}))
    np.testing.assert_allclose(logits_f, logits_tp, atol=1e-5)


def test_tp_tiny_geometry_eval_parity(restore_global_mesh):
    """Regression for the PERF.md tiny-geometry tp divergence: the jitted
    (2,2,2)-mesh eval of test_vit@32 (5 tokens) now matches the eager model
    to fp noise, because `shard_activation` skips its constraints below the
    observed-safe token floor. Before the guard this diverged ~6e-2 (an
    XLA:CPU SPMD miscompile of the constrained residual + megatron-sharded
    MLP add, corrupting the interior batch shards' patch tokens)."""
    from timm_tpu.parallel import build_param_shardings, set_global_mesh
    from timm_tpu.parallel.constraints import _MIN_TOKENS, shard_activation

    mesh = _tp_mesh()
    set_global_mesh(mesh)
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    model.eval()
    graphdef, state = nnx.split(model)
    sharded = jax.device_put(state, build_param_shardings(state, mesh))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32)

    def fwd(s, xx):
        return nnx.merge(graphdef, s)(xx)

    eager = fwd(state, x)
    jitted = jax.jit(fwd)(sharded, shard_batch(x, mesh))
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), atol=1e-5)

    # the guard itself: below the floor the constraint is an identity even
    # inside jit; at/above the floor it still pins the tp layout
    tiny = jnp.zeros((8, _MIN_TOKENS - 1, 64))
    big = jnp.zeros((8, _MIN_TOKENS, 64))
    jaxpr_tiny = jax.make_jaxpr(lambda t: shard_activation(t, 'residual'))(tiny)
    jaxpr_big = jax.make_jaxpr(lambda t: shard_activation(t, 'residual'))(big)
    assert 'sharding_constraint' not in str(jaxpr_tiny)
    assert 'sharding_constraint' in str(jaxpr_big)
