"""Sharded-execution semantics tests (SURVEY §7 hard part (c)):
BatchNorm batch statistics under a sharded batch must equal the
global-batch statistics computed on one device."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.layers import BatchNormAct2d
from timm_tpu.parallel import shard_batch


def test_bn_sharded_stats_match_global(mesh8):
    """Train-mode BN over an 8-way sharded batch: running stats and outputs
    must match the single-device global-batch computation (XLA inserts the
    cross-device reductions for the batch mean/var)."""
    rng = np.random.RandomState(0)
    x_np = rng.rand(16, 8, 8, 6).astype(np.float32) * 3.0 + 1.0

    def run(shard: bool):
        bn = BatchNormAct2d(6, rngs=nnx.Rngs(0))
        bn.train()
        graphdef, state = nnx.split(bn)

        @jax.jit
        def step(state, x):
            m = nnx.merge(graphdef, state)
            y = m(x)
            _, new_state = nnx.split(m)
            return y, new_state

        x = jnp.asarray(x_np)
        if shard:
            x = shard_batch(x, mesh8)
        y, new_state = step(state, x)
        return np.asarray(y), jax.tree.map(np.asarray, nnx.to_pure_dict(new_state))

    y_global, state_global = run(shard=False)
    y_sharded, state_sharded = run(shard=True)

    np.testing.assert_allclose(y_sharded, y_global, rtol=1e-5, atol=1e-5)
    flat_g = jax.tree_util.tree_leaves_with_path(state_global)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(state_sharded))
    checked = 0
    for path, leaf_g in flat_g:
        leaf_s = flat_s[path]
        np.testing.assert_allclose(leaf_s, leaf_g, rtol=1e-5, atol=1e-6,
                                   err_msg=f'BN state diverged at {path}')
        checked += 1
    assert checked >= 2  # at least running mean + var compared


def test_bn_model_sharded_train_step_matches_global(mesh8):
    """Full jitted train step of a BN trunk (test_resnet) through the REAL
    task path: loss, grad norm, and updated BN running stats identical
    whether the batch is 8-way sharded or unsharded."""
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask
    rng = np.random.RandomState(0)
    x_np = rng.rand(16, 64, 64, 3).astype(np.float32)
    t_np = rng.randint(0, 10, 16)

    def run(shard: bool):
        model = timm_tpu.create_model('test_resnet', num_classes=10)
        task = ClassificationTask(
            model, optimizer=create_optimizer_v2(model, opt='sgd', lr=0.1), mesh=mesh8)
        batch = {'input': jnp.asarray(x_np), 'target': jnp.asarray(t_np)}
        if shard:
            batch = shard_batch(batch, mesh8)
        metrics = task.train_step(batch, lr=0.1, step=1)
        stats = jax.tree.map(np.asarray, nnx.to_pure_dict(nnx.state(model, nnx.BatchStat)))
        return float(metrics['loss']), float(metrics.get('grad_norm', 0.0)), stats

    loss_g, gnorm_g, stats_g = run(shard=False)
    loss_s, gnorm_s, stats_s = run(shard=True)
    assert abs(loss_s - loss_g) < 1e-4, f'sharded loss {loss_s} != global {loss_g}'
    assert abs(gnorm_s - gnorm_g) / max(gnorm_g, 1e-8) < 1e-3
    flat_g = jax.tree_util.tree_leaves_with_path(stats_g)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(stats_s))
    assert flat_g, 'model must expose BatchStat state'
    for path, leaf_g in flat_g:
        np.testing.assert_allclose(
            flat_s[path], leaf_g, rtol=1e-4, atol=1e-5,
            err_msg=f'sharded BN running stats diverged at {path}')
