"""Utility tests (reference: tests/test_utils.py — freeze/EMA/AGC/unwrap; plus
the extraction/relabel helpers)."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu


def test_ema_update_math():
    from timm_tpu.utils import ema_update
    ema = {'w': jnp.ones((4,))}
    new = {'w': jnp.zeros((4,))}
    out = ema_update(ema, new, decay=0.9)
    np.testing.assert_allclose(np.asarray(out['w']), 0.9, rtol=1e-6)


def test_ema_decay_warmup():
    from timm_tpu.utils import ModelEmaV3
    ema = ModelEmaV3(decay=0.999, use_warmup=True)
    assert ema.get_decay(0) == 0.0
    assert 0.0 < ema.get_decay(10) < ema.get_decay(1000) <= 0.999


def test_attention_extract_vit():
    from timm_tpu.utils import AttentionExtract
    m = timm_tpu.create_model('test_vit', num_classes=5)
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(1, 160, 160, 3), jnp.float32)
    maps = AttentionExtract(m, names=['blocks.0.attn', 1])(x)
    assert set(maps) == {'blocks.0.attn', 'blocks.1.attn'}
    for v in maps.values():
        assert v.shape == (1, 2, 101, 101)
        assert bool(jnp.allclose(v.sum(-1), 1.0, atol=1e-4))


def test_attention_extract_rope_model():
    from timm_tpu.utils import AttentionExtract
    m = timm_tpu.create_model('test_eva', num_classes=5)
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(1, 160, 160, 3), jnp.float32)
    maps = AttentionExtract(m, names=[0])(x)
    v = maps['blocks.0.attn']
    assert bool(jnp.allclose(v.sum(-1), 1.0, atol=1e-4))


def test_real_labels(tmp_path):
    from timm_tpu.data import RealLabelsImagenet
    rj = tmp_path / 'real.json'
    json.dump([[1], [2], []], open(rj, 'w'))
    rl = RealLabelsImagenet(
        [f'ILSVRC2012_val_{i + 1:08d}.JPEG' for i in range(3)], real_json=str(rj))
    logits = np.zeros((3, 5))
    logits[0, 1] = 9  # correct
    logits[1, 0] = 9  # wrong (top1), label 2 not in top1
    logits[1, 2] = 8  # ...but in top5
    logits[2, 4] = 9  # excluded (no labels)
    rl.add_result(logits)
    acc = rl.get_accuracy()
    assert acc[1] == pytest.approx(50.0)
    assert acc[5] == pytest.approx(100.0)
    # top-k path equivalence
    rl2 = RealLabelsImagenet(
        [f'ILSVRC2012_val_{i + 1:08d}.JPEG' for i in range(3)], real_json=str(rj))
    topk = np.argsort(logits, axis=-1)[:, ::-1][:, :5]
    rl2.add_result(topk, is_topk=True)
    assert rl2.get_accuracy() == acc


def test_freeze_unfreeze():
    from timm_tpu.utils import freeze, unfreeze
    m = timm_tpu.create_model('test_vit', num_classes=5)
    n_before = len(jax.tree.leaves(nnx.state(m, nnx.Param)))
    freeze(m, 'patch_embed')
    n_frozen = len(jax.tree.leaves(nnx.state(m, nnx.Param)))
    assert n_frozen < n_before
    unfreeze(m, 'patch_embed')
    assert len(jax.tree.leaves(nnx.state(m, nnx.Param))) == n_before


def test_flatten_unflatten_roundtrip():
    from timm_tpu.utils import flatten_pytree, unflatten_into
    tree = {'a': jnp.ones((2, 2)), 'b': [jnp.zeros((3,)), jnp.full((1,), 7.0)]}
    flat = flatten_pytree(tree, 'x')
    assert all(k.startswith('x.') for k in flat)
    rebuilt = unflatten_into(tree, flat, 'x')
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(rebuilt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
