"""Model zoo tests (reference: tests/test_models.py — forward/backward/cfg
consistency/features parametrized over the registry)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

import timm_tpu
from timm_tpu.models import list_models, get_pretrained_cfg

# size-capped like the reference (_get_input_size, EXCLUDE filters :79-113);
# the default (fast) forward sweep covers small per-family representatives,
# the full registry sweep runs under -m slow (reference shards this across CI)
FAST_FILTERS = [
    'test_*', 'vit_tiny*', 'vit_small_patch32*', '*_atto', '*_femto', '*_pico',
    'resnet18', 'resnet26', 'mixer_s32*', 'efficientnet_b0',
]
EXCLUDE_FILTERS = [
    '*_large*', '*_huge*', '*so400m*', '*_384', '*_giant*', '*_gigantic*', '*_xlarge*',
    'resnet101*', 'resnet152*', 'wide_resnet*', 'efficientnetv2_m*', 'mixer_l*',
    '*x4_clip*', '*x16_clip*', '*x64_clip*', 'repvgg_d2se', 'repvgg_b3*',
    'bat_*',  # BAT bilinear attn needs 256px inputs (block_size 8 divisibility)
]
TEST_MODELS = list_models(filter=FAST_FILTERS)
ALL_MODELS = list_models(exclude_filters=EXCLUDE_FILTERS)
SLOW_MODELS = [m for m in ALL_MODELS if m not in TEST_MODELS]
FWD_SIZE = 64


def _create_small(model_name, **kwargs):
    cfg = get_pretrained_cfg(model_name)
    fixed = cfg is not None and cfg.fixed_input_size
    try:
        return timm_tpu.create_model(model_name, img_size=FWD_SIZE, num_classes=10, **kwargs), FWD_SIZE
    except TypeError:
        return timm_tpu.create_model(model_name, num_classes=10, **kwargs), (cfg.input_size[-1] if cfg else 224)


@pytest.mark.base
@pytest.mark.parametrize('model_name', TEST_MODELS)
def test_model_forward(model_name):
    model, size = _create_small(model_name)
    model.eval()
    x = jnp.asarray(np.random.rand(2, size, size, 3), jnp.float32)
    out = model(x)
    assert out.shape == (2, 10)
    assert bool(jnp.isfinite(out).all()), 'Output contains NaN/Inf'


@pytest.mark.slow
@pytest.mark.parametrize('model_name', SLOW_MODELS)
def test_model_forward_slow(model_name):
    model, size = _create_small(model_name)
    model.eval()
    x = jnp.asarray(np.random.rand(1, size, size, 3), jnp.float32)
    out = model(x)
    assert out.shape == (1, 10)
    assert bool(jnp.isfinite(out).all())


# one small representative per family for gradient coverage (reference
# tests/test_models.py:213 runs backward over every model; we cover every
# FAMILY with its smallest member to keep CPU wall time bounded)
FAMILY_BACKWARD_MODELS = [
    'vit_tiny_patch16_224', 'vit_tiny_r_s16_p8_224', 'deit_tiny_distilled_patch16_224', 'eva02_tiny_patch14_336',
    'beit_base_patch16_224', 'cait_xxs24_224', 'xcit_nano_12_p16_224',
    'levit_128s', 'volo_d1_224', 'mvitv2_tiny', 'swin_tiny_patch4_window7_224', 'edgenext_xx_small',
    'repvit_m0_9', 'tiny_vit_5m_224', 'efficientformer_l1', 'efficientformerv2_s0',
    'mobilevit_xxs', 'mobilevitv2_050', 'twins_svt_small', 'mambaout_femto',
    'swinv2_tiny_window8_256', 'coatnet_pico_rw_224', 'maxvit_pico_rw_256',
    'mixer_s32_224', 'convnext_atto', 'resnet18', 'resnetv2_50', 'nf_resnet50',
    'regnetx_002', 'vgg11', 'densenet121', 'efficientnet_lite0',
    'mobilenetv3_small_100', 'mnasnet_050', 'lcnet_035', 'gernet_s',
    'halonet26t', 'lambda_resnet26t', 'botnet26t_256',
]
_family_backward = FAMILY_BACKWARD_MODELS


# halo blocked attention needs block_size (8) to divide every stage grid
_BACKWARD_SIZE_OVERRIDES = {
    'halonet26t': 256,
    'efficientformer_l1': 224,  # fixed 7x7 attention-bias table in the final stage
}


@pytest.mark.backward
@pytest.mark.slow
@pytest.mark.parametrize('model_name', _family_backward)
def test_model_backward_family(model_name):
    """Gradient sweep, one representative per family (markers: backward+slow).

    Also marked slow: each case re-traces and lowers a full-size model's
    fwd+bwd (~30s CPU; the persistent XLA cache only skips the compile, not
    the trace), so the 39-family sweep is a ~20-minute job that belongs in
    the explicit `-m backward` / `-m slow` tiers, not the fast suite. Until
    the flax-compat fixes these cases crashed at import time, which is the
    only reason they ever looked cheap enough for the fast tier."""
    cfg = get_pretrained_cfg(model_name)
    want = _BACKWARD_SIZE_OVERRIDES.get(model_name, 96)
    try:
        model = timm_tpu.create_model(model_name, img_size=want, num_classes=5)
        size = want
    except TypeError:
        model = timm_tpu.create_model(model_name, num_classes=5)
        size = cfg.input_size[-1] if cfg else 224
    model.train()
    x = jnp.asarray(np.random.rand(2, size, size, 3), jnp.float32)
    t = jnp.asarray([0, 1])

    def loss_fn(model):
        out = model(x)
        out = out[0] if isinstance(out, tuple) else out
        return jnp.mean((out - jax.nn.one_hot(t, out.shape[-1])) ** 2)

    grads = nnx.grad(loss_fn)(model)
    num_params = len(jax.tree.leaves(nnx.state(model, nnx.Param)))
    num_grads = len([g for g in jax.tree.leaves(grads) if g is not None])
    assert num_params == num_grads, 'Some params missing gradients'
    finite = all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))
    assert finite, 'NaN/Inf gradient'


@pytest.mark.base
@pytest.mark.parametrize('model_name', list_models('test_*'))
def test_model_backward(model_name):
    model, size = _create_small(model_name)
    model.train()
    x = jnp.asarray(np.random.rand(2, size, size, 3), jnp.float32)
    t = jnp.asarray([0, 1])

    def loss_fn(model):
        out = model(x)
        return jnp.mean((out - jax.nn.one_hot(t, out.shape[-1])) ** 2)

    grads = nnx.grad(loss_fn)(model)
    num_params = len(jax.tree.leaves(nnx.state(model, nnx.Param)))
    num_grads = len([g for g in jax.tree.leaves(grads) if g is not None])
    assert num_params == num_grads, 'Some params missing gradients'
    for g in jax.tree.leaves(grads):
        assert bool(jnp.isfinite(g).all()), 'NaN/Inf gradient'


@pytest.mark.cfg
@pytest.mark.parametrize('model_name', ALL_MODELS)
def test_model_default_cfg(model_name):
    cfg = get_pretrained_cfg(model_name)
    if cfg is None:
        pytest.skip('no pretrained cfg')
    # headless feature models (e.g. CLIP trunks) legitimately ship num_classes=0
    assert cfg.num_classes >= 0
    assert len(cfg.input_size) == 3
    assert cfg.classifier is not None
    assert cfg.first_conv is not None


@pytest.mark.cfg
@pytest.mark.parametrize('model_name', list_models('test_*'))
def test_model_classifier_reset(model_name):
    model, size = _create_small(model_name)
    model.eval()
    x = jnp.asarray(np.random.rand(1, size, size, 3), jnp.float32)
    # pre-logits / identity head
    model.reset_classifier(0)
    out = model(x)
    # heads with a pre-logits MLP keep it on reset (reference ClNormMlpClassifierHead
    # semantics: reset() without reset_other preserves hidden layers)
    want = {model.num_features, getattr(model, 'head_hidden_size', model.num_features)}
    assert out.ndim == 2 and out.shape[-1] in want
    # new head size
    model.reset_classifier(7)
    assert model(x).shape == (1, 7)


@pytest.mark.features
@pytest.mark.parametrize('model_name', list_models('test_*'))
def test_model_forward_intermediates(model_name):
    model, size = _create_small(model_name)
    model.eval()
    x = jnp.asarray(np.random.rand(1, size, size, 3), jnp.float32)
    final, intermediates = model.forward_intermediates(x, indices=(0, 1))
    assert len(intermediates) == 2
    for feat in intermediates:
        assert feat.ndim == 4  # NHWC grid
        assert feat.shape[0] == 1
    # parity with features_only wrapper
    try:
        wrapped = timm_tpu.create_model(
            model_name, img_size=size, num_classes=10, features_only=True, out_indices=(0, 1))
    except TypeError:
        wrapped = timm_tpu.create_model(model_name, num_classes=10, features_only=True, out_indices=(0, 1))
    wrapped.eval()
    feats = wrapped(x)
    assert len(feats) == 2
    assert feats[-1].shape == intermediates[-1].shape


@pytest.mark.features
def test_features_info():
    model = timm_tpu.create_model('test_vit', features_only=True, out_indices=(0, 1))
    assert len(model.feature_info.channels()) == 2
    assert all(c == 64 for c in model.feature_info.channels())


@pytest.mark.base
def test_model_no_weight_decay():
    model = timm_tpu.create_model('test_vit')
    nwd = model.no_weight_decay()
    assert 'pos_embed' in nwd and 'cls_token' in nwd


@pytest.mark.base
def test_model_group_matcher():
    from timm_tpu.models import group_parameters
    model = timm_tpu.create_model('test_vit')
    groups = group_parameters(model, model.group_matcher())
    # stem group + per-block groups + final-norm merged into last
    assert len(groups) >= 3


@pytest.mark.base
def test_grad_checkpointing_forward_match():
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=FWD_SIZE)
    model.eval()
    x = jnp.asarray(np.random.rand(1, FWD_SIZE, FWD_SIZE, 3), jnp.float32)
    out_ref = model(x)
    model.set_grad_checkpointing(True)
    out_ckpt = model(x)
    assert bool(jnp.allclose(out_ref, out_ckpt, atol=1e-5))


@pytest.mark.base
def test_state_dict_roundtrip(tmp_path):
    from timm_tpu.models import load_checkpoint, model_state_dict, save_state_dict
    m1 = timm_tpu.create_model('test_vit', num_classes=10, img_size=FWD_SIZE, seed=0)
    m2 = timm_tpu.create_model('test_vit', num_classes=10, img_size=FWD_SIZE, seed=99)
    m1.eval(), m2.eval()
    x = jnp.asarray(np.random.rand(1, FWD_SIZE, FWD_SIZE, 3), jnp.float32)
    path = str(tmp_path / 'w.safetensors')
    save_state_dict(model_state_dict(m1), path)
    load_checkpoint(m2, path)
    assert bool(jnp.allclose(m1(x), m2(x), atol=1e-6))


@pytest.mark.base
def test_torch_checkpoint_conversion():
    torch = pytest.importorskip('torch')
    from timm_tpu.models._torch_convert import convert_torch_state_dict
    sd = {
        'head.weight': torch.zeros(10, 64).numpy(),
        'head.bias': torch.zeros(10).numpy(),
        'patch_embed.proj.weight': torch.zeros(64, 3, 16, 16).numpy(),
        'norm.weight': torch.ones(64).numpy(),
        'bn.running_mean': torch.zeros(64).numpy(),
    }
    out = convert_torch_state_dict(sd)
    assert out['head.kernel'].shape == (64, 10)
    assert out['patch_embed.proj.kernel'].shape == (16, 16, 3, 64)
    assert 'norm.scale' in out
    assert 'bn.mean' in out


@pytest.mark.base
def test_byobnet_reparameterize_matches():
    """RepVGG/MobileOne branch fusion must be numerically transparent."""
    from timm_tpu.utils import reparameterize_model
    x = jnp.asarray(np.random.RandomState(0).rand(1, 64, 64, 3), jnp.float32)
    for name in ('repvgg_a0', 'mobileone_s0'):
        m = timm_tpu.create_model(name, num_classes=10)
        m.train()
        _ = m(x + 0.3)  # populate BN running stats with non-trivial values
        m.eval()
        before = np.asarray(m(x))
        reparameterize_model(m)
        after = np.asarray(m(x))
        rel = np.abs(before - after).max() / max(1.0, np.abs(before).max())
        assert rel < 1e-5, (name, rel)


@pytest.mark.base
def test_byobnet_head_types():
    """attn_abs / attn_rot / mlp heads produce correctly-shaped outputs."""
    from timm_tpu.models.byobnet import ByoBlockCfg, ByoModelCfg, ByobNet
    cfg = ByoModelCfg(
        blocks=(ByoBlockCfg(type='basic', d=1, c=32, s=2),),
        stem_chs=16, stem_pool='',
    )
    x = jnp.asarray(np.random.rand(2, 64, 64, 3), jnp.float32)
    from dataclasses import replace as dc_replace
    for head_type, kw in (('classifier', {}), ('mlp', dict(head_hidden_size=24)),
                          ('attn_abs', dict(head_hidden_size=64)), ('attn_rot', dict(head_hidden_size=64))):
        m = ByobNet(dc_replace(cfg, head_type=head_type, **kw), num_classes=10, img_size=64, rngs=nnx.Rngs(0))
        m.eval()
        assert m(x).shape == (2, 10), head_type
        pre = m.forward_head(m.forward_features(x), pre_logits=True)
        assert pre.ndim == 2 and pre.shape[0] == 2, head_type
