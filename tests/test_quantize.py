"""Int8 post-training weight-only quantization + distill-to-serve.

Covers the PR-11 acceptance surface:

  1. quantize→dequantize round-trip error bounds per layer kind (symmetric
     per-output-channel scales bound elementwise error by scale/2), with
     biases / norms / tokens provably NOT quantized;
  2. quantized-vs-fp32 logits tolerance on the golden fixture (vit_tiny,
     img 64) — the checked-in constant the quantize-then-validate gate pins;
  3. scale-spec inheritance lint: every quantized kernel's scale resolves to
     its kernel's PartitionSpec last axis (or replicates), the qvalues ride
     the UNCHANGED partition-rule table, and the rule table stays disjoint +
     exhaustive over the quantized pytree's paths;
  4. engine serve parity through a padded bucket, and residency byte
     accounting charging the real int8 footprint (oversized warn reports
     both the int8 and dense numbers);
  5. cross-mesh drill: a quantized checkpoint saved on 8 devices loads and
     serves on 1 (subprocess, like the fsdp parity drills);
  6. distillation smoke: the dormant LogitDistillationTask /
     FeatureDistillationTask run under the functional donated train step.
"""
import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import nnx
from jax.sharding import PartitionSpec as P

import timm_tpu
from timm_tpu.parallel import (
    build_quant_shardings, create_mesh, quant_path_specs, quant_scale_spec,
    set_global_mesh, shard_batch,
)
from timm_tpu.parallel.sharding import (
    _kp_str, default_partition_rules, spec_for_param,
)
from timm_tpu.quantize import (
    QUANT_QVALUES, QUANT_SCALES, dequantize_tree, load_quantized,
    quantization_stats, quantize_tree, quantized_paths, save_quantized,
    tree_bytes,
)

pytestmark = pytest.mark.quant

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(os.path.dirname(__file__), 'fixtures', 'vit_tiny_img64_golden.npz')

# measured 0.0105 max-abs on the untrained golden fixture (logit range ~±0.83);
# 0.05 gives headroom for compiler drift while still catching a broken scale
GOLDEN_LOGITS_TOL = 0.05


def _split_eval(name, **kwargs):
    model = timm_tpu.create_model(name, **kwargs)
    model.eval()
    return nnx.split(model)


# ---- 1. core transform -------------------------------------------------------

def test_round_trip_error_bounds_per_layer_kind():
    """Symmetric per-output-channel int8: |w - dequant(q)| <= scale/2
    elementwise for EVERY quantized kernel (absmax maps to exactly ±127, so
    clipping never bites), across attention, MLP, and patch-embed kernels."""
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    paths = quantized_paths(qstate)
    # every transformer layer kind is represented
    for kind in ('attn.qkv.kernel', 'attn.proj.kernel',
                 'mlp.fc1.kernel', 'mlp.fc2.kernel', 'patch_embed.proj.kernel'):
        assert any(p.endswith(kind) or kind in p for p in paths), \
            f'no quantized kernel of kind {kind}: {sorted(paths)}'

    flat = {_kp_str(kp): leaf for kp, leaf in
            jax.tree_util.tree_flatten_with_path(state)[0]}
    dense = dequantize_tree(qstate)
    dflat = {_kp_str(kp): leaf for kp, leaf in
             jax.tree_util.tree_flatten_with_path(dense)[0]}
    for path in paths:
        w = np.asarray(flat[path])
        wq = np.asarray(dflat[path])
        scale = np.asarray(qstate[QUANT_SCALES][path])
        bound = scale.reshape((1,) * (w.ndim - 1) + (-1,)) / 2.0
        err = np.abs(w - wq)
        assert (err <= bound + 1e-7).all(), \
            f'{path}: max err {err.max()} exceeds scale/2 bound {bound.max()}'
        assert wq.dtype == w.dtype


def test_biases_norms_tokens_not_quantized():
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    paths = quantized_paths(qstate)
    assert all(p.endswith('.kernel') for p in paths)
    for bad in ('bias', 'norm', 'cls_token', 'pos_embed', 'scale'):
        assert not any(bad in p.rsplit('.', 1)[-1] for p in paths)
    # untouched leaves survive bit-exactly with their dtype
    flat_q = {_kp_str(kp): leaf for kp, leaf in
              jax.tree_util.tree_flatten_with_path(qstate[QUANT_QVALUES])[0]}
    flat_s = {_kp_str(kp): leaf for kp, leaf in
              jax.tree_util.tree_flatten_with_path(state)[0]}
    for path, leaf in flat_q.items():
        if path not in paths:
            assert leaf.dtype == flat_s[path].dtype
            assert (np.asarray(leaf) == np.asarray(flat_s[path])).all()
    # the head kernel (64x10 = 640 < MIN_QUANT_SIZE) stays dense
    assert not any('head' in p for p in paths)


def test_quantization_stats_halve_bytes():
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    stats = quantization_stats(state, qstate)
    assert stats['num_quantized'] >= 9
    assert stats['bytes_ratio'] <= 0.35, stats
    assert tree_bytes(qstate) == stats['quantized_bytes']


def test_save_load_round_trip(tmp_path):
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    path = str(tmp_path / 'q.npz')
    save_quantized(qstate, path)
    loaded = load_quantized(path, state)
    for (kp_a, a), (kp_b, b) in zip(
            jax.tree_util.tree_flatten_with_path(qstate)[0],
            jax.tree_util.tree_flatten_with_path(loaded)[0]):
        assert _kp_str(kp_a) == _kp_str(kp_b)
        assert a.dtype == b.dtype
        assert (np.asarray(a) == np.asarray(b)).all(), _kp_str(kp_a)
    # wrong template (different arch) must refuse, not silently mis-load
    _, other = _split_eval('test_vit3', num_classes=10, img_size=32)
    with pytest.raises((KeyError, ValueError)):
        load_quantized(path, other)


# ---- 2. golden fixture -------------------------------------------------------

def test_golden_fixture_quantized_logits_tolerance():
    """The quantized forward of the golden-fixture ViT stays within the
    checked-in tolerance of the recorded fp32 logits — the same bound
    `validate.py --quantize int8` gates on (top-1 can only move if logits
    move; here even the raw logits barely do)."""
    g = np.load(_FIXTURE)
    gd, state = _split_eval('vit_tiny_patch16_224', img_size=64)
    qstate = quantize_tree(state)
    stats = quantization_stats(state, qstate)
    assert stats['bytes_ratio'] <= 0.30, stats
    qlogits = np.asarray(nnx.merge(gd, dequantize_tree(qstate))(jnp.asarray(g['x'])))
    diff = np.abs(qlogits - g['logits'])
    assert diff.max() <= GOLDEN_LOGITS_TOL, \
        f'quantized logits drifted {diff.max():.4f} > {GOLDEN_LOGITS_TOL}'
    assert (qlogits.argmax(-1) == g['logits'].argmax(-1)).all()


# ---- 3. scale-spec inheritance lint ------------------------------------------

def test_scale_specs_inherit_kernel_last_axis():
    """Every quantized kernel's scale resolves to P(kernel_spec[-1]) when the
    kernel's last axis is sharded (so dequant needs NO collective: each shard
    holds exactly the scale rows of its output channels), else P()."""
    mesh = create_mesh(fsdp=2, tp=2)
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    specs = quant_path_specs(qstate, mesh)
    rules = default_partition_rules()
    axis_sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    checked_sharded = 0
    for path in quantized_paths(qstate):
        q = {_kp_str(kp): l for kp, l in
             jax.tree_util.tree_flatten_with_path(qstate[QUANT_QVALUES])[0]}[path]
        kernel_spec = spec_for_param(path, q.shape, mesh, rules)
        scale = qstate[QUANT_SCALES][path]
        expect = quant_scale_spec(kernel_spec, scale.shape, mesh)
        got = specs[f'{QUANT_SCALES}.{path}']
        assert got == expect, f'{path}: scale spec {got} != {expect}'
        last = kernel_spec[-1] if len(kernel_spec) else None
        if last is not None:
            axes = (last,) if isinstance(last, str) else tuple(last)
            if scale.shape[0] % int(np.prod([axis_sizes[a] for a in axes])) == 0:
                assert got == P(last), f'{path}: sharded kernel but scale {got}'
                checked_sharded += 1
        # the qvalues spec is the kernel's own rule-table spec, unchanged
        assert specs[f'{QUANT_QVALUES}.{path}'] == kernel_spec
    assert checked_sharded >= 4, 'lint never saw a sharded-last-axis kernel'


def test_rules_disjoint_exhaustive_over_quantized_paths():
    """The rule table needs NO quant-specific entries: flattened qvalue paths
    still end `.kernel` etc., so each matches EXACTLY one non-catch-all rule
    (or the catch-all) exactly like its dense twin."""
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    rules = default_partition_rules()
    specific, catchall = rules[:-1], rules[-1]
    assert catchall.pattern == '.*'
    for kp, _ in jax.tree_util.tree_flatten_with_path(qstate[QUANT_QVALUES])[0]:
        path = _kp_str(kp)
        n = sum(1 for r in specific if r.matches(path))
        assert n <= 1, f'{path} matched {n} specific rules'


def test_quant_shardings_place_every_leaf(mesh8):
    """build_quant_shardings covers the WHOLE qstate (qvalues + scales) and
    device_put under it succeeds on the data mesh (all-replicated) — the
    placement path the serve pool uses on every load."""
    _, state = _split_eval('test_vit', num_classes=10, img_size=32)
    qstate = quantize_tree(state)
    placed = jax.device_put(qstate, build_quant_shardings(qstate, mesh8))
    n_leaves = len(jax.tree.leaves(qstate))
    assert len(jax.tree.leaves(placed)) == n_leaves
    for leaf in jax.tree.leaves(placed):
        assert tuple(getattr(leaf.sharding, 'spec', ())) in ((), tuple(P()))


# ---- 4. serve engine + residency accounting ----------------------------------

def test_engine_quantized_serve_parity_through_padded_bucket():
    """5 requests pad into the bucket-8 program; the served logits must match
    a direct dequantized forward <= 1e-5, and the resident entry must be the
    int8 pytree with the int8 byte accounting."""
    from timm_tpu.serve import InferenceEngine

    set_global_mesh(create_mesh())
    eng = InferenceEngine(buckets=(8,), max_wait_ms=1500.0)
    eng.add_model('test_vit', num_classes=10, img_size=32, quantize='int8')
    res = eng.pool.acquire('test_vit')
    assert res.quantize == 'int8'
    dense_bytes = tree_bytes(dequantize_tree(res.state))
    assert res.param_bytes <= 0.35 * dense_bytes

    rng = np.random.RandomState(0)
    imgs = rng.standard_normal((5, 32, 32, 3)).astype(np.float32)
    eng.start()
    try:
        futs = [eng.submit(im, model='test_vit') for im in imgs]
        rows = np.stack([f.result(timeout=120.0) for f in futs])
    finally:
        eng.shutdown(drain=True)
    direct = np.asarray(
        nnx.merge(res.graphdef, dequantize_tree(res.state))(jnp.asarray(imgs)))
    assert np.abs(rows - direct).max() <= 1e-5
    # the padded-bucket program really ran (bucket 8 for 5 requests)
    assert 8 in eng.snapshot_stats()['steps_by_bucket']


def test_residency_budget_sees_int8_footprint(caplog):
    """The LRU budget must charge the ACTUAL loaded pytree's bytes: an int8
    model fits where its fp32 twin cannot, and the oversized warn reports
    both the int8 and the dense number."""
    from timm_tpu.serve.residency import ModelPool, _state_bytes_per_device

    mesh = create_mesh()

    def factory():
        return timm_tpu.create_model('test_vit', num_classes=10, img_size=32)

    m = factory()
    m.eval()
    _, state = nnx.split(m)
    fp32_bytes = _state_bytes_per_device(state, mesh)
    int8_bytes = _state_bytes_per_device(quantize_tree(state), mesh)
    assert int8_bytes <= 0.35 * fp32_bytes

    # budget between the two footprints: int8 loads cleanly...
    pool = ModelPool(mesh, budget_bytes=int(int8_bytes * 1.2))
    pool.register('tv_q', factory, quantize='int8')
    res = pool.acquire('tv_q')
    assert abs(res.param_bytes - int8_bytes) <= 0.02 * int8_bytes
    assert pool.stats['evictions'] == 0

    # ...and a budget below even the int8 footprint warns with BOTH numbers
    pool2 = ModelPool(mesh, budget_bytes=int(int8_bytes * 0.5))
    pool2.register('tv_q', factory, quantize='int8')
    with caplog.at_level(logging.WARNING, logger='timm_tpu.serve.residency'):
        pool2.acquire('tv_q')
    warn = [r.message for r in caplog.records if 'exceeds the HBM budget' in r.message]
    assert warn and 'dense' in warn[0], warn


# ---- 5. cross-mesh drill: quantize on 8 devices, serve on 1 ------------------

_DRILL = os.path.join(os.path.dirname(__file__), 'fsdp_drill.py')


def _run_drill(mode, workdir, devices):
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        XLA_FLAGS=f'--xla_force_host_platform_device_count={devices}',
        TIMM_TPU_DRILL_DEVICES=str(devices),
        TF_CPP_MIN_LOG_LEVEL='3',
    )
    r = subprocess.run([sys.executable, _DRILL, mode, str(workdir)],
                       capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=300)
    assert r.returncode == 0, f'{mode} drill failed rc={r.returncode}:\n{r.stderr[-3000:]}'
    out = [l for l in r.stdout.strip().splitlines() if l.startswith('{')]
    assert out, f'no JSON result from {mode} drill:\n{r.stdout[-2000:]}'
    return json.loads(out[-1])


def test_quantized_checkpoint_saved_on_8_serves_on_1(tmp_path):
    """Acceptance drill: quantize + place on a ('data','fsdp')=(2,4) mesh
    (qvalues really sharded over 'fsdp'), save the int8 checkpoint, then a
    fresh 1-device process loads it into a quantized engine and serves
    logits identical to the 8-device engine's."""
    res8 = _run_drill('quant_save8', tmp_path, devices=8)
    assert res8['devices'] == 8 and res8['mesh'] == [2, 4]
    assert res8['qvalues_sharded_over_fsdp'], res8
    assert res8['quantize'] == 'int8'
    assert res8['num_quantized'] >= 9
    assert os.path.exists(tmp_path / 'quant_ckpt.npz')

    res1 = _run_drill('quant_load1', tmp_path, devices=1)
    assert res1['devices'] == 1 and res1['quantize'] == 'int8'
    assert res1['logits_max_diff'] <= 1e-5, res1
    # per-device int8 bytes: the fsdp=4 engine holds ~1/4 of the 1-device tree
    assert res8['param_bytes'] < res1['param_bytes']
    assert res1['param_bytes'] <= 0.35 * res8['dense_bytes']


# ---- 6. distillation smoke (the dormant task classes) ------------------------

def _dense_batch(mesh, n=8, img=32, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    return shard_batch({'input': jnp.asarray(rng.rand(n, img, img, 3).astype(np.float32)),
                        'target': jnp.asarray(rng.randint(0, classes, n))}, mesh)


def test_logit_distillation_loss_decreases_and_donates():
    """The dormant LogitDistillationTask under the functional donated train
    step: repeated steps on one batch decrease the blended CE+KD loss, and
    the compiled step's HLO header declares the state-buffer aliases."""
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.perfbudget.probe import donation_evidence
    from timm_tpu.task import LogitDistillationTask

    mesh = create_mesh()
    set_global_mesh(mesh)
    student = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    teacher = timm_tpu.create_model('test_vit2', num_classes=10, img_size=32)
    opt = create_optimizer_v2(student, opt='sgd', lr=0.05)
    task = LogitDistillationTask(student, teacher=teacher, optimizer=opt, mesh=mesh,
                                 distill_alpha=0.5, distill_temperature=2.0)
    batch = _dense_batch(mesh)
    losses = [float(task.train_step(batch, lr=0.05)['loss']) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], f'distill loss did not decrease: {losses}'
    ev = donation_evidence(task.lower_train_step(batch))
    assert ev['aliases'] > 0, ev


def test_feature_distillation_projection_and_step():
    """FeatureDistillationTask with mismatched widths (64 -> 96): prepare_model
    attaches the projection BEFORE the optimizer captures the tree, the step
    is finite, and the projection's own kernel receives a gradient update."""
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import FeatureDistillationTask

    mesh = create_mesh()
    set_global_mesh(mesh)
    student = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    teacher = timm_tpu.create_model('test_vit3', num_classes=10, img_size=32)
    assert student.num_features != teacher.num_features
    FeatureDistillationTask.prepare_model(student, teacher)
    assert hasattr(student, 'distill_proj')
    opt = create_optimizer_v2(student, opt='sgd', lr=0.05)
    task = FeatureDistillationTask(student, teacher=teacher, optimizer=opt, mesh=mesh,
                                   distill_alpha=0.5, feat_loss='cosine')
    before = np.asarray(nnx.state(student, nnx.Param)['distill_proj']['kernel'].value).copy()
    m = task.train_step(_dense_batch(mesh), lr=0.05)
    assert np.isfinite(float(m['loss'])), m
    after = np.asarray(nnx.state(task.model, nnx.Param)['distill_proj']['kernel'].value)
    assert np.abs(after - before).max() > 0, 'projection kernel never updated'


def test_distillation_teacher_placed_on_mesh():
    """The frozen teacher's weights are device_put under the task's mesh
    partition rules — a big teacher shards instead of riding along as a
    single-device constant inside the SPMD step."""
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import LogitDistillationTask

    mesh = create_mesh(fsdp=2, tp=2)
    set_global_mesh(mesh)
    student = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    teacher = timm_tpu.create_model('test_vit3', num_classes=10, img_size=32)
    opt = create_optimizer_v2(student, opt='sgd', lr=0.05)
    task = LogitDistillationTask(student, teacher=teacher, optimizer=opt, mesh=mesh)
    tparams, _ = task._teacher_state
    sharded = [l for l in jax.tree.leaves(tparams)
               if any(s is not None for s in tuple(getattr(l.sharding, 'spec', ()) or ()))]
    assert sharded, 'teacher weights stayed replicated/single-device on the mesh'
    m = task.train_step(_dense_batch(mesh), lr=0.05)
    assert np.isfinite(float(m['loss'])), m
