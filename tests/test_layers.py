"""Layer unit tests (reference: tests/test_layers.py, test_layers_drop.py,
test_layers_pool.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from timm_tpu.layers import (
    Attention, DropPath, LayerScale, Mlp, PatchEmbed, SelectAdaptivePool2d,
    calculate_drop_path_rates, get_act_fn, get_norm_layer, global_pool_nlc,
    resample_abs_pos_embed,
)


def test_act_factory():
    for name in ('relu', 'gelu', 'silu', 'hard_swish', 'mish', 'quick_gelu', 'gelu_tanh'):
        fn = get_act_fn(name)
        out = fn(jnp.asarray([-1.0, 0.0, 1.0]))
        assert out.shape == (3,)
    assert get_act_fn(None) is None
    with pytest.raises(ValueError):
        get_act_fn('bogus')


def test_norm_factory():
    rngs = nnx.Rngs(0)
    for name in ('layernorm', 'rmsnorm', 'groupnorm', 'batchnorm2d', 'simplenorm'):
        cls = get_norm_layer(name)
        layer = cls(64, rngs=rngs)
        out = layer(jnp.ones((2, 4, 4, 64)))
        assert out.shape == (2, 4, 4, 64)


def test_attention_shapes_and_mask():
    rngs = nnx.Rngs(0)
    attn = Attention(64, num_heads=4, qkv_bias=True, rngs=rngs)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 10, 64), jnp.float32)
    out = attn(x)
    assert out.shape == (2, 10, 64)
    # boolean mask: masked key contributes nothing
    mask = jnp.ones((2, 1, 10, 10), bool).at[:, :, :, -1].set(False)
    out_masked = attn(x, attn_mask=mask)
    x_zeroed = x.at[:, -1].set(1e9)  # huge value in masked slot must not leak
    attn_out2 = attn(x_zeroed, attn_mask=mask)
    assert bool(jnp.allclose(out_masked[:, :-1], attn_out2[:, :-1], atol=1e-3))


def test_attention_qk_norm():
    from timm_tpu.layers import LayerNorm
    rngs = nnx.Rngs(0)
    attn = Attention(64, num_heads=4, qk_norm=True, norm_layer=LayerNorm, rngs=rngs)
    assert attn(jnp.ones((1, 5, 64))).shape == (1, 5, 64)


def test_drop_path_stats():
    rngs = nnx.Rngs(dropout=0)
    dp = DropPath(0.5, rngs=rngs)
    dp.train()
    x = jnp.ones((512, 4))
    out = dp(x)
    kept = float((out[:, 0] != 0).mean())
    assert 0.35 < kept < 0.65  # ~keep_prob
    # kept rows scaled by 1/keep_prob
    nz = np.asarray(out[out[:, 0] != 0])
    assert np.allclose(nz, 2.0)
    dp.eval()
    assert bool(jnp.allclose(dp(x), x))


def test_drop_path_rates():
    rates = calculate_drop_path_rates(0.3, 4)
    assert rates[0] == 0.0 and rates[-1] == pytest.approx(0.3)
    stage = calculate_drop_path_rates(0.3, [2, 2], stagewise=True)
    assert len(stage) == 2 and stage[1][1] == pytest.approx(0.3)


def test_patch_embed():
    rngs = nnx.Rngs(0)
    pe = PatchEmbed(img_size=32, patch_size=8, in_chans=3, embed_dim=64, rngs=rngs)
    out = pe(jnp.ones((2, 32, 32, 3)))
    assert out.shape == (2, 16, 64)
    assert pe.grid_size == (4, 4)
    pe2 = PatchEmbed(img_size=None, patch_size=8, embed_dim=64, flatten=False, rngs=rngs)
    assert pe2(jnp.ones((2, 40, 32, 3))).shape == (2, 5, 4, 64)


def test_pos_embed_resample():
    pe = jnp.asarray(np.random.RandomState(0).randn(1, 17, 8), jnp.float32)  # 4x4 + cls
    out = resample_abs_pos_embed(pe, new_size=(8, 8), num_prefix_tokens=1)
    assert out.shape == (1, 65, 8)
    assert bool(jnp.allclose(out[:, 0], pe[:, 0]))  # prefix untouched
    # non-square same-count must NOT no-op
    out2 = resample_abs_pos_embed(pe, new_size=(2, 8), num_prefix_tokens=1)
    assert out2.shape == (1, 17, 8)
    assert not bool(jnp.allclose(out2[:, 1:], pe[:, 1:]))


def test_pooling():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 9, 16), jnp.float32)
    assert global_pool_nlc(x, 'token').shape == (2, 16)
    assert bool(jnp.allclose(global_pool_nlc(x, 'avg', num_prefix_tokens=1), x[:, 1:].mean(1)))
    assert bool(jnp.allclose(global_pool_nlc(x, 'max', num_prefix_tokens=0), x.max(1)))
    g = jnp.asarray(np.random.RandomState(1).randn(2, 4, 4, 16), jnp.float32)
    assert SelectAdaptivePool2d(pool_type='avg')(g).shape == (2, 16)
    assert SelectAdaptivePool2d(pool_type='catavgmax')(g).shape == (2, 32)
    assert SelectAdaptivePool2d(pool_type='')(g).shape == g.shape


def test_mlp_variants():
    from timm_tpu.layers import GluMlp, SwiGLU
    rngs = nnx.Rngs(0)
    x = jnp.ones((2, 5, 32))
    assert Mlp(32, 64, rngs=rngs)(x).shape == (2, 5, 32)
    assert GluMlp(32, 64, rngs=rngs)(x).shape == (2, 5, 32)
    assert SwiGLU(32, 64, rngs=rngs)(x).shape == (2, 5, 32)


def test_layer_scale():
    ls = LayerScale(16, init_values=1e-4, rngs=nnx.Rngs(0))
    x = jnp.ones((2, 3, 16))
    assert bool(jnp.allclose(ls(x), x * 1e-4))


def test_sincos_pos_embed():
    from timm_tpu.layers import build_sincos2d_pos_embed
    emb = build_sincos2d_pos_embed((4, 4), dim=64)
    assert emb.shape == (16, 64)
    assert bool(jnp.isfinite(emb).all())


def test_rotary_embed():
    from timm_tpu.layers import RotaryEmbeddingCat
    from timm_tpu.layers.attention import apply_rot_embed_cat
    rope = RotaryEmbeddingCat(32, in_pixels=False, feat_shape=(4, 4))
    emb = rope.get_embed()
    assert emb.shape == (16, 64)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 16, 32), jnp.float32)
    out = apply_rot_embed_cat(x, emb)
    assert out.shape == x.shape
    # norm-preserving
    assert bool(jnp.allclose(jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(x, axis=-1), atol=1e-3))


def test_clip_grads():
    from timm_tpu.utils import adaptive_clip_grad, clip_grad_norm, clip_grad_value
    grads = {'a': jnp.full((4, 4), 10.0), 'b': jnp.full((4,), -10.0)}
    clipped, norm = clip_grad_norm(grads, 1.0)
    from timm_tpu.utils import global_grad_norm
    assert float(global_grad_norm(clipped)) == pytest.approx(1.0, abs=1e-3)
    clipped, _ = clip_grad_value(grads, 0.5)
    assert float(jnp.max(jnp.abs(clipped['a']))) == 0.5
    params = {'a': jnp.ones((4, 4)), 'b': jnp.ones((4,))}
    agc = adaptive_clip_grad(params, grads, clip_factor=0.01)
    assert float(jnp.abs(jax.tree.leaves(agc)[0]).max()) < 10.0


def test_attn_modules():
    from timm_tpu.layers import CbamModule, EcaModule, create_attn
    rngs = nnx.Rngs(0)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 64), jnp.float32)
    for name in ('se', 'ese', 'eca', 'cbam'):
        mod = create_attn(name, 64, rngs=rngs)
        assert mod(x).shape == x.shape
    assert create_attn(None, 64, rngs=rngs) is None
    with pytest.raises(ValueError):
        create_attn('bogus', 64, rngs=rngs)


def test_blur_pool():
    from timm_tpu.layers import BlurPool2d
    x = jnp.ones((1, 8, 8, 4))
    out = BlurPool2d(4)(x)
    assert out.shape == (1, 4, 4, 4)
    assert bool(jnp.allclose(out, 1.0, atol=1e-5))  # low-pass of constant = constant


def test_scaled_std_conv():
    from timm_tpu.layers import ScaledStdConv2d
    rngs = nnx.Rngs(0)
    conv = ScaledStdConv2d(8, 16, 3, rngs=rngs)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 8), jnp.float32)
    assert conv(x).shape == (2, 8, 8, 16)
    # kernel itself must stay unstandardized (standardization is call-time)
    w = conv.kernel[...]
    assert float(jnp.abs(w.mean(axis=(0, 1, 2))).max()) > 1e-4


def test_evo_norms():
    from timm_tpu.layers import EvoNorm2dB0, EvoNorm2dS0
    rngs = nnx.Rngs(0)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 64), jnp.float32)
    b0 = EvoNorm2dB0(64, rngs=rngs)
    assert b0(x).shape == x.shape
    rv_before = b0.running_var[...].copy()
    b0(x)
    assert not bool(jnp.allclose(rv_before, b0.running_var[...]))  # stats update
    s0 = EvoNorm2dS0(64, rngs=rngs)
    assert s0(x).shape == x.shape


def test_diff_attention_layer():
    from timm_tpu.layers import DiffAttention
    rngs = nnx.Rngs(0)
    attn = DiffAttention(64, num_heads=4, depth=3, rngs=rngs)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 10, 64), jnp.float32)
    out = attn(x)
    assert out.shape == (2, 10, 64)
    assert 0.2 < attn.lambda_init < 0.8


# ---- round-2 layer pack ----------------------------------------------------

def test_rel_pos_bias_shapes():
    from timm_tpu.layers import RelPosBias, RelPosMlp, gen_relative_position_index
    idx = gen_relative_position_index((4, 4))
    assert idx.shape == (16, 16) and idx.max() == 7 * 7 - 1 and idx.min() == 0
    idx_cls = gen_relative_position_index((4, 4), class_token=True)
    assert idx_cls.shape == (17, 17) and idx_cls.max() == 7 * 7 + 2
    rpb = RelPosBias(window_size=(4, 4), num_heads=3, rngs=nnx.Rngs(0))
    bias = rpb.get_bias()
    assert bias.shape == (1, 3, 16, 16)
    # relative bias must be symmetric under query/key swap of identical offsets
    attn = jnp.zeros((2, 3, 16, 16))
    out = rpb(attn)
    assert out.shape == attn.shape
    rpm = RelPosMlp(window_size=(4, 4), num_heads=3, mode='cr', rngs=nnx.Rngs(0))
    assert rpm.get_bias().shape == (1, 3, 16, 16)
    rpm_swin = RelPosMlp(window_size=(4, 4), num_heads=2, mode='swin', rngs=nnx.Rngs(0))
    assert rpm_swin.get_bias().shape == (1, 2, 16, 16)


def test_rel_pos_bias_translation_invariance():
    from timm_tpu.layers import RelPosBias
    rpb = RelPosBias(window_size=(3, 3), num_heads=1, rngs=nnx.Rngs(0))
    b = np.asarray(rpb.get_bias())[0, 0]
    # tokens 0→4 and 4→8 have the same relative offset (1,1): same bias value
    assert b[0, 4] == b[4, 8]
    assert b[1, 5] == b[4, 8]


def test_split_attn():
    from timm_tpu.layers import SplitAttn
    m = SplitAttn(16, radix=2, rngs=nnx.Rngs(0))
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    y = m(x)
    assert y.shape == (2, 8, 8, 16)
    m1 = SplitAttn(16, radix=1, rngs=nnx.Rngs(0))
    m1.eval()
    assert m1(x).shape == (2, 8, 8, 16)


def test_selective_kernel():
    from timm_tpu.layers import SelectiveKernel
    m = SelectiveKernel(16, 16, split_input=True, rngs=nnx.Rngs(0))
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    assert m(x).shape == (2, 8, 8, 16)


def test_selective_kernel_aa_drop_wired():
    from timm_tpu.layers import BlurPool2d, SelectiveKernel
    from timm_tpu.layers.drop import Dropout
    import functools
    m = SelectiveKernel(
        16, 16, stride=2, split_input=False,
        aa_layer=BlurPool2d,
        drop_layer=functools.partial(Dropout, 0.5, rngs=nnx.Rngs(7)),
        rngs=nnx.Rngs(0))
    # aa pool must actually be attached (conv strides 1, aa strides 2)
    assert all(p.aa is not None for p in m.paths)
    assert all(p.conv.strides == (1, 1) for p in m.paths)
    assert all(p.bn.drop is not None for p in m.paths)
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    assert m(x).shape == (2, 4, 4, 16)


def test_gather_excite_and_global_context():
    from timm_tpu.layers import GatherExcite, GlobalContext
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    for kwargs in (dict(extent=0), dict(extent=2), dict(extent=2, extra_params=True)):
        m = GatherExcite(16, **kwargs, rngs=nnx.Rngs(0))
        m.eval()
        assert m(x).shape == x.shape, kwargs
    gc = GlobalContext(16, rngs=nnx.Rngs(0))
    gc.eval()
    assert gc(x).shape == x.shape
    gca = GlobalContext(16, fuse_add=True, fuse_scale=False, rngs=nnx.Rngs(0))
    gca.eval()
    assert gca(x).shape == x.shape


def test_drop_block_2d_stats():
    from timm_tpu.layers import drop_block_2d
    x = jnp.ones((4, 16, 16, 8))
    key = jax.random.PRNGKey(0)
    y = drop_block_2d(x, key, drop_prob=0.2, block_size=5, scale_by_keep=False)
    dropped = float((y == 0).mean())
    assert 0.05 < dropped < 0.5  # roughly drop_prob worth of area zeroed
    # scale_by_keep keeps the expectation roughly constant
    y2 = drop_block_2d(x, key, drop_prob=0.2, block_size=5, scale_by_keep=True)
    assert abs(float(y2.mean()) - 1.0) < 0.05


def test_split_batchnorm_distinct_stats():
    from timm_tpu.layers import SplitBatchNormAct2d, convert_splitbn_model
    m = SplitBatchNormAct2d(8, num_splits=2, apply_act=False, rngs=nnx.Rngs(0))
    rng = np.random.RandomState(0)
    # first half ~N(0,1), second half ~N(4,1): aux stats should diverge
    x = np.concatenate([rng.randn(8, 4, 4, 8), rng.randn(8, 4, 4, 8) + 4.0]).astype(np.float32)
    m.train()
    m(jnp.asarray(x))
    # one EMA update at momentum 0.1: primary ≈ 0.1*0, aux ≈ 0.1*4
    assert float(m.mean[...].mean()) < 0.1
    assert float(m.aux_bn[0].mean[...].mean()) > 0.25
    # eval uses primary stats on the full batch
    m.eval()
    y = m(jnp.asarray(x))
    assert y.shape == x.shape

    # conversion walks a small model and swaps BN layers in place
    import timm_tpu
    model = timm_tpu.create_model('test_efficientnet', num_classes=10)
    convert_splitbn_model(model, num_splits=2)
    found = []

    def walk(mod):
        for v in vars(mod).values():
            if isinstance(v, SplitBatchNormAct2d):
                found.append(v)
            elif isinstance(v, nnx.List):
                for it in v:
                    if isinstance(it, SplitBatchNormAct2d):
                        found.append(it)
                    elif isinstance(it, nnx.Module):
                        walk(it)
            elif isinstance(v, nnx.Module):
                walk(v)
    walk(model)
    assert found, 'no BN layers converted'


def test_split_batchnorm_plain_no_act():
    from timm_tpu.layers import SplitBatchNorm2d
    m = SplitBatchNorm2d(8, num_splits=2, rngs=nnx.Rngs(0))
    m.train()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 4, 4, 8), jnp.float32)
    y = m(x)
    # plain BN: negative outputs survive (no hidden relu)
    assert float(y.min()) < 0.0


def test_filter_response_norm():
    from timm_tpu.layers import FilterResponseNormAct2d, FilterResponseNormTlu2d
    x = jnp.asarray(np.random.RandomState(0).rand(2, 6, 6, 8) * 3, jnp.float32)
    y = FilterResponseNormAct2d(8, rngs=nnx.Rngs(0))(x)
    assert y.shape == x.shape and float(y.min()) >= 0.0  # relu applied
    y2 = FilterResponseNormTlu2d(8, rngs=nnx.Rngs(0))(x)
    assert y2.shape == x.shape


def test_cond_conv2d_routing():
    from timm_tpu.layers import CondConv2d
    m = CondConv2d(8, 16, 3, num_experts=4, bias=True, rngs=nnx.Rngs(0))
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 8), jnp.float32)
    r_a = jax.nn.softmax(jnp.asarray([[1.0, 0, 0, 0], [0, 1.0, 0, 0]]) * 10)
    y = m(x, r_a)
    assert y.shape == (2, 8, 8, 16)
    # different routing → different outputs for the same input
    r_b = jax.nn.softmax(jnp.asarray([[0, 0, 1.0, 0], [0, 0, 0, 1.0]]) * 10)
    assert not np.allclose(np.asarray(y), np.asarray(m(x, r_b)))
    # padding=None resolves like create_conv2d (same-when-stride-1), and
    # unknown strings raise instead of silently meaning VALID
    m2 = CondConv2d(8, 16, 3, padding=None, rngs=nnx.Rngs(0))
    assert m2(x, r_a[:, :4]).shape == (2, 8, 8, 16)
    with pytest.raises(ValueError):
        CondConv2d(8, 16, 3, padding='samee', rngs=nnx.Rngs(0))


def test_mixed_conv2d():
    from timm_tpu.layers import MixedConv2d
    m = MixedConv2d(16, 16, kernel_size=[3, 5], depthwise=True, rngs=nnx.Rngs(0))
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    assert m(x).shape == (2, 8, 8, 16)


def test_test_time_pool_head():
    import timm_tpu
    from timm_tpu.layers import TestTimePoolHead
    model = timm_tpu.create_model('test_efficientnet', num_classes=10)
    model.eval()
    wrapped = TestTimePoolHead(model, original_pool=2)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 96, 96, 3), jnp.float32)
    out = wrapped(x)
    assert out.shape == (2, 10)


def test_create_attn_new_modules():
    from timm_tpu.layers import create_attn
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    for name in ('ge', 'gc', 'splat', 'sk'):
        m = create_attn(name, 16, rngs=nnx.Rngs(0))
        m.eval()
        assert m(x).shape == x.shape, name


def test_radix_softmax_cardinality_order():
    """radix weights must be radix-major after flatten so the caller's
    (B, radix, C) reshape picks weights for the right cardinal group."""
    from timm_tpu.layers.split_attn import radix_softmax
    B, card, radix, ch = 1, 2, 2, 3
    logits = jnp.arange(card * radix * ch, dtype=jnp.float32).reshape(1, 1, 1, -1) * 100
    out = radix_softmax(logits, radix, card).reshape(B, radix, card * ch)
    # within each (card, ch) column the two radix entries sum to 1
    sums = np.asarray(out.sum(axis=1))
    assert np.allclose(sums, 1.0, atol=1e-5)


def test_split_attn_groups():
    from timm_tpu.layers import SplitAttn
    m = SplitAttn(16, radix=2, groups=2, rngs=nnx.Rngs(0))
    m.eval()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 16), jnp.float32)
    assert m(x).shape == (2, 8, 8, 16)


# The hard-coded-fp32-softmax lint is now the analysis rule `fp32-softmax`
# (timm_tpu/analysis/source_rules.py), enforced by tests/test_analysis.py.
