"""Serving engine tests: bucketing, admission queue, AOT prewarm + compile
cache, padded-slot handling, LRU residency, drain semantics, load drill."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from timm_tpu.serve import (
    InferenceEngine, RequestQueue, batch_bucket, pad_rows, select_bucket,
    strip_rows, validate_buckets,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.serve


# ---- 1. bucket selection -----------------------------------------------------

def test_select_bucket_smallest_fitting():
    buckets = (1, 4, 16, 64, 256)
    assert select_bucket(1, buckets) == 1
    assert select_bucket(2, buckets) == 4
    assert select_bucket(4, buckets) == 4
    assert select_bucket(5, buckets) == 16
    assert select_bucket(17, buckets) == 64
    assert select_bucket(256, buckets) == 256


def test_select_bucket_rejects_out_of_range():
    with pytest.raises(ValueError, match='largest declared bucket'):
        select_bucket(257, (1, 4, 16, 64, 256))
    with pytest.raises(ValueError):
        select_bucket(0, (1, 4))


def test_validate_buckets():
    assert validate_buckets((16, 4, 4, 1)) == (1, 4, 16)
    with pytest.raises(ValueError, match='at least one'):
        validate_buckets(())
    with pytest.raises(ValueError, match='positive'):
        validate_buckets((0, 4))
    # mesh divisibility is checked at construction, not serve time
    with pytest.raises(ValueError, match='not divisible'):
        validate_buckets((1, 4, 16), divisor=8)
    assert validate_buckets((8, 16), divisor=8) == (8, 16)


def test_batch_bucket_rounds_to_shard_count():
    assert batch_bucket(256, 1) == 256
    assert batch_bucket(100, 8) == 104
    assert batch_bucket(8, 8) == 8
    assert batch_bucket(1, 8) == 8


def test_engine_rejects_indivisible_buckets():
    from timm_tpu.parallel import create_mesh
    mesh = create_mesh()  # all 8 virtual CPU devices
    assert mesh.size == 8
    with pytest.raises(ValueError, match='not divisible'):
        InferenceEngine(buckets=(1, 4), mesh=mesh)


# ---- 2. padding / stripping --------------------------------------------------

def test_pad_rows_and_strip_rows():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = np.array([7, 8, 9])
    xp, tp, valid = pad_rows(x, 8, t)
    assert xp.shape == (8, 4) and tp.shape == (8,)
    assert valid.tolist() == [True] * 3 + [False] * 5
    # padded slots repeat row 0 (finite, in-distribution — not zeros/NaN)
    assert np.array_equal(xp[3:], np.repeat(x[:1], 5, axis=0))
    np.testing.assert_array_equal(strip_rows(xp, 3), x)
    # exact fit: arrays pass through unchanged
    xs, v2 = pad_rows(x, 3)
    assert xs is x and v2.all()
    with pytest.raises(ValueError, match='does not fit'):
        pad_rows(x, 2)


# ---- 3. admission queue ------------------------------------------------------

def test_queue_full_bucket_admitted_immediately():
    q = RequestQueue(max_bucket=4, max_wait_s=10.0)  # deadline far away
    for _ in range(4):
        q.submit('m', np.zeros(2))
    t0 = time.perf_counter()
    model, reqs = q.wait_admission(timeout=5.0)
    assert model == 'm' and len(reqs) == 4
    assert time.perf_counter() - t0 < 1.0  # did NOT wait for the deadline


def test_queue_never_starves_past_deadline():
    """A partial run is admitted once its oldest request's deadline expires —
    a lone request never waits for batch-mates that aren't coming."""
    q = RequestQueue(max_bucket=64, max_wait_s=0.03)
    for _ in range(3):
        q.submit('m', np.zeros(2))
    t0 = time.perf_counter()
    admission = q.wait_admission(timeout=2.0)
    waited = time.perf_counter() - t0
    assert admission is not None, 'request starved past its deadline'
    model, reqs = admission
    assert len(reqs) == 3  # partial: far fewer than max_bucket
    assert 0.02 <= waited < 1.0, f'deadline admission took {waited:.3f}s'


def test_queue_oldest_model_first():
    q = RequestQueue(max_bucket=8, max_wait_s=0.0)  # everything ready at once
    q.submit('b', np.zeros(2), now=1.0)
    q.submit('a', np.zeros(2), now=2.0)
    q.submit('b', np.zeros(2), now=3.0)
    model, reqs = q.wait_admission(timeout=1.0)
    assert model == 'b' and len(reqs) == 2  # oldest head wins, run coalesces
    model, reqs = q.wait_admission(timeout=1.0)
    assert model == 'a' and len(reqs) == 1


def test_queue_close_without_drain_fails_pending():
    q = RequestQueue(max_bucket=4, max_wait_s=10.0)
    fut = q.submit('m', np.zeros(2))
    q.close(drain=False)
    with pytest.raises(RuntimeError, match='shut down'):
        fut.result(timeout=1.0)
    with pytest.raises(RuntimeError, match='no new requests'):
        q.submit('m', np.zeros(2))
    assert q.wait_admission(timeout=0.1) is None and q.finished()


def test_queue_capacity_sheds_load():
    q = RequestQueue(max_bucket=4, max_wait_s=10.0, max_pending=2)
    q.submit('m', np.zeros(2))
    q.submit('m', np.zeros(2))
    with pytest.raises(RuntimeError, match='over capacity'):
        q.submit('m', np.zeros(2))


# ---- 4. engine end-to-end (single device, in-process) ------------------------

@pytest.fixture(scope='module')
def engine():
    eng = InferenceEngine(buckets=(2, 4), max_wait_ms=10.0)
    eng.add_model('test_vit', img_size=32)
    eng.start()
    yield eng
    eng.shutdown(drain=True)


def test_engine_padded_slot_outputs_dropped(engine):
    """3 requests into the 4-bucket: every caller gets its own row back and
    the padded slot's output goes nowhere."""
    import jax.numpy as jnp
    from flax import nnx

    rng = np.random.RandomState(0)
    imgs = rng.standard_normal((3, 32, 32, 3)).astype(np.float32)
    before = dict(engine.stats)
    futs = [engine.submit(im) for im in imgs]
    rows = [f.result(timeout=120.0) for f in futs]
    assert all(r.ndim == 1 for r in rows)
    assert engine.stats['padded_slots'] > before['padded_slots']

    # padding must not change the answer: compare against a direct forward
    res = engine.pool.acquire('test_vit')
    direct = np.asarray(nnx.merge(res.graphdef, res.state)(jnp.asarray(imgs)))
    np.testing.assert_allclose(np.stack(rows), direct, atol=1e-5, rtol=1e-5)


def test_engine_only_declared_buckets_dispatch(engine):
    futs = [engine.submit(np.zeros((32, 32, 3), np.float32)) for _ in range(7)]
    for f in futs:
        f.result(timeout=120.0)
    assert set(engine.stats['steps_by_bucket']) <= set(engine.buckets)


def test_engine_bad_input_shape_fails_that_request(engine):
    fut = engine.submit(np.zeros((16, 16, 3), np.float32))  # wrong image size
    with pytest.raises(Exception):
        fut.result(timeout=120.0)
    # the engine survives: a good request still completes
    ok = engine.submit(np.zeros((32, 32, 3), np.float32))
    assert ok.result(timeout=120.0).ndim == 1


def test_engine_submit_requires_start():
    eng = InferenceEngine(buckets=(2,))
    with pytest.raises(RuntimeError, match='start'):
        eng.submit(np.zeros((32, 32, 3), np.float32))


def test_engine_clean_drain_on_shutdown():
    """Requests in the queue at shutdown(drain=True) all complete."""
    eng = InferenceEngine(buckets=(2, 4), max_wait_ms=10_000.0)  # deadline far off
    eng.add_model('test_vit', img_size=32)
    eng.start()
    # 5 requests: one full 4-bucket + a 1-remainder that only drain can flush
    futs = [eng.submit(np.zeros((32, 32, 3), np.float32)) for _ in range(5)]
    eng.shutdown(drain=True)
    for f in futs:
        assert f.result(timeout=1.0).ndim == 1  # already done; no waiting
    stats = eng.snapshot_stats()
    assert stats['completed'] == 5 and stats['failed'] == 0
    assert eng.pending() == 0


# ---- 5. LRU residency / HBM budget -------------------------------------------

def test_lru_eviction_respects_hbm_budget():
    eng = InferenceEngine(buckets=(2,), hbm_budget_bytes=None)
    eng.add_model('test_vit', img_size=32, prewarm=False)
    eng.add_model('test_vit2', img_size=32, prewarm=False)
    a = eng.pool.acquire('test_vit')
    # budget fits exactly one of the pair
    eng.pool.budget_bytes = int(1.25 * a.param_bytes)
    eng.pool.acquire('test_vit2')
    assert eng.pool.resident_names == ('test_vit2',), 'LRU victim not evicted'
    assert eng.pool.stats['evictions'] == 1
    assert eng.pool.resident_bytes() <= eng.pool.budget_bytes
    # re-acquiring the victim reloads it and evicts the other way
    eng.pool.acquire('test_vit')
    assert eng.pool.resident_names == ('test_vit',)
    assert eng.pool.stats['evictions'] == 2


def test_eviction_keeps_oversized_model():
    """A single model larger than the whole budget is kept (with a warning),
    not evict-looped into a livelock."""
    eng = InferenceEngine(buckets=(2,), hbm_budget_bytes=1)  # absurd budget
    eng.add_model('test_vit', img_size=32, prewarm=False)
    res = eng.pool.acquire('test_vit')
    assert res.param_bytes > 1
    assert eng.pool.resident_names == ('test_vit',)


def test_executables_survive_weight_eviction():
    """AOT programs hold code, not parameters: re-admitting an evicted model
    must not recompile (the exec cache hit is the reload fast path)."""
    eng = InferenceEngine(buckets=(2,))
    eng.add_model('test_vit', img_size=32)
    from timm_tpu.perfbudget import check_counter

    first = dict(eng.pool.acquire('test_vit').prewarm_stats)
    eng.pool.evict('test_vit')
    second = dict(eng.pool.acquire('test_vit').prewarm_stats)
    check_counter('first admit exec_cache_hits', first['exec_cache_hits'], 0)
    check_counter('re-admit exec_cache_hits', second['exec_cache_hits'], len(eng.buckets))
    check_counter('re-admit fresh_compiles', second['fresh_compiles'], 0)


# ---- 6. AOT warmup × persistent compile cache (two cold processes) -----------

_AOT_PROBE = r'''
import json, sys
from timm_tpu.serve import InferenceEngine
eng = InferenceEngine(buckets=(2, 4), persist_all_programs=True)
eng.add_model('test_vit', img_size=32)
print('PREWARM ' + json.dumps(eng.stats['prewarm']['test_vit']))
'''


@pytest.mark.serve
def test_aot_warmup_hits_compile_cache_on_second_startup(tmp_path):
    """Acceptance: the second engine startup performs ZERO fresh XLA compiles
    for pre-declared buckets — every bucket program comes back from the
    persistent compile cache (observed via JAX's cache-hit events)."""
    cache_dir = str(tmp_path / 'serve_xla_cache')
    env = dict(os.environ, JAX_PLATFORMS='cpu', TIMM_TPU_COMPILE_CACHE=cache_dir)
    env.pop('XLA_FLAGS', None)  # single-device probe processes, cheap compiles

    def startup():
        r = subprocess.run([sys.executable, '-c', _AOT_PROBE], env=env,
                           cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        line = [l for l in r.stdout.splitlines() if l.startswith('PREWARM ')][-1]
        return json.loads(line[len('PREWARM '):])

    from timm_tpu.perfbudget import check_counter, check_counter_min

    cold = startup()
    check_counter('cold startup programs', cold['programs'], 2)
    check_counter('cold startup fresh_compiles', cold['fresh_compiles'], 2)
    assert os.listdir(cache_dir), 'cold startup persisted no executables'
    warm = startup()
    check_counter('warm startup fresh_compiles', warm['fresh_compiles'], 0)
    check_counter_min('warm startup cache_hits', warm['cache_hits'], warm['programs'])


# ---- 7. sharded serving (8-device subprocess drill) --------------------------

@pytest.mark.serve
def test_sharded_serving_matches_single_device(tmp_path):
    """fsdp_drill serve8: an engine on a ('data','fsdp')=(2,4) 8-device mesh
    loads the same mesh-shape-agnostic checkpoint as a single-device engine
    and serves identical logits (≤1e-5) for identical requests."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', TIMM_TPU_DRILL_DEVICES='8',
               XLA_FLAGS='--xla_force_host_platform_device_count=8')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, 'tests', 'fsdp_drill.py'),
         'serve8', str(tmp_path)],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]
    d = json.loads(r.stdout.strip().splitlines()[-1])
    assert d['devices'] == 8 and d['mesh'] == [2, 4]
    assert d['param_sharded_over_fsdp'] is True
    assert set(map(int, d['steps_by_bucket'])) == {8}  # one declared bucket
    assert d['logits_max_diff'] <= 1e-5, d


# ---- 8. load-drill subprocess smoke ------------------------------------------

@pytest.mark.serve
def test_bench_serve_drill_smoke():
    """`bench.py --serve --dry-run`: canonical A/B drill (two buckets, two
    models, eviction) prints the p50/p99 summary line and a result line whose
    value is the continuous-vs-per-request speedup (> 1.0 by acceptance)."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)  # single-device: the drill engine is one replica
    r = subprocess.run(
        [sys.executable, 'bench.py', '--serve', '--dry-run'],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    lines = r.stdout.strip().splitlines()
    assert any(l.startswith('serve-drill:') and 'p50' in l and 'p99' in l
               for l in lines), lines
    result = json.loads(lines[-1])
    assert result['unit'] == 'x img/s vs per-request'
    assert result['value'] > 1.0, result
    assert 'eviction' in result['metric']
