"""Scheduler tests (reference: tests/test_scheduler.py — warmup, cycles,
noise determinism, k-decay, state_dict round-trip, per-update stepping)."""
import math

import pytest

from timm_tpu.scheduler import (
    CosineLRScheduler, MultiStepLRScheduler, PlateauLRScheduler, PolyLRScheduler,
    StepLRScheduler, TanhLRScheduler, create_scheduler_v2,
)


def test_cosine_warmup_and_decay():
    sch = CosineLRScheduler(1.0, t_initial=10, warmup_t=2, warmup_lr_init=0.1, lr_min=0.0)
    lrs = [sch.step(t)[0] for t in range(10)]
    assert lrs[0] == pytest.approx(0.1)
    assert lrs[1] == pytest.approx(0.55)
    assert lrs[2] == pytest.approx(1.0 * 0.5 * (1 + math.cos(math.pi * 2 / 10)))
    assert lrs[-1] < lrs[2]


def test_cosine_cycles():
    sch = CosineLRScheduler(1.0, t_initial=5, cycle_limit=2, cycle_decay=0.5)
    lrs = [sch.step(t)[0] for t in range(10)]
    assert lrs[0] == pytest.approx(1.0)
    assert lrs[5] == pytest.approx(0.5)  # second cycle peak decayed


def test_cosine_k_decay():
    sch1 = CosineLRScheduler(1.0, t_initial=10, k_decay=1.0)
    sch2 = CosineLRScheduler(1.0, t_initial=10, k_decay=2.0)
    # higher k decays slower early
    assert sch2.step(3)[0] > sch1.step(3)[0]


def test_per_update_stepping():
    sch = CosineLRScheduler(1.0, t_initial=100, t_in_epochs=False)
    lr_epoch = sch.step(5)
    assert lr_epoch == sch.get_last_lr()  # epoch stepping inert
    lr_up = sch.step_update(50)[0]
    assert lr_up == pytest.approx(0.5, abs=1e-2)


def test_noise_determinism():
    a = CosineLRScheduler(1.0, t_initial=10, noise_range_t=0, noise_seed=7)
    b = CosineLRScheduler(1.0, t_initial=10, noise_range_t=0, noise_seed=7)
    for t in range(10):
        assert a.step(t) == b.step(t)


def test_state_dict_roundtrip():
    a = PlateauLRScheduler(1.0, decay_rate=0.5, patience_t=1)
    for e in range(5):
        a.step(e, metric=1.0)  # no improvement → decays
    sd = a.state_dict()
    b = PlateauLRScheduler(1.0)
    b.load_state_dict(sd)
    assert b.step(6, metric=1.0) == a.step(6, metric=1.0)


def test_plateau_decays_on_stall():
    sch = PlateauLRScheduler(1.0, decay_rate=0.1, patience_t=2, warmup_t=0, mode='max')
    lrs = [sch.step(e, metric=0.5)[0] for e in range(8)]
    assert lrs[0] == 1.0
    assert lrs[-1] < 1.0


def test_step_multistep_poly_tanh():
    s = StepLRScheduler(1.0, decay_t=2, decay_rate=0.5, warmup_t=0)
    assert s.step(0)[0] == 1.0 and s.step(2)[0] == 0.5 and s.step(4)[0] == 0.25
    m = MultiStepLRScheduler(1.0, decay_t=[2, 4], decay_rate=0.1, warmup_t=0)
    assert m.step(0)[0] == 1.0 and m.step(2)[0] == pytest.approx(0.1) and m.step(4)[0] == pytest.approx(0.01)
    p = PolyLRScheduler(1.0, t_initial=10, power=1.0, warmup_t=0)
    assert p.step(5)[0] == pytest.approx(0.5)
    t = TanhLRScheduler(1.0, t_initial=10, warmup_t=0)
    assert t.step(9)[0] < 0.1


def test_factory():
    sch, n = create_scheduler_v2(base_lr=0.1, sched='cosine', num_epochs=10, warmup_epochs=2, cooldown_epochs=3)
    assert n == 13
    sch, n = create_scheduler_v2(base_lr=0.1, sched='cosine', num_epochs=10, step_on_epochs=False, updates_per_epoch=100)
    assert sch.step_update(500)[0] == pytest.approx(0.05, abs=1e-3)
    with pytest.raises(ValueError):
        create_scheduler_v2(sched='bogus')
