"""Fault-tolerance subsystem tests (timm_tpu/resilience): durable checkpoint
verification + fallback, recovery ordering, non-finite sentinel, reader
retry/skip policy, fault injection, and the SIGTERM→`--resume auto` parity
drill on a tiny CPU model."""
import os
import subprocess
import sys

import numpy as np
import pytest

from timm_tpu.resilience import (
    CorruptCheckpointError, FaultInjector, NonFiniteError, SkipBudget,
    TooManyBadSamples, atomic_write_npz, backoff_delays, capture_host_rng,
    fault_selftest, find_checkpoints, load_with_fallback, resolve_auto_resume,
    restore_host_rng, retry_io, verify_checkpoint,
)

pytestmark = pytest.mark.resilience

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- durable checkpoints -----------------------------------------------------

def test_atomic_write_verify_roundtrip(tmp_path):
    path = str(tmp_path / 'last.npz')
    arrays = {'state_dict.w': np.arange(16.0).reshape(4, 4), 'epoch': np.asarray(3)}
    atomic_write_npz(path, arrays, meta={'epoch': 3})
    ok, reason = verify_checkpoint(path)
    assert ok, reason
    state, meta, used = load_with_fallback(path)
    assert used == path and meta['epoch'] == 3
    np.testing.assert_array_equal(state['state_dict.w'], arrays['state_dict.w'])
    # no temp litter from the atomic write
    assert not [n for n in os.listdir(tmp_path) if n.endswith('.tmp')]


def test_manifest_detects_bit_corruption(tmp_path):
    """A flipped byte INSIDE a structurally-valid zip only the manifest catches."""
    path = str(tmp_path / 'last.npz')
    atomic_write_npz(path, {'w': np.zeros(64, np.float32)}, meta={})
    data = bytearray(open(path, 'rb').read())
    # flip a byte in the middle of the (uncompressed) array payload
    data[len(data) // 2] ^= 0xFF
    open(path, 'wb').write(bytes(data))
    ok, reason = verify_checkpoint(path)
    assert not ok and ('sha256' in reason or 'unreadable' in reason)


def test_truncated_checkpoint_falls_back_to_newest_valid(tmp_path):
    older = str(tmp_path / 'checkpoint-0.npz')
    newest = str(tmp_path / 'checkpoint-1.npz')
    atomic_write_npz(older, {'w': np.ones(8)}, meta={'epoch': 0})
    atomic_write_npz(newest, {'w': np.full(8, 2.0)}, meta={'epoch': 1})
    with open(newest, 'r+b') as f:
        f.truncate(os.path.getsize(newest) // 2)
    ok, _ = verify_checkpoint(newest)
    assert not ok
    state, _meta, used = load_with_fallback(newest, search_dir=str(tmp_path))
    assert used == older
    np.testing.assert_array_equal(state['w'], np.ones(8))
    with pytest.raises(CorruptCheckpointError):
        with open(older, 'r+b') as f:
            f.truncate(8)
        load_with_fallback(newest, search_dir=str(tmp_path))


def test_checkpoint_ordering_numeric_not_lexicographic(tmp_path):
    # the seed bug: sorted() ranked recovery-1-999 above recovery-1-1000
    for epoch, batch in [(1, 999), (1, 1000), (0, 5)]:
        atomic_write_npz(str(tmp_path / f'recovery-{epoch}-{batch}.npz'),
                         {'w': np.asarray(float(batch))}, meta={'epoch': epoch})
    names = [os.path.basename(p) for p in find_checkpoints(str(tmp_path))]
    assert names[0] == 'recovery-1-1000.npz'
    assert names.index('recovery-1-1000.npz') < names.index('recovery-1-999.npz')
    # a completed epoch 1 outranks any mid-epoch-1 recovery
    atomic_write_npz(str(tmp_path / 'last.npz'),
                     {'w': np.asarray(0.0), 'epoch': np.asarray(1)}, meta={'epoch': 1})
    assert os.path.basename(find_checkpoints(str(tmp_path))[0]) == 'last.npz'
    assert resolve_auto_resume(str(tmp_path)).endswith('last.npz')


def test_saver_find_recovery_and_startup_cleanup(tmp_path):
    from timm_tpu.utils import CheckpointSaver
    d = str(tmp_path)
    atomic_write_npz(os.path.join(d, 'recovery-1-999.npz'), {'w': np.asarray(1.0)})
    atomic_write_npz(os.path.join(d, 'recovery-1-1000.npz'), {'w': np.asarray(2.0)})
    # orphaned tmp artifacts + a corrupt recovery file from a "crash"
    open(os.path.join(d, 'tmp.npz'), 'wb').write(b'partial')
    open(os.path.join(d, '.last.npz.123.tmp'), 'wb').write(b'partial')
    open(os.path.join(d, 'recovery-1-2000.npz'), 'wb').write(b'torn write')
    saver = CheckpointSaver(task=None, checkpoint_dir=d, recovery_dir=d)
    names = set(os.listdir(d))
    assert 'tmp.npz' not in names and '.last.npz.123.tmp' not in names
    assert 'recovery-1-2000.npz' not in names  # corrupt → swept
    assert saver.find_recovery().endswith('recovery-1-1000.npz')


# -- non-finite sentinel -----------------------------------------------------

@pytest.fixture(scope='module')
def tiny_task(mesh8):
    import timm_tpu
    from timm_tpu.loss import LabelSmoothingCrossEntropy
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3)
    return ClassificationTask(
        model, optimizer=opt, mesh=mesh8,
        train_loss_fn=LabelSmoothingCrossEntropy(0.1), nonfinite_tolerance=3)


def _batch(mesh, nan=False, seed=0):
    import jax.numpy as jnp
    from timm_tpu.parallel import shard_batch
    rng = np.random.RandomState(seed)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    if nan:
        x = x * np.nan
    return shard_batch({'input': jnp.asarray(x), 'target': jnp.asarray(rng.randint(0, 10, 8))},
                       mesh)


def test_nonfinite_step_commits_nothing(mesh8, tiny_task):
    import jax
    from flax import nnx
    tiny_task.reset_nonfinite()
    tiny_task.train_step(_batch(mesh8), lr=1e-3, step=0)
    before = [np.asarray(p) for p in jax.tree.leaves(nnx.state(tiny_task.model, nnx.Param))]
    opt_before = [np.asarray(l) for l in jax.tree.leaves(tiny_task.opt_state)]
    metrics = tiny_task.train_step(_batch(mesh8, nan=True), lr=1e-3, step=1)
    assert int(metrics['nonfinite_count']) == 1 and int(metrics['nonfinite_total']) == 1
    after = [np.asarray(p) for p in jax.tree.leaves(nnx.state(tiny_task.model, nnx.Param))]
    opt_after = [np.asarray(l) for l in jax.tree.leaves(tiny_task.opt_state)]
    assert all(np.array_equal(a, b) for a, b in zip(before, after))
    assert all(np.array_equal(a, b) for a, b in zip(opt_before, opt_after))
    # a good step resets the consecutive counter (total stays)
    metrics = tiny_task.train_step(_batch(mesh8), lr=1e-3, step=2)
    assert int(metrics['nonfinite_count']) == 0 and int(metrics['nonfinite_total']) == 1


def test_nonfinite_tolerance_aborts(mesh8, tiny_task):
    tiny_task.reset_nonfinite()
    with pytest.raises(NonFiniteError) as ei:
        for step in range(5):
            tiny_task.train_step(_batch(mesh8, nan=True), lr=1e-3, step=step)
    assert ei.value.consecutive == 3  # tolerance from the fixture
    tiny_task.reset_nonfinite()


# -- retry / skip policy -----------------------------------------------------

def test_retry_io_backoff_then_success():
    sleeps = []
    calls = {'n': 0}

    def flaky():
        calls['n'] += 1
        if calls['n'] < 3:
            raise IOError('transient')
        return 'ok'

    assert retry_io(flaky, retries=3, base_delay=0.1, jitter=0.5,
                    sleep=sleeps.append) == 'ok'
    assert calls['n'] == 3 and len(sleeps) == 2
    # jittered exponential: each delay within ±50% of base*2^i, capped
    assert 0.05 <= sleeps[0] <= 0.15 and 0.1 <= sleeps[1] <= 0.3


def test_retry_io_exhaustion_and_poison_passthrough():
    with pytest.raises(IOError):
        retry_io(lambda: (_ for _ in ()).throw(IOError('down')),
                 retries=2, base_delay=0.0, sleep=lambda s: None)
    calls = {'n': 0}

    def poison():
        calls['n'] += 1
        raise ValueError('bad record')

    with pytest.raises(ValueError):
        retry_io(poison, retries=3, base_delay=0.0, sleep=lambda s: None)
    assert calls['n'] == 1  # non-transient: no retries


def test_backoff_delays_bounded():
    ds = list(backoff_delays(6, base_delay=0.1, max_delay=1.0, jitter=0.0))
    assert ds == [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]


def test_skip_budget():
    b = SkipBudget(budget=2)
    b.record(ValueError('x'), 'a')
    b.record(ValueError('x'), 'b')
    with pytest.raises(TooManyBadSamples):
        b.record(ValueError('x'), 'c')


class _FlakyDataset:
    """Map-style dataset where some indices are poison (undecodable)."""

    def __init__(self, n=12, bad=()):
        self.n, self.bad = n, set(bad)

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        if idx in self.bad:
            raise ValueError(f'undecodable sample {idx}')
        return np.full((4, 4, 3), idx, np.float32), idx


def test_loader_skips_poison_within_budget(monkeypatch):
    from timm_tpu.data.loader import ThreadedLoader
    monkeypatch.setenv('TIMM_TPU_POISON_BUDGET', '4')
    loader = ThreadedLoader(_FlakyDataset(12, bad={3, 7}), batch_size=4,
                            is_training=False, num_workers=2)
    batches = list(loader)
    got = sorted(int(t) for _x, ts in batches for t in ts)
    assert got == [i for i in range(12) if i not in (3, 7)]  # order kept, poison dropped


def test_loader_budget_exhaustion_fails_loudly(monkeypatch):
    from timm_tpu.data.loader import ThreadedLoader
    monkeypatch.setenv('TIMM_TPU_POISON_BUDGET', '1')
    loader = ThreadedLoader(_FlakyDataset(12, bad={1, 2, 5}), batch_size=4,
                            is_training=False, num_workers=2)
    with pytest.raises(TooManyBadSamples):
        list(loader)


# -- fault injection ----------------------------------------------------------

def test_fault_injector_spec_parse():
    fi = FaultInjector('truncate_ckpt, nan_grads@4:2, sigterm@9, io_error%3')
    assert fi.take('truncate_ckpt') and not fi.take('truncate_ckpt')
    assert not fi.nan_at(3) and fi.nan_at(4) and fi.nan_at(5) and not fi.nan_at(6)
    assert fi.sigterm_at(9) and not fi.sigterm_at(9)
    assert [fi.io_error_tick() for _ in range(6)] == [False, False, True, False, False, True]
    assert not FaultInjector('')
    with pytest.raises(ValueError):
        FaultInjector('explode@3')


def test_fault_selftest_all_checks_pass(tmp_path):
    result = fault_selftest('truncate_ckpt,nan_grads@1,io_error%2',
                            tmp_dir=str(tmp_path))
    assert result['ok'], result


def test_bench_dry_run_fault_inject_smoke():
    """`bench.py --dry-run --fault-inject` exercises the injection hooks in
    tier-1 without a slow run (in-process, same idiom as
    test_precision_policy's dry-run sweep)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'bench_resilience', os.path.join(REPO_ROOT, 'bench.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Args:
        model = 'test_vit'
        img_size = 32
        pad_tokens = ''
        softmax_dtype = ''
        norm_dtype = ''
        mu_dtype = ''
        fault_inject = 'truncate_ckpt,io_error%2,nan_grads@1:2,sigterm@3'

    assert bench._dry_run(Args()) == 0


# -- host RNG capture ---------------------------------------------------------

def test_host_rng_capture_restore_bit_identical():
    np.random.seed(123)
    import random as pyrandom
    pyrandom.seed(321)
    np.random.rand(7)  # advance the streams off the seed point
    pyrandom.random()
    snap = capture_host_rng()
    expect_np = np.random.rand(16)
    expect_py = [pyrandom.random() for _ in range(4)]
    np.random.rand(99)  # diverge
    pyrandom.random()
    assert restore_host_rng(snap)
    np.testing.assert_array_equal(np.random.rand(16), expect_np)
    assert [pyrandom.random() for _ in range(4)] == expect_py


def test_load_state_dict_rejects_corrupt_npz(tmp_path):
    from timm_tpu.models import load_checkpoint
    import timm_tpu
    path = str(tmp_path / 'weights.npz')
    atomic_write_npz(path, {'w': np.ones(4)})
    with open(path, 'r+b') as f:
        f.truncate(16)
    model = timm_tpu.create_model('test_vit', num_classes=5)
    with pytest.raises(CorruptCheckpointError):
        load_checkpoint(model, path)


# -- end-to-end CPU drills (subprocess train.py) ------------------------------

def _train_cmd(out_dir, experiment, *extra):
    return [
        sys.executable, os.path.join(REPO_ROOT, 'train.py'),
        '--synthetic-data', '--model', 'test_vit', '--img-size', '32', '-b', '8',
        '--synthetic-len', '64', '--epochs', '1', '--opt', 'sgd', '--lr', '0.05',
        '--sched', 'cosine', '--warmup-epochs', '0', '--workers', '1',
        '--log-interval', '50', '--output', str(out_dir), '--experiment', experiment,
        *extra,
    ]


def _run(cmd):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO_ROOT, timeout=240)


def _params(path):
    with np.load(path, allow_pickle=False) as d:
        return {k: d[k] for k in d.files if k.startswith(('state_dict.', 'optimizer.'))}


def test_sigterm_resume_parity(tmp_path):
    """Acceptance drill (b): a run killed by SIGTERM mid-epoch and restarted
    with `--resume auto` ends bit-identical to an uninterrupted run."""
    r = _run(_train_cmd(tmp_path, 'base'))
    assert r.returncode == 0, r.stderr[-2000:]
    # interrupted run: injected SIGTERM after update 3 → recovery + exit 0
    r = _run(_train_cmd(tmp_path, 'pre', '--fault-inject', 'sigterm@3'))
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'recovery-0-3.npz' in os.listdir(tmp_path / 'pre'), r.stderr[-2000:]
    r = _run(_train_cmd(tmp_path, 'pre', '--resume', 'auto'))
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'Resumed mid-epoch' in r.stderr

    base = _params(tmp_path / 'base' / 'last.npz')
    resumed = _params(tmp_path / 'pre' / 'last.npz')
    assert set(base) == set(resumed)
    mismatched = [k for k in base if not np.array_equal(base[k], resumed[k])]
    assert not mismatched, f'{len(mismatched)} tensors differ after resume: {mismatched[:5]}'
    # end-of-epoch checkpoint supersedes the mid-epoch recovery file
    assert not [n for n in os.listdir(tmp_path / 'pre') if n.startswith('recovery-')]


def test_nan_abort_exit_code_and_intact_checkpoint(tmp_path):
    """Acceptance drill (c): K consecutive injected NaN steps abort with a
    non-zero exit while the committed checkpoints stay valid."""
    r = _run(_train_cmd(tmp_path, 'nanabort',
                        '--fault-inject', 'nan_grads@2:3', '--nonfinite-tolerance', '3'))
    assert r.returncode == 3, (r.returncode, r.stderr[-2000:])
    assert 'consecutive non-finite' in r.stderr
    # no checkpoint was committed this epoch — but nothing half-written either
    litter = [n for n in os.listdir(tmp_path / 'nanabort') if n.endswith('.tmp')]
    assert not litter
    for name in os.listdir(tmp_path / 'nanabort'):
        if name.endswith('.npz'):
            ok, reason = verify_checkpoint(str(tmp_path / 'nanabort' / name))
            assert ok, (name, reason)
