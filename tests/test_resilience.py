"""Fault-tolerance subsystem tests (timm_tpu/resilience): durable checkpoint
verification + fallback, recovery ordering, non-finite sentinel, reader
retry/skip policy, fault injection, elastic rescale planning, the async
checkpoint writer, and the SIGTERM→`--resume auto` parity drill on a tiny
CPU model."""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from timm_tpu.resilience import (
    AsyncCheckpointWriter, CorruptCheckpointError, FaultInjector, GracefulShutdown,
    NonFiniteError, SkipBudget, TooManyBadSamples, atomic_write_npz, backoff_delays,
    capture_host_rng, convert_loader_position, fault_selftest, find_checkpoints,
    load_with_fallback, plan_elastic_resume, rescale_for_devices, resolve_auto_resume,
    restore_host_rng, retry_io, set_durable_write_listener, set_fault_injector,
    snapshot_to_host, verify_checkpoint,
)

pytestmark = pytest.mark.resilience

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- durable checkpoints -----------------------------------------------------

def test_atomic_write_verify_roundtrip(tmp_path):
    path = str(tmp_path / 'last.npz')
    arrays = {'state_dict.w': np.arange(16.0).reshape(4, 4), 'epoch': np.asarray(3)}
    atomic_write_npz(path, arrays, meta={'epoch': 3})
    ok, reason = verify_checkpoint(path)
    assert ok, reason
    state, meta, used = load_with_fallback(path)
    assert used == path and meta['epoch'] == 3
    np.testing.assert_array_equal(state['state_dict.w'], arrays['state_dict.w'])
    # no temp litter from the atomic write
    assert not [n for n in os.listdir(tmp_path) if n.endswith('.tmp')]


def test_manifest_detects_bit_corruption(tmp_path):
    """A flipped byte INSIDE a structurally-valid zip only the manifest catches."""
    path = str(tmp_path / 'last.npz')
    atomic_write_npz(path, {'w': np.zeros(64, np.float32)}, meta={})
    data = bytearray(open(path, 'rb').read())
    # flip a byte in the middle of the (uncompressed) array payload
    data[len(data) // 2] ^= 0xFF
    open(path, 'wb').write(bytes(data))
    ok, reason = verify_checkpoint(path)
    assert not ok and ('sha256' in reason or 'unreadable' in reason)


def test_truncated_checkpoint_falls_back_to_newest_valid(tmp_path):
    older = str(tmp_path / 'checkpoint-0.npz')
    newest = str(tmp_path / 'checkpoint-1.npz')
    atomic_write_npz(older, {'w': np.ones(8)}, meta={'epoch': 0})
    atomic_write_npz(newest, {'w': np.full(8, 2.0)}, meta={'epoch': 1})
    with open(newest, 'r+b') as f:
        f.truncate(os.path.getsize(newest) // 2)
    ok, _ = verify_checkpoint(newest)
    assert not ok
    state, _meta, used = load_with_fallback(newest, search_dir=str(tmp_path))
    assert used == older
    np.testing.assert_array_equal(state['w'], np.ones(8))
    with pytest.raises(CorruptCheckpointError):
        with open(older, 'r+b') as f:
            f.truncate(8)
        load_with_fallback(newest, search_dir=str(tmp_path))


def test_checkpoint_ordering_numeric_not_lexicographic(tmp_path):
    # the seed bug: sorted() ranked recovery-1-999 above recovery-1-1000
    for epoch, batch in [(1, 999), (1, 1000), (0, 5)]:
        atomic_write_npz(str(tmp_path / f'recovery-{epoch}-{batch}.npz'),
                         {'w': np.asarray(float(batch))}, meta={'epoch': epoch})
    names = [os.path.basename(p) for p in find_checkpoints(str(tmp_path))]
    assert names[0] == 'recovery-1-1000.npz'
    assert names.index('recovery-1-1000.npz') < names.index('recovery-1-999.npz')
    # a completed epoch 1 outranks any mid-epoch-1 recovery
    atomic_write_npz(str(tmp_path / 'last.npz'),
                     {'w': np.asarray(0.0), 'epoch': np.asarray(1)}, meta={'epoch': 1})
    assert os.path.basename(find_checkpoints(str(tmp_path))[0]) == 'last.npz'
    assert resolve_auto_resume(str(tmp_path)).endswith('last.npz')


def test_saver_find_recovery_and_startup_cleanup(tmp_path):
    from timm_tpu.utils import CheckpointSaver
    d = str(tmp_path)
    atomic_write_npz(os.path.join(d, 'recovery-1-999.npz'), {'w': np.asarray(1.0)})
    atomic_write_npz(os.path.join(d, 'recovery-1-1000.npz'), {'w': np.asarray(2.0)})
    # orphaned tmp artifacts + a corrupt recovery file from a "crash"
    open(os.path.join(d, 'tmp.npz'), 'wb').write(b'partial')
    open(os.path.join(d, '.last.npz.123.tmp'), 'wb').write(b'partial')
    open(os.path.join(d, 'recovery-1-2000.npz'), 'wb').write(b'torn write')
    saver = CheckpointSaver(task=None, checkpoint_dir=d, recovery_dir=d)
    names = set(os.listdir(d))
    assert 'tmp.npz' not in names and '.last.npz.123.tmp' not in names
    assert 'recovery-1-2000.npz' not in names  # corrupt → swept
    assert saver.find_recovery().endswith('recovery-1-1000.npz')


# -- non-finite sentinel -----------------------------------------------------

@pytest.fixture(scope='module')
def tiny_task(mesh8):
    import timm_tpu
    from timm_tpu.loss import LabelSmoothingCrossEntropy
    from timm_tpu.optim import create_optimizer_v2
    from timm_tpu.task import ClassificationTask
    model = timm_tpu.create_model('test_vit', num_classes=10, img_size=32)
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3)
    return ClassificationTask(
        model, optimizer=opt, mesh=mesh8,
        train_loss_fn=LabelSmoothingCrossEntropy(0.1), nonfinite_tolerance=3)


def _batch(mesh, nan=False, seed=0):
    import jax.numpy as jnp
    from timm_tpu.parallel import shard_batch
    rng = np.random.RandomState(seed)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    if nan:
        x = x * np.nan
    return shard_batch({'input': jnp.asarray(x), 'target': jnp.asarray(rng.randint(0, 10, 8))},
                       mesh)


def test_nonfinite_step_commits_nothing(mesh8, tiny_task):
    import jax
    from flax import nnx
    tiny_task.reset_nonfinite()
    tiny_task.train_step(_batch(mesh8), lr=1e-3, step=0)
    before = [np.asarray(p) for p in jax.tree.leaves(nnx.state(tiny_task.model, nnx.Param))]
    opt_before = [np.asarray(l) for l in jax.tree.leaves(tiny_task.opt_state)]
    metrics = tiny_task.train_step(_batch(mesh8, nan=True), lr=1e-3, step=1)
    assert int(metrics['nonfinite_count']) == 1 and int(metrics['nonfinite_total']) == 1
    after = [np.asarray(p) for p in jax.tree.leaves(nnx.state(tiny_task.model, nnx.Param))]
    opt_after = [np.asarray(l) for l in jax.tree.leaves(tiny_task.opt_state)]
    assert all(np.array_equal(a, b) for a, b in zip(before, after))
    assert all(np.array_equal(a, b) for a, b in zip(opt_before, opt_after))
    # a good step resets the consecutive counter (total stays)
    metrics = tiny_task.train_step(_batch(mesh8), lr=1e-3, step=2)
    assert int(metrics['nonfinite_count']) == 0 and int(metrics['nonfinite_total']) == 1


def test_nonfinite_tolerance_aborts(mesh8, tiny_task):
    tiny_task.reset_nonfinite()
    with pytest.raises(NonFiniteError) as ei:
        for step in range(5):
            tiny_task.train_step(_batch(mesh8, nan=True), lr=1e-3, step=step)
    assert ei.value.consecutive == 3  # tolerance from the fixture
    tiny_task.reset_nonfinite()


# -- retry / skip policy -----------------------------------------------------

def test_retry_io_backoff_then_success():
    sleeps = []
    calls = {'n': 0}

    def flaky():
        calls['n'] += 1
        if calls['n'] < 3:
            raise IOError('transient')
        return 'ok'

    assert retry_io(flaky, retries=3, base_delay=0.1, jitter=0.5,
                    sleep=sleeps.append) == 'ok'
    assert calls['n'] == 3 and len(sleeps) == 2
    # jittered exponential: each delay within ±50% of base*2^i, capped
    assert 0.05 <= sleeps[0] <= 0.15 and 0.1 <= sleeps[1] <= 0.3


def test_retry_io_exhaustion_and_poison_passthrough():
    with pytest.raises(IOError):
        retry_io(lambda: (_ for _ in ()).throw(IOError('down')),
                 retries=2, base_delay=0.0, sleep=lambda s: None)
    calls = {'n': 0}

    def poison():
        calls['n'] += 1
        raise ValueError('bad record')

    with pytest.raises(ValueError):
        retry_io(poison, retries=3, base_delay=0.0, sleep=lambda s: None)
    assert calls['n'] == 1  # non-transient: no retries


def test_backoff_delays_bounded():
    ds = list(backoff_delays(6, base_delay=0.1, max_delay=1.0, jitter=0.0))
    assert ds == [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]


def test_skip_budget():
    b = SkipBudget(budget=2)
    b.record(ValueError('x'), 'a')
    b.record(ValueError('x'), 'b')
    with pytest.raises(TooManyBadSamples):
        b.record(ValueError('x'), 'c')


class _FlakyDataset:
    """Map-style dataset where some indices are poison (undecodable)."""

    def __init__(self, n=12, bad=()):
        self.n, self.bad = n, set(bad)

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        if idx in self.bad:
            raise ValueError(f'undecodable sample {idx}')
        return np.full((4, 4, 3), idx, np.float32), idx


def test_loader_skips_poison_within_budget(monkeypatch):
    from timm_tpu.data.loader import ThreadedLoader
    monkeypatch.setenv('TIMM_TPU_POISON_BUDGET', '4')
    loader = ThreadedLoader(_FlakyDataset(12, bad={3, 7}), batch_size=4,
                            is_training=False, num_workers=2)
    batches = list(loader)
    got = sorted(int(t) for _x, ts in batches for t in ts)
    assert got == [i for i in range(12) if i not in (3, 7)]  # order kept, poison dropped


def test_loader_budget_exhaustion_fails_loudly(monkeypatch):
    from timm_tpu.data.loader import ThreadedLoader
    monkeypatch.setenv('TIMM_TPU_POISON_BUDGET', '1')
    loader = ThreadedLoader(_FlakyDataset(12, bad={1, 2, 5}), batch_size=4,
                            is_training=False, num_workers=2)
    with pytest.raises(TooManyBadSamples):
        list(loader)


# -- fault injection ----------------------------------------------------------

def test_fault_injector_spec_parse():
    fi = FaultInjector('truncate_ckpt, nan_grads@4:2, sigterm@9, io_error%3')
    assert fi.take('truncate_ckpt') and not fi.take('truncate_ckpt')
    assert not fi.nan_at(3) and fi.nan_at(4) and fi.nan_at(5) and not fi.nan_at(6)
    assert fi.sigterm_at(9) and not fi.sigterm_at(9)
    assert [fi.io_error_tick() for _ in range(6)] == [False, False, True, False, False, True]
    assert not FaultInjector('')
    with pytest.raises(ValueError):
        FaultInjector('explode@3')


def test_fault_selftest_all_checks_pass(tmp_path):
    result = fault_selftest('truncate_ckpt,nan_grads@1,io_error%2',
                            tmp_dir=str(tmp_path))
    assert result['ok'], result


def test_bench_dry_run_fault_inject_smoke():
    """`bench.py --dry-run --fault-inject` exercises the injection hooks in
    tier-1 without a slow run (in-process, same idiom as
    test_precision_policy's dry-run sweep)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'bench_resilience', os.path.join(REPO_ROOT, 'bench.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Args:
        model = 'test_vit'
        img_size = 32
        pad_tokens = ''
        softmax_dtype = ''
        norm_dtype = ''
        mu_dtype = ''
        fault_inject = 'truncate_ckpt,io_error%2,nan_grads@1:2,sigterm@3,resize@5:4'

    assert bench._dry_run(Args()) == 0


def test_resize_fault_spec():
    fi = FaultInjector('resize@4:2')
    assert fi.resize_devices == 2
    assert not fi.resize_at(3) and fi.resize_at(4) and not fi.resize_at(4)  # fires once
    with pytest.raises(ValueError, match='resize fault needs a device count'):
        FaultInjector('resize@4')  # the :D suffix is mandatory


# -- elastic rescale planning --------------------------------------------------

def test_rescale_holds_global_batch_constant():
    # 8->4 devices, global batch 256: keep the loader batch if it still shards
    assert rescale_for_devices(256, 4, prefer_batch_size=32) == (32, 8)
    # loader batch no longer divisible -> nearest shardable batch wins
    # (ties break toward the smaller batch: 8 and 16 are both 4 away from 12)
    assert rescale_for_devices(256, 8, prefer_batch_size=12) == (8, 32)
    assert rescale_for_devices(256, 8, prefer_batch_size=13) == (16, 16)
    # exact fit, no accum
    assert rescale_for_devices(64, 8, prefer_batch_size=64) == (64, 1)
    for g, n in ((256, 4), (96, 6), (512, 8)):
        bs, accum = rescale_for_devices(g, n)
        assert bs * accum == g and bs % n == 0


def test_rescale_refuses_with_nearest_legal_suggestion():
    # 100 is not a multiple of 8: no loader batch can shard evenly
    with pytest.raises(ValueError) as ei:
        rescale_for_devices(100, 8)
    msg = str(ei.value)
    assert 'Nearest legal global batch: 96 or 104' in msg
    assert 'multiples of the mesh batch-shard count 8' in msg
    # the accum cap shapes the solution: a tiny preferred batch is pushed up
    # to the smallest batch whose accum still fits the cap
    assert rescale_for_devices(1024, 2, prefer_batch_size=2, max_accum=4) == (256, 4)


def test_convert_loader_position():
    assert convert_loader_position(10, 32, 32) == (10, True)
    assert convert_loader_position(10, 32, 16) == (20, True)   # samples invariant
    assert convert_loader_position(5, 24, 16) == (7, False)    # 120 samples, inexact
    with pytest.raises(ValueError):
        convert_loader_position(1, 0, 16)


def test_plan_elastic_resume_from_checkpoint(tmp_path):
    # the dead run: 8 devices, batch 32 x accum 8 = global 256
    ckpt = str(tmp_path / 'recovery-0-3.npz')
    atomic_write_npz(ckpt, {
        'state_dict.w': np.zeros(4),
        '_resume.batch_size': np.asarray(32),
        '_resume.global_batch': np.asarray(256),
        '_resume.device_count': np.asarray(8),
    }, meta={'epoch': 0})
    # restart on 4 devices with the same flags: global batch held at 256
    plan = plan_elastic_resume(devices=4, batch_size=32, grad_accum=8,
                               fsdp=8, resume=ckpt)
    assert plan.global_batch == 256 and plan.batch_size * plan.grad_accum == 256
    assert plan.batch_size % 4 == 0
    assert plan.fsdp == 4  # clamped to what divides the live topology
    assert plan.source == ckpt
    assert any('clamped' in n for n in plan.notes)
    # fresh start (no resume): plan only validates the fresh configuration
    fresh = plan_elastic_resume(devices=4, batch_size=32, grad_accum=1)
    assert (fresh.batch_size, fresh.grad_accum, fresh.source) == (32, 1, '')


def test_resolve_elastic_axes_clamps_to_divisors():
    from timm_tpu.parallel import create_mesh, resolve_elastic_axes
    assert resolve_elastic_axes(8, fsdp=4) == (4, None)
    assert resolve_elastic_axes(4, fsdp=8) == (4, None)     # clamp down
    assert resolve_elastic_axes(6, fsdp=4) == (3, None)     # largest divisor <= 4
    assert resolve_elastic_axes(8, fsdp=4, tp=4) == (2, 4)  # tp wins the factor
    assert resolve_elastic_axes(5, fsdp=4, tp=2) == (None, None)  # prime: no axes
    # the contract: create_mesh always accepts the clamped result
    import jax
    devs = jax.devices()
    for n in (1, 2, 4, 8):
        fsdp, tp = resolve_elastic_axes(n, fsdp=4, tp=2)
        create_mesh(devices=devs[:n], fsdp=fsdp, tp=tp)


# -- async checkpoint writer ---------------------------------------------------

def test_async_writer_supersede_and_ordering():
    w = AsyncCheckpointWriter()
    started, release = threading.Event(), threading.Event()
    ran = []

    def blocker():
        started.set()
        release.wait(10)
        ran.append('first')

    try:
        w.submit(blocker, label='first', key='recovery')
        assert started.wait(10)
        w.submit(lambda: ran.append('stale'), label='stale', key='recovery')
        w.submit(lambda: ran.append('ckpt'), label='ckpt', key='checkpoint')
        w.submit(lambda: ran.append('newest'), label='newest', key='recovery')
        assert w.superseded == 1  # 'stale' replaced before it ever ran
        release.set()
        w.drain()
    finally:
        release.set()
        w.close()
    # supersede re-queues at the tail; distinct keys keep submission order
    assert ran == ['first', 'ckpt', 'newest']


def test_async_writer_drain_ordering_and_error_propagation():
    w = AsyncCheckpointWriter()
    ran = []
    for i in range(3):
        w.submit(lambda i=i: ran.append(i), label=f'op-{i}', key=f'k{i}')
    w.drain()
    assert ran == [0, 1, 2]
    # a persistent (non-transient) failure re-raises on the caller thread
    w.submit(lambda: (_ for _ in ()).throw(ValueError('disk gone')), key='bad')
    with pytest.raises(ValueError, match='disk gone'):
        w.drain()
    w.close()
    with pytest.raises(RuntimeError, match='closed'):
        w.submit(lambda: None)


def test_async_writer_retries_transient_io_error():
    """io_error%M must exercise the ASYNC durable path: the injected OSError
    fires inside the retried closure and the backoff rides through it."""
    set_fault_injector('io_error%2')
    try:
        w = AsyncCheckpointWriter(base_delay=0.0)
        ran = []
        for i in range(4):  # every 2nd closure attempt hits the injected fault
            w.submit(lambda i=i: ran.append(i), label=f'op-{i}', key=f'k{i}')
        w.close()
        assert ran == [0, 1, 2, 3]
    finally:
        set_fault_injector('')


def test_async_save_keeps_durable_writes_off_step_thread(tmp_path, mesh8):
    """The instrumentation hook the acceptance criteria name: every durable
    write of an async save runs on the writer thread, never the step thread —
    and the npz bytes + SHA-256 manifest are byte-identical to a sync save."""
    import jax.numpy as jnp
    from timm_tpu.resilience.durable import read_manifest

    state = {'state_dict.w': jnp.arange(64.0).reshape(8, 8),
             'epoch': np.asarray(0)}
    sync_path = str(tmp_path / 'sync.npz')
    async_path = str(tmp_path / 'async.npz')
    atomic_write_npz(sync_path, state, meta={'epoch': 0})

    writes = []
    prev = set_durable_write_listener(lambda path, thread: writes.append((path, thread.name)))
    try:
        w = AsyncCheckpointWriter()
        host = snapshot_to_host(state)  # step-thread half: gather only, no I/O
        w.submit(lambda: atomic_write_npz(async_path, host, meta={'epoch': 0}),
                 key='ckpt')
        w.close()
    finally:
        set_durable_write_listener(prev)
    assert writes and all(t == AsyncCheckpointWriter.THREAD_NAME for _p, t in writes), writes

    msync, masync = read_manifest(sync_path), read_manifest(async_path)
    assert {k: v['sha256'] for k, v in msync['arrays'].items()} == \
           {k: v['sha256'] for k, v in masync['arrays'].items()}
    assert open(sync_path, 'rb').read() == open(async_path, 'rb').read()


def test_saver_async_matches_sync_save(tmp_path, mesh8):
    """CheckpointSaver in async mode: save_recovery/save_checkpoint produce
    byte-identical npz + manifests to sync mode, all durable writes stay on
    the writer thread, and no staging litter survives."""
    import jax.numpy as jnp
    from timm_tpu.utils import CheckpointSaver

    class _Task:
        def get_checkpoint_state(self):
            return {'state_dict.w': jnp.full((4, 4), 7.0),
                    'optimizer.m': jnp.zeros(4)}

    def run(d, writer):
        saver = CheckpointSaver(task=_Task(), checkpoint_dir=d, recovery_dir=d,
                                async_writer=writer)
        saver.save_recovery(0, 3, extra_state={'_resume.num_updates': np.asarray(3)})
        saver.save_checkpoint(0, metric=1.0)
        if writer is not None:
            writer.close()
        return saver

    d_sync, d_async = str(tmp_path / 'sync'), str(tmp_path / 'async')
    os.makedirs(d_sync), os.makedirs(d_async)
    run(d_sync, None)
    writes = []
    prev = set_durable_write_listener(lambda path, thread: writes.append(thread.name))
    try:
        run(d_async, AsyncCheckpointWriter())
    finally:
        set_durable_write_listener(prev)
    assert writes and set(writes) == {AsyncCheckpointWriter.THREAD_NAME}

    sync_names = sorted(os.listdir(d_sync))
    assert sorted(os.listdir(d_async)) == sync_names  # incl. NO .async-stage-* dir
    for name in sync_names:
        a, b = os.path.join(d_sync, name), os.path.join(d_async, name)
        if name.endswith('.npz'):
            assert open(a, 'rb').read() == open(b, 'rb').read(), name


def test_saver_sweeps_orphaned_async_staging_dir(tmp_path):
    """Regression: a writer killed mid-write leaves `.async-stage-<pid>/` with
    temp litter; the next process's startup sweep must reap it wholesale."""
    from timm_tpu.utils import CheckpointSaver
    d = str(tmp_path)
    stage = os.path.join(d, '.async-stage-99999')  # "killed" writer's pid
    os.makedirs(stage)
    open(os.path.join(stage, '.last.npz.123.tmp'), 'wb').write(b'partial')
    atomic_write_npz(os.path.join(d, 'last.npz'), {'w': np.ones(4)}, meta={'epoch': 0})
    CheckpointSaver(task=None, checkpoint_dir=d, recovery_dir=d)
    assert not os.path.exists(stage)
    ok, reason = verify_checkpoint(os.path.join(d, 'last.npz'))
    assert ok, reason  # the sweep never touches committed checkpoints


def test_saver_async_staging_dir_killed_writer_subprocess(tmp_path):
    """End-to-end injected kill: a child process starts an async save and is
    SIGKILLed while the writer holds the temp file open; the parent's startup
    sweep reaps the orphaned staging dir."""
    import signal
    child = f'''
import os, sys, threading, numpy as np
sys.path.insert(0, {repr(REPO_ROOT)})
import jax; jax.config.update('jax_platforms', 'cpu')
from timm_tpu.resilience import AsyncCheckpointWriter
from timm_tpu.utils import CheckpointSaver

class T:
    def get_checkpoint_state(self):
        return {{'state_dict.w': np.zeros((256, 256), np.float32)}}

d = {repr(str(tmp_path))}
hold = threading.Event()
w = AsyncCheckpointWriter()
saver = CheckpointSaver(task=T(), checkpoint_dir=d, recovery_dir=d, async_writer=w)
# wedge the writer AFTER the staging dir exists so the kill lands mid-flight
w.submit(lambda: hold.wait(30), key='wedge')
saver.save_recovery(0, 1, extra_state={{'_resume.num_updates': np.asarray(1)}})
open(os.path.join(d, 'ready'), 'w').write('1')
hold.clear()
import time; time.sleep(30)
'''
    proc = subprocess.Popen([sys.executable, '-c', child],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        for _ in range(600):
            if os.path.exists(tmp_path / 'ready'):
                break
            import time
            time.sleep(0.05)
        else:
            raise AssertionError(proc.stderr.read().decode()[-2000:])
        proc.send_signal(signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    stages = [n for n in os.listdir(tmp_path) if n.startswith('.async-stage-')]
    assert stages  # the kill really orphaned a staging dir
    from timm_tpu.utils import CheckpointSaver
    CheckpointSaver(task=None, checkpoint_dir=str(tmp_path), recovery_dir=str(tmp_path))
    assert not [n for n in os.listdir(tmp_path) if n.startswith('.async-stage-')]


# -- graceful shutdown install/uninstall ---------------------------------------

def test_graceful_shutdown_install_idempotent_and_finally_safe():
    import signal
    before = {s: signal.getsignal(s) for s in (signal.SIGTERM, signal.SIGINT)}
    sd = GracefulShutdown()
    assert sd.install() is sd
    assert sd.install() is sd  # second install: no-op, does NOT record itself
    try:
        assert signal.getsignal(signal.SIGTERM) is not before[signal.SIGTERM]
    finally:
        sd.uninstall()
    for s, h in before.items():
        assert signal.getsignal(s) is h, f'handler for {s} not restored'
    sd.uninstall()  # idempotent: already uninstalled is a no-op


# -- host RNG capture ---------------------------------------------------------

def test_host_rng_capture_restore_bit_identical():
    np.random.seed(123)
    import random as pyrandom
    pyrandom.seed(321)
    np.random.rand(7)  # advance the streams off the seed point
    pyrandom.random()
    snap = capture_host_rng()
    expect_np = np.random.rand(16)
    expect_py = [pyrandom.random() for _ in range(4)]
    np.random.rand(99)  # diverge
    pyrandom.random()
    assert restore_host_rng(snap)
    np.testing.assert_array_equal(np.random.rand(16), expect_np)
    assert [pyrandom.random() for _ in range(4)] == expect_py


def test_load_state_dict_rejects_corrupt_npz(tmp_path):
    from timm_tpu.models import load_checkpoint
    import timm_tpu
    path = str(tmp_path / 'weights.npz')
    atomic_write_npz(path, {'w': np.ones(4)})
    with open(path, 'r+b') as f:
        f.truncate(16)
    model = timm_tpu.create_model('test_vit', num_classes=5)
    with pytest.raises(CorruptCheckpointError):
        load_checkpoint(model, path)


# -- end-to-end CPU drills (subprocess train.py) ------------------------------

def _train_cmd(out_dir, experiment, *extra):
    return [
        sys.executable, os.path.join(REPO_ROOT, 'train.py'),
        '--synthetic-data', '--model', 'test_vit', '--img-size', '32', '-b', '8',
        '--synthetic-len', '64', '--epochs', '1', '--opt', 'sgd', '--lr', '0.05',
        '--sched', 'cosine', '--warmup-epochs', '0', '--workers', '1',
        '--log-interval', '50', '--output', str(out_dir), '--experiment', experiment,
        *extra,
    ]


def _run(cmd):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO_ROOT, timeout=240)


def _params(path):
    with np.load(path, allow_pickle=False) as d:
        return {k: d[k] for k in d.files if k.startswith(('state_dict.', 'optimizer.'))}


def test_sigterm_resume_parity(tmp_path):
    """Acceptance drill (b): a run killed by SIGTERM mid-epoch and restarted
    with `--resume auto` ends bit-identical to an uninterrupted run."""
    r = _run(_train_cmd(tmp_path, 'base'))
    assert r.returncode == 0, r.stderr[-2000:]
    # interrupted run: injected SIGTERM after update 3 → recovery + exit 0
    r = _run(_train_cmd(tmp_path, 'pre', '--fault-inject', 'sigterm@3'))
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'recovery-0-3.npz' in os.listdir(tmp_path / 'pre'), r.stderr[-2000:]
    r = _run(_train_cmd(tmp_path, 'pre', '--resume', 'auto'))
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'Resumed mid-epoch' in r.stderr

    base = _params(tmp_path / 'base' / 'last.npz')
    resumed = _params(tmp_path / 'pre' / 'last.npz')
    assert set(base) == set(resumed)
    mismatched = [k for k in base if not np.array_equal(base[k], resumed[k])]
    assert not mismatched, f'{len(mismatched)} tensors differ after resume: {mismatched[:5]}'
    # end-of-epoch checkpoint supersedes the mid-epoch recovery file
    assert not [n for n in os.listdir(tmp_path / 'pre') if n.startswith('recovery-')]


def test_nan_abort_exit_code_and_intact_checkpoint(tmp_path):
    """Acceptance drill (c): K consecutive injected NaN steps abort with a
    non-zero exit while the committed checkpoints stay valid."""
    r = _run(_train_cmd(tmp_path, 'nanabort',
                        '--fault-inject', 'nan_grads@2:3', '--nonfinite-tolerance', '3'))
    assert r.returncode == 3, (r.returncode, r.stderr[-2000:])
    assert 'consecutive non-finite' in r.stderr
    # no checkpoint was committed this epoch — but nothing half-written either
    litter = [n for n in os.listdir(tmp_path / 'nanabort') if n.endswith('.tmp')]
    assert not litter
    for name in os.listdir(tmp_path / 'nanabort'):
        if name.endswith('.npz'):
            ok, reason = verify_checkpoint(str(tmp_path / 'nanabort' / name))
            assert ok, (name, reason)
