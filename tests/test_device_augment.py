"""On-device data path (data/device_augment.py): host/device parity, seeded
param-sampling equivalence, pipeline stages, NaFlex packed batching, and the
zero-recompile-after-warmup contract.

The load-bearing invariant: the host pipeline (Mixup.__call__ / RandomErasing
.__call__ / normalize) and the device pipeline (sample_params on host + the
jitted appliers on device) compute the SAME math from the SAME RNG stream, so
flipping --device-augment changes where the float work runs, never what the
model sees.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from timm_tpu.data.device_augment import (
    DeviceAugmentStage, NaFlexDeviceAugment, augment_image_batch,
    augment_image_batch_np, augment_naflex_batch, batch_donate_argnums,
    erase_images, erase_images_np, mixup_images, mixup_images_np,
    mixup_targets, mixup_targets_np,
)
from timm_tpu.data.mixup import FastCollateMixup, Mixup
from timm_tpu.data.random_erasing import RandomErasing
from timm_tpu.utils.compile_cache import cache_event_total, collect_cache_events

pytestmark = pytest.mark.deviceaug

B, H, W, C, NC = 8, 16, 16, 3, 10


def _img01(seed=0, b=B, h=H, w=W):
    return np.random.RandomState(seed).rand(b, h, w, C).astype(np.float32)


# ---- 1. host __call__ vs sampled-params device appliers ---------------------

@pytest.mark.parametrize('mode', ['batch', 'elem', 'pair'])
@pytest.mark.parametrize('alphas', [(0.8, 0.0), (0.0, 1.0), (0.5, 0.5)])
def test_mixup_host_vs_device_parity(mode, alphas):
    """Identically seeded Mixup: pixels+targets from the host path equal the
    device appliers fed by sample_params to <=1e-6 (same RNG draw order)."""
    ma, ca = alphas
    kw = dict(mixup_alpha=ma, cutmix_alpha=ca, mode=mode, label_smoothing=0.1,
              num_classes=NC, seed=33)
    x = _img01(1)
    t = np.arange(B) % NC

    host_x, host_y = Mixup(**kw)(x.copy(), t)

    params = Mixup(**kw).sample_params(x.shape)
    dev_x = np.asarray(mixup_images(jnp.asarray(x), jnp.asarray(params['lam']),
                                    jnp.asarray(params['use_cutmix']),
                                    jnp.asarray(params['bbox'])))
    dev_y = np.asarray(mixup_targets(jnp.asarray(t), jnp.asarray(params['lam']),
                                     NC, 0.1))
    np.testing.assert_allclose(dev_x, host_x, atol=1e-6)
    np.testing.assert_allclose(dev_y, host_y, atol=1e-6)


@pytest.mark.parametrize('mode', ['const', 'rand'])
def test_random_erasing_host_vs_device_parity(mode):
    """Seeded RandomErasing: in-place host erase equals the broadcast-mask
    device applier fed by sample_params (identical rectangles and fills)."""
    kw = dict(probability=1.0, mode=mode, min_count=1, max_count=3,
              mean=(0.2, 0.3, 0.4), std=(0.5, 0.5, 0.5), seed=11)
    x = _img01(2)

    host = RandomErasing(**kw)(x.copy())

    params = RandomErasing(**kw).sample_params(x.shape)
    dev = np.asarray(erase_images(
        jnp.asarray(x), jnp.asarray(params['erase_box']),
        jnp.asarray(params['erase_fill']) if mode == 'rand' else None,
        mode=mode, mean=(0.2, 0.3, 0.4)))
    np.testing.assert_allclose(dev, host, atol=1e-6)
    assert (params['erase_box'][:, :, 2:] > 0).any(), 'p=1.0 must erase'


def test_sample_params_consumes_identical_rng_stream():
    """After host __call__ vs sample_params, the two seeded instances' RNG
    streams are in the SAME state — the next draws coincide, so --resume
    replay is bit-identical whichever path a run uses."""
    x, t = _img01(3), np.arange(B) % NC
    kw = dict(mixup_alpha=0.6, cutmix_alpha=0.4, mode='elem', num_classes=NC,
              seed=5)
    a, b = Mixup(**kw), Mixup(**kw)
    a(x.copy(), t)
    b.sample_params(x.shape)
    assert a._rng.random() == b._rng.random()

    rkw = dict(probability=0.7, mode='rand', max_count=2, seed=6)
    ra, rb = RandomErasing(**rkw), RandomErasing(**rkw)
    ra(x.copy())
    rb.sample_params(x.shape)
    assert ra._rng.random() == rb._rng.random()


def test_mixup_disabled_emits_identity_values():
    """mixup_off_epoch path: a disabled sampler keeps emitting the SAME pytree
    (lam=1, zero boxes) so the compiled program set never changes."""
    m = Mixup(mixup_alpha=0.8, cutmix_alpha=0.8, num_classes=NC, seed=1)
    m.mixup_enabled = False
    p = m.sample_params((B, H, W, C))
    assert (p['lam'] == 1.0).all() and not p['use_cutmix'].any()
    assert (p['bbox'] == 0).all()


# ---- 2. the fused device program vs its numpy oracle ------------------------

@pytest.mark.parametrize('re_mode', ['const', 'rand', 'pixel'])
def test_augment_image_batch_matches_np_oracle(re_mode):
    """Full fused program (uint8 -> erase -> mixup -> normalize -> soft
    targets) against the eager numpy twin; 'pixel' exercises the on-device
    threaded-key noise, which the oracle reproduces via the same key."""
    rng = np.random.RandomState(4)
    mix = Mixup(mixup_alpha=0.8, cutmix_alpha=1.0, mode='batch',
                num_classes=NC, seed=21)
    re = RandomErasing(probability=1.0, mode=re_mode, max_count=2,
                       mean=(0.1, 0.1, 0.1), std=(0.4, 0.4, 0.4), seed=22)
    batch = {'image': rng.randint(0, 256, (B, H, W, C)).astype(np.uint8),
             'target': (np.arange(B) % NC).astype(np.int64)}
    batch.update(re.sample_params(batch['image'].shape))
    batch.update(mix.sample_params(batch['image'].shape))
    if re_mode == 'pixel':
        batch['noise_epoch'] = np.uint32(3)
        batch['noise_step'] = np.uint32(7)
    kw = dict(mean=(0.48, 0.45, 0.41), std=(0.22, 0.22, 0.22), re_mode=re_mode,
              re_mean=(0.1, 0.1, 0.1), re_std=(0.4, 0.4, 0.4), noise_seed=9,
              num_classes=NC, smoothing=0.1)
    x_np, y_np = augment_image_batch_np(batch, **kw)
    x_dev, y_dev = jax.jit(
        lambda bt: augment_image_batch(bt, **kw))(
            {k: jnp.asarray(v) for k, v in batch.items()})
    np.testing.assert_allclose(np.asarray(x_dev), x_np, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y_dev), y_np, atol=1e-6)


def test_mixup_erase_appliers_match_oracles_elementwise():
    """The individual appliers and their numpy twins agree on hand-built
    params (cutmix bbox rows mixed with plain-lam rows in one batch)."""
    x = _img01(5)
    lam = np.linspace(0.1, 1.0, B).astype(np.float32)
    use_cutmix = (np.arange(B) % 2).astype(bool)
    bbox = np.zeros((B, 4), np.int32)
    bbox[use_cutmix] = (2, 10, 3, 12)
    np.testing.assert_allclose(
        np.asarray(mixup_images(jnp.asarray(x), jnp.asarray(lam),
                                jnp.asarray(use_cutmix), jnp.asarray(bbox))),
        mixup_images_np(x, lam, use_cutmix, bbox), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(mixup_targets(jnp.asarray(np.arange(B) % NC),
                                 jnp.asarray(lam), NC, 0.1)),
        mixup_targets_np(np.arange(B) % NC, lam, NC, 0.1), atol=1e-6)

    boxes = np.zeros((B, 2, 4), np.int32)
    boxes[:, 0] = (1, 1, 4, 5)
    boxes[3:, 1] = (8, 2, 6, 6)  # second slot only for some rows
    np.testing.assert_allclose(
        np.asarray(erase_images(jnp.asarray(x), jnp.asarray(boxes),
                                mode='const', mean=(0.3, 0.3, 0.3))),
        erase_images_np(x, boxes, mode='const', mean=(0.3, 0.3, 0.3)),
        atol=1e-6)


# ---- 3. pipeline stages: determinism + zero recompiles ----------------------

class _FakeImageLoader:
    """Host loader stand-in: deterministic uint8 (image, target) batches over
    a small set of bucket shapes, same sequence every epoch."""

    def __init__(self, shapes, batches_per_shape=2):
        self.shapes = shapes
        self.n = batches_per_shape
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        return len(self.shapes) * self.n

    def __iter__(self):
        for i in range(self.n):
            for h, w in self.shapes:
                rng = np.random.RandomState(hash((h, w, i)) % (2 ** 31))
                yield (rng.randint(0, 256, (B, h, w, C)).astype(np.uint8),
                       (np.arange(B) % NC).astype(np.int64))


def _make_stage(mesh):
    mix = Mixup(mixup_alpha=0.8, cutmix_alpha=0.8, num_classes=NC, seed=17)
    re = RandomErasing(probability=1.0, mode='pixel', max_count=2, seed=18)
    return DeviceAugmentStage(
        _FakeImageLoader([(16, 16), (16, 24), (24, 24)]),
        mean=(0.5,) * 3, std=(0.25,) * 3, mixup=mix, random_erasing=re,
        re_mode='pixel', noise_seed=19, mesh=mesh)


def test_device_augment_stage_epoch_replay_is_deterministic(mesh8):
    """set_epoch(e) fully re-derives every stream (mixup, erase, pixel noise):
    two independent stages replay identical device batches — the --resume
    auto contract for the on-device path."""

    def run_epoch(stage, epoch):
        stage.set_epoch(epoch)
        return [(np.asarray(x), np.asarray(y)) for x, y in stage]

    a = run_epoch(_make_stage(mesh8), 4)
    b = run_epoch(_make_stage(mesh8), 4)
    c = run_epoch(_make_stage(mesh8), 5)
    assert len(a) == len(b) == 6
    for (xa, ya), (xb, yb) in zip(a, b):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    assert any(not np.array_equal(xa, xc) for (xa, _), (xc, _) in zip(a, c)), \
        'different epochs must draw different augmentations'


def test_device_augment_stage_zero_recompiles_after_warmup(mesh8):
    """The bucketed-shape contract: after one epoch over all (H, W) buckets,
    a second epoch triggers ZERO fresh XLA compiles (identity is encoded in
    param values, pytree structure is shape-stable)."""
    stage = _make_stage(mesh8)
    stage.set_epoch(0)
    for x, _ in stage:
        jax.block_until_ready(x)
    stage.set_epoch(1)
    with collect_cache_events() as counts:
        for x, _ in stage:
            jax.block_until_ready(x)
    assert cache_event_total(counts, 'cache_misses') == 0, counts


class _FakePackedLoader:
    """NaFlex loader stand-in: deterministic packed dict batches over a
    seq-len bucket ladder, [0,1] patches + erase_mask (device-augment host
    contract)."""

    def __init__(self, seq_lens=(16, 25, 36), patch_size=4):
        self.seq_lens = seq_lens
        self.p = patch_size

    def __len__(self):
        return len(self.seq_lens)

    def __iter__(self):
        for sl in self.seq_lens:
            rng = np.random.RandomState(sl)
            gw = int(np.sqrt(sl))
            coord = np.stack(np.meshgrid(np.arange(sl // gw), np.arange(gw),
                                         indexing='ij'), -1).reshape(-1, 2)
            n = len(coord)
            yield {
                'patches': rng.rand(B, sl, self.p * self.p * C).astype(np.float32),
                'patch_coord': np.tile(np.pad(coord, ((0, sl - n), (0, 0))), (B, 1, 1)).astype(np.int32),
                'patch_valid': np.tile(np.arange(sl) < n, (B, 1)),
                'target': (np.arange(B) % NC).astype(np.int64),
                'erase_mask': np.tile(np.arange(sl) % 5 == 0, (B, 1)),
                'seq_len': sl,
            }


def test_naflex_device_augment_stage_parity_and_zero_recompiles(mesh8):
    """The per-bucket naflex program normalizes and fills erased
    token slots exactly like the host path (normalize-then-const-0 fill),
    strips the param keys, keeps host metadata — and a second epoch over the
    same ladder compiles nothing."""
    mean = std = (0.5, 0.5, 0.5)
    stage = NaFlexDeviceAugment(_FakePackedLoader(), mean=mean, std=std,
                                re_mode='const', mesh=mesh8)
    host_batches = list(_FakePackedLoader())
    for out, src in zip(stage, host_batches):
        assert 'erase_mask' not in out and out['seq_len'] == src['seq_len']
        expect = (src['patches'] - 0.5) / 0.5
        expect = np.where(src['erase_mask'][..., None], 0.0, expect)
        np.testing.assert_allclose(np.asarray(out['patches']), expect, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(out['patch_valid']),
                                      src['patch_valid'])
    with collect_cache_events() as counts:
        for out in stage:
            jax.block_until_ready(out['patches'])
    assert cache_event_total(counts, 'cache_misses') == 0, counts


# ---- 4. NaFlex packed-vs-unpacked forward -----------------------------------

def test_naflex_packed_padding_invariance_forward():
    """The model output over valid tokens must not depend on (a) how much a
    batch is padded to reach its bucket or (b) the garbage occupying padded
    slots: packing variable-resolution images into a shared bucket is
    semantically free."""
    import timm_tpu

    model = timm_tpu.create_model('test_naflexvit', num_classes=NC)
    model.eval()
    p = model.embeds.patch_size
    rng = np.random.RandomState(8)
    gh = gw = 4
    n = gh * gw
    coord = np.stack(np.meshgrid(np.arange(gh), np.arange(gw),
                                 indexing='ij'), -1).reshape(-1, 2)

    def forward(L, junk):
        patches = np.zeros((B, L, p * p * C), np.float32)
        patches[:, :n] = np.random.RandomState(8).rand(B, n, p * p * C)
        if junk:
            patches[:, n:] = rng.rand(B, L - n, p * p * C) * 100
        pc = np.zeros((B, L, 2), np.int32)
        pc[:, :n] = coord
        return np.asarray(model({
            'patches': jnp.asarray(patches),
            'patch_coord': jnp.asarray(pc),
            'patch_valid': jnp.asarray(np.arange(L)[None] < n).repeat(B, 0),
        }))

    exact = forward(n, junk=False)
    padded = forward(n + 9, junk=False)
    padded_junk = forward(n + 9, junk=True)
    np.testing.assert_allclose(padded, exact, atol=1e-5)
    np.testing.assert_allclose(padded_junk, exact, atol=1e-5)


def test_naflex_attention_mask_tolerates_integer_valid():
    """Post-transfer masks may arrive as uint8/int32 — the attention mask
    builder casts, so a loader handing over non-bool validity cannot flip
    attention weights."""
    from timm_tpu.models.naflexvit import create_attention_mask
    valid = np.array([[1, 1, 0, 0], [1, 1, 1, 0]], np.uint8)
    m_int = create_attention_mask(jnp.asarray(valid))
    m_bool = create_attention_mask(jnp.asarray(valid.astype(bool)))
    np.testing.assert_array_equal(np.asarray(m_int), np.asarray(m_bool))


# ---- 5. loader wiring: config errors + budgets ------------------------------

def test_create_loader_rejects_fast_collate_mixup_and_eval():
    from timm_tpu.data import create_loader

    class _DS:
        def __getitem__(self, i):
            raise IndexError

        def __len__(self):
            return 0

    fcm = FastCollateMixup(num_classes=NC)
    with pytest.raises(ValueError, match='double-apply'):
        create_loader(_DS(), (3, 16, 16), 8, is_training=True,
                      device_augment=True, mixup=fcm)
    with pytest.raises(ValueError, match='train-path'):
        create_loader(_DS(), (3, 16, 16), 8, is_training=False,
                      device_augment=True)


def test_naflex_loader_native_mode_validation():
    from timm_tpu.data.naflex_loader import NaFlexLoader

    class _DS:
        transform = None

        def __len__(self):
            return 0

        def __getitem__(self, i):
            raise IndexError

    with pytest.raises(ValueError, match='bucket_mode'):
        NaFlexLoader(_DS(), bucket_mode='nope')
    with pytest.raises(ValueError, match='multi-host|process'):
        NaFlexLoader(_DS(), bucket_mode='native', process_count=2)
    with pytest.raises(ValueError, match='patch_size'):
        NaFlexLoader(_DS(), bucket_mode='native',
                     patch_size_choices=(8, 16))


@pytest.mark.perfbudget
def test_device_augment_probes_within_budgets():
    """The two on-device data-path probe configs stay within their checked-in
    budgets (trace_ms excluded in-process, same policy as the seed-budget
    test: warmth-sensitive; every deterministic metric has full teeth)."""
    from timm_tpu.perfbudget import compare_budgets, format_violations, load_budgets
    from timm_tpu.perfbudget.probe import run_matrix

    names = ['device_augment', 'naflex_packed']
    measured = run_matrix(names=names)
    violations = [v for v in compare_budgets(measured, load_budgets(), configs=names)
                  if v['metric'] != 'trace_ms']
    assert not violations, format_violations(violations)
    assert measured['device_augment']['naflex_donation_ok']
    assert measured['naflex_packed']['donation_ok']


def test_batch_donation_gated_off_on_cpu(monkeypatch):
    """A donated augment program deserialized from the persistent compile
    cache returns corrupted buffers on XLA:CPU (fresh compiles are fine; the
    poison bites the second warm-cache process), so the runtime stages must
    not request donation on the CPU backend — and must keep it on
    accelerators, where freeing the staged batch buffers is the point."""
    assert jax.default_backend() == 'cpu'
    assert batch_donate_argnums() == ()
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    assert batch_donate_argnums() == (0,)
