"""Multi-process pod runtime tests: KV-store consensus, process-local sharded
checkpoints, crash-safe manifest commits, and the 2-process host-loss drill.

The subprocess drill (`test_kill_host_drill_2process`) is the tier-1
acceptance gate: real cluster bring-up over `jax.distributed.initialize`,
SIGKILL of one host mid-epoch, survivor consensus via the coordination
service's KV store, and an elastic single-process resume from the
host-sharded checkpoint that matches the uninterrupted baseline. Everything
else here is its fast in-process decomposition.
"""
import json
import os

import numpy as np
import pytest

from timm_tpu.resilience import durable

pytestmark = pytest.mark.multihost

FIXTURES = os.path.join(os.path.dirname(__file__), 'fixtures')


# ---------------------------------------------------------------------------
# KV-store consensus (all_hosts_flag with a name)
# ---------------------------------------------------------------------------

class FakeKV:
    """Stand-in for the coordination-service client: a dict with timeouts."""

    def __init__(self, fail_set=False):
        self.store = {}
        self.fail_set = fail_set
        self.sets = []

    def key_value_set(self, k, v):
        if self.fail_set:
            raise RuntimeError('coordinator unreachable')
        self.sets.append(k)
        self.store[k] = v

    def blocking_key_value_get(self, k, timeout_ms):
        if k in self.store:
            return self.store[k]
        raise TimeoutError(f'timeout waiting for {k}')


@pytest.fixture
def three_process_world(monkeypatch):
    """Pretend this host is process 0 of 3 for the KV consensus path (pure
    gRPC bookkeeping — no device collectives are touched)."""
    import jax
    monkeypatch.setattr(jax, 'process_count', lambda: 3)
    monkeypatch.setattr(jax, 'process_index', lambda: 0)
    yield


def _consensus(client, local, mode, name, timeout_s=0.01):
    from timm_tpu.parallel.distributed import _kv_flag_consensus
    return _kv_flag_consensus(client, local, mode, name, timeout_s)


def _prefill(client, name, values):
    """Publish peer votes for the NEXT consensus round of `name`."""
    from timm_tpu.parallel.distributed import _FLAG_SEQ
    seq = _FLAG_SEQ.get(name, 0)
    for p, v in values.items():
        client.store[f'timm_tpu/flag/{name}/{seq}/p{p}'] = v


def test_kv_consensus_any_and_all(three_process_world):
    kv = FakeKV()
    _prefill(kv, 't-any', {1: '0', 2: '1'})
    assert _consensus(kv, False, 'any', 't-any') is True  # one host voted stop
    _prefill(kv, 't-all', {1: '1', 2: '1'})
    assert _consensus(kv, True, 'all', 't-all') is True
    _prefill(kv, 't-all2', {1: '1', 2: '0'})
    assert _consensus(kv, True, 'all', 't-all2') is False


def test_kv_consensus_lost_peer_semantics(three_process_world):
    # peer 2 never publishes: lost host => 'any' stops the pod, 'all' blocks
    # the commit — both degradations are safe, neither deadlocks
    kv = FakeKV()
    _prefill(kv, 't-lost-any', {1: '0'})
    assert _consensus(kv, False, 'any', 't-lost-any') is True
    _prefill(kv, 't-lost-all', {1: '1'})
    assert _consensus(kv, True, 'all', 't-lost-all') is False


def test_kv_consensus_coordinator_unreachable(three_process_world):
    kv = FakeKV(fail_set=True)
    assert _consensus(kv, False, 'any', 't-down') is True
    assert _consensus(kv, True, 'all', 't-down') is False


def test_kv_consensus_rounds_use_fresh_keys(three_process_world):
    # the KV store never forgets: per-name sequence numbers must isolate
    # consecutive rounds or round 2 would read round 1's stale votes
    kv = FakeKV()
    _prefill(kv, 't-seq', {1: '1', 2: '1'})
    assert _consensus(kv, True, 'all', 't-seq') is True
    # round 2: peers have NOT voted yet — stale round-1 keys must not count
    assert _consensus(kv, True, 'all', 't-seq') is False
    assert len(set(kv.sets)) == len(kv.sets) == 2  # fresh key each round


def test_all_hosts_flag_single_process_identity():
    from timm_tpu.parallel import all_hosts_flag
    assert all_hosts_flag(True, mode='any', name='t-id') is True
    assert all_hosts_flag(False, mode='any', name='t-id') is False
    assert all_hosts_flag(True, mode='all') is True
    assert all_hosts_flag(False, mode='all') is False


# ---------------------------------------------------------------------------
# process-local sharded checkpoints (in-process, simulated 2-process split)
# ---------------------------------------------------------------------------

def _two_process_snapshots(arrays):
    """Split a state dict into two process snapshots along axis 0 (chunked
    like a 2-way batch/fsdp sharding would be); host scalars go to p0."""
    snaps = []
    for p in range(2):
        chunks, specs = [], {}
        for k, v in arrays.items():
            v = np.asarray(v)
            specs[k] = {'shape': list(v.shape), 'dtype': str(v.dtype)}
            if v.ndim == 0 or v.shape[0] % 2:
                if p == 0:
                    chunks.append((k, [0] * v.ndim, list(v.shape), v))
                continue
            h = v.shape[0] // 2
            start = [p * h] + [0] * (v.ndim - 1)
            stop = [(p + 1) * h] + list(v.shape[1:])
            chunks.append((k, start, stop, v[p * h:(p + 1) * h]))
        snaps.append({'process_index': p, 'process_count': 2,
                      'chunks': chunks, 'specs': specs})
    return snaps


def _state():
    rng = np.random.RandomState(7)
    return {
        'state_dict.w': rng.randn(8, 6).astype(np.float32),
        'optimizer.mu.w': rng.randn(8, 6).astype(np.float32),
        'epoch': np.asarray(2),
        '_resume.num_updates': np.asarray(11),
        '_resume.global_batch': np.asarray(16),
    }


def test_sharded_roundtrip_two_process(tmp_path):
    arrays = _state()
    path = str(tmp_path / 'recovery-2-11.npz')
    ok_barrier = lambda ok, mode, name=None: True  # noqa: E731
    for snap in _two_process_snapshots(arrays):
        durable.write_sharded_checkpoint(path, snap, meta={'epoch': 2}, barrier=ok_barrier)
    ok, reason = durable.verify_checkpoint(path)
    assert ok, reason
    loaded, meta = durable.load_verified(path)
    assert meta['epoch'] == 2
    for k, v in arrays.items():
        np.testing.assert_array_equal(loaded[k], v)
    # sharded checkpoints surface under their logical name in dir scans
    assert durable.find_checkpoints(str(tmp_path)) == [path]
    assert durable.read_checkpoint_scalar(path, '_resume.global_batch') == 16


def test_sharded_commit_requires_all_barrier(tmp_path):
    """Manifest-commit ordering: a failed 'all' barrier (dead peer) must leave
    the PREVIOUS checkpoint as the newest valid one — the manifest is the
    commit record, shard files alone are litter."""
    old = str(tmp_path / 'recovery-0-1.npz')
    new = str(tmp_path / 'recovery-0-3.npz')
    ok_barrier = lambda ok, mode, name=None: True  # noqa: E731
    dead_barrier = lambda ok, mode, name=None: False  # noqa: E731
    for snap in _two_process_snapshots(_state()):
        durable.write_sharded_checkpoint(old, snap, meta={'epoch': 0}, barrier=ok_barrier)
    # the next save: shards land, the barrier fails (host died) => no commit
    p0_only = _two_process_snapshots(_state())[0]
    assert durable.write_sharded_checkpoint(new, p0_only, meta={'epoch': 0},
                                            barrier=dead_barrier) is None
    assert not os.path.exists(durable.manifest_path(new))
    assert os.path.exists(durable.shard_file_path(new, 0, 2))  # litter stays
    assert durable.resolve_auto_resume(str(tmp_path)) == old
    # startup sweep removes the orphan shard; the committed one survives
    removed = durable.sweep_orphan_shards(str(tmp_path))
    assert durable.shard_file_path(new, 0, 2) in removed
    assert durable.verify_checkpoint(old)[0]


def test_sharded_corrupt_shard_falls_back(tmp_path):
    ok_barrier = lambda ok, mode, name=None: True  # noqa: E731
    old = str(tmp_path / 'recovery-0-1.npz')
    new = str(tmp_path / 'recovery-0-3.npz')
    for p_snap in _two_process_snapshots(_state()):
        durable.write_sharded_checkpoint(old, p_snap, meta={'epoch': 0}, barrier=ok_barrier)
        durable.write_sharded_checkpoint(new, p_snap, meta={'epoch': 0}, barrier=ok_barrier)
    # flip bytes in one committed shard: verification must reject the WHOLE
    # sharded checkpoint and fall back to the older valid one
    victim = durable.shard_file_path(new, 1, 2)
    with open(victim, 'r+b') as f:
        f.seek(os.path.getsize(victim) // 2)
        f.write(b'\xff\xff\xff\xff')
    ok, reason = durable.verify_checkpoint(new)
    assert not ok and 'shard' in reason
    _, _, used = durable.load_with_fallback(new, search_dir=str(tmp_path))
    assert used == old


def test_sharded_remove_and_copy(tmp_path):
    ok_barrier = lambda ok, mode, name=None: True  # noqa: E731
    src = str(tmp_path / 'last.npz')
    dst = str(tmp_path / 'checkpoint-0.npz')
    for snap in _two_process_snapshots(_state()):
        durable.write_sharded_checkpoint(src, snap, meta={'epoch': 0}, barrier=ok_barrier)
    for p in range(2):
        durable.copy_sharded_checkpoint(src, dst, p, 2, barrier=ok_barrier)
    assert durable.verify_checkpoint(dst)[0]
    durable.remove_checkpoint_files(dst)  # primary removes everything
    assert not os.path.exists(durable.manifest_path(dst))
    assert not os.path.exists(durable.shard_file_path(dst, 0, 2))
    assert durable.verify_checkpoint(src)[0]  # source untouched


# ---------------------------------------------------------------------------
# single-process byte-identity regression (the refactor must not change the
# on-disk format of plain checkpoints — manifest vs the checked-in HEAD one)
# ---------------------------------------------------------------------------

def _head_fixture_state():
    """EXACT recipe used to generate fixtures/durable_manifest_head.json at
    HEAD, before the sharded-checkpoint refactor touched durable.py."""
    rng = np.random.RandomState(1234)
    state = {}
    state['state_dict.blocks.0.attn.qkv.kernel'] = rng.standard_normal((8, 24)).astype(np.float32)
    state['state_dict.head.bias'] = rng.standard_normal((10,)).astype(np.float32)
    state['optimizer.mu.head.bias'] = rng.standard_normal((10,)).astype(np.float32)
    state['epoch'] = np.asarray(3)
    state['_resume.num_updates'] = np.asarray(17)
    state['ema.pos_embed'] = rng.standard_normal((1, 4, 8)).astype(np.float16)
    return state


def test_single_process_save_byte_identical_to_head(tmp_path):
    with open(os.path.join(FIXTURES, 'durable_manifest_head.json')) as f:
        head = json.load(f)
    path = str(tmp_path / 'last.npz')
    durable.atomic_write_npz(path, _head_fixture_state(),
                             meta={'epoch': 3, 'metric': 0.5})
    with open(durable.manifest_path(path)) as f:
        now = json.load(f)
    assert now['arrays'] == head['arrays'], (
        'single-process checkpoint bytes changed: per-array SHA-256 no longer '
        'matches the pre-refactor HEAD manifest')
    assert now['schema_version'] == head['schema_version']
    assert now['meta'] == head['meta']


def test_head_single_process_checkpoint_loads_unchanged(tmp_path):
    """A checkpoint written in the HEAD (pre-refactor) format — plain npz +
    manifest, no 'format' key — must verify and load through the new code."""
    path = str(tmp_path / 'last.npz')
    state = _head_fixture_state()
    durable.atomic_write_npz(path, state, meta={'epoch': 3, 'metric': 0.5})
    manifest = durable.read_manifest(path)
    assert not durable.is_sharded_manifest(manifest)
    ok, reason = durable.verify_checkpoint(path)
    assert ok, reason
    loaded, meta = durable.load_verified(path)
    assert meta['epoch'] == 3
    for k, v in state.items():
        np.testing.assert_array_equal(loaded[k], v)
    assert durable.read_checkpoint_scalar(path, '_resume.num_updates') == 17


# ---------------------------------------------------------------------------
# loader position under process-count change (global-batch invariant)
# ---------------------------------------------------------------------------

def test_loader_position_invariant_under_process_count_change():
    """`_resume.batch_size` stores the GLOBAL batch, so a 2-process -> 1-
    process restart needs NO conversion (same global batch => same loader
    position), and a halved global batch doubles the position exactly."""
    from timm_tpu.resilience import convert_loader_position
    same, exact = convert_loader_position(5, 16, 16)
    assert (same, exact) == (5, True)
    doubled, exact = convert_loader_position(5, 16, 8)
    assert (doubled, exact) == (10, True)
    halved, exact = convert_loader_position(5, 8, 16)
    assert (halved, exact) == (2, False)  # partial batch re-seen, never skipped


def test_synthetic_loader_process_shards_union_to_global():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'train_mod', os.path.join(os.path.dirname(__file__), '..', 'train.py'))
    train_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train_mod)
    single = train_mod.SyntheticLoader(32, 8, 16, 10, seed=3)
    shard0 = train_mod.SyntheticLoader(32, 8, 16, 10, seed=3, process_index=0, process_count=2)
    shard1 = train_mod.SyntheticLoader(32, 8, 16, 10, seed=3, process_index=1, process_count=2)
    assert len(single) == len(shard0) == len(shard1)
    for (x, y), (x0, y0), (x1, y1) in zip(single, shard0, shard1):
        np.testing.assert_array_equal(np.concatenate([x0, x1]), x)
        np.testing.assert_array_equal(np.concatenate([y0, y1]), y)
    with pytest.raises(ValueError):
        train_mod.SyntheticLoader(32, 9, 16, 10, process_count=2)


def test_kill_host_fault_spec():
    from timm_tpu.resilience import FaultInjector
    fi = FaultInjector('kill_host@6:1')
    assert fi.kill_host_process == 1
    assert not fi.kill_host_at(6, process_index=0)
    assert fi.kill_host_at(6, process_index=1)
    assert not fi.kill_host_at(6, process_index=1)  # fires exactly once
    assert FaultInjector('kill_host@2').kill_host_process == 0
    with pytest.raises(ValueError):
        FaultInjector('kill_host@2:-1')


# ---------------------------------------------------------------------------
# the real thing: 2-process cluster, host killed mid-epoch (tier-1 gate)
# ---------------------------------------------------------------------------

def test_kill_host_drill_2process(tmp_path):
    """Full acceptance drill (see timm_tpu/resilience/multihost.py): sharded
    save -> SIGKILL host 1 mid-epoch -> survivor stops via KV consensus and
    exits 0 -> uncommitted shard litter is ignored -> fresh single-process
    cluster resumes `--resume auto --elastic` -> final params match the
    uninterrupted baseline to 1e-6."""
    from timm_tpu.resilience import run_kill_drill
    result = run_kill_drill(str(tmp_path), processes=2, kill_update=4,
                            timeout=240, log=lambda m: print(f'[drill] {m}'))
    assert result['ok'], (result['checks'], result['details'])
    assert result['details']['max_param_diff'] <= 1e-6
