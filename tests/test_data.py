"""Data pipeline tests (reference: tests dir lacks loader tests; transforms/
mixup invariants modeled on timm test style)."""
import os

import numpy as np
import pytest
from PIL import Image

from timm_tpu.data import (
    Mixup, RandomErasing, create_dataset, create_loader, create_transform,
    rand_augment_transform, resolve_data_config,
)


@pytest.fixture(scope='module')
def image_root(tmp_path_factory):
    root = tmp_path_factory.mktemp('imgs')
    rng = np.random.RandomState(0)
    for split in ('train', 'val'):
        for cls in ('a', 'b'):
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(6 if split == 'train' else 3):
                Image.fromarray(rng.randint(0, 255, (48, 56, 3), np.uint8)).save(d / f'{i}.jpg')
    return str(root)


def test_dataset_folder(image_root):
    ds = create_dataset('', root=image_root, split='train')
    assert len(ds) == 12
    assert ds.reader.class_to_idx == {'a': 0, 'b': 1}
    img, target = ds[0]
    assert target in (0, 1)


def test_dataset_split_search(image_root):
    ds = create_dataset('', root=image_root, split='validation')  # resolves to val/
    assert len(ds) == 6


def test_train_loader(image_root):
    ds = create_dataset('', root=image_root, split='train', is_training=True)
    loader = create_loader(ds, input_size=(3, 32, 32), batch_size=4, is_training=True,
                           num_workers=2, auto_augment='rand-m5', re_prob=0.3)
    batches = list(loader)
    assert len(batches) == 3  # 12 samples, drop_last
    x, t = batches[0]
    assert x.shape == (4, 32, 32, 3) and x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert len(loader) == 3


def test_eval_loader_keeps_tail(image_root):
    ds = create_dataset('', root=image_root, split='val')
    loader = create_loader(ds, input_size=(3, 32, 32), batch_size=4, is_training=False)
    batches = list(loader)
    assert sum(b[0].shape[0] for b in batches) == 6  # no samples dropped


def test_loader_deterministic_order_eval(image_root):
    ds = create_dataset('', root=image_root, split='val')
    loader = create_loader(ds, input_size=(3, 32, 32), batch_size=3, is_training=False, num_workers=3)
    t1 = np.concatenate([b[1] for b in loader])
    t2 = np.concatenate([b[1] for b in loader])
    assert np.array_equal(t1, t2)


def test_transform_shapes():
    img = Image.fromarray(np.random.RandomState(0).randint(0, 255, (60, 80, 3), np.uint8))
    for is_training in (True, False):
        tf = create_transform(48, is_training=is_training)
        out = tf(img)
        assert out.shape == (48, 48, 3)


def test_rand_augment_config():
    ra = rand_augment_transform('rand-m9-mstd0.5-inc1', {})
    assert ra.num_layers == 2
    assert all(op.magnitude == 9 for op in ra.ops)
    assert all(op.magnitude_std == 0.5 for op in ra.ops)
    names = {op.name for op in ra.ops}
    assert 'PosterizeIncreasing' in names  # inc1 selected increasing set
    img = Image.fromarray(np.random.RandomState(0).randint(0, 255, (40, 40, 3), np.uint8))
    out = ra(img)
    assert out.size == (40, 40)


def test_mixup_batch_mode():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16, 16, 3).astype(np.float32)
    t = rng.randint(0, 10, 8)
    mix = Mixup(mixup_alpha=1.0, cutmix_alpha=1.0, num_classes=10, label_smoothing=0.1)
    xm, tm = mix(x, t)
    assert xm.shape == x.shape and tm.shape == (8, 10)
    np.testing.assert_allclose(tm.sum(-1), np.ones(8), rtol=1e-5)


def test_mixup_elem_mode():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16, 16, 3).astype(np.float32)
    t = rng.randint(0, 10, 8)
    mix = Mixup(mixup_alpha=1.0, mode='elem', num_classes=10)
    xm, tm = mix(x, t)
    assert xm.shape == x.shape and tm.shape == (8, 10)


def test_random_erasing():
    rng = np.random.RandomState(0)
    x = np.ones((4, 32, 32, 3), np.float32)
    re = RandomErasing(probability=1.0, mode='const')
    out = re(x.copy())
    assert (out == 0).any()  # something was erased
    re_none = RandomErasing(probability=0.0)
    out2 = re_none(x.copy())
    assert (out2 == 1).all()


def test_resolve_data_config_priority():
    cfg = resolve_data_config(
        {'img_size': 192, 'mean': (0.1,), 'crop_pct': 0.8},
        pretrained_cfg={'input_size': (3, 224, 224), 'mean': (0.5, 0.5, 0.5), 'std': (0.2, 0.2, 0.2)})
    assert cfg['input_size'] == (3, 192, 192)
    assert cfg['mean'] == (0.1, 0.1, 0.1)  # single value expanded
    assert cfg['std'] == (0.2, 0.2, 0.2)
    assert cfg['crop_pct'] == 0.8


def test_repeat_aug_sampler_semantics(tmp_path):
    """RepeatAugSampler: replicas see different repeats of the same shuffled
    order; per-replica count ~len/replicas (reference distributed_sampler.py:54)."""
    import numpy as np
    from timm_tpu.data.loader import ThreadedLoader

    class FakeDs:
        def __len__(self):
            return 300

        def __getitem__(self, i):
            return np.zeros((8, 8, 3), np.float32), i

    per_rank = []
    for rank in range(3):
        loader = ThreadedLoader(
            FakeDs(), batch_size=4, is_training=True, num_aug_repeats=3,
            process_index=rank, process_count=3, seed=0)
        idx = loader._shard_indices(shuffled=True)
        per_rank.append(list(idx))
    # reference defaults: floor(300/256*256/3) = 85 selected per rank
    assert all(len(ix) == 85 for ix in per_rank)
    # the three replicas start from the same repeated sequence offset by one:
    # each sample index appears on multiple replicas (different augs per replica)
    combined = per_rank[0] + per_rank[1] + per_rank[2]
    from collections import Counter
    counts = Counter(combined)
    assert max(counts.values()) == 3, 'a sample should repeat across replicas'
    # all replicas sample from the same shuffled epoch order
    loader2 = ThreadedLoader(
        FakeDs(), batch_size=4, is_training=True, num_aug_repeats=3,
        process_index=0, process_count=3, seed=0)
    assert list(loader2._shard_indices(shuffled=True)) == per_rank[0]


def test_augmix_jsd_splitbn_pipeline(tmp_path):
    """AugMix aug-splits end-to-end: tuple collate, JSD loss, split BN
    (reference train.py:886-913 + dataset.py:170)."""
    import numpy as np
    from PIL import Image

    from timm_tpu.data import create_dataset, create_loader
    from timm_tpu.data.dataset import AugMixDataset
    from timm_tpu.layers import convert_splitbn_model
    from timm_tpu.loss import JsdCrossEntropy
    import timm_tpu

    for cls in ('a', 'b'):
        d = tmp_path / 'train' / cls
        d.mkdir(parents=True)
        for i in range(4):
            Image.fromarray((np.random.rand(64, 64, 3) * 255).astype('uint8')).save(d / f'{i}.jpg')

    ds = create_dataset('', root=str(tmp_path), split='train', is_training=True)
    ds = AugMixDataset(ds, num_splits=3)
    loader = create_loader(
        ds, input_size=(3, 64, 64), batch_size=4, is_training=True,
        num_aug_splits=3, num_workers=0, auto_augment='augmix-m3-w2')
    x, t = next(iter(loader))
    assert x.shape == (12, 64, 64, 3)  # 4 samples x 3 splits, split-major
    assert t.shape == (12,)
    assert (t[:4] == t[4:8]).all() and (t[:4] == t[8:]).all()

    import jax.numpy as jnp

    model = timm_tpu.create_model('test_efficientnet', num_classes=5)
    model = convert_splitbn_model(model, 3)
    model.train()
    out = model(jnp.asarray(x, jnp.float32) / 255.0)
    loss = JsdCrossEntropy(num_splits=3, smoothing=0.1)(out, jnp.asarray(t))
    assert bool(jnp.isfinite(loss))


# The silent-exception-swallow lint is now the analysis rule `silent-except`
# (timm_tpu/analysis/source_rules.py) — widened from timm_tpu/data to the
# whole package plus the top-level scripts, enforced by tests/test_analysis.py.
