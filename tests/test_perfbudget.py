"""Perf-budget suite: hardware-independent regression gates + replay smoke.

1. Budget semantics: the tolerance policy fails on regression AND on silent
   improvement (re-baseline only via --update-budgets), and a metric that
   silently stops being measured fails as 'missing'.
2. Seed budgets: probing the live code against tests/fixtures/
   perf_budgets.json stays clean; an injected block_scan=False regression
   trips the jaxpr-eqn AND trace-time budgets for the scanned config.
3. BENCH_SELF.json v2 document: result/abort/replay round-trips, v1 upgrade,
   bounded abort history, schema validation.
4. `bench.py --replay --dry-run` (subprocess): the ENTIRE queued PERF.md
   checklist completes unattended with a schema-valid BENCH_SELF.json; an
   aborted bench round appends a structured abort record while preserving
   the prior result.
5. Profiler: perfetto parsing + MXU vs non-MXU classification on a
   synthetic trace (deterministic; the real-trace path is exercised by the
   replay's `profile` step).
"""
import gzip
import json
import os
import subprocess
import sys

import pytest

from timm_tpu.perfbudget import (
    DEFAULT_MATRIX, ProbeConfig, check_counter, check_counter_min, check_ratio_max,
    check_ratio_min, check_upper, compare_budgets, compare_config, format_violations,
    latest_trace_file, load_budgets, load_self_doc, parse_trace, probe_config,
    record_abort, record_result, run_matrix, summarize_events, tolerance_for,
    update_budgets, validate_self_result,
)
from timm_tpu.perfbudget.replay import REPLAY_STEPS, SELF_SCHEMA, _MAX_ABORTS

pytestmark = pytest.mark.perfbudget

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), '..'))
BENCH = os.path.join(REPO_ROOT, 'bench.py')


# ---- 1. tolerance policy (pure, no jax) -------------------------------------

def test_tolerance_policy_directions():
    budget = {'jaxpr_eqns': 1000, 'trace_ms': 400.0, 'donation_aliases': 100,
              'donation_ok': True}

    # within band: clean
    ok = {'jaxpr_eqns': 1040, 'trace_ms': 380.0, 'donation_aliases': 99,
          'donation_ok': True}
    assert compare_config(ok, budget, 'cfg') == []

    # regression: band exceeded upward
    worse = dict(ok, jaxpr_eqns=1200)
    v = compare_config(worse, budget, 'cfg')
    assert [x['direction'] for x in v] == ['regression'] and v[0]['metric'] == 'jaxpr_eqns'

    # silent improvement: band exceeded downward must ALSO fail
    better = dict(ok, jaxpr_eqns=500)
    v = compare_config(better, budget, 'cfg')
    assert [x['direction'] for x in v] == ['improvement']
    assert 'update-budgets' in v[0]['detail']

    # upper-only metric: improvement is free, regression is not
    assert compare_config(dict(ok, trace_ms=10.0), budget, 'cfg') == []
    v = compare_config(dict(ok, trace_ms=900.0), budget, 'cfg')
    assert [x['direction'] for x in v] == ['regression']

    # lower-only metric: losing aliases is a regression, gaining is free
    v = compare_config(dict(ok, donation_aliases=50), budget, 'cfg')
    assert [x['direction'] for x in v] == ['regression']
    assert compare_config(dict(ok, donation_aliases=150), budget, 'cfg') == []

    # bool mismatch + silently-dropped metric
    v = compare_config(dict(ok, donation_ok=False), budget, 'cfg')
    assert [x['direction'] for x in v] == ['mismatch']
    dropped = {k: v for k, v in ok.items() if k != 'donation_ok'}
    v = compare_config(dropped, budget, 'cfg')
    assert [x['direction'] for x in v] == ['missing']

    # un-probed budgeted config
    v = compare_budgets({}, {'configs': {'cfg': budget}})
    assert [x['direction'] for x in v] == ['missing'] and v[0]['metric'] == '*'
    assert 'violation' in format_violations(v)

    assert tolerance_for('flops') == ('band', 0.05)
    assert tolerance_for('never_seen_metric') == ('band', 0.10)


def test_shared_check_helpers():
    check_counter('c', 2, 2)
    with pytest.raises(AssertionError, match='expected exactly'):
        check_counter('c', 3, 2)
    check_counter_min('c', 5, 5)
    with pytest.raises(AssertionError, match='>='):
        check_counter_min('c', 4, 5)
    check_ratio_max('r', 199, 100, 2.0)
    with pytest.raises(AssertionError, match='>= 2'):
        check_ratio_max('r', 200, 100, 2.0)
    check_ratio_min('r', 201, 100, 2.0)
    with pytest.raises(AssertionError, match='<= 2'):
        check_ratio_min('r', 200, 100, 2.0)
    check_upper('u', 1.0, 1.0)
    with pytest.raises(AssertionError, match='> budget'):
        check_upper('u', 1.1, 1.0, unit='ms')


def test_improvement_requires_explicit_rebaseline(tmp_path):
    """The --update-budgets workflow: a genuine win fails comparison until
    the budgets file is regenerated, after which it passes."""
    budgets = load_budgets()
    base = dict(budgets['configs']['base'])
    improved = dict(base, jaxpr_eqns=base['jaxpr_eqns'] // 2)

    v = compare_config(improved, base, 'base')
    assert [x['direction'] for x in v] == ['improvement']

    path = str(tmp_path / 'budgets.json')
    doc = update_budgets({'base': improved}, path=path, note='test rebaseline')
    assert doc['schema'] == 'perf_budgets/v1'
    reloaded = load_budgets(path)
    assert compare_budgets({'base': improved}, reloaded) == []


# ---- 2. live probe vs seed budgets ------------------------------------------

@pytest.fixture(scope='module')
def seed_budgets():
    return load_budgets()


def test_seed_budgets_pass_on_live_code(seed_budgets, analysis_programs):
    """The session-scoped capture (tests/conftest.py `analysis_programs`,
    shared with the analysis suite's Tier B/C passes in test_analysis.py)
    probes base/accum4/serve_test_vit/tp22/elastic_resize exactly ONCE per
    tier-1 run; this test compares those measurements against the checked-in
    budgets. tp22 rides along as new comparison coverage (it previously only
    ran via the CLI). The full matrix is still the CLI
    (`python -m timm_tpu.perfbudget`); scan_depth12's budget is exercised by
    the injected-regression test below.

    trace_ms is excluded HERE only: for the small configs it is sensitive to
    how much tracing already warmed the process (the seed CLI probes the full
    matrix in order; this subset doesn't), and the 1.3x tolerance is sized
    for the consistent-context CLI run. The trace-time budget still has
    tier-1 teeth via the scan_depth12 injection test below, where the signal
    (~1.45x) dwarfs warmth effects."""
    names = list(analysis_programs['names'])
    measured = analysis_programs['measured']
    violations = [v for v in compare_budgets(measured, seed_budgets, configs=names)
                  if v['metric'] != 'trace_ms']
    assert not violations, format_violations(violations)


def test_injected_blockscan_regression_trips_budgets(seed_budgets):
    """Acceptance: turning block_scan OFF for the depth-12 config must trip
    BOTH the jaxpr-equation and the trace-time budgets (the O(1)-in-depth
    contract), proving the suite catches the regression it was built for.

    jaxpr_eqns is deterministic, so it compares against the checked-in seed.
    The trace_ms baseline is re-probed in THIS process instead: trace wall
    time shifts with how warm the interpreter is, so the only apples-to-apples
    comparison is scan-on vs scan-off under identical warmth — exactly what a
    regression lands as. The budget machinery (kind/tolerance) is unchanged.

    The injected regression is probed at depth 24 (the O(depth) loop cost
    doubles, the scanned side barely moves): at depth 12 the scan/loop trace
    ratio sits right AT the 30% band tolerance on slower hosts (~1.2-1.3x),
    so the acceptance check would flake on exactly the machinery it is meant
    to prove out."""
    scan_cfg = next(c for c in DEFAULT_MATRIX if c.name == 'scan_depth12')

    def probe(block_scan):
        return probe_config(ProbeConfig(
            name='scan_depth12', model=scan_cfg.model,
            model_kwargs=scan_cfg.model_kwargs + (('depth', 24),),
            batch_size=scan_cfg.batch_size,
            block_scan=block_scan, collect='trace'))

    probe(True)  # discard: the first probe pays one-time warm-up costs
    baseline, measured = None, None
    for _ in range(2):  # interleaved so drift hits both sides equally
        b, m = probe(True), probe(False)
        if baseline is None or b['trace_ms'] < baseline['trace_ms']:
            baseline = b
        if measured is None or m['trace_ms'] < measured['trace_ms']:
            measured = m
    print(f'scan trace_ms={baseline["trace_ms"]} '
          f'loop trace_ms={measured["trace_ms"]}')  # shown iff the test fails
    budget = dict(seed_budgets['configs']['scan_depth12'])
    budget['trace_ms'] = baseline['trace_ms']
    violations = compare_config(measured, budget,
                                'scan_depth12', metrics=('jaxpr_eqns', 'trace_ms'))
    tripped = {v['metric'] for v in violations if v['direction'] == 'regression'}
    assert tripped == {'jaxpr_eqns', 'trace_ms'}, format_violations(violations)


def test_elastic_resize_probe_within_budgets(analysis_programs):
    """PR-13 acceptance: the re-placed-after-resize train step stays legal —
    state saved on the 8-device (2,4) mesh re-places sharded on the 4-device
    mesh, the rescale solver holds the global batch, and donation survives
    the resize. The exact bools/counts pinned in perf_budgets.json are
    compared by the test above (same shared capture, probed once); the two
    elastic invariants are additionally asserted here directly."""
    measured = analysis_programs['measured']
    assert measured['elastic_resize']['elastic_resharding_ok'] is True
    assert measured['elastic_resize']['donation_ok'] is True


def test_run_matrix_rejects_unknown_config():
    with pytest.raises(ValueError, match='unknown'):
        run_matrix(names=['no_such_config'])


# ---- 3. BENCH_SELF.json v2 document -----------------------------------------

def test_self_doc_roundtrip_abort_history_and_v1_upgrade(tmp_path):
    path = str(tmp_path / 'BENCH_SELF.json')

    # missing and corrupt files both yield a writable fresh document
    assert load_self_doc(path)['schema'] == SELF_SCHEMA
    with open(path, 'w') as f:
        f.write('{truncated')
    assert load_self_doc(path)['schema'] == SELF_SCHEMA

    result = {'metric': 'm', 'value': 1.0, 'unit': 'ok', 'vs_baseline': None}
    record_result(path, result)
    doc = load_self_doc(path)
    assert doc['result'] == result and doc['measured_at']
    assert validate_self_result(doc) == []

    # aborts append without clobbering the result, capped at _MAX_ABORTS
    for i in range(_MAX_ABORTS + 5):
        record_abort(path, f'reason {i}', {'model': 'x'})
    doc = load_self_doc(path)
    assert doc['result'] == result
    assert len(doc['aborts']) == _MAX_ABORTS
    assert doc['aborts'][-1]['reason'] == f'reason {_MAX_ABORTS + 4}'
    assert all(a['at'] and a['reason'] for a in doc['aborts'])
    assert validate_self_result(doc) == []

    # pre-v2 files (bare {'measured_at', 'result'}) upgrade losslessly
    v1 = str(tmp_path / 'v1.json')
    with open(v1, 'w') as f:
        json.dump({'measured_at': '2026-01-01T00:00:00Z', 'result': result}, f)
    doc = load_self_doc(v1)
    assert doc['schema'] == SELF_SCHEMA and doc['result'] == result
    assert doc['measured_at'] == '2026-01-01T00:00:00Z' and doc['aborts'] == []

    # validator actually rejects malformed documents
    assert validate_self_result({'schema': 'bogus'})
    bad = load_self_doc(path)
    bad['aborts'] = [{'reason': 'no timestamp'}]
    assert validate_self_result(bad)


# ---- 4. bench.py integration (subprocess) -----------------------------------

def _bench_env(tmp_path, **extra):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               TIMM_TPU_BENCH_SELF=str(tmp_path / 'BENCH_SELF.json'))
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _last_json(stdout):
    return json.loads(stdout.strip().splitlines()[-1])


def test_replay_dry_run_completes_full_checklist(tmp_path):
    """Acceptance: `bench.py --replay --dry-run` runs the ENTIRE queued
    PERF.md checklist unattended and leaves a schema-valid BENCH_SELF.json
    with a record for every step."""
    env = _bench_env(tmp_path)
    r = subprocess.run([sys.executable, BENCH, '--replay', '--dry-run'],
                       env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    out = _last_json(r.stdout)
    assert out['unit'] == 'checklist steps ok'

    doc = load_self_doc(env['TIMM_TPU_BENCH_SELF'])
    assert validate_self_result(doc) == [], validate_self_result(doc)
    replay = doc['replay']
    assert replay['dry_run'] is True and replay['failed'] == 0
    ran = {s['id']: s['status'] for s in replay['steps']}
    assert set(ran) == {s['id'] for s in REPLAY_STEPS}
    assert set(ran.values()) == {'ok'}, ran
    assert out['value'] == float(replay['completed']) == float(len(REPLAY_STEPS))
    # the profiler step actually parsed device ops out of its own trace
    prof = next(s for s in replay['steps'] if s['id'] == 'profile')
    assert prof['result']['total_events'] > 0


def test_replay_steps_subset_and_unknown_id(tmp_path):
    env = _bench_env(tmp_path)
    r = subprocess.run([sys.executable, BENCH, '--replay', '--dry-run',
                        '--replay-steps', 'serve_drill'],
                       env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    doc = load_self_doc(env['TIMM_TPU_BENCH_SELF'])
    assert [s['id'] for s in doc['replay']['steps']] == ['serve_drill']
    assert doc['replay']['steps'][0]['status'] == 'ok'

    r = subprocess.run([sys.executable, BENCH, '--replay', '--dry-run',
                        '--replay-steps', 'bogus_step'],
                       env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode != 0


def test_aborted_round_leaves_structured_record(tmp_path):
    """Satellite fix: a round whose probe fails no longer leaves an empty
    file — it appends an abort record, PRESERVES the prior self-measured
    result, and replays it clearly labelled with exit code 3."""
    self_path = str(tmp_path / 'BENCH_SELF.json')
    prior = {'metric': 'vit_tiny_patch16_224 train img/s/chip', 'value': 321.0,
             'unit': 'img/s/chip', 'vs_baseline': None}
    record_result(self_path, prior)

    env = _bench_env(tmp_path, TIMM_TPU_BENCH_FORCE_PROBE_FAIL='1',
                     BENCH_TOTAL_BUDGET='40', TIMM_TPU_BENCH_PROBE_TIMEOUT='5')
    r = subprocess.run([sys.executable, BENCH, '--fast', '--save-self'],
                       env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 3, (r.returncode, r.stdout[-2000:], r.stderr[-1000:])
    out = _last_json(r.stdout)
    assert out['replay'] is True and out['value'] == 321.0
    assert 'REPLAY' in out['metric']

    doc = load_self_doc(self_path)
    assert doc['result'] == prior, 'abort clobbered the prior result'
    assert len(doc['aborts']) == 1
    abort = doc['aborts'][0]
    assert 'probe failed' in abort['reason'] and abort['at']
    assert abort['model'] == 'vit_tiny_patch16_224'
    assert validate_self_result(doc) == []


def test_abort_only_self_file_refuses_replay(tmp_path):
    """A v2 file holding only abort records has nothing honest to replay:
    the fallback must exit 2 with the 'no BENCH_SELF to replay' line, not
    fabricate a result."""
    self_path = str(tmp_path / 'BENCH_SELF.json')
    record_abort(self_path, 'earlier abort', {})

    env = _bench_env(tmp_path, TIMM_TPU_BENCH_FORCE_PROBE_FAIL='1',
                     BENCH_TOTAL_BUDGET='40', TIMM_TPU_BENCH_PROBE_TIMEOUT='5')
    r = subprocess.run([sys.executable, BENCH, '--fast'],
                       env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 2
    assert 'no BENCH_SELF.json to replay' in _last_json(r.stdout)['metric']


# ---- 5. profiler parsing (synthetic trace, deterministic) -------------------

def _write_trace(tmp_path, events):
    run_dir = tmp_path / 'plugins' / 'profile' / 'run1'
    run_dir.mkdir(parents=True)
    path = run_dir / 'host.trace.json.gz'
    with gzip.open(path, 'wt') as f:
        json.dump({'traceEvents': events}, f)
    return str(tmp_path)


def test_profiler_classifies_mxu_vs_other(tmp_path):
    trace_dir = _write_trace(tmp_path, [
        {'ph': 'M', 'name': 'thread_name', 'pid': 1, 'tid': 1,
         'args': {'name': 'tf_XLAEigen/1'}},
        {'ph': 'M', 'name': 'thread_name', 'pid': 1, 'tid': 2,
         'args': {'name': 'python'}},
        {'ph': 'M', 'name': 'thread_name', 'pid': 1, 'tid': 3,
         'args': {'name': 'main'}},
        # device ops: one MXU-class (dot), one not (fusion)
        {'ph': 'X', 'name': 'dot.3', 'pid': 1, 'tid': 1, 'ts': 0, 'dur': 100},
        {'ph': 'X', 'name': 'fusion.7', 'pid': 1, 'tid': 1, 'ts': 100, 'dur': 50},
        # noise that must NOT count: python frame, compile event, class name
        {'ph': 'X', 'name': 'loss_fn', 'pid': 1, 'tid': 2, 'ts': 0, 'dur': 999},
        {'ph': 'X', 'name': 'backend_compile', 'pid': 1, 'tid': 3, 'ts': 0, 'dur': 500},
        {'ph': 'X', 'name': 'TfrtCpuClient::Compile', 'pid': 1, 'tid': 3, 'ts': 0, 'dur': 500},
    ])
    path = latest_trace_file(trace_dir)
    assert path and path.endswith('.trace.json.gz')
    ops = parse_trace(path)
    assert sorted(ev['name'] for ev in ops) == ['dot.3', 'fusion.7']
    s = summarize_events(ops)
    assert s['total_events'] == 2
    assert s['mxu_us'] == 100.0 and s['non_mxu_us'] == 50.0
    assert abs(s['mxu_frac'] - 100.0 / 150.0) < 1e-3
    assert s['top_ops'][0]['op'] == 'dot'


def test_profiler_empty_trace_dir(tmp_path):
    assert latest_trace_file(str(tmp_path)) is None
    assert summarize_events([]) == {'total_events': 0, 'mxu_us': 0.0,
                                    'non_mxu_us': 0.0, 'mxu_frac': 0.0,
                                    'top_ops': []}
