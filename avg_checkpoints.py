#!/usr/bin/env python3
"""Average N checkpoints into one (reference: avg_checkpoints.py:1-153)."""
from __future__ import annotations

import argparse
import glob
import os

import numpy as np

parser = argparse.ArgumentParser(description='Checkpoint averager')
parser.add_argument('--input', default='', type=str, metavar='PATH', help='checkpoint dir or glob')
parser.add_argument('--output', default='./averaged.safetensors', type=str, metavar='PATH')
parser.add_argument('--filter', default='checkpoint-*.npz', type=str)
parser.add_argument('-n', type=int, default=10, help='average the last/best n')
parser.add_argument('--use-ema', action='store_true')


def load_model_weights(path: str, use_ema: bool):
    from timm_tpu.models import load_state_dict
    return load_state_dict(path, use_ema=use_ema)


def main():
    args = parser.parse_args()
    pattern = args.input
    if os.path.isdir(pattern):
        pattern = os.path.join(pattern, args.filter)
    def _num_key(path):
        import re
        nums = re.findall(r'(\d+)', os.path.basename(path))
        return [int(n) for n in nums] if nums else [0]

    files = sorted(glob.glob(pattern), key=_num_key)[-args.n:]
    assert files, f'No checkpoints found for {pattern}'
    print(f'Averaging {len(files)} checkpoints:')
    for f in files:
        print(f'  {f}')

    avg = None
    for f in files:
        sd = load_model_weights(f, args.use_ema)
        if avg is None:
            avg = {k: v.astype(np.float64) for k, v in sd.items()}
        else:
            for k, v in sd.items():
                avg[k] += v.astype(np.float64)
    avg = {k: (v / len(files)).astype(np.float32) for k, v in avg.items()}

    from timm_tpu.models import save_state_dict
    save_state_dict(avg, args.output)
    print(f'Wrote averaged checkpoint to {args.output}')


if __name__ == '__main__':
    main()
