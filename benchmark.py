#!/usr/bin/env python3
"""Model benchmark: inference/train step time + FLOP profile
(reference: benchmark.py:1-692 — same CSV schema: samples_per_sec, step_time,
batch_size, img_size, param_count, gmacs).

Timing fuses K steps into one XLA program (lax.scan) so results are device
time, analogous to the reference's CUDA-event timing (benchmark.py:149-157).
GMACs come from the compiled HLO cost analysis in place of the reference's
deepspeed/fvcore profilers (benchmark.py:181-204).
"""
from __future__ import annotations

import argparse
import csv as csv_mod
import json
import logging
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

_logger = logging.getLogger('benchmark')

parser = argparse.ArgumentParser(description='TPU-native model benchmark')
parser.add_argument('--model-list', metavar='NAME', default='', help='txt file or wildcard of models')
parser.add_argument('--model', '-m', metavar='NAME', default='resnet50')
parser.add_argument('--bench', default='infer', type=str,
                    help="('infer', 'train', 'both', 'profile')")
parser.add_argument('-b', '--batch-size', default=256, type=int)
parser.add_argument('--img-size', default=None, type=int)
parser.add_argument('--num-warm-iter', default=2, type=int)
parser.add_argument('--num-bench-iter', default=10, type=int)
parser.add_argument('--amp', action='store_true', default=True)
parser.add_argument('--no-amp', dest='amp', action='store_false')
parser.add_argument('--precision', default='', type=str, help='bfloat16|float32 (overrides --amp)')
parser.add_argument('--num-classes', type=int, default=None)
parser.add_argument('--opt', default='sgd', type=str)
parser.add_argument('--results-file', default='', type=str)
parser.add_argument('--results-format', default='csv', type=str)


def _resolve_img_size(model, args):
    if args.img_size:
        return args.img_size
    if hasattr(model, 'pretrained_cfg'):
        return model.pretrained_cfg.input_size[-1]
    return 224


def benchmark_model(model_name: str, args) -> OrderedDict:
    import optax
    from flax import nnx
    import timm_tpu
    from timm_tpu.loss import cross_entropy
    from timm_tpu.models import model_state_dict
    from timm_tpu.optim import create_optimizer_v2

    precision = args.precision or ('bfloat16' if args.amp else 'float32')
    dtype = jnp.bfloat16 if precision == 'bfloat16' else None

    model = timm_tpu.create_model(model_name, num_classes=args.num_classes, dtype=dtype)
    img_size = _resolve_img_size(model, args)
    param_count = sum(v.size for v in model_state_dict(model, include_stats=False).values())
    B, K = args.batch_size, args.num_bench_iter

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, img_size, img_size, 3),
                    jnp.bfloat16 if dtype is not None else jnp.float32)

    results = OrderedDict(
        model=model_name,
        batch_size=B,
        img_size=img_size,
        precision=precision,
        param_count=round(param_count / 1e6, 2),
    )

    model.eval()
    graphdef_e, state_e = nnx.split(model)

    @jax.jit
    def multi_fwd(state, x):
        def body(c, _):
            out = nnx.merge(graphdef_e, state)(x + c * 0)
            return out.mean().astype(x.dtype), ()
        return jax.lax.scan(body, jnp.zeros((), x.dtype), None, length=K)[0]

    # GMACs from compiled forward cost analysis
    try:
        fwd_flops = jax.jit(lambda s, xx: nnx.merge(graphdef_e, s)(xx)).lower(
            state_e, x).compile().cost_analysis().get('flops', 0)
        results['gmacs'] = round(fwd_flops / 2 / B / 1e9, 2)
    except Exception:
        results['gmacs'] = None

    if args.bench in ('infer', 'both', 'profile'):
        for _ in range(max(1, args.num_warm_iter)):
            float(multi_fwd(state_e, x))
        t0 = time.perf_counter()
        float(multi_fwd(state_e, x))
        dt = (time.perf_counter() - t0) / K
        results['infer_samples_per_sec'] = round(B / dt, 2)
        results['infer_step_time'] = round(dt * 1000, 3)

    if args.bench in ('train', 'both'):
        model.train()
        opt = create_optimizer_v2(model, opt=args.opt, lr=1e-4)
        graphdef_t, params, rest = nnx.split(model, nnx.Param, ...)
        opt_state = opt.init(params)
        t = jnp.asarray(rng.randint(0, model.num_classes, B))

        @jax.jit
        def multi_train(params, opt_state, x, t):
            def body(carry, _):
                params, opt_state = carry

                def loss_fn(p):
                    return cross_entropy(nnx.merge(graphdef_t, p, rest)(x), t)
                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params, lr=1e-4)
                return (optax.apply_updates(params, updates), opt_state), loss
            (_, _), losses = jax.lax.scan(body, (params, opt_state), None, length=K)
            return losses[-1]

        for _ in range(max(1, args.num_warm_iter)):
            float(multi_train(params, opt_state, x, t))
        t0 = time.perf_counter()
        float(multi_train(params, opt_state, x, t))
        dt = (time.perf_counter() - t0) / K
        results['train_samples_per_sec'] = round(B / dt, 2)
        results['train_step_time'] = round(dt * 1000, 3)

    # reference-compatible alias columns
    if 'infer_samples_per_sec' in results:
        results['samples_per_sec'] = results['infer_samples_per_sec']
        results['step_time'] = results['infer_step_time']
    elif 'train_samples_per_sec' in results:
        results['samples_per_sec'] = results['train_samples_per_sec']
        results['step_time'] = results['train_step_time']
    return results


def main():
    import os
    from timm_tpu.models import list_models
    from timm_tpu.utils import setup_default_logging
    setup_default_logging()
    args = parser.parse_args()

    model_names = [args.model]
    if args.model_list:
        if os.path.exists(args.model_list):
            with open(args.model_list) as f:
                model_names = [l.strip() for l in f if l.strip()]
        else:
            model_names = list_models(args.model_list)

    results = []
    for name in model_names:
        try:
            r = benchmark_model(name, args)
            _logger.info(json.dumps(r))
            results.append(r)
        except Exception as e:
            _logger.error(f'{name} failed: {e}')

    if args.results_file and results:
        if args.results_format == 'json':
            with open(args.results_file, 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process benchmark driver; no pod launch path
                json.dump(results, f, indent=2)
        else:
            keys = max(results, key=len).keys()
            with open(args.results_file, 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process benchmark driver; no pod launch path
                dw = csv_mod.DictWriter(f, fieldnames=keys)
                dw.writeheader()
                for r in results:
                    dw.writerow(r)
    print(json.dumps(results if len(results) > 1 else results[0], indent=2))


if __name__ == '__main__':
    main()
