#!/usr/bin/env python3
"""ImageNet-style training script, TPU-native.

Re-designed from the reference train.py (1533 LoC) for JAX: one jitted train
step over a data-parallel mesh; host-side scheduler; bf16 compute via --amp.
Flag names mirror the reference where the concept carries over
(reference: train.py:71-475 argparse, :487 main, :1231 train_one_epoch).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from collections import OrderedDict
from datetime import datetime

import jax
import jax.numpy as jnp
import numpy as np
import yaml

_logger = logging.getLogger('train')


def make_parser():
    parser = argparse.ArgumentParser(description='TPU-native training')
    # dataset
    group = parser.add_argument_group('Dataset parameters')
    group.add_argument('--data-dir', metavar='DIR', default=None, help='path to dataset root')
    group.add_argument('--dataset', metavar='NAME', default='', help='dataset type/scheme')
    group.add_argument('--train-split', metavar='NAME', default='train')
    group.add_argument('--val-split', metavar='NAME', default='validation')
    group.add_argument('--synthetic-data', action='store_true',
                       help='use an on-the-fly synthetic dataset (no --data-dir needed)')
    group.add_argument('--num-classes', type=int, default=None)
    group.add_argument('--class-map', default='', type=str)
    # model
    group = parser.add_argument_group('Model parameters')
    group.add_argument('--model', default='vit_tiny_patch16_224', type=str, metavar='MODEL')
    group.add_argument('--pretrained', action='store_true', default=False)
    group.add_argument('--initial-checkpoint', default='', type=str, metavar='PATH')
    group.add_argument('--resume', default='', type=str, metavar='PATH',
                       help="checkpoint to resume from, or 'auto' to pick the newest valid "
                            "checkpoint/recovery file in the experiment dir (use with --experiment)")
    group.add_argument('--no-resume-opt', action='store_true', default=False)
    group.add_argument('--img-size', type=int, default=None, metavar='N')
    group.add_argument('--in-chans', type=int, default=None, metavar='N')
    group.add_argument('--input-size', default=None, nargs=3, type=int, metavar='N N N')
    group.add_argument('--mean', type=float, nargs='+', default=None, metavar='MEAN')
    group.add_argument('--std', type=float, nargs='+', default=None, metavar='STD')
    group.add_argument('--interpolation', default='', type=str, metavar='NAME')
    group.add_argument('-b', '--batch-size', type=int, default=128, metavar='N')
    group.add_argument('-vb', '--validation-batch-size', type=int, default=None, metavar='N')
    group.add_argument('--model-kwargs', nargs='*', default={}, action=ParseKwargs)
    group.add_argument('--drop', type=float, default=0.0, metavar='PCT')
    group.add_argument('--drop-path', type=float, default=None, metavar='PCT')
    group.add_argument('--grad-accum-steps', type=int, default=1, metavar='N')
    group.add_argument('--grad-checkpointing', action='store_true', default=False)
    group.add_argument('--block-scan', action='store_true', default=False,
                       help='run homogeneous transformer block stacks as one lax.scan '
                            'over stacked per-layer params (O(1)-in-depth trace/compile)')
    group.add_argument('--fused-update', action='store_true', default=False,
                       help='route the optimizer update through the one-HBM-pass fused '
                            'AdamW+EMA Pallas kernel (timm_tpu/kernels/fused_adamw.py). '
                            'Requires a plain adamw --opt chain; optax stays the default '
                            'and the parity oracle')
    group.add_argument('--distill', default='', type=str, metavar='SPEC',
                       help="knowledge-distillation spec "
                            "'teacher=NAME[,kind=logit|feature][,alpha=F][,temperature=F]"
                            "[,feat_loss=cosine|mse][,checkpoint=PATH]': fine-tune the "
                            'student against a frozen teacher running inside the same '
                            'jitted donated train step (big-teacher -> small-student on '
                            'the mesh); the distill-to-serve recipe pairs this with '
                            'validate.py --quantize int8')
    group.add_argument('--device-prefetch', type=int, default=0, metavar='N',
                       help='keep N batches in flight on device (async host->device '
                            'transfer overlapped with the step); 0 disables')
    group.add_argument('--device-augment', action='store_true', default=False,
                       help='run normalize + mixup/cutmix + random-erase as one donated '
                            'jitted on-device program per batch shape; the host collates '
                            'raw uint8 (or [0,1] NaFlex patches) and only samples augment '
                            'parameters. Requires --grad-accum-steps 1 and a real dataset')
    group.add_argument('--naflex-bucket-mode', type=str, default='budget',
                       choices=('budget', 'native'),
                       help='NaFlex seq-len assignment: "budget" schedules random ladder '
                            'buckets per batch; "native" puts each image in the smallest '
                            'bucket holding its natural grid (single-process only)')
    group.add_argument('--fsdp', type=int, default=0, metavar='N',
                       help="shard params + optimizer state over an N-way 'fsdp' mesh axis "
                            '(ZeRO-style; batch still shards over all devices). N must '
                            'divide the per-slice device count; 0 disables '
                            '(env TIMM_TPU_FSDP is the fallback default)')
    group.add_argument('--tp', type=int, default=0, metavar='N',
                       help="tensor parallelism: shard attention heads + MLP hidden over an "
                            "N-way 'model' mesh axis (Megatron split) with activation "
                            'sharding constraints on the residual stream. Composes with '
                            '--fsdp (fsdp*tp must divide the per-slice device count); '
                            '0 disables (env TIMM_TPU_TP is the fallback default)')
    group.add_argument('--autotune', action='store_true', default=False,
                       help='enumerate legal {fsdp x tp x batch x accum x scan x remat} '
                            'configs for the live topology, rank them on the compiled-'
                            'cost roofline, print the table, and apply the winner '
                            'before building the mesh (the global batch '
                            'batch_size * grad_accum_steps is held exactly constant)')
    group.add_argument('--autotune-probe-top-k', type=int, default=0, metavar='K',
                       help="with --autotune: lower the top-K candidates' REAL train "
                            'steps and re-rank the shortlist on their compiled costs '
                            '(K extra compiles; 0 = estimator tier only)')
    group.add_argument('--amp', action='store_true', default=False,
                       help='bf16 compute (the TPU-native AMP)')
    group.add_argument('--amp-dtype', default='bfloat16', type=str)
    group.add_argument('--device', default=None, type=str,
                       help='pin the JAX platform (tpu/cpu); default = auto '
                            '(reference train.py --device)')
    group.add_argument('--distributed', action='store_true', default=False,
                       help='multi-process pod runtime: call jax.distributed.initialize() '
                            'before any device op (coordinator/rank from the cluster env: '
                            'COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, or '
                            'auto-detected on TPU pods). Shards the input pipeline by '
                            'process and switches checkpoints to one-shard-file-per-'
                            'process (README "Multi-host training")')
    # optimizer
    group = parser.add_argument_group('Optimizer parameters')
    group.add_argument('--opt', default='sgd', type=str, metavar='OPTIMIZER')
    group.add_argument('--opt-eps', default=None, type=float, metavar='EPSILON')
    group.add_argument('--opt-betas', default=None, type=float, nargs='+', metavar='BETA')
    group.add_argument('--momentum', type=float, default=0.9, metavar='M')
    group.add_argument('--weight-decay', type=float, default=2e-5)
    group.add_argument('--clip-grad', type=float, default=None, metavar='NORM')
    group.add_argument('--clip-mode', type=str, default='norm')
    group.add_argument('--layer-decay', type=float, default=None)
    group.add_argument('--opt-kwargs', nargs='*', default={}, action=ParseKwargs)
    group.add_argument('--opt-caution', action='store_true', default=False)
    # schedule
    group = parser.add_argument_group('Learning rate schedule parameters')
    group.add_argument('--sched', type=str, default='cosine', metavar='SCHEDULER')
    group.add_argument('--sched-on-updates', action='store_true', default=False)
    group.add_argument('--lr', type=float, default=None, metavar='LR')
    group.add_argument('--lr-base', type=float, default=0.1, metavar='LR')
    group.add_argument('--lr-base-size', type=int, default=256, metavar='DIV')
    group.add_argument('--lr-base-scale', type=str, default='', metavar='SCALE')
    group.add_argument('--lr-noise', type=float, nargs='+', default=None, metavar='pct, pct')
    group.add_argument('--lr-noise-pct', type=float, default=0.67, metavar='PERCENT')
    group.add_argument('--lr-noise-std', type=float, default=1.0, metavar='STDDEV')
    group.add_argument('--lr-cycle-mul', type=float, default=1.0, metavar='MULT')
    group.add_argument('--lr-cycle-decay', type=float, default=0.5, metavar='MULT')
    group.add_argument('--lr-cycle-limit', type=int, default=1, metavar='N')
    group.add_argument('--lr-k-decay', type=float, default=1.0)
    group.add_argument('--warmup-lr', type=float, default=1e-5, metavar='LR')
    group.add_argument('--min-lr', type=float, default=0, metavar='LR')
    group.add_argument('--epochs', type=int, default=300, metavar='N')
    group.add_argument('--epoch-size', type=int, default=0, metavar='N',
                       help='samples per epoch when the loader length is unknown (streaming datasets)')
    group.add_argument('--epoch-repeats', type=float, default=0.0, metavar='N')
    group.add_argument('--start-epoch', default=None, type=int, metavar='N')
    group.add_argument('--decay-milestones', default=[90, 180, 270], type=int, nargs='+', metavar='MILESTONES')
    group.add_argument('--decay-epochs', type=float, default=90, metavar='N')
    group.add_argument('--warmup-epochs', type=int, default=5, metavar='N')
    group.add_argument('--warmup-prefix', action='store_true', default=False)
    group.add_argument('--cooldown-epochs', type=int, default=0, metavar='N')
    group.add_argument('--patience-epochs', type=int, default=10, metavar='N')
    group.add_argument('--decay-rate', '--dr', type=float, default=0.1, metavar='RATE')
    # augmentation / regularization (consumed by the data pipeline)
    group = parser.add_argument_group('Augmentation and regularization parameters')
    group.add_argument('--no-aug', action='store_true', default=False)
    group.add_argument('--scale', type=float, nargs='+', default=[0.08, 1.0], metavar='PCT')
    group.add_argument('--ratio', type=float, nargs='+', default=[3. / 4., 4. / 3.], metavar='RATIO')
    group.add_argument('--hflip', type=float, default=0.5)
    group.add_argument('--vflip', type=float, default=0.0)
    group.add_argument('--color-jitter', type=float, default=0.4, metavar='PCT')
    group.add_argument('--aa', type=str, default=None, metavar='NAME')
    group.add_argument('--reprob', type=float, default=0.0, metavar='PCT')
    group.add_argument('--remode', type=str, default='pixel')
    group.add_argument('--recount', type=int, default=1)
    group.add_argument('--mixup', type=float, default=0.0)
    group.add_argument('--cutmix', type=float, default=0.0)
    group.add_argument('--cutmix-minmax', type=float, nargs='+', default=None)
    group.add_argument('--mixup-prob', type=float, default=1.0)
    group.add_argument('--mixup-switch-prob', type=float, default=0.5)
    group.add_argument('--mixup-mode', type=str, default='batch')
    group.add_argument('--mixup-off-epoch', default=0, type=int, metavar='N')
    group.add_argument('--smoothing', type=float, default=0.1)
    group.add_argument('--train-interpolation', type=str, default='random')
    group.add_argument('--bce-loss', action='store_true', default=False)
    group.add_argument('--bce-sum', action='store_true', default=False)
    group.add_argument('--bce-target-thresh', type=float, default=None)
    group.add_argument('--jsd-loss', action='store_true', default=False)
    group.add_argument('--aug-splits', type=int, default=0,
                       help='Number of augmentation splits (AugMix/JSD; 0 or >=2)')
    group.add_argument('--split-bn', action='store_true',
                       help='Use separate BN statistics per augmentation split')
    # ema
    group = parser.add_argument_group('Model EMA parameters')
    group.add_argument('--model-ema', action='store_true', default=False)
    group.add_argument('--model-ema-decay', type=float, default=0.9998)
    group.add_argument('--model-ema-warmup', action='store_true')
    # misc
    group = parser.add_argument_group('Miscellaneous parameters')
    group.add_argument('--seed', type=int, default=42, metavar='S')
    group.add_argument('--worker-seeding', type=str, default='all')
    group.add_argument('--log-interval', type=int, default=50, metavar='N')
    group.add_argument('--recovery-interval', type=int, default=0, metavar='N')
    group.add_argument('--checkpoint-hist', type=int, default=10, metavar='N')
    group.add_argument('-j', '--workers', type=int, default=4, metavar='N')
    group.add_argument('--output', default='', type=str, metavar='PATH')
    group.add_argument('--experiment', default='', type=str, metavar='NAME')
    group.add_argument('--eval-metric', default='top1', type=str, metavar='EVAL_METRIC')
    group.add_argument('--log-wandb', action='store_true', default=False)
    group.add_argument('--synthetic-len', type=int, default=1024,
                       help='samples per epoch for --synthetic-data')
    # fault tolerance (timm_tpu/resilience; README "Fault tolerance")
    group = parser.add_argument_group('Fault tolerance parameters')
    group.add_argument('--fault-inject', default='', type=str, metavar='SPEC',
                       help="arm the fault-injection harness for drills, e.g. "
                            "'truncate_ckpt,nan_grads@12,sigterm@7,io_error%%50,resize@7:4' "
                            "(timm_tpu/resilience/faultinject.py)")
    group.add_argument('--elastic', action='store_true', default=False,
                       help='elastic resume: rebuild the mesh from the LIVE device '
                            'topology (clamping --fsdp/--tp to what still divides it) '
                            'and rescale --batch-size x --grad-accum-steps so the '
                            "interrupted run's global batch stays constant; refuses "
                            'loudly when no integer solution exists. Combine with '
                            '--resume auto after a slice preemption '
                            '(timm_tpu/resilience/elastic.py)')
    group.add_argument('--nonfinite-tolerance', type=int, default=None, metavar='K',
                       help='abort after K consecutive non-finite (NaN/Inf) train steps '
                            '(default: env TIMM_TPU_NONFINITE_TOLERANCE or 3); skipped '
                            'steps commit nothing and are counted in metrics')
    group.add_argument('--no-nonfinite-guard', action='store_true', default=False,
                       help='disable the in-step all-finite check entirely')
    group.add_argument('--nonfinite-rollback', action='store_true', default=False,
                       help='when the non-finite tolerance trips, reload the newest valid '
                            'checkpoint and continue instead of aborting (budget: '
                            'TIMM_TPU_ROLLBACK_BUDGET, default 1)')
    # NaFlex variable-resolution training (reference train.py --naflex-loader)
    group = parser.add_argument_group('NaFlex parameters')
    group.add_argument('--naflex-loader', action='store_true', help='token-budget variable-res training')
    group.add_argument('--naflex-train-seq-lens', type=int, nargs='+', default=[128, 256, 576, 784, 1024])
    group.add_argument('--naflex-max-seq-len', type=int, default=576)
    group.add_argument('--naflex-patch-sizes', type=int, nargs='+', default=None,
                       help='variable patch sizes sampled per train batch (e.g. 8 12 16)')
    return parser


class ParseKwargs(argparse.Action):
    def __call__(self, parser, namespace, values, option_string=None):
        kw = {}
        for value in values:
            key, _, v = value.partition('=')
            try:
                kw[key] = json.loads(v)
            except json.JSONDecodeError:
                kw[key] = v
        setattr(namespace, self.dest, kw)


def _parse_args():
    # two-stage parse: --config YAML sets defaults, CLI overrides (ref train.py:71)
    config_parser = argparse.ArgumentParser(description='Config', add_help=False)
    config_parser.add_argument('-c', '--config', default='', type=str, metavar='FILE')
    args_config, remaining = config_parser.parse_known_args()
    parser = make_parser()
    if args_config.config:
        with open(args_config.config, 'r') as f:
            cfg = yaml.safe_load(f)
            parser.set_defaults(**cfg)
    args = parser.parse_args(remaining)
    args_text = yaml.safe_dump(args.__dict__, default_flow_style=False)
    return args, args_text


def _parse_distill(spec):
    """'teacher=NAME,kind=logit,alpha=0.5,temperature=2.0' -> dict."""
    out = dict(kind='logit', alpha=0.5, temperature=1.0, feat_loss='cosine', checkpoint='')
    for item in filter(None, (s.strip() for s in spec.split(','))):
        if '=' not in item:
            raise ValueError(f"--distill: expected key=value, got {item!r}")
        k, v = item.split('=', 1)
        if k not in ('teacher', 'kind', 'alpha', 'temperature', 'feat_loss', 'checkpoint'):
            raise ValueError(f'--distill: unknown key {k!r}')
        out[k] = float(v) if k in ('alpha', 'temperature') else v
    if 'teacher' not in out:
        raise ValueError("--distill requires teacher=MODEL_NAME")
    if out['kind'] not in ('logit', 'feature'):
        raise ValueError(f"--distill: kind must be logit|feature, got {out['kind']!r}")
    return out


class SyntheticLoader:
    """Deterministic random image/label batches for smoke runs.

    `batch_size` is the GLOBAL batch. Multi-process runs draw the same global
    batch from the seeded stream on every host and each process yields its own
    contiguous row slice, so the union across processes is bit-identical to a
    single-process run — the property the multi-host kill drill asserts on.
    """

    def __init__(self, length, batch_size, img_size, num_classes, seed=0,
                 process_index=0, process_count=1):
        if batch_size % process_count != 0:
            raise ValueError(
                f'synthetic batch size {batch_size} not divisible by '
                f'{process_count} processes')
        self.length = max(1, length // batch_size)
        self.batch_size = batch_size
        self.img_size = img_size
        self.num_classes = num_classes
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count

    def __len__(self):
        return self.length

    def __iter__(self):
        rng = np.random.RandomState(self.seed)
        local = self.batch_size // self.process_count
        lo = self.process_index * local
        for _ in range(self.length):
            x = rng.rand(self.batch_size, self.img_size, self.img_size, 3).astype(np.float32)
            y = rng.randint(0, self.num_classes, self.batch_size)
            yield x[lo:lo + local], y[lo:lo + local]


def _solver_model_kwargs(args):
    """create_model kwargs for the autotune solver's abstract
    (`nnx.eval_shape`) model build — the pre-mesh surfaces (--autotune, the
    elastic re-solve) run before the real factory_kwargs are assembled."""
    kw = dict(args.model_kwargs)
    if args.num_classes is not None:
        kw.setdefault('num_classes', args.num_classes)
    if args.img_size is not None:
        kw.setdefault('img_size', args.img_size)
    return kw


def _bootstrap_distributed(args):
    """Cluster bring-up for --distributed / pod launches. Must run before ANY
    timm_tpu import: importing the package pulls in flax, which touches the
    XLA backend, and jax.distributed.initialize() refuses to run after the
    first backend touch. init_distributed_device() later detects the already-
    initialized runtime and only fills in args.{world_size,rank,...}."""
    coord = os.environ.get('COORDINATOR_ADDRESS') or os.environ.get('JAX_COORDINATOR_ADDRESS')
    env_cluster = (bool(coord)
                   or int(os.environ.get('SLURM_NTASKS') or 1) > 1
                   or int(os.environ.get('OMPI_COMM_WORLD_SIZE') or 1) > 1)
    if not (getattr(args, 'distributed', False) or env_cluster):
        return
    kwargs = {}
    if coord:
        kwargs['coordinator_address'] = coord
        if os.environ.get('NUM_PROCESSES'):
            kwargs['num_processes'] = int(os.environ['NUM_PROCESSES'])
        if os.environ.get('PROCESS_ID'):
            kwargs['process_id'] = int(os.environ['PROCESS_ID'])
    try:
        if 'jax_cpu_collectives_implementation' in jax.config.values:
            # CPU clusters (tests, local drills): cross-process collectives
            # need the gloo transport; harmless no-op on TPU backends
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
        jax.distributed.initialize(**kwargs)
        _logger.info(f'Initialized multi-host JAX: process '
                     f'{jax.process_index()}/{jax.process_count()}')
    except Exception:
        if env_cluster:
            raise
        _logger.warning('--distributed requested but no coordinator/cluster '
                        'env detected; continuing single-process')


def main():
    args, args_text = _parse_args()
    if args.device:
        # must land before the first device op; env JAX_PLATFORMS loses to the
        # axon plugin's sitecustomize registration, jax.config wins
        jax.config.update('jax_platforms', args.device)
    _bootstrap_distributed(args)

    from timm_tpu import create_model
    from timm_tpu.loss import BinaryCrossEntropy, JsdCrossEntropy, LabelSmoothingCrossEntropy, SoftTargetCrossEntropy
    from timm_tpu.optim import create_optimizer_v2, optimizer_kwargs
    from timm_tpu.parallel import (
        create_mesh, init_distributed_device, is_primary, set_global_mesh, shard_batch,
    )
    from timm_tpu.scheduler import create_scheduler_v2, scheduler_kwargs
    from timm_tpu.task import ClassificationTask
    from timm_tpu.utils import (
        AverageMeter, CheckpointSaver, accuracy, get_outdir, random_seed,
        setup_default_logging, update_summary,
    )

    from timm_tpu.resilience import (
        AsyncCheckpointWriter, GracefulShutdown, NonFiniteError, TrainingPreempted,
        convert_loader_position, load_with_fallback, plan_elastic_resume,
        resolve_auto_resume, restore_host_rng, set_fault_injector,
    )

    setup_default_logging()
    if args.fault_inject:
        set_fault_injector(args.fault_inject)
    world_size, rank, _ = init_distributed_device(args)
    # durable compiles: every process reuses the on-disk XLA executable cache
    # (TIMM_TPU_COMPILE_CACHE; see timm_tpu/utils/compile_cache.py)
    from timm_tpu.utils import configure_compile_cache
    configure_compile_cache()
    random_seed(args.seed, rank)

    if args.elastic:
        # elastic pre-pass: clamp mesh axes to the LIVE topology and hold the
        # interrupted run's global batch constant, BEFORE mesh/loaders exist.
        # (The resume path is re-resolved here because output_dir is built
        # later; `--resume auto` needs --experiment for a stable dir.)
        probe_dir = (os.path.join(args.output or './output/train', args.experiment)
                     if args.experiment else '')
        elastic_resume = args.resume
        if args.resume == 'auto':
            elastic_resume = (resolve_auto_resume(probe_dir) or '') if probe_dir else ''
        plan = plan_elastic_resume(
            devices=jax.device_count(),
            batch_size=args.batch_size, grad_accum=args.grad_accum_steps,
            fsdp=args.fsdp or None, tp=args.tp or None, resume=elastic_resume,
            model=args.model, model_kwargs=_solver_model_kwargs(args))
        args.fsdp, args.tp = plan.fsdp or 0, plan.tp or 0
        args.batch_size, args.grad_accum_steps = plan.batch_size, plan.grad_accum
        for note in plan.notes:
            _logger.info(f'[elastic] {note}')
        _logger.info(
            f'[elastic] live topology: {plan.devices} devices, fsdp={plan.fsdp}, '
            f'tp={plan.tp}; global batch {plan.global_batch} = '
            f'{plan.batch_size} x {plan.grad_accum}'
            + (f' (held constant from {os.path.basename(plan.source)})' if plan.source else ''))

    if args.autotune:
        # rank every legal config for the live topology at the (possibly
        # elastic-recovered) global batch, then apply the winner's flags —
        # all before the mesh exists, so the run IS the winning config
        from timm_tpu.autotune import apply_to_args, autotune, format_table
        result = autotune(
            args.model, _solver_model_kwargs(args),
            global_batch=args.batch_size * args.grad_accum_steps,
            probe_top_k=args.autotune_probe_top_k,
            log=lambda m: _logger.info(f'[autotune] {m}'))
        for line in format_table(result).splitlines():
            _logger.info(f'[autotune] {line}')
        for note in apply_to_args(args, result):
            _logger.info(f'[autotune] applied {note}')

    mesh = create_mesh(fsdp=args.fsdp if args.fsdp else None,
                       tp=args.tp if args.tp else None)
    set_global_mesh(mesh)
    n_devices = mesh.size
    _logger.info(f'Training on mesh {mesh} ({n_devices} devices, {world_size} processes)')

    dtype = jnp.bfloat16 if args.amp else None
    model_kwargs = dict(args.model_kwargs)
    if args.drop:
        model_kwargs['drop_rate'] = args.drop
    if args.drop_path is not None:
        model_kwargs['drop_path_rate'] = args.drop_path
    factory_kwargs = dict(
        pretrained=args.pretrained,
        num_classes=args.num_classes,
        in_chans=args.in_chans,
        checkpoint_path=args.initial_checkpoint,
        dtype=dtype,
        seed=args.seed,
    )
    # pass img_size only to models whose constructor takes it; fixed-field
    # conv nets get resized inputs via resolve_data_config instead. The retry
    # is limited to the exact img_size TypeError so real errors still surface.
    def _build_model():
        if args.img_size is not None:
            try:
                return create_model(args.model, img_size=args.img_size, **factory_kwargs, **model_kwargs)
            except TypeError as e:
                if 'img_size' not in str(e):
                    raise
        return create_model(args.model, **factory_kwargs, **model_kwargs)

    if 'fsdp' in mesh.axis_names or 'model' in mesh.axis_names:
        # abstract init: nnx.eval_shape resolves the partition rules against
        # the abstract param shapes and a jitted constructor materializes each
        # shard on its owning devices — a replicated full-model copy never
        # exists (falls back to eager build + reshard for non-traceable
        # constructors, e.g. pretrained-weight loading)
        from timm_tpu.parallel import create_sharded_model
        model = create_sharded_model(_build_model, mesh)
    else:
        model = _build_model()
    if args.num_classes is None:
        args.num_classes = model.num_classes
    if args.grad_checkpointing:
        model.set_grad_checkpointing(True)
    if args.block_scan:
        if hasattr(model, 'set_block_scan'):
            model.set_block_scan(True)
        else:
            _logger.warning(f'--block-scan: {args.model} has no scannable block stack; ignored')

    # AugMix aug-splits (reference train.py:886-913): wrap BNs with per-split
    # statistics before the optimizer captures the param tree
    num_aug_splits = 0
    if args.aug_splits > 0:
        assert args.aug_splits > 1, 'a split of 1 makes no sense'
        num_aug_splits = args.aug_splits
    if args.split_bn:
        assert num_aug_splits > 1
        from timm_tpu.layers import convert_splitbn_model
        model = convert_splitbn_model(model, max(num_aug_splits, 2))

    from timm_tpu.data import resolve_data_config
    data_config = resolve_data_config(vars(args), model=model, verbose=rank == 0)
    img_size = data_config['input_size'][-1]

    # LR auto-scale from global batch (ref train.py:837-849)
    global_batch_size = args.batch_size * args.grad_accum_steps
    if args.lr is None:
        on = args.opt.lower()
        scale = 'sqrt' if any(o in on for o in ('ada', 'lamb', 'lion')) else 'linear'
        if args.lr_base_scale:
            scale = args.lr_base_scale
        batch_ratio = global_batch_size / args.lr_base_size
        if scale == 'sqrt':
            batch_ratio = batch_ratio ** 0.5
        args.lr = args.lr_base * batch_ratio
        _logger.info(f'LR ({args.lr}) from base ({args.lr_base}) * {scale} batch ratio')

    # distillation teacher: built (and, for feature distill, the student's
    # projection attached) BEFORE the optimizer captures the param tree
    distill = _parse_distill(args.distill) if args.distill else None
    teacher = None
    if distill is not None:
        if args.naflex_loader:
            raise ValueError('--distill does not compose with --naflex-loader '
                             '(the teacher forward expects dense NHWC batches)')
        from timm_tpu.models import load_checkpoint
        from timm_tpu.task import FeatureDistillationTask, LogitDistillationTask
        teacher_kwargs = dict(num_classes=args.num_classes, in_chans=args.in_chans, dtype=dtype)
        try:
            teacher = create_model(distill['teacher'], img_size=img_size, **teacher_kwargs)
        except TypeError as e:
            if 'img_size' not in str(e):
                raise
            teacher = create_model(distill['teacher'], **teacher_kwargs)
        if distill['checkpoint']:
            load_checkpoint(teacher, distill['checkpoint'])
        teacher.eval()
        if distill['kind'] == 'feature':
            FeatureDistillationTask.prepare_model(model, teacher)
        _logger.info(
            f"Distilling from teacher {distill['teacher']} "
            f"({distill['kind']}, alpha={distill['alpha']}, "
            + (f"T={distill['temperature']}" if distill['kind'] == 'logit'
               else f"feat_loss={distill['feat_loss']}") + ')')

    optimizer = create_optimizer_v2(model, **optimizer_kwargs(args))
    norm_mean = data_config['mean']
    norm_std = data_config['std']
    if args.naflex_loader:
        from timm_tpu.task import NaFlexClassificationTask
        task_cls = NaFlexClassificationTask
        # NaFlex batches are normalized host-side by the loader
        norm_mean = norm_std = None
    else:
        task_cls = ClassificationTask
    if distill is not None:
        task_cls = (LogitDistillationTask if distill['kind'] == 'logit'
                    else FeatureDistillationTask)
    if args.device_augment:
        if args.grad_accum_steps != 1:
            raise ValueError(
                '--device-augment yields device-resident batches; the host-side '
                'micro-batch concatenation of --grad-accum-steps > 1 would bounce '
                'them back to host. Use --grad-accum-steps 1')
        if num_aug_splits > 1:
            raise ValueError('--device-augment does not compose with --aug-splits '
                             '(split-batch augmentation collates on host)')
        if not args.naflex_loader and (args.synthetic_data or not args.data_dir):
            raise ValueError('--device-augment needs a real dataset pipeline; '
                             'pass --data-dir (synthetic batches are already device floats)')
        # the on-device augment stage normalizes; the task must not re-normalize
        norm_mean = norm_std = None
    task_kwargs = {}
    if args.naflex_loader and (args.mixup > 0 or args.cutmix > 0):
        # smoothing folds into the soft mixed targets (reference mixup_target)
        task_kwargs['mixup_label_smoothing'] = args.smoothing
    if distill is not None:
        task_kwargs['teacher'] = teacher
        task_kwargs['distill_alpha'] = distill['alpha']
        if distill['kind'] == 'logit':
            task_kwargs['distill_temperature'] = distill['temperature']
        else:
            task_kwargs['feat_loss'] = distill['feat_loss']
    task = task_cls(
        model,
        optimizer=optimizer,
        mesh=mesh,
        grad_accum_steps=args.grad_accum_steps,
        clip_grad=args.clip_grad,
        clip_mode=args.clip_mode,
        mean=norm_mean,
        std=norm_std,
        nonfinite_guard=False if args.no_nonfinite_guard else None,
        nonfinite_tolerance=args.nonfinite_tolerance,
        fused_update=args.fused_update,
        **task_kwargs,
    )

    if 'fsdp' in mesh.axis_names or 'model' in mesh.axis_names:
        from flax import nnx
        from timm_tpu.parallel import activation_bytes_per_device, param_bytes_per_device
        rep_b, shard_b = param_bytes_per_device(nnx.state(model, nnx.Param), mesh)
        axes_str = ' x '.join(f'{a}={mesh.shape[a]}' for a in mesh.axis_names)
        _logger.info(
            f'Sharded mesh ({axes_str}): params per device '
            f'{shard_b / 1e6:.1f} MB (vs {rep_b / 1e6:.1f} MB replicated); optimizer '
            f'm/v shard identically (parallel/sharding.py rules)')
        width = getattr(model, 'embed_dim', None)
        depth = len(getattr(model, 'blocks', None) or ())
        seq_len = getattr(getattr(model, 'patch_embed', None), 'num_patches', None)
        if width and depth and seq_len:
            act_u, act_c = activation_bytes_per_device(
                mesh, batch_size=args.batch_size, seq_len=seq_len, width=width, depth=depth)
            _logger.info(
                f'Estimated block activations per device: {act_c / 1e6:.1f} MB with '
                f'activation sharding constraints (vs {act_u / 1e6:.1f} MB without)')

    # loss selection (ref train.py:886-913)
    if args.jsd_loss:
        assert num_aug_splits > 1, '--jsd-loss requires --aug-splits > 1'
        from timm_tpu.loss import JsdCrossEntropy
        train_loss = JsdCrossEntropy(num_splits=num_aug_splits, smoothing=args.smoothing)
    elif args.mixup > 0 or args.cutmix > 0:
        train_loss = BinaryCrossEntropy(
            smoothing=0.0, target_threshold=args.bce_target_thresh, sum_classes=args.bce_sum,
        ) if args.bce_loss else SoftTargetCrossEntropy()
    elif args.smoothing:
        train_loss = BinaryCrossEntropy(
            smoothing=args.smoothing, target_threshold=args.bce_target_thresh, sum_classes=args.bce_sum,
        ) if args.bce_loss else LabelSmoothingCrossEntropy(smoothing=args.smoothing)
    else:
        train_loss = LabelSmoothingCrossEntropy(0.0)
    task.train_loss_fn = train_loss

    if args.model_ema:
        task.setup_ema(decay=args.model_ema_decay, warmup=args.model_ema_warmup)

    # data
    if args.naflex_loader:
        if not args.data_dir:
            raise ValueError('--naflex-loader requires --data-dir')
        from timm_tpu.data import create_dataset
        from timm_tpu.data.naflex_loader import create_naflex_loader
        patch_size = getattr(model.embeds, 'patch_size', 16) if hasattr(model, 'embeds') else 16
        dataset_train = create_dataset(
            args.dataset, root=args.data_dir, split=args.train_split, is_training=True,
            class_map=args.class_map)
        dataset_eval = create_dataset(
            args.dataset, root=args.data_dir, split=args.val_split, class_map=args.class_map)
        loader_train = create_naflex_loader(
            dataset_train, patch_size=patch_size,
            patch_size_choices=tuple(args.naflex_patch_sizes) if args.naflex_patch_sizes else None,
            train_seq_lens=tuple(args.naflex_train_seq_lens),
            max_seq_len=args.naflex_max_seq_len,
            batch_size=args.batch_size, is_training=True,
            mean=data_config['mean'], std=data_config['std'],
            interpolation=data_config['interpolation'], hflip=args.hflip,
            mixup_alpha=args.mixup, cutmix_alpha=args.cutmix,
            mixup_prob=args.mixup_prob, mixup_switch_prob=args.mixup_switch_prob,
            re_prob=args.reprob, re_mode='pixel' if args.remode == 'pixel' else 'const',
            seed=args.seed, grad_accum_steps=args.grad_accum_steps,
            device_augment=args.device_augment,
            bucket_mode=args.naflex_bucket_mode,
            device_prefetch=args.device_prefetch if args.device_augment else 0)
        loader_eval = create_naflex_loader(
            dataset_eval, patch_size=patch_size,
            max_seq_len=args.naflex_max_seq_len,
            batch_size=args.validation_batch_size or args.batch_size,
            mean=data_config['mean'], std=data_config['std'],
            interpolation=data_config['interpolation'], seed=args.seed)
        mixup_fn = None
    elif args.synthetic_data or not args.data_dir:
        _logger.info('Using synthetic data')
        loader_train = SyntheticLoader(args.synthetic_len, args.batch_size, img_size,
                                       args.num_classes, args.seed,
                                       process_index=rank, process_count=world_size)
        loader_eval = SyntheticLoader(max(args.synthetic_len // 4, args.batch_size),
                                      args.validation_batch_size or args.batch_size,
                                      img_size, args.num_classes, args.seed + 1,
                                      process_index=rank, process_count=world_size)
        mixup_fn = 'auto'
    else:
        from timm_tpu.data import create_dataset, create_loader
        dataset_train = create_dataset(
            args.dataset, root=args.data_dir, split=args.train_split, is_training=True,
            class_map=args.class_map, num_classes=args.num_classes)
        dataset_eval = create_dataset(
            args.dataset, root=args.data_dir, split=args.val_split, is_training=False,
            class_map=args.class_map, num_classes=args.num_classes)
        if num_aug_splits > 1:
            if not hasattr(dataset_train, '__getitem__'):
                raise ValueError(
                    '--aug-splits requires a map-style dataset (folder/tar/hfds); '
                    'streaming schemes (wds/tfds/hfids) are not supported')
            from timm_tpu.data.dataset import AugMixDataset
            dataset_train = AugMixDataset(dataset_train, num_splits=num_aug_splits)
        train_mixup = None
        if args.device_augment and (args.mixup > 0 or args.cutmix > 0):
            # parameter sampler only — the pixel/target math runs in the
            # loader's jitted on-device program (data/device_augment.py)
            from timm_tpu.data.mixup import Mixup
            train_mixup = Mixup(
                mixup_alpha=args.mixup, cutmix_alpha=args.cutmix, cutmix_minmax=args.cutmix_minmax,
                prob=args.mixup_prob, switch_prob=args.mixup_switch_prob, mode=args.mixup_mode,
                label_smoothing=args.smoothing, num_classes=args.num_classes, seed=args.seed)
        loader_train = create_loader(
            dataset_train,
            input_size=data_config['input_size'],
            batch_size=args.batch_size,
            is_training=True,
            no_aug=args.no_aug,
            scale=args.scale,
            ratio=args.ratio,
            hflip=args.hflip,
            vflip=args.vflip,
            color_jitter=args.color_jitter,
            auto_augment=args.aa,
            re_prob=args.reprob,
            re_mode=args.remode,
            re_count=args.recount,
            num_aug_splits=num_aug_splits,
            interpolation=args.train_interpolation,
            mean=data_config['mean'],
            std=data_config['std'],
            num_workers=args.workers,
            seed=args.seed,
            device_augment=args.device_augment,
            mixup=train_mixup,
            device_prefetch=args.device_prefetch if args.device_augment else 0,
        )
        loader_eval = create_loader(
            dataset_eval,
            input_size=data_config['input_size'],
            batch_size=args.validation_batch_size or args.batch_size,
            is_training=False,
            interpolation=data_config['interpolation'],
            mean=data_config['mean'],
            std=data_config['std'],
            num_workers=args.workers,
            crop_pct=data_config['crop_pct'],
        )
        # device_augment folds mixup into the loader's on-device program
        mixup_fn = None if args.device_augment else 'auto'

    # mixup applies to any (input, target)-tuple loader; naflex handles its own
    if mixup_fn == 'auto':
        from timm_tpu.data.mixup import Mixup
        mixup_fn = None
        if args.mixup > 0 or args.cutmix > 0:
            mixup_fn = Mixup(
                mixup_alpha=args.mixup, cutmix_alpha=args.cutmix, cutmix_minmax=args.cutmix_minmax,
                prob=args.mixup_prob, switch_prob=args.mixup_switch_prob, mode=args.mixup_mode,
                label_smoothing=args.smoothing, num_classes=args.num_classes)

    if args.device_prefetch:
        from timm_tpu.data.loader import DevicePrefetcher
        loader_eval = DevicePrefetcher(loader_eval, size=args.device_prefetch)
        if args.device_augment:
            # create_loader / create_naflex_loader already prefetch inside
            # the device-augment stack; batches here are device-resident
            pass
        elif mixup_fn is None and args.grad_accum_steps == 1:
            loader_train = DevicePrefetcher(loader_train, size=args.device_prefetch)
        else:
            # mixup / grad-accum concatenation still mutate batches on host;
            # prefetching to device first would bounce them straight back
            _logger.info('--device-prefetch: train loader stays on host '
                         '(mixup or --grad-accum-steps > 1 active); eval loader prefetches')

    # scheduler
    try:
        steps_per_epoch = len(loader_train)
    except TypeError:
        # streaming dataset with unknown length: --epoch-size defines the epoch
        if not args.epoch_size:
            raise ValueError(
                'streaming dataset has no known length; pass --epoch-size N '
                '(samples per epoch) or provide an _info.json shard sidecar')
        steps_per_epoch = max(args.epoch_size // args.batch_size, 1)
    if args.naflex_loader:
        # each NaFlex loader batch is one update (accumulation happens INSIDE
        # task.train_step over microbatches of the accum-scaled batch)
        updates_per_epoch = steps_per_epoch
    else:
        updates_per_epoch = (steps_per_epoch + args.grad_accum_steps - 1) // args.grad_accum_steps
    lr_scheduler, num_epochs = create_scheduler_v2(
        base_lr=args.lr,
        **{k: v for k, v in scheduler_kwargs(args).items() if k != 'num_epochs'},
        num_epochs=args.epochs,
        updates_per_epoch=updates_per_epoch,
    )
    start_epoch = 0
    if args.start_epoch is not None:
        start_epoch = args.start_epoch

    # output / saver — created BEFORE resume so `--resume auto` can scan the
    # experiment dir (pass --experiment for a stable dir across restarts);
    # CheckpointSaver's constructor also sweeps orphaned tmp / corrupt
    # recovery files left by a crash
    saver = None
    output_dir = None
    exp_name = args.experiment or '-'.join([
        datetime.now().strftime('%Y%m%d-%H%M%S'), args.model, str(img_size)])
    async_writer = None
    if rank == 0:
        output_dir = get_outdir(args.output if args.output else './output/train', exp_name)
    elif args.experiment:
        # non-primary hosts resolve the same (shared-FS) dir for auto-resume
        # and — multi-process — for their own checkpoint shard files
        output_dir = os.path.join(args.output if args.output else './output/train', exp_name)
        os.makedirs(output_dir, exist_ok=True)
    if output_dir is not None and (rank == 0 or world_size > 1):
        if os.environ.get('TIMM_TPU_ASYNC_CKPT', '1') != '0':
            # async checkpointing (default on): the step loop only snapshots
            # state to host; fsync/os.replace run on this writer thread.
            # TIMM_TPU_ASYNC_CKPT=0 restores fully synchronous writes.
            # Multi-process keeps one writer thread PER PROCESS: each host
            # writes only its own shard file.
            async_writer = AsyncCheckpointWriter()
        saver = CheckpointSaver(
            task, args=args, checkpoint_dir=output_dir, recovery_dir=output_dir,
            decreasing=args.eval_metric == 'loss', max_history=args.checkpoint_hist,
            async_writer=async_writer,
            process_index=rank, process_count=world_size)
    if rank == 0 and output_dir is not None:
        with open(os.path.join(output_dir, 'args.yaml'), 'w') as f:
            f.write(args_text)

    # resume: integrity-verified load with fallback to the newest valid
    # checkpoint; 'auto' resolves recovery/last/checkpoint-* newest-first
    start_batch_idx = 0
    resume_num_updates = None
    resume_path = ''
    if args.resume == 'auto':
        resume_path = resolve_auto_resume(output_dir) if output_dir else None
        if not resume_path:
            _logger.info(f'auto-resume: no valid checkpoint under {output_dir}; starting fresh')
    elif args.resume:
        resume_path = args.resume
    if resume_path:
        state, _ck_meta, used_path = load_with_fallback(
            resume_path, search_dir=output_dir or os.path.dirname(os.path.abspath(resume_path)))
        # one-line diff of state keys instead of a strict=True stack trace
        template = set(task.get_checkpoint_state())
        loaded = {k for k in state if not k.startswith('_resume.') and k not in ('epoch', 'metric')}
        missing, unexpected = sorted(template - loaded), sorted(loaded - template)
        if missing or unexpected:
            _logger.warning(
                f'Resume state diff: {len(missing)} missing '
                f'{missing[:5] + (["..."] if len(missing) > 5 else [])}, '
                f'{len(unexpected)} unexpected '
                f'{unexpected[:5] + (["..."] if len(unexpected) > 5 else [])}')
        task.load_checkpoint_state(state, strict=False, load_opt=not args.no_resume_opt)
        restore_host_rng(state)
        ck_epoch = int(state['epoch']) if 'epoch' in state else 0
        if state.get('_resume.mid_epoch') is not None and int(state['_resume.mid_epoch']):
            # step-granular recovery: re-enter the SAME epoch, skip the
            # already-consumed loader batches, continue the update counter
            start_epoch = ck_epoch
            start_batch_idx = int(state['_resume.batches_consumed'])
            if '_resume.batch_size' in state:
                old_bs = int(state['_resume.batch_size'])
                if old_bs != args.batch_size:
                    start_batch_idx, exact = convert_loader_position(
                        start_batch_idx, old_bs, args.batch_size)
                    _logger.warning(
                        f'Loader batch size changed {old_bs} -> {args.batch_size} on '
                        f'resume: position converted to {start_batch_idx} batches'
                        + ('' if exact else ' (inexact: partial batch re-seen)')
                        + '; data order is only bit-identical when the loader '
                          'batch size is unchanged')
            resume_num_updates = int(state['_resume.num_updates'])
            _logger.info(
                f'Resumed mid-epoch from {used_path}: epoch {start_epoch}, '
                f'batch {start_batch_idx}, update {resume_num_updates}')
        else:
            if args.start_epoch is None:
                start_epoch = ck_epoch + 1
            _logger.info(f'Resumed from {used_path} at epoch {start_epoch}')

    # prime the scheduler so epoch 0 (or the resume epoch) starts at warmup LR
    if lr_scheduler is not None:
        if args.sched_on_updates:
            lr_scheduler.step_update(resume_num_updates if resume_num_updates is not None
                                     else start_epoch * updates_per_epoch)
        else:
            lr_scheduler.step(start_epoch)
            if resume_num_updates is not None:
                lr_scheduler.step_update(resume_num_updates)

    # preemption-aware shutdown: SIGTERM/SIGINT set a flag the train loop
    # polls; on preemption a step-granular recovery checkpoint is written and
    # the process exits 0 (resume with `--resume auto`)
    shutdown = GracefulShutdown().install()
    rollback_budget = [int(os.environ.get('TIMM_TPU_ROLLBACK_BUDGET', '1'))
                       if args.nonfinite_rollback else 0]

    best_metric = None
    best_epoch = None
    eval_metrics = {}
    try:
        for epoch in range(start_epoch, num_epochs):
            if shutdown.requested:
                # preempted at an epoch boundary: last.npz already covers resume
                _logger.warning(f'Shutdown requested; stopping before epoch {epoch} '
                                f'(resume with --resume auto)')
                raise SystemExit(0)
            if hasattr(loader_train, 'set_epoch'):
                loader_train.set_epoch(epoch)  # fresh shuffle/schedule (ref train.py:478)
            if args.mixup_off_epoch and epoch >= args.mixup_off_epoch:
                if mixup_fn is not None:
                    mixup_fn.mixup_enabled = False  # ref train.py disable-mixup schedule
                elif getattr(loader_train, 'mixup', None) is not None:
                    # device-augment stage: same schedule; the sampler emits
                    # identity params (lam=1) so the jitted program is unchanged
                    loader_train.mixup.mixup_enabled = False
            try:
                train_metrics = train_one_epoch(
                    epoch, task, loader_train, args, lr_scheduler, mesh, shard_batch,
                    updates_per_epoch, saver=saver, mixup_fn=mixup_fn, shutdown=shutdown,
                    skip_batches=start_batch_idx if epoch == start_epoch else 0,
                    start_updates=resume_num_updates if epoch == start_epoch else None,
                    rollback_budget=rollback_budget)
            except TrainingPreempted as e:
                _logger.warning(f'Preempted during epoch {epoch}; recovery checkpoint: '
                                f'{e.recovery_path or "(non-primary host)"}. Exiting 0 for reschedule.')
                raise SystemExit(0)
            except NonFiniteError as e:
                _logger.error(f'Aborting training: {e}')
                raise SystemExit(3)

            eval_metrics = validate(task, loader_eval, args, mesh, shard_batch)
            if task.ema_params is not None:
                ema_metrics = validate(task, loader_eval, args, mesh, shard_batch, use_ema=True)
                eval_metrics.update({f'{k}_ema': v for k, v in ema_metrics.items()})

            if output_dir is not None and is_primary(args):
                update_summary(
                    epoch, train_metrics, eval_metrics,
                    filename=os.path.join(output_dir, 'summary.csv'),
                    lr=train_metrics.get('lr'),
                    write_header=epoch == start_epoch, log_wandb=args.log_wandb)
            if saver is not None:
                best_metric, best_epoch = saver.save_checkpoint(epoch, metric=eval_metrics.get(args.eval_metric))
            if lr_scheduler is not None:
                lr_scheduler.step(epoch + 1, eval_metrics.get(args.eval_metric))
    finally:
        # drain the async writer on EVERY exit — including the SystemExit(0)
        # a SIGTERM/TrainingPreempted turns into — so the recovery checkpoint
        # is durable before the scheduler restarts us. A pending write failure
        # raises here: an undrained writer must fail as loudly as a sync one.
        if async_writer is not None:
            async_writer.close()

    if best_metric is not None:
        _logger.info(f'*** Best metric: {best_metric} (epoch {best_epoch})')
        if is_primary(args):
            print(json.dumps({'result': {args.eval_metric: best_metric, 'epoch': best_epoch}}))
    return eval_metrics


def _recovery_extras(batches_consumed, num_updates, args=None):
    """Step-granular resume state stored alongside the task state in a
    recovery checkpoint: loader position, update counter, host RNG streams —
    plus the batch geometry an `--elastic` restart needs to hold the global
    batch constant on a different topology."""
    from timm_tpu.resilience import capture_host_rng
    extras = {
        '_resume.mid_epoch': np.asarray(1),
        '_resume.batches_consumed': np.asarray(batches_consumed),
        '_resume.num_updates': np.asarray(num_updates),
    }
    if args is not None:
        extras['_resume.batch_size'] = np.asarray(args.batch_size)
        extras['_resume.global_batch'] = np.asarray(args.batch_size * args.grad_accum_steps)
        extras['_resume.device_count'] = np.asarray(jax.device_count())
        extras['_resume.process_count'] = np.asarray(jax.process_count())
    extras.update(capture_host_rng())
    return extras


def _resilient_train_step(task, batch, lr, step, args, saver, rollback_budget):
    """task.train_step with optional rollback-to-last-checkpoint when the
    non-finite tolerance trips. Returns metrics, or None when the step was
    dropped by a rollback (caller skips the batch and continues)."""
    from timm_tpu.resilience import NonFiniteError, load_with_fallback, resolve_auto_resume
    try:
        return task.train_step(batch, lr=lr, step=step)
    except NonFiniteError:
        if not rollback_budget or rollback_budget[0] <= 0 or saver is None:
            raise
        rb = resolve_auto_resume(saver.checkpoint_dir)
        if rb is None:
            raise
        state, _meta, used = load_with_fallback(rb, search_dir=saver.checkpoint_dir)
        task.load_checkpoint_state(state, strict=False)
        task.reset_nonfinite()
        rollback_budget[0] -= 1
        _logger.warning(
            f'Non-finite tolerance hit at update {step}: rolled back to {used} '
            f'({rollback_budget[0]} rollback(s) left); continuing')
        return None


def train_one_epoch(epoch, task, loader, args, lr_scheduler, mesh, shard_batch,
                    updates_per_epoch, saver=None, mixup_fn=None, shutdown=None,
                    skip_batches=0, start_updates=None, rollback_budget=None):
    from timm_tpu.resilience import TrainingPreempted, get_fault_injector
    from timm_tpu.utils import AverageMeter
    loss_m = AverageMeter()
    accum = args.grad_accum_steps
    num_updates = start_updates if start_updates is not None else epoch * updates_per_epoch
    lr = lr_scheduler.get_last_lr()[0] if lr_scheduler else args.lr
    injector = get_fault_injector()

    def poll_faults_and_shutdown(batch_idx, update_idx):
        """After each committed update: deliver injected SIGKILL/SIGTERM, then
        write a step-granular recovery checkpoint and stop if shutdown was
        requested."""
        if injector is not None and injector.kill_host_at(num_updates - 1, jax.process_index()):
            # host-loss drill: die NOW, before any consensus/recovery save —
            # the victim must never publish its stop vote, so the survivors'
            # next named consensus times out on it and resolves to stop.
            # Drain the dispatched step first (its collective sends must land
            # so survivors can materialize the post-step state on their own).
            jax.block_until_ready((metrics, task.opt_state))
            _logger.warning(f'[fault-inject] kill_host at update {num_updates - 1}: SIGKILL')
            os.kill(os.getpid(), __import__('signal').SIGKILL)
        if injector is not None and injector.sigterm_at(num_updates - 1):
            _logger.warning(f'[fault-inject] SIGTERM at update {num_updates - 1}')
            os.kill(os.getpid(), __import__('signal').SIGTERM)
        if injector is not None and injector.resize_at(num_updates - 1):
            # in-process, a resize IS a preemption: SIGTERM now; the restart
            # harness (tests/fsdp_drill.py) relaunches with the new topology
            _logger.warning(f'[fault-inject] resize to {injector.resize_devices} '
                            f'devices at update {num_updates - 1}: delivering SIGTERM')
            os.kill(os.getpid(), __import__('signal').SIGTERM)
        if shutdown is not None and shutdown.should_stop(update_idx):
            path = ''
            if saver is not None:
                path = saver.save_recovery(
                    epoch, update_idx,
                    extra_state=_recovery_extras(batch_idx + 1, num_updates, args))
            raise TrainingPreempted(path)

    metrics = {}
    micro_inputs, micro_targets = [], []
    update_idx = skip_batches // accum  # display/recovery cadence continuity on resume
    samples_since_log = 0
    log_t0 = time.time()
    for batch_idx, batch_data in enumerate(loader):
        if batch_idx < skip_batches:
            continue  # mid-epoch resume: already consumed before preemption
        if isinstance(batch_data, dict):
            # NaFlex dict batch; scalar metadata (seq_len/patch_size) stays on
            # host — the model derives the patch size from the patch dim shape
            n = batch_data['patches'].shape[0]
            if injector is not None and injector.nan_at(num_updates):
                _logger.warning(f'[fault-inject] NaN batch at update {num_updates}')
                batch_data = dict(batch_data, patches=np.asarray(batch_data['patches']) * np.nan)
            batch = shard_batch(
                {k: jnp.asarray(v) for k, v in batch_data.items()
                 if k not in ('seq_len', 'patch_size')}, mesh)
            metrics = _resilient_train_step(task, batch, lr, num_updates, args, saver, rollback_budget)
            if metrics is None:
                update_idx += 1
                continue
            num_updates += 1
            samples_since_log += n
            if lr_scheduler is not None:
                lr = lr_scheduler.step_update(num_updates)[0]
            if update_idx % args.log_interval == 0:
                loss_val = float(metrics['loss'])
                if np.isfinite(loss_val):
                    loss_m.update(loss_val, n=n)
                elapsed = time.time() - log_t0
                _logger.info(
                    f'Train: {epoch} [{update_idx:>4d}/{updates_per_epoch}] '
                    f'Loss: {loss_m.val:#.3g} ({loss_m.avg:#.3g}) LR: {lr:.3e} '
                    f'seq: {batch_data["seq_len"]} {samples_since_log / max(elapsed, 1e-9):.1f} img/s')
                samples_since_log = 0
                log_t0 = time.time()
            if saver is not None and args.recovery_interval and (update_idx + 1) % args.recovery_interval == 0:
                saver.save_recovery(epoch, update_idx,
                                    extra_state=_recovery_extras(batch_idx + 1, num_updates, args))
            poll_faults_and_shutdown(batch_idx, update_idx)
            update_idx += 1
            continue
        input_np, target_np = batch_data
        if mixup_fn is not None:
            input_np, target_np = mixup_fn(input_np, target_np)
        micro_inputs.append(input_np)
        micro_targets.append(target_np)
        if len(micro_inputs) < accum:
            continue  # accumulate across loader batches (ref train.py:1266-1281)
        if accum > 1:
            input_all = np.concatenate(micro_inputs, axis=0)
            target_all = np.concatenate(micro_targets, axis=0)
        else:
            input_all, target_all = micro_inputs[0], micro_targets[0]
        micro_inputs, micro_targets = [], []
        if injector is not None and injector.nan_at(num_updates):
            _logger.warning(f'[fault-inject] NaN batch at update {num_updates}')
            input_all = np.asarray(input_all) * np.nan
        batch = shard_batch({'input': jnp.asarray(input_all), 'target': jnp.asarray(target_all)}, mesh)
        metrics = _resilient_train_step(task, batch, lr, num_updates, args, saver, rollback_budget)
        if metrics is None:
            update_idx += 1
            continue
        num_updates += 1
        samples_since_log += input_all.shape[0]
        if lr_scheduler is not None:
            lr = lr_scheduler.step_update(num_updates)[0]
        if update_idx % args.log_interval == 0:
            loss_val = float(metrics['loss'])  # sync point
            if np.isfinite(loss_val):  # a skipped non-finite step must not poison the meter
                loss_m.update(loss_val, n=input_all.shape[0])
            elapsed = time.time() - log_t0
            ips = samples_since_log / max(elapsed, 1e-9)
            samples_since_log = 0
            log_t0 = time.time()
            nf = int(metrics['nonfinite_total']) if 'nonfinite_total' in metrics else 0
            _logger.info(
                f'Train: {epoch} [{update_idx:>4d}/{updates_per_epoch}] '
                f'Loss: {loss_m.val:#.3g} ({loss_m.avg:#.3g}) LR: {lr:.3e} '
                f'{ips:.1f} img/s' + (f' NaN-skipped: {nf}' if nf else ''))
        if saver is not None and args.recovery_interval and (update_idx + 1) % args.recovery_interval == 0:
            saver.save_recovery(epoch, update_idx,
                                extra_state=_recovery_extras(batch_idx + 1, num_updates, args))
        poll_faults_and_shutdown(batch_idx, update_idx)
        update_idx += 1
    if micro_inputs:
        # flush trailing partial accumulation group: pad by wrapping samples so
        # the step shape stays static (slight duplicate weighting on the tail)
        input_all = np.concatenate(micro_inputs, axis=0)
        target_all = np.concatenate(micro_targets, axis=0)
        need = accum * micro_inputs[0].shape[0] - input_all.shape[0]
        if need > 0:
            reps = -(-need // input_all.shape[0])
            input_all = np.concatenate([input_all] + [input_all] * reps, axis=0)[:accum * micro_inputs[0].shape[0]]
            target_all = np.concatenate([target_all] + [target_all] * reps, axis=0)[:accum * micro_inputs[0].shape[0]]
        batch = shard_batch({'input': jnp.asarray(input_all), 'target': jnp.asarray(target_all)}, mesh)
        metrics = _resilient_train_step(task, batch, lr, num_updates, args, saver, rollback_budget)
        if metrics is not None:
            num_updates += 1
            if lr_scheduler is not None:
                lr = lr_scheduler.step_update(num_updates)[0]
    out = OrderedDict([('loss', loss_m.avg if loss_m.count else float((metrics or {}).get('loss', 0.0))), ('lr', lr)])
    if metrics and 'nonfinite_total' in metrics:
        out['nonfinite_steps'] = int(metrics['nonfinite_total'])
    return out


def _local_rows(arr):
    """Host-local rows of a (possibly) multi-process sharded array, in batch
    order. `float()`/eager jnp ops are illegal on non-fully-addressable
    arrays; metrics therefore reduce the ADDRESSABLE shards (deduped by
    replica_id, so tensor-parallel replication doesn't double-count) on host
    and cross-process-average at the end via `reduce_tensor`."""
    if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
        return np.asarray(arr)
    shards = [s for s in arr.addressable_shards if s.replica_id == 0]
    shards.sort(key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


def validate(task, loader, args, mesh, shard_batch, use_ema=False):
    """Eval loop. Each process scores its own addressable rows of the sharded
    eval output; per-process means are averaged across hosts at the end
    (every host sees the same batch count, so the mean-of-means is exact)."""
    from timm_tpu.parallel import reduce_tensor
    from timm_tpu.utils import AverageMeter
    loss_m = AverageMeter()
    top1_m = AverageMeter()
    top5_m = AverageMeter()
    for batch_data in loader:
        if isinstance(batch_data, dict):
            batch = shard_batch(
                {k: jnp.asarray(v) for k, v in batch_data.items() if k != 'seq_len'}, mesh)
            output = task.eval_step({k: batch[k] for k in batch if k != 'target'}, use_ema=use_ema)
            target = batch['target']
        else:
            input_np, target_np = batch_data
            batch = shard_batch({'input': jnp.asarray(input_np), 'target': jnp.asarray(target_np)}, mesh)
            output = task.eval_step({'input': batch['input']}, use_ema=use_ema)
            target = batch['target']
        out_np = _local_rows(output).astype(np.float32)
        tgt_np = _local_rows(target)
        if out_np.shape[0] == 0:
            continue
        shifted = out_np - out_np.max(axis=-1, keepdims=True)
        logprobs = shifted - np.log(np.exp(shifted).sum(axis=-1, keepdims=True))
        loss = -np.take_along_axis(logprobs, tgt_np[:, None], axis=-1).mean()
        top_pred = np.argsort(out_np, axis=-1)[:, -5:]
        correct1 = (top_pred[:, -1] == tgt_np).mean() * 100.0
        correct5 = (top_pred == tgt_np[:, None]).any(axis=-1).mean() * 100.0
        n = out_np.shape[0]
        loss_m.update(float(loss), n)
        top1_m.update(float(correct1), n)
        top5_m.update(float(correct5), n)
    return OrderedDict([('loss', float(reduce_tensor(loss_m.avg))),
                        ('top1', float(reduce_tensor(top1_m.avg))),
                        ('top5', float(reduce_tensor(top5_m.avg)))])


if __name__ == '__main__':
    try:
        main()
    except SystemExit as e:
        # Preemption/abort exits in a multi-process run must NOT run the
        # distributed client's atexit shutdown barrier: after a host loss it
        # raises a fatal C++ error that turns a clean exit-0 into SIGABRT.
        # Recovery state is already durable (the writer drained in main's
        # finally), so a hard exit loses nothing.
        if jax.process_count() > 1:
            logging.shutdown()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(int(e.code or 0))
        raise
