#!/usr/bin/env python3
"""Checkpoint evaluation script (reference: validate.py:1-571).

Evaluates a model (optionally from checkpoint) on a validation set; outputs
top-1/top-5, loss, throughput; csv/json results; bulk model-list mode.
"""
from __future__ import annotations

import argparse
import csv
import glob
import json
import logging
import os
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

_logger = logging.getLogger('validate')

parser = argparse.ArgumentParser(description='TPU-native ImageNet validation')
parser.add_argument('data', nargs='?', metavar='DIR', const=None, help='path to dataset (positional)')
parser.add_argument('--data-dir', metavar='DIR', help='path to dataset root')
parser.add_argument('--dataset', metavar='NAME', default='')
parser.add_argument('--split', metavar='NAME', default='validation')
parser.add_argument('--model', '-m', metavar='NAME', default='vit_tiny_patch16_224')
parser.add_argument('--pretrained', dest='pretrained', action='store_true')
parser.add_argument('--checkpoint', default='', type=str, metavar='PATH')
parser.add_argument('--use-ema', dest='use_ema', action='store_true')
parser.add_argument('-b', '--batch-size', default=256, type=int, metavar='N')
parser.add_argument('--img-size', default=None, type=int, metavar='N')
parser.add_argument('--device', default=None, type=str,
                    help="jax platform override (e.g. 'cpu'); must be set before first device op")
parser.add_argument('--input-size', default=None, nargs=3, type=int, metavar='N N N')
parser.add_argument('--crop-pct', default=None, type=float, metavar='N')
parser.add_argument('--crop-mode', default=None, type=str, metavar='N')
parser.add_argument('--mean', type=float, nargs='+', default=None, metavar='MEAN')
parser.add_argument('--std', type=float, nargs='+', default=None, metavar='STD')
parser.add_argument('--interpolation', default='', type=str, metavar='NAME')
parser.add_argument('--num-classes', type=int, default=None)
parser.add_argument('--class-map', default='', type=str, metavar='FILENAME')
parser.add_argument('-j', '--workers', default=4, type=int, metavar='N')
parser.add_argument('--log-freq', default=20, type=int, metavar='N')
parser.add_argument('--amp', action='store_true', default=False, help='bf16 compute')
parser.add_argument('--test-pool', dest='test_pool', action='store_true',
                    help='(not yet supported; warns if set)')
parser.add_argument('--real-labels', default='', type=str, metavar='FILENAME',
                    help='ImageNet-Real labels json for relabeled eval')
parser.add_argument('--results-file', default='', type=str, metavar='FILENAME')
parser.add_argument('--results-format', default='csv', type=str)
parser.add_argument('--model-list', default='', type=str, metavar='FILENAME or WILDCARD',
                    help='evaluate a list/wildcard of models in sequence')
parser.add_argument('--retry', default=False, action='store_true',
                    help='halve batch size and retry on resource exhaustion')
parser.add_argument('--block-scan', action='store_true', default=False,
                    help='scan-over-layers block execution (O(1)-in-depth trace/compile)')
parser.add_argument('--device-prefetch', type=int, default=0, metavar='N',
                    help='keep N batches in flight on device while the step runs; 0 disables')
parser.add_argument('--quantize', default='', type=str, choices=['', 'int8'],
                    help='post-training weight-only quantization of the eval forward '
                         '(serve-path parity): int8 per-output-channel symmetric scales, '
                         'dequantized at use inside the jitted step')
parser.add_argument('--quant-top1-delta', default=0.5, type=float, metavar='PCT',
                    help='with --quantize: also run the fp32 arm on every batch (same data '
                         'pass) and fail if quantized top-1 drops more than this many '
                         'points below fp32; <= 0 skips the fp32 arm and the gate')
parser.add_argument('--fsdp', type=int, default=0, metavar='N',
                    help="shard model weights over an N-way 'fsdp' mesh axis for eval "
                         '(fits models larger than one chip HBM); 0 disables')
parser.add_argument('--tp', type=int, default=0, metavar='N',
                    help="tensor parallelism for eval: shard attention heads + MLP hidden "
                         "over an N-way 'model' mesh axis (composes with --fsdp); 0 disables")


def validate(args):
    import timm_tpu
    from timm_tpu.data import create_dataset, create_loader, resolve_data_config
    from timm_tpu.models import load_checkpoint
    from timm_tpu.parallel import create_mesh, set_global_mesh, shard_batch
    from timm_tpu.utils import AverageMeter

    if args.device:
        # must land before the first device op; env JAX_PLATFORMS loses to the
        # axon plugin's sitecustomize registration
        jax.config.update('jax_platforms', args.device)
    from timm_tpu.utils import configure_compile_cache
    configure_compile_cache()
    mesh = create_mesh(fsdp=args.fsdp if args.fsdp else None,
                       tp=args.tp if args.tp else None)
    set_global_mesh(mesh)

    dtype = jnp.bfloat16 if args.amp else None
    try:
        model = timm_tpu.create_model(
            args.model,
            pretrained=args.pretrained,
            num_classes=args.num_classes,
            img_size=args.img_size,
            dtype=dtype,
        )
    except TypeError:
        # conv archs take no img_size; it still drives the data config below
        model = timm_tpu.create_model(
            args.model, pretrained=args.pretrained, num_classes=args.num_classes, dtype=dtype)
    num_classes = args.num_classes or model.num_classes
    if args.checkpoint:
        load_checkpoint(model, args.checkpoint, use_ema=args.use_ema)
    if args.block_scan:
        if hasattr(model, 'set_block_scan'):
            model.set_block_scan(True)
        else:
            _logger.warning(f'--block-scan: {args.model} has no scannable block stack; ignored')
    model.eval()

    data_config = resolve_data_config(vars(args), model=model)
    from timm_tpu.models import model_state_dict
    param_count = sum(v.size for v in model_state_dict(model, include_stats=False).values())
    _logger.info(f'Model {args.model} created, param count: {param_count/1e6:.1f}M')

    test_time_pool = False
    if args.test_pool:
        from timm_tpu.layers import apply_test_time_pool
        model, test_time_pool = apply_test_time_pool(model, data_config)
        if test_time_pool:
            data_config['crop_pct'] = 1.0  # full-image input for TTA pooling
        else:
            _logger.info('--test-pool requested but eval size does not exceed the '
                         'pretrained default; using the standard head')

    root = args.data_dir or args.data
    dataset = create_dataset(
        args.dataset, root=root, split=args.split, class_map=args.class_map)
    loader = create_loader(
        dataset,
        input_size=data_config['input_size'],
        batch_size=args.batch_size,
        interpolation=data_config['interpolation'],
        mean=data_config['mean'],
        std=data_config['std'],
        num_workers=args.workers,
        crop_pct=data_config['crop_pct'],
        crop_mode=data_config['crop_mode'],
        device_prefetch=args.device_prefetch,
    )

    real_labels = None
    if args.real_labels:
        from timm_tpu.data import RealLabelsImagenet
        real_labels = RealLabelsImagenet(
            dataset.filenames(basename=True), real_json=args.real_labels)

    from flax import nnx
    graphdef, state = nnx.split(model)
    if 'fsdp' in mesh.axis_names or 'model' in mesh.axis_names:
        # large weights shard over 'fsdp'/'model' (path-rule placement); XLA
        # gathers/keeps shards as the constraints dictate, so eval fits models
        # larger than one chip's HBM
        from timm_tpu.parallel import build_param_shardings
        state = jax.device_put(state, build_param_shardings(state, mesh))
    mean = jnp.asarray(data_config['mean'], jnp.float32).reshape(1, 1, 1, -1)
    std = jnp.asarray(data_config['std'], jnp.float32).reshape(1, 1, 1, -1)

    def make_eval_step(to_dense):
        @jax.jit
        def eval_step(state, x, target, valid):
            x = (x - mean) / std
            if dtype is not None:
                x = x.astype(dtype)
            logits = nnx.merge(graphdef, to_dense(state))(x).astype(jnp.float32)
            logprobs = jax.nn.log_softmax(logits, axis=-1)
            w = valid.astype(jnp.float32)
            denom = jnp.maximum(w.sum(), 1.0)
            loss = -(jnp.take_along_axis(logprobs, target[:, None], axis=-1)[:, 0] * w).sum() / denom
            top = jnp.argsort(logits, axis=-1)[:, -5:]
            acc1 = ((top[:, -1] == target) * w).sum() / denom * 100.0
            acc5 = ((top == target[:, None]).any(axis=-1) * w).sum() / denom * 100.0
            return loss, acc1, acc5, top[:, ::-1]  # top-5 preds, best first
        return eval_step

    # quantize-then-validate: the primary arm evaluates the int8 weights
    # (dequantized at use inside the jit, exactly the serve-path program);
    # the gate arm reruns fp32 on the SAME batches so the top-1 delta is a
    # single-pass paired comparison, not two dataset traversals
    eval_step_fp32 = None
    if args.quantize:
        from timm_tpu.quantize import dequantize_tree, quantize_tree
        eval_state = quantize_tree(state)
        if 'fsdp' in mesh.axis_names or 'model' in mesh.axis_names:
            from timm_tpu.parallel import build_quant_shardings
            eval_state = jax.device_put(
                eval_state, build_quant_shardings(eval_state, mesh))
        eval_step = make_eval_step(dequantize_tree)
        if args.quant_top1_delta > 0:
            eval_step_fp32 = make_eval_step(lambda s: s)
        _logger.info(f'Quantized weights to {args.quantize} for eval'
                     + ('' if eval_step_fp32 is None else
                        f' (fp32 gate arm on, max top-1 delta {args.quant_top1_delta})'))
    else:
        eval_state = state
        eval_step = make_eval_step(lambda s: s)

    # one bucket shape for the whole eval: batch_size rounded up to the mesh
    # shard count. The final partial batch pads up to the SAME shape as every
    # other batch (masked slots), so the loop compiles exactly one executable
    # instead of paying a fresh XLA compile for the odd-sized last batch.
    from timm_tpu.serve import batch_bucket, pad_rows
    bucket = batch_bucket(args.batch_size, mesh.size)

    loss_m, top1_m, top5_m, time_m = AverageMeter(), AverageMeter(), AverageMeter(), AverageMeter()
    top1_fp32_m = AverageMeter()
    end = time.time()
    for batch_idx, (x_np, t_np) in enumerate(loader):
        n = x_np.shape[0]
        x_np, t_np, valid_np = pad_rows(np.asarray(x_np), bucket, np.asarray(t_np))
        batch = shard_batch({'x': jnp.asarray(x_np), 't': jnp.asarray(t_np),
                             'v': jnp.asarray(valid_np)}, mesh)
        loss, acc1, acc5, topk = eval_step(eval_state, batch['x'], batch['t'], batch['v'])
        if eval_step_fp32 is not None:
            _, ref1, _, _ = eval_step_fp32(state, batch['x'], batch['t'], batch['v'])
            top1_fp32_m.update(float(ref1), n)
        if real_labels is not None:
            real_labels.add_result(np.asarray(topk)[:n], is_topk=True)  # drop pad rows
        loss_m.update(float(loss), n)
        top1_m.update(float(acc1), n)
        top5_m.update(float(acc5), n)
        time_m.update(time.time() - end)
        end = time.time()
        if batch_idx % args.log_freq == 0:
            _logger.info(
                f'Test: [{batch_idx:>4d}/{len(loader)}]  '
                f'Time: {time_m.val:.3f}s ({n / max(time_m.avg, 1e-9):>7.1f}/s)  '
                f'Loss: {loss_m.val:>7.4f} ({loss_m.avg:>6.4f})  '
                f'Acc@1: {top1_m.val:>7.3f} ({top1_m.avg:>7.3f})  '
                f'Acc@5: {top5_m.val:>7.3f} ({top5_m.avg:>7.3f})')

    if real_labels is not None:
        # replace top-1/5 with the relabeled scores (reference validate.py:418)
        top1_m.avg = real_labels.get_accuracy(k=1)
        top5_m.avg = real_labels.get_accuracy(k=5)
    results = OrderedDict(
        model=args.model,
        top1=round(top1_m.avg, 4), top1_err=round(100 - top1_m.avg, 4),
        top5=round(top5_m.avg, 4), top5_err=round(100 - top5_m.avg, 4),
        param_count=round(param_count / 1e6, 2),
        img_size=data_config['input_size'][-1],
        crop_pct=data_config['crop_pct'],
        interpolation=data_config['interpolation'],
    )
    if args.quantize:
        results['quantize'] = args.quantize
    _logger.info(' * Acc@1 {:.3f} ({:.3f}) Acc@5 {:.3f} ({:.3f})'.format(
        results['top1'], results['top1_err'], results['top5'], results['top5_err']))
    if eval_step_fp32 is not None:
        delta = top1_fp32_m.avg - top1_m.avg
        results['top1_fp32'] = round(top1_fp32_m.avg, 4)
        results['quant_top1_delta'] = round(delta, 4)
        _logger.info(f' * Quant gate: fp32 Acc@1 {top1_fp32_m.avg:.3f}, '
                     f'{args.quantize} Acc@1 {top1_m.avg:.3f}, delta {delta:+.4f} '
                     f'(max allowed {args.quant_top1_delta})')
        if delta > args.quant_top1_delta:
            raise RuntimeError(
                f'quantize-then-validate gate failed: {args.quantize} top-1 '
                f'{top1_m.avg:.4f} is {delta:.4f} points below fp32 '
                f'{top1_fp32_m.avg:.4f} (max allowed {args.quant_top1_delta})')
    return results


def main():
    from timm_tpu.models import is_model, list_models
    from timm_tpu.utils import setup_default_logging
    setup_default_logging()
    args = parser.parse_args()

    model_names = []
    if args.model_list:
        if os.path.exists(args.model_list):
            with open(args.model_list) as f:
                model_names = [line.strip() for line in f if line.strip()]
        else:
            model_names = list_models(args.model_list)
    def _validate_with_retry(args):
        """Batch-size decay retry (reference utils/decay_batch.py:8-43)."""
        batch_size = args.batch_size
        while batch_size >= 1:
            args.batch_size = batch_size
            try:
                return validate(args)
            except Exception as e:
                if args.retry and 'RESOURCE_EXHAUSTED' in str(e).upper() and batch_size > 1:
                    batch_size = max(1, batch_size // 2)
                    _logger.warning(f'OOM, retrying with batch size {batch_size}')
                    continue
                raise

    results = []
    if model_names:
        orig_batch = args.batch_size
        for name in model_names:
            args.model = name
            args.batch_size = orig_batch
            try:
                r = _validate_with_retry(args)
            except Exception as e:
                _logger.error(f'{name} failed: {e}')
                continue
            results.append(r)
        results = sorted(results, key=lambda x: x['top1'], reverse=True)
    else:
        results = [_validate_with_retry(args)]

    if args.results_file:
        if args.results_format == 'json':
            with open(args.results_file, 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process evaluation driver; no pod launch path
                json.dump(results, f, indent=2)
        else:
            with open(args.results_file, 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process evaluation driver; no pod launch path
                dw = csv.DictWriter(f, fieldnames=results[0].keys())
                dw.writeheader()
                for r in results:
                    dw.writerow(r)
    print(f'--result\n{json.dumps(results if len(results) > 1 else results[0], indent=4)}')


if __name__ == '__main__':
    main()
