#!/usr/bin/env python3
"""Run validate.py / benchmark.py over model lists as subprocesses
(reference: bulk_runner.py:1-244 — used to produce results/*.csv).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

parser = argparse.ArgumentParser(description='Per-model subprocess launcher')
parser.add_argument('script', choices=['validate', 'benchmark'], help='which script to run per model')
parser.add_argument('--model-list', default='', type=str,
                    help='txt file of model names, or a wildcard for list_models')
parser.add_argument('--pretrained', action='store_true', help='restrict wildcard to pretrained models')
parser.add_argument('--results-file', default='bulk_results.json', type=str)
parser.add_argument('--timeout', default=3600, type=int, help='per-model timeout (s)')
parser.add_argument('--start', default=0, type=int, help='resume: skip first N models')
# everything after '--' is forwarded to the child script


def main():
    argv = sys.argv[1:]
    passthrough = []
    if '--' in argv:
        idx = argv.index('--')
        passthrough = argv[idx + 1:]
        argv = argv[:idx]
    args = parser.parse_args(argv)

    if os.path.exists(args.model_list):
        with open(args.model_list) as f:
            model_names = [l.strip() for l in f if l.strip()]
    else:
        from timm_tpu.models import list_models
        model_names = list_models(args.model_list or '*', pretrained=args.pretrained)
    model_names = model_names[args.start:]
    print(f'Running {args.script} over {len(model_names)} models')

    def _extract_json(text: str):
        """Parse the trailing (possibly multi-line, indented) JSON payload."""
        for opener in ('{', '['):
            idx = text.rfind('\n' + opener)
            if idx == -1 and text.startswith(opener):
                idx = -1  # payload starts at position 0
            if idx != -1 or text.startswith(opener):
                candidate = text[idx + 1 if idx != -1 else 0:]
                try:
                    return json.loads(candidate)
                except json.JSONDecodeError:
                    continue
        return None

    results = []
    if args.start > 0 and os.path.exists(args.results_file):
        with open(args.results_file) as f:
            results = json.load(f)  # resume: keep completed entries
    for i, name in enumerate(model_names):
        cmd = [sys.executable, f'{args.script}.py', '--model', name] + passthrough
        t0 = time.time()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=args.timeout)
            ok = proc.returncode == 0
            payload = _extract_json(proc.stdout.strip())
            results.append({'model': name, 'ok': ok, 'seconds': round(time.time() - t0, 1),
                            'result': payload,
                            'error': proc.stderr.strip().splitlines()[-1] if (not ok and proc.stderr.strip()) else None})
        except subprocess.TimeoutExpired:
            results.append({'model': name, 'ok': False, 'seconds': args.timeout, 'error': 'timeout'})
        print(f'[{i + 1}/{len(model_names)}] {name}: {"OK" if results[-1]["ok"] else "FAIL"}')
        with open(args.results_file, 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process bulk driver; children are processes, not a pod
            json.dump(results, f, indent=2)
    print(f'Wrote {args.results_file}')


if __name__ == '__main__':
    main()
