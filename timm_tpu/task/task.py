"""Training task abstraction (reference: timm/task/task.py:17-231).

The task owns the model, optimizer, EMA and — unlike the torch reference —
the **jitted train/eval step functions**. Design:

  * the train step is a FUNCTIONAL `jax.jit` over explicit state pytrees
    (params, non-param model state, optimizer state, EMA, sentinel) with
    **explicit `in_shardings`/`out_shardings` and `donate_argnums` for every
    state argument**: XLA aliases the donated input buffers to the matching
    outputs (params/AdamW m,v/EMA update in place — ~2 GB/step less HBM copy
    traffic for ViT-B, PERF.md §2 item 3a), and the sharding annotations are
    what make the aliasing legal (donation requires input and output
    placement to agree leaf-for-leaf).
  * placement comes from `parallel/sharding.py`: on a 1-axis data mesh every
    sharding is replicated (exact pre-FSDP behaviour); on a
    ``('data', 'fsdp')`` mesh large weights and their optimizer slots shard
    over 'fsdp' and GSPMD emits the gather/scatter collectives; on a
    ``('data', 'fsdp', 'model')`` mesh the attention/MLP kernels additionally
    shard heads/hidden over 'model' (Megatron split) and the models'
    activation constraints (parallel/constraints.py) keep the residual
    stream and block internals sharded inside the scanned step. The jit
    wiring below is axis-agnostic — the same in/out sharding trees carry
    1-, 2-, and 3-axis placements, and donation stays legal because the
    optimizer/EMA state inherits each param's spec leaf-for-leaf.
  * optimizer/EMA state is created ON-MESH via `jax.eval_shape` + jitted
    init with `out_shardings` — a replicated host copy of m/v never exists.
  * the reference's AMP scaler (utils/cuda.py:46) is unnecessary — bf16
    compute is native on TPU and fp32 master params are the default.
  * DDP wrap / no_sync (task.py:222, classification.py:64) have no analogue:
    the batch is sharded over the mesh batch axes and XLA emits the gradient
    all-reduce over ICI.
  * grad accumulation is ONE `jax.lax.scan` over stacked microbatches, so
    trace/compile cost is O(1) in `grad_accum_steps` (composing with the
    models' `block_scan`); `grad_accum_scan=False` keeps the legacy Python
    unroll for parity testing.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import nnx

from ..kernels.fused_adamw import fused_adamw_step, validate_fused_opt_state
from ..optim import Optimizer
from ..parallel import (
    build_opt_shardings, build_param_shardings, get_global_mesh, replicate_sharding,
)
from ..resilience import (
    NonFiniteSentinel, guard_enabled, new_sentinel_state, tree_all_finite,
    update_sentinel_state,
)
from ..utils.clip_grad import dispatch_clip_grad, global_grad_norm
from ..utils.model_ema import ModelEmaV3, ema_update
from ..utils.serialization import flatten_pytree, unflatten_into

_logger = logging.getLogger(__name__)

__all__ = ['TrainingTask']


class TrainingTask:
    def __init__(
            self,
            model: nnx.Module,
            optimizer: Optional[Optimizer] = None,
            mesh=None,
            grad_accum_steps: int = 1,
            grad_accum_scan: bool = True,
            clip_grad: Optional[float] = None,
            clip_mode: str = 'norm',
            mean=None,
            std=None,
            nonfinite_guard: Optional[bool] = None,
            nonfinite_tolerance: Optional[int] = None,
            partition_rules=None,
            fused_update: bool = False,
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh or get_global_mesh()
        self.grad_accum_steps = max(1, grad_accum_steps)
        self.grad_accum_scan = grad_accum_scan
        self.clip_grad = clip_grad
        self.clip_mode = clip_mode
        self.partition_rules = partition_rules
        # opt-in one-pass fused AdamW+EMA update (kernels/fused_adamw.py);
        # the optax path stays the default and the parity oracle
        self.fused_update = bool(fused_update)
        # non-finite sentinel (resilience/sentinel.py): an all-finite reduction
        # over loss+grads fused into the jitted step; bad steps commit nothing
        # and K consecutive bad steps abort via NonFiniteError. Default on
        # (disable with nonfinite_guard=False or TIMM_TPU_NONFINITE_GUARD=0).
        self._nonfinite_guard = guard_enabled(nonfinite_guard)
        self.sentinel = NonFiniteSentinel(nonfinite_tolerance) if self._nonfinite_guard else None
        self._sentinel_state = new_sentinel_state() if self._nonfinite_guard else None
        # on-device input normalization, fused into the jitted step (the
        # reference normalizes on-GPU in PrefetchLoader, loader.py:124-159)
        if mean is not None:
            self._norm_mean = jnp.asarray(mean, jnp.float32).reshape(1, 1, 1, -1)
            self._norm_std = jnp.asarray(std if std is not None else 1.0, jnp.float32).reshape(1, 1, 1, -1)
        else:
            self._norm_mean = self._norm_std = None

        # placement: params by partition rule (all-replicated on a plain data
        # mesh, fsdp-sharded on a ('data','fsdp') mesh), everything else
        # (BN stats, RNG counters) replicated
        rep = replicate_sharding(self.mesh)
        params = nnx.state(model, nnx.Param)
        self._param_shardings = build_param_shardings(params, self.mesh, self.partition_rules)
        nnx.update(model, jax.device_put(params, self._param_shardings))
        other = nnx.state(model, nnx.Not(nnx.Param))
        if jax.tree.leaves(other):
            nnx.update(model, jax.device_put(other, rep))
        if self.optimizer is not None:
            params = nnx.state(model, nnx.Param)
            self._opt_shardings, _ = build_opt_shardings(
                self.optimizer, params, self.mesh, self.partition_rules)
            try:
                # abstract init: m/v materialize directly on their owning
                # devices; no replicated copy of the optimizer state exists
                # (no-donate: init consumes fresh params, there is no prior
                # state whose buffers an output could alias)
                self.opt_state = jax.jit(
                    self.optimizer.init, out_shardings=self._opt_shardings)(params)
            except Exception as e:
                _logger.warning(f'sharded optimizer init failed ({e!r}); '
                                'falling back to eager init + device_put')
                self.opt_state = jax.device_put(self.optimizer.init(params), self._opt_shardings)
        else:
            self.opt_state = None
            self._opt_shardings = None

        if self.fused_update and self.optimizer is not None:
            # fail at construction, not first step: the fused kernel mirrors
            # the plain adamw chain only (create_optimizer_v2 attaches
            # fused_adamw_args exactly when that chain was built)
            if getattr(self.optimizer, 'fused_adamw_args', None) is None:
                raise ValueError(
                    'fused_update=True requires a plain adamw optimizer from '
                    'create_optimizer_v2 (no lookahead/caution/layer-decay '
                    'wrappers) — this optimizer carries no fused_adamw_args')
            validate_fused_opt_state(self.opt_state)

        self.ema: Optional[ModelEmaV3] = None
        self.ema_params = None
        self._train_step = None
        self._eval_step = None
        self.compiled = False  # jit is always on; flag kept for API parity

    # -- overridables --------------------------------------------------------
    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        """Return (loss, output). Subclasses implement the objective."""
        raise NotImplementedError

    def eval_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        return model(batch['input'])

    def normalize_input(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        if self._norm_mean is None or 'input' not in batch:
            return batch
        x = batch['input']
        x = (x.astype(jnp.float32) - self._norm_mean) / self._norm_std
        return dict(batch, input=x.astype(batch['input'].dtype)
                    if batch['input'].dtype != jnp.float32 else x)

    # -- setup ---------------------------------------------------------------
    def setup_ema(self, decay: float = 0.9999, warmup: bool = False, **kwargs):
        """(reference task.py:110). The EMA tree is a deep COPY placed like the
        params (donation aliases param and EMA buffers independently; sharing
        storage with the live params would alias one buffer twice)."""
        self.ema = ModelEmaV3(decay=decay, use_warmup=warmup, **kwargs)
        self.ema_params = jax.device_put(
            jax.tree.map(lambda p: jnp.array(p, copy=True), nnx.state(self.model, nnx.Param)),
            self._param_shardings)
        self._train_step = None  # EMA presence is baked into the jitted step; rebuild

    def set_block_scan(self, enable: bool = True) -> bool:
        """Toggle scan-over-layers execution on the owned model (and its
        sync'd EMA clone, which inherits the flag at sync time). The jitted
        steps are invalidated explicitly: block_scan is a static model attr,
        so a stale traced step would silently keep the old execution mode on
        flax versions whose jit cache ignores attr-only graphdef changes."""
        if not hasattr(self.model, 'set_block_scan'):
            return False
        self.model.set_block_scan(enable)
        self._train_step = None
        self._eval_step = None
        return True

    def set_grad_accum(self, steps: int) -> bool:
        """Rescale gradient accumulation (elastic resume holds
        global_batch = loader_batch x accum invariant across topology
        changes). `accum` is captured inside the jitted train step's
        accumulation scan, so the step is invalidated exactly like
        set_block_scan; returns True when the value actually changed."""
        steps = max(1, int(steps))
        if steps == self.grad_accum_steps:
            return False
        self.grad_accum_steps = steps
        self._train_step = None
        return True

    def compile(self, backend: str = ''):
        self.compiled = True  # parity no-op; the steps are always jitted

    def prepare_distributed(self):
        return self  # sharded-batch DP needs no wrapping; parity (classification.py:64)

    # -- jitted steps ----------------------------------------------------------
    def _split_model(self) -> Tuple[Any, Any, Any]:
        return nnx.split(self.model, nnx.Param, ...)

    def _build_train_step(self):
        if self.optimizer is None:
            raise RuntimeError('TrainingTask.train_step requires an optimizer')
        optimizer = self.optimizer
        accum = self.grad_accum_steps
        accum_scan = self.grad_accum_scan
        clip_grad, clip_mode = self.clip_grad, self.clip_mode
        has_ema = self.ema_params is not None
        guard = self._nonfinite_guard
        fused_cfg = getattr(optimizer, 'fused_adamw_args', None) if self.fused_update else None
        if self.fused_update and fused_cfg is None:
            raise ValueError('fused_update=True but the optimizer carries no '
                             'fused_adamw_args (plain adamw chain required)')
        loss_forward = self.loss_forward
        normalize_input = self.normalize_input

        self.model.train()
        graphdef, _, _ = self._split_model()

        rep = replicate_sharding(self.mesh)
        # pytree-prefix shardings: a single sharding broadcasts over a whole
        # subtree (non-param state, metrics). The batch position is None =
        # inherit from the argument: parallel.shard_batch is the explicit
        # placement mechanism, and eval/debug batches smaller than the mesh
        # batch-shard count stay legal (they run replicated).
        param_sh = self._param_shardings
        opt_sh = self._opt_shardings
        ema_sh = param_sh if has_ema else rep

        def loss_and_state(params, rest, mb):
            """Merge → loss_forward → re-split, so grads flow w.r.t. params
            while BN-stat / RNG-counter mutations are carried functionally."""
            m = nnx.merge(graphdef, params, rest)
            loss, _output = loss_forward(m, mb)
            _, _, new_rest = nnx.split(m, nnx.Param, ...)
            return loss.astype(jnp.float32), new_rest

        grad_fn = jax.value_and_grad(loss_and_state, has_aux=True)

        def microbatch_split(batch):
            """[accum*mb, ...] → [accum, mb, ...]; scalar leaves (e.g. NaFlex
            seq_len metadata) broadcast to every microbatch instead."""
            return jax.tree.map(
                lambda x: x.reshape(accum, -1, *x.shape[1:]) if getattr(x, 'ndim', 0) >= 1 else x,
                batch)

        def train_step(params, rest, opt_state, ema_params, sentinel_state, batch, lr, ema_decay):
            batch = normalize_input(batch)

            if accum > 1 and accum_scan:
                # ONE lax.scan over stacked microbatches: trace/compile cost
                # no longer scales with grad_accum_steps. Array leaves ride
                # the scan xs; scalar leaves stay in the carry-free closure.
                flat, treedef = jax.tree_util.tree_flatten(microbatch_split(batch))
                scan_idx = [i for i, leaf in enumerate(flat) if getattr(leaf, 'ndim', 0) >= 1]
                xs = [flat[i] for i in scan_idx]

                def rebuild(scanned):
                    leaves = list(flat)
                    for i, leaf in zip(scan_idx, scanned):
                        leaves[i] = leaf
                    return jax.tree_util.tree_unflatten(treedef, leaves)

                def body(carry, scanned):
                    grads_acc, loss_acc, r = carry
                    (l_i, new_r), g_i = grad_fn(params, r, rebuild(scanned))
                    return (jax.tree.map(jnp.add, grads_acc, g_i), loss_acc + l_i, new_r), None

                init = (jax.tree.map(jnp.zeros_like, params), jnp.zeros((), jnp.float32), rest)
                (grads, loss, new_rest), _ = jax.lax.scan(body, init, xs)
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
            elif accum > 1:
                # legacy unrolled accumulation (grad_accum_scan=False): kept
                # for trace-cost A/B and scan-vs-unroll parity tests
                microbatches = microbatch_split(batch)
                loss = jnp.zeros((), jnp.float32)
                grads, r = None, rest
                for i in range(accum):
                    mb = jax.tree.map(
                        lambda x: x[i] if getattr(x, 'ndim', 0) >= 2 else x, microbatches)
                    (l_i, r), g_i = grad_fn(params, r, mb)
                    loss = loss + l_i
                    grads = g_i if grads is None else jax.tree.map(jnp.add, grads, g_i)
                new_rest = r
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
            else:
                (loss, new_rest), grads = grad_fn(params, rest, batch)

            grad_norm = global_grad_norm(grads)
            if clip_grad is not None:
                params_for_clip = params if clip_mode == 'agc' else None
                grads, _ = dispatch_clip_grad(grads, clip_grad, mode=clip_mode, params=params_for_clip)

            if fused_cfg is not None:
                # one-pass fused AdamW+EMA kernel: replaces update + apply
                # (+ the EMA pass below); opt_state structure is preserved so
                # the shardings/donation annotations hold unchanged
                new_params, new_opt_state, fused_ema = fused_adamw_step(
                    params, grads, opt_state, ema_params if has_ema else None,
                    lr=lr, ema_decay=ema_decay, **fused_cfg)
            else:
                updates, new_opt_state = optimizer.update(grads, opt_state, params, lr=lr)
                new_params = optax.apply_updates(params, updates)
                fused_ema = None
            if guard:
                # all-finite reduction over loss + raw grads; a bad step keeps
                # params/opt_state/EMA bit-identical to the previous step
                ok = tree_all_finite(loss, grads)
                select = lambda new, old: jnp.where(ok, new, old)  # noqa: E731
                new_params = jax.tree.map(select, new_params, params)
                new_opt_state = jax.tree.map(select, new_opt_state, opt_state)
                sentinel_state = update_sentinel_state(sentinel_state, ok)

            if has_ema:
                # decay==0 naturally syncs EMA to model (reference ModelEmaV3
                # lerp weight 1.0 during the update_after_step window).
                new_ema = fused_ema if fused_ema is not None else \
                    ema_update(ema_params, new_params, ema_decay)
                if guard:
                    new_ema = jax.tree.map(select, new_ema, ema_params)
                ema_params = new_ema
            metrics = {'loss': loss, 'grad_norm': grad_norm}
            if guard:
                metrics['nonfinite'] = sentinel_state[0] > 0
            return new_params, new_rest, new_opt_state, ema_params, sentinel_state, metrics

        # donation + matching in/out shardings let XLA alias every state
        # buffer in place (params, m/v, EMA, RNG counters, sentinel); the
        # sharding annotations are REQUIRED for the aliasing to be legal
        return jax.jit(
            train_step,
            donate_argnums=(0, 1, 2, 3, 4),
            in_shardings=(param_sh, rep, opt_sh, ema_sh, rep, None, rep, rep),
            out_shardings=(param_sh, rep, opt_sh, ema_sh, rep, rep),
        )

    def _build_eval_step(self):
        eval_forward = self.eval_forward
        normalize_input = self.normalize_input
        self.model.eval()
        graphdef, _, _ = self._split_model()
        rep = replicate_sharding(self.mesh)

        def eval_step(params, rest, batch):
            m = nnx.merge(graphdef, params, rest)
            return eval_forward(m, normalize_input(batch))

        # no-donate: eval reuses params/rest across calls (and for EMA eval the
        # live train params are passed straight back in on the next call).
        # Batch placement is inherited (shard_batch), outputs follow it.
        return jax.jit(
            eval_step,
            in_shardings=(self._param_shardings, rep, None),
            out_shardings=None,
        )

    # -- public step API -------------------------------------------------------
    def train_step(self, batch: Dict[str, Any], lr: float, step: int = 0):
        """One optimization step; `batch['input']` is NHWC, batch dim sharded
        over the mesh (use parallel.shard_batch)."""
        if self._train_step is None:
            self._train_step = self._build_train_step()
        self.model.train()
        _, params, rest = self._split_model()
        ema_decay = self.ema.get_decay(step) if self.ema is not None else 0.0
        ema_in = self.ema_params if self.ema_params is not None else ()
        sent_in = self._sentinel_state if self._sentinel_state is not None else ()
        params, rest, self.opt_state, ema_out, sent_out, metrics = self._train_step(
            params, rest, self.opt_state, ema_in, sent_in, batch,
            jnp.asarray(lr, jnp.float32), jnp.asarray(ema_decay, jnp.float32))
        nnx.update(self.model, params, rest)
        if self.ema_params is not None:
            self.ema_params = ema_out
        if self._sentinel_state is not None:
            self._sentinel_state = sent_out
            metrics['nonfinite_count'] = sent_out[0]
            metrics['nonfinite_total'] = sent_out[1]
            if self.sentinel is not None:
                # polls the device counters (every TIMM_TPU_NONFINITE_CHECK_EVERY
                # steps) and raises NonFiniteError after K consecutive bad steps
                self.sentinel.observe(sent_out, step=step)
        return metrics

    def trace_train_step(self, batch: Dict[str, Any], lr: float = 0.1, step: int = 0):
        """AOT-trace the jitted train step on `batch` WITHOUT executing it;
        returns the ClosedJaxpr (trace-cost regression tests count its
        equations to pin the O(1)-in-grad_accum_steps property)."""
        if self._train_step is None:
            self._train_step = self._build_train_step()
        self.model.train()
        _, params, rest = self._split_model()
        ema_decay = self.ema.get_decay(step) if self.ema is not None else 0.0
        ema_in = self.ema_params if self.ema_params is not None else ()
        sent_in = self._sentinel_state if self._sentinel_state is not None else ()
        traced = self._train_step.trace(
            params, rest, self.opt_state, ema_in, sent_in, batch,
            jnp.asarray(lr, jnp.float32), jnp.asarray(ema_decay, jnp.float32))
        return traced.jaxpr

    def lower_train_step(self, batch: Dict[str, Any], lr: float = 0.1, step: int = 0):
        """AOT-lower-and-compile the jitted train step on `batch` WITHOUT
        executing it; returns the jax.stages.Compiled. The perfbudget probe
        reads `cost_analysis()` (FLOPs / bytes accessed) and the HLO
        `input_output_alias` header (donation legality) off it, and the
        compile goes through the persistent cache so repeated probes are
        disk-bound."""
        if self._train_step is None:
            self._train_step = self._build_train_step()
        self.model.train()
        _, params, rest = self._split_model()
        ema_decay = self.ema.get_decay(step) if self.ema is not None else 0.0
        ema_in = self.ema_params if self.ema_params is not None else ()
        sent_in = self._sentinel_state if self._sentinel_state is not None else ()
        return self._train_step.lower(
            params, rest, self.opt_state, ema_in, sent_in, batch,
            jnp.asarray(lr, jnp.float32), jnp.asarray(ema_decay, jnp.float32)).compile()

    def reset_nonfinite(self):
        """Clear the consecutive-bad-step counters (after a rollback)."""
        if self._sentinel_state is not None:
            self._sentinel_state = new_sentinel_state()
        if self.sentinel is not None:
            self.sentinel.reset()

    def update_ema(self, step: int):
        pass  # fused into train_step; parity no-op (task.py update_ema)

    def eval_step(self, batch: Dict[str, Any], use_ema: bool = False):
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        self.model.eval()
        _, params, rest = self._split_model()
        if use_ema and self.ema_params is not None:
            return self._eval_step(self.ema_params, rest, batch)
        out = self._eval_step(params, rest, batch)
        self.model.train()
        return out

    # -- module sync / checkpoint ------------------------------------------------
    def sync_model(self, use_ema: bool = False) -> nnx.Module:
        if use_ema and self.ema_params is not None:
            nnx.update(self.model, self.ema_params)
        return self.model

    def get_checkpoint_state(self) -> Dict[str, np.ndarray]:
        """Flat checkpoint dict (schema mirrors reference checkpoint_saver.py:89).
        fsdp-sharded leaves are gathered to full host arrays by np.asarray, so
        the checkpoint bytes are identical for every mesh shape."""
        state = flatten_pytree(nnx.state(self.model, nnx.Param), 'state_dict')
        if self.ema_params is not None:
            state.update(flatten_pytree(self.ema_params, 'state_dict_ema'))
        if self.opt_state is not None:
            state.update(flatten_pytree(self.opt_state, 'optimizer'))
        # non-param model variables (e.g. BN stats) minus rng bookkeeping
        other = nnx.state(self.model, nnx.Not(nnx.Param))
        flat_other = {k: v for k, v in flatten_pytree(other, 'model_state').items() if 'rngs' not in k}
        state.update(flat_other)
        return state

    @staticmethod
    def _place(tree, shardings):
        """device_put a host pytree under `shardings` (a matching tree or one
        sharding for every leaf). Multi-process meshes route through
        `place_global`, which builds non-fully-addressable global arrays from
        each host's local pieces; single-process this IS jax.device_put."""
        from ..parallel.mesh import place_global
        if isinstance(shardings, jax.sharding.Sharding):
            return jax.tree.map(lambda x: place_global(x, shardings), tree)
        return jax.tree.map(place_global, tree, shardings)

    def load_checkpoint_state(self, state: Dict[str, np.ndarray], strict: bool = True, load_opt: bool = True):
        """Restore from a flat checkpoint dict; loaded leaves are re-placed
        under THIS task's shardings, so a checkpoint saved on any mesh shape
        (single-device, data-only, data×fsdp, multi-process sharded) loads on
        any other."""
        params = unflatten_into(nnx.state(self.model, nnx.Param), state, 'state_dict', strict=strict)
        nnx.update(self.model, self._place(params, self._param_shardings))
        if self.ema_params is not None and any(k.startswith('state_dict_ema.') for k in state):
            ema = unflatten_into(self.ema_params, state, 'state_dict_ema', strict=strict)
            self.ema_params = self._place(ema, self._param_shardings)
        if load_opt and self.opt_state is not None and any(k.startswith('optimizer.') for k in state):
            opt = unflatten_into(self.opt_state, state, 'optimizer', strict=strict)
            self.opt_state = self._place(opt, self._opt_shardings)
        if any(k.startswith('model_state.') for k in state):
            other = nnx.state(self.model, nnx.Not(nnx.Param))
            other = unflatten_into(other, state, 'model_state', strict=False)
            nnx.update(self.model, self._place(other, replicate_sharding(self.mesh)))
