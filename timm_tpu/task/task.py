"""Training task abstraction (reference: timm/task/task.py:17-231).

The task owns the model, optimizer, EMA and — unlike the torch reference —
the **jitted train/eval step functions**. Design:

  * one `nnx.jit` step covers forward+backward+clip+optimizer+EMA; nnx lifts
    the module's variables (params, batch stats, RNG stream counters) in and
    out of the compiled program, so RNG-consuming layers (dropout, drop-path)
    work under grad without manual state plumbing.
  * the reference's AMP scaler (utils/cuda.py:46) is unnecessary — bf16
    compute is native on TPU and fp32 master params are the default.
  * DDP wrap / no_sync (task.py:222, classification.py:64) have no analogue:
    the batch is sharded over the mesh ('data' axis), params are replicated,
    and XLA emits the gradient all-reduce over ICI.
  * grad accumulation unrolls microbatches inside the same compiled step.
"""
from __future__ import annotations

import logging
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import nnx

from ..optim import Optimizer
from ..parallel import get_global_mesh, replicate_sharding
from ..resilience import (
    NonFiniteSentinel, guard_enabled, new_sentinel_state, tree_all_finite,
    update_sentinel_state,
)
from ..utils.clip_grad import dispatch_clip_grad, global_grad_norm
from ..utils.model_ema import ModelEmaV3, ema_update
from ..utils.serialization import flatten_pytree, unflatten_into

_logger = logging.getLogger(__name__)

__all__ = ['TrainingTask']


class TrainingTask:
    def __init__(
            self,
            model: nnx.Module,
            optimizer: Optional[Optimizer] = None,
            mesh=None,
            grad_accum_steps: int = 1,
            clip_grad: Optional[float] = None,
            clip_mode: str = 'norm',
            mean=None,
            std=None,
            nonfinite_guard: Optional[bool] = None,
            nonfinite_tolerance: Optional[int] = None,
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh or get_global_mesh()
        self.grad_accum_steps = max(1, grad_accum_steps)
        self.clip_grad = clip_grad
        self.clip_mode = clip_mode
        # non-finite sentinel (resilience/sentinel.py): an all-finite reduction
        # over loss+grads fused into the jitted step; bad steps commit nothing
        # and K consecutive bad steps abort via NonFiniteError. Default on
        # (disable with nonfinite_guard=False or TIMM_TPU_NONFINITE_GUARD=0).
        self._nonfinite_guard = guard_enabled(nonfinite_guard)
        self.sentinel = NonFiniteSentinel(nonfinite_tolerance) if self._nonfinite_guard else None
        self._sentinel_state = new_sentinel_state() if self._nonfinite_guard else None
        # on-device input normalization, fused into the jitted step (the
        # reference normalizes on-GPU in PrefetchLoader, loader.py:124-159)
        if mean is not None:
            self._norm_mean = jnp.asarray(mean, jnp.float32).reshape(1, 1, 1, -1)
            self._norm_std = jnp.asarray(std if std is not None else 1.0, jnp.float32).reshape(1, 1, 1, -1)
        else:
            self._norm_mean = self._norm_std = None

        # replicate model + optimizer state over the mesh
        rep = replicate_sharding(self.mesh)
        state = nnx.state(model)
        nnx.update(model, jax.device_put(state, rep))
        if self.optimizer is not None:
            self.opt_state = jax.device_put(self.optimizer.init(nnx.state(model, nnx.Param)), rep)
        else:
            self.opt_state = None

        self.ema: Optional[ModelEmaV3] = None
        self.ema_params = None
        self._train_step = None
        self._eval_step = None
        self.compiled = False  # jit is always on; flag kept for API parity

    # -- overridables --------------------------------------------------------
    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        """Return (loss, output). Subclasses implement the objective."""
        raise NotImplementedError

    def eval_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        return model(batch['input'])

    def normalize_input(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        if self._norm_mean is None or 'input' not in batch:
            return batch
        x = batch['input']
        x = (x.astype(jnp.float32) - self._norm_mean) / self._norm_std
        return dict(batch, input=x.astype(batch['input'].dtype)
                    if batch['input'].dtype != jnp.float32 else x)

    # -- setup ---------------------------------------------------------------
    def setup_ema(self, decay: float = 0.9999, warmup: bool = False, **kwargs):
        """(reference task.py:110)."""
        self.ema = ModelEmaV3(decay=decay, use_warmup=warmup, **kwargs)
        self.ema_params = jax.tree.map(jnp.asarray, nnx.state(self.model, nnx.Param))
        self._train_step = None  # EMA presence is baked into the jitted step; rebuild

    def set_block_scan(self, enable: bool = True) -> bool:
        """Toggle scan-over-layers execution on the owned model (and its
        sync'd EMA clone, which inherits the flag at sync time). The jitted
        steps are invalidated explicitly: block_scan is a static model attr,
        so a stale traced step would silently keep the old execution mode on
        flax versions whose jit cache ignores attr-only graphdef changes."""
        if not hasattr(self.model, 'set_block_scan'):
            return False
        self.model.set_block_scan(enable)
        self._train_step = None
        self._eval_step = None
        return True

    def compile(self, backend: str = ''):
        self.compiled = True  # parity no-op; nnx.jit is always on (task.py:90)

    def prepare_distributed(self):
        return self  # sharded-batch DP needs no wrapping; parity (classification.py:64)

    # -- jitted steps ----------------------------------------------------------
    def _build_train_step(self):
        optimizer = self.optimizer
        accum = self.grad_accum_steps
        clip_grad, clip_mode = self.clip_grad, self.clip_mode
        has_ema = self.ema_params is not None
        guard = self._nonfinite_guard
        loss_forward = self.loss_forward

        normalize_input = self.normalize_input

        @nnx.jit
        def train_step(model, opt_state, ema_params, sentinel_state, batch, lr, ema_decay):
            batch = normalize_input(batch)

            def loss_fn(model, mb):
                loss, _output = loss_forward(model, mb)
                return loss.astype(jnp.float32)

            if accum > 1:
                # scalar leaves (e.g. NaFlex seq_len/patch_size metadata) are
                # broadcast to every microbatch rather than reshaped
                def _split(x):
                    return x.reshape(accum, -1, *x.shape[1:]) if getattr(x, 'ndim', 0) >= 1 else x

                microbatches = jax.tree.map(_split, batch)
                loss = jnp.zeros((), jnp.float32)
                grads = None
                for i in range(accum):
                    mb = jax.tree.map(
                        lambda x: x[i] if getattr(x, 'ndim', 0) >= 2 else x, microbatches)
                    l_i, g_i = nnx.value_and_grad(loss_fn)(model, mb)
                    loss = loss + l_i
                    grads = g_i if grads is None else jax.tree.map(jnp.add, grads, g_i)
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
            else:
                loss, grads = nnx.value_and_grad(loss_fn)(model, batch)

            grad_norm = global_grad_norm(grads)
            if clip_grad is not None:
                params_for_clip = nnx.state(model, nnx.Param) if clip_mode == 'agc' else None
                grads, _ = dispatch_clip_grad(grads, clip_grad, mode=clip_mode, params=params_for_clip)

            old_params = nnx.state(model, nnx.Param)
            updates, new_opt_state = optimizer.update(grads, opt_state, old_params, lr=lr)
            params = optax.apply_updates(old_params, updates)
            if guard:
                # all-finite reduction over loss + raw grads; a bad step keeps
                # params/opt_state/EMA bit-identical to the previous step
                ok = tree_all_finite(loss, grads)
                select = lambda new, old: jnp.where(ok, new, old)  # noqa: E731
                params = jax.tree.map(select, params, old_params)
                new_opt_state = jax.tree.map(select, new_opt_state, opt_state)
                sentinel_state = update_sentinel_state(sentinel_state, ok)
            opt_state = new_opt_state
            nnx.update(model, params)

            if has_ema:
                # decay==0 naturally syncs EMA to model (reference ModelEmaV3
                # lerp weight 1.0 during the update_after_step window).
                new_ema = ema_update(ema_params, params, ema_decay)
                if guard:
                    new_ema = jax.tree.map(select, new_ema, ema_params)
                ema_params = new_ema
            metrics = {'loss': loss, 'grad_norm': grad_norm}
            if guard:
                metrics['nonfinite'] = sentinel_state[0] > 0
            return opt_state, ema_params, sentinel_state, metrics

        return train_step

    def _build_eval_step(self):
        eval_forward = self.eval_forward
        normalize_input = self.normalize_input

        @nnx.jit
        def eval_step(model, batch):
            return eval_forward(model, normalize_input(batch))

        return eval_step

    # -- public step API -------------------------------------------------------
    def train_step(self, batch: Dict[str, Any], lr: float, step: int = 0):
        """One optimization step; `batch['input']` is NHWC, batch dim sharded
        over the mesh (use parallel.shard_batch)."""
        if self._train_step is None:
            self._train_step = self._build_train_step()
        self.model.train()
        ema_decay = self.ema.get_decay(step) if self.ema is not None else 0.0
        ema_in = self.ema_params if self.ema_params is not None else ()
        sent_in = self._sentinel_state if self._sentinel_state is not None else ()
        self.opt_state, ema_out, sent_out, metrics = self._train_step(
            self.model, self.opt_state, ema_in, sent_in, batch,
            jnp.asarray(lr, jnp.float32), jnp.asarray(ema_decay, jnp.float32))
        if self.ema_params is not None:
            self.ema_params = ema_out
        if self._sentinel_state is not None:
            self._sentinel_state = sent_out
            metrics['nonfinite_count'] = sent_out[0]
            metrics['nonfinite_total'] = sent_out[1]
            if self.sentinel is not None:
                # polls the device counters (every TIMM_TPU_NONFINITE_CHECK_EVERY
                # steps) and raises NonFiniteError after K consecutive bad steps
                self.sentinel.observe(sent_out, step=step)
        return metrics

    def reset_nonfinite(self):
        """Clear the consecutive-bad-step counters (after a rollback)."""
        if self._sentinel_state is not None:
            self._sentinel_state = new_sentinel_state()
        if self.sentinel is not None:
            self.sentinel.reset()

    def update_ema(self, step: int):
        pass  # fused into train_step; parity no-op (task.py update_ema)

    def eval_step(self, batch: Dict[str, Any], use_ema: bool = False):
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        self.model.eval()
        if use_ema and self.ema_params is not None:
            train_params = jax.tree.map(jnp.asarray, nnx.state(self.model, nnx.Param))
            nnx.update(self.model, self.ema_params)
            out = self._eval_step(self.model, batch)
            nnx.update(self.model, train_params)
            return out
        out = self._eval_step(self.model, batch)
        self.model.train()
        return out

    # -- module sync / checkpoint ------------------------------------------------
    def sync_model(self, use_ema: bool = False) -> nnx.Module:
        if use_ema and self.ema_params is not None:
            nnx.update(self.model, self.ema_params)
        return self.model

    def get_checkpoint_state(self) -> Dict[str, np.ndarray]:
        """Flat checkpoint dict (schema mirrors reference checkpoint_saver.py:89)."""
        state = flatten_pytree(nnx.state(self.model, nnx.Param), 'state_dict')
        if self.ema_params is not None:
            state.update(flatten_pytree(self.ema_params, 'state_dict_ema'))
        if self.opt_state is not None:
            state.update(flatten_pytree(self.opt_state, 'optimizer'))
        # non-param model variables (e.g. BN stats) minus rng bookkeeping
        other = nnx.state(self.model, nnx.Not(nnx.Param))
        flat_other = {k: v for k, v in flatten_pytree(other, 'model_state').items() if 'rngs' not in k}
        state.update(flat_other)
        return state

    def load_checkpoint_state(self, state: Dict[str, np.ndarray], strict: bool = True, load_opt: bool = True):
        params = unflatten_into(nnx.state(self.model, nnx.Param), state, 'state_dict', strict=strict)
        nnx.update(self.model, params)
        if self.ema_params is not None and any(k.startswith('state_dict_ema.') for k in state):
            self.ema_params = unflatten_into(self.ema_params, state, 'state_dict_ema', strict=strict)
        if load_opt and self.opt_state is not None and any(k.startswith('optimizer.') for k in state):
            self.opt_state = unflatten_into(self.opt_state, state, 'optimizer', strict=strict)
        if any(k.startswith('model_state.') for k in state):
            other = nnx.state(self.model, nnx.Not(nnx.Param))
            other = unflatten_into(other, state, 'model_state', strict=False)
            nnx.update(self.model, other)
