"""Distillation tasks (reference: timm/task/distillation.py).

The frozen teacher's (graphdef, state) is closed over by the jitted step; it
runs in eval mode inside the same XLA program as the student forward.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..loss import LabelSmoothingCrossEntropy
from ..parallel import build_param_shardings, replicate_sharding
from .task import TrainingTask

__all__ = ['LogitDistillationTask', 'FeatureDistillationTask']


def _split_teacher(teacher: nnx.Module, mesh):
    """Split the frozen teacher and place it on the task's mesh: weights under
    the same partition rules as the student's (a big teacher must not end up
    as a single-device or replicated constant inside the SPMD step), non-param
    state replicated. Returns (graphdef, state) for nnx.merge at use."""
    graphdef, params, rest = nnx.split(teacher, nnx.Param, ...)
    params = jax.device_put(params, build_param_shardings(params, mesh))
    if jax.tree.leaves(rest):
        rest = jax.device_put(rest, replicate_sharding(mesh))
    return graphdef, (params, rest)


class LogitDistillationTask(TrainingTask):
    """KL(student_T || teacher_T) * T^2 blended with CE
    (reference distillation.py LogitDistillationTask)."""

    def __init__(
            self,
            model: nnx.Module,
            teacher: nnx.Module,
            optimizer=None,
            train_loss_fn: Optional[Callable] = None,
            distill_alpha: float = 0.5,
            distill_temperature: float = 1.0,
            **kwargs,
    ):
        super().__init__(model, optimizer=optimizer, **kwargs)
        teacher.eval()
        self._teacher_graphdef, self._teacher_state = _split_teacher(teacher, self.mesh)
        self.train_loss_fn = train_loss_fn or LabelSmoothingCrossEntropy(0.0)
        self.alpha = distill_alpha
        self.temperature = distill_temperature

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        x = batch['input']
        output = model(x)
        teacher = nnx.merge(self._teacher_graphdef, *self._teacher_state)
        teacher_logits = jax.lax.stop_gradient(teacher(x))

        base_loss = self.train_loss_fn(output, batch['target'])
        T = self.temperature
        s = jax.nn.log_softmax(output.astype(jnp.float32) / T, axis=-1)
        t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / T, axis=-1)
        kd = (t * (jnp.log(jnp.clip(t, 1e-9)) - s)).sum(axis=-1).mean() * (T * T)
        loss = (1.0 - self.alpha) * base_loss + self.alpha * kd
        return loss, output


class FeatureDistillationTask(TrainingTask):
    """Match intermediate features to a teacher via a learned projection
    (reference distillation.py FeatureDistillationTask). The projection params
    live in task_state and persist through checkpoints."""

    @staticmethod
    def prepare_model(model: nnx.Module, teacher: nnx.Module, *, rngs: Optional[nnx.Rngs] = None) -> nnx.Module:
        """Attach the student→teacher projection. Call BEFORE building the
        optimizer so its weight-decay/lr-scale pytrees include the projection."""
        student_dim = getattr(model, 'num_features')
        teacher_dim = getattr(teacher, 'num_features')
        if student_dim != teacher_dim and not hasattr(model, 'distill_proj'):
            model.distill_proj = nnx.Linear(student_dim, teacher_dim, rngs=rngs or nnx.Rngs(0))
        return model

    def __init__(
            self,
            model: nnx.Module,
            teacher: nnx.Module,
            optimizer=None,
            train_loss_fn: Optional[Callable] = None,
            distill_alpha: float = 0.5,
            feat_loss: str = 'cosine',
            **kwargs,
    ):
        needs_proj = getattr(model, 'num_features') != getattr(teacher, 'num_features')
        if needs_proj and not hasattr(model, 'distill_proj'):
            if optimizer is not None:
                raise ValueError(
                    'Student/teacher feature dims differ: call '
                    'FeatureDistillationTask.prepare_model(model, teacher) before '
                    'building the optimizer so its param pytrees include the projection.')
            self.prepare_model(model, teacher)
        super().__init__(model, optimizer=optimizer, **kwargs)
        teacher.eval()
        self._teacher_graphdef, self._teacher_state = _split_teacher(teacher, self.mesh)
        self.train_loss_fn = train_loss_fn or LabelSmoothingCrossEntropy(0.0)
        self.alpha = distill_alpha
        self.feat_loss = feat_loss

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        x = batch['input']
        feats = model.forward_features(x)
        output = model.forward_head(feats)
        teacher = nnx.merge(self._teacher_graphdef, *self._teacher_state)
        t_feats = jax.lax.stop_gradient(teacher.forward_features(x))

        s_pool = feats.mean(axis=1) if feats.ndim == 3 else feats.mean(axis=(1, 2))
        t_pool = t_feats.mean(axis=1) if t_feats.ndim == 3 else t_feats.mean(axis=(1, 2))
        if hasattr(model, 'distill_proj'):
            s_pool = model.distill_proj(s_pool)
        s_pool = s_pool.astype(jnp.float32)
        t_pool = t_pool.astype(jnp.float32)
        if self.feat_loss == 'cosine':
            sn = s_pool / (jnp.linalg.norm(s_pool, axis=-1, keepdims=True) + 1e-6)
            tn = t_pool / (jnp.linalg.norm(t_pool, axis=-1, keepdims=True) + 1e-6)
            kd = (1.0 - (sn * tn).sum(axis=-1)).mean()
        else:  # mse
            kd = jnp.mean(jnp.square(s_pool - t_pool))

        base_loss = self.train_loss_fn(output, batch['target'])
        loss = (1.0 - self.alpha) * base_loss + self.alpha * kd
        return loss, output
