"""Classification task (reference: timm/task/classification.py:13-100)."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from flax import nnx

from ..loss import LabelSmoothingCrossEntropy
from .task import TrainingTask

__all__ = ['ClassificationTask']


class ClassificationTask(TrainingTask):
    def __init__(
            self,
            model: nnx.Module,
            optimizer=None,
            train_loss_fn: Optional[Callable] = None,
            eval_loss_fn: Optional[Callable] = None,
            **kwargs,
    ):
        super().__init__(model, optimizer=optimizer, **kwargs)
        self.train_loss_fn = train_loss_fn or LabelSmoothingCrossEntropy(0.0)
        self.eval_loss_fn = eval_loss_fn or self.train_loss_fn

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        output = model(batch['input'])
        loss = self.train_loss_fn(output, batch['target'])
        return loss, output


class NaFlexClassificationTask(ClassificationTask):
    """Classification over NaFlex dict batches ({patches, patch_coord,
    patch_valid, target[, target_b, lam]}); each (seq_len, patch_size)
    bucket traces once. When the loader performed variable-size mixup/cutmix,
    the per-sample lam-mixed (and optionally smoothed) soft target
    distribution is built here and fed to the CONFIGURED train loss
    (SoftTargetCrossEntropy, BCE, ... — anything accepting dense targets),
    mirroring how the reference's Mixup builds soft labels for the tuple
    pipeline (reference mixup.py mixup_target)."""

    def __init__(self, *args, mixup_label_smoothing: Optional[float] = None, **kwargs):
        super().__init__(*args, **kwargs)
        # not-None ⇒ the train loss expects DENSE targets (mixup configured);
        # un-mixed batches then get smoothed one-hot targets too
        self.mixup_label_smoothing = mixup_label_smoothing

    def _soft_targets(self, batch, nc):
        import jax.numpy as jnp
        s = self.mixup_label_smoothing or 0.0
        off, on = s / nc, 1.0 - s + s / nc
        B = batch['target'].shape[0]
        oh_a = jnp.full((B, nc), off).at[jnp.arange(B), batch['target']].set(on)
        if 'lam' not in batch:
            return oh_a
        oh_b = jnp.full((B, nc), off).at[jnp.arange(B), batch['target_b']].set(on)
        lam = batch['lam'].astype(jnp.float32)[:, None]
        return lam * oh_a + (1.0 - lam) * oh_b

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        output = model({
            'patches': batch['patches'],
            'patch_coord': batch['patch_coord'],
            'patch_valid': batch['patch_valid'],
        })
        if self.mixup_label_smoothing is not None or 'lam' in batch:
            loss = self.train_loss_fn(output, self._soft_targets(batch, output.shape[-1]))
        else:
            loss = self.train_loss_fn(output, batch['target'])
        return loss, output

    def eval_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        return model({
            'patches': batch['patches'],
            'patch_coord': batch['patch_coord'],
            'patch_valid': batch['patch_valid'],
        })
