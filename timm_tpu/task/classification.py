"""Classification task (reference: timm/task/classification.py:13-100)."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from flax import nnx

from ..loss import LabelSmoothingCrossEntropy
from .task import TrainingTask

__all__ = ['ClassificationTask']


class ClassificationTask(TrainingTask):
    def __init__(
            self,
            model: nnx.Module,
            optimizer=None,
            train_loss_fn: Optional[Callable] = None,
            eval_loss_fn: Optional[Callable] = None,
            **kwargs,
    ):
        super().__init__(model, optimizer=optimizer, **kwargs)
        self.train_loss_fn = train_loss_fn or LabelSmoothingCrossEntropy(0.0)
        self.eval_loss_fn = eval_loss_fn or self.train_loss_fn

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        output = model(batch['input'])
        loss = self.train_loss_fn(output, batch['target'])
        return loss, output


class NaFlexClassificationTask(ClassificationTask):
    """Classification over NaFlex dict batches ({patches, patch_coord,
    patch_valid, target}); each seq-len bucket traces once."""

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        output = model({
            'patches': batch['patches'],
            'patch_coord': batch['patch_coord'],
            'patch_valid': batch['patch_valid'],
        })
        loss = self.train_loss_fn(output, batch['target'])
        return loss, output

    def eval_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        return model({
            'patches': batch['patches'],
            'patch_coord': batch['patch_coord'],
            'patch_valid': batch['patch_valid'],
        })
