from .classification import ClassificationTask, NaFlexClassificationTask
from .distillation import FeatureDistillationTask, LogitDistillationTask
from .token_distillation import TokenDistillationTask
from .task import TrainingTask
