from .classification import ClassificationTask
from .distillation import FeatureDistillationTask, LogitDistillationTask
from .task import TrainingTask
