"""DeiT-style token distillation (reference: timm/task/token_distillation.py).

Student must expose `set_distilled_training(True)` and return
(cls_logits, dist_logits) in distilled-training mode.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..loss import LabelSmoothingCrossEntropy, cross_entropy
from .task import TrainingTask

__all__ = ['TokenDistillationTask']


class TokenDistillationTask(TrainingTask):
    def __init__(
            self,
            model: nnx.Module,
            teacher: nnx.Module,
            optimizer=None,
            train_loss_fn: Optional[Callable] = None,
            distill_type: str = 'hard',
            distill_alpha: float = 0.5,
            distill_temperature: float = 1.0,
            **kwargs,
    ):
        assert distill_type in ('soft', 'hard')
        assert hasattr(model, 'set_distilled_training'), 'model must support the distilled-training contract'
        model.set_distilled_training(True)
        super().__init__(model, optimizer=optimizer, **kwargs)
        teacher.eval()
        self._teacher_graphdef, self._teacher_state = nnx.split(teacher)
        self.train_loss_fn = train_loss_fn or LabelSmoothingCrossEntropy(0.0)
        self.distill_type = distill_type
        self.alpha = distill_alpha
        self.temperature = distill_temperature

    def loss_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        x = batch['input']
        out = model(x)
        assert isinstance(out, tuple), 'distilled model must return (cls, dist) logits in training'
        cls_logits, dist_logits = out
        teacher = nnx.merge(self._teacher_graphdef, self._teacher_state)
        teacher_logits = jax.lax.stop_gradient(teacher(x))

        base_loss = self.train_loss_fn(cls_logits, batch['target'])
        if self.distill_type == 'hard':
            kd = cross_entropy(dist_logits, jnp.argmax(teacher_logits, axis=-1))
        else:
            T = self.temperature
            s = jax.nn.log_softmax(dist_logits.astype(jnp.float32) / T, axis=-1)
            t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / T, axis=-1)
            kd = (t * (jnp.log(jnp.clip(t, 1e-9)) - s)).sum(axis=-1).mean() * (T * T)
        loss = (1.0 - self.alpha) * base_loss + self.alpha * kd
        return loss, cls_logits

    def eval_forward(self, model: nnx.Module, batch: Dict[str, Any]):
        # averaged-head eval WITHOUT flipping distilled_training — attribute
        # mutation inside the jitted step would leak to the shared model and
        # break subsequent train steps (flags are trace-time structure)
        feats = model.forward_features(batch['input'])
        x_cls = model.head(feats[:, 0])
        x_dist = model.head_dist(feats[:, 1])
        return (x_cls + x_dist) / 2
