"""Host RNG stream capture/restore for step-granular (mid-epoch) resume.

Host-side randomness (numpy's global MT19937 used by mixup/random-erasing,
python's `random` used by augmentation policies) must continue from the exact
preemption point for `--resume auto` to be bit-identical to an uninterrupted
run. Device RNG streams (nnx dropout counters) are keyed per-step and need no
capture. All values serialize as plain arrays so they ride inside the same
.npz recovery checkpoint under the `_resume.` prefix.
"""
from __future__ import annotations

import logging
import random as _pyrandom
from typing import Dict

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = ['RESUME_PREFIX', 'capture_host_rng', 'restore_host_rng']

# Every step-granular resume key (RNG streams here; loader position, update
# counter, global batch in train.py) rides inside the recovery .npz under
# this prefix, and is filtered back out before state re-placement.
RESUME_PREFIX = '_resume.'


def capture_host_rng() -> Dict[str, np.ndarray]:
    name, keys, pos, has_gauss, cached = np.random.get_state()
    out = {
        RESUME_PREFIX + 'np_rng_keys': np.asarray(keys, np.uint32),
        RESUME_PREFIX + 'np_rng_meta': np.asarray([pos, has_gauss], np.int64),
        RESUME_PREFIX + 'np_rng_gauss': np.asarray(cached, np.float64),
    }
    version, internal, gauss_next = _pyrandom.getstate()
    if version == 3:
        out[RESUME_PREFIX + 'py_rng_state'] = np.asarray(internal, np.uint64)
        out[RESUME_PREFIX + 'py_rng_gauss'] = np.asarray(
            [1.0, gauss_next] if gauss_next is not None else [0.0, 0.0], np.float64)
    return out


def restore_host_rng(state: Dict[str, np.ndarray]) -> bool:
    """Restore streams captured by `capture_host_rng` from a checkpoint state
    dict; returns True if anything was restored. Missing keys (end-of-epoch
    checkpoints don't carry them) are a silent no-op."""
    restored = False
    if '_resume.np_rng_keys' in state:
        meta = np.asarray(state['_resume.np_rng_meta'])
        np.random.set_state((
            'MT19937',
            np.asarray(state['_resume.np_rng_keys'], np.uint32),
            int(meta[0]), int(meta[1]),
            float(np.asarray(state['_resume.np_rng_gauss'])),
        ))
        restored = True
    if '_resume.py_rng_state' in state:
        gauss = np.asarray(state['_resume.py_rng_gauss'])
        _pyrandom.setstate((
            3,
            tuple(int(x) for x in np.asarray(state['_resume.py_rng_state'])),
            float(gauss[1]) if gauss[0] else None,
        ))
        restored = True
    if restored:
        _logger.info('Restored host RNG streams from recovery checkpoint')
    return restored
