"""Multi-process host-loss drill: the executable proof that the `dcn` axis is
real and that a pod survives losing a host mid-epoch.

`run_kill_drill` launches a real N-process JAX cluster on CPU (one device per
process, coordinator on a free localhost port), trains a tiny ViT on the
process-sharded synthetic pipeline with process-local sharded checkpoints,
then SIGKILLs one host mid-epoch via `kill_host@N:P` fault injection. It
asserts the full recovery contract:

  1. the victim dies hard (no recovery save, no consensus vote);
  2. every survivor detects the loss through the KV-store consensus timeout
     (`all_hosts_flag(name=...)`) and exits 0 at the SAME update;
  3. the survivor's post-loss recovery save writes its shard but CANNOT
     commit (the `mode='all'` barrier fails on the dead peer), so the
     previous committed checkpoint remains the newest valid one — the
     manifest-commit ordering is crash-safe by construction;
  4. `--resume auto --elastic` on a fresh (smaller) cluster re-places the
     host-sharded checkpoint under the live mesh and finishes the run;
  5. the final parameters match an uninterrupted single-process baseline.

Used by tests/test_multihost.py (tier-1), tests/multihost_drill.py (manual /
slow), and the `multihost` step of `bench.py --replay`.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

__all__ = ['run_kill_drill', 'free_port', 'cluster_env']

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    """An OS-assigned free TCP port for the cluster coordinator."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(('localhost', 0))
        return s.getsockname()[1]


def cluster_env(process_id: int, num_processes: int, port: int,
                devices_per_process: int = 1,
                barrier_timeout: float = 6.0) -> Dict[str, str]:
    """Environment for one member of a CPU JAX cluster (train.py
    --distributed reads COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID)."""
    env = dict(os.environ)
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': f'--xla_force_host_platform_device_count={devices_per_process}',
        'COORDINATOR_ADDRESS': f'localhost:{port}',
        'NUM_PROCESSES': str(num_processes),
        'PROCESS_ID': str(process_id),
        # consensus at every update so the victim's death is detected at the
        # same step it happens; short barrier so the drill stays fast
        'TIMM_TPU_PREEMPTION_POLL': '1',
        'TIMM_TPU_BARRIER_TIMEOUT': str(barrier_timeout),
    })
    return env


def _train_cmd(workdir: str, experiment: str, *extra: str,
               model: str = 'test_vit', img_size: int = 32,
               global_batch: int = 8, synthetic_len: int = 64,
               epochs: int = 1, recovery_interval: int = 2) -> List[str]:
    return [
        sys.executable, os.path.join(_REPO, 'train.py'),
        '--synthetic-data', '--model', model, '--img-size', str(img_size),
        '-b', str(global_batch), '--synthetic-len', str(synthetic_len),
        '--epochs', str(epochs), '--opt', 'sgd', '--lr', '0.05',
        '--sched', 'cosine', '--warmup-epochs', '0', '--workers', '1',
        '--log-interval', '50', '--recovery-interval', str(recovery_interval),
        '--output', workdir, '--experiment', experiment, *extra,
    ]


def _run(cmd: List[str], env: Dict[str, str], log_path: str, timeout: int):
    with open(log_path, 'w') as f:
        proc = subprocess.run(cmd, env=env, cwd=_REPO, stdout=f, stderr=subprocess.STDOUT,
                              timeout=timeout)
    with open(log_path) as f:
        return proc.returncode, f.read()


def run_kill_drill(workdir: str, processes: int = 2, kill_update: int = 4,
                   victim: Optional[int] = None, synthetic_len: int = 64,
                   global_batch: int = 8, epochs: int = 1,
                   recovery_interval: int = 2, model: str = 'test_vit',
                   img_size: int = 32, barrier_timeout: float = 6.0,
                   compare: bool = True, resume: bool = True,
                   timeout: int = 420, log=None) -> dict:
    """Run the host-loss drill; returns {'ok', 'checks', 'details'}.

    compare=False / resume=False trims the baseline and resume legs (the
    replay dry arm only proves bring-up + kill + consensus + commit safety).
    """
    from .durable import load_verified, manifest_path, resolve_auto_resume, verify_checkpoint

    log = log or (lambda m: None)
    checks: Dict[str, bool] = {}
    details: Dict[str, object] = {}
    os.makedirs(workdir, exist_ok=True)
    if victim is None:
        victim = processes - 1  # keep process 0 (the coordinator host) alive
    base_kw = dict(model=model, img_size=img_size, global_batch=global_batch,
                   synthetic_len=synthetic_len, epochs=epochs,
                   recovery_interval=recovery_interval)

    # --- leg 0: uninterrupted single-process baseline -----------------------
    if compare:
        log('baseline: single-process uninterrupted run')
        env = cluster_env(0, 1, free_port(), barrier_timeout=barrier_timeout)
        for k in ('COORDINATOR_ADDRESS', 'NUM_PROCESSES', 'PROCESS_ID'):
            env.pop(k, None)
        rc, _ = _run(_train_cmd(workdir, 'baseline', **base_kw), env,
                     os.path.join(workdir, 'baseline.log'), timeout)
        checks['baseline_ok'] = rc == 0

    # --- leg 1: N-process cluster, kill one host mid-epoch ------------------
    log(f'cluster: {processes} processes, kill_host@{kill_update}:{victim}')
    port = free_port()
    procs, log_paths = [], []
    for p in range(processes):
        lp = os.path.join(workdir, f'pod-p{p}.log')
        log_paths.append(lp)
        cmd = _train_cmd(workdir, 'pod', '--distributed',
                         '--fault-inject', f'kill_host@{kill_update}:{victim}',
                         **base_kw)
        procs.append(subprocess.Popen(
            cmd, env=cluster_env(p, processes, port, barrier_timeout=barrier_timeout),
            cwd=_REPO, stdout=open(lp, 'w'), stderr=subprocess.STDOUT))
    deadline = time.time() + timeout
    rcs = [None] * processes
    try:
        for p, proc in enumerate(procs):
            rcs[p] = proc.wait(timeout=max(1, deadline - time.time()))
    except subprocess.TimeoutExpired:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        rcs = [proc.wait() for proc in procs]
        details['timeout'] = True
    finally:
        for proc in procs:
            if proc.stdout:
                proc.stdout.close()
    logs = []
    for lp in log_paths:
        with open(lp) as f:
            logs.append(f.read())
    details['pod_returncodes'] = rcs
    checks['victim_sigkilled'] = rcs[victim] == -signal.SIGKILL
    checks['survivors_exit0'] = all(rcs[p] == 0 for p in range(processes) if p != victim)
    # every survivor must stop via the consensus path (no signal was sent
    # to it) and report the failed post-loss commit barrier
    survivor_logs = [logs[p] for p in range(processes) if p != victim]
    checks['survivor_consensus'] = all('Preempted during epoch' in sl for sl in survivor_logs)
    checks['uncommitted_post_loss_save'] = any(
        'shard barrier failed' in sl for sl in survivor_logs)

    # --- crash-safety: newest VALID checkpoint is the last committed one ----
    pod_dir = os.path.join(workdir, 'pod')
    resolved = resolve_auto_resume(pod_dir) or ''
    details['resolved_resume'] = resolved
    checks['resume_committed'] = bool(resolved) and verify_checkpoint(resolved)[0]
    # the survivor's post-loss shard (written but never committed) must still
    # be on disk, newer than the resolved checkpoint — proof the manifest is
    # the commit record, not the shard write
    litter = [f for f in os.listdir(pod_dir) if '.shard' in f and f.endswith('.npz')]
    logical = lambda f: f.split('.shard')[0] + '.npz'  # noqa: E731
    uncommitted = [f for f in litter
                   if not os.path.exists(manifest_path(os.path.join(pod_dir, logical(f))))]
    details['uncommitted_shards'] = uncommitted
    checks['uncommitted_litter_ignored'] = (
        bool(uncommitted) and bool(resolved)
        and all(logical(f) != os.path.basename(resolved) for f in uncommitted))

    # --- leg 2: fresh smaller cluster resumes the host-sharded checkpoint ---
    if resume:
        log('resume: single-process --resume auto --elastic from the sharded recovery')
        env = cluster_env(0, 1, free_port(), barrier_timeout=barrier_timeout)
        for k in ('COORDINATOR_ADDRESS', 'NUM_PROCESSES', 'PROCESS_ID'):
            env.pop(k, None)
        rc, out = _run(_train_cmd(workdir, 'pod', '--resume', 'auto', '--elastic', **base_kw),
                       env, os.path.join(workdir, 'resume.log'), timeout)
        checks['resume_ok'] = rc == 0
        checks['resumed_mid_epoch'] = 'Resumed mid-epoch from' in out
        checks['elastic_replaced'] = '[elastic] live topology' in out

    # --- final-state parity against the uninterrupted baseline --------------
    if compare and resume:
        final = os.path.join(workdir, 'pod', 'last.npz')
        ref = os.path.join(workdir, 'baseline', 'last.npz')
        if os.path.exists(final) and os.path.exists(ref):
            import numpy as np
            got, _ = load_verified(final)
            want, _ = load_verified(ref)
            keys = [k for k in want if k.startswith(('state_dict.', 'optimizer.'))]
            diffs = [float(np.max(np.abs(np.asarray(got[k], np.float64)
                                         - np.asarray(want[k], np.float64))))
                     for k in keys if k in got]
            details['max_param_diff'] = max(diffs) if diffs else float('inf')
            checks['final_match'] = (len(diffs) == len(keys) > 0
                                     and details['max_param_diff'] <= 1e-6)
        else:
            checks['final_match'] = False

    ok = all(checks.values())
    if not ok:
        failed = [k for k, v in checks.items() if not v]
        log(f'kill drill FAILED checks: {failed}')
        for p, l in enumerate(logs):
            log(f'--- pod-p{p} tail ---\n' + '\n'.join(l.splitlines()[-15:]))
    return {'ok': ok, 'checks': checks, 'details': details}


if __name__ == '__main__':
    import tempfile
    wd = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(prefix='timm_tpu_multihost_')
    result = run_kill_drill(wd, log=lambda m: print(f'[multihost] {m}', flush=True))
    print(json.dumps(result, indent=2, default=str))
    sys.exit(0 if result['ok'] else 1)
