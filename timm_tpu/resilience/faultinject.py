"""Fault-injection harness for resilience drills.

Spec grammar (comma-separated, via `train.py --fault-inject`, `bench.py
--dry-run --fault-inject`, or env `TIMM_TPU_FAULT_INJECT`):

  truncate_ckpt     truncate the NEXT checkpoint write after commit (one-shot)
  nan_grads@N       poison the batch at global update N so loss/grads go NaN;
                    nan_grads@N:K poisons K consecutive updates (abort drills)
  sigterm@N         deliver SIGTERM to this process at global update N (one-shot)
  io_error%M        raise IOError on every M-th sample read (exercises the
                    reader retry/backoff + poison-skip budget — and, when an
                    async checkpoint writer is armed, its durable-write path)
  resize@N:D        elastic-resize drill: deliver SIGTERM at global update N
                    (one-shot, like sigterm@N); the restarting harness reads
                    `resize_devices` = D and relaunches with that forced
                    device count (`--elastic` resume rebuilds the mesh)
  kill_host@N[:P]   host-loss drill: SIGKILL process P (default 0) at global
                    update N — no recovery save, no clean exit, exactly what
                    a preempted/failed pod host looks like. Every process can
                    carry the same spec; only the one whose
                    `jax.process_index()` == P dies (single-process runs with
                    P=0 kill themselves)

The injector is deliberately dumb: hooks call `take`/`nan_at`/`sigterm_at`/
`io_error_tick` at the natural fault site, so the tests and manual drills
exercise the REAL recovery paths (durable fallback, non-finite sentinel,
preemption save, reader retry) rather than mocks.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

_logger = logging.getLogger(__name__)

__all__ = ['FaultInjector', 'get_fault_injector', 'set_fault_injector', 'fault_selftest']

_KINDS_ONESHOT = ('truncate_ckpt',)
_KINDS_AT = ('nan_grads', 'sigterm', 'resize', 'kill_host')
_KINDS_EVERY = ('io_error',)


class FaultInjector:
    """Parsed fault spec with thread-safe trigger bookkeeping."""

    def __init__(self, spec: str = ''):
        self.spec = (spec or '').strip()
        self._lock = threading.Lock()
        self._oneshot: Dict[str, bool] = {}     # kind -> armed
        self._at: Dict[str, tuple] = {}         # kind -> (start_update, count)
        self._fired: Dict[str, bool] = {}
        self._every: Dict[str, int] = {}        # kind -> period M
        self._ticks: Dict[str, int] = {}
        self.resize_devices: Optional[int] = None
        self.kill_host_process: int = 0
        for part in filter(None, (p.strip() for p in self.spec.split(','))):
            if '@' in part:
                kind, _, n = part.partition('@')
                if kind not in _KINDS_AT:
                    raise ValueError(f'unknown @-fault {kind!r} in spec {spec!r}')
                n, _, suffix = n.partition(':')
                if kind == 'kill_host':
                    # kill_host@N:P — the :P suffix is the target process
                    # index (default 0), not a window; fires exactly once
                    if suffix and int(suffix) < 0:
                        raise ValueError(f'kill_host process index must be >= 0: {part!r}')
                    self.kill_host_process = int(suffix) if suffix else 0
                    self._at[kind] = (int(n), 1)
                elif kind == 'resize':
                    # resize@N:D — the :D suffix is the restart's forced
                    # device count, not a window; the fault fires exactly once
                    if not suffix or int(suffix) < 1:
                        raise ValueError(
                            f'resize fault needs a device count >= 1: {part!r} '
                            f'(want resize@N:D)')
                    self.resize_devices = int(suffix)
                    self._at[kind] = (int(n), 1)
                else:
                    self._at[kind] = (int(n), max(1, int(suffix)) if suffix else 1)
            elif '%' in part:
                kind, _, m = part.partition('%')
                if kind not in _KINDS_EVERY:
                    raise ValueError(f'unknown %-fault {kind!r} in spec {spec!r}')
                if int(m) < 1:
                    raise ValueError(f'fault period must be >= 1: {part!r}')
                self._every[kind] = int(m)
            elif part in _KINDS_ONESHOT:
                self._oneshot[part] = True
            else:
                raise ValueError(f'unknown fault {part!r} in spec {spec!r} '
                                 f'(known: {_KINDS_ONESHOT + _KINDS_AT + _KINDS_EVERY})')

    def __bool__(self):
        return bool(self._oneshot or self._at or self._every)

    def take(self, kind: str) -> bool:
        """Consume a one-shot fault; True exactly once if armed."""
        with self._lock:
            if self._oneshot.get(kind):
                self._oneshot[kind] = False
                return True
        return False

    def _at_window(self, kind: str, update_idx: int) -> bool:
        window = self._at.get(kind)
        return window is not None and window[0] <= update_idx < window[0] + window[1]

    def nan_at(self, update_idx: int) -> bool:
        return self._at_window('nan_grads', update_idx)

    def sigterm_at(self, update_idx: int) -> bool:
        with self._lock:
            if self._at_window('sigterm', update_idx) and not self._fired.get('sigterm'):
                self._fired['sigterm'] = True
                return True
        return False

    def resize_at(self, update_idx: int) -> bool:
        """True exactly once when `resize@N:D` is armed and update N is
        reached. The caller SIGTERMs itself (same recovery-save path as a
        real preemption); the restarting harness reads `resize_devices` for
        the forced device count of the relaunch."""
        with self._lock:
            if self._at_window('resize', update_idx) and not self._fired.get('resize'):
                self._fired['resize'] = True
                return True
        return False

    def kill_host_at(self, update_idx: int, process_index: int = 0) -> bool:
        """True exactly once when `kill_host@N[:P]` is armed, update N is
        reached, AND this is process P. The caller SIGKILLs itself — no
        recovery save, no consensus: the survivors must detect the loss via
        the KV-store consensus timeout and stop on their own."""
        if process_index != self.kill_host_process:
            return False
        with self._lock:
            if self._at_window('kill_host', update_idx) and not self._fired.get('kill_host'):
                self._fired['kill_host'] = True
                return True
        return False

    def io_error_tick(self) -> bool:
        """True on every M-th call when `io_error%M` is armed (thread-safe)."""
        period = self._every.get('io_error')
        if not period:
            return False
        with self._lock:
            self._ticks['io_error'] = self._ticks.get('io_error', 0) + 1
            return self._ticks['io_error'] % period == 0


_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def get_fault_injector() -> Optional[FaultInjector]:
    """Process-wide injector; lazily built from TIMM_TPU_FAULT_INJECT. Returns
    None when no faults are armed (hooks stay zero-cost)."""
    global _injector
    if _injector is None:
        spec = os.environ.get('TIMM_TPU_FAULT_INJECT', '')
        if not spec.strip():
            return None
        with _injector_lock:
            if _injector is None:
                _injector = FaultInjector(spec)
    return _injector if _injector else None


def set_fault_injector(spec_or_injector) -> Optional[FaultInjector]:
    """Install (or clear, with ''/None) the process-wide injector."""
    global _injector
    with _injector_lock:
        if spec_or_injector is None or spec_or_injector == '':
            _injector = None
        elif isinstance(spec_or_injector, FaultInjector):
            _injector = spec_or_injector
        else:
            _injector = FaultInjector(str(spec_or_injector))
        if _injector:
            _logger.info(f'Fault injection armed: {_injector.spec}')
    return _injector


def fault_selftest(spec: str = '', tmp_dir: Optional[str] = None) -> dict:
    """Exercise every injection hook + its recovery path on CPU, no model.

    Used by `bench.py --dry-run --fault-inject` and tests/test_resilience.py
    so the harness itself is covered in tier-1 without slow runs. Returns
    {'ok': bool, 'checks': {name: bool}, 'spec': parsed-spec}.
    """
    import tempfile

    import numpy as np

    from . import durable
    from .retry import SkipBudget, TooManyBadSamples, retry_io

    if spec:
        FaultInjector(spec)  # parse check of the user-provided spec
    checks = {}
    prev = _injector
    work = tmp_dir or tempfile.mkdtemp(prefix='timm_tpu_faultdrill_')
    try:
        # 1. truncate_ckpt → verification fails → fallback finds the older valid file
        set_fault_injector('')
        good = os.path.join(work, 'checkpoint-0.npz')
        durable.atomic_write_npz(good, {'w': np.arange(8.0)}, meta={'epoch': 0})
        set_fault_injector('truncate_ckpt')
        bad = os.path.join(work, 'checkpoint-1.npz')
        durable.atomic_write_npz(bad, {'w': np.arange(8.0) + 1}, meta={'epoch': 1})
        ok_bad, _ = durable.verify_checkpoint(bad)
        _, _, used = durable.load_with_fallback(bad, search_dir=work)
        checks['truncate_then_fallback'] = (not ok_bad) and used == good
        # 2. io_error%2 → retry_io rides through transient faults
        set_fault_injector('io_error%2')
        injector = get_fault_injector()

        def read():
            if injector.io_error_tick():
                raise IOError('injected')
            return 42

        checks['io_retry'] = retry_io(read, retries=3, base_delay=0.0, desc='selftest') == 42
        # 3. poison-skip budget trips after the configured number of bad samples
        budget = SkipBudget(budget=2)
        budget.record(ValueError('poison'), 'sample 0')
        budget.record(ValueError('poison'), 'sample 1')
        try:
            budget.record(ValueError('poison'), 'sample 2')
            checks['skip_budget'] = False
        except TooManyBadSamples:
            checks['skip_budget'] = True
        # 4. @-faults: nan window covers [N, N+K), sigterm fires exactly once
        fi = FaultInjector('nan_grads@3:2,sigterm@5')
        checks['at_faults'] = (not fi.nan_at(2) and fi.nan_at(3) and fi.nan_at(4)
                               and not fi.nan_at(5)
                               and fi.sigterm_at(5) and not fi.sigterm_at(5))
        # 5. resize@N:D parses the forced device count and fires exactly once
        fi = FaultInjector('resize@4:2')
        checks['resize'] = (fi.resize_devices == 2 and not fi.resize_at(3)
                            and fi.resize_at(4) and not fi.resize_at(4))
        # 6. kill_host@N:P targets exactly process P, fires exactly once
        fi = FaultInjector('kill_host@6:1')
        checks['kill_host'] = (fi.kill_host_process == 1
                               and not fi.kill_host_at(6, process_index=0)
                               and not fi.kill_host_at(5, process_index=1)
                               and fi.kill_host_at(6, process_index=1)
                               and not fi.kill_host_at(6, process_index=1)
                               and FaultInjector('kill_host@2').kill_host_process == 0)
    finally:
        set_fault_injector(prev)
        if tmp_dir is None:
            import shutil
            shutil.rmtree(work, ignore_errors=True)
    return {'ok': all(checks.values()), 'checks': checks, 'spec': spec}
