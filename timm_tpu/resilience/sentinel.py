"""Non-finite (NaN/Inf) step sentinel.

Device side: `tree_all_finite` is a jit-compatible all-finite reduction over
loss + gradients that fuses into the compiled train step; the step keeps a
device-resident `[consecutive, total]` int32 counter pair and selects between
the updated and previous (params, opt_state, EMA) with `jnp.where`, so a bad
step costs its compute but commits nothing — no retrace, no host round-trip.

Host side: `NonFiniteSentinel` polls the counter (every
TIMM_TPU_NONFINITE_CHECK_EVERY steps; 1 = precise, larger values avoid a
per-step device sync on TPU — correct either way because the consecutive
counter only resets on a GOOD step, so a run long enough to abort is still
standing at the next poll) and raises `NonFiniteError` after K consecutive
bad steps (K = TIMM_TPU_NONFINITE_TOLERANCE, default 3).

Because loss and grads are computed from the globally-sharded batch with
replicated params, the all-finite flag is identical on every host of a pod —
all hosts skip the same step and abort at the same poll without extra
cross-host coordination (see parallel.all_hosts_flag for host-local signals
like preemption, which DO need it).
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp

_logger = logging.getLogger(__name__)

__all__ = ['NonFiniteError', 'NonFiniteSentinel', 'tree_all_finite',
           'new_sentinel_state', 'update_sentinel_state', 'guard_enabled']

DEFAULT_TOLERANCE = 3


class NonFiniteError(RuntimeError):
    def __init__(self, consecutive: int, total: int, step: int, tolerance: int):
        self.consecutive = consecutive
        self.total = total
        self.step = step
        self.tolerance = tolerance
        super().__init__(
            f'{consecutive} consecutive non-finite train steps at update {step} '
            f'(tolerance {tolerance}, {total} bad steps total). The last '
            f'committed checkpoint is intact; lower the LR / enable grad '
            f'clipping, or resume with --nonfinite-rollback to retry from it. '
            f'Set TIMM_TPU_NONFINITE_TOLERANCE to adjust the abort threshold.')


def guard_enabled(explicit: Optional[bool] = None) -> bool:
    """Guard default: on, unless TIMM_TPU_NONFINITE_GUARD=0."""
    if explicit is not None:
        return explicit
    return os.environ.get('TIMM_TPU_NONFINITE_GUARD', '1') not in ('0', 'false', 'off')


def tree_all_finite(*trees) -> jax.Array:
    """Scalar bool: every inexact-dtype leaf of every tree is finite.
    Jit-compatible; integer/bool leaves (e.g. optimizer step counts) are
    finite by construction and skipped."""
    ok = jnp.asarray(True)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                ok = jnp.logical_and(ok, jnp.isfinite(leaf).all())
    return ok


def new_sentinel_state() -> jax.Array:
    """[consecutive_bad, total_bad] int32 device counters."""
    return jnp.zeros((2,), jnp.int32)


def update_sentinel_state(state: jax.Array, ok: jax.Array) -> jax.Array:
    bad = jnp.logical_not(ok).astype(jnp.int32)
    consecutive = jnp.where(ok, 0, state[0] + 1)
    return jnp.stack([consecutive, state[1] + bad])


class NonFiniteSentinel:
    def __init__(self, tolerance: Optional[int] = None, check_every: Optional[int] = None):
        if tolerance is None:
            tolerance = int(os.environ.get('TIMM_TPU_NONFINITE_TOLERANCE', DEFAULT_TOLERANCE))
        if check_every is None:
            check_every = int(os.environ.get('TIMM_TPU_NONFINITE_CHECK_EVERY', 1))
        assert tolerance >= 1, 'nonfinite tolerance must be >= 1'
        self.tolerance = tolerance
        self.check_every = max(1, check_every)
        self.consecutive = 0   # as of the last poll
        self.total = 0
        self._calls = 0

    def reset(self):
        self.consecutive = 0
        self._calls = 0

    def observe(self, sentinel_state, step: int = 0) -> bool:
        """Poll the device counters; True if the LAST step was skipped.
        Raises NonFiniteError once `tolerance` consecutive steps went bad."""
        self._calls += 1
        if self._calls % self.check_every != 0:
            return False
        counts = jax.device_get(sentinel_state)
        consecutive, total = int(counts[0]), int(counts[1])
        newly_bad = total - self.total
        self.consecutive, self.total = consecutive, total
        if newly_bad > 0:
            _logger.warning(
                f'Non-finite loss/grads at update {step}: update skipped '
                f'({consecutive} consecutive, {total} total)')
        if consecutive >= self.tolerance:
            raise NonFiniteError(consecutive, total, step, self.tolerance)
        return newly_bad > 0
