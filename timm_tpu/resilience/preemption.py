"""Preemption-aware shutdown (Borg/Pathways-style SIGTERM handling).

TPU-pod maintenance events and preemptible-slice reclaims deliver SIGTERM
with a grace window. `GracefulShutdown` converts the signal into a flag the
train loop polls between updates; the loop then writes a step-granular
recovery checkpoint (data-loader position, host RNG state, update counter)
and exits 0 so the scheduler restarts the job, which resumes mid-epoch via
`--resume auto`.

Multi-host: the signal may reach only some hosts, but every host must stop
at the SAME update or the next collective deadlocks. `should_stop` therefore
reaches cross-host consensus via `parallel.all_hosts_flag` at a fixed update
cadence (TIMM_TPU_PREEMPTION_POLL, default 16) — all hosts evaluate the same
updates, so they agree on the stop step by construction.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Optional

_logger = logging.getLogger(__name__)

__all__ = ['GracefulShutdown', 'TrainingPreempted']

DEFAULT_CONSENSUS_EVERY = 16


class TrainingPreempted(Exception):
    """Raised by the train loop after the recovery checkpoint is written; the
    top level logs and exits 0 (preemption is a normal, rescheduable exit)."""

    def __init__(self, recovery_path: str = ''):
        self.recovery_path = recovery_path
        super().__init__(f'preempted; recovery checkpoint: {recovery_path or "n/a"}')


class GracefulShutdown:
    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), consensus_every: Optional[int] = None):
        self.signals = tuple(signals)
        if consensus_every is None:
            consensus_every = int(os.environ.get('TIMM_TPU_PREEMPTION_POLL', DEFAULT_CONSENSUS_EVERY))
        self.consensus_every = max(1, consensus_every)
        self._flag = threading.Event()
        self._signum: Optional[int] = None
        self._prev_handlers = {}
        self._installed = False

    def install(self) -> 'GracefulShutdown':
        """Install handlers (main thread only; no-op elsewhere so library use
        inside workers stays safe). Idempotent: a second install keeps the
        ORIGINAL handler chain — it must not record our own handler as the
        previous one, or uninstall() could never restore the caller's. A
        partial install (one signal.signal raising) rolls back so no signal
        is left pointing at a handler whose siblings never registered."""
        if threading.current_thread() is not threading.main_thread():
            _logger.warning('GracefulShutdown.install() skipped: not on the main thread')
            return self
        if self._installed:
            return self
        installed = []
        try:
            for sig in self.signals:
                self._prev_handlers[sig] = signal.signal(sig, self._handle)
                installed.append(sig)
        except BaseException:
            for sig in installed:
                signal.signal(sig, self._prev_handlers.pop(sig))
            raise
        self._installed = True
        return self

    def uninstall(self):
        """Restore the previous handlers. Finally-safe: every recorded
        handler is restored (and forgotten) even when one restore raises;
        the first error propagates after the rest are back in place."""
        first_err = None
        for sig in list(self._prev_handlers):
            prev = self._prev_handlers.pop(sig)
            try:
                signal.signal(sig, prev)
            except BaseException as e:  # keep restoring the remaining signals
                if first_err is None:
                    first_err = e
        self._installed = False
        if first_err is not None:
            raise first_err

    def _handle(self, signum, frame):
        if self._flag.is_set() and signum == signal.SIGINT:
            # second ctrl-c: the user really means it
            raise KeyboardInterrupt
        self._signum = signum
        self._flag.set()
        _logger.warning(
            f'Received {signal.Signals(signum).name}: finishing the current update, '
            f'then writing a recovery checkpoint and exiting cleanly')

    @property
    def requested(self) -> bool:
        return self._flag.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def request(self):
        """Programmatic trigger (tests / fault injection without a real signal)."""
        self._signum = signal.SIGTERM
        self._flag.set()

    def should_stop(self, update_idx: int) -> bool:
        """Poll between updates. Single-process: the local flag. Multi-host:
        cross-host ANY-consensus at a fixed update cadence so every host stops
        at the same step. The consensus is NAMED, so it rides the coordination
        service's KV store when available: a dead peer resolves to True (host
        loss ⇒ the pod stops and recovers) instead of deadlocking the way a
        device collective would."""
        import jax
        if jax.process_count() <= 1:
            return self.requested
        if (update_idx + 1) % self.consensus_every != 0:
            return False
        from ..parallel import all_hosts_flag
        return all_hosts_flag(self.requested, mode='any', name='preemption-consensus')
