"""Fault-tolerance subsystem: durable checkpoints, non-finite step sentinel,
preemption-aware shutdown, reader retry policy, and a fault-injection harness.

See README "Fault tolerance" for the knobs:
  TIMM_TPU_NONFINITE_TOLERANCE / _GUARD / _CHECK_EVERY, TIMM_TPU_POISON_BUDGET,
  TIMM_TPU_PREEMPTION_POLL, TIMM_TPU_FAULT_INJECT, train.py --resume auto /
  --fault-inject / --nonfinite-rollback.
"""
from .durable import (
    SCHEMA_VERSION, CorruptCheckpointError, atomic_copy, atomic_write_bytes,
    atomic_write_json, atomic_write_npz, checkpoint_progress_key, copy_sharded_checkpoint,
    find_checkpoints, is_sharded_manifest, load_verified, load_with_fallback, manifest_path,
    read_checkpoint_scalar, read_manifest, remove_checkpoint_files, resolve_auto_resume,
    set_durable_write_listener, shard_file_path, snapshot_process_shards, snapshot_to_host,
    sweep_orphan_shards, verify_checkpoint, write_sharded_checkpoint,
)
from .elastic import (
    AsyncCheckpointWriter, ElasticPlan, convert_loader_position,
    plan_elastic_resume, rescale_for_devices,
)
from .faultinject import FaultInjector, fault_selftest, get_fault_injector, set_fault_injector
from .hoststate import RESUME_PREFIX, capture_host_rng, restore_host_rng
from .multihost import cluster_env, free_port, run_kill_drill
from .preemption import GracefulShutdown, TrainingPreempted
from .retry import (
    DEFAULT_POISON_BUDGET, SkipBudget, TooManyBadSamples, backoff_delays, retry_io,
)
from .sentinel import (
    NonFiniteError, NonFiniteSentinel, guard_enabled, new_sentinel_state,
    tree_all_finite, update_sentinel_state,
)
