"""Durable (atomic + checksummed) checkpoint I/O.

Every checkpoint write goes tmp-file → flush → fsync → `os.replace`, then a
sidecar manifest (`<name>.manifest.json`) records a SHA-256 per array plus
schema version and step metadata. The manifest is the COMMIT RECORD: it is
written after the data file, so a crash mid-write leaves either the previous
(file, manifest) pair intact or a data file without a matching manifest —
both detectable. Verification recomputes the per-array hashes; loading falls
back to the newest *valid* checkpoint in the directory when the requested one
is truncated or corrupt (the Orbax-style durability contract, owned here
because TPU-pod runs on preemptible slices cannot lean on torch.save +
host-side retries the way the reference does).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = [
    'SCHEMA_VERSION', 'CorruptCheckpointError',
    'atomic_write_bytes', 'atomic_write_json', 'atomic_write_npz', 'atomic_copy',
    'manifest_path', 'read_manifest', 'verify_checkpoint', 'load_verified',
    'find_checkpoints', 'load_with_fallback', 'resolve_auto_resume',
    'checkpoint_progress_key', 'set_durable_write_listener', 'snapshot_to_host',
]

SCHEMA_VERSION = 1


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (truncated zip, manifest
    hash mismatch, missing arrays, or unreadable file)."""


def _fsync_dir(path: str):
    """fsync the containing directory so the rename itself is durable."""
    try:
        fd = os.open(path or '.', os.O_RDONLY)
    except OSError:
        return  # e.g. platforms without O_RDONLY dirs; rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


_write_listener = None


def set_durable_write_listener(fn):
    """Test instrumentation: `fn(path, thread)` runs at the start of every
    durable write (atomic_write_bytes / atomic_write_npz) with the thread the
    write executes on — how tier-1 asserts that async checkpointing keeps
    fsync off the step-loop thread. Returns the previous listener; pass None
    to clear."""
    global _write_listener
    prev, _write_listener = _write_listener, fn
    return prev


def _notify_write(path: str):
    if _write_listener is not None:
        _write_listener(path, threading.current_thread())


def atomic_write_bytes(path: str, data: bytes, tmp_dir: Optional[str] = None):
    """tmp → fsync → os.replace; the final path is never partially written.

    `tmp_dir` (must be on the destination's filesystem — e.g. a staging
    subdirectory) confines the temp file so a writer killed mid-flight leaves
    its litter where a startup sweep can reap it wholesale."""
    _notify_write(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix='.' + os.path.basename(path) + '.', suffix='.tmp',
                               dir=tmp_dir or d)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, tmp_dir: Optional[str] = None):
    atomic_write_bytes(path, json.dumps(obj, indent=1, default=str).encode(), tmp_dir=tmp_dir)


def manifest_path(path: str) -> str:
    base, _ = os.path.splitext(path)
    return base + '.manifest.json'


def _gather_to_host(v) -> np.ndarray:
    """Gather a (possibly fsdp-sharded) array to one full host copy before it
    is hashed/written, so the npz bytes and the SHA-256 sidecar are identical
    for EVERY mesh shape: save-on-8-device and save-on-1-device produce
    byte-equal checkpoints. Single-process sharded arrays gather via
    np.asarray; multi-host (not fully addressable) arrays ride a process
    allgather first."""
    if hasattr(v, 'is_fully_addressable') and not v.is_fully_addressable:
        from jax.experimental import multihost_utils  # deferred: numpy-only module otherwise
        v = multihost_utils.process_allgather(v)
    return np.asarray(v)


def snapshot_to_host(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Device→host snapshot of a checkpoint state dict — the cheap, bounded
    half of an async write, run on the step thread at submit time.

    Mandatory before handing state to a background writer: the next train
    step DELETES donated input buffers, so live jax.Arrays must be gathered
    now. The result is plain numpy, making atomic_write_npz's own gather a
    no-op — which is why async npz bytes and SHA-256 manifests stay
    byte-identical to a synchronous save of the same state."""
    return {k: _gather_to_host(v) for k, v in arrays.items()}


def _array_digest(arr: np.ndarray) -> str:
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def atomic_write_npz(path: str, arrays: Dict[str, np.ndarray], meta: Optional[dict] = None,
                     tmp_dir: Optional[str] = None) -> str:
    """Durably write `arrays` as an .npz at `path` with a sidecar manifest.

    Write order: data file committed first (tmp+fsync+replace), manifest
    second — the manifest's presence with matching hashes is what marks the
    checkpoint complete. Returns the manifest path. `tmp_dir` stages the temp
    file as in atomic_write_bytes.
    """
    from .faultinject import get_fault_injector

    _notify_write(path)
    arrays = {k: _gather_to_host(v) for k, v in arrays.items()}
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix='.' + os.path.basename(path) + '.', suffix='.tmp',
                               dir=tmp_dir or d)
    try:
        with os.fdopen(fd, 'wb') as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        injector = get_fault_injector()
        if injector is not None and injector.take('truncate_ckpt'):
            # simulate a torn write: chop the committed bytes in half so the
            # verification/fallback path is exercised end-to-end
            size = os.path.getsize(tmp)
            with open(tmp, 'r+b') as f:
                f.truncate(max(size // 2, 1))
            _logger.warning(f'[fault-inject] truncated checkpoint write: {path}')
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

    manifest = {
        'schema_version': SCHEMA_VERSION,
        'file': os.path.basename(path),
        'arrays': {k: {'sha256': _array_digest(v), 'shape': list(v.shape), 'dtype': str(v.dtype)}
                   for k, v in arrays.items()},
        'meta': dict(meta or {}),
    }
    mpath = manifest_path(path)
    atomic_write_json(mpath, manifest, tmp_dir=tmp_dir)
    return mpath


def atomic_copy(src: str, dst: str, with_sidecars: bool = True):
    """Copy a committed checkpoint (and its manifest / args sidecars) so the
    destination also appears atomically."""
    with open(src, 'rb') as f:
        atomic_write_bytes(dst, f.read())
    if not with_sidecars:
        return
    for side_src, side_dst in (
            (manifest_path(src), manifest_path(dst)),
            (os.path.splitext(src)[0] + '.json', os.path.splitext(dst)[0] + '.json'),
    ):
        if os.path.exists(side_src):
            with open(side_src, 'rb') as f:
                atomic_write_bytes(side_dst, f.read())


def read_manifest(path: str) -> Optional[dict]:
    mpath = manifest_path(path)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        _logger.warning(f'Unreadable checkpoint manifest {mpath}: {e}')
        return None


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Return (ok, reason). With a manifest: schema + per-array SHA-256 check.
    Without one (legacy/foreign checkpoint): accept iff the npz itself loads."""
    if not os.path.exists(path):
        return False, 'missing'
    manifest = read_manifest(path)
    try:
        with np.load(path, allow_pickle=False) as data:
            if manifest is None:
                _ = data.files  # zip directory parse is the only check we have
                return True, 'no-manifest (legacy checkpoint; hashes not verified)'
            if int(manifest.get('schema_version', 0)) > SCHEMA_VERSION:
                return False, f'schema_version {manifest.get("schema_version")} > {SCHEMA_VERSION}'
            declared = manifest.get('arrays', {})
            missing = [k for k in declared if k not in data.files]
            if missing:
                return False, f'arrays missing from file: {missing[:4]}'
            for k, info in declared.items():
                if _array_digest(data[k]) != info['sha256']:
                    return False, f'sha256 mismatch for array {k!r}'
    except Exception as e:
        # a torn write surfaces as BadZipFile / zlib.error / EOFError /
        # OSError depending on where the bytes were cut — any read failure
        # means the checkpoint is not loadable, which is what we're deciding
        return False, f'unreadable: {e!r}'
    return True, 'ok'


def load_verified(path: str) -> Tuple[Dict[str, np.ndarray], dict]:
    """Load a checkpoint after integrity verification; raises
    CorruptCheckpointError with the reason on failure. Returns (state, meta)."""
    ok, reason = verify_checkpoint(path)
    if not ok:
        raise CorruptCheckpointError(f'{path}: {reason}')
    with np.load(path, allow_pickle=False) as data:
        state = {k: data[k] for k in data.files}
    manifest = read_manifest(path)
    return state, (manifest or {}).get('meta', {})


_RECOVERY_RE = re.compile(r'recovery-(\d+)-(\d+)\.npz$')
_CHECKPOINT_RE = re.compile(r'checkpoint-(\d+)\.npz$')


def checkpoint_progress_key(path: str) -> Tuple[float, int, float]:
    """Training-progress ordering key for a checkpoint file (higher = newer).

    A completed-epoch checkpoint (last/checkpoint-E/model_best, epoch E) ranks
    as (E+1, 0); a mid-epoch recovery-E-B ranks as (E, B+1) — so end-of-epoch
    state supersedes any recovery from the same epoch, and recovery-1-1000
    correctly beats recovery-1-999 (ints, not lexicographic). mtime breaks
    ties."""
    name = os.path.basename(path)
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0.0
    m = _RECOVERY_RE.search(name)
    if m:
        return float(m.group(1)), int(m.group(2)) + 1, mtime
    m = _CHECKPOINT_RE.search(name)
    if m:
        return float(m.group(1)) + 1.0, 0, mtime
    # last.npz / model_best.npz / foreign name: epoch from manifest meta or
    # the stored epoch array
    manifest = read_manifest(path)
    epoch = None
    if manifest is not None:
        epoch = manifest.get('meta', {}).get('epoch')
    if epoch is None:
        try:
            with np.load(path, allow_pickle=False) as data:
                if 'epoch' in data.files:
                    epoch = int(data['epoch'])
        except Exception:
            epoch = None  # unreadable file ranks last; verification rejects it
    return (float(epoch) + 1.0 if epoch is not None else -1.0), 0, mtime


def find_checkpoints(directory: str) -> List[str]:
    """All checkpoint files in `directory`, newest-first by training progress."""
    if not directory or not os.path.isdir(directory):
        return []
    names = [n for n in os.listdir(directory)
             if n.endswith('.npz') and not n.startswith('.') and n != 'tmp.npz']
    paths = [os.path.join(directory, n) for n in names]
    return sorted(paths, key=checkpoint_progress_key, reverse=True)


def load_with_fallback(
        path: str,
        search_dir: Optional[str] = None,
) -> Tuple[Dict[str, np.ndarray], dict, str]:
    """Load `path`, falling back to the newest valid checkpoint in
    `search_dir` (default: path's directory) when it is corrupt. Returns
    (state, meta, used_path); raises CorruptCheckpointError only when no
    valid candidate exists."""
    search_dir = search_dir or os.path.dirname(os.path.abspath(path))
    tried = []
    candidates = [path] + [c for c in find_checkpoints(search_dir)
                           if os.path.abspath(c) != os.path.abspath(path)]
    for cand in candidates:
        ok, reason = verify_checkpoint(cand)
        if ok:
            if tried:
                _logger.warning(
                    f'Checkpoint fallback: {", ".join(tried)} — using {cand} instead')
            state, meta = load_verified(cand)
            return state, meta, cand
        tried.append(f'{cand} ({reason})')
        _logger.warning(f'Checkpoint failed verification: {cand}: {reason}')
    raise CorruptCheckpointError(
        f'No valid checkpoint found (tried: {"; ".join(tried) or path})')


def resolve_auto_resume(directory: str) -> Optional[str]:
    """`--resume auto`: newest valid checkpoint in `directory`, or None."""
    for cand in find_checkpoints(directory):
        ok, reason = verify_checkpoint(cand)
        if ok:
            return cand
        _logger.warning(f'auto-resume skipping invalid checkpoint {cand}: {reason}')
    return None
