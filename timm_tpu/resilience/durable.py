"""Durable (atomic + checksummed) checkpoint I/O.

Every checkpoint write goes tmp-file → flush → fsync → `os.replace`, then a
sidecar manifest (`<name>.manifest.json`) records a SHA-256 per array plus
schema version and step metadata. The manifest is the COMMIT RECORD: it is
written after the data file, so a crash mid-write leaves either the previous
(file, manifest) pair intact or a data file without a matching manifest —
both detectable. Verification recomputes the per-array hashes; loading falls
back to the newest *valid* checkpoint in the directory when the requested one
is truncated or corrupt (the Orbax-style durability contract, owned here
because TPU-pod runs on preemptible slices cannot lean on torch.save +
host-side retries the way the reference does).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = [
    'SCHEMA_VERSION', 'CorruptCheckpointError',
    'atomic_write_bytes', 'atomic_write_json', 'atomic_write_npz', 'atomic_copy',
    'manifest_path', 'read_manifest', 'verify_checkpoint', 'load_verified',
    'find_checkpoints', 'load_with_fallback', 'resolve_auto_resume',
    'checkpoint_progress_key', 'set_durable_write_listener', 'snapshot_to_host',
    'is_sharded_manifest', 'shard_file_path', 'snapshot_process_shards',
    'write_sharded_checkpoint', 'copy_sharded_checkpoint',
    'remove_checkpoint_files', 'sweep_orphan_shards', 'read_checkpoint_scalar',
]

SCHEMA_VERSION = 1


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (truncated zip, manifest
    hash mismatch, missing arrays, or unreadable file)."""


def _fsync_dir(path: str):
    """fsync the containing directory so the rename itself is durable."""
    try:
        fd = os.open(path or '.', os.O_RDONLY)
    except OSError:
        return  # e.g. platforms without O_RDONLY dirs; rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


_write_listener = None


def set_durable_write_listener(fn):
    """Test instrumentation: `fn(path, thread)` runs at the start of every
    durable write (atomic_write_bytes / atomic_write_npz) with the thread the
    write executes on — how tier-1 asserts that async checkpointing keeps
    fsync off the step-loop thread. Returns the previous listener; pass None
    to clear."""
    global _write_listener
    prev, _write_listener = _write_listener, fn
    return prev


def _notify_write(path: str):
    if _write_listener is not None:
        _write_listener(path, threading.current_thread())


def atomic_write_bytes(path: str, data: bytes, tmp_dir: Optional[str] = None):
    """tmp → fsync → os.replace; the final path is never partially written.

    `tmp_dir` (must be on the destination's filesystem — e.g. a staging
    subdirectory) confines the temp file so a writer killed mid-flight leaves
    its litter where a startup sweep can reap it wholesale."""
    _notify_write(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix='.' + os.path.basename(path) + '.', suffix='.tmp',
                               dir=tmp_dir or d)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, tmp_dir: Optional[str] = None):
    atomic_write_bytes(path, json.dumps(obj, indent=1, default=str).encode(), tmp_dir=tmp_dir)


def manifest_path(path: str) -> str:
    base, _ = os.path.splitext(path)
    return base + '.manifest.json'


def _gather_to_host(v) -> np.ndarray:
    """Gather a (possibly fsdp-sharded) array to one full host copy before it
    is hashed/written, so the npz bytes and the SHA-256 sidecar are identical
    for EVERY mesh shape: save-on-8-device and save-on-1-device produce
    byte-equal checkpoints. Single-process sharded arrays gather via
    np.asarray; multi-host (not fully addressable) arrays ride a process
    allgather first."""
    if hasattr(v, 'is_fully_addressable') and not v.is_fully_addressable:
        from jax.experimental import multihost_utils  # deferred: numpy-only module otherwise
        v = multihost_utils.process_allgather(v)
    return np.asarray(v)


def snapshot_to_host(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Device→host snapshot of a checkpoint state dict — the cheap, bounded
    half of an async write, run on the step thread at submit time.

    Mandatory before handing state to a background writer: the next train
    step DELETES donated input buffers, so live jax.Arrays must be gathered
    now. The result is plain numpy, making atomic_write_npz's own gather a
    no-op — which is why async npz bytes and SHA-256 manifests stay
    byte-identical to a synchronous save of the same state."""
    return {k: _gather_to_host(v) for k, v in arrays.items()}


def _array_digest(arr: np.ndarray) -> str:
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def atomic_write_npz(path: str, arrays: Dict[str, np.ndarray], meta: Optional[dict] = None,
                     tmp_dir: Optional[str] = None) -> str:
    """Durably write `arrays` as an .npz at `path` with a sidecar manifest.

    Write order: data file committed first (tmp+fsync+replace), manifest
    second — the manifest's presence with matching hashes is what marks the
    checkpoint complete. Returns the manifest path. `tmp_dir` stages the temp
    file as in atomic_write_bytes.
    """
    from .faultinject import get_fault_injector

    _notify_write(path)
    arrays = {k: _gather_to_host(v) for k, v in arrays.items()}
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix='.' + os.path.basename(path) + '.', suffix='.tmp',
                               dir=tmp_dir or d)
    try:
        with os.fdopen(fd, 'wb') as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        injector = get_fault_injector()
        if injector is not None and injector.take('truncate_ckpt'):
            # simulate a torn write: chop the committed bytes in half so the
            # verification/fallback path is exercised end-to-end
            size = os.path.getsize(tmp)
            with open(tmp, 'r+b') as f:
                f.truncate(max(size // 2, 1))
            _logger.warning(f'[fault-inject] truncated checkpoint write: {path}')
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

    manifest = {
        'schema_version': SCHEMA_VERSION,
        'file': os.path.basename(path),
        'arrays': {k: {'sha256': _array_digest(v), 'shape': list(v.shape), 'dtype': str(v.dtype)}
                   for k, v in arrays.items()},
        'meta': dict(meta or {}),
    }
    mpath = manifest_path(path)
    atomic_write_json(mpath, manifest, tmp_dir=tmp_dir)
    return mpath


def atomic_copy(src: str, dst: str, with_sidecars: bool = True):
    """Copy a committed checkpoint (and its manifest / args sidecars) so the
    destination also appears atomically."""
    with open(src, 'rb') as f:
        atomic_write_bytes(dst, f.read())
    if not with_sidecars:
        return
    for side_src, side_dst in (
            (manifest_path(src), manifest_path(dst)),
            (os.path.splitext(src)[0] + '.json', os.path.splitext(dst)[0] + '.json'),
    ):
        if os.path.exists(side_src):
            with open(side_src, 'rb') as f:
                atomic_write_bytes(side_dst, f.read())


# ---- process-local sharded checkpoints --------------------------------------
#
# Multi-process (pod) saves invert the gather-everything-to-host-0
# process_allgather: each process durably writes ONLY its addressable shards
# (`<name>.shard<p>-of-<P>.npz`, tmp→fsync→rename, per-chunk SHA-256 in the
# shard's own sidecar manifest), then process 0 commits ONE global manifest
# (`<name>.manifest.json`, format='sharded': shard list, global array specs,
# meta) — and only after an all_hosts_flag(mode='all') barrier confirms every
# shard landed. There is no `<name>.npz` data file in sharded format; the
# global manifest IS the checkpoint's commit record, so a crash (or host
# loss) between shard write and manifest commit leaves the previous
# checkpoint as the newest valid one. Shard files are themselves ordinary
# npz+manifest pairs, so the existing verification machinery validates each
# shard byte-for-byte.

_SHARD_RE = re.compile(r'\.shard(\d+)-of-(\d+)\.npz$')


def shard_file_path(path: str, process_index: int, process_count: int) -> str:
    base, _ = os.path.splitext(path)
    return f'{base}.shard{process_index}-of-{process_count}.npz'


def is_sharded_manifest(manifest: Optional[dict]) -> bool:
    return bool(manifest) and manifest.get('format') == 'sharded'


def snapshot_process_shards(arrays: Dict, process_index: Optional[int] = None,
                            process_count: Optional[int] = None) -> Dict:
    """Device→host snapshot of THIS process's unique chunks of a checkpoint
    state dict — the sharded twin of `snapshot_to_host`, run on the step
    thread at submit time (the next train step deletes donated buffers).

    Chunk selection: for every jax.Array, each addressable shard with
    replica_id == 0 contributes (its global index slices, its host copy) —
    the union across processes covers each array exactly once with no
    cross-host communication. Host-side numpy values (`_resume.*` extras,
    epoch/metric scalars) are recorded by process 0 only."""
    import jax  # deferred: numpy-only module otherwise

    p = jax.process_index() if process_index is None else int(process_index)
    n = jax.process_count() if process_count is None else int(process_count)
    chunks = []  # (key, start, stop, host chunk)
    specs = {}
    for k, v in arrays.items():
        if hasattr(v, 'addressable_shards') and hasattr(v, 'sharding'):
            specs[k] = {'shape': list(v.shape), 'dtype': str(v.dtype)}
            for sh in v.addressable_shards:
                if sh.replica_id != 0:
                    continue
                start = [0 if s.start is None else int(s.start) for s in sh.index]
                stop = [v.shape[i] if s.stop is None else int(s.stop)
                        for i, s in enumerate(sh.index)]
                # np.array copies: np.asarray would be a zero-copy VIEW of
                # the device buffer, and the next train step donates it —
                # an async write would then hash/serialize mutating bytes
                chunks.append((k, start, stop, np.array(sh.data)))
        else:
            arr = np.array(v)
            specs[k] = {'shape': list(arr.shape), 'dtype': str(arr.dtype)}
            if p == 0:
                chunks.append((k, [0] * arr.ndim, list(arr.shape), arr))
    return {'process_index': p, 'process_count': n,
            'chunks': chunks, 'specs': specs}


def _write_shard_file(spath: str, snapshot: Dict, parent: str,
                      tmp_dir: Optional[str] = None) -> str:
    """Durably write one process's shard npz + its sidecar manifest. The shard
    manifest uses the ordinary npz-manifest schema (per-chunk SHA-256 under
    'arrays'), plus a 'shard' section mapping chunk keys back to (array key,
    start, stop) for reassembly."""
    from .faultinject import get_fault_injector

    _notify_write(spath)
    data, chunk_meta = {}, {}
    for j, (key, start, stop, arr) in enumerate(snapshot['chunks']):
        ck = f'{key}::{j}'
        data[ck] = arr
        chunk_meta[ck] = {'key': key, 'start': list(start), 'stop': list(stop)}
    d = os.path.dirname(os.path.abspath(spath))
    fd, tmp = tempfile.mkstemp(prefix='.' + os.path.basename(spath) + '.', suffix='.tmp',
                               dir=tmp_dir or d)
    try:
        with os.fdopen(fd, 'wb') as f:
            np.savez(f, **data)
            f.flush()
            os.fsync(f.fileno())
        injector = get_fault_injector()
        if injector is not None and injector.take('truncate_ckpt'):
            size = os.path.getsize(tmp)
            with open(tmp, 'r+b') as f:
                f.truncate(max(size // 2, 1))
            _logger.warning(f'[fault-inject] truncated shard write: {spath}')
        os.replace(tmp, spath)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    manifest = {
        'schema_version': SCHEMA_VERSION,
        'file': os.path.basename(spath),
        'arrays': {ck: {'sha256': _array_digest(v), 'shape': list(v.shape),
                        'dtype': str(v.dtype)}
                   for ck, v in data.items()},
        'shard': {'process': snapshot['process_index'],
                  'count': snapshot['process_count'],
                  'parent': os.path.basename(parent),
                  'chunks': chunk_meta},
        'meta': {},
    }
    mpath = manifest_path(spath)
    atomic_write_json(mpath, manifest, tmp_dir=tmp_dir)
    return mpath


def write_sharded_checkpoint(path: str, snapshot: Dict, meta: Optional[dict] = None,
                             tmp_dir: Optional[str] = None,
                             barrier=None) -> Optional[str]:
    """Write this process's shard of the checkpoint at `path` and, on process
    0, commit the global manifest — but ONLY after an all-hosts 'all' barrier
    confirms every shard landed. Returns the global manifest path on the
    committing process, '' on other processes, and None when the barrier
    failed (a peer died or its write failed): then NO manifest is committed
    and the previous checkpoint remains the newest valid one."""
    from ..parallel.distributed import all_hosts_flag

    if barrier is None:
        barrier = all_hosts_flag
    p, n = snapshot['process_index'], snapshot['process_count']
    spath = shard_file_path(path, p, n)
    ok, err = True, None
    try:
        _write_shard_file(spath, snapshot, parent=path, tmp_dir=tmp_dir)
    except BaseException as e:  # still vote False so peers do not commit
        ok, err = False, e
    landed = barrier(ok, mode='all', name=f'ckpt-commit:{os.path.basename(path)}')
    if err is not None:
        raise err
    if not landed:
        _logger.warning(
            f'[durable] shard barrier failed for {path}: manifest NOT committed '
            f'(previous checkpoint remains newest valid)')
        return None
    if p != 0:
        return ''
    manifest = {
        'schema_version': SCHEMA_VERSION,
        'format': 'sharded',
        'file': None,
        'shards': [os.path.basename(shard_file_path(path, i, n)) for i in range(n)],
        'process_count': n,
        'arrays': dict(snapshot['specs']),
        'meta': dict(meta or {}),
    }
    mpath = manifest_path(path)
    atomic_write_json(mpath, manifest, tmp_dir=tmp_dir)
    return mpath


def copy_sharded_checkpoint(src: str, dst: str, process_index: int,
                            process_count: int, barrier=None) -> Optional[str]:
    """Sharded twin of `atomic_copy`: each process copies ITS shard (data +
    sidecar, with file/parent fields renamed), then process 0 commits the
    destination's global manifest after the all-hosts barrier — same ordering
    contract as `write_sharded_checkpoint`."""
    from ..parallel.distributed import all_hosts_flag

    if barrier is None:
        barrier = all_hosts_flag
    s_src = shard_file_path(src, process_index, process_count)
    s_dst = shard_file_path(dst, process_index, process_count)
    ok, err = True, None
    try:
        with open(s_src, 'rb') as f:
            atomic_write_bytes(s_dst, f.read())
        sm = read_manifest(s_src) or {}
        sm['file'] = os.path.basename(s_dst)
        sm.setdefault('shard', {})['parent'] = os.path.basename(dst)
        atomic_write_json(manifest_path(s_dst), sm)
    except BaseException as e:
        ok, err = False, e
    landed = barrier(ok, mode='all', name=f'ckpt-copy:{os.path.basename(dst)}')
    if err is not None:
        raise err
    if not landed:
        _logger.warning(f'[durable] shard-copy barrier failed for {dst}: '
                        f'manifest NOT committed')
        return None
    if process_index != 0:
        return ''
    gm = read_manifest(src)
    if not is_sharded_manifest(gm):
        raise CorruptCheckpointError(f'{src}: source global manifest missing/not sharded')
    gm = dict(gm)
    gm['shards'] = [os.path.basename(shard_file_path(dst, i, process_count))
                    for i in range(process_count)]
    mpath = manifest_path(dst)
    atomic_write_json(mpath, gm)
    side_src = os.path.splitext(src)[0] + '.json'
    if os.path.exists(side_src):
        with open(side_src, 'rb') as f:
            atomic_write_bytes(os.path.splitext(dst)[0] + '.json', f.read())
    return mpath


def remove_checkpoint_files(path: str, process_index: Optional[int] = None):
    """Remove a checkpoint and every file belonging to it. For sharded
    checkpoints a non-primary process (process_index > 0) removes only its
    own shard; process 0 (or single-process callers) removes the manifest,
    sidecars, and ALL listed shards. Missing files are ignored."""
    manifest = read_manifest(path)
    targets: List[str] = []
    if is_sharded_manifest(manifest):
        d = os.path.dirname(os.path.abspath(path))
        shards = [os.path.join(d, n) for n in manifest.get('shards', [])]
        if process_index is not None and process_index > 0:
            n = int(manifest.get('process_count', len(shards)) or len(shards))
            own = shard_file_path(path, process_index, n)
            targets = [own, manifest_path(own)]
        else:
            targets = [path, manifest_path(path), os.path.splitext(path)[0] + '.json']
            for sp in shards:
                targets += [sp, manifest_path(sp)]
    else:
        if process_index is not None and process_index > 0:
            return  # plain checkpoints are single-writer: nothing local to remove
        targets = [path, manifest_path(path), os.path.splitext(path)[0] + '.json']
    for t in targets:
        try:
            os.unlink(t)
        except OSError:
            pass


def sweep_orphan_shards(directory: str) -> List[str]:
    """Startup sweep: shard files whose parent checkpoint never committed its
    global manifest (host died between shard write and commit) are litter —
    remove them so they can never shadow a valid checkpoint. Returns the
    removed shard paths."""
    removed: List[str] = []
    if not directory or not os.path.isdir(directory):
        return removed
    for n in sorted(os.listdir(directory)):
        m = _SHARD_RE.search(n)
        if not m or not n.endswith('.npz'):
            continue
        parent = os.path.join(directory, n[:m.start()] + '.npz')
        ok, _ = verify_checkpoint(parent)
        if ok:
            continue
        sp = os.path.join(directory, n)
        for t in (sp, manifest_path(sp)):
            try:
                os.unlink(t)
            except OSError:
                pass
        removed.append(sp)
        _logger.warning(f'Startup sweep: removed orphan shard {sp} '
                        f'(parent checkpoint never committed)')
    return removed


def read_checkpoint_scalar(path: str, key: str):
    """Read one host scalar (e.g. '_resume.global_batch') from a checkpoint
    without loading the full state: plain npz → direct read; sharded → the
    chunk lives in process 0's shard (host values are recorded by process 0).
    Returns None when absent/unreadable."""
    try:
        manifest = read_manifest(path)
        if is_sharded_manifest(manifest):
            n = int(manifest.get('process_count', 1) or 1)
            spath = shard_file_path(path, 0, n)
            with np.load(spath, allow_pickle=False) as data:
                for ck in data.files:
                    if ck == key or ck.startswith(key + '::'):
                        return np.asarray(data[ck])
            return None
        with np.load(path, allow_pickle=False) as data:
            if key in data.files:
                return np.asarray(data[key])
    except Exception:
        return None
    return None


def read_manifest(path: str) -> Optional[dict]:
    mpath = manifest_path(path)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        _logger.warning(f'Unreadable checkpoint manifest {mpath}: {e}')
        return None


def _verify_sharded(path: str, manifest: dict) -> Tuple[bool, str]:
    """Sharded verification: every listed shard must exist and pass the
    ordinary npz+manifest hash check, and the union of chunk slices must
    cover every declared array exactly (element-count check; chunks are
    disjoint by construction — replica_id-0 dedupe)."""
    if int(manifest.get('schema_version', 0)) > SCHEMA_VERSION:
        return False, f'schema_version {manifest.get("schema_version")} > {SCHEMA_VERSION}'
    d = os.path.dirname(os.path.abspath(path))
    declared = manifest.get('arrays', {})
    covered = {k: 0 for k in declared}
    for n in manifest.get('shards', []):
        spath = os.path.join(d, n)
        ok, reason = verify_checkpoint(spath)
        if not ok:
            return False, f'shard {n}: {reason}'
        sm = read_manifest(spath) or {}
        for ck, info in sm.get('shard', {}).get('chunks', {}).items():
            k = info['key']
            if k not in covered:
                return False, f'shard {n} declares unknown array {k!r}'
            covered[k] += int(np.prod([b - a for a, b in
                                       zip(info['start'], info['stop'])], dtype=np.int64))
    for k, info in declared.items():
        want = int(np.prod(info['shape'], dtype=np.int64))
        if covered[k] != want:
            return False, (f'array {k!r} coverage {covered[k]}/{want} elements '
                           f'across shards')
    return True, 'ok'


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Return (ok, reason). With a manifest: schema + per-array SHA-256 check
    (for sharded checkpoints: every shard verifies + full coverage). Without
    one (legacy/foreign checkpoint): accept iff the npz itself loads."""
    manifest = read_manifest(path)
    if is_sharded_manifest(manifest):
        return _verify_sharded(path, manifest)
    if not os.path.exists(path):
        return False, 'missing'
    try:
        with np.load(path, allow_pickle=False) as data:
            if manifest is None:
                _ = data.files  # zip directory parse is the only check we have
                return True, 'no-manifest (legacy checkpoint; hashes not verified)'
            if int(manifest.get('schema_version', 0)) > SCHEMA_VERSION:
                return False, f'schema_version {manifest.get("schema_version")} > {SCHEMA_VERSION}'
            declared = manifest.get('arrays', {})
            missing = [k for k in declared if k not in data.files]
            if missing:
                return False, f'arrays missing from file: {missing[:4]}'
            for k, info in declared.items():
                if _array_digest(data[k]) != info['sha256']:
                    return False, f'sha256 mismatch for array {k!r}'
    except Exception as e:
        # a torn write surfaces as BadZipFile / zlib.error / EOFError /
        # OSError depending on where the bytes were cut — any read failure
        # means the checkpoint is not loadable, which is what we're deciding
        return False, f'unreadable: {e!r}'
    return True, 'ok'


def _load_sharded(path: str, manifest: dict) -> Dict[str, np.ndarray]:
    """Reassemble full host arrays from the shard files (shared filesystem:
    every process reads all shards). The caller re-places the result under
    the LIVE mesh's shardings — which is how a sharded save composes with
    elastic re-placement onto a different topology."""
    d = os.path.dirname(os.path.abspath(path))
    state = {k: np.empty(info['shape'], dtype=np.dtype(info['dtype']))
             for k, info in manifest.get('arrays', {}).items()}
    for n in manifest.get('shards', []):
        spath = os.path.join(d, n)
        sm = read_manifest(spath) or {}
        chunk_meta = sm.get('shard', {}).get('chunks', {})
        with np.load(spath, allow_pickle=False) as data:
            for ck, info in chunk_meta.items():
                idx = tuple(slice(a, b) for a, b in zip(info['start'], info['stop']))
                state[info['key']][idx] = data[ck]
    return state


def load_verified(path: str) -> Tuple[Dict[str, np.ndarray], dict]:
    """Load a checkpoint after integrity verification; raises
    CorruptCheckpointError with the reason on failure. Returns (state, meta)."""
    ok, reason = verify_checkpoint(path)
    if not ok:
        raise CorruptCheckpointError(f'{path}: {reason}')
    manifest = read_manifest(path)
    if is_sharded_manifest(manifest):
        return _load_sharded(path, manifest), manifest.get('meta', {})
    with np.load(path, allow_pickle=False) as data:
        state = {k: data[k] for k in data.files}
    return state, (manifest or {}).get('meta', {})


_RECOVERY_RE = re.compile(r'recovery-(\d+)-(\d+)\.npz$')
_CHECKPOINT_RE = re.compile(r'checkpoint-(\d+)\.npz$')


def checkpoint_progress_key(path: str) -> Tuple[float, int, float]:
    """Training-progress ordering key for a checkpoint file (higher = newer).

    A completed-epoch checkpoint (last/checkpoint-E/model_best, epoch E) ranks
    as (E+1, 0); a mid-epoch recovery-E-B ranks as (E, B+1) — so end-of-epoch
    state supersedes any recovery from the same epoch, and recovery-1-1000
    correctly beats recovery-1-999 (ints, not lexicographic). mtime breaks
    ties."""
    name = os.path.basename(path)
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        try:  # sharded checkpoints have no data file: rank by manifest mtime
            mtime = os.path.getmtime(manifest_path(path))
        except OSError:
            mtime = 0.0
    m = _RECOVERY_RE.search(name)
    if m:
        return float(m.group(1)), int(m.group(2)) + 1, mtime
    m = _CHECKPOINT_RE.search(name)
    if m:
        return float(m.group(1)) + 1.0, 0, mtime
    # last.npz / model_best.npz / foreign name: epoch from manifest meta or
    # the stored epoch array
    manifest = read_manifest(path)
    epoch = None
    if manifest is not None:
        epoch = manifest.get('meta', {}).get('epoch')
    if epoch is None:
        try:
            with np.load(path, allow_pickle=False) as data:
                if 'epoch' in data.files:
                    epoch = int(data['epoch'])
        except Exception:
            epoch = None  # unreadable file ranks last; verification rejects it
    return (float(epoch) + 1.0 if epoch is not None else -1.0), 0, mtime


def find_checkpoints(directory: str) -> List[str]:
    """All checkpoint files in `directory`, newest-first by training progress.
    Shard files are components, not checkpoints — excluded; sharded
    checkpoints (global manifest, no data file) are surfaced under their
    logical `.npz` name."""
    if not directory or not os.path.isdir(directory):
        return []
    listing = os.listdir(directory)
    names = [n for n in listing
             if n.endswith('.npz') and not n.startswith('.') and n != 'tmp.npz'
             and not _SHARD_RE.search(n)]
    for n in listing:
        if not n.endswith('.manifest.json') or n.startswith('.'):
            continue
        base = n[:-len('.manifest.json')] + '.npz'
        if base in names or _SHARD_RE.search(base):
            continue
        if is_sharded_manifest(read_manifest(os.path.join(directory, base))):
            names.append(base)
    paths = [os.path.join(directory, n) for n in names]
    return sorted(paths, key=checkpoint_progress_key, reverse=True)


def load_with_fallback(
        path: str,
        search_dir: Optional[str] = None,
) -> Tuple[Dict[str, np.ndarray], dict, str]:
    """Load `path`, falling back to the newest valid checkpoint in
    `search_dir` (default: path's directory) when it is corrupt. Returns
    (state, meta, used_path); raises CorruptCheckpointError only when no
    valid candidate exists."""
    search_dir = search_dir or os.path.dirname(os.path.abspath(path))
    tried = []
    candidates = [path] + [c for c in find_checkpoints(search_dir)
                           if os.path.abspath(c) != os.path.abspath(path)]
    for cand in candidates:
        ok, reason = verify_checkpoint(cand)
        if ok:
            if tried:
                _logger.warning(
                    f'Checkpoint fallback: {", ".join(tried)} — using {cand} instead')
            state, meta = load_verified(cand)
            return state, meta, cand
        tried.append(f'{cand} ({reason})')
        _logger.warning(f'Checkpoint failed verification: {cand}: {reason}')
    raise CorruptCheckpointError(
        f'No valid checkpoint found (tried: {"; ".join(tried) or path})')


def resolve_auto_resume(directory: str) -> Optional[str]:
    """`--resume auto`: newest valid checkpoint in `directory`, or None."""
    for cand in find_checkpoints(directory):
        ok, reason = verify_checkpoint(cand)
        if ok:
            return cand
        _logger.warning(f'auto-resume skipping invalid checkpoint {cand}: {reason}')
    return None
