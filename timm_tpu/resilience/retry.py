"""Reader retry policy: jittered exponential backoff + poison-sample budget.

Transient faults (OSError/IOError from network filesystems, GCS fuse mounts,
flaky tar reads) are retried with jittered exponential backoff. Permanent
per-sample faults (undecodable images, malformed records) are SKIPPED against
a bounded budget — replacing the previous behaviour where a single bad sample
either killed the epoch or was silently swallowed.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

_logger = logging.getLogger(__name__)

__all__ = ['retry_io', 'backoff_delays', 'SkipBudget', 'TooManyBadSamples',
           'DEFAULT_POISON_BUDGET']

# env TIMM_TPU_POISON_BUDGET: max permanently-bad samples tolerated per
# loader pass before the run aborts (a corrupt dataset should fail loudly)
DEFAULT_POISON_BUDGET = 16


class TooManyBadSamples(RuntimeError):
    """The poison-sample skip budget was exhausted; the dataset (not a
    transient fault) is broken and the run must stop."""


def backoff_delays(retries: int, base_delay: float, max_delay: float, jitter: float,
                   rng: Optional[random.Random] = None):
    """Yield `retries` jittered exponential delays: base*2^i * U[1-j, 1+j]."""
    rng = rng or random
    for i in range(retries):
        d = min(base_delay * (2 ** i), max_delay)
        yield max(0.0, d * (1.0 + jitter * (2.0 * rng.random() - 1.0)))


def retry_io(
        fn: Callable,
        retries: int = 3,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        jitter: float = 0.5,
        retry_on: Tuple[Type[BaseException], ...] = (OSError,),
        desc: str = '',
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
):
    """Call `fn()`; on a transient (`retry_on`) exception, back off and retry
    up to `retries` times. The final failure re-raises. Non-transient
    exceptions propagate immediately (those are poison, not flakiness)."""
    delays = backoff_delays(retries, base_delay, max_delay, jitter, rng)
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            try:
                delay = next(delays)
            except StopIteration:
                raise e
            _logger.warning(
                f'Transient I/O error{f" ({desc})" if desc else ""}: {e!r}; '
                f'retry {attempt}/{retries} in {delay:.2f}s')
            sleep(delay)


class SkipBudget:
    """Thread-safe poison-sample budget. `record` logs the skip and raises
    TooManyBadSamples once more than `budget` samples have been dropped."""

    def __init__(self, budget: Optional[int] = None):
        if budget is None:
            import os
            budget = int(os.environ.get('TIMM_TPU_POISON_BUDGET', DEFAULT_POISON_BUDGET))
        self.budget = budget
        self.skipped = 0
        self._lock = threading.Lock()

    def record(self, exc: BaseException, where: str = ''):
        with self._lock:
            self.skipped += 1
            n = self.skipped
        if n > self.budget:
            raise TooManyBadSamples(
                f'{n} bad samples exceed the poison budget of {self.budget} '
                f'(last: {where}: {exc!r}); set TIMM_TPU_POISON_BUDGET to raise it') from exc
        _logger.warning(f'Skipped bad sample {where}: {exc!r} ({n}/{self.budget} budget used)')
