"""Elastic pod-scale training: resume on a changed topology, never block a step on fsync.

Two halves, composed by train.py:

1. Elastic resume (:func:`plan_elastic_resume`): a preempted run restarts with
   whatever devices survived.  The plan rebuilds the mesh axes from the live
   topology (clamping the dead run's ``--fsdp``/``--tp`` to what still divides
   the surviving device count), re-reads the interrupted run's global batch
   from its recovery state, and re-solves
   ``per_device_batch x devices x accum`` so the global batch stays invariant
   — refusing loudly, with the nearest legal global batch, when no integer
   solution exists (the same contract ``shard_batch`` already enforces).

2. Async checkpointing (:class:`AsyncCheckpointWriter`): every durable write
   splits into snapshot-to-host (a cheap device->host gather on the step
   thread; see ``durable.snapshot_to_host``) and the existing
   tmp->fsync->os.replace->SHA-256-manifest pipeline, replayed unchanged on a
   single background writer thread.  At most one write is in flight; a newer
   snapshot supersedes a queued one of the same kind; transient ``OSError``s
   ride the ``retry.retry_io`` backoff; the first persistent failure is
   re-raised on the step thread (fail loudly, never silently drop a
   checkpoint); SIGTERM paths drain the writer before exit so the recovery
   guarantees of the synchronous path are unchanged byte for byte.
"""
import dataclasses
import json
import os
import threading
import time

import numpy as np

from .faultinject import get_fault_injector
from .retry import retry_io

__all__ = [
    'AsyncCheckpointWriter',
    'ElasticPlan',
    'convert_loader_position',
    'plan_elastic_resume',
    'rescale_for_devices',
]


# ---------------------------------------------------------------------------
# batch/accum rescale solver
# ---------------------------------------------------------------------------

def rescale_for_devices(global_batch, n_shards, prefer_batch_size=None,
                        max_accum=64):
    """Solve (loader batch size, grad accum) holding the global batch constant.

    ``global_batch = batch_size * accum`` must survive a device-count change,
    and every loader batch must still shard evenly over the mesh
    (``batch_size % n_shards == 0``, the ``shard_batch`` divisibility rule).
    Returns ``(batch_size, accum)`` with ``accum <= max_accum``, preferring a
    batch size closest to ``prefer_batch_size`` (keeping the loader batch size
    unchanged preserves bit-deterministic data-order on resume).

    Raises ValueError — loudly, with the nearest legal global batch, exactly
    like ``shard_batch`` does — when no integer solution exists.
    """
    g, n = int(global_batch), int(n_shards)
    if g <= 0:
        raise ValueError(f'global_batch must be positive, got {global_batch}')
    if n <= 0:
        raise ValueError(f'n_shards must be positive, got {n_shards}')
    candidates = [b for b in range(n, g + 1, n)
                  if g % b == 0 and g // b <= max_accum]
    if not candidates:
        lo, hi = (g // n) * n, -(-g // n) * n
        nearest = str(hi) if lo <= 0 or lo == hi else f'{lo} or {hi}'
        raise ValueError(
            f'Global batch {g} cannot be held constant on a mesh with '
            f'{n} batch shards: no loader batch size b satisfies '
            f'b % {n} == 0, {g} % b == 0 and {g} // b <= {max_accum} '
            f'(grad-accum cap). Nearest legal global batch: {nearest} '
            f'(multiples of the mesh batch-shard count {n}).')
    prefer = int(prefer_batch_size) if prefer_batch_size else g
    batch_size = min(candidates, key=lambda b: (abs(b - prefer), b))
    return batch_size, g // batch_size


def convert_loader_position(batches_consumed, old_batch_size, new_batch_size):
    """Convert a mid-epoch loader position across a batch-size change.

    Positions are stored as loader batches consumed; the invariant unit is
    samples.  Rounds down (re-seeing a partial batch beats skipping samples).
    Returns ``(new_batches_consumed, exact)`` where ``exact`` is False when
    the sample count did not divide evenly — bit-determinism of the resumed
    data order is only guaranteed when the loader batch size is unchanged.
    """
    old_bs, new_bs = int(old_batch_size), int(new_batch_size)
    if old_bs <= 0 or new_bs <= 0:
        raise ValueError('batch sizes must be positive')
    samples = int(batches_consumed) * old_bs
    return samples // new_bs, samples % new_bs == 0


# ---------------------------------------------------------------------------
# elastic resume planning
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """Everything train.py must override before building mesh/loaders."""
    devices: int
    fsdp: int | None       # clamped to the live topology (None = unrequested)
    tp: int | None
    batch_size: int        # loader batch size (per optimizer micro-step)
    grad_accum: int
    global_batch: int      # the invariant: batch_size * grad_accum
    source: str            # checkpoint the global batch was recovered from
    notes: tuple = ()      # human-readable decisions, for the resume log


def _checkpoint_global_batch(path):
    """Recover (global_batch, batch_size) recorded by the interrupted run.

    Prefers the ``_resume.*`` arrays inside the recovery npz; falls back to
    the args json sidecar written next to every checkpoint (older recovery
    files predate the ``_resume.global_batch`` key).  Returns (None, None)
    when neither source exists.
    """
    from .durable import read_checkpoint_scalar
    gb = read_checkpoint_scalar(path, '_resume.global_batch')
    if gb is not None:
        bs = read_checkpoint_scalar(path, '_resume.batch_size')
        return int(gb), (int(bs) if bs is not None else None)
    sidecar = os.path.splitext(path)[0] + '.json'
    try:
        with open(sidecar, encoding='utf-8') as f:
            args = json.load(f)
        bs = int(args['batch_size'])
        accum = int(args.get('grad_accum_steps', 1) or 1)
        return bs * accum, bs
    except (OSError, ValueError, KeyError, TypeError):
        return None, None


def plan_elastic_resume(devices, batch_size, grad_accum, fsdp=None, tp=None,
                        resume='', num_slices=1, max_accum=64,
                        model='', model_kwargs=None):
    """Plan a restart on the live topology, holding the global batch constant.

    ``devices`` is what is actually there now (``jax.device_count()``), not
    the flag the dead run used.  ``batch_size``/``grad_accum``/``fsdp``/``tp``
    are this restart's requested values (normally the same flags as the dead
    run); ``resume`` is the resolved checkpoint path ('' for a fresh start —
    the plan then only validates/clamps the fresh run's own configuration).

    With ``model`` given, the autotune solver re-solves
    (fsdp, tp, batch_size, accum) for the new topology instead of clamping
    ("first, do no harm": a requested config that is still legal is returned
    unchanged — the 8<->4 drill parity bound is untouched — and only an
    illegal request is re-solved by cost rank).  The largest-divisor clamp
    (`resolve_elastic_axes`) + `rescale_for_devices` path below stays as the
    documented fallback whenever the solver refuses: no model given, no ViT
    dims, no legal point, or any solver error (each fallback is a note).
    """
    from ..parallel.mesh import resolve_elastic_axes

    devices = int(devices)
    notes = []

    global_batch = int(batch_size) * int(grad_accum)
    source = ''
    if resume:
        ckpt_gb, ckpt_bs = _checkpoint_global_batch(resume)
        if ckpt_gb is not None:
            if ckpt_gb != global_batch:
                notes.append(f'global batch {global_batch} -> {ckpt_gb} '
                             f'(held constant from {os.path.basename(resume)})')
            global_batch = ckpt_gb
            if ckpt_bs:
                batch_size = ckpt_bs   # prefer the dead run's loader batch
            source = resume

    if model:
        try:
            from ..autotune import resolve_config_for_topology
            cfg = resolve_config_for_topology(
                devices, global_batch, model=model, model_kwargs=model_kwargs,
                fsdp=fsdp, tp=tp, prefer_batch_size=batch_size,
                num_slices=num_slices, max_accum=max_accum)
        except Exception as e:   # noqa: BLE001 — fallback must note WHY
            cfg = None
            notes.append(f'autotune re-solve unavailable ({type(e).__name__}: '
                         f'{e}) — falling back to the largest-divisor clamp')
        if cfg is not None and cfg.global_batch == global_batch:
            # 1 = axis omitted, same convention resolve_elastic_axes uses
            fsdp_eff = cfg.fsdp if cfg.fsdp > 1 else None
            tp_eff = cfg.tp if cfg.tp > 1 else None
            if (cfg.fsdp, cfg.tp, cfg.batch_size, cfg.grad_accum) != (
                    int(fsdp or 1), int(tp or 1), int(batch_size), int(grad_accum)):
                notes.append(
                    f'autotune re-solved for {devices} devices: '
                    f'fsdp={cfg.fsdp} tp={cfg.tp} batch_size={cfg.batch_size} '
                    f'accum={cfg.grad_accum} (global batch {global_batch} '
                    f'invariant; requested config was illegal here)')
            return ElasticPlan(devices=devices, fsdp=fsdp_eff, tp=tp_eff,
                               batch_size=cfg.batch_size,
                               grad_accum=cfg.grad_accum,
                               global_batch=global_batch, source=source,
                               notes=tuple(notes))

    fsdp_eff, tp_eff = resolve_elastic_axes(devices, fsdp=fsdp, tp=tp,
                                            num_slices=num_slices)
    if fsdp and fsdp_eff != fsdp:
        notes.append(f'fsdp clamped {fsdp} -> {fsdp_eff} for {devices} devices')
    if tp and tp_eff != tp:
        notes.append(f'tp clamped {tp} -> {tp_eff} for {devices} devices')

    new_bs, new_accum = rescale_for_devices(
        global_batch, devices, prefer_batch_size=batch_size,
        max_accum=max_accum)
    if (new_bs, new_accum) != (int(batch_size), int(grad_accum)):
        notes.append(f'rescaled batch_size x accum: {batch_size} x '
                     f'{grad_accum} -> {new_bs} x {new_accum} '
                     f'(global batch {global_batch} invariant)')
    return ElasticPlan(devices=devices, fsdp=fsdp_eff, tp=tp_eff,
                       batch_size=new_bs, grad_accum=new_accum,
                       global_batch=global_batch, source=source,
                       notes=tuple(notes))


# ---------------------------------------------------------------------------
# async durable writer
# ---------------------------------------------------------------------------

class AsyncCheckpointWriter:
    """Single background thread running durable checkpoint writes.

    The step loop snapshots state to host (``durable.snapshot_to_host`` —
    mandatory: donated device buffers are deleted by the next train step) and
    submits a closure that replays the unchanged synchronous write pipeline,
    so the npz bytes and SHA-256 manifests stay byte-identical to a
    synchronous save.

    Queue discipline: one write in flight, one queued slot per ``key``.  A
    newer submit with the same key supersedes the queued (not yet started)
    closure — recovery snapshots overwrite the same file anyway, so only the
    newest matters.  Distinct keys (e.g. 'recovery' vs 'checkpoint') queue
    side by side and run in submission order.

    Failure discipline: transient ``OSError``s retry with backoff
    (``retry.retry_io``); the injected ``io_error%M`` fault fires inside the
    retried closure so the drill exercises this exact path.  The first
    persistent failure is stored and re-raised on the caller thread at the
    next submit()/drain() — an async writer must fail as loudly as the
    synchronous write it replaced.
    """

    THREAD_NAME = 'timm-tpu-ckpt-writer'

    def __init__(self, retries=3, base_delay=0.05, max_delay=2.0):
        self._cond = threading.Condition()
        self._queue = {}          # key -> (label, fn); insertion-ordered
        self._in_flight = None    # label while a write runs
        self._error = None        # first persistent failure, raised on caller
        self._closed = False
        self._retries = int(retries)
        self._base_delay = float(base_delay)
        self._max_delay = float(max_delay)
        self.superseded = 0       # queued closures replaced before running
        self.completed = 0        # closures finished (success or failure)
        self._thread = threading.Thread(
            target=self._run, name=self.THREAD_NAME, daemon=True)
        self._thread.start()

    # -- caller-thread API --------------------------------------------------

    def submit(self, fn, label='checkpoint', key=None):
        """Queue ``fn`` for the writer thread; raises any pending failure."""
        with self._cond:
            self._raise_pending_locked()
            if self._closed:
                raise RuntimeError('AsyncCheckpointWriter is closed')
            key = key if key is not None else label
            if key in self._queue:
                self.superseded += 1
            self._queue.pop(key, None)   # re-insert at the tail
            self._queue[key] = (label, fn)
            self._cond.notify_all()

    def drain(self, timeout=60.0):
        """Block until queued + in-flight writes finish; raise any failure."""
        deadline = None if timeout is None else time.monotonic() + float(timeout)
        with self._cond:
            while self._queue or self._in_flight is not None:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f'async checkpoint writer did not drain within '
                        f'{timeout}s (in flight: {self._in_flight!r}, '
                        f'queued: {list(self._queue)})')
                self._cond.wait(remaining)
            self._raise_pending_locked()

    def close(self, timeout=60.0):
        """Drain, then stop the writer thread (idempotent)."""
        try:
            self.drain(timeout)
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
            self._thread.join(timeout)

    @property
    def pending(self):
        with self._cond:
            return len(self._queue) + (self._in_flight is not None)

    def _raise_pending_locked(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    # -- writer thread ------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue:
                    return    # closed and drained
                key = next(iter(self._queue))
                label, fn = self._queue.pop(key)
                self._in_flight = label
            try:
                retry_io(lambda: self._call_with_faults(fn),
                         retries=self._retries, base_delay=self._base_delay,
                         max_delay=self._max_delay,
                         desc=f'async checkpoint write ({label})')
            except BaseException as e:   # noqa: BLE001 — stored, re-raised on caller
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._in_flight = None
                    self.completed += 1
                    self._cond.notify_all()

    @staticmethod
    def _call_with_faults(fn):
        # io_error%M must exercise the async durable path, not just loader
        # workers: consume a tick inside the retried closure so retry_io's
        # backoff is what rides through the transient failure.
        injector = get_fault_injector()
        if injector is not None and injector.io_error_tick():
            raise OSError('injected transient io_error (async writer)')
        return fn()
