"""timm_tpu — a TPU-native (JAX/XLA/Pallas) image-models framework.

A ground-up re-design of the capabilities of huggingface/pytorch-image-models
for TPU hardware: NHWC layouts, bf16 compute, one jitted train step over a
`jax.sharding.Mesh`, explicit RNG, and Pallas kernels for the hot ops.
"""
__version__ = '0.1.0'

from . import _compat  # noqa: F401  (must precede everything: flax shims)
from .layers import *  # noqa: F401,F403
from .models import (  # noqa: F401
    create_model, is_model, list_models, list_modules, list_pretrained,
    model_entrypoint, register_model,
)
