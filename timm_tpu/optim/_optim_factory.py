"""Optimizer registry + factory (reference: timm/optim/_optim_factory.py:58-1339).

Optimizers are optax gradient transformations wrapped in an `Optimizer` object
that (a) injects the per-step LR computed by the host-side scheduler,
(b) applies timm's param-group semantics as pytree masks (WD exclusion,
layer-decay lr scales), and (c) optionally applies 'cautious' update masking.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Set, Tuple, Union

import jax
import jax.numpy as jnp
import optax
from flax import nnx

from ._param_groups import param_groups_layer_decay, param_groups_weight_decay

_logger = logging.getLogger(__name__)

__all__ = ['OptimInfo', 'OptimizerRegistry', 'Optimizer', 'create_optimizer_v2',
           'optimizer_kwargs', 'list_optimizers', 'get_optimizer_info']


@dataclass
class OptimInfo:
    """Optimizer metadata (reference _optim_factory.py:58)."""
    name: str
    opt_class: Callable  # factory(learning_rate=..., **opt_args) -> GradientTransformation
    description: str = ''
    has_eps: bool = True
    has_momentum: bool = False
    has_betas: bool = False
    num_betas: int = 2
    second_order: bool = False
    defaults: Optional[Dict[str, Any]] = None


def _cautious(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """'Cautious optimizer' wrapper: zero update components whose sign
    disagrees with the raw gradient (reference: caution flag in
    timm/optim/adamw.py etc., arXiv:2411.16085)."""

    def init(params):
        return tx.init(params)

    def update(grads, state, params=None, **extra):
        updates, state = tx.update(grads, state, params, **extra)

        def mask(u, g):
            if u is None or g is None:
                return u
            m = (u * g < 0).astype(u.dtype)  # optax updates are negative-gradient sense
            scale = m.size / jnp.maximum(m.sum(), 1.0)
            return u * m * scale
        updates = jax.tree.map(mask, updates, grads)
        return updates, state

    return optax.GradientTransformationExtraArgs(init, update)


def _lookahead(inner: optax.GradientTransformation, sync_period: int = 6,
               slow_step_size: float = 0.5) -> optax.GradientTransformation:
    """Lookahead (reference: timm/optim/lookahead.py:1-66) as a plain transform:
    slow weights live in optimizer state, so params keep their normal pytree
    shape (unlike optax.lookahead's paired params)."""

    def init(params):
        return (inner.init(params), jax.tree.map(jnp.asarray, params), jnp.zeros((), jnp.int32))

    def update(grads, state, params=None, **extra):
        inner_state, slow, count = state
        updates, inner_state = inner.update(grads, inner_state, params, **extra)
        count = count + 1
        is_sync = (count % sync_period) == 0

        def sync(u, p, s):
            fast_new = p + u
            target = s + slow_step_size * (fast_new - s)
            new_u = jnp.where(is_sync, target - p, u)
            new_s = jnp.where(is_sync, target, s)
            return new_u, new_s

        pairs = jax.tree.map(sync, updates, params, slow)
        updates = jax.tree.map(lambda pr: pr[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        slow = jax.tree.map(lambda pr: pr[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        return updates, (inner_state, slow, count)

    return optax.GradientTransformationExtraArgs(init, update)


def _scale_by_tree(scales) -> optax.GradientTransformation:
    """Per-param lr scaling for layer decay."""

    def init(params):
        return optax.EmptyState()

    def update(updates, state, params=None, **extra):
        updates = jax.tree.map(lambda u, s: u * s, updates, scales)
        return updates, state

    return optax.GradientTransformationExtraArgs(init, update)


class Optimizer:
    """Bundles an optax tx with timm-style group semantics + LR injection.

    Usage inside a jitted step:
        updates, opt_state = optimizer.update(grads, opt_state, params, lr=lr)
        params = optax.apply_updates(params, updates)
    """

    # set by create_optimizer_v2 iff the chain is plain adamw — the exact
    # recipe (b1/b2/eps/wd/mu_dtype/mask) the fused one-pass kernel mirrors;
    # None means TrainingTask(fused_update=True) must refuse this optimizer
    fused_adamw_args: Optional[Dict[str, Any]] = None

    def __init__(
            self,
            tx_factory: Callable[..., optax.GradientTransformation],
            lr: float,
            opt_args: Dict[str, Any],
            lr_scales=None,
            caution: bool = False,
            defaults: Optional[Dict[str, Any]] = None,
    ):
        self.defaults = dict(defaults or {}, lr=lr, **{k: v for k, v in opt_args.items() if isinstance(v, (int, float, str, bool, type(None)))})
        # only learning_rate is a dynamic (per-step injected) hyperparam
        import inspect
        sig_names, has_var_kw = [], False
        try:
            sig = inspect.signature(tx_factory)
            for pname, p in sig.parameters.items():
                if p.kind == inspect.Parameter.VAR_KEYWORD:
                    has_var_kw = True
                elif pname != 'learning_rate':
                    sig_names.append(pname)
        except (TypeError, ValueError):
            pass
        static = set(sig_names)
        if has_var_kw:
            static |= {k for k in opt_args if k != 'learning_rate'}
        static = sorted(static)
        inner = optax.inject_hyperparams(tx_factory, static_args=static)(learning_rate=lr, **opt_args)
        if caution:
            inner = _cautious(inner)
        if lr_scales is not None:
            inner = optax.chain(inner, _scale_by_tree(lr_scales))
        self.tx = inner
        self._has_lr_scales = lr_scales is not None
        self._caution = caution

    def init(self, params):
        return self.tx.init(params)

    def _find_hyperparams(self, state):
        # inject_hyperparams state may be nested under chain/caution wrappers
        if hasattr(state, 'hyperparams'):
            return state
        if isinstance(state, tuple) and not hasattr(state, '_fields'):
            for s in state:
                found = self._find_hyperparams(s)
                if found is not None:
                    return found
        return None

    def update(self, grads, state, params=None, lr=None):
        if lr is not None:
            hp_state = self._find_hyperparams(state)
            if hp_state is not None:
                hp_state.hyperparams['learning_rate'] = jnp.asarray(
                    lr, dtype=hp_state.hyperparams['learning_rate'].dtype)
        return self.tx.update(grads, state, params)


class OptimizerRegistry:
    """(reference _optim_factory.py:82)."""

    def __init__(self):
        self._optimizers: Dict[str, OptimInfo] = {}

    def register(self, info: OptimInfo):
        self._optimizers[info.name.lower()] = info

    def list_optimizers(self, filter: str = '', with_description: bool = False):
        import fnmatch
        names = sorted(self._optimizers)
        if filter:
            names = fnmatch.filter(names, filter)
        if with_description:
            return [(n, self._optimizers[n].description) for n in names]
        return names

    def get_optimizer_info(self, name: str) -> OptimInfo:
        name = name.lower()
        if name not in self._optimizers:
            raise ValueError(f'Optimizer {name} not found in registry')
        return self._optimizers[name]


def _sgdw(learning_rate, momentum=0.9, weight_decay=0.0, nesterov=False, mask=None):
    """SGD w/ decoupled weight decay (reference sgdw.py)."""
    steps = [optax.trace(decay=momentum, nesterov=nesterov)] if momentum else []
    if weight_decay:
        steps.append(optax.add_decayed_weights(weight_decay, mask=mask))
    steps.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*steps)


def _scale_by_rms_tf(decay: float, eps: float) -> optax.GradientTransformation:
    """eps-inside-sqrt RMS scaling for optax versions whose scale_by_rms has
    no eps_in_sqrt flag: nu ← decay·nu + (1-decay)·g²; u = g/√(nu+eps)."""

    def init(params):
        return jax.tree.map(jnp.zeros_like, params)

    def update(updates, nu, params=None, **extra):
        nu = jax.tree.map(lambda n, g: decay * n + (1 - decay) * (g * g), nu, updates)
        updates = jax.tree.map(lambda g, n: g * jax.lax.rsqrt(n + eps), updates, nu)
        return updates, nu

    return optax.GradientTransformationExtraArgs(init, update)


def _rmsprop_tf(learning_rate, alpha=0.9, eps=1e-10, momentum=0.9, weight_decay=0.0, mask=None):
    """TF1-behaviour RMSprop (reference rmsprop_tf.py: eps inside sqrt)."""
    import inspect
    if 'eps_in_sqrt' in inspect.signature(optax.scale_by_rms).parameters:
        steps = [optax.scale_by_rms(decay=alpha, eps=eps, eps_in_sqrt=True, bias_correction=False)]
    else:
        steps = [_scale_by_rms_tf(decay=alpha, eps=eps)]
    if weight_decay:
        steps.append(optax.add_decayed_weights(weight_decay, mask=mask))
    if momentum:
        steps.append(optax.trace(decay=momentum))
    steps.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*steps)


def _muon(learning_rate, weight_decay=0.0, momentum=0.95, beta1=0.9, beta2=0.95, eps=1e-8, mask=None):
    """Muon (Newton-Schulz orthogonalized momentum) for 2D params w/ AdamW
    fallback for others (reference muon.py:1-1056)."""
    return optax.contrib.muon(
        learning_rate=learning_rate,
        beta=momentum,
        weight_decay=weight_decay,
        weight_decay_mask=mask if mask is not None else True,
        adam_b1=beta1,
        adam_b2=beta2,
        adam_eps_root=0.0,
    )


def _lamb(learning_rate, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0, mask=None, mu_dtype=None):
    if mu_dtype is None:
        return optax.lamb(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay, mask=mask)
    # optax.lamb doesn't expose mu_dtype; rebuild its exact chain with the
    # first moment stored reduced (m reads/writes halve; v stays fp32)
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps, eps_root=0.0, mu_dtype=mu_dtype),
        optax.add_decayed_weights(weight_decay, mask),
        optax.scale_by_trust_ratio(),
        optax.scale_by_learning_rate(learning_rate),
    )


def _lars(learning_rate, momentum=0.9, weight_decay=0.0, trust_coefficient=0.001, mask=None):
    return optax.lars(
        learning_rate, weight_decay=weight_decay, weight_decay_mask=mask if mask is not None else True,
        trust_coefficient=trust_coefficient, momentum=momentum)


def _adafactor(learning_rate, eps=None, clipping_threshold=1.0, decay_rate=0.8, weight_decay=0.0, mask=None, min_dim_size_to_factor=32):
    return optax.adafactor(
        learning_rate=learning_rate,
        min_dim_size_to_factor=min_dim_size_to_factor,
        decay_rate=decay_rate,
        clipping_threshold=clipping_threshold,
        weight_decay_rate=weight_decay or None,
        weight_decay_mask=mask if mask is not None else True,
    )


def _default_registry() -> OptimizerRegistry:
    r = OptimizerRegistry()

    def wd_first(fn):
        return fn

    r.register(OptimInfo('sgd', partial(optax.sgd), 'SGD w/ Nesterov momentum', has_eps=False, has_momentum=True,
                         defaults={'nesterov': True}))
    r.register(OptimInfo('momentum', partial(optax.sgd), 'SGD w/ classical momentum', has_eps=False, has_momentum=True,
                         defaults={'nesterov': False}))
    r.register(OptimInfo('sgdw', _sgdw, 'SGD w/ decoupled weight decay', has_eps=False, has_momentum=True))
    r.register(OptimInfo('sgdp', _sgdw, 'SGDP (approx. via decoupled-WD SGD)', has_eps=False, has_momentum=True))
    r.register(OptimInfo('adam', optax.adam, 'Adam', has_betas=True))
    r.register(OptimInfo('adamw', optax.adamw, 'Adam w/ decoupled weight decay', has_betas=True))
    r.register(OptimInfo('adamp', optax.adamw, 'AdamP (approx. via AdamW)', has_betas=True))
    r.register(OptimInfo('nadam', optax.nadam, 'Adam w/ Nesterov momentum', has_betas=True))
    r.register(OptimInfo('nadamw', optax.nadamw, 'NAdamW (MLCommons algorithmic-efficiency)', has_betas=True))
    r.register(OptimInfo('radam', optax.radam, 'Rectified Adam', has_betas=True))
    r.register(OptimInfo('adamax', optax.adamax, 'Adamax (inf-norm Adam)', has_betas=True))
    r.register(OptimInfo('adabelief', optax.adabelief, 'AdaBelief', has_betas=True))
    r.register(OptimInfo('adadelta', optax.adadelta, 'Adadelta'))
    r.register(OptimInfo('adagrad', optax.adagrad, 'Adagrad'))
    r.register(OptimInfo('adafactor', _adafactor, 'Adafactor (memory-factored)', has_eps=False))
    r.register(OptimInfo('adafactorbv', _adafactor, 'Big-Vision Adafactor variant', has_eps=False,
                         defaults={'min_dim_size_to_factor': 32}))
    # not present in every optax release the container may ship; register
    # only what exists so one missing contrib optimizer can't break imports
    if hasattr(optax.contrib, 'adopt'):
        r.register(OptimInfo('adopt', optax.contrib.adopt, 'ADOPT - modified Adam', has_betas=True))
    if hasattr(optax, 'adan'):
        r.register(OptimInfo('adan', optax.adan, 'Adaptive Nesterov momentum', has_betas=True, num_betas=3))
    r.register(OptimInfo('lamb', _lamb, 'LAMB (layer-wise adaptation)', has_betas=True))
    r.register(OptimInfo('lars', _lars, 'LARS', has_eps=False, has_momentum=True))
    r.register(OptimInfo('lion', optax.lion, 'Lion (evolved sign momentum)', has_eps=False, has_betas=True))
    r.register(OptimInfo('lookahead', optax.sgd, 'placeholder; use lookahead_* prefix', has_eps=False))
    if hasattr(optax.contrib, 'muon'):
        r.register(OptimInfo('muon', _muon, 'Muon (Newton-Schulz orthogonalization, AdamW fallback)', has_momentum=True))
        r.register(OptimInfo('adamuon', _muon, 'AdaMuon alias (optax muon w/ adam fallback)', has_momentum=True))
        r.register(OptimInfo('nadamuon', _muon, 'NadaMuon alias (optax muon w/ adam fallback)', has_momentum=True))
    r.register(OptimInfo('novograd', optax.novograd, 'NovoGrad', has_betas=True))
    r.register(OptimInfo('nvnovograd', optax.novograd, 'NVIDIA NovoGrad alias', has_betas=True))
    r.register(OptimInfo('rmsprop', partial(optax.rmsprop, decay=0.9, momentum=0.9), 'RMSprop', has_momentum=True))
    r.register(OptimInfo('rmsproptf', _rmsprop_tf, 'TF1-behaviour RMSprop', has_momentum=True))
    r.register(OptimInfo('yogi', optax.yogi, 'Yogi', has_betas=True))
    r.register(OptimInfo('sm3', optax.sm3, 'SM3 (memory-efficient)', has_eps=False))
    from ._extra import laprop, madgrad, mars
    r.register(OptimInfo('madgrad', madgrad, 'MADGRAD (momentumized dual averaging)', has_momentum=True))
    r.register(OptimInfo('madgradw', partial(madgrad, decoupled_decay=True),
                         'MADGRAD w/ decoupled weight decay', has_momentum=True))
    r.register(OptimInfo('laprop', laprop, 'LaProp (decoupled momentum/adaptivity)', has_betas=True))
    r.register(OptimInfo('mars', mars, 'MARS (variance-reduced adaptive momentum)', has_betas=True))
    return r


default_registry = _default_registry()


def list_optimizers(filter: str = '', with_description: bool = False):
    return default_registry.list_optimizers(filter, with_description)


def get_optimizer_info(name: str) -> OptimInfo:
    return default_registry.get_optimizer_info(name)


def optimizer_kwargs(cfg) -> Dict[str, Any]:
    """argparse bridge (reference _optim_factory.py:1300)."""
    kwargs = dict(
        opt=cfg.opt,
        lr=cfg.lr,
        weight_decay=cfg.weight_decay,
        momentum=cfg.momentum,
    )
    if getattr(cfg, 'opt_eps', None) is not None:
        kwargs['eps'] = cfg.opt_eps
    if getattr(cfg, 'opt_betas', None) is not None:
        kwargs['betas'] = cfg.opt_betas
    if getattr(cfg, 'layer_decay', None) is not None:
        kwargs['layer_decay'] = cfg.layer_decay
    if getattr(cfg, 'layer_decay_min_scale', None) is not None:
        kwargs['layer_decay_min_scale'] = cfg.layer_decay_min_scale
    if getattr(cfg, 'opt_kwargs', None):
        kwargs.update(cfg.opt_kwargs)
    if getattr(cfg, 'opt_caution', False):
        kwargs['caution'] = True
    return kwargs


def create_optimizer_v2(
        model_or_params,
        opt: str = 'sgd',
        lr: Optional[float] = None,
        weight_decay: float = 0.0,
        momentum: float = 0.9,
        foreach: Optional[bool] = None,  # torch-ism, accepted and ignored
        filter_bias_and_bn: bool = True,
        layer_decay: Optional[float] = None,
        layer_decay_min_scale: float = 0.0,
        param_group_fn: Optional[Callable] = None,  # accepted for parity; masks built internally
        caution: bool = False,
        mu_dtype=None,
        **kwargs,
) -> Optimizer:
    """Create an Optimizer from a model (reference _optim_factory.py:1199-1298).

    Precedence mirrors the reference: layer_decay > plain weight-decay
    filtering. Returns an `Optimizer` whose state aligns with
    `nnx.state(model, nnx.Param)`.

    `mu_dtype` ('bfloat16' / dtype) stores the first moment (m) of the
    Adam-family optimizers (adam/adamw/nadamw/lamb/...) reduced, halving its
    HBM read+write traffic per step (~0.7 GB/step of ViT-B's 2.08 GB
    optimizer traffic, PERF.md §2 item 3); v stays fp32. Default None keeps
    fp32 state bit-for-bit. Seeded from TIMM_TPU_MU_DTYPE when unset so
    bench.py can A/B it per process.
    """
    is_model = isinstance(model_or_params, nnx.Module)
    lr_scales = None
    wd_mask = None
    if is_model:
        model = model_or_params
        if layer_decay is not None:
            lr_scales, wd_mask = param_groups_layer_decay(
                model, weight_decay=weight_decay, layer_decay=layer_decay,
                min_scale=layer_decay_min_scale)
        elif weight_decay and filter_bias_and_bn:
            wd_mask = param_groups_weight_decay(model, weight_decay=weight_decay)

    # split opt string: 'lookahead_adamw' etc.
    opt_split = opt.lower().split('_')
    opt_name = opt_split[-1]
    use_lookahead = len(opt_split) > 1 and opt_split[0] == 'lookahead'
    info = default_registry.get_optimizer_info(opt_name.replace('_', ''))

    opt_args: Dict[str, Any] = dict(info.defaults or {})
    if lr is None:
        lr = 1e-3
    betas = kwargs.pop('betas', None)
    eps = kwargs.pop('eps', None)
    if info.has_betas and betas is not None:
        opt_args.update(b1=betas[0], b2=betas[1])
        if info.num_betas == 3 and len(betas) > 2:
            opt_args['b3'] = betas[2]
    if info.has_eps and eps is not None:
        opt_args['eps'] = eps
    if info.has_momentum:
        opt_args['momentum'] = momentum
    if mu_dtype is None:
        import os
        mu_dtype = os.environ.get('TIMM_TPU_MU_DTYPE') or None
    if mu_dtype is not None:
        from ..layers.config import resolve_dtype_arg
        opt_args['mu_dtype'] = resolve_dtype_arg(mu_dtype)

    # weight decay plumbing: pass decay + mask where the factory supports it
    import inspect
    sig_params = None
    try:
        sig_params = set(inspect.signature(info.opt_class).parameters)
    except (TypeError, ValueError):
        pass
    if sig_params is not None:
        if 'weight_decay' in sig_params:
            opt_args['weight_decay'] = weight_decay
        elif 'weight_decay_rate' in sig_params:
            opt_args['weight_decay_rate'] = weight_decay or None
        if wd_mask is not None:
            if 'mask' in sig_params:
                opt_args['mask'] = wd_mask
            elif 'weight_decay_mask' in sig_params:
                opt_args['weight_decay_mask'] = wd_mask
        if 'nesterov' in sig_params and 'nesterov' in opt_args:
            pass
        if 'mu_dtype' in opt_args and 'mu_dtype' not in sig_params:
            _logger.warning(f'optimizer {opt_name!r} has no mu_dtype support; ignoring mu_dtype={mu_dtype}')
        # drop unsupported kwargs
        opt_args = {k: v for k, v in opt_args.items() if k in sig_params or k == 'learning_rate'}
    # user opt_kwargs passthrough
    for k, v in kwargs.items():
        if sig_params is None or k in sig_params:
            opt_args[k] = v

    tx_factory = info.opt_class
    # Coupled L2 for optimizers whose optax factory has no weight-decay param
    # (sgd/momentum/adam/nadam/radam/rmsprop/adabelief/...): torch applies WD by
    # adding wd*p to the gradient before the transform (reference
    # _optim_factory.py param-group defaults); without this the default
    # `train.py --weight-decay` silently trains unregularized.
    supports_wd = sig_params is not None and (
        'weight_decay' in sig_params or 'weight_decay_rate' in sig_params)
    if weight_decay and not supports_wd:
        base_l2 = tx_factory
        bound_l2 = dict(opt_args)
        opt_args = {}

        def tx_factory(learning_rate, _base=base_l2, _bound=bound_l2,
                       _wd=weight_decay, _mask=wd_mask):
            return optax.chain(
                optax.add_decayed_weights(_wd, mask=_mask),
                _base(learning_rate, **_bound),
            )

    if use_lookahead:
        base_factory = tx_factory
        bound_args = dict(opt_args)
        opt_args = {}

        def tx_factory(learning_rate, _base=base_factory, _bound=bound_args):
            return _lookahead(_base(learning_rate, **_bound), sync_period=6, slow_step_size=0.5)

    optimizer = Optimizer(
        tx_factory,
        lr=lr,
        opt_args=opt_args,
        lr_scales=lr_scales,
        caution=caution,
        defaults={'opt': opt, 'weight_decay': weight_decay},
    )
    # The one-pass fused AdamW+EMA kernel (kernels/fused_adamw.py) mirrors
    # exactly the plain adamw chain: inject_hyperparams(adamw)(lr, ...). Any
    # wrapper that changes the update math (lookahead, caution, layer-decay
    # lr scales, coupled-L2 rebinding) is out of regime, so the recipe is
    # attached only when none apply; TrainingTask(fused_update=True) requires
    # it and refuses optimizers without it.
    if (opt_name == 'adamw' and not use_lookahead and not caution
            and lr_scales is None and tx_factory is optax.adamw):
        optimizer.fused_adamw_args = {
            'b1': float(opt_args.get('b1', 0.9)),
            'b2': float(opt_args.get('b2', 0.999)),
            'eps': float(opt_args.get('eps', 1e-8)),
            'weight_decay': float(opt_args.get('weight_decay', 0.0)),
            'mu_dtype': opt_args.get('mu_dtype'),
            'wd_mask': opt_args.get('mask'),
        }
    return optimizer
