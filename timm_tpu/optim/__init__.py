from ._optim_factory import (
    OptimInfo, Optimizer, OptimizerRegistry, create_optimizer_v2,
    get_optimizer_info, list_optimizers, optimizer_kwargs,
)
from ._param_groups import param_groups_layer_decay, param_groups_weight_decay


def create_optimizer(args, model, filter_bias_and_bn=True):
    """Legacy factory signature (reference: timm/optim/_optim_factory.py legacy shim)."""
    return create_optimizer_v2(
        model,
        **optimizer_kwargs(args),
        filter_bias_and_bn=filter_bias_and_bn,
    )
