"""Long-tail optimizers: MADGRAD, LaProp, MARS
(reference: timm/optim/madgrad.py:189, laprop.py:159, mars.py:207),
as optax gradient transformations.

All are written as pure update rules over pytrees — state lives in the optax
state tuple, updates are returned as parameter deltas, and everything traces
cleanly under jit (the step counter is a traced scalar, not python state).
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax


def _resolve_mask(mask, params):
    """Weight-decay mask → pytree of bools matching params (factory passes a
    pytree or callable like optax.add_decayed_weights)."""
    if mask is None:
        return None
    return mask(params) if callable(mask) else mask


class MadgradState(NamedTuple):
    step: chex.Array
    grad_sum_sq: optax.Updates
    s: optax.Updates
    x0: optax.Params


def madgrad(
        learning_rate: float = 1e-2,
        momentum: float = 0.9,
        weight_decay: float = 0.0,
        eps: float = 1e-6,
        decoupled_decay: bool = False,
        mask=None,
) -> optax.GradientTransformation:
    """MADGRAD: momentumized, adaptive dual-averaged gradient
    (reference madgrad.py:91-189)."""
    ck = 1 - momentum

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return MadgradState(
            step=jnp.zeros([], jnp.int32),
            grad_sum_sq=zeros,
            s=jax.tree.map(jnp.zeros_like, params),
            x0=jax.tree.map(jnp.asarray, params),
        )

    def update_fn(updates, state, params=None):
        assert params is not None, 'madgrad requires params'
        step = state.step + 1
        lr = learning_rate + eps
        lamb = lr * jnp.sqrt(step.astype(jnp.float32))
        wd_mask = _resolve_mask(mask, params)

        def one(g, p_orig, gss, s, x0, decay_ok):
            p = p_orig
            if weight_decay and decay_ok:
                if decoupled_decay:
                    p = p * (1.0 - learning_rate * weight_decay)
                else:
                    g = g + weight_decay * p
            gss = gss + lamb * g * g
            rms = jnp.cbrt(gss) + eps
            s = s + lamb * g
            z = x0 - s / rms
            if momentum == 0:
                new_p = z
            else:
                new_p = (1 - ck) * p + ck * z
            # delta is applied to the ORIGINAL param by optax.apply_updates
            return new_p - p_orig, gss, s

        flat_g, treedef = jax.tree.flatten(updates)
        flat_p = treedef.flatten_up_to(params)
        flat_gss = treedef.flatten_up_to(state.grad_sum_sq)
        flat_s = treedef.flatten_up_to(state.s)
        flat_x0 = treedef.flatten_up_to(state.x0)
        flat_m = treedef.flatten_up_to(wd_mask) if wd_mask is not None else [True] * len(flat_g)
        out = [one(g, p, gss, s, x0, m) for g, p, gss, s, x0, m in
               zip(flat_g, flat_p, flat_gss, flat_s, flat_x0, flat_m)]
        deltas = treedef.unflatten([o[0] for o in out])
        new_gss = treedef.unflatten([o[1] for o in out])
        new_s = treedef.unflatten([o[2] for o in out])
        return deltas, MadgradState(step=step, grad_sum_sq=new_gss, s=new_s, x0=state.x0)

    return optax.GradientTransformation(init_fn, update_fn)


class LapropState(NamedTuple):
    step: chex.Array
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    exp_avg_lr_1: chex.Array
    exp_avg_lr_2: chex.Array


def laprop(
        learning_rate: float = 4e-4,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-15,
        weight_decay: float = 0.0,
        mask=None,
) -> optax.GradientTransformation:
    """LaProp: decouples momentum from adaptive normalization — the momentum
    buffer accumulates lr-scaled NORMALIZED gradients (reference laprop.py:80-150)."""

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return LapropState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree.map(jnp.zeros_like, params),
            exp_avg_lr_1=jnp.zeros([], jnp.float32),
            exp_avg_lr_2=jnp.zeros([], jnp.float32),
        )

    def update_fn(updates, state, params=None):
        step = state.step + 1
        lr = learning_rate
        ealr1 = state.exp_avg_lr_1 * b1 + (1 - b1) * lr
        ealr2 = state.exp_avg_lr_2 * b2 + (1 - b2)
        lr_safe = jnp.where(lr != 0.0, lr, 1.0)
        bias1 = jnp.where(lr != 0.0, ealr1 / lr_safe, 1.0)
        step_size = 1.0 / bias1

        def moments(g, eas):
            return b2 * eas + (1 - b2) * g * g

        new_eas = jax.tree.map(moments, updates, state.exp_avg_sq)

        def momentum(g, ea, eas):
            denom = jnp.sqrt(eas / ealr2) + eps
            return b1 * ea + lr * (1 - b1) * (g / denom)

        new_ea = jax.tree.map(momentum, updates, state.exp_avg, new_eas)

        if params is not None:
            wd_mask = _resolve_mask(mask, params)

            def delta(ea, p, decay_ok):
                d = -step_size * ea
                if weight_decay and decay_ok:
                    d = d - lr * weight_decay * p
                return d

            ones = jax.tree.map(lambda _: True, params) if wd_mask is None else wd_mask
            deltas = jax.tree.map(delta, new_ea, params, ones)
        else:
            deltas = jax.tree.map(lambda ea: -step_size * ea, new_ea)
        return deltas, LapropState(
            step=step, exp_avg=new_ea, exp_avg_sq=new_eas,
            exp_avg_lr_1=ealr1, exp_avg_lr_2=ealr2)

    return optax.GradientTransformation(init_fn, update_fn)


class MarsState(NamedTuple):
    step: chex.Array
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    last_grad: optax.Updates


def mars(
        learning_rate: float = 3e-3,
        b1: float = 0.9,
        b2: float = 0.99,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        gamma: float = 0.025,
        mars_type: str = 'adamw',
        optimize_1d: bool = False,
        lr_1d_factor: float = 1.0,
        betas_1d: Optional[Tuple[float, float]] = None,
        mask=None,
) -> optax.GradientTransformation:
    """MARS: variance-reduced adaptive momentum — the momentum input is the
    gradient plus a clipped scaled gradient difference
    (reference mars.py:25-105)."""
    assert mars_type in ('adamw', 'lion')
    b1_1d, b2_1d = betas_1d or (b1, b2)

    def init_fn(params):
        return MarsState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=jax.tree.map(jnp.zeros_like, params),
            exp_avg_sq=jax.tree.map(jnp.zeros_like, params),
            last_grad=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        assert params is not None, 'mars requires params'
        step = state.step + 1
        stepf = step.astype(jnp.float32)


        def one(g, p, ea, eas, lg, decay_ok):
            wd = weight_decay if decay_ok else 0.0
            if optimize_1d or g.ndim >= 2:
                c_t_raw = g + gamma * (b1 / (1 - b1)) * (g - lg)
                norm = jnp.linalg.norm(c_t_raw)
                c_t_clipped = jnp.where(norm > 1.0, c_t_raw / jnp.maximum(norm, 1e-12), c_t_raw)
                # first step uses the raw gradient (timm consistency tweak)
                c_t = jnp.where(step == 1, g, c_t_clipped)
                new_ea = b1 * ea + (1 - b1) * c_t
                if mars_type == 'adamw':
                    new_eas = b2 * eas + (1 - b2) * c_t * c_t
                    bc1 = 1.0 - b1 ** stepf
                    bc2 = 1.0 - b2 ** stepf
                    denom = jnp.sqrt(new_eas) / jnp.sqrt(bc2) + eps
                    update = p * wd + (new_ea / bc1) / denom
                else:  # lion
                    new_eas = eas
                    update = p * wd + jnp.sign(new_ea)
                return -learning_rate * update, new_ea, new_eas
            # 1-D params fall back to AdamW
            new_ea = b1_1d * ea + (1 - b1_1d) * g
            new_eas = b2_1d * eas + (1 - b2_1d) * g * g
            bc1 = 1.0 - b1_1d ** stepf
            bc2 = 1.0 - b2_1d ** stepf
            denom = jnp.sqrt(new_eas) / jnp.sqrt(bc2) + eps
            update = p * wd + (new_ea / bc1) / denom
            return -(learning_rate * lr_1d_factor) * update, new_ea, new_eas

        flat_g, treedef = jax.tree.flatten(updates)
        flat_p = treedef.flatten_up_to(params)
        flat_ea = treedef.flatten_up_to(state.exp_avg)
        flat_eas = treedef.flatten_up_to(state.exp_avg_sq)
        flat_lg = treedef.flatten_up_to(state.last_grad)
        wd_mask = _resolve_mask(mask, params)
        flat_m = treedef.flatten_up_to(wd_mask) if wd_mask is not None else [True] * len(flat_g)
        out = [one(g, p, ea, eas, lg, m) for g, p, ea, eas, lg, m in
               zip(flat_g, flat_p, flat_ea, flat_eas, flat_lg, flat_m)]
        deltas = treedef.unflatten([o[0] for o in out])
        new_ea = treedef.unflatten([o[1] for o in out])
        new_eas = treedef.unflatten([o[2] for o in out])
        return deltas, MarsState(step=step, exp_avg=new_ea, exp_avg_sq=new_eas, last_grad=updates)

    return optax.GradientTransformation(init_fn, update_fn)
