"""Parameter-group semantics as pytree masks/scales
(reference: timm/optim/_param_groups.py:19-194).

torch param groups don't exist in optax; the same semantics are expressed as
pytrees aligned with the param state:
  * weight-decay exclusion  → boolean mask tree (True = apply WD)
  * layer-decay             → float lr-scale tree
"""
from __future__ import annotations

import fnmatch
import logging
from typing import Any, Callable, Dict, Optional, Set, Tuple

from flax import nnx

from ..models._manipulate import group_with_matcher, named_parameters
from ..utils.serialization import _kp_str as _keypath_str

_logger = logging.getLogger(__name__)

__all__ = ['param_groups_weight_decay', 'param_groups_layer_decay', 'auto_group_layers']


def _matches_no_decay(name: str, no_decay_names: Set[str]) -> bool:
    for pat in no_decay_names:
        if name == pat or name.startswith(pat + '.') or fnmatch.fnmatch(name, pat) or name.endswith(pat):
            return True
    return False


def _tree_from_name_fn(model: nnx.Module, fn: Callable[[str, Any], Any]):
    """Build a pytree over nnx.Param state with values from fn(name, value)."""
    import jax
    state = nnx.state(model, nnx.Param)
    return jax.tree_util.tree_map_with_path(
        lambda kp, v: fn(_keypath_str(kp), v), state)





def param_groups_weight_decay(
        model: nnx.Module,
        weight_decay: float = 1e-5,
        no_weight_decay_list: Tuple[str, ...] = (),
):
    """Boolean WD mask: False for 1-d params / bias / listed names
    (reference _param_groups.py:19)."""
    no_decay = set(no_weight_decay_list)
    if hasattr(model, 'no_weight_decay'):
        no_decay |= set(model.no_weight_decay())

    def decide(name, value):
        if value is None or not hasattr(value, 'ndim'):
            return False
        if value.ndim <= 1 or name.endswith('.bias') or _matches_no_decay(name, no_decay):
            return False
        return True

    return _tree_from_name_fn(model, decide)


def auto_group_layers(model: nnx.Module, group_matcher=None, reverse: bool = True):
    """name → layer-id mapping from the model's group_matcher."""
    if group_matcher is None:
        group_matcher = model.group_matcher(coarse=False)
    return group_with_matcher(
        named_parameters(model).items(), group_matcher, return_values=False, reverse=reverse)


def param_groups_layer_decay(
        model: nnx.Module,
        weight_decay: float = 0.05,
        no_weight_decay_list: Tuple[str, ...] = (),
        layer_decay: float = 0.75,
        min_scale: float = 0.0,
):
    """Float lr-scale tree via group_matcher layer ids
    (reference _param_groups.py:113). Returns (scale_tree, wd_mask_tree)."""
    wd_mask = param_groups_weight_decay(model, weight_decay, no_weight_decay_list)

    param_to_layer = auto_group_layers(model, reverse=True)
    num_layers = max(param_to_layer.values()) + 1 if param_to_layer else 1
    layer_max = num_layers - 1
    layer_scales = [max(layer_decay ** (layer_max - i), min_scale) for i in range(num_layers)]

    def scale(name, value):
        lid = param_to_layer.get(name, layer_max)
        return layer_scales[lid]

    scale_tree = _tree_from_name_fn(model, scale)
    return scale_tree, wd_mask
