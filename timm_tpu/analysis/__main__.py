"""CLI: run the static-analysis suite.

    python -m timm_tpu.analysis                      # all rules, full zoo
    python -m timm_tpu.analysis --rules silent-except,fp32-softmax
    python -m timm_tpu.analysis --tiers A            # source rules only
    python -m timm_tpu.analysis --json out.json      # machine-readable report
    python -m timm_tpu.analysis --list               # rule table

Exit codes: 0 clean / 2 violations / 3 internal error (a crashed rule is
never evidence of a clean repo).

Tier B/C rules consume programs the perfbudget probes lower, which needs
the forced 8-virtual-CPU-device topology — set before jax is imported.
Like perfbudget's CLI, this module re-execs itself once with the XLA flag
exported when the device count is short (guarded so a topology that still
comes up short fails loudly instead of looping).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REQUIRED_DEVICES = 8
_REEXEC_GUARD = 'TIMM_TPU_ANALYSIS_REEXEC'


def _maybe_reexec(argv, needed: bool) -> None:
    import jax
    if (not needed or jax.device_count() >= _REQUIRED_DEVICES
            or os.environ.get(_REEXEC_GUARD)):
        return
    env = dict(os.environ)
    flags = env.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={_REQUIRED_DEVICES}').strip()
    env.setdefault('JAX_PLATFORMS', 'cpu')  # every verdict is CPU-provable
    env[_REEXEC_GUARD] = '1'
    raise SystemExit(subprocess.call(
        [sys.executable, '-m', 'timm_tpu.analysis'] + list(argv), env=env))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(prog='python -m timm_tpu.analysis')
    parser.add_argument('--rules', default='', metavar='A,B',
                        help='comma-separated rule subset (default: all)')
    parser.add_argument('--tiers', default='', metavar='A,B,C',
                        help='comma-separated tier subset')
    parser.add_argument('--json', default=None, metavar='PATH',
                        help='write the full report as JSON ("-" = stdout)')
    parser.add_argument('--list', action='store_true',
                        help='print the rule table and exit')
    parser.add_argument('--source-root', default=None, metavar='DIR',
                        help='scan this tree instead of the repo (source '
                             'rules; used by the planted-violation tests)')
    parser.add_argument('--probe-configs', default='', metavar='A,B',
                        help='perfbudget configs to lower for Tier B/C '
                             '(default: the full analysis set)')
    parser.add_argument('--zoo-families', default='', metavar='A,B',
                        help='family subset for zoo-abstract-trace '
                             '(default: every registered family)')
    parser.add_argument('-q', '--quiet', action='store_true',
                        help='suppress progress logging')
    args = parser.parse_args(argv)

    from . import registry as R
    from .report import EXIT_ERROR

    if args.list:
        for r in R.all_rules():
            needs = ' [programs]' if r.needs_programs else ''
            print(f'{r.tier}  {r.name:24s}{needs}  {r.description}')
        return 0

    names = [n.strip() for n in args.rules.split(',') if n.strip()] or None
    tiers = [t.strip() for t in args.tiers.split(',') if t.strip()] or None
    try:
        rules = R.select(names=names, tiers=tiers)
    except KeyError as e:
        print(f'analysis: {e}', file=sys.stderr)
        return EXIT_ERROR

    _maybe_reexec(argv, needed=any(r.needs_programs or r.needs_devices > 1
                                   for r in rules))

    log = (lambda m: None) if args.quiet else (
        lambda m: print(m, file=sys.stderr, flush=True))
    probe_names = ([n.strip() for n in args.probe_configs.split(',')
                    if n.strip()] or None)
    zoo_families = ([f.strip() for f in args.zoo_families.split(',')
                     if f.strip()] or None)
    ctx = R.AnalysisContext(root=args.source_root, probe_names=probe_names,
                            zoo_families=zoo_families, log=log)
    try:
        report = R.run_analysis(ctx, rules)
    except Exception as e:  # noqa: BLE001 - driver failure = exit 3
        print(f'analysis: internal error: {type(e).__name__}: {e}',
              file=sys.stderr)
        return EXIT_ERROR

    if args.json == '-':
        print(report.to_json(indent=1))
    elif args.json:
        with open(args.json, 'w', encoding='utf-8') as f:
            f.write(report.to_json(indent=1))
        log(f'analysis: report -> {args.json}')
    print(report.format_text())
    return report.exit_code


if __name__ == '__main__':
    raise SystemExit(main())
