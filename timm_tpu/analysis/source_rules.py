"""Tier A — AST/source rules.

The five lints that used to live inline in tests/ (donation-declared,
partition-rules, kernel-registered, fp32-softmax, silent-except) plus the
new sweeps this PR adds (host-sync, traced-branch, pragma-syntax). All of
them honor the unified pragma (see pragmas.py); the first four keep their
historical waiver spellings via the shims.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Set

import numpy as np

from .registry import AnalysisContext, rule
from .report import Finding

# directories of the timm_tpu package swept by the repo-wide source rules
_PACKAGE = 'timm_tpu'


def _lineno(text: str, pos: int) -> int:
    return text.count('\n', 0, pos) + 1


# ---- silent-except (repo-wide; was tests/test_data.py, data/ only) ----------

_SILENT_EXCEPT_RE = re.compile(
    r'except\s+(Exception|BaseException)?\s*(as\s+\w+)?\s*:\s*\n\s*pass\b')


@rule('silent-except', 'A',
      'no `except [Exception]: pass` anywhere in timm_tpu/ or the top-level '
      'scripts — transient faults go through the resilience retry policy, '
      'permanent ones through the poison-skip budget; both log')
def silent_except(ctx: AnalysisContext) -> List[Finding]:
    files = list(ctx.walk_files(_PACKAGE))
    pkg_dir = ctx.source_dir(_PACKAGE)
    if pkg_dir != ctx.root:
        # top-level driver scripts ride along (bench.py, train.py, ...)
        files += [os.path.join(ctx.root, f) for f in sorted(os.listdir(ctx.root))
                  if f.endswith('.py')]
    findings = []
    for path in files:
        text = ctx.read(path)
        for m in _SILENT_EXCEPT_RE.finditer(text):
            line = _lineno(text, m.start())
            findings.append(ctx.finding(
                'silent-except', path, line,
                'silent exception swallow — log it, retry it, or waive '
                'with a reason'))
    return findings


# ---- fp32-softmax (was tests/test_layers.py) --------------------------------

@rule('fp32-softmax', 'A',
      'layers must route softmax dtype through config.softmax_with_policy; '
      'a hard-coded fp32 upcast next to a softmax bypasses '
      'TIMM_TPU_SOFTMAX_DTYPE (config.py is the one allowed location)')
def fp32_softmax(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for path in ctx.source_files(_PACKAGE, 'layers'):
        if os.path.basename(path) == 'config.py':
            continue
        for lineno, line in enumerate(ctx.read(path).splitlines(), 1):
            if 'softmax(' in line and 'float32' in line:
                findings.append(ctx.finding(
                    'fp32-softmax', path, lineno,
                    'hard-coded fp32 softmax outside the policy module '
                    '(use timm_tpu.layers.softmax_with_policy)'))
    return findings


# ---- donation-declared (was tests/test_sharding.py) -------------------------

_JIT_RE = re.compile(r'(?:jax|nnx)\.jit\s*\(')
_DONATION_WAIVERS = ('no-donate:', 'timm-tpu-lint: disable=donation-declared')


@rule('donation-declared', 'A',
      'every jax.jit/nnx.jit call in timm_tpu/task/ declares donate_argnums '
      'or carries an explicit `# no-donate: <reason>` — the PERF.md item-3a '
      'regression (donation landed in bench only) cannot silently return')
def donation_declared(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for path in ctx.source_files(_PACKAGE, 'task'):
        lines = ctx.read(path).splitlines()
        for i, line in enumerate(lines):
            if not _JIT_RE.search(line.split('#')[0]):
                continue
            window = '\n'.join(lines[max(0, i - 3):i + 12])
            if ('donate_argnums' in window
                    or any(w in window for w in _DONATION_WAIVERS)):
                continue
            findings.append(ctx.finding(
                'donation-declared', path, i + 1,
                f'jit call without donate_argnums or a `# no-donate: '
                f'<reason>` comment: {line.strip()}'))
    return findings


# ---- kernel-registered (was tests/test_kernels.py) --------------------------

@rule('kernel-registered', 'A',
      'each .py in timm_tpu/kernels/ registers a KernelSpec whose `module` '
      'names it, or opens with `# no-kernel-registry: <reason>` in its '
      'first 5 lines')
def kernel_registered(ctx: AnalysisContext) -> List[Finding]:
    from ..kernels import registry as kreg
    kreg.ensure_registered()
    registered = {spec.module for spec in kreg.all_specs()}
    findings = []
    for path in ctx.source_files(_PACKAGE, 'kernels'):
        stem = os.path.splitext(os.path.basename(path))[0]
        if f'{_PACKAGE}.kernels.{stem}' in registered:
            continue
        pragmas = ctx.pragmas(path)
        reason = pragmas.waiver_for('kernel-registered')
        if reason:
            continue
        findings.append(ctx.finding(
            'kernel-registered', path, 1,
            f'{stem}.py defines no registered kernel and carries no '
            f'`# no-kernel-registry: <reason>` waiver '
            f'(registered modules: {sorted(registered)})'))
    return findings


# ---- partition-rules (was tests/test_sharding.py, 2 tests) ------------------

@rule('partition-rules', 'A',
      'the default rule table stays disjoint + exhaustive over every swept '
      'family (each param path matches exactly one non-catch-all rule; the '
      'tier-1 smoke covers the zoo smoke set, the CLI run all ~51), and '
      'under tp>1 every model-axis rule shards at least one real param and '
      'the conv rules place real hierarchical kernels',
      needs_devices=4)
def partition_rules(ctx: AnalysisContext) -> List[Finding]:
    from flax import nnx

    import timm_tpu
    from ..parallel import (
        create_mesh, default_partition_rules, match_rule, path_specs,
    )
    from ..utils.serialization import flatten_pytree
    from .zoo import family_representative

    findings: List[Finding] = []
    rules = default_partition_rules()
    specific, catchall = rules[:-1], rules[-1]
    if catchall.pattern != '.*':
        findings.append(Finding('partition-rules', 'parallel/rules', 0,
                                'last rule is not the catch-all'))
        return findings

    def paths_for(model_name, **kwargs):
        model = timm_tpu.create_model(model_name, **kwargs)
        return flatten_pytree(nnx.state(model, nnx.Param))

    def abstract_paths_for(model_name):
        # nnx.eval_shape constructs without allocating arrays, so sweeping
        # every family stays milliseconds per family
        model = nnx.eval_shape(
            lambda: timm_tpu.create_model(model_name, num_classes=10))
        return flatten_pytree(nnx.state(model, nnx.Param))

    # disjoint + exhaustive over the swept families: first-match-wins never
    # has to disambiguate. zoo_families=None (the CLI path) sweeps all
    # registered families; the tier-1 fixture injects the smoke subset.
    for module in (ctx.zoo_families or timm_tpu.list_modules()):
        try:
            name, _ = family_representative(module)
            paths = abstract_paths_for(name)
        except Exception:
            continue  # a family that cannot construct is zoo-abstract-trace's finding
        for path in paths:
            n = sum(1 for r in specific if r.matches(path))
            if n != 1:
                findings.append(Finding(
                    'partition-rules', f'{name}:{path}', 0,
                    f'matched {n} non-catch-all rules (expected exactly 1)'))

    # sized-model exhaustiveness spot check on a real (non-test-size) config
    for path in abstract_paths_for('vit_tiny_patch16_224'):
        n = sum(1 for r in specific if r.matches(path))
        if n != 1:
            findings.append(Finding(
                'partition-rules', f'vit_tiny_patch16_224:{path}', 0,
                f'matched {n} non-catch-all rules (expected exactly 1)'))

    # tp exercise: each of the four model-axis rules shards >=1 real param,
    # and the tp kernels also carry fsdp on the other dim (2-D sharding)
    mesh = create_mesh(fsdp=2, tp=2)
    paths = paths_for('test_vit', num_classes=10, img_size=32)
    specs = path_specs(paths, mesh)
    by_rule: Dict[str, List[str]] = {}
    for path in paths:
        _, r = match_rule(path, rules)
        by_rule.setdefault(r.name, []).append(path)
    for rname in ('attn-qkv', 'attn-out', 'mlp-fc1', 'mlp-fc2'):
        hit = [p for p in by_rule.get(rname, ())
               if any(ax == 'model' for ax in specs[p])]
        if not hit:
            findings.append(Finding(
                'partition-rules', f'rule:{rname}', 0,
                'tp rule not exercised by any test_vit param '
                '(dead weight that would silently rot)'))
    qkv = tuple(specs.get('blocks.0.attn.qkv.kernel', ()))
    if 'model' not in qkv or 'fsdp' not in qkv:
        findings.append(Finding(
            'partition-rules', 'blocks.0.attn.qkv.kernel', 0,
            f'tp kernel not 2-D sharded (got spec {qkv})'))

    # conv exercise on the same real 2x2 mesh: a hierarchical family's large
    # conv kernels shard their OUT-CHANNEL dim over fsdp, depthwise kernels
    # replicate, and its NHWC MLP Linears (1x1 convs) still pick up tp
    cpaths = paths_for('test_convnext', num_classes=10)
    cspecs = path_specs(cpaths, mesh)
    large_conv = [p for p in cpaths
                  if p.endswith('.kernel') and len(cpaths[p].shape) == 4
                  and cpaths[p].shape[-2] > 1
                  and int(np.prod(cpaths[p].shape)) >= 1024]
    if not any(tuple(cspecs[p])[-1:] == ('fsdp',) for p in large_conv):
        findings.append(Finding(
            'partition-rules', 'test_convnext:conv-out', 0,
            f'no large conv kernel sharded fsdp on its out-channel dim '
            f'(candidates: {large_conv[:4]})'))
    dw = [p for p in cpaths
          if p.endswith('.kernel') and len(cpaths[p].shape) == 4
          and cpaths[p].shape[-2] == 1]
    bad_dw = [p for p in dw if tuple(cspecs[p]) != ()]
    if not dw or bad_dw:
        findings.append(Finding(
            'partition-rules', 'test_convnext:depthwise', 0,
            f'depthwise conv kernels must replicate (violations: {bad_dw[:4]}, '
            f'found {len(dw)} dw kernels)'))
    if not any('model' in tuple(cspecs[p]) for p in cpaths
               if '.mlp.' in p and p.endswith('.kernel')):
        findings.append(Finding(
            'partition-rules', 'test_convnext:mlp-tp', 0,
            'no convnext MLP kernel carries the model axis — the NHWC '
            '1x1-conv Linears should reuse the attention-era tp rules'))
    return findings


# ---- host-sync + traced-branch (new AST sweeps) -----------------------------

def _is_jit_attr(node) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == 'jit'


def _has_jit_decorator(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _is_jit_attr(target):
            return True
        if (isinstance(dec, ast.Call) and dec.args
                and _is_jit_attr(dec.args[0])):
            return True  # @partial(jax.jit, ...)
    return False


def _scoped_children(node):
    """(defs, other_nodes) whose nearest enclosing scope is `node` — the
    walk stops at nested function/class boundaries."""
    defs, others = [], []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        ch = stack.pop()
        if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            defs.append(ch)
        else:
            others.append(ch)
            stack.extend(ast.iter_child_nodes(ch))
    return defs, others


def _jitted_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    """Function defs that are jit boundaries: decorated with *.jit (possibly
    through functools.partial), or passed by name to a jax.jit/nnx.jit call.
    Names resolve lexically — `jax.jit(step)` binds to the `step` visible
    from the call site, so a jitted inner function never implicates an
    outer method that happens to share its name."""
    out: List[ast.FunctionDef] = []
    seen: Set[int] = set()

    def flag(fn) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    def visit(node, env: Dict[str, ast.FunctionDef]) -> None:
        defs, others = _scoped_children(node)
        env = dict(env)
        env.update({d.name: d for d in defs
                    if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))})
        for o in others:
            if (isinstance(o, ast.Call) and _is_jit_attr(o.func)
                    and o.args and isinstance(o.args[0], ast.Name)
                    and o.args[0].id in env):
                flag(env[o.args[0].id])
        for d in defs:
            if (isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and _has_jit_decorator(d)):
                flag(d)
            visit(d, env)

    visit(tree, {})
    return sorted(out, key=lambda f: f.lineno)


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    return {n for n in names if n not in ('self', 'cls')}


_HOST_SYNC_NP_CALLS = {'asarray', 'array'}


def _host_sync_hits(fn: ast.FunctionDef) -> Iterable[ast.Call]:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == 'item':
            yield node
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name)
              and f.value.id in ('np', 'numpy')
              and f.attr in _HOST_SYNC_NP_CALLS):
            yield node
        elif (isinstance(f, ast.Name) and f.id in ('float', 'int')
              and node.args
              and not isinstance(node.args[0], ast.Constant)):
            yield node


@rule('host-sync', 'A',
      'no host-synchronizing call (`.item()`, `np.asarray`/`np.array`, '
      '`float()`/`int()` on a non-literal) inside a jitted function body — '
      'under jit these either fail on tracers or force a device sync')
def host_sync(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for path in ctx.walk_files(_PACKAGE):
        tree = ctx.ast_of(path)
        if tree is None:
            continue
        for fn in _jitted_functions(tree):
            for call in _host_sync_hits(fn):
                findings.append(ctx.finding(
                    'host-sync', path, call.lineno,
                    f'host-sync call inside jitted `{fn.name}` '
                    f'(traced values cannot leave the device here)'))
    return findings


_STATIC_ATTRS = ('shape', 'ndim', 'dtype', 'size')
_STATIC_CALLS = ('len', 'isinstance', 'getattr', 'hasattr', 'callable')


def _hazardous_params(test: ast.expr, params: Set[str]) -> Set[str]:
    """Param names whose runtime VALUE the test consults. Static uses branch
    at trace time and are skipped: `x is None`, `x.shape`/`.ndim`/`.dtype`/
    `.size`, `len(x)`, `isinstance(x, ...)`."""
    hazards: Set[str] = set()

    class _V(ast.NodeVisitor):
        def visit_Attribute(self, node):
            if (isinstance(node.value, ast.Name)
                    and node.attr in _STATIC_ATTRS):
                return
            self.generic_visit(node)

        def visit_Call(self, node):
            if isinstance(node.func, ast.Name) and node.func.id in _STATIC_CALLS:
                return
            self.generic_visit(node)

        def visit_Compare(self, node):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return
            self.generic_visit(node)

        def visit_Name(self, node):
            if node.id in params:
                hazards.add(node.id)

    _V().visit(test)
    return hazards


def _branch_hits(fn: ast.FunctionDef) -> Iterable[ast.stmt]:
    params = _param_names(fn)
    for node in ast.walk(fn):
        if (isinstance(node, (ast.If, ast.While))
                and _hazardous_params(node.test, params)):
            yield node


@rule('traced-branch', 'A',
      'no Python `if`/`while` on a traced argument value inside a jitted '
      'function — the branch freezes at trace time (or raises '
      'TracerBoolConversionError); use lax.cond/jnp.where')
def traced_branch(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for path in ctx.walk_files(_PACKAGE):
        tree = ctx.ast_of(path)
        if tree is None:
            continue
        for fn in _jitted_functions(tree):
            for stmt in _branch_hits(fn):
                findings.append(ctx.finding(
                    'traced-branch', path, stmt.lineno,
                    f'Python branch on a traced argument inside jitted '
                    f'`{fn.name}` — this freezes at trace time; use '
                    f'lax.cond / jnp.where'))
    return findings


# ---- process-zero-io --------------------------------------------------------

_RANK_NAMES = ('rank', 'local_rank', 'process_index', 'process_id')


def _mentions_rank(node: ast.expr) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _RANK_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_NAMES:
            return True
    return False


def _is_primary_guard(test: ast.expr) -> bool:
    """True when an `if` test gates on the primary process: a call to
    `is_primary(...)`, or a comparison of a rank/process_index value
    against 0 (`rank == 0`, `jax.process_index() == 0`, ...)."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            f = n.func
            if ((isinstance(f, ast.Name) and f.id == 'is_primary')
                    or (isinstance(f, ast.Attribute) and f.attr == 'is_primary')):
                return True
        if isinstance(n, ast.Compare):
            sides = [n.left] + list(n.comparators)
            if (any(isinstance(s, ast.Constant) and s.value == 0 for s in sides)
                    and any(_mentions_rank(s) for s in sides)):
                return True
    return False


def _open_for_write(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Name) and node.func.id == 'open'):
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == 'mode':
            mode = kw.value
    return (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
            and any(c in mode.value for c in 'wax'))


def _unguarded_writes(tree: ast.Module) -> Iterable[ast.Call]:
    def visit(node, guarded: bool):
        if isinstance(node, ast.If) and _is_primary_guard(node.test):
            # the else-branch of a primary guard is explicitly NOT primary
            for ch in node.body:
                visit(ch, True)
            for ch in node.orelse:
                visit(ch, guarded)
            return
        if isinstance(node, ast.Call) and _open_for_write(node) and not guarded:
            yield_list.append(node)
        for ch in ast.iter_child_nodes(node):
            visit(ch, guarded)

    yield_list: List[ast.Call] = []
    visit(tree, False)
    return yield_list


@rule('process-zero-io', 'A',
      'top-level driver scripts write non-shard files only on the primary '
      'process: every open-for-write sits under an `is_primary()` / '
      '`rank == 0` guard or carries a waiver — on a pod, N hosts racing one '
      'summary/args/results file corrupt it (per-process shard writes live '
      'in the durable library, not in drivers)')
def process_zero_io(ctx: AnalysisContext) -> List[Finding]:
    pkg_dir = ctx.source_dir(_PACKAGE)
    if pkg_dir != ctx.root:
        files = [os.path.join(ctx.root, f) for f in sorted(os.listdir(ctx.root))
                 if f.endswith('.py')]
    else:
        # fixture layout: the flat planted-violation directory IS the root
        files = ctx.walk_files()
    findings = []
    for path in files:
        tree = ctx.ast_of(path)
        if tree is None:
            continue
        for call in _unguarded_writes(tree):
            findings.append(ctx.finding(
                'process-zero-io', path, call.lineno,
                'file write outside an `is_primary()` / `rank == 0` guard — '
                'every pod host would race this write; guard it or waive '
                'with `# timm-tpu-lint: disable=process-zero-io <reason>`'))
    return findings


# ---- pragma-syntax ----------------------------------------------------------

@rule('pragma-syntax', 'A',
      'every `# timm-tpu-lint:` pragma and waiver shim parses and carries a '
      'reason — reasonless waivers waive nothing')
def pragma_syntax(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for path in ctx.walk_files(_PACKAGE):
        for lineno, msg in ctx.pragmas(path).malformed:
            findings.append(Finding('pragma-syntax', ctx.rel(path),
                                    lineno, msg))
    return findings
