"""Finding/Report spine shared by every analyzer tier.

One :class:`Finding` = one rule hit at one location (a source line, a
captured program, or a model family). Waived findings stay in the report —
the waiver and its reason are part of the audit trail — but only unwaived
findings count as violations and drive the exit code.

Exit-code contract (pinned by tests/test_analysis.py):

  * 0 — every selected rule ran and produced no unwaived finding;
  * 2 — at least one unwaived finding (violations);
  * 3 — a rule raised (internal error) — the run is NOT evidence of a clean
    repo, so it must never be conflated with exit 2.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

__all__ = ['Finding', 'Report', 'EXIT_CLEAN', 'EXIT_VIOLATIONS', 'EXIT_ERROR']

SCHEMA = 'timm-tpu-analysis/v1'
EXIT_CLEAN = 0
EXIT_VIOLATIONS = 2
EXIT_ERROR = 3


@dataclasses.dataclass
class Finding:
    rule: str
    path: str                 # source file, captured-program name, or family
    line: int = 0             # 0 = not line-anchored
    message: str = ''
    waived: bool = False
    waive_reason: str = ''

    @property
    def location(self) -> str:
        return f'{self.path}:{self.line}' if self.line else self.path

    def to_dict(self) -> Dict:
        d = {'rule': self.rule, 'path': self.path, 'line': self.line,
             'message': self.message}
        if self.waived:
            d['waived'] = True
            d['waive_reason'] = self.waive_reason
        return d


class Report:
    """Per-rule results + the aggregate verdict."""

    def __init__(self):
        self.rules: Dict[str, Dict] = {}
        self.started = time.time()

    def add(self, name: str, findings: List[Finding], wall_s: float,
            error: Optional[str] = None) -> None:
        unwaived = [f for f in findings if not f.waived]
        status = ('error' if error is not None
                  else 'violations' if unwaived else 'ok')
        self.rules[name] = {
            'status': status,
            'findings': findings,
            'wall_s': round(wall_s, 3),
            'error': error,
        }

    @property
    def violations(self) -> List[Finding]:
        return [f for r in self.rules.values() for f in r['findings']
                if not f.waived]

    @property
    def waived(self) -> List[Finding]:
        return [f for r in self.rules.values() for f in r['findings']
                if f.waived]

    @property
    def errors(self) -> Dict[str, str]:
        return {n: r['error'] for n, r in self.rules.items()
                if r['error'] is not None}

    @property
    def exit_code(self) -> int:
        if self.errors:
            return EXIT_ERROR
        if self.violations:
            return EXIT_VIOLATIONS
        return EXIT_CLEAN

    def to_dict(self) -> Dict:
        return {
            'schema': SCHEMA,
            'exit_code': self.exit_code,
            'violations': len(self.violations),
            'waived': len(self.waived),
            'wall_s': round(time.time() - self.started, 3),
            'rules': {
                name: {
                    'status': r['status'],
                    'wall_s': r['wall_s'],
                    'error': r['error'],
                    'findings': [f.to_dict() for f in r['findings']],
                }
                for name, r in self.rules.items()
            },
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def format_text(self) -> str:
        lines = []
        for name, r in sorted(self.rules.items()):
            n_viol = sum(1 for f in r['findings'] if not f.waived)
            n_waived = len(r['findings']) - n_viol
            tail = f' ({n_waived} waived)' if n_waived else ''
            lines.append(f"{r['status']:10s} {name:24s} "
                         f"{n_viol} violation(s){tail} [{r['wall_s']:.2f}s]")
            if r['error'] is not None:
                lines.append(f'           ! {r["error"]}')
            for f in r['findings']:
                mark = 'waived' if f.waived else 'FAIL'
                lines.append(f'           {mark}: {f.location}: {f.message}'
                             + (f' (waiver: {f.waive_reason})' if f.waived else ''))
        lines.append(f'analysis: {len(self.violations)} violation(s), '
                     f'{len(self.waived)} waived, exit {self.exit_code}')
        return '\n'.join(lines)
