"""Rule registry + the AnalysisContext every rule runs against.

Three tiers share this spine:

  * **A** — AST/source rules: pure text/AST, no jax work, always cheap;
  * **B** — jaxpr rules: walk traced programs (captured from the perfbudget
    probes, or traced abstractly) before XLA sees them;
  * **C** — compiled-HLO rules: verdicts on the artifacts XLA actually
    emitted — the ground truth GSPMD leaves us (PAPERS.md [2]).

Tier B/C rules declare ``needs_programs``: they consume the jaxprs and
compiled executables the perfbudget probes already lower, captured via
:func:`timm_tpu.perfbudget.probe.capture_programs` so nothing is lowered
twice. ``ctx.ensure_programs()`` lowers on demand only when the caller did
not inject a capture (the CLI path); the tier-1 session fixture injects the
capture it shares with the perf-budget comparisons.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .pragmas import FilePragmas
from .report import Finding, Report

__all__ = ['Rule', 'AnalysisContext', 'register', 'rule', 'all_rules', 'get',
           'select', 'ensure_registered', 'run_analysis',
           'DEFAULT_PROBE_NAMES']

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

# every probe config whose programs feed Tier B/C in a full CLI run: train
# (base), accum trace, tp forward (replicated-residual), serve AOT ladder,
# quant serve, on-device augment, naflex packed step, and elastic resize
DEFAULT_PROBE_NAMES: Tuple[str, ...] = (
    'base', 'accum4', 'tp22', 'serve_test_vit', 'quant_serve_int8',
    'device_augment', 'naflex_packed', 'elastic_resize',
)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    tier: str                      # 'A' | 'B' | 'C'
    description: str
    fn: Callable[['AnalysisContext'], List[Finding]]
    needs_programs: bool = False   # consumes captured probe programs
    needs_devices: int = 1         # minimum jax device count (mesh rules)


_RULES: Dict[str, Rule] = {}
_TIERS = ('A', 'B', 'C')


def register(r: Rule) -> Rule:
    if r.tier not in _TIERS:
        raise ValueError(f'unknown tier {r.tier!r} for rule {r.name!r}')
    if r.name in _RULES:
        raise ValueError(f'rule {r.name!r} already registered')
    _RULES[r.name] = r
    return r


def rule(name: str, tier: str, description: str, **kw):
    """Decorator: register `fn` as a Rule."""
    def deco(fn):
        register(Rule(name=name, tier=tier, description=description,
                      fn=fn, **kw))
        return fn
    return deco


def ensure_registered() -> None:
    from . import hlo_rules, jaxpr_rules, source_rules, zoo  # noqa: F401


def all_rules() -> Tuple[Rule, ...]:
    ensure_registered()
    return tuple(sorted(_RULES.values(), key=lambda r: (r.tier, r.name)))


def get(name: str) -> Rule:
    ensure_registered()
    if name not in _RULES:
        raise KeyError(f'unknown rule {name!r} '
                       f'(known: {sorted(_RULES)})')
    return _RULES[name]


def select(names: Optional[Sequence[str]] = None,
           tiers: Optional[Sequence[str]] = None) -> List[Rule]:
    rules = list(all_rules())
    if names is not None:
        unknown = set(names) - {r.name for r in rules}
        if unknown:
            raise KeyError(f'unknown rule(s): {sorted(unknown)} '
                           f'(known: {sorted(r.name for r in rules)})')
        rules = [r for r in rules if r.name in set(names)]
    if tiers is not None:
        bad = set(tiers) - set(_TIERS)
        if bad:
            raise KeyError(f'unknown tier(s): {sorted(bad)}')
        rules = [r for r in rules if r.tier in set(tiers)]
    return rules


class AnalysisContext:
    """Everything a rule may consult: the source root, parsed pragmas, and
    the captured probe programs (Tier B/C)."""

    def __init__(self, root: Optional[str] = None,
                 programs: Optional[List[Dict]] = None,
                 probe_names: Optional[Sequence[str]] = None,
                 zoo_families: Optional[Sequence[str]] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.root = os.path.abspath(root or REPO_ROOT)
        self.programs = programs
        self.probe_names = tuple(probe_names or DEFAULT_PROBE_NAMES)
        self.zoo_families = tuple(zoo_families) if zoo_families else None
        self.log = log or (lambda msg: None)
        self._pragmas: Dict[str, FilePragmas] = {}
        self._asts: Dict[str, object] = {}

    # ---- source-file access -------------------------------------------------

    def source_dir(self, *rel: str) -> str:
        """`<root>/<rel...>` if it exists, else the root itself — so the same
        rule scans the real package on the repo and a flat directory of
        planted fixtures under tests/."""
        path = os.path.join(self.root, *rel)
        return path if os.path.isdir(path) else self.root

    def source_files(self, *rel: str) -> List[str]:
        d = self.source_dir(*rel)
        return [os.path.join(d, f) for f in sorted(os.listdir(d))
                if f.endswith('.py')]

    def walk_files(self, *rel: str) -> List[str]:
        """All .py files under `<root>/<rel...>` (or the root), recursively."""
        top = self.source_dir(*rel)
        out = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
            out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                       if f.endswith('.py'))
        return out

    def read(self, path: str) -> str:
        with open(path, encoding='utf-8') as f:
            return f.read()

    def pragmas(self, path: str) -> FilePragmas:
        if path not in self._pragmas:
            self._pragmas[path] = FilePragmas(self.read(path), path=path)
        return self._pragmas[path]

    def ast_of(self, path: str):
        """Parsed AST, cached across rules (host-sync and traced-branch walk
        the same trees); None for unparseable files."""
        import ast as ast_mod
        if path not in self._asts:
            try:
                self._asts[path] = ast_mod.parse(self.read(path))
            except SyntaxError:
                self._asts[path] = None
        return self._asts[path]

    def rel(self, path: str) -> str:
        try:
            return os.path.relpath(path, self.root)
        except ValueError:
            return path

    def finding(self, rule_name: str, path: str, line: int,
                message: str) -> Finding:
        """Build a Finding, applying any pragma waiver at (path, line)."""
        reason = self.pragmas(path).waiver_for(rule_name, line)
        return Finding(rule=rule_name, path=self.rel(path), line=line,
                       message=message, waived=reason is not None,
                       waive_reason=reason or '')

    # ---- captured probe programs (Tier B/C) ---------------------------------

    def ensure_programs(self) -> List[Dict]:
        if self.programs is None:
            from ..perfbudget.probe import capture_programs, run_matrix
            self.log(f'analysis: lowering probe programs '
                     f'{",".join(self.probe_names)}')
            with capture_programs() as captured:
                run_matrix(names=list(self.probe_names), log=self.log)
            self.programs = list(captured)
        return self.programs


def run_analysis(ctx: AnalysisContext,
                 rules: Optional[Sequence[Rule]] = None) -> Report:
    """Run `rules` (default: all registered) against `ctx` -> Report.

    A rule that raises is recorded as an internal error (exit 3) — an
    analyzer crash must never read as a clean repo."""
    report = Report()
    for r in (rules if rules is not None else all_rules()):
        t0 = time.perf_counter()
        try:
            findings = list(r.fn(ctx))
            error = None
        except Exception as e:  # noqa: BLE001 - reported as exit-3 error
            findings, error = [], f'{type(e).__name__}: {e}'
        report.add(r.name, findings, time.perf_counter() - t0, error=error)
        ctx.log(f'analysis rule {r.name}: {report.rules[r.name]["status"]} '
                f'({report.rules[r.name]["wall_s"]}s)')
    return report
