"""Tier C — compiled-HLO rules: verdicts on the artifact XLA emitted.

GSPMD makes the compiled program, not the source, the ground truth: a
`donate_argnums` the compiler dropped, a residual it replicated, a constant
it baked — none of those are visible in source or jaxpr. These passes
generalize the one-off checks that caught each of those by hand (PR 8's
dropped donation, PR 6's replicated residual, PR 9's baked batch) into
verdicts over EVERY captured probe program: train, serve AOT ladder, quant,
augment, naflex, and elastic.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from .registry import AnalysisContext, rule
from .report import Finding

__all__ = ['BAKED_CONSTANT_BYTES', 'large_hlo_constants', 'hlo_text']

BAKED_CONSTANT_BYTES = 1 << 20  # 1 MB

# `name = f32[512,1024]{1,0} constant({...})` — dims group empty for scalars
_CONST_RE = re.compile(r'=\s*([a-z]\w*)\[([\d,]*)\][^ ]*\s+constant\(')

_DTYPE_BYTES = {
    'pred': 1, 's8': 1, 'u8': 1, 's4': 1, 'u4': 1,
    's16': 2, 'u16': 2, 'bf16': 2, 'f16': 2,
    's32': 4, 'u32': 4, 'f32': 4,
    's64': 8, 'u64': 8, 'f64': 8, 'c64': 8, 'c128': 16,
}


def hlo_text(compiled) -> str:
    try:
        return compiled.as_text() if hasattr(compiled, 'as_text') else ''
    except Exception:
        return ''


def large_hlo_constants(text: str,
                        threshold: int = BAKED_CONSTANT_BYTES
                        ) -> List[Tuple[int, str]]:
    """(nbytes, 'dtype[dims]') for every HLO constant op over `threshold`."""
    out = []
    for m in _CONST_RE.finditer(text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(','):
            if d:
                n *= int(d)
        nbytes = n * _DTYPE_BYTES[dtype]
        if nbytes > threshold:
            out.append((nbytes, f'{dtype}[{dims}]'))
    return out


def _programs(ctx: AnalysisContext, compiled_only: bool = True) -> List[Dict]:
    return [rec for rec in ctx.ensure_programs()
            if not compiled_only or rec.get('compiled') is not None]


@rule('donation-alias', 'C',
      'donation on the COMPILED artifacts, not donate_argnums presence: '
      'train-style programs must carry a real input_output_alias table; '
      'serve bucket programs must show the donation reached lowering',
      needs_programs=True)
def donation_alias(ctx: AnalysisContext) -> List[Finding]:
    from ..perfbudget.probe import donation_evidence

    findings = []
    checked = 0
    for rec in _programs(ctx, compiled_only=False):
        expect = rec.get('expect', {})
        donation = expect.get('donation')
        if donation == 'alias':
            checked += 1
            ev = donation_evidence(rec['compiled'])
            if ev['aliases'] <= 0:
                findings.append(Finding(
                    'donation-alias', rec['name'], 0,
                    'compiled with an empty input_output_alias table — '
                    'XLA silently dropped the declared donation'))
        elif donation == 'declared':
            checked += 1
            if not expect.get('declared'):
                findings.append(Finding(
                    'donation-alias', rec['name'], 0,
                    'input donation never reached lowering '
                    '(donate_argnums dropped before compile)'))
    if checked == 0:
        findings.append(Finding(
            'donation-alias', '<capture>', 0,
            'no captured program carries a donation expectation — the '
            'probe capture hook is disconnected'))
    return findings


@rule('replicated-residual', 'C',
      'tp forward programs keep the residual stream sharded: the per-device '
      'residual shape appears in the HLO and the full (replicated) shape '
      'never materializes (the PR 6 involuntary-remat regression)',
      needs_programs=True)
def replicated_residual(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    checked = 0
    for rec in _programs(ctx):
        expect = rec.get('expect', {})
        shard = expect.get('expect_shard')
        if not shard:
            continue
        checked += 1
        text = hlo_text(rec['compiled'])
        if shard not in text:
            findings.append(Finding(
                'replicated-residual', rec['name'], 0,
                f'per-device residual shape {shard} missing from the '
                f'compiled HLO — GSPMD is not sharding the residual'))
        forbid = expect.get('forbid_full')
        if forbid and forbid in text:
            findings.append(Finding(
                'replicated-residual', rec['name'], 0,
                f'full residual shape {forbid} materialized in the '
                f'compiled HLO (replicated residual / involuntary remat)'))
    if checked == 0:
        findings.append(Finding(
            'replicated-residual', '<capture>', 0,
            'no captured program carries a residual-sharding expectation — '
            'include the tp forward probe (tp22) in the capture'))
    return findings


@rule('baked-constant', 'C',
      'no compiled probe program embeds a constant > 1 MB — the HLO-level '
      'twin of the Tier B large-literal pass (catches constants XLA '
      'materializes after optimization, not just traced literals)',
      needs_programs=True)
def baked_constant(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for rec in _programs(ctx):
        for nbytes, desc in large_hlo_constants(hlo_text(rec['compiled'])):
            findings.append(Finding(
                'baked-constant', rec['name'], 0,
                f'compiled HLO embeds constant {desc} = '
                f'{nbytes / 1e6:.1f} MB'))
    return findings
