"""The family coverage matrix — ISSUE-20's checked-in sweep artifact.

The zoo sweep (zoo.py) proves every family *traces*; this module proves how
far each family gets through the repo's actual machinery and pins the answer
in ``tests/fixtures/coverage_matrix.json``:

  * ``abstract_trace``        — the zoo gate: eval_shape ctor + abstract fwd
  * ``stage_or_block_scan``   — a scan entry point exists AND at least one
                                block list plans (plan_stage_stack)
  * ``sharded_donated_step``  — ClassificationTask train step lowers on an
                                fsdp=2 mesh with live input_output_alias
  * ``serve_aot``             — InferenceEngine AOT-compiles every bucket
                                with donation declared at lowering
  * ``device_prefetch``       — DevicePrefetcher double-buffers host batches
                                through shard_batch and the forward is finite

The three deep checks compile real programs, so they run only for families
whose representative is small (native size <= DEEP_MAX_SIZE — the test_*
fixtures plus the <=160px families); big-representative families record
``null`` there, and regenerating on a bigger box flips them to real booleans
without a schema change. A ~5-family smoke re-derives its rows in tier-1;
the full matrix re-derives under ``-m slow`` and via the CLI:

    python -m timm_tpu.analysis.coverage            # regenerate the fixture
    python -m timm_tpu.analysis.coverage --check    # recompute + diff, exit 2
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .zoo import family_representative, sweep

__all__ = ['COVERAGE_CHECKS', 'DEEP_CHECKS', 'SMOKE_COVERAGE_FAMILIES',
           'MATRIX_PATH', 'SCHEMA', 'DEEP_MAX_SIZE', 'deep_eligible',
           'scan_capability', 'family_coverage', 'load_matrix', 'write_matrix',
           'diff_matrix']

SCHEMA = 'coverage_matrix/v1'
COVERAGE_CHECKS: Tuple[str, ...] = (
    'abstract_trace', 'stage_or_block_scan', 'sharded_donated_step',
    'serve_aot', 'device_prefetch')
DEEP_CHECKS: Tuple[str, ...] = (
    'sharded_donated_step', 'serve_aot', 'device_prefetch')

# the tier-1 smoke subset: the flat-trunk baseline plus stage-scan families
# across conv (convnext), windowed attention (swin) and BN-conv (regnet)
SMOKE_COVERAGE_FAMILIES: Tuple[str, ...] = (
    'vision_transformer', 'convnext', 'swin_transformer', 'regnet',
    'mlp_mixer')

# deep checks compile the real train/serve programs — only affordable when
# the family representative is small (every test_* fixture model qualifies)
DEEP_MAX_SIZE = 160

_NUM_CLASSES = 10
_BATCH = 2

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
MATRIX_PATH = os.environ.get(
    'TIMM_TPU_COVERAGE_MATRIX',
    os.path.join(_REPO_ROOT, 'tests', 'fixtures', 'coverage_matrix.json'))


def deep_eligible(module: str) -> bool:
    """True when the family's representative is cheap enough to compile the
    deep checks' real programs on the tier-1 CPU topology."""
    _name, size = family_representative(module)
    return size <= DEEP_MAX_SIZE


def _scan_block_lists(model) -> List[list]:
    """Candidate homogeneous-block sequences: each stage's block list for
    hierarchical models (regnet's stages ARE the block lists), else the flat
    trunk ``model.blocks``."""
    lists: List[list] = []
    for attr in ('stages', 'layers'):
        stages = getattr(model, attr, None)
        if stages is None:
            continue
        for st in stages:
            blocks = getattr(st, 'blocks', None)
            if blocks is None:
                try:
                    blocks = list(st)
                except TypeError:
                    continue
            try:
                blocks = list(blocks)
            except TypeError:
                continue
            if blocks:
                lists.append(blocks)
        if lists:
            return lists
    blocks = getattr(model, 'blocks', None)
    if blocks is not None:
        try:
            lists.append(list(blocks))
        except TypeError:
            pass
    return lists


def scan_capability(model) -> bool:
    """True when the model exposes a scan switch AND at least one of its
    block lists actually plans (a switch whose every stage falls back to the
    loop is not coverage)."""
    from ..models._manipulate import BlockStackError, plan_stage_stack

    if not (hasattr(model, 'set_stage_scan') or hasattr(model, 'set_block_scan')):
        return False
    for blocks in _scan_block_lists(model):
        try:
            plan_stage_stack(blocks)
            return True
        except BlockStackError:
            continue
    return False


def _abstract_scan_check(name: str) -> Tuple[bool, Optional[str]]:
    from flax import nnx

    import timm_tpu

    try:
        model = nnx.eval_shape(
            lambda: timm_tpu.create_model(name, num_classes=_NUM_CLASSES))
        return scan_capability(model), None
    except Exception as e:  # noqa: BLE001 - per-family reporting
        return False, f'{type(e).__name__}: {e}'


def _deep_checks(name: str, size: int, log=None) -> Dict[str, object]:
    """The three compile-for-real checks for one family representative.
    Each check is independently try/excepted: one family's missing subsystem
    records `false` + an error note instead of aborting the sweep."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    import timm_tpu
    from ..data.loader import DevicePrefetcher
    from ..optim import create_optimizer_v2
    from ..parallel import create_mesh, set_global_mesh, shard_batch
    from ..perfbudget.probe import donation_evidence
    from ..serve import InferenceEngine
    from ..task import ClassificationTask

    out: Dict[str, object] = {}
    rng = np.random.RandomState(0)

    # -- sharded donated step: fsdp=2 over a 2-device sub-mesh --------------
    try:
        mesh = create_mesh(devices=jax.devices()[:2], fsdp=2)
        set_global_mesh(mesh)
        model = timm_tpu.create_model(name, num_classes=_NUM_CLASSES)
        task = ClassificationTask(
            model, optimizer=create_optimizer_v2(model, opt='adamw', lr=0.1),
            mesh=mesh)
        batch = shard_batch(
            {'input': jnp.asarray(rng.rand(_BATCH, size, size, 3), jnp.float32),
             'target': jnp.asarray(rng.randint(0, _NUM_CLASSES, _BATCH))}, mesh)
        compiled = task.lower_train_step(batch, lr=0.1)
        out['sharded_donated_step'] = donation_evidence(compiled)['aliases'] > 0
    except Exception as e:  # noqa: BLE001
        out['sharded_donated_step'] = False
        out['sharded_donated_step_error'] = f'{type(e).__name__}: {e}'

    # -- serve AOT bucket + device prefetch: single-device mesh -------------
    set_global_mesh(create_mesh(devices=jax.devices()[:1]))
    try:
        eng = InferenceEngine(buckets=(_BATCH,))
        eng.add_model(name, num_classes=_NUM_CLASSES)
        exes = eng.aot_executables(name)
        report = eng.donation_report(name)
        out['serve_aot'] = (set(exes) == {_BATCH}
                            and all(r.get('declared') for r in report.values()))
    except Exception as e:  # noqa: BLE001
        out['serve_aot'] = False
        out['serve_aot_error'] = f'{type(e).__name__}: {e}'

    try:
        model = timm_tpu.create_model(name, num_classes=_NUM_CLASSES)
        model.eval()
        graphdef, state = nnx.split(model)
        fwd = jax.jit(lambda s, x: nnx.merge(graphdef, s)(x))
        host = [{'input': np.asarray(rng.rand(_BATCH, size, size, 3), np.float32)}
                for _ in range(2)]
        seen, finite = 0, True
        for dev_batch in DevicePrefetcher(host):
            seen += 1
            finite = finite and bool(jnp.isfinite(fwd(state, dev_batch['input'])).all())
        out['device_prefetch'] = finite and seen == len(host)
    except Exception as e:  # noqa: BLE001
        out['device_prefetch'] = False
        out['device_prefetch_error'] = f'{type(e).__name__}: {e}'

    if log is not None:
        log(f'coverage deep {name}@{size}: ' + ' '.join(
            f'{c}={out.get(c)}' for c in DEEP_CHECKS))
    return out


def family_coverage(families: Optional[Sequence[str]] = None,
                    deep: Optional[bool] = None,
                    log=None) -> Dict[str, Dict]:
    """{module: row} for the requested families (default: every registered
    family). `deep=None` auto-selects (representative <= DEEP_MAX_SIZE);
    True/False force the deep checks on/off. Shallow rows carry ``null`` for
    the deep checks — distinct from a measured `false`."""
    import jax

    import timm_tpu
    from ..parallel import mesh as mesh_mod

    modules = list(families or timm_tpu.list_modules())
    zoo = {r['module']: r for r in sweep(families=modules)}

    rows: Dict[str, Dict] = {}
    saved_mesh = mesh_mod.peek_global_mesh()
    try:
        for module in modules:
            name, size = family_representative(module)
            z = zoo[module]
            run_deep = (size <= DEEP_MAX_SIZE) if deep is None else bool(deep)
            if run_deep and jax.device_count() < 2:
                raise RuntimeError(
                    'deep coverage checks need >=2 devices (fsdp=2 mesh): run '
                    'under XLA_FLAGS=--xla_force_host_platform_device_count=8 '
                    'or pass deep=False')
            row: Dict[str, object] = {
                'model': name, 'img_size': size, 'deep': run_deep,
                'abstract_trace': bool(z['ok']),
            }
            if not z['ok']:
                row['abstract_trace_error'] = z.get('error', 'failed')
            ok, err = _abstract_scan_check(name)
            row['stage_or_block_scan'] = ok
            if err:
                row['stage_or_block_scan_error'] = err
            if run_deep:
                row.update(_deep_checks(name, size, log=log))
            else:
                row.update({c: None for c in DEEP_CHECKS})
            rows[module] = row
            if log is not None:
                log(f'coverage {module}: {name}@{size} ' + ' '.join(
                    f'{c}={row[c]}' for c in COVERAGE_CHECKS))
    finally:
        mesh_mod._GLOBAL_MESH = saved_mesh
    return rows


# ---- the checked-in artifact ------------------------------------------------

def write_matrix(rows: Dict[str, Dict], path: Optional[str] = None) -> Dict:
    path = path or MATRIX_PATH
    doc = {
        'schema': SCHEMA,
        'note': 'per-family machinery coverage; regenerate via '
                'python -m timm_tpu.analysis.coverage',
        'checks': list(COVERAGE_CHECKS),
        'families': {m: rows[m] for m in sorted(rows)},
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(doc, f, indent=1)
        f.write('\n')
    os.replace(tmp, path)
    return doc


def load_matrix(path: Optional[str] = None) -> Dict:
    path = path or MATRIX_PATH
    with open(path) as f:
        doc = json.load(f)
    if doc.get('schema') != SCHEMA:
        raise ValueError(f'{path}: unexpected coverage schema '
                         f'{doc.get("schema")!r} (want {SCHEMA!r})')
    return doc


def diff_matrix(fixture_rows: Dict[str, Dict], live_rows: Dict[str, Dict],
                checks: Sequence[str] = COVERAGE_CHECKS) -> List[str]:
    """Compare live per-check booleans against the checked-in rows (only the
    check keys — error notes and sizes don't gate). Returns human-readable
    mismatch lines; empty = the matrix still matches reality."""
    problems: List[str] = []
    for module, live in sorted(live_rows.items()):
        pinned = fixture_rows.get(module)
        if pinned is None:
            problems.append(f'{module}: missing from the checked-in matrix')
            continue
        for check in checks:
            if pinned.get(check) != live.get(check):
                problems.append(
                    f'{module}.{check}: checked-in {pinned.get(check)} '
                    f'!= live {live.get(check)} '
                    f'({live.get(check + "_error", "no error recorded")})')
    return problems


def main(argv=None) -> int:
    import argparse
    import subprocess
    import sys

    parser = argparse.ArgumentParser(prog='python -m timm_tpu.analysis.coverage')
    parser.add_argument('--out', default=None,
                        help=f'matrix path (default {MATRIX_PATH})')
    parser.add_argument('--families', default='',
                        help='comma-separated family subset (default: all)')
    parser.add_argument('--no-deep', action='store_true',
                        help='skip the compile-for-real checks everywhere')
    parser.add_argument('--check', action='store_true',
                        help='recompute and diff against the checked-in matrix '
                             'instead of writing; exit 2 on mismatch')
    args = parser.parse_args(argv)

    import jax
    if jax.device_count() < 8 and not os.environ.get('TIMM_TPU_COVERAGE_REEXEC'):
        env = dict(os.environ)
        flags = env.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' not in flags:
            env['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=8').strip()
        env.setdefault('JAX_PLATFORMS', 'cpu')
        env['TIMM_TPU_COVERAGE_REEXEC'] = '1'
        return subprocess.call(
            [sys.executable, '-m', 'timm_tpu.analysis.coverage']
            + list(sys.argv[1:] if argv is None else argv), env=env)

    families = [f.strip() for f in args.families.split(',') if f.strip()] or None
    rows = family_coverage(families=families,
                           deep=False if args.no_deep else None,
                           log=lambda m: print(m, file=sys.stderr, flush=True))
    if args.check:
        doc = load_matrix(args.out)
        problems = diff_matrix(doc['families'], rows)
        if problems:
            print('\n'.join(problems))
            return 2
        print(f'coverage matrix matches reality ({len(rows)} families)')
        return 0
    path = args.out or MATRIX_PATH
    write_matrix(rows, path)
    deep_rows = [m for m, r in rows.items() if r['deep']]
    green = [m for m in deep_rows
             if all(rows[m][c] for c in COVERAGE_CHECKS)]
    print(f'coverage: {len(rows)} families -> {path} '
          f'({len(deep_rows)} deep, {len(green)} fully green)')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
