"""Tier B — jaxpr rules: the traced program, before XLA sees it.

* ``large-literal`` — walks the jaxprs of every program the perfbudget
  probes lower and fails on any baked constant > 1 MB. This is the PR 9
  landmine (a 19 MB uint8 batch closed over into the compiled augment
  program) as a pass instead of a memory.
* ``dtype-promotion`` — audits the canonical softmax program under a
  declared-bf16 policy: the exp/div pipeline must stay in the declared
  dtype (the f32 max-subtraction is the one allowed upcast — it is
  stop-gradient'd and numerically load-bearing).
"""
from __future__ import annotations

import importlib.util
import os
from typing import Iterable, List, Optional, Tuple

import numpy as np

from .pragmas import FilePragmas
from .registry import AnalysisContext, rule
from .report import Finding

__all__ = ['LARGE_LITERAL_BYTES', 'large_literals', 'unintended_upcasts',
           'scan_module_program']

LARGE_LITERAL_BYTES = 1 << 20  # 1 MB


def _jaxpr_of(closed):
    return getattr(closed, 'jaxpr', closed)


def _consts_of(closed):
    return getattr(closed, 'consts', ()) or ()


def _sub_jaxprs(params) -> Iterable:
    for v in params.values():
        for item in (v if isinstance(v, (list, tuple)) else (v,)):
            if hasattr(item, 'eqns') or hasattr(item, 'jaxpr'):
                yield item


def _iter_constants(closed, _seen=None) -> Iterable[object]:
    """Every baked array in a (Closed)Jaxpr: top-level consts, eqn literals,
    and everything the same way down in sub-jaxprs (scan/cond/pjit bodies)."""
    if _seen is None:
        _seen = set()
    if id(closed) in _seen:
        return
    _seen.add(id(closed))
    yield from _consts_of(closed)
    jaxpr = _jaxpr_of(closed)
    for eqn in getattr(jaxpr, 'eqns', ()):
        for invar in eqn.invars:
            val = getattr(invar, 'val', None)
            if val is not None:
                yield val
        yield from (c for sub in _sub_jaxprs(eqn.params)
                    for c in _iter_constants(sub, _seen))


def large_literals(closed,
                   threshold: int = LARGE_LITERAL_BYTES
                   ) -> List[Tuple[int, str]]:
    """(nbytes, 'dtype[shape]') for every baked constant over `threshold`."""
    out = []
    for val in _iter_constants(closed):
        arr = np.asarray(val) if not hasattr(val, 'nbytes') else val
        nbytes = int(getattr(arr, 'nbytes', 0))
        if nbytes > threshold:
            shape = 'x'.join(map(str, getattr(arr, 'shape', ())))
            out.append((nbytes, f'{getattr(arr, "dtype", "?")}[{shape}]'))
    return out


@rule('large-literal', 'B',
      'no program the perfbudget probes lower may close over a baked '
      'constant > 1 MB — big arrays must arrive as arguments (donatable, '
      'shardable), never as compiled-in literals (the PR 9 landmine)',
      needs_programs=True)
def large_literal(ctx: AnalysisContext) -> List[Finding]:
    findings = []
    for rec in ctx.ensure_programs():
        if rec.get('jaxpr') is None:
            continue
        for nbytes, desc in large_literals(rec['jaxpr']):
            findings.append(Finding(
                'large-literal', rec['name'], 0,
                f'baked constant {desc} = {nbytes / 1e6:.1f} MB in the '
                f'traced program (pass it as an argument instead)'))
    return findings


def scan_module_program(path: str,
                        threshold: int = LARGE_LITERAL_BYTES
                        ) -> List[Finding]:
    """Fixture entry point: load a module file defining ``program`` and
    ``example_args()``, trace it, and run the large-literal check with the
    module's own pragmas honored (file-wide waivers apply)."""
    import jax

    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(f'_timm_tpu_lint_{name}', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    closed = jax.make_jaxpr(mod.program)(*mod.example_args())
    with open(path, encoding='utf-8') as f:
        pragmas = FilePragmas(f.read(), path=path)
    reason = pragmas.waiver_for('large-literal')
    return [Finding('large-literal', path, 0,
                    f'baked constant {desc} = {nbytes / 1e6:.1f} MB',
                    waived=reason is not None, waive_reason=reason or '')
            for nbytes, desc in large_literals(closed, threshold)]


# ---- dtype-promotion --------------------------------------------------------

_AUDITED_PRIMS = ('exp', 'div')


def unintended_upcasts(closed, declared: str = 'bfloat16'
                       ) -> List[Tuple[str, str]]:
    """(prim, dtype) for every exp/div equation whose OUTPUT left the
    declared dtype — in a declared-bf16 softmax region only the
    max-subtraction may run f32; the exp/div pipeline staying f32 means the
    policy lever silently disconnected."""
    out = []

    def walk(c, seen):
        if id(c) in seen:
            return
        seen.add(id(c))
        jaxpr = _jaxpr_of(c)
        for eqn in getattr(jaxpr, 'eqns', ()):
            prim = getattr(eqn.primitive, 'name', str(eqn.primitive))
            if prim in _AUDITED_PRIMS:
                for outvar in eqn.outvars:
                    dt = str(getattr(outvar.aval, 'dtype', ''))
                    if dt and dt != declared:
                        out.append((prim, dt))
            for sub in _sub_jaxprs(eqn.params):
                walk(sub, seen)

    walk(closed, set())
    return out


def audit_softmax_policy(fn=None, args=None,
                         declared: str = 'bfloat16') -> List[Finding]:
    """Trace `fn(*args)` (default: the canonical softmax_with_policy
    program) under a declared-bf16 softmax policy and report upcasts."""
    import jax
    import jax.numpy as jnp

    from ..layers import config as layer_config

    if fn is None:
        fn = layer_config.softmax_with_policy
        args = (jnp.zeros((2, 4, 16, 16), jnp.bfloat16),)
    with layer_config.set_softmax_dtype(declared):
        closed = jax.make_jaxpr(fn)(*args)
    return [Finding('dtype-promotion', getattr(fn, '__name__', 'program'), 0,
                    f'`{prim}` ran in {dt} inside a declared-{declared} '
                    f'softmax region (policy upcast leak)')
            for prim, dt in unintended_upcasts(closed, declared)]


@rule('dtype-promotion', 'B',
      'under a declared-bf16 softmax policy the exp/div pipeline stays '
      'bf16 (the f32 max-subtraction is the one allowed upcast) — a stray '
      'upcast means TIMM_TPU_SOFTMAX_DTYPE silently disconnected')
def dtype_promotion(ctx: AnalysisContext) -> List[Finding]:
    return audit_softmax_policy()
