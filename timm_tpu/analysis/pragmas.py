"""The unified waiver pragma + back-compat shims.

Unified syntax (any source line)::

    # timm-tpu-lint: disable=<rule>[,<rule2>] <reason>

Placement decides scope:

  * trailing on a code line       -> waives findings anchored to THAT line;
  * on its own comment line       -> waives findings on the NEXT line;
  * within the first 5 file lines -> waives the rule file-wide.

A reason is mandatory — a reasonless pragma waives nothing and is itself a
finding (rule ``pragma-syntax``), so waivers can't silently accrete.

Back-compat shims (pre-existing waiver spellings, kept verbatim so no
call-site churn was needed when the lints moved out of tests/):

  * ``# no-donate: <reason>``          == disable=donation-declared
  * ``# no-kernel-registry: <reason>`` == disable=kernel-registered
    (first 5 lines of a kernel module, exactly as before)
"""
from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ['FilePragmas', 'PRAGMA_PREFIX', 'SHIMS', 'MODULE_SCOPE_LINES']

PRAGMA_PREFIX = '# timm-tpu-lint:'
MODULE_SCOPE_LINES = 5

_PRAGMA_RE = re.compile(r'#\s*timm-tpu-lint:\s*(.*)$')
_DISABLE_RE = re.compile(r'disable=([\w,.-]+)\s*(.*)$', re.DOTALL)

# shim comment prefix -> rule it waives (same scoping as the unified pragma)
SHIMS = {
    '# no-donate:': 'donation-declared',
    '# no-kernel-registry:': 'kernel-registered',
}


class FilePragmas:
    """Parsed waivers for one source file's text."""

    def __init__(self, text: str, path: str = '<text>'):
        self.path = path
        # lineno -> {rule: reason}
        self.line_waivers: Dict[int, Dict[str, str]] = {}
        self.module_waivers: Dict[str, str] = {}
        # (lineno, message) — fed to the pragma-syntax rule
        self.malformed: List[Tuple[int, str]] = []
        self._parse(text)

    def _record(self, lineno: int, standalone: bool, rules: List[str],
                reason: str) -> None:
        if lineno <= MODULE_SCOPE_LINES:
            for r in rules:
                self.module_waivers.setdefault(r, reason)
            return
        target = lineno + 1 if standalone else lineno
        slot = self.line_waivers.setdefault(target, {})
        for r in rules:
            slot.setdefault(r, reason)

    @staticmethod
    def _iter_comments(text: str) -> Iterable[Tuple[int, str]]:
        """(lineno, comment_text) for every REAL comment token — pragma
        spellings inside strings/docstrings are not pragmas."""
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable (partial fixture files): raw line scan fallback
            for lineno, line in enumerate(text.splitlines(), 1):
                idx = line.find('#')
                if idx >= 0:
                    yield lineno, line[idx:]

    def _parse(self, text: str) -> None:
        lines = text.splitlines()
        for lineno, line in self._iter_comments(text):
            src = lines[lineno - 1] if lineno <= len(lines) else line
            standalone = src.strip().startswith('#')
            m = _PRAGMA_RE.search(line)
            if m:
                body = m.group(1).strip()
                dm = _DISABLE_RE.match(body)
                if not dm:
                    self.malformed.append(
                        (lineno, f'malformed pragma (expected '
                                 f'"disable=<rule> <reason>"): {line.strip()}'))
                    continue
                rules = [r for r in dm.group(1).split(',') if r]
                reason = dm.group(2).strip()
                if not reason:
                    self.malformed.append(
                        (lineno, f'pragma waives {",".join(rules)} without a '
                                 f'reason — reasons are mandatory'))
                    continue
                self._record(lineno, standalone, rules, reason)
                continue
            for prefix, rule in SHIMS.items():
                idx = line.find(prefix)
                if idx < 0:
                    continue
                reason = line[idx + len(prefix):].strip()
                if not reason:
                    self.malformed.append(
                        (lineno, f'{prefix!r} waiver without a reason'))
                    continue
                self._record(lineno, standalone, [rule], reason)

    def waiver_for(self, rule: str, lineno: int = 0) -> Optional[str]:
        """Reason string if `rule` is waived at `lineno` (or file-wide)."""
        if lineno and rule in self.line_waivers.get(lineno, ()):
            return self.line_waivers[lineno][rule]
        return self.module_waivers.get(rule)
