"""timm_tpu.analysis — the unified static-analysis suite.

One rule registry, three analyzer tiers, one report/waiver/CLI spine:

  * **Tier A** (source/AST): donation-declared, partition-rules,
    kernel-registered, fp32-softmax, silent-except, host-sync,
    traced-branch, pragma-syntax;
  * **Tier B** (jaxpr): large-literal (>1 MB baked constants in traced
    programs), dtype-promotion, zoo-abstract-trace;
  * **Tier C** (compiled HLO): donation-alias, replicated-residual,
    baked-constant — verdicts over every captured perfbudget probe program.

Waivers use ``# timm-tpu-lint: disable=<rule> <reason>`` (pragmas.py; the
historical ``# no-donate:`` / ``# no-kernel-registry:`` spellings still
work). CLI: ``python -m timm_tpu.analysis [--rules ...] [--json out.json]``
— exit 0 clean / 2 violations / 3 internal error.
"""
from .pragmas import FilePragmas
from .registry import (
    AnalysisContext, DEFAULT_PROBE_NAMES, Rule, all_rules, ensure_registered,
    get, register, rule, run_analysis, select,
)
from .report import (
    EXIT_CLEAN, EXIT_ERROR, EXIT_VIOLATIONS, Finding, Report,
)

__all__ = [
    'AnalysisContext', 'DEFAULT_PROBE_NAMES', 'FilePragmas', 'Finding',
    'Report', 'Rule', 'EXIT_CLEAN', 'EXIT_ERROR', 'EXIT_VIOLATIONS',
    'all_rules', 'ensure_registered', 'get', 'register', 'rule',
    'run_analysis', 'select',
]
