"""The whole-zoo abstract-trace sweep — ROADMAP item 5's first
model-agnostic gate.

For every registered family, pick one representative, construct it under
``nnx.eval_shape`` (no parameter arrays allocated) and push an abstract
batch through ``jax.eval_shape`` (no compiles). A family that cannot even
trace — a constructor kwarg mismatch, a shape bug at its native input size
— fails here in milliseconds instead of hiding behind `-m slow`. This
sweep is exactly how the res2net/resnest/sknet `aa_layer` constructor bug
was found: those families only ever ran under `-m slow`, so tier-1 never
built them.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .registry import AnalysisContext, rule
from .report import Finding

__all__ = ['family_representative', 'sweep', 'SMOKE_FAMILIES',
           'SIZE_OVERRIDES']

# families cheap enough for the tier-1 smoke (full sweep: CLI + -m slow);
# swin + metaformer keep the hierarchical stage-scan families represented
# alongside convnext, per ISSUE 20
SMOKE_FAMILIES: Tuple[str, ...] = (
    'vision_transformer', 'resnet', 'convnext', 'naflexvit', 'mlp_mixer',
    'swin_transformer', 'metaformer',
)

# native-input-size overrides where the default cfg size cannot trace:
# halo attention needs its block/halo grid, efficientformer's attention
# bias table is built for the 224px stage-4 resolution
SIZE_OVERRIDES: Dict[str, int] = {
    'halonet26t': 256,
    'efficientformer_l1': 224,
}

_NUM_CLASSES = 10
_BATCH = 2


def family_representative(module: str) -> Tuple[str, int]:
    """(model_name, img_size) for one family: prefer the test_* fixture
    model, else the first registered name; size from the pretrained cfg."""
    import timm_tpu
    from ..models._registry import get_pretrained_cfg

    names = timm_tpu.list_models(module=module)
    if not names:
        raise ValueError(f'family {module!r} registers no models')
    test = [n for n in names if n.startswith('test_')]
    name = test[0] if test else names[0]
    if name in SIZE_OVERRIDES:
        return name, SIZE_OVERRIDES[name]
    cfg = get_pretrained_cfg(name)
    size = getattr(cfg, 'input_size', None)
    return name, int(size[-1]) if size else 224


def sweep(families: Optional[Sequence[str]] = None,
          log=None) -> List[Dict]:
    """Abstract-trace every family -> [{'module', 'model', 'img_size',
    'ok', 'out_shape' | 'error'}]. No arrays, no compiles."""
    import jax
    import jax.numpy as jnp
    from flax import nnx

    import timm_tpu

    records = []
    for module in (families or timm_tpu.list_modules()):
        name, size = family_representative(module)
        rec: Dict = {'module': module, 'model': name, 'img_size': size}
        try:
            model = nnx.eval_shape(
                lambda n=name: timm_tpu.create_model(n, num_classes=_NUM_CLASSES))
            model.eval()
            graphdef, state = nnx.split(model)
            out = jax.eval_shape(
                lambda s, x: nnx.merge(graphdef, s)(x), state,
                jax.ShapeDtypeStruct((_BATCH, size, size, 3), jnp.float32))
            rec['out_shape'] = tuple(out.shape)
            rec['ok'] = tuple(out.shape) == (_BATCH, _NUM_CLASSES)
            if not rec['ok']:
                rec['error'] = (f'abstract forward returned {rec["out_shape"]}, '
                                f'expected ({_BATCH}, {_NUM_CLASSES})')
        except Exception as e:  # noqa: BLE001 - each family reports its own failure
            rec['ok'] = False
            rec['error'] = f'{type(e).__name__}: {e}'
        records.append(rec)
        if log is not None:
            status = 'ok' if rec['ok'] else f'FAIL {rec["error"]}'
            log(f'zoo {module}: {name}@{size} {status}')
    return records


@rule('zoo-abstract-trace', 'B',
      'every registered family constructs under nnx.eval_shape and its '
      'representative abstract-forwards to (B, num_classes) at its native '
      'input size — no arrays, no compiles (ROADMAP item 5 gate)')
def zoo_abstract_trace(ctx: AnalysisContext) -> List[Finding]:
    records = sweep(families=ctx.zoo_families, log=ctx.log)
    return [Finding('zoo-abstract-trace', f'{r["module"]}:{r["model"]}', 0,
                    r.get('error', 'failed'))
            for r in records if not r['ok']]
