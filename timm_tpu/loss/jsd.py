"""Jensen-Shannon divergence loss for AugMix (reference: timm/loss/jsd.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .cross_entropy import cross_entropy

__all__ = ['JsdCrossEntropy']


class JsdCrossEntropy:
    """CE on the clean split + JSD consistency across aug splits
    (reference jsd.py:10)."""

    def __init__(self, num_splits: int = 3, alpha: float = 12.0, smoothing: float = 0.1):
        self.num_splits = num_splits
        self.alpha = alpha
        self.smoothing = smoothing or 0.0

    def __call__(self, output, target):
        split_size = output.shape[0] // self.num_splits
        logits_split = jnp.split(output, self.num_splits, axis=0)

        loss = cross_entropy(logits_split[0], target[:split_size], smoothing=self.smoothing)
        probs = [jax.nn.softmax(l.astype(jnp.float32), axis=-1) for l in logits_split]
        mix = jnp.clip(sum(probs) / len(probs), 1e-7, 1.0)
        logp_mixture = jnp.log(mix)
        kl = sum((p * (jnp.log(jnp.clip(p, 1e-7, 1.0)) - logp_mixture)).sum(axis=-1).mean() for p in probs)
        loss = loss + self.alpha * kl / len(probs)
        return loss
