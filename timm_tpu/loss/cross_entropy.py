"""Cross-entropy losses (reference: timm/loss/cross_entropy.py).

Losses are stateless callables: `loss = fn(logits, target)` returning a
scalar mean over the batch. Integer targets are class indices; float targets
of shape (B, C) are soft distributions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ['LabelSmoothingCrossEntropy', 'SoftTargetCrossEntropy', 'cross_entropy']


def cross_entropy(logits, target, smoothing: float = 0.0):
    """CE over (B, C) logits; target (B,) int or (B, C) soft."""
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if target.ndim == logits.ndim:
        loss = -(target * logprobs).sum(axis=-1)
    else:
        nll = -jnp.take_along_axis(logprobs, target[:, None], axis=-1)[:, 0]
        if smoothing > 0.0:
            smooth = -logprobs.mean(axis=-1)
            loss = (1.0 - smoothing) * nll + smoothing * smooth
        else:
            loss = nll
    return loss.mean()


class LabelSmoothingCrossEntropy:
    """NLL w/ uniform label smoothing (reference cross_entropy.py:11)."""

    def __init__(self, smoothing: float = 0.1):
        assert smoothing < 1.0
        self.smoothing = smoothing

    def __call__(self, x, target):
        return cross_entropy(x, target, smoothing=self.smoothing)


class SoftTargetCrossEntropy:
    """CE against a soft target distribution (reference cross_entropy.py:29)."""

    def __call__(self, x, target):
        logprobs = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        loss = -(target * logprobs).sum(axis=-1)
        return loss.mean()
