"""BCE w/ soft-target support (reference: timm/loss/binary_cross_entropy.py)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ['BinaryCrossEntropy']


class BinaryCrossEntropy:
    """BCE-with-logits treating dense targets, w/ smoothing, thresholding,
    optional sum-mode and pos_weight (reference binary_cross_entropy.py:14)."""

    def __init__(
            self,
            smoothing: float = 0.1,
            target_threshold: Optional[float] = None,
            weight=None,
            reduction: str = 'mean',
            sum_classes: bool = False,
            pos_weight=None,
    ):
        assert 0.0 <= smoothing < 1.0
        self.smoothing = smoothing
        self.target_threshold = target_threshold
        self.reduction = 'none' if sum_classes else reduction
        self.sum_classes = sum_classes
        self.weight = weight
        self.pos_weight = pos_weight

    def __call__(self, x, target):
        batch_size = x.shape[0]
        num_classes = x.shape[-1]
        if target.ndim == 1:
            # dense int targets → one-hot w/ smoothing values
            off_value = self.smoothing / num_classes
            on_value = 1.0 - self.smoothing + off_value
            target = jax.nn.one_hot(target, num_classes) * (on_value - off_value) + off_value
        # dense (B, C) targets are assumed pre-softened upstream (mixup/cutmix);
        # the reference never re-smooths them (binary_cross_entropy.py:41)
        if self.target_threshold is not None:
            target = (target > self.target_threshold).astype(x.dtype)

        x = x.astype(jnp.float32)
        target = target.astype(jnp.float32)
        log_p = jax.nn.log_sigmoid(x)
        log_not_p = jax.nn.log_sigmoid(-x)
        if self.pos_weight is not None:
            loss = -(self.pos_weight * target * log_p + (1.0 - target) * log_not_p)
        else:
            loss = -(target * log_p + (1.0 - target) * log_not_p)
        if self.weight is not None:
            loss = loss * self.weight

        if self.sum_classes:
            return loss.sum(axis=-1).mean()
        if self.reduction == 'mean':
            return loss.mean()
        if self.reduction == 'sum':
            return loss.sum()
        return loss
