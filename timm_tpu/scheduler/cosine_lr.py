"""Cosine decay w/ warmup, cycles, k-decay (reference: timm/scheduler/cosine_lr.py)."""
from __future__ import annotations

import math
from typing import List

from .scheduler import Scheduler

__all__ = ['CosineLRScheduler']


class CosineLRScheduler(Scheduler):
    def __init__(
            self,
            base_lr,
            t_initial: int,
            lr_min: float = 0.0,
            cycle_mul: float = 1.0,
            cycle_decay: float = 1.0,
            cycle_limit: int = 1,
            warmup_t: int = 0,
            warmup_lr_init: float = 0.0,
            warmup_prefix: bool = False,
            t_in_epochs: bool = True,
            k_decay: float = 1.0,
            initialize: bool = True,
            **kwargs,
    ):
        super().__init__(base_lr, initialize=initialize, **kwargs)
        assert t_initial > 0
        self.t_initial = t_initial
        self.lr_min = lr_min
        self.cycle_mul = cycle_mul
        self.cycle_decay = cycle_decay
        self.cycle_limit = cycle_limit
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_prefix = warmup_prefix
        self.t_in_epochs = t_in_epochs
        self.k_decay = k_decay
        if self.warmup_t:
            self.warmup_steps = [(v - warmup_lr_init) / self.warmup_t for v in self.base_values]
        else:
            self.warmup_steps = [1 for _ in self.base_values]

    def _get_lr(self, t: int) -> List[float]:
        if t < self.warmup_t:
            return [self.warmup_lr_init + t * s for s in self.warmup_steps]
        if self.warmup_prefix:
            t = t - self.warmup_t
        if self.cycle_mul != 1:
            i = math.floor(math.log(1 - t / self.t_initial * (1 - self.cycle_mul), self.cycle_mul))
            t_i = self.cycle_mul ** i * self.t_initial
            t_curr = t - (1 - self.cycle_mul ** i) / (1 - self.cycle_mul) * self.t_initial
        else:
            i = t // self.t_initial
            t_i = self.t_initial
            t_curr = t - (self.t_initial * i)

        gamma = self.cycle_decay ** i
        lr_max_values = [v * gamma for v in self.base_values]
        k = self.k_decay

        if i < self.cycle_limit:
            return [
                self.lr_min + 0.5 * (lr_max - self.lr_min) * (
                    1 + math.cos(math.pi * t_curr ** k / t_i ** k))
                for lr_max in lr_max_values
            ]
        return [self.lr_min for _ in self.base_values]

    def get_cycle_length(self, cycles: int = 0) -> int:
        cycles = max(1, cycles or self.cycle_limit)
        if self.cycle_mul == 1.0:
            t = self.t_initial * cycles
        else:
            t = int(math.floor(-self.t_initial * (self.cycle_mul ** cycles - 1) / (1 - self.cycle_mul)))
        return t + self.warmup_t if self.warmup_prefix else t
