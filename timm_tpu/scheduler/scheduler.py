"""Scheduler base (reference: timm/scheduler/scheduler.py:8-127).

TPU-first design: schedulers are host-side objects producing a scalar LR that
is passed into the jitted train step as an argument each update — LR is data,
not code, so no recompilation and full parity with the reference's
per-epoch `step()` / per-update `step_update()` semantics (incl. metric-driven
plateau scheduling, which cannot be a pure function of step).
"""
from __future__ import annotations

import abc
import math
import random
from typing import Any, Dict, List, Optional, Union

__all__ = ['Scheduler']


class Scheduler(abc.ABC):
    def __init__(
            self,
            base_lr: Union[float, List[float]],
            noise_range_t=None,
            noise_type: str = 'normal',
            noise_pct: float = 0.67,
            noise_std: float = 1.0,
            noise_seed: Optional[int] = None,
            initialize: bool = True,
    ):
        self.base_values = [base_lr] if not isinstance(base_lr, (list, tuple)) else list(base_lr)
        self.noise_range_t = noise_range_t
        self.noise_pct = noise_pct
        self.noise_type = noise_type
        self.noise_std = noise_std
        self.noise_seed = noise_seed if noise_seed is not None else 42
        self.metric = None
        self._last_values = list(self.base_values)

    @abc.abstractmethod
    def _get_lr(self, t: int) -> List[float]:
        ...

    def _get_values(self, t: int, on_epoch: bool = True) -> Optional[List[float]]:
        proceed = (on_epoch and self.t_in_epochs) or (not on_epoch and not self.t_in_epochs)
        if not proceed:
            return None
        return self._get_lr(t)

    def step(self, epoch: int, metric: Optional[float] = None) -> List[float]:
        self.metric = metric
        values = self._get_values(epoch, on_epoch=True)
        if values is not None:
            values = self._add_noise(values, epoch)
            self._last_values = values
        return self._last_values

    def step_update(self, num_updates: int, metric: Optional[float] = None) -> List[float]:
        self.metric = metric
        values = self._get_values(num_updates, on_epoch=False)
        if values is not None:
            values = self._add_noise(values, num_updates)
            self._last_values = values
        return self._last_values

    def get_last_lr(self) -> List[float]:
        return self._last_values

    @property
    def last_lr(self) -> float:
        return self._last_values[0]

    def state_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def load_state_dict(self, state_dict: Dict[str, Any]):
        self.__dict__.update(state_dict)

    def _is_apply_noise(self, t: int) -> bool:
        if self.noise_range_t is None:
            return False
        if isinstance(self.noise_range_t, (list, tuple)):
            return self.noise_range_t[0] <= t < self.noise_range_t[1]
        return t >= self.noise_range_t

    def _calculate_noise(self, t: int) -> float:
        g = random.Random(self.noise_seed + t)
        if self.noise_type == 'normal':
            while True:
                noise = g.gauss(0, self.noise_std)
                if abs(noise) < self.noise_pct:
                    return noise
        return 2 * (g.random() - 0.5) * self.noise_pct

    def _add_noise(self, lrs: List[float], t: int) -> List[float]:
        if self._is_apply_noise(t):
            noise = self._calculate_noise(t)
            lrs = [v + v * noise for v in lrs]
        return lrs
