from .cosine_lr import CosineLRScheduler
from .scheduler import Scheduler
from .scheduler_factory import create_scheduler_v2, scheduler_kwargs
from .step_lr import MultiStepLRScheduler, PlateauLRScheduler, PolyLRScheduler, StepLRScheduler
from .tanh_lr import TanhLRScheduler
