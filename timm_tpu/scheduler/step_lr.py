"""Step / multi-step / poly / plateau schedulers
(reference: timm/scheduler/step_lr.py, multistep_lr.py, poly_lr.py, plateau_lr.py).
"""
from __future__ import annotations

import bisect
import math
from typing import List, Optional

from .scheduler import Scheduler

__all__ = ['StepLRScheduler', 'MultiStepLRScheduler', 'PolyLRScheduler', 'PlateauLRScheduler']


class StepLRScheduler(Scheduler):
    def __init__(
            self,
            base_lr,
            decay_t: float,
            decay_rate: float = 1.0,
            warmup_t: int = 0,
            warmup_lr_init: float = 0.0,
            warmup_prefix: bool = True,
            t_in_epochs: bool = True,
            **kwargs,
    ):
        super().__init__(base_lr, **kwargs)
        self.decay_t = decay_t
        self.decay_rate = decay_rate
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_prefix = warmup_prefix
        self.t_in_epochs = t_in_epochs
        if self.warmup_t:
            self.warmup_steps = [(v - warmup_lr_init) / self.warmup_t for v in self.base_values]
        else:
            self.warmup_steps = [1 for _ in self.base_values]

    def _get_lr(self, t: int) -> List[float]:
        if t < self.warmup_t:
            return [self.warmup_lr_init + t * s for s in self.warmup_steps]
        if self.warmup_prefix:
            t = t - self.warmup_t
        return [v * (self.decay_rate ** (t // self.decay_t)) for v in self.base_values]


class MultiStepLRScheduler(Scheduler):
    def __init__(
            self,
            base_lr,
            decay_t: List[int],
            decay_rate: float = 1.0,
            warmup_t: int = 0,
            warmup_lr_init: float = 0.0,
            warmup_prefix: bool = True,
            t_in_epochs: bool = True,
            **kwargs,
    ):
        super().__init__(base_lr, **kwargs)
        self.decay_t = decay_t
        self.decay_rate = decay_rate
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_prefix = warmup_prefix
        self.t_in_epochs = t_in_epochs
        if self.warmup_t:
            self.warmup_steps = [(v - warmup_lr_init) / self.warmup_t for v in self.base_values]
        else:
            self.warmup_steps = [1 for _ in self.base_values]

    def get_curr_decay_steps(self, t: int) -> int:
        return bisect.bisect_right(self.decay_t, t + 1)

    def _get_lr(self, t: int) -> List[float]:
        if t < self.warmup_t:
            return [self.warmup_lr_init + t * s for s in self.warmup_steps]
        if self.warmup_prefix:
            t = t - self.warmup_t
        return [v * (self.decay_rate ** self.get_curr_decay_steps(t)) for v in self.base_values]


class PolyLRScheduler(Scheduler):
    def __init__(
            self,
            base_lr,
            t_initial: int,
            power: float = 0.5,
            lr_min: float = 0.0,
            cycle_mul: float = 1.0,
            cycle_decay: float = 1.0,
            cycle_limit: int = 1,
            warmup_t: int = 0,
            warmup_lr_init: float = 0.0,
            warmup_prefix: bool = False,
            t_in_epochs: bool = True,
            k_decay: float = 1.0,
            **kwargs,
    ):
        super().__init__(base_lr, **kwargs)
        assert t_initial > 0
        self.t_initial = t_initial
        self.power = power
        self.lr_min = lr_min
        self.cycle_mul = cycle_mul
        self.cycle_decay = cycle_decay
        self.cycle_limit = cycle_limit
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.warmup_prefix = warmup_prefix
        self.t_in_epochs = t_in_epochs
        self.k_decay = k_decay
        if self.warmup_t:
            self.warmup_steps = [(v - warmup_lr_init) / self.warmup_t for v in self.base_values]
        else:
            self.warmup_steps = [1 for _ in self.base_values]

    def _get_lr(self, t: int) -> List[float]:
        if t < self.warmup_t:
            return [self.warmup_lr_init + t * s for s in self.warmup_steps]
        if self.warmup_prefix:
            t = t - self.warmup_t
        if self.cycle_mul != 1:
            i = math.floor(math.log(1 - t / self.t_initial * (1 - self.cycle_mul), self.cycle_mul))
            t_i = self.cycle_mul ** i * self.t_initial
            t_curr = t - (1 - self.cycle_mul ** i) / (1 - self.cycle_mul) * self.t_initial
        else:
            i = t // self.t_initial
            t_i = self.t_initial
            t_curr = t - (self.t_initial * i)

        if i < self.cycle_limit:
            gamma = self.cycle_decay ** i
            lr_max_values = [v * gamma for v in self.base_values]
            k = self.k_decay
            return [
                self.lr_min + (lr_max - self.lr_min) * (1 - t_curr ** k / t_i ** k) ** self.power
                for lr_max in lr_max_values
            ]
        return [self.lr_min for _ in self.base_values]

    def get_cycle_length(self, cycles: int = 0) -> int:
        cycles = max(1, cycles or self.cycle_limit)
        if self.cycle_mul == 1.0:
            t = self.t_initial * cycles
        else:
            t = int(math.floor(-self.t_initial * (self.cycle_mul ** cycles - 1) / (1 - self.cycle_mul)))
        return t + self.warmup_t if self.warmup_prefix else t


class PlateauLRScheduler(Scheduler):
    """Decay on metric plateau (reference plateau_lr.py). Metric-driven, so it
    only steps per-epoch via `step(epoch, metric)`."""

    def __init__(
            self,
            base_lr,
            decay_rate: float = 0.1,
            patience_t: int = 10,
            verbose: bool = True,
            threshold: float = 1e-4,
            cooldown_t: int = 0,
            warmup_t: int = 0,
            warmup_lr_init: float = 0.0,
            lr_min: float = 0.0,
            mode: str = 'max',
            **kwargs,
    ):
        super().__init__(base_lr, **kwargs)
        self.decay_rate = decay_rate
        self.patience_t = patience_t
        self.threshold = threshold
        self.cooldown_t = cooldown_t
        self.cooldown_counter = 0
        self.mode = mode
        self.lr_min = lr_min
        self.warmup_t = warmup_t
        self.warmup_lr_init = warmup_lr_init
        self.t_in_epochs = True
        self.best = None
        self.num_bad_epochs = 0
        self.restore_lr = None
        self._current = list(self.base_values)
        if self.warmup_t:
            self.warmup_steps = [(v - warmup_lr_init) / self.warmup_t for v in self.base_values]
        else:
            self.warmup_steps = [1 for _ in self.base_values]

    def _is_better(self, metric: float) -> bool:
        if self.best is None:
            return True
        if self.mode == 'max':
            return metric > self.best + self.threshold
        return metric < self.best - self.threshold

    def _get_lr(self, t: int) -> List[float]:
        # warmup only; plateau logic lives in step()
        return [self.warmup_lr_init + t * s for s in self.warmup_steps]

    def step(self, epoch: int, metric: Optional[float] = None) -> List[float]:
        if epoch < self.warmup_t:
            self._last_values = self._get_lr(epoch)
            return self._last_values
        if metric is not None:
            if self._is_better(metric):
                self.best = metric
                self.num_bad_epochs = 0
            else:
                self.num_bad_epochs += 1
            if self.cooldown_counter > 0:
                self.cooldown_counter -= 1
                self.num_bad_epochs = 0
            if self.num_bad_epochs > self.patience_t:
                self._current = [max(v * self.decay_rate, self.lr_min) for v in self._current]
                self.cooldown_counter = self.cooldown_t
                self.num_bad_epochs = 0
        self._last_values = self._add_noise(list(self._current), epoch)
        return self._last_values

    def step_update(self, num_updates: int, metric: Optional[float] = None) -> List[float]:
        return self._last_values
