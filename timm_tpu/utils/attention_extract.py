"""Attention-map extraction (reference: timm/utils/attention_extract.py:9-85).

Functional JAX has no forward hooks; extraction re-runs attention score
computation from per-block token inputs gathered via forward_intermediates —
the getter-style analogue of the reference's fx/hook wrapper.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp

__all__ = ['AttentionExtract']


class AttentionExtract:
    """Extract softmax attention maps from ViT-style models.

    Works with any model whose blocks expose `.attn` with the standard
    (qkv | q_proj/k_proj/v_proj, num_heads, head_dim, scale) contract and a
    `forward_intermediates` that returns per-block token outputs.
    """

    def __init__(self, model, names: Optional[List[Union[int, str]]] = None):
        self.model = model
        num_blocks = len(model.blocks)
        if names is None:
            self.indices = list(range(num_blocks))
        else:
            self.indices = [n if isinstance(n, int) else self._parse_index(n) for n in names]

    @staticmethod
    def _parse_index(name: str) -> int:
        # accepts 3, 'blocks.3', or 'blocks.3.attn'
        for part in str(name).split('.'):
            if part.isdigit():
                return int(part)
        raise ValueError(f'No block index found in name {name!r}')

    def _scores(self, attn, tokens, rope=None):
        from ..layers.attention import apply_rot_embed_cat
        B, N, C = tokens.shape
        if getattr(attn, 'qkv', None) is not None:
            qkv = attn.qkv(tokens)
            if getattr(attn, 'q_bias', None) is not None:
                bias = jnp.concatenate([
                    attn.q_bias[...], jnp.zeros_like(attn.q_bias[...]), attn.v_bias[...]])
                qkv = qkv + bias.astype(qkv.dtype)
            qkv = qkv.reshape(B, N, 3, attn.num_heads, attn.head_dim).transpose(2, 0, 3, 1, 4)
            q, k = qkv[0], qkv[1]
        else:
            q = attn.q_proj(tokens).reshape(B, N, attn.num_heads, attn.head_dim).transpose(0, 2, 1, 3)
            k = attn.k_proj(tokens).reshape(B, N, attn.num_heads, attn.head_dim).transpose(0, 2, 1, 3)
        if getattr(attn, 'q_norm', None) is not None:
            q = attn.q_norm(q)
        if getattr(attn, 'k_norm', None) is not None:
            k = attn.k_norm(k)
        if rope is not None:
            half = getattr(attn, 'rotate_half', False)
            num_prefix = N - rope.shape[-2]
            if num_prefix > 0:
                q = jnp.concatenate(
                    [q[..., :num_prefix, :], apply_rot_embed_cat(q[..., num_prefix:, :], rope, half=half)], axis=-2)
                k = jnp.concatenate(
                    [k[..., :num_prefix, :], apply_rot_embed_cat(k[..., num_prefix:, :], rope, half=half)], axis=-2)
            else:
                q, k = apply_rot_embed_cat(q, rope, half=half), apply_rot_embed_cat(k, rope, half=half)
        scores = jnp.einsum('bhqd,bhkd->bhqk', q * attn.scale, k)
        return jax.nn.softmax(scores.astype(jnp.float32), axis=-1)

    def __call__(self, x) -> Dict[str, jnp.ndarray]:
        # block i's attention consumes block i-1's (normed) output
        need = sorted({i - 1 for i in self.indices if i > 0})
        inputs = {}
        if any(i == 0 for i in self.indices):
            grid = None
            if getattr(self.model, 'dynamic_img_size', False):
                grid = self.model.patch_embed.dynamic_feat_size(x.shape[1:3])
            tokens0 = self.model.patch_embed(x)
            try:
                tokens0 = self.model._pos_embed(tokens0, grid_size=grid)
            except TypeError:
                tokens0 = self.model._pos_embed(tokens0)
            if isinstance(tokens0, tuple):  # Eva returns (tokens, rope table)
                tokens0 = tokens0[0]
            if getattr(self.model, 'norm_pre', None) is not None:
                tokens0 = self.model.norm_pre(tokens0)
            inputs[0] = tokens0
        if need:
            inters = self.model.forward_intermediates(
                x, indices=need, output_fmt='NLC', intermediates_only=True,
                return_prefix_tokens=True)
            for i, feat in zip(need, inters):
                if isinstance(feat, tuple):  # (spatial, prefix) → full token stream
                    feat = jnp.concatenate([feat[1], feat[0]], axis=1)
                inputs[i + 1] = feat

        rope = None
        if getattr(self.model, 'rope', None) is not None:
            # dynamic-size models cache no feat_shape — derive the grid from x
            shape = None
            if self.model.rope.feat_shape is None:
                shape = self.model.patch_embed.dynamic_feat_size(x.shape[1:3])
            rope = self.model.rope.get_embed(shape)

        out = {}
        for i in self.indices:
            blk = self.model.blocks[i]
            # mixed rope: per-depth table (depth, num_heads, N, head_dim)
            blk_rope = rope[i] if (rope is not None and getattr(self.model, 'rope_mixed', False)) else rope
            # post-norm blocks (ResPost*) feed attention the RAW residual stream
            post_norm = 'ResPost' in type(blk).__name__
            tokens = inputs[i] if post_norm else blk.norm1(inputs[i])
            out[f'blocks.{i}.attn'] = self._scores(blk.attn, tokens, rope=blk_rope)
        return out
