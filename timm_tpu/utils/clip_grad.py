"""Gradient clipping (reference: timm/utils/clip_grad.py, agc.py).

Pure functions over grad pytrees, composed inside the jitted train step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ['clip_grad_norm', 'clip_grad_value', 'adaptive_clip_grad', 'dispatch_clip_grad', 'global_grad_norm']


def global_grad_norm(grads) -> jnp.ndarray:
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


def clip_grad_norm(grads, max_norm: float):
    norm = global_grad_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads), norm


def clip_grad_value(grads, clip_value: float):
    return jax.tree.map(lambda g: jnp.clip(g, -clip_value, clip_value), grads), None


def _unitwise_norm(x):
    if x.ndim <= 1:
        return jnp.abs(x)
    # linear (I,O): norm over input dim; conv HWIO: norm over HWI
    axes = tuple(range(x.ndim - 1))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))


def adaptive_clip_grad(params, grads, clip_factor: float = 0.01, eps: float = 1e-3):
    """AGC (reference agc.py:30): clip grads unit-wise relative to param norms."""

    def clip(p, g):
        if p is None or g is None:
            return g
        p_norm = jnp.maximum(_unitwise_norm(p), eps)
        g_norm = _unitwise_norm(g)
        max_norm = p_norm * clip_factor
        clipped = g * (max_norm / jnp.maximum(g_norm, 1e-6))
        return jnp.where(g_norm > max_norm, clipped, g)

    return jax.tree.map(clip, params, grads)


def dispatch_clip_grad(grads, value: float, mode: str = 'norm', params=None):
    """(reference clip_grad.py:dispatch_clip_grad). Returns (grads, grad_norm?)."""
    if mode == 'norm':
        return clip_grad_norm(grads, value)
    if mode == 'value':
        return clip_grad_value(grads, value)
    if mode == 'agc':
        assert params is not None, 'AGC requires params'
        return adaptive_clip_grad(params, grads, clip_factor=value), None
    raise ValueError(f'Unknown clip mode {mode}')
