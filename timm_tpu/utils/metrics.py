"""Metrics (reference: timm/utils/metrics.py)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ['AverageMeter', 'accuracy']


class AverageMeter:
    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


def accuracy(output, target, topk=(1,)):
    """Top-k accuracy in percent (reference metrics.py:19)."""
    maxk = min(max(topk), output.shape[-1])
    batch_size = target.shape[0]
    pred = jnp.argsort(output, axis=-1)[:, ::-1][:, :maxk]
    correct = pred == target[:, None]
    return [float(correct[:, :min(k, maxk)].any(axis=-1).sum()) * 100.0 / batch_size for k in topk]
