"""Logging setup (reference: timm/utils/log.py)."""
from __future__ import annotations

import logging
import logging.handlers

__all__ = ['setup_default_logging', 'FormatterNoInfo']


class FormatterNoInfo(logging.Formatter):
    def __init__(self, fmt: str = '%(levelname)s: %(message)s'):
        logging.Formatter.__init__(self, fmt)

    def format(self, record):
        if record.levelno == logging.INFO:
            return str(record.getMessage())
        return logging.Formatter.format(self, record)


def setup_default_logging(default_level=logging.INFO, log_path: str = ''):
    console_handler = logging.StreamHandler()
    console_handler.setFormatter(FormatterNoInfo())
    logging.root.addHandler(console_handler)
    logging.root.setLevel(default_level)
    if log_path:
        file_handler = logging.handlers.RotatingFileHandler(log_path, maxBytes=(2 ** 20) * 10, backupCount=3)
        file_formatter = logging.Formatter('%(asctime)s - %(name)20s: [%(levelname)8s] - %(message)s')
        file_handler.setFormatter(file_formatter)
        logging.root.addHandler(file_handler)
