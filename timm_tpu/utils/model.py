"""Model utilities (reference: timm/utils/model.py)."""
from __future__ import annotations

from typing import List, Optional, Union

from flax import nnx

__all__ = ['unwrap_model', 'get_state_dict', 'freeze', 'unfreeze', 'reparameterize_model']


def unwrap_model(model):
    return getattr(model, 'model', model) if type(model).__name__ == 'FeatureGetterNet' else model


def get_state_dict(model, unwrap_fn=unwrap_model):
    from ..models._helpers import model_state_dict
    return model_state_dict(unwrap_fn(model))


class _Frozen(nnx.Variable):
    """Marker variable type for frozen params (excluded from nnx.Param state)."""
    pass


def _iter_submodules(model: nnx.Module, prefix: str = ''):
    yield prefix, model
    for name, attr in vars(model).items():
        if isinstance(attr, nnx.Module):
            yield from _iter_submodules(attr, f'{prefix}.{name}' if prefix else name)
        elif isinstance(attr, (list, tuple)) or type(attr).__name__ == 'List':
            for i, item in enumerate(attr):
                if isinstance(item, nnx.Module):
                    yield from _iter_submodules(item, f'{prefix}.{name}.{i}' if prefix else f'{name}.{i}')


def _set_frozen(module: nnx.Module, submodules: List[str], frozen: bool):
    for name, sub in _iter_submodules(module):
        if not submodules or any(name == s or name.startswith(s + '.') for s in submodules):
            for attr_name, attr in list(vars(sub).items()):
                if isinstance(attr, nnx.Param) and frozen:
                    setattr(sub, attr_name, _Frozen(attr[...]))
                elif isinstance(attr, _Frozen) and not frozen:
                    setattr(sub, attr_name, nnx.Param(attr[...]))


def freeze(module: nnx.Module, submodules: Union[str, List[str]] = ()):
    """Convert Params to non-trainable variables (reference model.py:181)."""
    if isinstance(submodules, str):
        submodules = [submodules]
    _set_frozen(module, list(submodules), True)


def unfreeze(module: nnx.Module, submodules: Union[str, List[str]] = ()):
    if isinstance(submodules, str):
        submodules = [submodules]
    _set_frozen(module, list(submodules), False)


def reparameterize_model(model: nnx.Module, inplace: bool = False) -> nnx.Module:
    """Fuse reparameterizable blocks (RepVGG-style) for inference
    (reference model.py:233). Models expose `reparameterize()` per-module."""
    for _, sub in _iter_submodules(model):
        if hasattr(sub, 'reparameterize') and callable(sub.reparameterize):
            sub.reparameterize()
    return model
