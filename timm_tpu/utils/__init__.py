from .attention_extract import AttentionExtract
from .checkpoint_saver import CheckpointSaver
from .clip_grad import adaptive_clip_grad, clip_grad_norm, clip_grad_value, dispatch_clip_grad, global_grad_norm
from .compile_cache import (cache_event_total, collect_cache_events,
                            configure_compile_cache, count_jaxpr_eqns)
from .log import FormatterNoInfo, setup_default_logging
from .metrics import AverageMeter, accuracy
from .model import freeze, get_state_dict, reparameterize_model, unfreeze, unwrap_model
from .model_ema import ModelEmaV3, ema_update
from .random import random_seed
from .serialization import flatten_pytree, unflatten_into
from .summary import get_outdir, update_summary
