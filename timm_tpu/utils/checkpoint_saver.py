"""Checkpoint retention + recovery (reference: timm/utils/checkpoint_saver.py:22-187).

Checkpoint = one `.npz` file holding the flattened task state (model params,
EMA, optimizer state, epoch metadata) — same single-file UX as the reference's
torch.save dict, schema keys mirrored from checkpoint_saver.py:89-110.
Retention: `last` always, top-k by metric, `model_best` copied.

Durability (resilience subsystem): every write goes tmp → fsync →
`os.replace` with a SHA-256 sidecar manifest (resilience/durable.py), so a
preemption or crash mid-write can never leave a torn `last.npz` as the only
resume candidate. Startup sweeps orphaned tmp files, async staging dirs left
by a writer thread killed mid-flight, and corrupt recovery files;
`find_recovery` orders `(epoch, batch_idx)` numerically and returns the
newest file that passes verification.

Async mode (`async_writer`): the step thread only snapshots state to host
(resilience.snapshot_to_host — mandatory before the next step deletes
donated buffers) and computes retention/best bookkeeping; the unchanged
durable pipeline (write + prune + copies) replays in order on the writer
thread, staging temp files inside a `.async-stage-<pid>/` subdirectory so a
kill mid-write leaves nothing loose next to real checkpoints.
"""
from __future__ import annotations

import glob
import logging
import operator
import os
import re
import shutil
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..resilience import (
    atomic_copy, atomic_write_json, atomic_write_npz, manifest_path, snapshot_to_host,
    verify_checkpoint,
)
from ..resilience.durable import (
    copy_sharded_checkpoint, find_checkpoints, remove_checkpoint_files,
    snapshot_process_shards, sweep_orphan_shards, write_sharded_checkpoint,
)

_logger = logging.getLogger(__name__)

__all__ = ['CheckpointSaver']

_RECOVERY_RE = re.compile(r'-(\d+)-(\d+)\.npz$')


class CheckpointSaver:
    def __init__(
            self,
            task,
            args=None,
            checkpoint_prefix: str = 'checkpoint',
            recovery_prefix: str = 'recovery',
            checkpoint_dir: str = '',
            recovery_dir: str = '',
            decreasing: bool = False,
            max_history: int = 10,
            async_writer=None,
            process_index: int = 0,
            process_count: int = 1,
    ):
        self.task = task
        self.args = args
        self.async_writer = async_writer  # resilience.AsyncCheckpointWriter or None
        # multi-process (pod) mode: EVERY process owns a saver; each writes
        # only its addressable shards (durable.write_sharded_checkpoint),
        # process 0 commits manifests/sidecars after the all-hosts barrier.
        # Retention/best bookkeeping must stay process-deterministic: all
        # processes call save_* with the same (epoch, metric) sequence.
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.sharded = self.process_count > 1
        self.primary = self.process_index == 0
        self.checkpoint_files: List[Tuple[str, float]] = []
        self.best_epoch: Optional[int] = None
        self.best_metric: Optional[float] = None
        self.curr_recovery_file = ''
        self.prev_recovery_file = ''

        self.checkpoint_dir = checkpoint_dir
        self.recovery_dir = recovery_dir
        self.save_prefix = checkpoint_prefix
        self.recovery_prefix = recovery_prefix
        self.extension = '.npz'
        self.decreasing = decreasing
        self.cmp = operator.lt if decreasing else operator.gt
        self.max_history = max_history
        assert self.max_history >= 1
        self._cleanup_startup()

    def _cleanup_startup(self):
        """Sweep artifacts of a previous crash: orphaned tmp files from
        interrupted atomic writes, the legacy non-atomic `tmp.npz`, async
        staging dirs from a writer thread killed mid-flight, shard files whose
        global manifest never committed (host died between shard write and
        commit), and recovery files that fail integrity verification. In
        multi-process mode only process 0 sweeps (shared filesystem — one
        janitor; missing-file unlinks are ignored anyway)."""
        if self.sharded and not self.primary:
            return
        for d in {self.checkpoint_dir, self.recovery_dir}:
            if not d or not os.path.isdir(d):
                continue
            sweep_orphan_shards(d)
            for name in os.listdir(d):
                path = os.path.join(d, name)
                if name.startswith('.async-stage-') and os.path.isdir(path):
                    _logger.info(f'Removing orphaned async staging dir: {path}')
                    shutil.rmtree(path, ignore_errors=True)
                elif name.endswith('.tmp') or name in ('tmp.npz', 'tmp.json'):
                    _logger.info(f'Removing orphaned checkpoint temp file: {path}')
                    self._unlink(path)
                elif name.startswith(self.recovery_prefix) and name.endswith(self.extension):
                    ok, reason = verify_checkpoint(path)
                    if not ok:
                        _logger.warning(f'Removing corrupt recovery file {path}: {reason}')
                        self._unlink(path)
                        self._unlink(manifest_path(path))
                elif (name.startswith(self.recovery_prefix)
                      and name.endswith('.manifest.json') and '.shard' not in name
                      and not os.path.exists(os.path.join(d, name[:-len('.manifest.json')] + self.extension))):
                    # sharded recovery checkpoint (manifest only, no data
                    # file): drop it wholesale if any shard is missing/corrupt
                    logical = os.path.join(d, name[:-len('.manifest.json')] + self.extension)
                    ok, reason = verify_checkpoint(logical)
                    if not ok:
                        _logger.warning(f'Removing corrupt sharded recovery {logical}: {reason}')
                        remove_checkpoint_files(logical)

    def _stage_for(self, directory: str) -> Optional[str]:
        """Staging dir for async temp files (same filesystem as the
        destination, so os.replace stays atomic); None in sync mode."""
        if self.async_writer is None or not directory:
            return None
        stage = os.path.join(directory, f'.async-stage-{os.getpid()}')
        os.makedirs(stage, exist_ok=True)
        return stage

    def _dispatch(self, commit, label: str, key: str):
        """Run the durable closure inline (sync) or hand it to the writer
        thread (async; a newer snapshot supersedes a same-key queued one)."""
        if self.async_writer is None:
            commit()
        else:
            self.async_writer.submit(commit, label=label, key=key)

    @staticmethod
    def _unlink(path: str):
        try:
            os.remove(path)
        except OSError:
            pass

    def _snapshot(self, save_path: str, epoch: int, metric: Optional[float] = None,
                  extra_state: Optional[Dict[str, np.ndarray]] = None):
        """Caller-thread half of a save: assemble + host-snapshot the state,
        return the durable-commit closure (the unchanged sync pipeline)."""
        state = self.task.get_checkpoint_state()
        state['epoch'] = np.asarray(epoch)
        if metric is not None:
            state['metric'] = np.asarray(metric)
        if extra_state:
            state.update({k: np.asarray(v) for k, v in extra_state.items()})
        meta = {'epoch': epoch, 'metric': metric}
        if extra_state and '_resume.num_updates' in extra_state:
            meta['num_updates'] = int(np.asarray(extra_state['_resume.num_updates']))
        snap = None
        if self.sharded:
            # sharded mode: extract this process's chunks NOW (same donated-
            # buffer constraint as snapshot_to_host, and cheap: local shards
            # only — no process_allgather anywhere on the save path)
            snap = snapshot_process_shards(state, self.process_index, self.process_count)
        elif self.async_writer is not None:
            # must happen NOW: the next train step deletes donated buffers
            state = snapshot_to_host(state)
        args_doc = None
        if self.args is not None and (not self.sharded or self.primary):
            args_doc = {
                'epoch': epoch, 'metric': metric, 'arch': getattr(self.args, 'model', None),
                'args': {k: str(v) for k, v in vars(self.args).items()}}
        stage = self._stage_for(os.path.dirname(save_path))

        def commit():
            landed = True
            if stage is not None:
                os.makedirs(stage, exist_ok=True)
            if snap is not None:
                committed = write_sharded_checkpoint(save_path, snap, meta=meta,
                                                     tmp_dir=stage)
                landed = committed is not None
                if landed and args_doc is not None:
                    atomic_write_json(save_path.replace(self.extension, '.json'), args_doc,
                                      tmp_dir=stage)
            else:
                atomic_write_npz(save_path, state, meta=meta, tmp_dir=stage)
                if args_doc is not None:
                    atomic_write_json(save_path.replace(self.extension, '.json'), args_doc,
                                      tmp_dir=stage)
            if stage is not None:
                try:
                    os.rmdir(stage)  # empty after a clean write; litter keeps it
                except OSError:
                    pass
            return landed
        return commit

    def _save(self, save_path: str, epoch: int, metric: Optional[float] = None,
              extra_state: Optional[Dict[str, np.ndarray]] = None):
        self._snapshot(save_path, epoch, metric, extra_state)()

    def _copy(self, src: str, dst: str):
        """Sharded-aware checkpoint copy (each process copies its own shard,
        process 0 commits the destination manifest after the barrier)."""
        if self.sharded:
            copy_sharded_checkpoint(src, dst, self.process_index, self.process_count)
        else:
            atomic_copy(src, dst)

    def _remove(self, path: str):
        """Sharded-aware checkpoint removal (non-primary removes only its own
        shard; process 0 removes manifest + sidecars + every shard)."""
        if self.sharded:
            remove_checkpoint_files(path, process_index=self.process_index)
        else:
            remove_checkpoint_files(path)

    def save_checkpoint(self, epoch: int, metric: Optional[float] = None):
        assert epoch >= 0
        last_save_path = os.path.join(self.checkpoint_dir, 'last' + self.extension)
        # retention/best bookkeeping happens eagerly on the caller thread;
        # `ops` collects the durable file operations, replayed in order
        ops = [self._snapshot(last_save_path, epoch, metric)]
        # an end-of-epoch checkpoint supersedes any mid-epoch recovery of this
        # or an earlier epoch — drop them so `--resume auto` can't step back
        # (the dir scan runs in the closure, AFTER any queued recovery write)
        ops.append(lambda: self._prune_stale_recovery_files(epoch))
        for attr in ('curr_recovery_file', 'prev_recovery_file'):
            m = _RECOVERY_RE.search(getattr(self, attr) or '')
            if m and int(m.group(1)) <= epoch:
                setattr(self, attr, '')

        worst_file = self.checkpoint_files[-1] if self.checkpoint_files else None
        if len(self.checkpoint_files) < self.max_history or metric is None or self.cmp(metric, worst_file[1]):
            if len(self.checkpoint_files) >= self.max_history:
                ops.append(self._cleanup_checkpoints(1))
            filename = '-'.join([self.save_prefix, str(epoch)]) + self.extension
            save_path = os.path.join(self.checkpoint_dir, filename)
            ops.append(lambda: self._copy(last_save_path, save_path))
            self.checkpoint_files.append((save_path, metric))
            self.checkpoint_files = sorted(
                self.checkpoint_files, key=lambda x: x[1] if x[1] is not None else -float('inf'),
                reverse=not self.decreasing)

            checkpoints_str = 'Current checkpoints:\n'
            for c in self.checkpoint_files:
                checkpoints_str += ' {}\n'.format(c)
            _logger.info(checkpoints_str)

            if metric is not None and (self.best_metric is None or self.cmp(metric, self.best_metric)):
                self.best_epoch = epoch
                self.best_metric = metric
                best_save_path = os.path.join(self.checkpoint_dir, 'model_best' + self.extension)
                ops.append(lambda: self._copy(last_save_path, best_save_path))

        def commit():
            for op in ops:
                op()

        self._dispatch(commit, label=f'checkpoint-{epoch}', key='checkpoint')
        return (None, None) if self.best_metric is None else (self.best_metric, self.best_epoch)

    def _cleanup_checkpoints(self, trim: int = 0):
        """Trim the tracked checkpoint list now; return the closure that
        removes the files (run inline in sync mode, on the writer in async)."""
        trim = min(len(self.checkpoint_files), trim)
        delete_index = self.max_history - trim
        if delete_index < 0 or len(self.checkpoint_files) <= delete_index:
            return lambda: None
        to_delete = self.checkpoint_files[delete_index:]
        self.checkpoint_files = self.checkpoint_files[:delete_index]

        def remove():
            for d in to_delete:
                _logger.debug(f'Cleaning checkpoint: {d}')
                self._remove(d[0])
        return remove

    def save_recovery(self, epoch: int, batch_idx: int = 0,
                      extra_state: Optional[Dict[str, np.ndarray]] = None) -> str:
        filename = '-'.join([self.recovery_prefix, str(epoch), str(batch_idx)]) + self.extension
        save_path = os.path.join(self.recovery_dir, filename)
        commit_write = self._snapshot(save_path, epoch, extra_state=extra_state)
        prev_to_remove = self.prev_recovery_file

        def commit():
            if not commit_write():
                # sharded commit barrier failed (peer lost): the previous
                # recovery must stay — it is still the newest VALID checkpoint
                return
            if prev_to_remove and (os.path.exists(prev_to_remove)
                                   or os.path.exists(manifest_path(prev_to_remove))):
                self._remove(prev_to_remove)

        self._dispatch(commit, label=f'recovery-{epoch}-{batch_idx}', key='recovery')
        self.prev_recovery_file = self.curr_recovery_file
        self.curr_recovery_file = save_path
        return save_path

    def _recovery_files(self) -> List[str]:
        """Recovery files newest-first by numeric (epoch, batch_idx) — the
        seed's lexicographic sort ranked recovery-1-999 above recovery-1-1000.
        Sharded recovery checkpoints (manifest, no data file) are surfaced by
        durable.find_checkpoints under their logical `.npz` name."""
        if self.sharded:
            files = [f for f in find_checkpoints(self.recovery_dir)
                     if os.path.basename(f).startswith(self.recovery_prefix)]
        else:
            recovery_path = os.path.join(self.recovery_dir, self.recovery_prefix)
            files = glob.glob(recovery_path + '*' + self.extension)

        def key(f):
            m = _RECOVERY_RE.search(f)
            return (int(m.group(1)), int(m.group(2))) if m else (-1, -1)

        return sorted(files, key=key, reverse=True)

    def _prune_stale_recovery_files(self, completed_epoch: int):
        """File-system half of recovery pruning (writer-thread safe: no
        bookkeeping mutation — save_checkpoint clears curr/prev eagerly)."""
        for f in self._recovery_files():
            m = _RECOVERY_RE.search(f)
            if m and int(m.group(1)) <= completed_epoch:
                self._remove(f)

    def find_recovery(self) -> str:
        """Newest recovery checkpoint that passes integrity verification."""
        for f in self._recovery_files():
            ok, reason = verify_checkpoint(f)
            if ok:
                return f
            _logger.warning(f'Skipping invalid recovery checkpoint {f}: {reason}')
        return ''
