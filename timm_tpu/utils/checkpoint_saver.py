"""Checkpoint retention + recovery (reference: timm/utils/checkpoint_saver.py:22-187).

Checkpoint = one `.npz` file holding the flattened task state (model params,
EMA, optimizer state, epoch metadata) — same single-file UX as the reference's
torch.save dict, schema keys mirrored from checkpoint_saver.py:89-110.
Retention: `last` always, top-k by metric, `model_best` copied.
"""
from __future__ import annotations

import glob
import json
import logging
import operator
import os
import shutil
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = ['CheckpointSaver']


class CheckpointSaver:
    def __init__(
            self,
            task,
            args=None,
            checkpoint_prefix: str = 'checkpoint',
            recovery_prefix: str = 'recovery',
            checkpoint_dir: str = '',
            recovery_dir: str = '',
            decreasing: bool = False,
            max_history: int = 10,
    ):
        self.task = task
        self.args = args
        self.checkpoint_files: List[Tuple[str, float]] = []
        self.best_epoch: Optional[int] = None
        self.best_metric: Optional[float] = None
        self.curr_recovery_file = ''
        self.prev_recovery_file = ''

        self.checkpoint_dir = checkpoint_dir
        self.recovery_dir = recovery_dir
        self.save_prefix = checkpoint_prefix
        self.recovery_prefix = recovery_prefix
        self.extension = '.npz'
        self.decreasing = decreasing
        self.cmp = operator.lt if decreasing else operator.gt
        self.max_history = max_history
        assert self.max_history >= 1

    def _save(self, save_path: str, epoch: int, metric: Optional[float] = None):
        state = self.task.get_checkpoint_state()
        state['epoch'] = np.asarray(epoch)
        if metric is not None:
            state['metric'] = np.asarray(metric)
        np.savez(save_path, **state)
        if self.args is not None:
            meta_path = save_path.replace(self.extension, '.json')
            with open(meta_path, 'w') as f:
                json.dump({'epoch': epoch, 'metric': metric, 'arch': getattr(self.args, 'model', None),
                           'args': {k: str(v) for k, v in vars(self.args).items()}}, f, indent=2, default=str)

    def save_checkpoint(self, epoch: int, metric: Optional[float] = None):
        assert epoch >= 0
        tmp_save_path = os.path.join(self.checkpoint_dir, 'tmp' + self.extension)
        last_save_path = os.path.join(self.checkpoint_dir, 'last' + self.extension)
        self._save(tmp_save_path, epoch, metric)
        if os.path.exists(last_save_path):
            os.unlink(last_save_path)
        os.rename(tmp_save_path, last_save_path)
        tmp_meta = tmp_save_path.replace(self.extension, '.json')
        if os.path.exists(tmp_meta):
            os.replace(tmp_meta, last_save_path.replace(self.extension, '.json'))

        worst_file = self.checkpoint_files[-1] if self.checkpoint_files else None
        if len(self.checkpoint_files) < self.max_history or metric is None or self.cmp(metric, worst_file[1]):
            if len(self.checkpoint_files) >= self.max_history:
                self._cleanup_checkpoints(1)
            filename = '-'.join([self.save_prefix, str(epoch)]) + self.extension
            save_path = os.path.join(self.checkpoint_dir, filename)
            shutil.copy2(last_save_path, save_path)
            if self.args is not None and os.path.exists(last_save_path.replace(self.extension, '.json')):
                shutil.copy2(last_save_path.replace(self.extension, '.json'),
                             save_path.replace(self.extension, '.json'))
            self.checkpoint_files.append((save_path, metric))
            self.checkpoint_files = sorted(
                self.checkpoint_files, key=lambda x: x[1] if x[1] is not None else -float('inf'),
                reverse=not self.decreasing)

            checkpoints_str = 'Current checkpoints:\n'
            for c in self.checkpoint_files:
                checkpoints_str += ' {}\n'.format(c)
            _logger.info(checkpoints_str)

            if metric is not None and (self.best_metric is None or self.cmp(metric, self.best_metric)):
                self.best_epoch = epoch
                self.best_metric = metric
                best_save_path = os.path.join(self.checkpoint_dir, 'model_best' + self.extension)
                shutil.copy2(last_save_path, best_save_path)

        return (None, None) if self.best_metric is None else (self.best_metric, self.best_epoch)

    def _cleanup_checkpoints(self, trim: int = 0):
        trim = min(len(self.checkpoint_files), trim)
        delete_index = self.max_history - trim
        if delete_index < 0 or len(self.checkpoint_files) <= delete_index:
            return
        to_delete = self.checkpoint_files[delete_index:]
        for d in to_delete:
            try:
                _logger.debug(f'Cleaning checkpoint: {d}')
                os.remove(d[0])
                meta = d[0].replace(self.extension, '.json')
                if os.path.exists(meta):
                    os.remove(meta)
            except OSError:
                _logger.error(f'Exception removing checkpoint {d}')
        self.checkpoint_files = self.checkpoint_files[:delete_index]

    def save_recovery(self, epoch: int, batch_idx: int = 0):
        filename = '-'.join([self.recovery_prefix, str(epoch), str(batch_idx)]) + self.extension
        save_path = os.path.join(self.recovery_dir, filename)
        self._save(save_path, epoch)
        if os.path.exists(self.prev_recovery_file):
            try:
                os.remove(self.prev_recovery_file)
            except OSError:
                _logger.error(f'Exception removing {self.prev_recovery_file}')
        self.prev_recovery_file = self.curr_recovery_file
        self.curr_recovery_file = save_path

    def find_recovery(self) -> str:
        recovery_path = os.path.join(self.recovery_dir, self.recovery_prefix)
        files = glob.glob(recovery_path + '*' + self.extension)
        files = sorted(files)
        return files[0] if files else ''
