"""Model EMA (reference: timm/utils/model_ema.py:135-261, ModelEmaV3).

EMA weights are just a second param pytree; the update is a fused lerp inside
the jitted train step (the reference needs torch._foreach_lerp_; XLA fuses the
tree-map for free). The decay warmup schedule is computed host-side per step
and passed in as a scalar.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ['ema_update', 'ModelEmaV3']


def ema_update(ema_params, params, decay):
    """ema = decay * ema + (1-decay) * params."""
    d = jnp.asarray(decay, jnp.float32)
    return jax.tree.map(
        lambda e, p: (e.astype(jnp.float32) * d + p.astype(jnp.float32) * (1.0 - d)).astype(e.dtype),
        ema_params, params)


class ModelEmaV3:
    """Host-side EMA controller: owns the decay schedule; the param tree lives
    with the train state (reference model_ema.py:135, warmup at :188-206)."""

    def __init__(
            self,
            decay: float = 0.9999,
            min_decay: float = 0.0,
            update_after_step: int = 0,
            use_warmup: bool = False,
            warmup_gamma: float = 1.0,
            warmup_power: float = 2.0 / 3.0,
    ):
        self.decay = decay
        self.min_decay = min_decay
        self.update_after_step = update_after_step
        self.use_warmup = use_warmup
        self.warmup_gamma = warmup_gamma
        self.warmup_power = warmup_power

    def get_decay(self, step: int) -> float:
        step = max(0, step - self.update_after_step - 1)
        if step <= 0:
            return 0.0
        if self.use_warmup:
            decay = 1 - (1 + step / self.warmup_gamma) ** -self.warmup_power
            decay = max(min(decay, self.decay), self.min_decay)
        else:
            decay = self.decay
        return decay
