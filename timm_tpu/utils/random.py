"""Seeding (reference: timm/utils/random.py)."""
from __future__ import annotations

import random

import numpy as np

__all__ = ['random_seed']


def random_seed(seed: int = 42, rank: int = 0):
    random.seed(seed + rank)
    np.random.seed(seed + rank)
