"""Persistent XLA compilation cache + compile-cost reporting utilities.

JAX's persistent compilation cache makes compiled executables durable across
processes: a second cold process re-loading the same program pays only a disk
read instead of a full XLA compile. Until this module existed, the warm
``/tmp/timm_tpu_xla_cache`` that tier-1's wall-clock budget depends on was set
only by tests/conftest.py — entry-script runs (train/validate/inference/bench)
recompiled everything from scratch every process.

One subtlety this module handles: JAX latches its "is the cache enabled?"
decision at the FIRST compilation of the process (``_cache_checked`` in
``jax._src.compilation_cache``). Setting ``jax_compilation_cache_dir`` after
any jit has run silently does nothing. ``configure_compile_cache`` therefore
resets the cache state after (re)configuring so late configuration still takes
effect.

Environment knobs:
  TIMM_TPU_COMPILE_CACHE            cache dir; '', '0' or 'off' disables.
                                    (TIMM_TPU_XLA_CACHE is honored as a
                                    legacy fallback spelling.)
  TIMM_TPU_COMPILE_CACHE_MIN_ENTRY_BYTES    min executable size to persist
                                            (default 0 = everything)
  TIMM_TPU_COMPILE_CACHE_MIN_COMPILE_SECS   min compile time to persist
                                            (default 0.5s)
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Dict, Optional

_logger = logging.getLogger(__name__)

DEFAULT_CACHE_DIR = '/tmp/timm_tpu_xla_cache'

_DISABLED = ('', '0', 'off', 'false', 'none')


def resolve_cache_dir(cache_dir: Optional[str] = None) -> Optional[str]:
    """Explicit arg > TIMM_TPU_COMPILE_CACHE > legacy TIMM_TPU_XLA_CACHE >
    DEFAULT_CACHE_DIR. Returns None when disabled."""
    if cache_dir is None:
        cache_dir = os.environ.get(
            'TIMM_TPU_COMPILE_CACHE',
            os.environ.get('TIMM_TPU_XLA_CACHE', DEFAULT_CACHE_DIR))
    if cache_dir is None or cache_dir.strip().lower() in _DISABLED:
        return None
    return cache_dir


def configure_compile_cache(
        cache_dir: Optional[str] = None,
        min_entry_size_bytes: Optional[int] = None,
        min_compile_time_secs: Optional[float] = None,
) -> Optional[str]:
    """Point JAX's persistent compilation cache at a durable directory.

    Call at process start (all four entry scripts and the tier-1 conftest do)
    so every compile in the process is eligible. Returns the configured dir,
    or None when disabled. Safe to call more than once and after jits have
    already run (the cache-enabled latch is reset).
    """
    import jax

    cache_dir = resolve_cache_dir(cache_dir)
    if cache_dir is None:
        return None
    if min_entry_size_bytes is None:
        min_entry_size_bytes = int(os.environ.get('TIMM_TPU_COMPILE_CACHE_MIN_ENTRY_BYTES', '0'))
    if min_compile_time_secs is None:
        min_compile_time_secs = float(os.environ.get('TIMM_TPU_COMPILE_CACHE_MIN_COMPILE_SECS', '0.5'))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', min_entry_size_bytes)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', min_compile_time_secs)
    except Exception as e:  # out-of-tree jax without these flags: degrade loudly
        _logger.warning(f'persistent compile cache not configured: {e}')
        return None
    try:
        # un-latch the once-per-process enabled check so configuration after
        # an early jit (imports, probes) still takes effect
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception as e:
        # best-effort: the private reset hook moves between jax versions;
        # without it the cache still works for jits issued after configure
        _logger.debug(f'compile-cache reset hook unavailable: {e}')
    return cache_dir


# -- compile-cache event accounting -------------------------------------------
# JAX emits '/jax/compilation_cache/cache_hits' / 'cache_misses' monitoring
# events on every compile with the persistent cache enabled. One module-level
# listener fans out to whichever collectors are active, so nested measurements
# (engine prewarm inside drill inside test) each see their own counts.

_ACTIVE_COLLECTORS: list = []
_LISTENER_INSTALLED = False


def _install_cache_listener():
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring

        def _on_event(event, **kwargs):
            if '/compilation_cache/' not in event:
                return
            for c in list(_ACTIVE_COLLECTORS):
                c[event] = c.get(event, 0) + 1

        monitoring.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True
    except Exception as e:  # out-of-tree jax: counts degrade to zeros
        _logger.warning(f'compile-cache event listener unavailable: {e}')


@contextlib.contextmanager
def collect_cache_events():
    """Collect JAX compilation-cache events within the block into a dict."""
    _install_cache_listener()
    counts: Dict[str, int] = {}
    _ACTIVE_COLLECTORS.append(counts)
    try:
        yield counts
    finally:
        _ACTIVE_COLLECTORS.remove(counts)


def cache_event_total(counts: Dict[str, int], suffix: str) -> int:
    """Sum event counts whose key ends with ``suffix`` (e.g. 'cache_hits')."""
    return sum(v for k, v in counts.items() if k.endswith(suffix))


def count_jaxpr_eqns(jaxpr) -> int:
    """Total equation count of a (closed) jaxpr including nested sub-jaxprs
    (scan/while/cond bodies, remat). The proxy for trace/lowering cost: a
    Python block loop contributes O(depth) equations, a scanned stack O(1)."""
    jaxpr = getattr(jaxpr, 'jaxpr', jaxpr)
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for v in eqn.params.values():
            if hasattr(v, 'jaxpr'):
                n += count_jaxpr_eqns(v)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if hasattr(item, 'jaxpr'):
                        n += count_jaxpr_eqns(item)
    return n
