"""Arbitrary-pytree ↔ flat-dict serialization for train-state checkpoints.

Optimizer states are nested namedtuples/dataclasses; we flatten them with
keypaths into a flat {str: array} dict (safetensors/npz-compatible) and
restore into a freshly-built template of identical structure. This gives the
reference's single-file checkpoint UX (checkpoint_saver.py:89-110) without a
pickle dependency.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

__all__ = ['flatten_pytree', 'unflatten_into']


def _kp_str(kp) -> str:
    parts = []
    for p in kp:
        if hasattr(p, 'key'):
            parts.append(str(p.key))
        elif hasattr(p, 'idx'):
            parts.append(str(p.idx))
        elif hasattr(p, 'name'):
            # drop the Variable '.value' attribute hop — params are addressed
            # by their module path, matching model_state_dict naming
            if str(p.name) == 'value':
                continue
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return '.'.join(parts)


def flatten_pytree(tree, prefix: str = '') -> Dict[str, np.ndarray]:
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for kp, leaf in flat:
        if leaf is None:
            continue
        if hasattr(leaf, 'dtype') and jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            continue  # RNG stream keys aren't checkpoint content
        key = _kp_str(kp)
        if prefix:
            key = f'{prefix}.{key}' if key else prefix
        out[key] = np.asarray(leaf)
    return out


def unflatten_into(template, flat_dict: Dict[str, np.ndarray], prefix: str = '', strict: bool = True):
    """Rebuild a pytree with `template`'s structure from flat_dict values."""
    import jax.numpy as jnp
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for kp, leaf in paths_leaves:
        key = _kp_str(kp)
        if prefix:
            key = f'{prefix}.{key}' if key else prefix
        if key in flat_dict:
            val = jnp.asarray(flat_dict[key])
            if leaf is not None and hasattr(leaf, 'dtype'):
                val = val.astype(leaf.dtype)
            new_leaves.append(val)
        elif strict:
            raise KeyError(f'Missing checkpoint key: {key}')
        else:
            new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
