"""Run-dir + summary CSV (reference: timm/utils/summary.py)."""
from __future__ import annotations

import csv
import os
from collections import OrderedDict

__all__ = ['get_outdir', 'update_summary']


def get_outdir(path: str, *paths, inc: bool = False) -> str:
    outdir = os.path.join(path, *paths)
    if not os.path.exists(outdir):
        os.makedirs(outdir)
    elif inc:
        count = 1
        outdir_inc = outdir + '-' + str(count)
        while os.path.exists(outdir_inc):
            count = count + 1
            outdir_inc = outdir + '-' + str(count)
            assert count < 100
        outdir = outdir_inc
        os.makedirs(outdir)
    return outdir


def update_summary(
        epoch: int,
        train_metrics: dict,
        eval_metrics: dict,
        filename: str,
        lr=None,
        write_header: bool = False,
        log_wandb: bool = False,
):
    rowd = OrderedDict(epoch=epoch)
    rowd.update([('train_' + k, v) for k, v in train_metrics.items()])
    if eval_metrics:
        rowd.update([('eval_' + k, v) for k, v in eval_metrics.items()])
    if lr is not None:
        rowd['lr'] = lr
    if log_wandb:
        try:
            import wandb
            wandb.log(rowd)
        except ImportError:
            pass
    with open(filename, mode='a') as cf:
        dw = csv.DictWriter(cf, fieldnames=rowd.keys())
        if write_header:
            dw.writeheader()
        dw.writerow(rowd)
