"""Legal-config enumeration for the autotuner.

The search space is {fsdp x tp x batch_size x grad_accum x block_scan x
remat} at a FIXED global batch (the same invariant elastic resume holds).
A point is legal iff:

  * mesh divisibility — ``fsdp * tp`` divides the per-slice device count
    (the exact rule `parallel.mesh.create_mesh` raises on);
  * batch divisibility — the loader batch shards evenly over the product of
    ALL mesh axes AND divides the global batch with ``accum <= max_accum``
    (the `shard_batch` / `rescale_for_devices` contract);
  * partition-rule legality — an axis must actually shard something: with
    ``fsdp > 1`` at least one param resolves to a spec containing 'fsdp',
    with ``tp > 1`` at least one to 'model' (a mesh axis that shards nothing
    is pure collective overhead — the degraded-placement regime
    `parallel/sharding.py` warns about);
  * HBM fit — per-device params + grads + optimizer state + activations
    (the `param_bytes_per_device` / `activation_bytes_per_device`
    calculators) stay under the budget.

Illegal points are not silently dropped: every pruned point becomes a
:class:`Rejection` carrying the same loud nearest-legal suggestion style
``shard_batch`` and ``rescale_for_devices`` pioneered, so `--autotune`
output explains WHY a config the user hoped for is absent.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    'CandidateConfig', 'LegalPoint', 'Rejection', 'enumerate_configs',
    'mesh_axis_points', 'batch_splits', 'OPT_SLOTS',
]

# AdamW carries two fp32 slots (m, v) per param shard; the HBM estimate and
# the analytic weight-traffic model both key off this.
OPT_SLOTS = 2

# Full remat saves only the per-block input (seq_len x width) instead of the
# ~(4 + mlp_ratio) working tensors activation_bytes_per_device counts, and
# buys it back with ~one extra forward (see cost.REMAT_FLOPS_FACTOR).
def _remat_fraction(mlp_ratio: float) -> float:
    return 1.0 / (4.0 + float(mlp_ratio))


@dataclasses.dataclass(frozen=True)
class CandidateConfig:
    """One point of the search space. ``fsdp``/``tp`` use 1 (not 0/None) for
    'axis omitted' — `flags()` converts back to the train.py convention."""
    fsdp: int = 1
    tp: int = 1
    batch_size: int = 8
    grad_accum: int = 1
    block_scan: bool = True
    remat: bool = False

    @property
    def global_batch(self) -> int:
        return self.batch_size * self.grad_accum

    def label(self) -> str:
        bits = [f'fsdp={self.fsdp}', f'tp={self.tp}',
                f'b={self.batch_size}', f'accum={self.grad_accum}']
        bits.append('scan' if self.block_scan else 'no-scan')
        if self.remat:
            bits.append('remat')
        return ' '.join(bits)

    def flags(self) -> str:
        """The train.py flag string that reproduces this point."""
        parts = [f'-b {self.batch_size}', f'--grad-accum-steps {self.grad_accum}']
        if self.fsdp > 1:
            parts.append(f'--fsdp {self.fsdp}')
        if self.tp > 1:
            parts.append(f'--tp {self.tp}')
        if self.block_scan:
            parts.append('--block-scan')
        if self.remat:
            parts.append('--grad-checkpointing')
        return ' '.join(parts)


@dataclasses.dataclass(frozen=True)
class LegalPoint:
    """A legal candidate plus the per-device byte estimates the legality
    check already computed (the cost model reuses them instead of
    re-deriving)."""
    config: CandidateConfig
    param_bytes_full: int       # one full (unsharded) copy of the params
    param_bytes: int            # per-device resident param bytes (sharded)
    opt_bytes: int              # per-device optimizer slots (OPT_SLOTS * sharded)
    act_bytes: int              # per-device activation residency at batch_size
    hbm_bytes: int              # the budget the point was admitted under


@dataclasses.dataclass(frozen=True)
class Rejection:
    point: str                  # human label of the pruned point / axis pair
    reason: str
    suggestion: str = ''

    def __str__(self) -> str:
        s = f'{self.point}: {self.reason}'
        return f'{s} ({self.suggestion})' if self.suggestion else s


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def mesh_axis_points(
        n_devices: int,
        num_slices: int = 1,
        allow_tp: bool = True,
        fsdp_candidates: Optional[Sequence[int]] = None,
        tp_candidates: Optional[Sequence[int]] = None,
) -> Tuple[List[Tuple[int, int]], List[Rejection]]:
    """All (fsdp, tp) pairs with ``fsdp * tp`` dividing the per-slice device
    count. Explicit candidate lists may contain illegal sizes — those come
    back as Rejections with the nearest legal pair (resolve_elastic_axes'
    largest-divisor clamp) as the suggestion."""
    from ..parallel.mesh import resolve_elastic_axes

    per_slice = max(1, int(n_devices) // max(1, int(num_slices)))
    fs = sorted(set(int(f) for f in (fsdp_candidates or _divisors(per_slice))))
    ts = sorted(set(int(t) for t in (tp_candidates or _divisors(per_slice)))) \
        if allow_tp else [1]
    points, rejected = [], []
    for f in fs:
        for t in ts:
            if f < 1 or t < 1:
                continue
            if per_slice % max(f * t, 1) == 0:
                points.append((f, t))
            else:
                cf, ct = resolve_elastic_axes(n_devices, fsdp=f, tp=t,
                                              num_slices=num_slices)
                rejected.append(Rejection(
                    point=f'fsdp={f} tp={t}',
                    reason=f'fsdp*tp = {f * t} does not divide the {per_slice} '
                           f'devices per slice (create_mesh would refuse)',
                    suggestion=f'nearest legal axes: fsdp={cf or 1} tp={ct or 1}'))
    return points, rejected


def batch_splits(global_batch: int, n_shards: int,
                 max_accum: int = 64) -> List[Tuple[int, int]]:
    """All (batch_size, accum) decompositions holding ``global_batch``
    constant with the batch sharding evenly over ``n_shards`` devices —
    exactly the candidate set `rescale_for_devices` picks one element of."""
    g, n = int(global_batch), int(n_shards)
    return [(b, g // b) for b in range(n, g + 1, n)
            if g % b == 0 and g // b <= int(max_accum)]


def _tree_bytes(params, mesh, rules) -> Tuple[int, int, bool, bool]:
    """(full_bytes, sharded_bytes, any_fsdp_sharded, any_tp_sharded) under
    the rule table — one `path_specs` pass instead of two calculators."""
    import jax
    import numpy as np

    from ..parallel.sharding import _kp_str, path_specs

    specs = path_specs(params, mesh, rules)
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    full = shard = 0
    any_fsdp = any_tp = False
    for kp, leaf in flat:
        shape = getattr(leaf, 'shape', ()) or (1,)
        nbytes = int(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
        full += nbytes
        spec = specs[_kp_str(kp)]
        div = 1
        for ax in spec:
            if ax is None:
                continue
            for a in (ax if isinstance(ax, tuple) else (ax,)):
                div *= int(mesh.shape[a])
                any_fsdp = any_fsdp or a == 'fsdp'
                any_tp = any_tp or a == 'model'
        shard += nbytes // div
    return full, shard, any_fsdp, any_tp


def enumerate_configs(
        *,
        n_devices: int,
        global_batch: int,
        params=None,
        model_dims: Optional[Tuple[int, int, int]] = None,
        hbm_budget_bytes: Optional[int] = None,
        num_slices: int = 1,
        max_accum: int = 64,
        allow_tp: bool = True,
        allow_remat: bool = True,
        include_block_scan: bool = True,
        fsdp_candidates: Optional[Sequence[int]] = None,
        tp_candidates: Optional[Sequence[int]] = None,
        rules=None,
        mlp_ratio: float = 4.0,
        devices: Optional[Sequence] = None,
) -> Tuple[List[LegalPoint], List[Rejection]]:
    """Enumerate every legal search-space point for a fixed global batch.

    ``params`` is a (possibly abstract — `nnx.eval_shape`) param pytree; when
    given, partition-rule legality and per-device byte estimates are computed
    against a REAL mesh built for each (fsdp, tp) pair, so every emitted
    point is guaranteed to survive `create_mesh` + `build_param_shardings`.
    ``model_dims`` = (seq_len, width, depth) feeds the activation calculator;
    without it activation bytes are reported as 0 (weight-only HBM check).

    Returns (legal_points, rejections); rejections carry loud nearest-legal
    suggestions in the `shard_batch` style.
    """
    from ..parallel.mesh import create_mesh
    from ..parallel.sharding import activation_bytes_per_device

    import jax

    n_devices = int(n_devices)
    legal: List[LegalPoint] = []
    rejected: List[Rejection] = []

    pairs, rejected_pairs = mesh_axis_points(
        n_devices, num_slices=num_slices, allow_tp=allow_tp,
        fsdp_candidates=fsdp_candidates, tp_candidates=tp_candidates)
    rejected.extend(rejected_pairs)

    splits = batch_splits(global_batch, n_devices, max_accum=max_accum)
    if not splits:
        g, n = int(global_batch), n_devices
        lo, hi = (g // n) * n, -(-g // n) * n
        nearest = str(hi) if lo <= 0 or lo == hi else f'{lo} or {hi}'
        rejected.append(Rejection(
            point=f'global_batch={g}',
            reason=f'no loader batch size b satisfies b % {n} == 0, '
                   f'{g} % b == 0 and {g} // b <= {max_accum} (grad-accum cap)',
            suggestion=f'nearest legal global batch: {nearest} '
                       f'(multiples of the mesh batch-shard count {n})'))
        return legal, rejected

    dev_list = list(devices) if devices is not None else list(jax.devices())
    can_mesh = params is not None and n_devices <= len(dev_list)

    scan_opts = (True, False) if include_block_scan else (True,)
    remat_opts = (False, True) if allow_remat else (False,)
    remat_frac = _remat_fraction(mlp_ratio)

    for fsdp, tp in pairs:
        mesh = None
        full = shard = 0
        any_fsdp = any_tp = False
        if can_mesh:
            mesh = create_mesh(devices=dev_list[:n_devices],
                               num_slices=num_slices,
                               fsdp=fsdp if fsdp > 1 else None,
                               tp=tp if tp > 1 else None)
            full, shard, any_fsdp, any_tp = _tree_bytes(params, mesh, rules)
            if fsdp > 1 and not any_fsdp:
                rejected.append(Rejection(
                    point=f'fsdp={fsdp} tp={tp}',
                    reason=f'no param shards over the fsdp axis under the rule '
                           f'table (every dim indivisible by {fsdp} or below '
                           f'the min shard size) — the axis is pure overhead',
                    suggestion='use a smaller fsdp, or tp instead'))
                continue
            if tp > 1 and not any_tp:
                rejected.append(Rejection(
                    point=f'fsdp={fsdp} tp={tp}',
                    reason=f'no param shards over the model axis under the rule '
                           f'table (head/hidden dims indivisible by {tp}) — '
                           f'tensor parallelism buys nothing here',
                    suggestion='use a tp that divides the head count and MLP '
                               'hidden dim, or fsdp instead'))
                continue
        opt_bytes = OPT_SLOTS * shard

        for batch_size, accum in splits:
            act = act_remat = 0
            if mesh is not None and model_dims is not None:
                seq_len, width, depth = model_dims
                _, act = activation_bytes_per_device(
                    mesh, batch_size=batch_size, seq_len=seq_len, width=width,
                    depth=depth, mlp_ratio=mlp_ratio)
                act_remat = int(act * remat_frac)
            for block_scan in scan_opts:
                for remat in remat_opts:
                    cfg = CandidateConfig(fsdp=fsdp, tp=tp,
                                          batch_size=batch_size,
                                          grad_accum=accum,
                                          block_scan=block_scan, remat=remat)
                    act_eff = act_remat if remat else act
                    # resident: sharded params + grads (same placement) +
                    # optimizer slots + live activations
                    hbm = shard * 2 + opt_bytes + act_eff
                    if hbm_budget_bytes is not None and hbm > hbm_budget_bytes:
                        biggest = _largest_fitting_batch(
                            shard, opt_bytes, act_eff, batch_size,
                            hbm_budget_bytes, n_devices, global_batch,
                            max_accum)
                        fix = ['enable --grad-checkpointing (remat)'] if not remat else []
                        if fsdp < n_devices:
                            fix.append('raise --fsdp')
                        if biggest:
                            fix.append(f'largest fitting batch size: {biggest}')
                        rejected.append(Rejection(
                            point=cfg.label(),
                            reason=f'estimated {hbm / 2**30:.2f} GiB/device exceeds '
                                   f'the {hbm_budget_bytes / 2**30:.2f} GiB HBM budget',
                            suggestion='; '.join(fix)))
                        continue
                    legal.append(LegalPoint(
                        config=cfg, param_bytes_full=full, param_bytes=shard,
                        opt_bytes=opt_bytes, act_bytes=act_eff, hbm_bytes=hbm))
    return legal, rejected


def _largest_fitting_batch(shard: int, opt_bytes: int, act: int,
                           batch_size: int, budget: int, n_shards: int,
                           global_batch: int, max_accum: int) -> Optional[int]:
    """Largest legal loader batch whose (linearly scaled) activation bytes
    fit the budget — the 'nearest legal' arm of an HBM rejection."""
    fixed = shard * 2 + opt_bytes
    if act <= 0 or fixed >= budget:
        return None
    per_sample = act / max(batch_size, 1)
    cap = int((budget - fixed) / per_sample)
    fitting = [b for b, _ in batch_splits(global_batch, n_shards, max_accum)
               if b <= cap]
    return max(fitting) if fitting else None
