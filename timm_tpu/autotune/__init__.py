"""Hands-free MFU: enumerate legal configs, rank them on a compiled-cost
roofline, apply the winner.

Surfaces: `train.py --autotune` (table + applied flags),
`python -m timm_tpu.autotune` (JSON), `autotune.propose_buckets` (serve
bucket-ladder advisory), and the elastic re-solve
(`resolve_config_for_topology`, called by `plan_elastic_resume`).

NOT imported by `timm_tpu/__init__.py` — importing this package pulls in
probe machinery lazily; all heavy imports happen inside functions.
"""
from .buckets import ladder_cost, ladder_waste, propose_buckets
from .cost import (
    DEVICE_CLASSES, CostEstimate, DeviceClass, analytic_cost,
    default_hbm_budget, detect_device_class, load_correction, probed_cost,
    roofline_ms,
)
from .solver import (
    AutotuneError, AutotuneResult, RankedPoint, apply_to_args, autotune,
    format_table, resolve_config_for_topology, to_json,
)
from .space import (
    CandidateConfig, LegalPoint, Rejection, batch_splits, enumerate_configs,
    mesh_axis_points,
)

__all__ = [
    'AutotuneError', 'AutotuneResult', 'CandidateConfig', 'CostEstimate',
    'DEVICE_CLASSES', 'DeviceClass', 'LegalPoint', 'RankedPoint', 'Rejection',
    'analytic_cost', 'apply_to_args', 'autotune', 'batch_splits',
    'default_hbm_budget', 'detect_device_class', 'enumerate_configs',
    'format_table', 'ladder_cost', 'ladder_waste', 'load_correction',
    'mesh_axis_points', 'probed_cost', 'propose_buckets',
    'resolve_config_for_topology', 'roofline_ms', 'to_json',
]
