"""Analytic roofline cost model over the legal-config space.

Three tiers, cheapest first; each is a strictly better-informed version of
the one below and all three rank with the SAME roofline:

  * ``analytic``  — closed-form transformer FLOPs (3x-forward rule over the
    attn/MLP matmuls) and a per-device byte-traffic model built from the
    enumerator's `param_bytes_per_device` numbers. Zero lowering; this is
    what the elastic re-solve runs in the restart pre-pass.
  * ``estimator`` — the analytic model rescaled so it passes EXACTLY through
    one probed anchor: `perfbudget.probe` lowers the real TrainingTask step
    once, and ``fit_scales`` divides XLA's compiled flops/bytes by the
    analytic prediction for the same point. Full enumeration then costs one
    compile, not hundreds.
  * ``probed``    — `--probe-top-k`: the shortlist's REAL programs are
    lowered and the roofline runs on their compiled `cost_analysis()`
    directly (trace time recorded as the tiebreak).

The roofline itself (Williams et al.): predicted step time is
``max(flops / peak_flops, bytes / hbm_bandwidth)`` per device class, with
trace/compile cost as a deterministic tiebreak (block_scan=False traces
O(depth) — it can never win a tie). A fitted live-hardware correction
factor (bench.py --replay step `autotune`, persisted in BENCH_SELF.json)
multiplies the predicted time; rankings are invariant to it but the printed
milliseconds become honest once hardware has answered.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional, Tuple

from .space import LegalPoint, OPT_SLOTS

__all__ = [
    'DeviceClass', 'DEVICE_CLASSES', 'detect_device_class', 'roofline_ms',
    'CostEstimate', 'analytic_flops', 'analytic_bytes', 'analytic_cost',
    'probed_cost', 'fit_scales', 'load_correction', 'REMAT_FLOPS_FACTOR',
]

# Full remat re-runs ~one forward of the fwd+bwd(≈3x fwd) step: 4/3 FLOPs.
REMAT_FLOPS_FACTOR = 4.0 / 3.0
# Train step ≈ forward + 2x backward (the 3x rule PERF.md measured at 3.05).
TRAIN_FLOPS_FACTOR = 3.0


@dataclasses.dataclass(frozen=True)
class DeviceClass:
    """Peak numbers per chip. bf16 matmul peak and HBM bandwidth are the
    roofline's two denominators; `hbm_bytes` is the default enumeration
    budget. The 'cpu' class exists so CPU runs rank deterministically —
    its absolute milliseconds are nominal, not meaningful."""
    name: str
    peak_flops: float   # bf16 FLOP/s
    hbm_bw: float       # bytes/s
    hbm_bytes: int      # capacity


# v5e numbers match PERF.md's ground truth (197e12 peak, 819 GB/s).
DEVICE_CLASSES: Dict[str, DeviceClass] = {
    'v4': DeviceClass('v4', 275e12, 1228e9, 32 << 30),
    'v5e': DeviceClass('v5e', 197e12, 819e9, 16 << 30),
    'v5p': DeviceClass('v5p', 459e12, 2765e9, 96 << 30),
    'v6e': DeviceClass('v6e', 918e12, 1640e9, 32 << 30),
    'cpu': DeviceClass('cpu', 1e12, 100e9, 4 << 30),
}


def detect_device_class(devices=None) -> DeviceClass:
    """Map `device_kind` strings onto the registry; unknown kinds fall back
    to 'cpu' (deterministic ranking with nominal constants)."""
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    kind = (getattr(devices[0], 'device_kind', '') or '').lower() if devices else ''
    for key in ('v6e', 'v5p', 'v5e', 'v4'):
        if key in kind or key.replace('v', 'tpu v') in kind:
            return DEVICE_CLASSES[key]
    if 'v5 lite' in kind or 'v5litepod' in kind:
        return DEVICE_CLASSES['v5e']
    return DEVICE_CLASSES['cpu']


def roofline_ms(flops: float, bytes_accessed: float,
                dc: DeviceClass) -> Tuple[float, float, float, str]:
    """(step_ms, compute_ms, memory_ms, bound): the max of the two service
    times, per device. Monotone in both inputs by construction."""
    compute_ms = 1e3 * float(flops) / dc.peak_flops
    memory_ms = 1e3 * float(bytes_accessed) / dc.hbm_bw
    if compute_ms >= memory_ms:
        return compute_ms, compute_ms, memory_ms, 'compute'
    return memory_ms, compute_ms, memory_ms, 'memory'


@dataclasses.dataclass(frozen=True)
class CostEstimate:
    step_ms: float          # predicted GLOBAL-step time (accum micro-steps)
    compute_ms: float
    memory_ms: float
    bound: str              # 'compute' | 'memory'
    tier: str               # 'analytic' | 'estimator' | 'probed'
    flops: float            # per-device, per global step
    bytes: float            # per-device, per global step
    trace_penalty: float    # deterministic tiebreak (block_scan off, depth)

    def sort_key(self) -> Tuple:
        """Total order: corrected time, then trace cost, then nothing —
        ties beyond that break on the candidate ordering the solver fixes."""
        return (round(self.step_ms, 6), round(self.trace_penalty, 6))


def analytic_flops(dims: Tuple[int, int, int], batch_size: int,
                   mlp_ratio: float = 4.0) -> float:
    """Whole-model train-step FLOPs for a batch (all devices combined).

    Per block and token: qkv (6LW^2 over the block: counted per token as
    6W^2), attention proj 2W^2, scores+apply 4LW, MLP 2*2*r*W^2 — times
    depth, times 3 for fwd+bwd. Patch embed/head are small and omitted;
    the estimator tier's fitted scale absorbs them."""
    seq_len, width, depth = (int(d) for d in dims)
    per_block = (6.0 + 2.0 + 4.0 * float(mlp_ratio)) * width * width \
        + 4.0 * seq_len * width
    fwd = float(batch_size) * seq_len * depth * per_block
    return TRAIN_FLOPS_FACTOR * fwd


def analytic_bytes(point: LegalPoint, n_devices: int) -> float:
    """Per-device HBM traffic for ONE GLOBAL step (accum micro-steps + one
    optimizer update).

    Each micro-step streams the full param bytes twice (fwd + bwd reads;
    under fsdp the all-gather still delivers full params to every device)
    plus ~2x the live activation bytes (written forward, read backward; the
    enumerator already discounted the remat fraction). The once-per-step
    update term reads+writes only the device's own shard: grads
    (reduce-scattered), OPT_SLOTS optimizer slots, and the param write."""
    cfg = point.config
    micro = 2.0 * point.param_bytes_full + 2.0 * point.act_bytes
    update = (3.0 + 2.0 * OPT_SLOTS) * point.param_bytes
    return cfg.grad_accum * micro + update


def analytic_cost(point: LegalPoint, dims: Optional[Tuple[int, int, int]],
                  dc: DeviceClass, n_devices: int, *,
                  mlp_ratio: float = 4.0,
                  flops_scale: float = 1.0, bytes_scale: float = 1.0,
                  correction: float = 1.0, tier: str = 'analytic') -> CostEstimate:
    """Roofline over the analytic model (optionally anchor-rescaled).

    FLOPs split evenly over devices (batch shards over every mesh axis;
    tp shards the matmuls themselves). `trace_penalty` charges
    block_scan=False a depth-proportional trace cost so the tiebreak always
    prefers the scanned program, mirroring the measured O(depth) contract."""
    cfg = point.config
    depth = int(dims[2]) if dims else 1
    if dims is not None:
        flops = analytic_flops(dims, cfg.batch_size, mlp_ratio) / max(n_devices, 1)
    else:
        flops = 0.0
    if cfg.remat:
        flops *= REMAT_FLOPS_FACTOR
    flops *= cfg.grad_accum * flops_scale
    bytes_ = analytic_bytes(point, n_devices) * bytes_scale
    step_ms, compute_ms, memory_ms, bound = roofline_ms(flops, bytes_, dc)
    penalty = float(depth if not cfg.block_scan else 1)
    return CostEstimate(step_ms=step_ms * correction, compute_ms=compute_ms,
                        memory_ms=memory_ms, bound=bound, tier=tier,
                        flops=flops, bytes=bytes_, trace_penalty=penalty)


def fit_scales(anchor_metrics: Dict, anchor_point: LegalPoint,
               dims: Tuple[int, int, int], dc: DeviceClass, n_devices: int,
               mlp_ratio: float = 4.0) -> Tuple[float, float]:
    """(flops_scale, bytes_scale) so the analytic model passes exactly
    through the probed anchor. `anchor_metrics` is a `perfbudget.probe`
    'full'-collect result for the anchor config (flops / bytes_accessed of
    the REAL compiled train step). Missing metrics leave that scale at 1."""
    base = analytic_cost(anchor_point, dims, dc, n_devices, mlp_ratio=mlp_ratio)
    flops_scale = bytes_scale = 1.0
    if anchor_metrics.get('flops') and base.flops > 0:
        flops_scale = float(anchor_metrics['flops']) / base.flops
    if anchor_metrics.get('bytes_accessed') and base.bytes > 0:
        bytes_scale = float(anchor_metrics['bytes_accessed']) / base.bytes
    return flops_scale, bytes_scale


def probed_cost(metrics: Dict, point: LegalPoint, dc: DeviceClass, *,
                correction: float = 1.0) -> Optional[CostEstimate]:
    """Roofline directly on a probed config's compiled cost analysis. The
    lowered program already contains the whole accum loop + update, so no
    scaling applies. Returns None when XLA reported no flops (the probe
    logged why — see `_cost_analysis`)."""
    if 'flops' not in metrics:
        return None
    flops = float(metrics['flops'])
    bytes_ = float(metrics.get('bytes_accessed', 0.0))
    step_ms, compute_ms, memory_ms, bound = roofline_ms(flops, bytes_, dc)
    return CostEstimate(step_ms=step_ms * correction, compute_ms=compute_ms,
                        memory_ms=memory_ms, bound=bound, tier='probed',
                        flops=flops, bytes=bytes_,
                        trace_penalty=float(metrics.get('trace_ms', 0.0)))


def load_correction(path: str = 'BENCH_SELF.json') -> float:
    """The fitted live-hardware correction factor the replay `autotune` step
    persisted (predicted->measured geomean ratio); 1.0 until a healthy relay
    window has verified the top-K."""
    try:
        with open(path, encoding='utf-8') as f:
            doc = json.load(f)
        c = float(doc.get('autotune', {}).get('correction', 1.0))
        return c if c > 0 else 1.0
    except (OSError, ValueError, TypeError):
        return 1.0


def default_hbm_budget(dc: DeviceClass) -> int:
    """Enumeration budget: the device's HBM minus a fixed XLA scratch
    reserve (env TIMM_TPU_AUTOTUNE_HBM_GB overrides end to end)."""
    env = os.environ.get('TIMM_TPU_AUTOTUNE_HBM_GB', '')
    if env:
        return int(float(env) * 2**30)
    return int(dc.hbm_bytes * 0.9)
