"""The autotuner: enumerate -> rank -> (optionally) probe -> apply.

`autotune()` is the one entry point behind every surface: `train.py
--autotune`, `python -m timm_tpu.autotune`, the replay checklist's
`autotune` step, and the elastic re-solve
(:func:`resolve_config_for_topology`). It holds the global batch exactly
constant — the same invariant elastic resume enforces — and only searches
placement/decomposition.

Elastic policy ("first, do no harm"): the re-solver returns the REQUESTED
config unchanged whenever it is legal on the live topology, so a working
run never churns its mesh (and the 8<->4 drill parity bound is untouched).
Only when the requested point is illegal — exactly when the old
largest-divisor clamp would have kicked in — does the cost model pick the
replacement, and the clamp remains the documented fallback when the solver
itself refuses (no model dims, no legal point, any internal error).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .cost import (
    CostEstimate, DeviceClass, analytic_cost, default_hbm_budget,
    detect_device_class, fit_scales, load_correction, probed_cost,
)
from .space import CandidateConfig, LegalPoint, Rejection, enumerate_configs

__all__ = ['AutotuneError', 'AutotuneResult', 'RankedPoint', 'autotune',
           'abstract_model_info', 'format_table', 'to_json', 'apply_to_args',
           'resolve_config_for_topology']


class AutotuneError(RuntimeError):
    """The solver cannot rank this request (no legal points, no model dims,
    ...). Carries the rejections so callers can print WHY."""

    def __init__(self, msg: str, rejections: Sequence[Rejection] = ()):
        super().__init__(msg)
        self.rejections = list(rejections)


@dataclasses.dataclass(frozen=True)
class RankedPoint:
    rank: int
    point: LegalPoint
    cost: CostEstimate
    probed: Optional[CostEstimate] = None   # set for the --probe-top-k shortlist

    @property
    def best(self) -> CostEstimate:
        return self.probed if self.probed is not None else self.cost

    @property
    def agreement(self) -> Optional[float]:
        """estimator/probed step-time ratio for shortlist points (the
        correction-factor protocol watches this band)."""
        if self.probed is None or self.probed.step_ms <= 0:
            return None
        return self.cost.step_ms / self.probed.step_ms


@dataclasses.dataclass
class AutotuneResult:
    model: str
    n_devices: int
    global_batch: int
    device_class: DeviceClass
    hbm_budget_bytes: int
    tier: str                       # best tier that ran: analytic|estimator|probed
    ranked: List[RankedPoint]
    rejections: List[Rejection]
    correction: float
    anchor: Dict                    # {'config': label, 'flops': ..., ...} or {}

    @property
    def winner(self) -> CandidateConfig:
        return self.ranked[0].point.config


def abstract_model_info(model: str, model_kwargs: Optional[Dict] = None):
    """(abstract param pytree, (seq_len, width, depth) or None, mlp_ratio)
    without materializing a single array: `nnx.eval_shape` runs the model
    constructor abstractly, and the probe helper reads the ViT dims off it."""
    from flax import nnx

    import timm_tpu
    from ..perfbudget.probe import _model_dims

    kwargs = dict(model_kwargs or {})
    try:
        abs_model = nnx.eval_shape(lambda: timm_tpu.create_model(model, **kwargs))
    except TypeError as e:
        # mirror train.py's _build_model: fixed-field models take no img_size
        if 'img_size' not in str(e) or 'img_size' not in kwargs:
            raise
        kwargs.pop('img_size')
        abs_model = nnx.eval_shape(lambda: timm_tpu.create_model(model, **kwargs))
    params = nnx.state(abs_model, nnx.Param)
    dims = _model_dims(abs_model)
    mlp_ratio = 4.0
    blocks = getattr(abs_model, 'blocks', None)
    try:
        fc1 = blocks[0].mlp.fc1.kernel.value.shape  # type: ignore[index]
        mlp_ratio = float(fc1[1]) / float(fc1[0])
    except (TypeError, AttributeError, IndexError, KeyError):
        pass
    return params, dims, mlp_ratio


def _probe_point(model: str, model_kwargs: Optional[Dict],
                 cfg: CandidateConfig, name: str) -> Dict:
    """Lower the REAL TrainingTask step for one candidate via the perfbudget
    probe (collect='full': compiled flops/bytes/donation + trace time)."""
    from ..perfbudget.probe import ProbeConfig, probe_config

    return probe_config(ProbeConfig(
        name=name, model=model,
        model_kwargs=tuple(sorted((model_kwargs or {}).items())),
        batch_size=cfg.batch_size, fsdp=cfg.fsdp, tp=cfg.tp,
        block_scan=cfg.block_scan if cfg.block_scan is not None else None,
        grad_accum=cfg.grad_accum, collect='full'))


def autotune(
        model: str,
        model_kwargs: Optional[Dict] = None,
        *,
        global_batch: int,
        n_devices: Optional[int] = None,
        num_slices: int = 1,
        hbm_budget_bytes: Optional[int] = None,
        probe_top_k: int = 0,
        probe_anchor: bool = True,
        anchor_metrics: Optional[Dict] = None,
        anchor_config: Optional[CandidateConfig] = None,
        max_accum: int = 64,
        allow_tp: bool = True,
        allow_remat: bool = True,
        include_block_scan: bool = True,
        fsdp_candidates: Optional[Sequence[int]] = None,
        tp_candidates: Optional[Sequence[int]] = None,
        device_class: Optional[DeviceClass] = None,
        correction: Optional[float] = None,
        log=None,
) -> AutotuneResult:
    """Rank every legal config for `model` at a fixed global batch.

    Tier selection: with ``anchor_metrics`` (or ``probe_anchor=True``) the
    estimator tier calibrates the analytic model against one probed anchor;
    ``probe_top_k > 0`` additionally lowers the shortlist's real programs
    and re-ranks it on their compiled costs. ``probe_anchor=False`` with no
    metrics runs the pure-analytic tier (the elastic re-solve path — zero
    lowering in the restart pre-pass)."""
    import jax

    n_devices = int(n_devices) if n_devices else jax.device_count()
    dc = device_class or detect_device_class()
    budget = hbm_budget_bytes if hbm_budget_bytes is not None else default_hbm_budget(dc)
    correction = load_correction() if correction is None else float(correction)

    params, dims, mlp_ratio = abstract_model_info(model, model_kwargs)
    if dims is None:
        raise AutotuneError(
            f'autotune: model {model!r} exposes no (pos_embed, blocks) ViT '
            f'dims — the analytic cost model cannot rank it (fallback: run '
            f'the probed tier per config by hand via perfbudget)')

    legal, rejections = enumerate_configs(
        n_devices=n_devices, global_batch=global_batch, params=params,
        model_dims=dims, hbm_budget_bytes=budget, num_slices=num_slices,
        max_accum=max_accum, allow_tp=allow_tp, allow_remat=allow_remat,
        include_block_scan=include_block_scan,
        fsdp_candidates=fsdp_candidates, tp_candidates=tp_candidates,
        mlp_ratio=mlp_ratio)
    if not legal:
        raise AutotuneError(
            f'autotune: no legal config for {model!r} at global batch '
            f'{global_batch} on {n_devices} devices — '
            + '; '.join(str(r) for r in rejections[:4]), rejections)
    if log:
        log(f'autotune: {len(legal)} legal points, {len(rejections)} rejected '
            f'({dc.name}, budget {budget / 2**30:.1f} GiB/device)')

    # ---- anchor (estimator tier) -------------------------------------------
    tier = 'analytic'
    anchor_info: Dict = {}
    flops_scale = bytes_scale = 1.0
    by_cfg = {p.config: p for p in legal}
    if anchor_metrics is None and probe_anchor:
        a_cfg = anchor_config or _default_anchor(legal)
        anchor_metrics = _probe_point(model, model_kwargs, a_cfg, 'autotune_anchor')
        anchor_config = a_cfg
    if anchor_metrics is not None:
        a_cfg = anchor_config or _default_anchor(legal)
        a_point = by_cfg.get(a_cfg) or _anchor_point(
            a_cfg, params, dims, n_devices, num_slices, mlp_ratio)
        flops_scale, bytes_scale = fit_scales(
            anchor_metrics, a_point, dims, dc, n_devices, mlp_ratio)
        tier = 'estimator'
        anchor_info = {'config': a_cfg.label(),
                       'flops': anchor_metrics.get('flops'),
                       'bytes_accessed': anchor_metrics.get('bytes_accessed'),
                       'flops_scale': round(flops_scale, 4),
                       'bytes_scale': round(bytes_scale, 4)}
        if log:
            log(f'autotune: anchor {a_cfg.label()} -> scales '
                f'flops x{flops_scale:.3g}, bytes x{bytes_scale:.3g}')

    # ---- rank ---------------------------------------------------------------
    scored = [(p, analytic_cost(p, dims, dc, n_devices, mlp_ratio=mlp_ratio,
                                flops_scale=flops_scale, bytes_scale=bytes_scale,
                                correction=correction, tier=tier))
              for p in legal]
    scored.sort(key=lambda pc: pc[1].sort_key() + _stable_key(pc[0].config))

    # ---- probe the shortlist (--probe-top-k) --------------------------------
    probed: Dict[CandidateConfig, CostEstimate] = {}
    if probe_top_k > 0:
        for i, (p, _c) in enumerate(scored[:probe_top_k]):
            metrics = _probe_point(model, model_kwargs, p.config,
                                   f'autotune_probe{i}')
            est = probed_cost(metrics, p, dc, correction=correction)
            if est is not None:
                probed[p.config] = est
            if log:
                log(f'autotune: probed #{i + 1} {p.config.label()} -> '
                    + (f'{est.step_ms:.3f} ms ({est.bound}-bound)' if est
                       else 'no cost analysis (ranked by estimator)'))
        if probed:
            tier = 'probed'
            # re-rank the shortlist on real compiled costs; the tail keeps
            # its estimator order below every probed point's re-ranked slot
            head = sorted(scored[:probe_top_k],
                          key=lambda pc: (probed.get(pc[0].config, pc[1]).sort_key()
                                          + _stable_key(pc[0].config)))
            scored = head + scored[probe_top_k:]

    ranked = [RankedPoint(rank=i + 1, point=p, cost=c,
                          probed=probed.get(p.config))
              for i, (p, c) in enumerate(scored)]
    return AutotuneResult(model=model, n_devices=n_devices,
                          global_batch=int(global_batch), device_class=dc,
                          hbm_budget_bytes=int(budget), tier=tier,
                          ranked=ranked, rejections=rejections,
                          correction=correction, anchor=anchor_info)


def _stable_key(cfg: CandidateConfig) -> Tuple:
    """Total-order tail so equal-cost points rank deterministically:
    prefer larger batch (fewer sequential micro-steps), then smaller axes,
    scan on, remat off."""
    return (cfg.grad_accum, cfg.fsdp, cfg.tp, not cfg.block_scan, cfg.remat)


def _default_anchor(legal: Sequence[LegalPoint]) -> CandidateConfig:
    """Deterministic anchor: the cheapest-to-lower legal point — smallest
    batch, no tp, smallest fsdp, scanned, no remat, accum=1."""
    def key(p: LegalPoint):
        c = p.config
        return (c.tp != 1, c.fsdp != 1, c.batch_size, c.grad_accum,
                not c.block_scan, c.remat)
    base = min(legal, key=key).config
    return dataclasses.replace(base, grad_accum=1, remat=False,
                               block_scan=True,
                               batch_size=min(p.config.batch_size for p in legal))


def _anchor_point(cfg: CandidateConfig, params, dims, n_devices: int,
                  num_slices: int, mlp_ratio: float) -> LegalPoint:
    """LegalPoint byte estimates for an anchor that is not in the enumerated
    set (e.g. its batch does not divide the requested global batch)."""
    pts, _rej = enumerate_configs(
        n_devices=n_devices, global_batch=cfg.global_batch, params=params,
        model_dims=dims, hbm_budget_bytes=None, num_slices=num_slices,
        allow_tp=cfg.tp > 1, allow_remat=cfg.remat,
        include_block_scan=not cfg.block_scan,
        fsdp_candidates=(cfg.fsdp,), tp_candidates=(cfg.tp,),
        mlp_ratio=mlp_ratio)
    for p in pts:
        if p.config == cfg:
            return p
    raise AutotuneError(f'anchor config {cfg.label()} is not legal on this topology')


# ---- output surfaces --------------------------------------------------------

def format_table(result: AutotuneResult, top: int = 10) -> str:
    """The ranked table `train.py --autotune` prints."""
    dc = result.device_class
    lines = [
        f'autotune: {result.model} | global batch {result.global_batch} | '
        f'{result.n_devices}x {dc.name} ({dc.peak_flops / 1e12:.0f} TF/s, '
        f'{dc.hbm_bw / 1e9:.0f} GB/s, budget '
        f'{result.hbm_budget_bytes / 2**30:.1f} GiB) | tier: {result.tier}'
        + (f' | correction x{result.correction:.3f}'
           if result.correction != 1.0 else ''),
        f'{"#":>3} {"config":<38} {"ms/step":>9} {"bound":>7} '
        f'{"GiB/dev":>8} {"tier":>9} {"est/probe":>9}',
    ]
    for rp in result.ranked[:top]:
        est = rp.best
        agree = f'{rp.agreement:.2f}' if rp.agreement is not None else '-'
        lines.append(
            f'{rp.rank:>3} {rp.point.config.label():<38} {est.step_ms:>9.3f} '
            f'{est.bound:>7} {rp.point.hbm_bytes / 2**30:>8.2f} '
            f'{est.tier:>9} {agree:>9}')
    if result.rejections:
        lines.append(f'pruned {len(result.rejections)} illegal point(s); first:')
        for r in result.rejections[:3]:
            lines.append(f'  - {r}')
    lines.append(f'winner: {result.winner.label()}  ->  {result.winner.flags()}')
    return '\n'.join(lines)


def to_json(result: AutotuneResult, top: Optional[int] = None) -> Dict:
    """The machine surface (`python -m timm_tpu.autotune`)."""
    def cost_dict(c: Optional[CostEstimate]):
        if c is None:
            return None
        return {'step_ms': round(c.step_ms, 6), 'bound': c.bound,
                'tier': c.tier, 'flops': c.flops, 'bytes': c.bytes,
                'compute_ms': round(c.compute_ms, 6),
                'memory_ms': round(c.memory_ms, 6)}

    return {
        'schema': 'autotune/v1',
        'model': result.model,
        'n_devices': result.n_devices,
        'global_batch': result.global_batch,
        'device_class': result.device_class.name,
        'hbm_budget_bytes': result.hbm_budget_bytes,
        'tier': result.tier,
        'correction': result.correction,
        'anchor': result.anchor,
        'winner': dataclasses.asdict(result.winner),
        'winner_flags': result.winner.flags(),
        'ranked': [{
            'rank': rp.rank,
            'config': dataclasses.asdict(rp.point.config),
            'hbm_bytes': rp.point.hbm_bytes,
            'cost': cost_dict(rp.cost),
            'probed': cost_dict(rp.probed),
            'agreement': rp.agreement,
        } for rp in (result.ranked[:top] if top else result.ranked)],
        'rejections': [{'point': r.point, 'reason': r.reason,
                        'suggestion': r.suggestion} for r in result.rejections],
    }


def apply_to_args(args, result: AutotuneResult) -> List[str]:
    """Write the winner's flags onto a train.py argparse namespace; returns
    human-readable change notes for the resume log."""
    w = result.winner
    notes = []

    def set_attr(name, new, old):
        if new != old:
            notes.append(f'{name}: {old} -> {new}')
        setattr(args, name, new)

    set_attr('fsdp', w.fsdp if w.fsdp > 1 else 0, getattr(args, 'fsdp', 0))
    set_attr('tp', w.tp if w.tp > 1 else 0, getattr(args, 'tp', 0))
    set_attr('batch_size', w.batch_size, getattr(args, 'batch_size', None))
    set_attr('grad_accum_steps', w.grad_accum,
             getattr(args, 'grad_accum_steps', 1))
    set_attr('block_scan', bool(w.block_scan), getattr(args, 'block_scan', False))
    set_attr('grad_checkpointing', bool(w.remat),
             getattr(args, 'grad_checkpointing', False))
    return notes


# ---- elastic re-solve -------------------------------------------------------

def resolve_config_for_topology(
        n_devices: int,
        global_batch: int,
        *,
        model: str,
        model_kwargs: Optional[Dict] = None,
        fsdp: Optional[int] = None,
        tp: Optional[int] = None,
        prefer_batch_size: Optional[int] = None,
        num_slices: int = 1,
        max_accum: int = 64,
) -> Optional[CandidateConfig]:
    """Re-solve (fsdp, tp, batch_size, accum) for a changed topology,
    holding the global batch exactly constant. Returns None when the solver
    refuses (caller falls back to the largest-divisor clamp + rescale).

    Policy (see module docstring): if the REQUESTED config is legal on the
    live topology it is returned unchanged — a working run never churns its
    mesh, and at an unchanged topology the re-solve is the identity. Only
    an illegal request is re-solved, by analytic-roofline rank (no lowering
    happens in the restart pre-pass), with the batch-size preference as the
    final tie-break."""
    fsdp_req = int(fsdp) if fsdp and int(fsdp) > 1 else 1
    tp_req = int(tp) if tp and int(tp) > 1 else 1
    result = autotune(
        model, model_kwargs, global_batch=int(global_batch),
        n_devices=int(n_devices), num_slices=num_slices, max_accum=max_accum,
        allow_tp=tp_req > 1, allow_remat=False, include_block_scan=False,
        probe_anchor=False, correction=1.0)

    prefer = int(prefer_batch_size) if prefer_batch_size else int(global_batch)
    legal = {rp.point.config: rp for rp in result.ranked}

    # identity fast-path: the requested point, if legal, wins outright
    if prefer_batch_size:
        requested = CandidateConfig(
            fsdp=fsdp_req, tp=tp_req, batch_size=prefer,
            grad_accum=int(global_batch) // max(prefer, 1),
            block_scan=True, remat=False)
        if requested.global_batch == int(global_batch) and requested in legal:
            return requested

    # otherwise: best cost, preferring the requested axes and batch among
    # near-ties (same step_ms after rounding)
    best = min(legal.values(), key=lambda rp: rp.cost.sort_key() + (
        abs(rp.point.config.fsdp - fsdp_req),
        abs(rp.point.config.tp - tp_req),
        abs(rp.point.config.batch_size - prefer),
        _stable_key(rp.point.config)))
    return best.point.config
