"""Serve bucket-ladder proposal: minimize padded-FLOPs waste.

The serve engine pads every dispatched batch up to its bucket
(`serve/bucketing.select_bucket`), so each request of size ``s`` costs
``bucket(s)`` rows of compute. Given the request-size histogram a running
engine accumulates, the optimal ladder of at most ``max_buckets`` rungs
minimizes ``sum_s count[s] * bucket(s)`` — computed rows, which is padded
FLOPs up to the per-row constant.

This is the classic 1-D DP: since an optimal ladder only ever needs rungs
at (divisor-rounded-up) observed sizes, sort the distinct sizes and let
``best[i][k]`` = min cost of covering the first i sizes with k rungs where
the k-th rung sits exactly at size i. O(n^2 * k) for n distinct sizes —
trivially small against real histograms, and small enough to brute-force
check in tests.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ['propose_buckets', 'ladder_cost', 'ladder_waste']


def _round_up(n: int, divisor: int) -> int:
    return -(-int(n) // max(1, int(divisor))) * max(1, int(divisor))


def ladder_cost(buckets: Sequence[int], histogram: Dict[int, int]) -> int:
    """Total computed rows: every request of size s pays its smallest
    covering rung (requests above the top rung split; the overflow part pays
    full rungs — same accounting `select_bucket` + chunking implies)."""
    rungs = sorted(int(b) for b in buckets)
    if not rungs:
        raise ValueError('empty bucket ladder')
    top = rungs[-1]
    total = 0
    for size, count in histogram.items():
        s, c = int(size), int(count)
        if s <= 0 or c <= 0:
            continue
        full, rem = divmod(s, top)
        rows = full * top
        if rem:
            rows += next(b for b in rungs if b >= rem)
        total += c * max(rows, rungs[0])
    return total


def ladder_waste(buckets: Sequence[int], histogram: Dict[int, int]) -> float:
    """Fraction of computed rows that is padding (0.0 = perfect ladder)."""
    useful = sum(int(s) * int(c) for s, c in histogram.items()
                 if int(s) > 0 and int(c) > 0)
    cost = ladder_cost(buckets, histogram)
    return (cost - useful) / cost if cost else 0.0


def propose_buckets(
        histogram: Dict[int, int],
        *,
        max_buckets: int = 5,
        divisor: int = 1,
        max_bucket: Optional[int] = None,
) -> Tuple[int, ...]:
    """The ladder (at most ``max_buckets`` rungs, every rung a multiple of
    ``divisor``) minimizing `ladder_cost` against the histogram.

    Candidate rungs are the distinct observed sizes rounded up to the
    divisor (an optimal rung always sits at one — lowering a rung onto the
    next observed size below it never increases any request's cost), capped
    at ``max_bucket`` when given. Deterministic: ties prefer fewer, smaller
    rungs."""
    sizes = sorted({min(_round_up(s, divisor), _round_up(max_bucket, divisor))
                    if max_bucket else _round_up(s, divisor)
                    for s, c in histogram.items() if int(s) > 0 and int(c) > 0})
    if not sizes:
        raise ValueError('propose_buckets: empty request-size histogram')
    max_buckets = max(1, int(max_buckets))

    # weight[j] = requests whose (capped, divisor-rounded) size is sizes[j]
    weight = [0] * len(sizes)
    for s, c in histogram.items():
        if int(s) <= 0 or int(c) <= 0:
            continue
        r = _round_up(s, divisor)
        if max_bucket:
            r = min(r, _round_up(max_bucket, divisor))
        weight[sizes.index(r)] += int(c)

    n = len(sizes)
    INF = float('inf')
    # best[k][i]: min rows covering sizes[0..i] with k rungs, top rung at i
    best = [[INF] * n for _ in range(max_buckets + 1)]
    back: List[List[Optional[Tuple[int, int]]]] = \
        [[None] * n for _ in range(max_buckets + 1)]
    # prefix weights for O(1) range sums
    pref = [0]
    for w in weight:
        pref.append(pref[-1] + w)

    for i in range(n):
        best[1][i] = sizes[i] * pref[i + 1]
    for k in range(2, max_buckets + 1):
        for i in range(k - 1, n):
            for j in range(k - 2, i):
                cand = best[k - 1][j] + sizes[i] * (pref[i + 1] - pref[j + 1])
                if cand < best[k][i]:
                    best[k][i] = cand
                    back[k][i] = (k - 1, j)

    # the ladder must cover the largest observed size: top rung at n-1
    k_best = min(range(1, max_buckets + 1), key=lambda k: (best[k][n - 1], k))
    rungs = []
    k, i = k_best, n - 1
    while True:
        rungs.append(sizes[i])
        step = back[k][i]
        if step is None:
            break
        k, i = step
    return tuple(sorted(rungs))
