"""CLI: rank configs for a model and emit the table as JSON.

    python -m timm_tpu.autotune --model vit_base_patch16_224 --global-batch 1024
    python -m timm_tpu.autotune --model test_vit --global-batch 64 \
        --model-kwargs '{"num_classes": 10, "img_size": 32}' --probe-top-k 3
    python -m timm_tpu.autotune ... --table        # human table on stderr too

The probe-backed tiers need the forced 8-virtual-CPU-device topology when no
accelerator is attached (same constraint as perfbudget): re-exec once with
XLA_FLAGS set, guarded so a topology that still comes up short fails loudly
instead of looping. `--devices N` skips the re-exec and enumerates for a
hypothetical topology (analytic tier only — no probing a mesh we don't have).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REQUIRED_DEVICES = 8
_REEXEC_GUARD = 'TIMM_TPU_AUTOTUNE_REEXEC'


def _maybe_reexec(argv) -> None:
    import jax
    if jax.device_count() >= _REQUIRED_DEVICES or os.environ.get(_REEXEC_GUARD):
        return
    env = dict(os.environ)
    flags = env.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={_REQUIRED_DEVICES}').strip()
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env[_REEXEC_GUARD] = '1'
    raise SystemExit(subprocess.call(
        [sys.executable, '-m', 'timm_tpu.autotune'] + list(argv), env=env))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(prog='python -m timm_tpu.autotune')
    parser.add_argument('--model', required=True)
    parser.add_argument('--model-kwargs', default='{}', metavar='JSON',
                        help='create_model kwargs, e.g. \'{"img_size": 32}\'')
    parser.add_argument('--global-batch', type=int, required=True,
                        help='global batch held exactly constant across the search')
    parser.add_argument('--devices', type=int, default=0,
                        help='enumerate for N devices instead of the live '
                             'topology (analytic tier only, no probing)')
    parser.add_argument('--num-slices', type=int, default=1)
    parser.add_argument('--hbm-gb', type=float, default=0.0,
                        help='per-device HBM budget override in GiB '
                             '(default: 90%% of the detected device class)')
    parser.add_argument('--probe-top-k', type=int, default=0,
                        help='lower the top-K real programs and re-rank on '
                             'their compiled costs')
    parser.add_argument('--no-probe-anchor', action='store_true',
                        help='skip the one-anchor estimator calibration '
                             '(pure analytic tier)')
    parser.add_argument('--max-accum', type=int, default=64)
    parser.add_argument('--no-tp', action='store_true')
    parser.add_argument('--no-remat', action='store_true')
    parser.add_argument('--top', type=int, default=0,
                        help='truncate the emitted ranking to N rows')
    parser.add_argument('--table', action='store_true',
                        help='also print the human table on stderr')
    args = parser.parse_args(argv)

    hypothetical = bool(args.devices)
    if not hypothetical:
        _maybe_reexec(argv)

    from .solver import AutotuneError, autotune, format_table, to_json

    try:
        result = autotune(
            args.model, json.loads(args.model_kwargs),
            global_batch=args.global_batch,
            n_devices=args.devices or None,
            num_slices=args.num_slices,
            hbm_budget_bytes=int(args.hbm_gb * 2**30) if args.hbm_gb else None,
            probe_top_k=0 if hypothetical else args.probe_top_k,
            probe_anchor=not (hypothetical or args.no_probe_anchor),
            max_accum=args.max_accum,
            allow_tp=not args.no_tp,
            allow_remat=not args.no_remat,
            log=lambda m: print(m, file=sys.stderr, flush=True))
    except AutotuneError as e:
        print(json.dumps({'schema': 'autotune/v1', 'error': str(e),
                          'rejections': [str(r) for r in e.rejections]},
                         indent=1))
        return 1

    if args.table:
        print(format_table(result), file=sys.stderr, flush=True)
    print(json.dumps(to_json(result, top=args.top or None), indent=1))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
