"""Global pooling for token (NLC) and spatial (NHWC) features
(reference: timm/layers/pool1d.py, adaptive_avgmax_pool.py).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import nnx

__all__ = ['Pool2d', 'SelectAdaptivePool2d', 'adaptive_pool_feat_mult', 'create_pool2d', 'global_pool_nlc']


class Pool2d:
    """Static NHWC max/avg pool with explicit torch-style padding
    (reference layers/create_pool2d — XLA reduce_window under the hood).
    Avg pool uses count_include_pad=False semantics (divides by valid count)."""

    def __init__(self, pool_type: str, kernel_size, stride=None, padding=0):
        from .helpers import to_2tuple
        self.pool_type = pool_type
        self.kernel = to_2tuple(kernel_size)
        self.stride = to_2tuple(stride if stride is not None else kernel_size)
        self.same = isinstance(padding, str) and padding.lower() == 'same'
        self.padding = (0, 0) if self.same else to_2tuple(padding)

    def _pads(self, H: int, W: int):
        if not self.same:
            ph, pw = self.padding
            return ((ph, ph), (pw, pw))
        # TF-SAME: possibly asymmetric, low = total // 2
        out = []
        for size, k, s in zip((H, W), self.kernel, self.stride):
            total = max((-(-size // s) - 1) * s + k - size, 0)
            out.append((total // 2, total - total // 2))
        return tuple(out)

    def __call__(self, x):
        import jax
        import jax.numpy as jnp
        kh, kw = self.kernel
        sh, sw = self.stride
        (pht, phb), (pwl, pwr) = self._pads(x.shape[1], x.shape[2])
        pads = ((0, 0), (pht, phb), (pwl, pwr), (0, 0))
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.pool_type == 'max':
            xp = jnp.pad(x, pads, constant_values=-jnp.inf)
            return jax.lax.reduce_window(xp, -jnp.inf, jax.lax.max, window, strides, 'VALID')
        xp = jnp.pad(x, pads)
        sums = jax.lax.reduce_window(xp, 0.0, jax.lax.add, window, strides, 'VALID')
        if pht == 0 and phb == 0 and pwl == 0 and pwr == 0:
            return sums / (kh * kw)
        ones = jnp.pad(jnp.ones(x.shape[1:3], x.dtype), ((pht, phb), (pwl, pwr)))
        counts = jax.lax.reduce_window(ones[None, :, :, None], 0.0, jax.lax.add, window, strides, 'VALID')
        return sums / counts


def create_pool2d(pool_type: str, kernel_size, stride=None, padding=0, count_include_pad: bool = False):
    """Factory matching the reference create_pool2d surface for max/avg.

    Only count_include_pad=False avg semantics are implemented (every shipped
    caller uses it); requesting True raises rather than silently diverging.
    """
    assert pool_type in ('max', 'avg')
    if count_include_pad:
        raise NotImplementedError('count_include_pad=True avg pooling not supported')
    return Pool2d(pool_type, kernel_size, stride=stride, padding=padding)


def global_pool_nlc(
        x,
        pool_type: str = 'token',
        num_prefix_tokens: int = 1,
        reduce_include_prefix: bool = False,
        mask=None,
):
    """Pool (B, N, C) tokens → (B, C). Mirrors reference pool1d.py:global_pool_nlc.

    `mask` is an optional key-padding mask, True = valid token, broadcastable
    to (B, N) (e.g. (N,), (B, N) or (B, 1, 1, N)): reductions then ignore
    padded tokens (masked mean divides by the valid count; masked max fills
    pads with -inf). Used by the tile-aligned token-padding path when pooling
    runs on a still-padded sequence; `mask=None` is the exact legacy path.
    """
    if not pool_type:
        return x
    if pool_type == 'token':
        return x[:, 0]
    if mask is not None:
        mask = jnp.reshape(mask, (mask.shape[0] if mask.ndim > 1 else 1, -1))  # (B|1, N)
    if not reduce_include_prefix:
        x = x[:, num_prefix_tokens:]
        if mask is not None:
            mask = mask[:, num_prefix_tokens:]
    if mask is None:
        if pool_type == 'avg':
            return x.mean(axis=1)
        if pool_type == 'max':
            return x.max(axis=1)
        if pool_type == 'avgmax':
            return 0.5 * (x.max(axis=1) + x.mean(axis=1))
        raise ValueError(f'Unknown pool type {pool_type}')
    m = mask[..., None]  # (B|1, N, 1)
    count = jnp.maximum(m.sum(axis=1), 1).astype(x.dtype)

    def _masked_avg():
        return jnp.where(m, x, 0).sum(axis=1) / count

    def _masked_max():
        return jnp.where(m, x, jnp.asarray(-jnp.inf, x.dtype)).max(axis=1)

    if pool_type == 'avg':
        return _masked_avg()
    if pool_type == 'max':
        return _masked_max()
    if pool_type == 'avgmax':
        return 0.5 * (_masked_max() + _masked_avg())
    raise ValueError(f'Unknown pool type {pool_type}')


def adaptive_pool_feat_mult(pool_type: str = 'avg') -> int:
    return 2 if pool_type.endswith('catavgmax') else 1


class SelectAdaptivePool2d(nnx.Module):
    """Global pooling over NHWC spatial dims with selectable mode.

    The reference's 'fast' NHWC variants (adaptive_avgmax_pool.py) are the
    *only* variants here — NHWC reductions are native on TPU.
    """

    def __init__(self, output_size=1, pool_type: str = 'avg', flatten: bool = False, input_fmt: str = 'NHWC'):
        assert input_fmt in ('NHWC', 'NCHW')
        self.pool_type = pool_type or ''
        self.flatten = flatten

    def is_identity(self) -> bool:
        return not self.pool_type

    def feat_mult(self) -> int:
        return adaptive_pool_feat_mult(self.pool_type)

    def __call__(self, x):
        # x: (B, H, W, C)
        if not self.pool_type:
            return x
        pt = self.pool_type
        if pt.startswith('fast'):
            pt = pt[4:].lstrip('_') or 'avg'
        if pt == 'avg':
            out = x.mean(axis=(1, 2))
        elif pt == 'max':
            out = x.max(axis=(1, 2))
        elif pt == 'avgmax':
            out = 0.5 * (x.mean(axis=(1, 2)) + x.max(axis=(1, 2)))
        elif pt == 'catavgmax':
            out = jnp.concatenate([x.mean(axis=(1, 2)), x.max(axis=(1, 2))], axis=-1)
        else:
            raise ValueError(f'Invalid pool type: {self.pool_type}')
        return out  # already flat (B, C[*2])
