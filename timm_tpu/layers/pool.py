"""Global pooling for token (NLC) and spatial (NHWC) features
(reference: timm/layers/pool1d.py, adaptive_avgmax_pool.py).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import nnx

__all__ = ['global_pool_nlc', 'SelectAdaptivePool2d', 'adaptive_pool_feat_mult']


def global_pool_nlc(
        x,
        pool_type: str = 'token',
        num_prefix_tokens: int = 1,
        reduce_include_prefix: bool = False,
):
    """Pool (B, N, C) tokens → (B, C). Mirrors reference pool1d.py:global_pool_nlc."""
    if not pool_type:
        return x
    if pool_type == 'token':
        return x[:, 0]
    if not reduce_include_prefix:
        x = x[:, num_prefix_tokens:]
    if pool_type == 'avg':
        return x.mean(axis=1)
    if pool_type == 'max':
        return x.max(axis=1)
    if pool_type == 'avgmax':
        return 0.5 * (x.max(axis=1) + x.mean(axis=1))
    raise ValueError(f'Unknown pool type {pool_type}')


def adaptive_pool_feat_mult(pool_type: str = 'avg') -> int:
    return 2 if pool_type.endswith('catavgmax') else 1


class SelectAdaptivePool2d(nnx.Module):
    """Global pooling over NHWC spatial dims with selectable mode.

    The reference's 'fast' NHWC variants (adaptive_avgmax_pool.py) are the
    *only* variants here — NHWC reductions are native on TPU.
    """

    def __init__(self, output_size=1, pool_type: str = 'avg', flatten: bool = False, input_fmt: str = 'NHWC'):
        assert input_fmt in ('NHWC', 'NCHW')
        self.pool_type = pool_type or ''
        self.flatten = flatten

    def is_identity(self) -> bool:
        return not self.pool_type

    def feat_mult(self) -> int:
        return adaptive_pool_feat_mult(self.pool_type)

    def __call__(self, x):
        # x: (B, H, W, C)
        if not self.pool_type:
            return x
        pt = self.pool_type
        if pt.startswith('fast'):
            pt = pt[4:].lstrip('_') or 'avg'
        if pt == 'avg':
            out = x.mean(axis=(1, 2))
        elif pt == 'max':
            out = x.max(axis=(1, 2))
        elif pt == 'avgmax':
            out = 0.5 * (x.mean(axis=(1, 2)) + x.max(axis=(1, 2)))
        elif pt == 'catavgmax':
            out = jnp.concatenate([x.mean(axis=(1, 2)), x.max(axis=(1, 2))], axis=-1)
        else:
            raise ValueError(f'Invalid pool type: {self.pool_type}')
        return out  # already flat (B, C[*2])
