"""Global-context attention block (GCNet) over NHWC features
(reference: timm/layers/global_context.py:21-90).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .create_conv2d import create_conv2d
from .helpers import make_divisible
from .mlp import ConvMlp
from .norm import LayerNorm

__all__ = ['GlobalContext']


class GlobalContext(nnx.Module):
    """Softmax-attention context pooling + scale/add fuse MLPs."""

    def __init__(
            self,
            channels: int,
            use_attn: bool = True,
            fuse_add: bool = False,
            fuse_scale: bool = True,
            init_last_zero: bool = False,
            rd_ratio: float = 1. / 8,
            rd_channels: Optional[int] = None,
            rd_divisor: int = 1,
            act_layer='relu',
            gate_layer='sigmoid',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.conv_attn = create_conv2d(
            channels, 1, 1, bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        ) if use_attn else None
        if rd_channels is None:
            rd_channels = make_divisible(channels * rd_ratio, rd_divisor, round_limit=0.)
        mlp_kw = dict(act_layer=act_layer, norm_layer=LayerNorm,
                      dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.mlp_add = ConvMlp(channels, rd_channels, **mlp_kw) if fuse_add else None
        self.mlp_scale = ConvMlp(channels, rd_channels, **mlp_kw) if fuse_scale else None
        self.gate = get_act_fn(gate_layer)
        if self.mlp_add is not None:
            # additive branch starts as identity (reference reset_parameters
            # zero-inits mlp_add.fc2 unconditionally)
            self.mlp_add.fc2.kernel[...] = jnp.zeros_like(self.mlp_add.fc2.kernel[...])

    def __call__(self, x):
        B, H, W, C = x.shape
        if self.conv_attn is not None:
            attn = self.conv_attn(x).reshape(B, H * W)  # (B, HW)
            attn = jax.nn.softmax(attn, axis=-1)
            context = jnp.einsum('bnc,bn->bc', x.reshape(B, H * W, C), attn)
            context = context.reshape(B, 1, 1, C)
        else:
            context = x.mean(axis=(1, 2), keepdims=True)

        if self.mlp_scale is not None:
            x = x * self.gate(self.mlp_scale(context))
        if self.mlp_add is not None:
            x = x + self.mlp_add(context)
        return x
