"""Global layer behaviour flags.

TPU-native re-design of the reference's layer-config singleton
(reference: timm/layers/config.py:101-165). Unlike the reference we keep the
surface minimal: flags only select which code path gets *traced* (e.g. Pallas
flash attention vs. plain XLA dot-product attention); they never mutate state
inside a jitted computation, so they are safe process-level switches.
"""
from __future__ import annotations

import os
from contextlib import contextmanager

__all__ = [
    'is_exportable', 'is_scriptable', 'set_exportable', 'set_scriptable',
    'use_fused_attn', 'set_fused_attn',
]

# Pallas flash-attention toggle. 0 = never, 1 = on TPU when shapes allow,
# 2 = always (error if unsupported).  Seeded from env like TIMM_FUSED_ATTN.
_USE_FUSED_ATTN = int(os.environ.get('TIMM_TPU_FUSED_ATTN', '1'))

# Export mode: prefer the most portable lowering (no Pallas custom kernels).
_EXPORTABLE = False
# Kept for API parity with the reference; TorchScript has no TPU analogue.
_SCRIPTABLE = False


def is_exportable() -> bool:
    return _EXPORTABLE


def is_scriptable() -> bool:
    return _SCRIPTABLE


@contextmanager
def set_exportable(value: bool):
    global _EXPORTABLE
    prev = _EXPORTABLE
    _EXPORTABLE = value
    try:
        yield
    finally:
        _EXPORTABLE = prev


@contextmanager
def set_scriptable(value: bool):
    global _SCRIPTABLE
    prev = _SCRIPTABLE
    _SCRIPTABLE = value
    try:
        yield
    finally:
        _SCRIPTABLE = prev


def use_fused_attn(experimental: bool = False) -> bool:
    """Whether attention layers should trace the Pallas fused kernel path."""
    if _EXPORTABLE:
        return False
    if _USE_FUSED_ATTN > 1:
        return True
    if _USE_FUSED_ATTN < 1:
        return False
    # Default: fused on real TPU backends only; CPU tests use the XLA path.
    import jax
    try:
        return jax.default_backend() == 'tpu'
    except Exception:
        return False


def set_fused_attn(enable: bool = True, experimental: bool = False):
    global _USE_FUSED_ATTN
    _USE_FUSED_ATTN = 2 if (enable and experimental) else (1 if enable else 0)
