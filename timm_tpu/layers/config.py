"""Global layer behaviour flags + the compute-precision policy.

TPU-native re-design of the reference's layer-config singleton
(reference: timm/layers/config.py:101-165). Unlike the reference we keep the
surface minimal: flags only select which code path gets *traced* (e.g. Pallas
flash attention vs. plain XLA dot-product attention, fp32 vs bf16 softmax
internals); they never mutate state inside a jitted computation, so they are
safe process-level switches.

Compute-precision policy (mirrors the reference's `fast_norm` global):

* ``softmax_dtype`` — dtype for attention-softmax internals. Default ``None``
  keeps the historical fp32-upcast softmax bit-for-bit. Setting ``bfloat16``
  traces the fast path: max-subtraction in fp32 (for range safety), exp and
  normalization in bf16 — halving vector-unit and VMEM traffic on the
  (B·H, N, N) probability tensor (PERF.md §2 item 2).
* ``norm_internal_dtype`` — dtype for LayerNorm/RmsNorm statistics. Default
  ``None`` keeps the framework fp32-stats path bit-for-bit; ``bfloat16``
  computes mean/var in bf16 (PERF.md: ~25 LayerNorms upcast per ViT step).

Both are seeded from ``TIMM_TPU_SOFTMAX_DTYPE`` / ``TIMM_TPU_NORM_DTYPE``
(values: ``float32`` | ``bfloat16`` | empty = default) so bench.py can A/B
each lever in a fresh process, and both are overridable per call/instance.
Every knob ships OFF by default with an exact-parity guarantee when disabled.
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional

__all__ = [
    'is_exportable', 'is_scriptable', 'set_exportable', 'set_scriptable',
    'use_fused_attn', 'set_fused_attn',
    'softmax_dtype', 'set_softmax_dtype', 'norm_internal_dtype',
    'set_norm_internal_dtype', 'resolve_dtype_arg', 'softmax_with_policy',
]

# Pallas flash-attention toggle. 0 = never, 1 = on TPU when shapes allow,
# 2 = always (error if unsupported).  Seeded from env like TIMM_FUSED_ATTN.
_USE_FUSED_ATTN = int(os.environ.get('TIMM_TPU_FUSED_ATTN', '1'))

# Export mode: prefer the most portable lowering (no Pallas custom kernels).
_EXPORTABLE = False
# Kept for API parity with the reference; TorchScript has no TPU analogue.
_SCRIPTABLE = False


def resolve_dtype_arg(value, allow_none: bool = True):
    """'bfloat16' / 'float32' / '' / dtype / None → jnp dtype or None."""
    import jax.numpy as jnp
    if value is None or value == '':
        if allow_none:
            return None
        raise ValueError('a dtype is required')
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ('', 'none', 'default'):
            return None
        return jnp.dtype({'bf16': 'bfloat16', 'fp32': 'float32', 'f32': 'float32'}.get(v, v))
    return jnp.dtype(value)


_SOFTMAX_DTYPE = resolve_dtype_arg(os.environ.get('TIMM_TPU_SOFTMAX_DTYPE', ''))
_NORM_DTYPE = resolve_dtype_arg(os.environ.get('TIMM_TPU_NORM_DTYPE', ''))


def is_exportable() -> bool:
    return _EXPORTABLE


def is_scriptable() -> bool:
    return _SCRIPTABLE


@contextmanager
def set_exportable(value: bool):
    global _EXPORTABLE
    prev = _EXPORTABLE
    _EXPORTABLE = value
    try:
        yield
    finally:
        _EXPORTABLE = prev


@contextmanager
def set_scriptable(value: bool):
    global _SCRIPTABLE
    prev = _SCRIPTABLE
    _SCRIPTABLE = value
    try:
        yield
    finally:
        _SCRIPTABLE = prev


def use_fused_attn(experimental: bool = False) -> bool:
    """Whether attention layers should trace the Pallas fused kernel path."""
    if _EXPORTABLE:
        return False
    if _USE_FUSED_ATTN > 1:
        return True
    if _USE_FUSED_ATTN < 1:
        return False
    # Default: fused on real TPU backends only; CPU tests use the XLA path.
    import jax
    try:
        return jax.default_backend() == 'tpu'
    except Exception:
        return False


def set_fused_attn(enable: bool = True, experimental: bool = False):
    global _USE_FUSED_ATTN
    _USE_FUSED_ATTN = 2 if (enable and experimental) else (1 if enable else 0)


# ---- compute-precision policy ------------------------------------------------

def softmax_dtype():
    """Process-level softmax internal dtype. None = legacy fp32 upcast."""
    return _SOFTMAX_DTYPE


def norm_internal_dtype():
    """Process-level norm-statistics dtype. None = framework fp32 stats."""
    return _NORM_DTYPE


class _PolicySetting:
    """Sets a module-level policy global immediately; restores the previous
    value if used as a context manager. Supports both styles:

        set_softmax_dtype('bfloat16')          # process-level, stays set
        with set_softmax_dtype('bfloat16'):    # scoped (tests / A-B)
            ...
    """

    def __init__(self, name: str, dtype):
        self._name = name
        self._prev = globals()[name]
        globals()[name] = resolve_dtype_arg(dtype)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        globals()[self._name] = self._prev
        return False


def set_softmax_dtype(dtype):
    """Set the softmax policy dtype (plain call or context manager)."""
    return _PolicySetting('_SOFTMAX_DTYPE', dtype)


def set_norm_internal_dtype(dtype):
    """Set the norm-internals policy dtype (plain call or context manager)."""
    return _PolicySetting('_NORM_DTYPE', dtype)


def softmax_with_policy(x, axis: int = -1, dtype=None):
    """The canonical softmax for attention layers.

    This is the ONLY place in `timm_tpu.layers` allowed to pick a softmax
    compute dtype (tests/test_layers.py lints for strays). `dtype=None`
    defers to the process policy; the policy's own default (None) is the
    historical fp32-upcast softmax, bit-identical to the pre-policy code.
    The result is returned in the *compute* dtype — callers cast back to
    their activation dtype, exactly as before.
    """
    import jax
    import jax.numpy as jnp
    dt = resolve_dtype_arg(dtype) if dtype is not None else _SOFTMAX_DTYPE
    if dt is None or dt == jnp.float32:
        return jax.nn.softmax(x.astype(jnp.float32), axis=axis)
    # Fast path: subtract the row max in fp32 (range safety — bf16 has fp32's
    # exponent but only 8 mantissa bits, so the subtraction itself is the
    # step that must not lose the large-magnitude cancellation), then exp and
    # normalize in the reduced dtype.
    xf = x.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(xf, axis=axis, keepdims=True))
    e = jnp.exp((xf - m).astype(dt))
    return e / jnp.sum(e, axis=axis, keepdims=True)
