"""Weight initializers (reference: timm/layers/weight_init.py:1-178).

Exposed as `jax.nn.initializers`-style callables usable as `kernel_init=` in
nnx modules. JAX's truncated_normal truncates at +/-2 sigma (the reference's
`trunc_normal_tf_` behaviour); for the tiny std values used by ViTs (0.02)
this is numerically indistinguishable from the reference's `trunc_normal_`.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.nn import initializers as jinit

__all__ = [
    'trunc_normal_', 'trunc_normal_tf_', 'variance_scaling_', 'lecun_normal_',
    'init_weight_vit', 'zeros_', 'ones_', 'normal_',
]


def trunc_normal_(std: float = 1.0, mean: float = 0.0):
    base = jinit.truncated_normal(stddev=std)
    if mean == 0.0:
        return base

    def init(key, shape, dtype=jnp.float32):
        return base(key, shape, dtype) + mean
    return init


# identical under JAX (see module docstring)
trunc_normal_tf_ = trunc_normal_


def variance_scaling_(scale: float = 1.0, mode: str = 'fan_in', distribution: str = 'normal'):
    if distribution == 'normal':
        distribution = 'truncated_normal'
    return jinit.variance_scaling(scale, mode, distribution)


def lecun_normal_():
    return jinit.variance_scaling(1.0, 'fan_in', 'truncated_normal')


def zeros_(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones_(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def normal_(std: float = 1.0):
    return jinit.normal(stddev=std)


def init_weight_vit(std: float = 0.02):
    """Default ViT linear/conv kernel init (trunc normal, std .02)."""
    return trunc_normal_(std=std)


def head_init_scaled(hidden_size: int):
    """`head_init_scale`-style zero-ish init used by some heads."""
    return jinit.truncated_normal(stddev=1.0 / math.sqrt(hidden_size))
