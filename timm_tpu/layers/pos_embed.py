"""Absolute position-embedding helpers (reference: timm/layers/pos_embed.py)."""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ['resample_abs_pos_embed', 'resample_abs_pos_embed_nhwc']


def resample_abs_pos_embed(
        posemb,
        new_size: Tuple[int, int],
        old_size: Optional[Tuple[int, int]] = None,
        num_prefix_tokens: int = 1,
        interpolation: str = 'cubic',
        antialias: bool = True,
):
    """Resize a (1, N, C) learned pos embed to a new token grid.

    Mirrors reference pos_embed.py:resample_abs_pos_embed — prefix (cls/reg)
    tokens are carried through untouched.
    """
    num_pos_tokens = posemb.shape[1]
    num_new_tokens = new_size[0] * new_size[1] + num_prefix_tokens
    # same token count is only a no-op for square grids (ref pos_embed.py:31)
    if num_new_tokens == num_pos_tokens and new_size[0] == new_size[1]:
        return posemb

    if old_size is None:
        hw = int(math.sqrt(num_pos_tokens - num_prefix_tokens))
        old_size = (hw, hw)

    if num_prefix_tokens:
        posemb_prefix, posemb = posemb[:, :num_prefix_tokens], posemb[:, num_prefix_tokens:]
    else:
        posemb_prefix = None

    embed_dim = posemb.shape[-1]
    orig_dtype = posemb.dtype
    posemb = posemb.astype(jnp.float32).reshape(1, old_size[0], old_size[1], embed_dim)
    posemb = jax.image.resize(
        posemb, (1, new_size[0], new_size[1], embed_dim), method=interpolation, antialias=antialias,
    )
    posemb = posemb.reshape(1, -1, embed_dim).astype(orig_dtype)

    if posemb_prefix is not None:
        posemb = jnp.concatenate([posemb_prefix, posemb], axis=1)
    return posemb


def resample_abs_pos_embed_nhwc(posemb, new_size, interpolation: str = 'cubic', antialias: bool = True):
    """Resize a (1, H, W, C) pos embed grid."""
    if tuple(posemb.shape[1:3]) == tuple(new_size):
        return posemb
    orig_dtype = posemb.dtype
    posemb = jax.image.resize(
        posemb.astype(jnp.float32),
        (posemb.shape[0], new_size[0], new_size[1], posemb.shape[-1]),
        method=interpolation, antialias=antialias,
    )
    return posemb.astype(orig_dtype)
