from .attention import Attention, AttentionRope, maybe_add_mask, scaled_dot_product_attention
from .attention2d import Attention2d, MultiQueryAttention2d, MultiQueryAttentionV2
from .attention_pool import AttentionPool2d, AttentionPoolLatent, RotAttentionPool2d
from .classifier import ClNormMlpClassifierHead, ClassifierHead, NormMlpClassifierHead, create_classifier
from .config import (
    is_exportable, is_scriptable, set_exportable, set_scriptable,
    set_fused_attn, use_fused_attn,
    norm_internal_dtype, resolve_dtype_arg, set_norm_internal_dtype,
    set_softmax_dtype, softmax_dtype, softmax_with_policy,
)
from .blur_pool import AvgPool2dAA, BlurPool2d, get_aa_layer
from .cbam import CbamModule, LightCbamModule
from .create_act import create_act_layer, get_act_fn, get_act_layer
from .create_attn import create_attn, get_attn
from .diff_attention import DiffAttention
from .eca import CecaModule, EcaModule
from .evo_norm import EvoNorm2dB0, EvoNorm2dS0, EvoNorm2dS0a
from .std_conv import ScaledStdConv2d, StdConv2d
from .create_conv2d import ConvNormAct, SeparableConvNormAct, create_conv2d, get_padding
from .cond_conv2d import CondConv2d, get_condconv_initializer
from .create_norm import create_norm_layer, get_norm_layer
from .drop import DropBlock2d, DropPath, Dropout, calculate_drop_path_rates, drop_block_2d, drop_path
from .filter_response_norm import FilterResponseNormAct2d, FilterResponseNormTlu2d
from .gather_excite import GatherExcite
from .global_context import GlobalContext
from .helpers import extend_tuple, make_divisible, to_1tuple, to_2tuple, to_3tuple, to_4tuple, to_ntuple
from .layer_scale import LayerScale, LayerScale2d
from .mixed_conv2d import MixedConv2d
from .mlp import ConvMlp, GatedMlp, GlobalResponseNorm, GlobalResponseNormMlp, GluMlp, Mlp, SwiGLU, SwiGLUPacked
from .non_local_attn import BatNonLocalAttn, BilinearAttnTransform, NonLocalAttn
from .norm import (
    BatchNorm2d, GroupNorm, GroupNorm1, LayerNorm, LayerNorm2d, LayerNormFp32,
    RmsNorm, RmsNorm2d, SimpleNorm, SimpleNorm2d,
)
from .norm_act import (
    BatchNormAct2d, FrozenBatchNormAct2d, GroupNorm1Act, GroupNormAct,
    LayerNormAct, LayerNormAct2d, get_norm_act_layer,
)
from .patch_dropout import PatchDropout
from .patch_embed import PatchEmbed, resample_patch_embed
from .pool import Pool2d, SelectAdaptivePool2d, adaptive_pool_feat_mult, create_pool2d, global_pool_nlc
from .pos_embed import resample_abs_pos_embed, resample_abs_pos_embed_nhwc
from .pos_embed_rel import (
    RelPosBias, RelPosBiasTf, RelPosMlp, gen_relative_log_coords, gen_relative_position_index,
    resize_rel_pos_bias_table_simple,
)
from .selective_kernel import SelectiveKernel, SelectiveKernelAttn
from .split_attn import SplitAttn
from .split_batchnorm import SplitBatchNorm2d, SplitBatchNormAct2d, convert_splitbn_model
from .test_time_pool import TestTimePoolHead, apply_test_time_pool
from .pos_embed_sincos import (
    RotaryEmbeddingCat, RotaryEmbeddingDinoV3, RotaryEmbeddingMixed,
    build_fourier_pos_embed, build_rotary_pos_embed,
    build_sincos2d_pos_embed, create_rope_embed, freq_bands, pixel_freq_bands,
)
from .squeeze_excite import EffectiveSEModule, SEModule, SqueezeExcite
from .weight_init import lecun_normal_, ones_, trunc_normal_, trunc_normal_tf_, variance_scaling_, zeros_
from .hybrid_embed import HybridEmbed
