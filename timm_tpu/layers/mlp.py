"""MLP blocks (reference: timm/layers/mlp.py:1-290).

All variants operate on channels-last inputs of any rank — the same module
serves transformer tokens (B, N, C) and NHWC conv features (B, H, W, C); a
1x1 conv over NHWC *is* a Linear on the last axis. `ConvMlp` still exists as
its own class because its op order differs (norm *before* act, relu default).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .drop import Dropout
from .helpers import to_2tuple
from .norm import LayerNorm
from .weight_init import trunc_normal_, zeros_

__all__ = ['Mlp', 'GluMlp', 'SwiGLU', 'SwiGLUPacked', 'GatedMlp', 'ConvMlp', 'GlobalResponseNormMlp']


def _shard_hidden(x):
    """Pin the post-fc1 hidden tensor over the 'model' mesh axis (no-op
    without one): fc1 is column-parallel under tensor parallelism, so the
    act/drop/norm elementwise chain runs on the shard fc1 produced instead of
    an all-gathered copy (parallel/constraints.py)."""
    from ..parallel import shard_activation
    return shard_activation(x, 'hidden')


class Mlp(nnx.Module):
    """fc1 → act → drop → (norm) → fc2 → drop."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Callable] = None,
            bias: Union[bool, tuple] = True,
            drop: Union[float, tuple] = 0.0,
            use_conv: bool = False,  # accepted for API parity; layout makes it moot
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        bias = to_2tuple(bias)
        drop_probs = to_2tuple(drop)
        linear = partial(
            nnx.Linear,
            dtype=dtype,
            param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02),
            bias_init=zeros_,
            rngs=rngs,
        )
        self.fc1 = linear(in_features, hidden_features, use_bias=bias[0])
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop_probs[0], rngs=rngs)
        self.norm = norm_layer(hidden_features, rngs=rngs) if norm_layer is not None else None
        self.fc2 = linear(hidden_features, out_features, use_bias=bias[1])
        self.drop2 = Dropout(drop_probs[1], rngs=rngs)

    def __call__(self, x):
        x = _shard_hidden(self.fc1(x))
        x = self.act(x)
        x = self.drop1(x)
        if self.norm is not None:
            x = self.norm(x)
        x = self.fc2(x)
        x = self.drop2(x)
        return x


class ConvMlp(nnx.Module):
    """fc1 → norm → act → drop → fc2 (reference mlp.py:215-248). The 1x1 convs
    collapse to Linear on the trailing axis in NHWC; note the norm sits
    *before* the activation, unlike `Mlp`."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Optional[Callable] = None,
            bias: Union[bool, tuple] = True,
            drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        bias = to_2tuple(bias)
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.fc1 = linear(in_features, hidden_features, use_bias=bias[0])
        self.norm = norm_layer(hidden_features, rngs=rngs) if norm_layer is not None else None
        self.act = get_act_fn(act_layer)
        self.drop = Dropout(drop, rngs=rngs)
        self.fc2 = linear(hidden_features, out_features, use_bias=bias[1])

    def __call__(self, x):
        x = self.fc1(x)
        if self.norm is not None:
            x = self.norm(x)
        x = self.act(x)
        x = self.drop(x)
        return self.fc2(x)


class GluMlp(nnx.Module):
    """GLU-style MLP: fc1 projects to 2*hidden, gate half through act."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'sigmoid',
            norm_layer: Optional[Callable] = None,
            bias: Union[bool, tuple] = True,
            drop: Union[float, tuple] = 0.0,
            gate_last: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        assert hidden_features % 2 == 0
        bias = to_2tuple(bias)
        drop_probs = to_2tuple(drop)
        self.gate_last = gate_last
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.fc1 = linear(in_features, hidden_features, use_bias=bias[0])
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop_probs[0], rngs=rngs)
        self.norm = norm_layer(hidden_features // 2, rngs=rngs) if norm_layer is not None else None
        self.fc2 = linear(hidden_features // 2, out_features, use_bias=bias[1])
        self.drop2 = Dropout(drop_probs[1], rngs=rngs)

    def __call__(self, x):
        x = self.fc1(x)
        x1, x2 = jnp.split(x, 2, axis=-1)
        x = x1 * self.act(x2) if self.gate_last else self.act(x1) * x2
        x = _shard_hidden(x)
        x = self.drop1(x)
        if self.norm is not None:
            x = self.norm(x)
        x = self.fc2(x)
        x = self.drop2(x)
        return x


class SwiGLU(nnx.Module):
    """SwiGLU with separate gate/value projections (reference mlp.py SwiGLU)."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'silu',
            norm_layer: Optional[Callable] = None,
            bias: Union[bool, tuple] = True,
            drop: Union[float, tuple] = 0.0,
            align_to: int = 0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        if align_to:
            hidden_features = hidden_features + (-hidden_features % align_to)
        bias = to_2tuple(bias)
        drop_probs = to_2tuple(drop)
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.fc1_g = linear(in_features, hidden_features, use_bias=bias[0])
        self.fc1_x = linear(in_features, hidden_features, use_bias=bias[0])
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop_probs[0], rngs=rngs)
        self.norm = norm_layer(hidden_features, rngs=rngs) if norm_layer is not None else None
        self.fc2 = linear(hidden_features, out_features, use_bias=bias[1])
        self.drop2 = Dropout(drop_probs[1], rngs=rngs)

    def __call__(self, x):
        x = _shard_hidden(self.act(self.fc1_g(x)) * self.fc1_x(x))
        x = self.drop1(x)
        if self.norm is not None:
            x = self.norm(x)
        x = self.fc2(x)
        x = self.drop2(x)
        return x


def SwiGLUPacked(in_features, hidden_features=None, **kwargs):
    """Packed-projection SwiGLU == GluMlp with silu gate on first half.

    Contract matches the reference (mlp.py SwiGLUPacked = partial(GluMlp, ...)):
    the caller passes the already-doubled hidden width.
    """
    return GluMlp(
        in_features,
        hidden_features=hidden_features,
        act_layer=kwargs.pop('act_layer', 'silu'),
        gate_last=False,
        **kwargs,
    )


class GatedMlp(nnx.Module):
    """MLP with a custom gating unit between fc1 and fc2 (gMLP)."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Callable] = None,
            gate_layer: Optional[Callable] = None,
            bias: Union[bool, tuple] = True,
            drop: Union[float, tuple] = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        bias = to_2tuple(bias)
        drop_probs = to_2tuple(drop)
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.fc1 = linear(in_features, hidden_features, use_bias=bias[0])
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop_probs[0], rngs=rngs)
        if gate_layer is not None:
            self.gate = gate_layer(hidden_features, rngs=rngs)
            hidden_features = hidden_features // 2
        else:
            self.gate = None
        self.norm = norm_layer(hidden_features, rngs=rngs) if norm_layer is not None else None
        self.fc2 = linear(hidden_features, out_features, use_bias=bias[1])
        self.drop2 = Dropout(drop_probs[1], rngs=rngs)

    def __call__(self, x):
        x = self.fc1(x)
        x = self.act(x)
        x = self.drop1(x)
        if self.gate is not None:
            x = self.gate(x)
        if self.norm is not None:
            x = self.norm(x)
        x = self.fc2(x)
        x = self.drop2(x)
        return x


class GlobalResponseNorm(nnx.Module):
    """GRN from ConvNeXt-V2 (reference: timm/layers/grn.py) — channels-last."""

    def __init__(self, dim: int, eps: float = 1e-6, *, param_dtype=jnp.float32, rngs: nnx.Rngs = None):
        self.eps = eps
        self.weight = nnx.Param(jnp.zeros((dim,), param_dtype))
        self.bias = nnx.Param(jnp.zeros((dim,), param_dtype))

    def __call__(self, x):
        # spatial axes = all but batch and channel
        spatial_axes = tuple(range(1, x.ndim - 1))
        gx = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)), axis=spatial_axes, keepdims=True))
        nx = gx / (jnp.mean(gx, axis=-1, keepdims=True) + self.eps)
        nx = nx.astype(x.dtype)
        return x + x * nx * self.weight[...].astype(x.dtype) + self.bias[...].astype(x.dtype)


class GlobalResponseNormMlp(nnx.Module):
    """Mlp w/ GRN inserted after activation (ConvNeXt-V2 block MLP)."""

    def __init__(
            self,
            in_features: int,
            hidden_features: Optional[int] = None,
            out_features: Optional[int] = None,
            act_layer: Union[str, Callable] = 'gelu',
            bias: Union[bool, tuple] = True,
            drop: Union[float, tuple] = 0.0,
            use_conv: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        bias = to_2tuple(bias)
        drop_probs = to_2tuple(drop)
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.fc1 = linear(in_features, hidden_features, use_bias=bias[0])
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop_probs[0], rngs=rngs)
        self.grn = GlobalResponseNorm(hidden_features, param_dtype=param_dtype, rngs=rngs)
        self.fc2 = linear(hidden_features, out_features, use_bias=bias[1])
        self.drop2 = Dropout(drop_probs[1], rngs=rngs)

    def __call__(self, x):
        x = self.fc1(x)
        x = self.act(x)
        x = self.drop1(x)
        x = self.grn(x)
        x = self.fc2(x)
        x = self.drop2(x)
        return x
