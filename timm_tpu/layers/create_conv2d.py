"""Conv layer factory for NHWC TPU convs
(reference: timm/layers/create_conv2d.py, conv2d_same.py, padding.py).

TF-'SAME' padding is native in lax/flax conv (`padding='SAME'`), so the
reference's Conv2dSame wrapper machinery collapses into a padding string.
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from flax import nnx

from .helpers import to_2tuple
from .weight_init import variance_scaling_, zeros_

__all__ = ['create_conv2d', 'ConvNormAct', 'get_padding']


def get_padding(kernel_size: int, stride: int = 1, dilation: int = 1):
    """Symmetric 'same-when-stride-1' padding amount (reference padding.py:get_padding)."""
    if isinstance(kernel_size, (tuple, list)):
        return tuple(get_padding(k, s, d) for k, s, d in
                     zip(kernel_size, to_2tuple(stride), to_2tuple(dilation)))
    return ((stride - 1) + dilation * (kernel_size - 1)) // 2


def _resolve_padding(padding, kernel_size, stride, dilation):
    """Map timm padding conventions onto flax conv padding.

    '' (the timm default) means SYMMETRIC torch-style padding, identical to
    None — NOT TF-SAME. Only the explicit 'same' string selects TF-SAME
    (asymmetric for stride>1 on even inputs), matching reference
    padding.py:get_padding_value.
    """
    if isinstance(padding, str):
        padding = padding.lower()
        if padding == 'same':
            return 'SAME'
        if padding == 'valid':
            return 'VALID'
        if padding == '':
            padding = None
        else:
            raise ValueError(f'Unknown padding {padding}')
    if padding is None:
        padding = get_padding(kernel_size, stride, dilation)
    if isinstance(padding, int):
        return [(padding, padding), (padding, padding)]
    # tuple of per-dim ints
    return [(p, p) for p in padding]


def create_conv2d(
        in_channels: int,
        out_channels: int,
        kernel_size: Union[int, tuple] = 3,
        stride: int = 1,
        padding='',
        dilation: int = 1,
        groups: int = 1,
        bias: bool = False,
        depthwise: bool = False,
        num_experts: int = 0,
        *,
        dtype=None,
        param_dtype=jnp.float32,
        rngs: nnx.Rngs,
):
    """NHWC conv with timm argument conventions (conv weights are HWIO).

    Dispatches like the reference create_conv2d (create_conv2d.py:1-36):
    a list kernel_size → MixedConv2d, num_experts > 0 → CondConv2d, else
    a plain nnx.Conv.
    """
    if isinstance(kernel_size, list):
        from .mixed_conv2d import MixedConv2d
        assert num_experts == 0
        return MixedConv2d(
            in_channels, out_channels, kernel_size, stride=stride, padding=padding,
            dilation=dilation, depthwise=depthwise or groups == in_channels, bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
    if depthwise:
        groups = in_channels
    if num_experts > 0:
        from .cond_conv2d import CondConv2d
        return CondConv2d(
            in_channels, out_channels, kernel_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, bias=bias, num_experts=num_experts,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
    kernel_size = to_2tuple(kernel_size)
    return nnx.Conv(
        in_channels, out_channels,
        kernel_size=kernel_size,
        strides=to_2tuple(stride),
        padding=_resolve_padding(padding, kernel_size, stride, dilation),
        kernel_dilation=to_2tuple(dilation),
        feature_group_count=groups,
        use_bias=bias,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'),
        bias_init=zeros_,
        rngs=rngs,
    )


class ConvNormAct(nnx.Module):
    """Conv + norm + act composite (reference: timm/layers/conv_bn_act.py)."""

    def __init__(
            self,
            in_channels: int,
            out_channels: int,
            kernel_size: Union[int, tuple] = 1,
            stride: int = 1,
            padding='',
            dilation: int = 1,
            groups: int = 1,
            bias: bool = False,
            apply_norm: bool = True,
            apply_act: bool = True,
            norm_layer=None,
            act_layer='relu',
            aa_layer=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        from .norm_act import BatchNormAct2d
        # anti-aliased downsampling: conv runs at stride 1, the aa pool strides
        # (reference conv_bn_act.py ConvNormAct + create_aa)
        use_aa = aa_layer is not None and to_2tuple(stride)[0] > 1
        self.conv = create_conv2d(
            in_channels, out_channels, kernel_size,
            stride=1 if use_aa else stride, padding=padding,
            dilation=dilation, groups=groups, bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        if apply_norm:
            norm_act = norm_layer or BatchNormAct2d
            self.bn = norm_act(
                out_channels, apply_act=apply_act, act_layer=act_layer,
                drop_layer=drop_layer,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs,
            )
            self.drop = None
        else:
            from .create_act import get_act_fn
            act = get_act_fn(act_layer) if apply_act else None
            self.bn = act
            self.drop = drop_layer() if drop_layer is not None else None
        self.aa = aa_layer(out_channels, stride=stride, rngs=rngs) if use_aa else None

    def __call__(self, x):
        x = self.conv(x)
        if self.drop is not None:
            x = self.drop(x)
        if self.bn is not None:
            x = self.bn(x)
        if self.aa is not None:
            x = self.aa(x)
        return x


class SeparableConvNormAct(nnx.Module):
    """Separable conv (dw + pw) with trailing norm-act
    (reference separable_conv.py:16-79; keeps conv_dw/conv_pw/bn names)."""

    def __init__(
            self,
            in_channels: int,
            out_channels: int,
            kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            padding='',
            bias: bool = False,
            channel_multiplier: float = 1.0,
            pw_kernel_size: int = 1,
            norm_layer=None,
            act_layer='relu',
            apply_act: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        from .norm_act import BatchNormAct2d
        self.conv_dw = create_conv2d(
            in_channels, int(in_channels * channel_multiplier), kernel_size,
            stride=stride, dilation=dilation, padding=padding, depthwise=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv_pw = create_conv2d(
            int(in_channels * channel_multiplier), out_channels, pw_kernel_size,
            padding=padding, bias=bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_act = norm_layer or BatchNormAct2d
        self.bn = norm_act(out_channels, apply_act=apply_act, act_layer=act_layer,
                           dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.bn(self.conv_pw(self.conv_dw(x)))
