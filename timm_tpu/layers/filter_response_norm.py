"""Filter Response Normalization (reference: timm/layers/filter_response_norm.py,
arXiv:1911.09737) for NHWC features.
"""
from __future__ import annotations

import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn

__all__ = ['FilterResponseNormAct2d', 'FilterResponseNormTlu2d', 'inv_instance_rms']


def inv_instance_rms(x, eps: float = 1e-5):
    """1/rms over the spatial dims, per sample per channel."""
    r = jnp.square(x.astype(jnp.float32)).mean(axis=(1, 2), keepdims=True)
    return ((r + eps) ** -0.5).astype(x.dtype)


class FilterResponseNormTlu2d(nnx.Module):
    """FRN + thresholded linear unit (tau) activation (reference :21-55)."""

    def __init__(self, num_features: int, apply_act: bool = True, eps: float = 1e-5,
                 rms: bool = True, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **_):
        self.apply_act = apply_act
        self.rms = rms
        self.eps = eps
        self.weight = nnx.Param(jnp.ones((num_features,), param_dtype))
        self.bias = nnx.Param(jnp.zeros((num_features,), param_dtype))
        self.tau = nnx.Param(jnp.zeros((num_features,), param_dtype)) if apply_act else None

    def __call__(self, x):
        dt = x.dtype
        x = x * inv_instance_rms(x, self.eps)
        x = x * self.weight[...].astype(dt) + self.bias[...].astype(dt)
        if self.tau is not None:
            return jnp.maximum(x, self.tau[...].astype(dt))
        return x


class FilterResponseNormAct2d(nnx.Module):
    """FRN + conventional activation (reference :58-95)."""

    def __init__(self, num_features: int, apply_act: bool = True, act_layer='relu',
                 rms: bool = True, eps: float = 1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **_):
        self.act = get_act_fn(act_layer) if (act_layer is not None and apply_act) else None
        self.rms = rms
        self.eps = eps
        self.weight = nnx.Param(jnp.ones((num_features,), param_dtype))
        self.bias = nnx.Param(jnp.zeros((num_features,), param_dtype))

    def __call__(self, x):
        dt = x.dtype
        x = x * inv_instance_rms(x, self.eps)
        x = x * self.weight[...].astype(dt) + self.bias[...].astype(dt)
        if self.act is not None:
            x = self.act(x)
        return x
