"""Multi-head attention (reference: timm/layers/attention.py:1-293).

TPU-first design: tokens are (B, N, C); the fused path dispatches to
`jax.nn.dot_product_attention` (XLA flash lowering) or the local Pallas
flash kernel (timm_tpu/kernels/flash_attention.py) when shapes allow; the
manual path is plain einsum+softmax which XLA also fuses well. Selection is
trace-time via `use_fused_attn()` — the reference's SDPA-vs-manual switch at
attention.py:123-129.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from .config import softmax_with_policy, use_fused_attn
from .drop import Dropout, dropout_rng_key
from .weight_init import trunc_normal_, zeros_

__all__ = ['Attention', 'AttentionRope', 'maybe_add_mask', 'apply_rot_embed_cat']


def maybe_add_mask(scores, attn_mask=None):
    if attn_mask is None:
        return scores
    if attn_mask.dtype == jnp.bool_:
        neg = jnp.finfo(scores.dtype).min
        return jnp.where(attn_mask, scores, neg)
    return scores + attn_mask


def apply_rot_embed_cat(x, emb, half: bool = False):
    """Apply concatenated (sin, cos) rotary embedding to (..., N, D) tokens.

    half=False: interleaved layout — sin/cos repeat per channel pair and the
    rotation swaps within each pair ([-x1, x0, -x3, x2, ...]).
    half=True: half layout (DINOv3 / LLaMA style) — sin/cos tile across the
    two halves and the rotation swaps halves ([-x[D/2:], x[:D/2]]).
    (reference pos_embed_sincos.py:281-297)
    """
    sin_emb, cos_emb = jnp.split(emb, 2, axis=-1)
    if half:
        xa, xb = jnp.split(x, 2, axis=-1)
        rot = jnp.concatenate([-xb, xa], axis=-1)
    else:
        x1, x2 = jnp.split(x.reshape(*x.shape[:-1], -1, 2), 2, axis=-1)
        x1 = x1[..., 0]
        x2 = x2[..., 0]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos_emb + rot * sin_emb


def _sdpa(q, k, v, attn_mask=None, dropout_p: float = 0.0, key=None, scale: Optional[float] = None,
          softmax_dtype=None):
    """Scaled dot-product attention on (B, H, N, D) tensors.

    Softmax internals follow the compute-precision policy (config.py):
    default is the historical fp32 upcast, bit-identical to the pre-policy
    code; `softmax_dtype` overrides per call.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q = q * scale
    attn = jnp.einsum('bhqd,bhkd->bhqk', q, k)
    attn = maybe_add_mask(attn, attn_mask)
    attn = softmax_with_policy(attn, axis=-1, dtype=softmax_dtype).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout_p), 0.0)
    return jnp.einsum('bhqk,bhkd->bhqd', attn, v)


def scaled_dot_product_attention(
        q, k, v,
        attn_mask=None,
        dropout_p: float = 0.0,
        dropout_key=None,
        scale: Optional[float] = None,
        fused: Optional[bool] = None,
        softmax_dtype=None,
):
    """Dispatcher over (B, H, N, D) q/k/v. `fused=None` → config default;
    `softmax_dtype=None` → config policy (fp32 upcast by default)."""
    fused = use_fused_attn() if fused is None else fused
    if fused and dropout_p == 0.0:
        from ..kernels import flash_attention_supported, flash_attention
        if flash_attention_supported(q, k, v, attn_mask):
            return flash_attention(q, k, v, mask=attn_mask, scale=scale)
        # At image-model sequence lengths the plain einsum+softmax graph beats
        # jax.nn.dot_product_attention on v5e (measured ViT-B/16 @224 train:
        # 867 vs 786 img/s/chip) — the N^2 score matrix is small enough that
        # XLA's fusion of it wins over the generic attention lowering.
        if q.shape[-2] <= 1024:
            return _sdpa(q, k, v, attn_mask, 0.0, None, scale, softmax_dtype)
        # XLA's fused path: expects (B, N, H, D)
        mask = attn_mask
        if mask is not None and mask.dtype != jnp.bool_:
            return _sdpa(q, k, v, attn_mask, 0.0, None, scale, softmax_dtype)
        out = jax.nn.dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            mask=mask, scale=scale,
        )
        return out.transpose(0, 2, 1, 3)
    return _sdpa(q, k, v, attn_mask, dropout_p, dropout_key, scale, softmax_dtype)


class Attention(nnx.Module):
    """Standard MHSA with optional qk-norm (reference attention.py:26-146)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            proj_bias: bool = True,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            scale_norm: bool = False,
            softmax_dtype=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert dim % num_heads == 0, 'dim should be divisible by num_heads'
        if qk_norm or scale_norm:
            assert norm_layer is not None, 'norm_layer must be provided if qk_norm or scale_norm is True'
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.attn_drop_rate = attn_drop
        self.softmax_dtype = softmax_dtype  # per-instance policy override

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs) if scale_norm else None
        self.proj = linear(dim, dim, use_bias=proj_bias)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def _qkv(self, x):
        from ..parallel import shard_activation
        B, N, C = x.shape
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim)
        qkv = qkv.transpose(2, 0, 3, 1, 4)  # (3, B, H, N, D)
        # heads over 'model' matches the column-parallel qkv kernel split, so
        # scores/softmax/values never leave the owning tp shard
        q, k, v = (shard_activation(t, 'heads') for t in (qkv[0], qkv[1], qkv[2]))
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        return q, k, v

    def __call__(self, x, attn_mask=None):
        from ..parallel import shard_activation
        B, N, C = x.shape
        q, k, v = self._qkv(x)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale,
            softmax_dtype=self.softmax_dtype,
        )
        x = shard_activation(x.transpose(0, 2, 1, 3).reshape(B, N, C), 'hidden')
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        x = self.proj_drop(x)
        return x


class AttentionRope(nnx.Module):
    """MHSA accepting a rotary position embedding, with fused or unfused qkv,
    qk/scale norms, and interleaved or half rotation layout
    (reference attention.py:148-290)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            dim_out: Optional[int] = None,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            num_prefix_tokens: int = 1,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            attn_head_dim: Optional[int] = None,
            norm_layer: Optional[Callable] = None,
            qk_norm: bool = False,
            scale_norm: bool = False,
            proj_bias: bool = True,
            rotate_half: bool = False,
            softmax_dtype=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        dim_out = dim_out or dim
        head_dim = attn_head_dim
        if head_dim is None:
            assert dim % num_heads == 0, 'dim should be divisible by num_heads'
            head_dim = dim // num_heads
        if scale_norm or qk_norm:
            assert norm_layer is not None, 'norm_layer must be provided if qk_norm or scale_norm is True'
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.attn_dim = head_dim * num_heads
        self.scale = head_dim ** -0.5
        self.num_prefix_tokens = num_prefix_tokens
        self.rotate_half = rotate_half
        self.attn_drop_rate = attn_drop
        self.softmax_dtype = softmax_dtype  # per-instance policy override

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        if qkv_fused:
            self.qkv = linear(dim, self.attn_dim * 3, use_bias=qkv_bias)
            self.q_proj = self.k_proj = self.v_proj = None
        else:
            self.qkv = None
            self.q_proj = linear(dim, self.attn_dim, use_bias=qkv_bias)
            self.k_proj = linear(dim, self.attn_dim, use_bias=qkv_bias)
            self.v_proj = linear(dim, self.attn_dim, use_bias=qkv_bias)
        self.q_norm = norm_layer(head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(self.attn_dim, rngs=rngs) if scale_norm else None
        self.proj = linear(self.attn_dim, dim_out, use_bias=proj_bias)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, rope=None, attn_mask=None):
        from ..parallel import shard_activation
        B, N, C = x.shape
        if self.qkv is not None:
            qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim)
            qkv = qkv.transpose(2, 0, 3, 1, 4)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            q = self.q_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            k = self.k_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            v = self.v_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        q, k, v = (shard_activation(t, 'heads') for t in (q, k, v))
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        if rope is not None:
            # don't rotate prefix (cls/reg) tokens — rope covers trailing tokens
            npt = self.num_prefix_tokens
            if npt > 0:
                q = jnp.concatenate(
                    [q[..., :npt, :], apply_rot_embed_cat(q[..., npt:, :], rope, half=self.rotate_half)], axis=-2)
                k = jnp.concatenate(
                    [k[..., :npt, :], apply_rot_embed_cat(k[..., npt:, :], rope, half=self.rotate_half)], axis=-2)
            else:
                q = apply_rot_embed_cat(q, rope, half=self.rotate_half)
                k = apply_rot_embed_cat(k, rope, half=self.rotate_half)
            q = q.astype(v.dtype)
            k = k.astype(v.dtype)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale,
            softmax_dtype=self.softmax_dtype,
        )
        x = shard_activation(x.transpose(0, 2, 1, 3).reshape(B, N, self.attn_dim), 'hidden')
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        x = self.proj_drop(x)
        return x
