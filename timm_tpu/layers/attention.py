"""Multi-head attention (reference: timm/layers/attention.py:1-293).

TPU-first design: tokens are (B, N, C); the fused path dispatches to
`jax.nn.dot_product_attention` (XLA flash lowering) or the local Pallas
flash kernel (timm_tpu/kernels/flash_attention.py) when shapes allow; the
manual path is plain einsum+softmax which XLA also fuses well. Selection is
trace-time via `use_fused_attn()` — the reference's SDPA-vs-manual switch at
attention.py:123-129.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from .config import use_fused_attn
from .drop import Dropout, dropout_rng_key
from .weight_init import trunc_normal_, zeros_

__all__ = ['Attention', 'AttentionRope', 'maybe_add_mask', 'apply_rot_embed_cat']


def maybe_add_mask(scores, attn_mask=None):
    if attn_mask is None:
        return scores
    if attn_mask.dtype == jnp.bool_:
        neg = jnp.finfo(scores.dtype).min
        return jnp.where(attn_mask, scores, neg)
    return scores + attn_mask


def apply_rot_embed_cat(x, emb):
    """Apply concatenated (sin, cos) rotary embedding to (..., N, D) tokens."""
    sin_emb, cos_emb = jnp.split(emb, 2, axis=-1)
    x1, x2 = jnp.split(x.reshape(*x.shape[:-1], -1, 2), 2, axis=-1)
    x1 = x1[..., 0]
    x2 = x2[..., 0]
    rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos_emb + rot * sin_emb


def _sdpa(q, k, v, attn_mask=None, dropout_p: float = 0.0, key=None, scale: Optional[float] = None):
    """Scaled dot-product attention on (B, H, N, D) tensors."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q = q * scale
    attn = jnp.einsum('bhqd,bhkd->bhqk', q, k)
    attn = maybe_add_mask(attn, attn_mask)
    attn = jax.nn.softmax(attn.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout_p), 0.0)
    return jnp.einsum('bhqk,bhkd->bhqd', attn, v)


def scaled_dot_product_attention(
        q, k, v,
        attn_mask=None,
        dropout_p: float = 0.0,
        dropout_key=None,
        scale: Optional[float] = None,
        fused: Optional[bool] = None,
):
    """Dispatcher over (B, H, N, D) q/k/v. `fused=None` → config default."""
    fused = use_fused_attn() if fused is None else fused
    if fused and dropout_p == 0.0:
        from ..kernels import flash_attention_supported, flash_attention
        if flash_attention_supported(q, k, v, attn_mask):
            return flash_attention(q, k, v, mask=attn_mask, scale=scale)
        # At image-model sequence lengths the plain einsum+softmax graph beats
        # jax.nn.dot_product_attention on v5e (measured ViT-B/16 @224 train:
        # 867 vs 786 img/s/chip) — the N^2 score matrix is small enough that
        # XLA's fusion of it wins over the generic attention lowering.
        if q.shape[-2] <= 1024:
            return _sdpa(q, k, v, attn_mask, 0.0, None, scale)
        # XLA's fused path: expects (B, N, H, D)
        mask = attn_mask
        if mask is not None and mask.dtype != jnp.bool_:
            return _sdpa(q, k, v, attn_mask, 0.0, None, scale)
        out = jax.nn.dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            mask=mask, scale=scale,
        )
        return out.transpose(0, 2, 1, 3)
    return _sdpa(q, k, v, attn_mask, dropout_p, dropout_key, scale)


class Attention(nnx.Module):
    """Standard MHSA with optional qk-norm (reference attention.py:26-146)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            proj_bias: bool = True,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            scale_norm: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert dim % num_heads == 0, 'dim should be divisible by num_heads'
        if qk_norm or scale_norm:
            assert norm_layer is not None, 'norm_layer must be provided if qk_norm or scale_norm is True'
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.attn_drop_rate = attn_drop

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs) if scale_norm else None
        self.proj = linear(dim, dim, use_bias=proj_bias)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def _qkv(self, x):
        B, N, C = x.shape
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim)
        qkv = qkv.transpose(2, 0, 3, 1, 4)  # (3, B, H, N, D)
        q, k, v = qkv[0], qkv[1], qkv[2]
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        return q, k, v

    def __call__(self, x, attn_mask=None):
        B, N, C = x.shape
        q, k, v = self._qkv(x)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale,
        )
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        x = self.proj_drop(x)
        return x


class AttentionRope(Attention):
    """MHSA accepting a rotary position embedding (reference attention.py:149+)."""

    def __call__(self, x, rope=None, attn_mask=None):
        B, N, C = x.shape
        q, k, v = self._qkv(x)
        if rope is not None:
            # don't rotate prefix (cls/reg) tokens — rope covers trailing tokens
            num_prefix = N - rope.shape[-2]
            if num_prefix > 0:
                qp, qr = q[..., :num_prefix, :], q[..., num_prefix:, :]
                kp, kr = k[..., :num_prefix, :], k[..., num_prefix:, :]
                q = jnp.concatenate([qp, apply_rot_embed_cat(qr, rope)], axis=-2)
                k = jnp.concatenate([kp, apply_rot_embed_cat(kr, rope)], axis=-2)
            else:
                q = apply_rot_embed_cat(q, rope)
                k = apply_rot_embed_cat(k, rope)
            q = q.astype(v.dtype)
            k = k.astype(v.dtype)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale,
        )
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        x = self.proj_drop(x)
        return x
